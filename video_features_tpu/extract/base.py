"""BaseExtractor: per-video orchestration, fault isolation, idempotent output.

Re-design of reference models/_base/base_extractor.py (132 LoC) with the same
externally observable contract:
  * ``_extract`` = skip-if-exists → ``extract()`` → [optional rgb||flow
    concat] → ``action_on_extraction``; any exception is isolated per video
    (KeyboardInterrupt re-raised) so one bad file never kills a worker
    (reference base_extractor.py:29-58);
  * ``action_on_extraction`` prints (with max/mean/min) or saves
    numpy/pickle, warns on empty values, and re-checks existence right before
    writing so concurrent shared-filesystem workers collide benignly
    (reference base_extractor.py:60-98);
  * ``is_already_exist`` requires ALL output files present *and loadable* —
    the load doubles as corruption detection, and is what makes workers
    restartable/elastic (reference base_extractor.py:100-132).

Unlike the fork (which concatenates rgb||flow unconditionally and thereby
breaks every non-I3D extractor, reference base_extractor.py:43-52), the concat
here is opt-in via ``concat_rgb_flow`` and only applies when both streams are
present — upstream behavior for everyone else.
"""
from __future__ import annotations

import logging as _logging
import os
import sys
import time as _time
import warnings
from pathlib import Path
from typing import Any, Dict, List, Union

import numpy as np

from video_features_tpu.obs.events import event
from video_features_tpu.utils.output import (
    ACTION_TO_EXT, ACTION_TO_LOAD, ACTION_TO_SAVE, make_path,
    read_fingerprint, write_fingerprint,
)
from video_features_tpu.utils.tracing import NULL_TRACER, Tracer


# dispatch-table sentinel: "this geometry permanently falls back to the
# jit" (store-side failure already reported) — distinct from None ("not
# looked up yet") so a failed ensure isn't retried on every batch
_AOT_FALLBACK = object()


def log_extraction_error(video_path, request_id=None, stage=None) -> None:
    """The one per-video failure report (fault-isolation contract): every
    loop — per-video, cross-video windower, packed finalize, serve worker
    — emits the same shape through the structured event log (obs/events:
    warning level, stderr, video path + full traceback), so operators and
    log scrapers see one format and ``on_extraction: print`` stdout stays
    byte-clean."""
    from video_features_tpu.obs.events import log_extraction_error as _log
    _log(video_path, request_id=request_id, stage=stage)


class BaseExtractor:
    """Common per-video orchestration inherited by every extractor."""

    # subclasses must set: output_feat_keys: List[str]
    output_feat_keys: List[str] = []

    def __init__(
        self,
        feature_type: str,
        on_extraction: str,
        tmp_path: str,
        output_path: str,
        keep_tmp_files: bool,
        device: str,
        concat_rgb_flow: bool = False,
        profile: bool = False,
        precision: str = 'highest',
        inflight: int = 2,
        compute_dtype: str = 'float32',
    ) -> None:
        self.feature_type = feature_type
        self.on_extraction = on_extraction
        self.tmp_path = tmp_path
        self.output_path = output_path
        self.keep_tmp_files = keep_tmp_files
        self.device = device
        self.concat_rgb_flow = concat_rgb_flow
        self.precision = precision
        # compute_dtype fast lanes (ops/precision.py): the STORAGE (+
        # activation) dtype of the device step — 'float32' is
        # byte-for-byte today's graph; 'bfloat16' halves params HBM/H2D
        # and runs bf16 activations with fp32 accumulation islands;
        # 'int8' quarter-sizes params via per-output-channel weight
        # quantization (ops/quant.py) with in-graph dequant and fp32
        # activations — each under the family's pinned parity bound.
        # sanity_check already refused unknown values and non-accepting
        # families at config time; extractors constructed directly get
        # the same guard here.
        from video_features_tpu.ops.precision import COMPUTE_DTYPES
        if compute_dtype not in COMPUTE_DTYPES:
            raise ValueError(f'compute_dtype must be one of '
                             f'{COMPUTE_DTYPES}; got {compute_dtype!r}')
        self.compute_dtype = compute_dtype
        # output-side pipelining depth: the device loop keeps up to this
        # many dispatched batches in flight before materializing the
        # oldest one's results (D2H + scatter + save overlap compute);
        # 1 = fully synchronous, outputs byte-identical at any depth
        self.inflight = max(int(inflight or 1), 1)
        # profile controls the PRINTED stage tables; the tracer may also
        # be enabled (tables off) by configure_obs for trace/manifest runs
        self.profile = profile
        self.tracer = Tracer(enabled=True) if profile else NULL_TRACER
        self._mesh = None  # set by _ensure_mesh for data_parallel extractors
        # mesh-sharded packed execution (mesh_devices=): resolved device
        # count for the packed loop's data-parallel mesh; 1 = today's
        # single-device loop. configure_mesh resolves 0 (auto) at build
        # time; extractors constructed directly stay single-device.
        self.mesh_devices = 1
        self._packed_mesh_ndev = 1
        # serve placement (serve/pool.DevicePlacer): the specific local
        # chip(s) this extractor is resident on — place_on pins them
        # right after build, before any batch flows; None = default
        # (first local device / every local device for a packed mesh)
        self._placement_devices = None
        # bytes the serve DevicePlacer charged this entry's chips at
        # placement time (params_nbytes at build) — released verbatim at
        # retirement so the per-chip residency ledger nets to zero
        self._placement_nbytes = 0
        # content-addressed feature cache + run identity — attached by
        # configure_cache (registry.create_extractor calls it with the
        # full merged config); None = legacy behavior everywhere
        self.cache = None
        self.run_fingerprint = None
        # persistent executable store (aot/) — attached by configure_aot
        # when aot_enabled; None = every program compiles via the jit,
        # exactly today's behavior. _aot_programs is the per-geometry
        # dispatch table aot_call maintains (resident AotPrograms keyed
        # by batch shape/dtype + static kwargs); aot_stats counts which
        # path each resident program took (the serve pool's
        # builds_loaded / builds_compiled split reads it).
        self._aot_store = None
        self._aot_programs: Dict[tuple, object] = {}
        self._aot_lock = None          # created lazily with the store
        self.aot_stats = {'loaded': 0, 'compiled': 0}
        # flight recorder (obs/) — attached by configure_obs when the
        # trace_out / manifest_out knobs are set; None = no telemetry
        # artifacts, exactly today's behavior
        self.trace_out = None
        self.manifest = None
        self.manifest_out = None
        # vft-flight: the run-level trace context (a CLI run is one
        # "request"; per-video spans derive children) and the crash-dump
        # black box (postmortem_dir knob) — both attached by
        # configure_obs, None = legacy behavior
        self.trace_ctx = None
        self.blackbox = None
        # stall-watchdog feed for farm decode workers: the serve layer
        # installs ``watchdog_pending(worker_idx, n_queued)`` and the
        # DecodeFarm mirrors each worker's backlog into it (None = no
        # watchdog, exactly today's behavior)
        self.watchdog_pending = None
        # decode farm (farm/) — the live DecodeFarm handle while (and
        # after) a farm-backed packed run, for the serve metrics surface;
        # run_packed installs it when decode_workers > 1 takes the
        # multi-process input path
        self._farm = None

    def precision_scope(self):
        """Matmul-precision context for the device loop. ``highest`` (the
        default) keeps full float32 passes for reference parity; ``default``
        lets the TPU run bf16 MXU passes — ~an order of magnitude faster at
        CLI geometry; ``mixed`` = parity-grade fast mode (ops/precision.py):
        ambient 3-pass bf16, measured ≤1e-3 feature drift on the fused path
        at ~1.9x the 'highest' throughput; ``precision_pins`` carries any
        tuned per-sub-graph overrides to extractors that support them."""
        import jax

        from video_features_tpu.ops.precision import MIXED_AMBIENT
        ambient = MIXED_AMBIENT if self.precision == 'mixed' else self.precision
        return jax.default_matmul_precision(ambient)

    @property
    def param_dtype(self):
        """Numpy STORAGE dtype for transplanted params on this lane
        (``ml_dtypes.bfloat16`` for the bf16 fast lane, ``int8`` for the
        weight-quantized lane, else float32) — what ``load_params``
        hands the transplant layer's ``dtype=`` seam, so a fast-lane
        entry's params are reduced-size in HBM from build (int8 selects
        the quantize-eligible-weights path, not a blanket cast)."""
        from video_features_tpu.ops.precision import param_np_dtype
        return param_np_dtype(self.compute_dtype)

    @property
    def compute_jnp_dtype(self):
        """The jnp activation dtype the device step casts its uint8
        input to — threaded into each family's jitted forward as a
        trace-time constant, so the float32 lane's program is
        byte-identical to the pre-knob graph. The int8 lane ACTIVATES in
        float32 (only weight storage is quantized; the in-graph dequant
        lands in the fp32 compute path)."""
        import jax.numpy as jnp
        return jnp.bfloat16 if self.compute_dtype == 'bfloat16' \
            else jnp.float32

    @property
    def precision_pins(self):
        """Per-sub-graph precision overrides for ``precision='mixed'``
        (None otherwise) — thread into step functions that support pins."""
        if self.precision == 'mixed':
            from video_features_tpu.ops.precision import MIXED_PINS
            return MIXED_PINS
        return None

    def fetch_outputs(self, out):
        """Materialize one dispatched device step's outputs on the host —
        the deferred D2H + host copy of the async device loop. ``out`` is
        whatever the step returned (a device array or any pytree of
        them); the result is the same structure as numpy arrays. This is
        the SYNC POINT: an asynchronously raised execution error (OOM, a
        geometry that won't run) surfaces here, not at dispatch, which is
        why the packed scheduler's fault isolation wraps this call too.
        Host arrays pass through unchanged, so legacy ``packed_step``
        overrides that still return numpy keep working."""
        import jax
        return jax.device_get(out)

    def put_input(self, batch):
        """Place one host input batch on the device(s): sharded over the
        mesh when data-parallel, else committed to the extractor's device.
        Safe to call from prefetch producer threads (device_put is async
        and thread-safe), which is how extractors overlap the H2D transfer
        of batch k+1 with the device computing batch k."""
        if self._mesh is not None:
            from video_features_tpu.parallel.mesh import require_shardable
            require_shardable(len(batch), self._mesh)
            return self._put_batch(batch)
        import jax
        return jax.device_put(batch, self._device)

    def _ensure_mesh(self, batch_attr: str) -> None:
        """Lazy in-graph data-parallel setup shared by every DP extractor.

        Builds the local-device mesh, rounds the batch attribute named
        ``batch_attr`` up to the global batch, replicates ``self.params``,
        and installs ``self._put_batch``. Lazy because subclasses set
        ``self.params`` after ``super().__init__``.
        """
        if self._mesh is not None:
            return
        from video_features_tpu.parallel import setup_data_parallel
        mesh, global_batch, params, put = setup_data_parallel(
            self.device, getattr(self, batch_attr), self.params)
        self._mesh, self.params, self._put_batch = mesh, params, put
        setattr(self, batch_attr, global_batch)
        # params just moved (replicated over the mesh): resident AOT
        # executables are bound to the old placement — re-resolve
        self._aot_invalidate()

    # -- mesh-sharded packed execution (mesh_devices=) ----------------------

    def configure_mesh(self, args) -> None:
        """Resolve the ``mesh_devices`` knob against this host's local
        devices: ``0`` auto-detects every local device of the extractor's
        platform, an over-ask raises a clear error at BUILD time (a serve
        submit then fails with 'extractor build failed', not a worker
        crash mid-batch). Called by ``registry.create_extractor``;
        extractors constructed directly stay single-device."""
        n = args.get('mesh_devices', 1)
        n = 1 if n is None else int(n)
        if n != 1:
            from video_features_tpu.utils.device import jax_devices_all
            local = jax_devices_all(self.device)
            if n == 0:
                n = len(local)
            elif n > len(local):
                raise ValueError(
                    f'mesh_devices={n} but this host has only '
                    f'{len(local)} local {local[0].platform} device(s) — '
                    'lower mesh_devices (or 0 to auto-detect)')
        self.mesh_devices = max(n, 1)

    def params_nbytes(self) -> int:
        """Per-chip device residency of this extractor's params (plus
        every declared ``_device_buffer_attrs`` buffer), in REAL bytes —
        what the serve placement layer (``serve/pool.DevicePlacer``)
        ranks chips by, so a bf16 fast-lane entry counts its actual
        ~half-size footprint instead of '1 entry'. Logical (per-copy)
        bytes: a mesh entry replicates params per chip, and the placer
        charges each assigned chip one copy."""
        total = 0
        trees = [getattr(self, 'params', None)]
        trees += [getattr(self, attr, None)
                  for attr in self._device_buffer_attrs]
        import jax
        for tree in trees:
            if tree is None:
                continue
            for leaf in jax.tree_util.tree_leaves(tree):
                total += int(getattr(leaf, 'nbytes', 0) or 0)
        return total

    # names of extra device-committed array attributes (beyond
    # ``params``) that ``place_on`` must migrate with the extractor —
    # subclasses that commit auxiliary buffers at build time (vggish's
    # PCA matrices) list them here, or a placed entry would feed a jit
    # call operands committed to two different chips
    _device_buffer_attrs: tuple = ()

    def place_on(self, devices) -> None:
        """Pin this extractor's residency to specific local chip(s) —
        the serve placement layer calls it right after build, BEFORE any
        batch flows, so different model families can be resident on
        different chips. One device: params (and every declared
        ``_device_buffer_attrs`` buffer) move there and every
        ``put_input`` commits there; several devices: the packed mesh
        (``mesh_devices``) builds over exactly these chips."""
        devices = list(devices)
        if not devices:
            return
        self._placement_devices = devices
        if self._mesh is None and len(devices) == 1 \
                and getattr(self, 'params', None) is not None:
            import jax
            self._device = devices[0]
            self.params = jax.device_put(self.params, devices[0])
            for attr in self._device_buffer_attrs:
                buf = getattr(self, attr, None)
                if buf is not None:
                    setattr(self, attr, jax.device_put(buf, devices[0]))
            # re-placed params invalidate device-bound AOT executables;
            # the next warm/dispatch re-keys under the new chip's ids
            self._aot_invalidate()

    def _ensure_packed_mesh(self) -> int:
        """Build the packed loop's data-parallel mesh when
        ``mesh_devices > 1``: an N-device data-only mesh (over the
        placement devices when the serve placer pinned some, else the
        platform's local devices), params replicated per chip, and the
        data-axis batch placement installed so ``put_input`` shards each
        stacked batch. Returns the data-axis size (1 = single-device
        loop, unchanged). Idempotent — a second ``run_packed`` over the
        same extractor (serve workers, bench warm passes) reuses the
        mesh. A ``data_parallel`` extractor already owns a mesh (with
        its batch attr rounded to the global batch), so this defers to
        it and leaves batch planning alone."""
        n = int(getattr(self, 'mesh_devices', 1) or 1)
        if n <= 1:
            return 1
        if self._mesh is not None:
            return self._packed_mesh_ndev
        from functools import partial

        from video_features_tpu.parallel.mesh import make_mesh
        from video_features_tpu.parallel.pipeline import (
            put_batch, put_replicated,
        )
        from video_features_tpu.utils.device import jax_devices_all
        devices = self._placement_devices or jax_devices_all(self.device)
        mesh = make_mesh(n_devices=n, time_parallel=1, devices=devices)
        self._mesh = mesh
        if getattr(self, 'params', None) is not None:
            self.params = put_replicated(mesh, self.params)
        self._put_batch = partial(put_batch, mesh)
        self._packed_mesh_ndev = n
        # params just replicated over the fresh mesh: drop any
        # single-device AOT residents (re-keyed under the mesh lane)
        self._aot_invalidate()
        return n

    # -- content-addressed feature cache (cache/) ---------------------------

    def configure_cache(self, args) -> None:
        """Attach the run fingerprint (config + weights identity — always,
        it also keys config-aware resume) and, when ``cache_enabled``, the
        shared :class:`cache.FeatureCache` for ``cache_dir``. Called by
        ``registry.create_extractor`` with the full merged config;
        extractors constructed directly (tests, stubs) stay legacy."""
        from video_features_tpu.cache import (
            FeatureCache, log_cache_error, run_fingerprint,
        )
        try:
            self.run_fingerprint = run_fingerprint(args)
        except Exception:
            # e.g. an unreadable checkpoint path: the build itself will
            # report it; a fingerprint failure must not mask that error
            log_cache_error('fingerprint derivation')
            self.run_fingerprint = None
            return
        if args.get('cache_enabled') and self.on_extraction in ACTION_TO_EXT:
            try:
                l2 = args.get('cache_l2_dir')
                if l2:
                    # fleet shared tier: local L1 + shared L2
                    from video_features_tpu.fleet.tier import (
                        TieredFeatureCache,
                    )
                    self.cache = TieredFeatureCache.get_pair(
                        args.get('cache_dir'), l2,
                        args.get('cache_max_bytes'))
                else:
                    self.cache = FeatureCache.get(
                        args.get('cache_dir'), args.get('cache_max_bytes'))
            except Exception:
                log_cache_error(f'open ({args.get("cache_dir")})')
                self.cache = None

    # -- persistent executable store (aot/) ---------------------------------

    def configure_aot(self, args) -> None:
        """Attach the persistent executable store when ``aot_enabled``
        — programs then load from disk instead of compiling whenever a
        previous process published the same program (same StableHLO
        identity, jax version, backend, device kind/ids). Called by
        ``registry.create_extractor``; extractors constructed directly
        (tests, stubs) stay legacy. Store failures degrade to
        compile-everything, never to a failed build."""
        if not args.get('aot_enabled'):
            return
        import threading

        from video_features_tpu.aot import ExecStore, log_aot_error
        try:
            l2 = args.get('aot_l2_dir')
            if l2:
                # fleet shared artifact tier: publish-on-compile,
                # pull-on-miss (fleet/artifacts.py)
                from video_features_tpu.fleet.artifacts import (
                    TieredExecStore,
                )
                self._aot_store = TieredExecStore.get_pair(
                    args.get('aot_dir'), l2, args.get('aot_max_bytes'))
            else:
                self._aot_store = ExecStore.get(args.get('aot_dir'),
                                                args.get('aot_max_bytes'))
            self._aot_lock = threading.Lock()
        except Exception:
            log_aot_error(f'open ({args.get("aot_dir")})')
            self._aot_store = None

    def _aot_lane(self) -> str:
        """The program's ``mesh<n>[@dtype]`` lane key — the same naming
        PROGRAMS.lock.json uses for its per-width/per-dtype variants."""
        from video_features_tpu.analysis.programs import mesh_key
        width = 1
        if self._mesh is not None:
            try:
                width = int(self._mesh.shape['data'])
            except (KeyError, TypeError):
                width = max(int(self._packed_mesh_ndev or 1), 1)
        return mesh_key(width, self.compute_dtype)

    def _aot_invalidate(self) -> None:
        """Drop every resident AotProgram. Called whenever params move
        (placement, mesh build): a resident executable is bound to the
        chips it was compiled for, and dispatching it with re-placed
        args would raise — the next ``aot_call`` re-traces and consults
        the store under the NEW device ids instead."""
        self._aot_programs.clear()

    def _aot_dispatch_key(self, name: str, batch, statics: dict) -> tuple:
        # params are attribute-stable between invalidations, so only the
        # batch geometry + the static kwargs + the ambient matmul
        # precision (a trace-context input: the jit re-traces per
        # context, and so must we) discriminate programs
        import jax
        return (name, tuple(batch.shape), str(batch.dtype),
                str(jax.config.jax_default_matmul_precision),
                tuple(sorted(statics.items())))

    def aot_call(self, name: str, jitted, params, batch, **statics):
        """The hot-path dispatch seam: run ``jitted(params, batch,
        **statics)`` through a resident AOT executable when one exists,
        installing one on first sight of a geometry — loaded from the
        persistent store when a previous process published this exact
        program, compiled (and republished) otherwise. Without a store
        this is EXACTLY the legacy call. Byte-identical either way
        (tests/test_aot.py pins loaded ≡ compiled ≡ jit)."""
        if self._aot_store is None or not hasattr(jitted, 'trace'):
            return jitted(params, batch, **statics)
        key = self._aot_dispatch_key(name, batch, statics)
        prog = self._aot_programs.get(key)
        if prog is None:
            with self._aot_lock:
                prog = self._aot_programs.get(key)
                if prog is None:
                    prog = self._aot_ensure(name, jitted, (params, batch),
                                            statics)
                    self._aot_programs[key] = prog or _AOT_FALLBACK
        if prog is None or prog is _AOT_FALLBACK:
            return jitted(params, batch, **statics)
        return prog(params, batch)

    def _aot_ensure(self, name: str, jitted, args: tuple, statics: dict):
        """Load-or-compile one program; None = fall back to the jit for
        this geometry forever (store-side failure, already reported)."""
        from video_features_tpu.aot import log_aot_error
        from video_features_tpu.aot.runtime import ensure_program
        try:
            prog, path = ensure_program(
                self._aot_store, name, jitted, args, statics,
                lane=self._aot_lane(), feature_type=self.feature_type)
        except Exception:
            log_aot_error(f'{self.feature_type}/{name}')
            return None
        self.aot_stats[path] += 1
        return prog

    def aot_warm(self) -> Dict[str, int]:
        """Eagerly warm every program this extractor's ``program_specs``
        declare, at its CURRENT device placement — the serve boot path
        (``serve_prewarm`` / cold submits call it right after
        ``place_on``), so the first request finds its executables
        resident instead of compiling under the request. Returns the
        {'loaded': n, 'compiled': n} delta. Never raises: a spec that
        won't warm falls back to the lazy dispatch path. No-op without
        a store."""
        before = dict(self.aot_stats)
        if self._aot_store is None:
            return {'loaded': 0, 'compiled': 0}
        try:
            import jax
            from jax.sharding import SingleDeviceSharding
            self._ensure_packed_mesh()
            specs = self.program_specs(mesh=self._mesh)
        except Exception:
            from video_features_tpu.aot import log_aot_error
            log_aot_error(f'warm specs for {self.feature_type}')
            return {'loaded': 0, 'compiled': 0}
        for spec in specs:
            if not hasattr(spec.jitted, 'trace'):
                # not an AOT-stageable jit (e.g. a data_parallel wrapper
                # closure): the dispatch seam falls back to it directly
                continue
            try:
                args = list(spec.args)
                params = getattr(self, 'params', None)
                if params is not None:
                    # the LIVE params (concrete, placed): the lowering
                    # then carries the real device binding, so the
                    # dispatch-time trace of an actual batch hashes to
                    # the SAME store key (verified equal in test_aot)
                    args[0] = params
                batch = args[spec.batch_argnum]
                if self._mesh is None and hasattr(batch, 'shape'):
                    device = getattr(self, '_device', None)
                    if device is not None:
                        batch = jax.ShapeDtypeStruct(
                            batch.shape, batch.dtype,
                            sharding=SingleDeviceSharding(device))
                        args[spec.batch_argnum] = batch
                with self._aot_lock, self.precision_scope():
                    key = self._aot_dispatch_key(
                        spec.name, batch, dict(spec.kwargs))
                    if key in self._aot_programs:
                        continue
                    prog = self._aot_ensure(spec.name, spec.jitted,
                                            tuple(args),
                                            dict(spec.kwargs))
                    self._aot_programs[key] = prog or _AOT_FALLBACK
            except Exception:
                from video_features_tpu.aot import log_aot_error
                log_aot_error(f'warm {self.feature_type}/{spec.name}')
        return {k: self.aot_stats[k] - before.get(k, 0)
                for k in ('loaded', 'compiled')}

    def aot_snapshot(self) -> Dict[str, Any]:
        """The run-manifest / metrics view of this extractor's AOT
        state: which path each resident program took, plus the pinned
        lock hashes the programs derive from."""
        doc: Dict[str, Any] = {'enabled': self._aot_store is not None,
                               'loaded': self.aot_stats['loaded'],
                               'compiled': self.aot_stats['compiled']}
        if self._aot_store is not None:
            doc['dir'] = self._aot_store.aot_dir
            # keyed by name + program identity, NOT name alone: one
            # name covers several geometry specializations (s3d/i3d),
            # and an audit surface must list every distinct program —
            # a 'compiled' entry must never be masked by a 'loaded'
            # same-name sibling
            doc['programs'] = {
                f'{prog.name}@{prog.program_sha[:12]}':
                    {'path': prog.source,
                     'stablehlo_sha256': prog.program_sha}
                for prog in self._aot_programs.values()
                if prog is not _AOT_FALLBACK and prog is not None}
        return doc

    # -- decode farm (farm/) ------------------------------------------------

    def configure_farm(self, args) -> None:
        """Normalize the decode-farm knobs onto the extractor. Every
        extractor gets ``decode_workers`` (families that already read it
        for their in-process transform thread pool keep the same value —
        one knob, one meaning: how much host-decode parallelism to buy)
        and ``decode_farm_ring_mb`` (per-worker SHM ring size). Called by
        ``registry.create_extractor``; extractors constructed directly
        keep the in-process default (``decode_workers=1``)."""
        self.decode_workers = max(
            int(args.get('decode_workers', 1) or 1), 1)
        self.decode_farm_ring_mb = max(
            int(args.get('decode_farm_ring_mb', 64) or 64), 1)

    def farm_recipe(self):
        """Picklable decode recipe (``farm/recipes.py``) replaying this
        extractor's decode + host-preprocess stack in a worker PROCESS
        with byte-exact parity, or None when the preprocessing can't be
        described as a spec (the packed scheduler then falls back to
        in-process decode with a structured warning). Families override
        via :class:`StackPackingMixin`/``BaseFrameWiseExtractor``."""
        return None

    def fused_decode_signature(self):
        """Fused-worklist eligibility (``features=[a,b,...]``): families
        whose signatures are EQUAL can share one raw decode pass per
        video (``parallel.packing.run_packed_fused``) because their
        loaders would decode byte-identical frame streams — the
        signature covers everything upstream of the per-frame host
        transform. None (the default) keeps the family out of any fused
        group; it then runs its own sequential pass, outputs unchanged.
        ``BaseFrameWiseExtractor`` overrides for the frame-wise
        families."""
        return None

    # -- flight recorder (obs/) ---------------------------------------------

    def configure_obs(self, args) -> None:
        """Attach the flight recorder when the ``trace_out`` /
        ``manifest_out`` knobs are set: a span recorder on the tracer
        (enabling timing if profiling is off — the printed tables stay
        gated on ``profile``) and a per-run manifest collector. Called by
        ``registry.create_extractor``; extractors constructed directly
        stay legacy."""
        trace_out = args.get('trace_out')
        manifest_out = args.get('manifest_out')
        if args.get('postmortem_dir'):
            # crash-dump black box: CLI/packed runs dump on fatal
            # signals and farm-worker deaths (run_packed hands this to
            # the DecodeFarm supervisor); the serve daemon builds its
            # own server-wide BlackBox instead
            from video_features_tpu.obs.blackbox import BlackBox
            self.blackbox = BlackBox(
                str(args['postmortem_dir']),
                max_bytes=args.get('postmortem_max_bytes'),
                recorders=lambda: [getattr(self.tracer, 'recorder',
                                           None)],
                manifest_fn=lambda: (self.manifest.document()
                                     if self.manifest is not None
                                     else None))
        if not (trace_out or manifest_out):
            return
        # a CLI run is one "request": mint a run-level trace context so
        # per-video spans share one trace_id end to end, like serve
        # requests do
        from video_features_tpu.obs.context import mint
        self.trace_ctx = mint()
        if not self.tracer.enabled:
            self.tracer = Tracer(enabled=True)
        if trace_out:
            from video_features_tpu.obs.spans import (
                DEFAULT_CAPACITY, SpanRecorder,
            )
            self.trace_out = str(trace_out)
            self.tracer.recorder = SpanRecorder(
                int(args.get('trace_capacity') or DEFAULT_CAPACITY))
        if manifest_out:
            from video_features_tpu.obs.manifest import RunManifest
            self.manifest_out = str(manifest_out)
            self.manifest = RunManifest(args)
            try:
                # which PINNED programs this family maps to
                # (PROGRAMS.lock.json): a production trace then names
                # exactly which contract-checked program ran
                from video_features_tpu.analysis.programs import (
                    family_lock_hashes,
                )
                hashes = family_lock_hashes(self.feature_type)
                if hashes:
                    self.manifest.note_programs_lock(
                        {self.feature_type: hashes})
            except Exception:
                # vft-lint: ok=swallowed-exception — telemetry never
                # fails a run; an unreadable lock reads as "unpinned"
                pass

    def finish_obs(self, export_trace: bool = True) -> None:
        """Publish the run's telemetry artifacts (CLI end-of-run; serve
        worker drain). ``export_trace=False`` skips the trace export for
        callers that own a merged export of the same path (the serve
        daemon's server-wide ``trace_out``). Never raises — a failed
        telemetry write must not fail a run whose outputs are already
        durably saved."""
        import logging as _logging

        from video_features_tpu.obs.events import event
        if self.manifest is not None and self.manifest_out:
            try:
                # residual stages (the loops fold+reset as they go; this
                # catches anything recorded since the last reset)
                self.manifest.fold_stages(self.tracer.report())
                if self._aot_store is not None:
                    # which path every program took (loaded vs compiled)
                    # — the manifest record the zero-cold-start contract
                    # is audited against
                    self.manifest.note_aot(self.aot_snapshot())
                self.manifest.write(self.manifest_out)
            except Exception:
                event(_logging.WARNING, 'run-manifest write failed',
                      exc_info=True, path=self.manifest_out)
        rec = getattr(self.tracer, 'recorder', None)
        if export_trace and rec is not None and self.trace_out:
            try:
                rec.export(self.trace_out)
            except Exception:
                event(_logging.WARNING, 'trace export failed',
                      exc_info=True, path=self.trace_out)

    # -- abstract program specs (analysis/programs.py: vft-programs) --------
    #
    # The program contract checker lowers each family's ACTUAL jitted
    # step at a canonical abstract geometry and pins the signature in
    # PROGRAMS.lock.json (docs/static_analysis.md "Program contracts").
    # Families override program_specs; the helpers below build the
    # abstract (ShapeDtypeStruct) inputs, sharded over a data mesh when
    # the checker pins a mesh-width variant.

    # canonical raw decode geometry (H, W) the program lock pins — one
    # representative shape; the contract is about dtypes/donation/
    # sharding/closure, which are geometry-independent
    PROGRAM_DECODE_HW = (240, 320)

    def program_specs(self, mesh=None) -> list:
        """Abstract AOT program specs for the vft-programs checker: the
        exact jitted callables the hot paths dispatch, paired with
        abstract inputs at the family's canonical lock geometry — the
        batch sharded over ``mesh``'s data axis when given. Families
        override; an empty list reads as "not covered" and is itself a
        checker finding for the eight known families."""
        return []

    def _abstract_params(self, mesh=None):
        """``self.params`` as ShapeDtypeStructs (replicated over ``mesh``
        when given) — lowering needs shapes/dtypes, never values."""
        import jax
        sharding = None
        if mesh is not None:
            from video_features_tpu.parallel.mesh import replicated
            sharding = replicated(mesh)
        return jax.tree_util.tree_map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype,
                                           sharding=sharding)
            if hasattr(x, 'shape') else x, self.params)

    def _abstract_batch(self, shape, dtype, mesh=None):
        """One abstract device batch, leading axis sharded over the data
        mesh when given (the packed loop's put_input layout)."""
        import jax
        sharding = None
        if mesh is not None:
            from video_features_tpu.parallel.mesh import batch_sharding
            sharding = batch_sharding(mesh)
        return jax.ShapeDtypeStruct(tuple(shape), dtype, sharding=sharding)

    def _program_batch_slots(self, mesh=None) -> int:
        """Global batch rows at the lock geometry: the family's
        per-device capacity × the mesh's data-axis size — the same
        ``plan_device_batch`` arithmetic the packed loop runs."""
        if self.supports_packing:
            capacity = self.packed_batch_size()
        else:
            capacity = int(getattr(self, 'batch_size', 1) or 1)
        if mesh is None:
            return capacity
        from video_features_tpu.parallel.mesh import plan_device_batch
        return plan_device_batch(capacity, mesh)

    def executable_cost(self, batch):
        """Best-effort XLA ``cost_analysis`` (FLOPs / bytes accessed) of
        the compiled step at ``batch``'s geometry — the run-manifest
        ``executables`` section. Works for families that follow the
        ``self._step = jax.jit(...)``, ``self._step(self.params, batch)``
        convention; returns None anywhere the convention doesn't hold.
        An optimization report, never a requirement."""
        step = getattr(self, '_step', None)
        params = getattr(self, 'params', None)
        if step is None or params is None or not hasattr(step, 'lower'):
            return None
        from video_features_tpu.obs.manifest import xla_cost_analysis
        return xla_cost_analysis(step, params, batch)

    def _video_cache_key(self, video_path: str, segment=None) -> str:
        from video_features_tpu.cache import video_cache_key
        return video_cache_key(video_path, self.run_fingerprint,
                               segment=segment)

    def cache_fetch(self, video_path: str, output_path: str = None,
                    segment=None, name_path: str = None) -> bool:
        """Serve this video's outputs from the cache if present: a hit
        atomically materializes byte-identical files under the output
        root (plus the resume sidecar) WITHOUT decoding or running the
        network. Cache failures degrade to a miss, never to a failed
        video. ``segment`` keys a range extraction separately from the
        full video; ``name_path`` (the segment-suffixed pseudo-path)
        names the materialized files — content hashing always uses the
        real ``video_path``."""
        if self.cache is None or self.run_fingerprint is None:
            return False
        out_root = output_path or self.output_path
        from video_features_tpu.cache import log_cache_error
        try:
            hit = self.cache.fetch_to(
                self._video_cache_key(video_path, segment),
                out_root, name_path or video_path,
                fingerprint=self.run_fingerprint)
        except Exception:
            log_cache_error(f'lookup for {video_path}')
            return False
        if hit:
            # reference-parity progress line; stdout-safe by construction:
            # the cache is warn-and-disabled under on_extraction=print
            # (sanity_check), so this never interleaves with features
            # vft-lint: ok=stdout-purity — save-mode-only progress line
            print(f'Features for {video_path} served from cache into '
                  f'{Path(out_root).absolute()}/ - skipping extraction..')
        return hit

    def cache_publish(self, video_path: str, output_path: str = None,
                      segment=None, name_path: str = None) -> None:
        """Publish the just-saved output files into the cache (exact
        bytes, so every future hit is byte-identical to this cold run)."""
        if self.cache is None or self.run_fingerprint is None:
            return
        out_root = output_path or self.output_path
        ext = ACTION_TO_EXT[self.on_extraction]
        name = name_path or video_path
        files = {key: (make_path(out_root, name, key, ext), ext)
                 for key in self._saved_feat_keys()}
        if not all(os.path.exists(src) for src, _ in files.values()):
            return                       # partial save (failed video): skip
        from video_features_tpu.cache import hash_file, log_cache_error
        try:
            # the video CONTENT hash (memoized — the cache key derivation
            # already paid for it) rides in the meta so downstream
            # consumers (the feature index) can group rows by source
            # video without re-reading it
            self.cache.put(self._video_cache_key(video_path, segment),
                           files,
                           meta={'video': Path(name).name,
                                 'feature_type': self.feature_type,
                                 'video_sha256': hash_file(video_path)})
        except Exception:
            log_cache_error(f'publish for {video_path}')

    # -- per-video driver ---------------------------------------------------

    def _extract(self, video_path: str) -> None:
        """Fault-isolating wrapper around :meth:`extract` for the work loop."""
        recorder = getattr(self.tracer, 'recorder', None)
        t0_video = _time.perf_counter() if recorder is not None else 0.0
        # per-video child span under the run-level trace (vft-flight)
        video_ctx = (self.trace_ctx.child()
                     if self.trace_ctx is not None else None)
        outcome = 'failed'
        try:
            if self.is_already_exist(video_path):
                outcome = 'skipped'
                return
            if self.cache is not None:
                with self.tracer.stage('cache_lookup',
                                       video=str(video_path)):
                    hit = self.cache_fetch(video_path)
                if hit:
                    outcome = 'cached'
                    return
            feats_dict = self.extract(video_path)
            feats_dict = self._maybe_concat_streams(feats_dict)
            with self.tracer.stage('save', video=str(video_path)):
                self.action_on_extraction(feats_dict, video_path)
            if self.cache is not None:
                with self.tracer.stage('cache_publish',
                                       video=str(video_path)):
                    self.cache_publish(video_path)
            outcome = ('saved' if self.on_extraction in ACTION_TO_EXT
                       else 'printed')
        except KeyboardInterrupt:
            raise
        except Exception:
            outcome = 'failed'
            log_extraction_error(video_path)
        finally:
            # report+reset even on failure so one bad video's timings never
            # leak into the next video's table; the run manifest keeps the
            # whole-run aggregate by folding each video's report first
            if self.tracer.enabled:
                rep = self.tracer.report()
                if rep:
                    if self.manifest is not None:
                        self.manifest.fold_stages(rep)
                    if self.profile:
                        # stderr: the stage table is a diagnostic, and
                        # with on_extraction=print stdout carries features
                        print(f'--- stage timing: {video_path}',
                              file=sys.stderr)
                        print(self.tracer.summary(), file=sys.stderr)
                    self.tracer.reset()
            if self.manifest is not None:
                self.manifest.video_done(video_path, outcome)
            if recorder is not None:
                recorder.span('video', t0_video, _time.perf_counter(),
                              video=str(video_path), outcome=outcome,
                              **(video_ctx.attrs()
                                 if video_ctx is not None else {}))

    def extract(self, video_path: str) -> Dict[str, np.ndarray]:
        raise NotImplementedError

    # -- packed corpus mode (pack_across_videos=true) -----------------------
    #
    # The batch-major outer loop: instead of draining one video at a time
    # (leaving every video's last batch mostly padded and paying pipeline
    # ramp per video), the scheduler in parallel.packing fills every device
    # batch across video boundaries and scatters features back per video.
    # Subclasses opt in by setting ``supports_packing = True`` and
    # implementing the three hooks below; every per-video contract (output
    # files, resume, fault isolation) is preserved by the scheduler.

    supports_packing = False

    def packed_batch_size(self) -> int:
        """Window slots per packed device batch (the compiled batch)."""
        return int(self.batch_size)

    def _packed_setup(self) -> None:
        """One-time pre-run setup (e.g. lazy data-parallel mesh build) —
        runs before ``packed_batch_size`` is read."""

    def packed_windows(self, task):
        """Yield ``(window, meta)`` for one video, in window order.

        ``window`` is the host array one batch slot carries (a frame stack
        or a single frame); ``meta`` is per-window metadata scattered back
        alongside the features (e.g. a timestamp), or None. Video-level
        metadata goes in ``task.info``. ``task.segment`` (when set) is a
        ``(start_s, end_s)`` time range: implementations must emit only
        the windows overlapping it and stop decoding past its end.
        """
        raise NotImplementedError

    def live_window_spec(self):
        """How to window RAW network frames for a live session, or None
        when the family can't (``registry.LIVE_FEATURES`` mirrors this).
        Returns ``(win, step, transform, timed)``: window length / stride
        in frames, an optional per-frame host transform (HWC uint8 →
        model-ready frame), and whether per-window meta is a timestamp
        (frame-wise families) or None (stack families). The live-session
        layer (``ingress/live.py``) replays the exact windowing the
        packed path applies to decoded files, so a live session's windows
        feed the same compiled step."""
        return None

    def packed_step(self, batch) -> Dict:
        """One compiled device step on a packed ``(B, ...)`` batch →
        ``{key: (B, D) DEVICE array}`` — the step DISPATCHES and returns
        without forcing a device→host readback (no ``np.asarray``); the
        scheduler materializes results later via :meth:`fetch_outputs`,
        k batches behind dispatch, so D2H and host finalization overlap
        device compute. Geometry-dependent state (pads, resize,
        per-shape executables) is derived from ``batch.shape`` and cached
        by the implementation."""
        raise NotImplementedError

    def packed_result(self, task) -> Dict[str, np.ndarray]:
        """Assemble one video's feats_dict from its scattered rows
        (``task.rows`` / ``task.meta_rows`` / ``task.info``) — the same
        mapping :meth:`extract` returns for that video."""
        raise NotImplementedError

    def extract_packed(self, video_paths, decode_ahead: int = 2,
                       batch_size: int = None, on_video_done=None,
                       max_pool_age_s: float = None,
                       inflight: int = None,
                       decode_workers: int = None) -> None:
        """Run the whole worklist batch-major (see parallel.packing).

        ``video_paths`` may be any (lazily consumed, possibly blocking)
        iterable of paths / ``VideoTask``s / ``FLUSH`` sentinels — the
        serving layer feeds a live request queue through here;
        ``on_video_done(task)`` fires as each video finalizes;
        ``max_pool_age_s`` bounds how long a partial geometry pool may
        wait for batch-mates (dynamic sources only — a static worklist
        wants maximally full batches); ``inflight`` overrides the
        extractor's output-side pipelining depth (1 = synchronous);
        ``decode_workers`` overrides the input side's parallelism (>1 =
        the multi-process decode farm, 1 = in-process decode)."""
        if not self.supports_packing:
            raise NotImplementedError(
                f'{type(self).__name__} does not support pack_across_videos')
        from video_features_tpu.parallel.packing import run_packed
        run_packed(self, video_paths, batch_size=batch_size,
                   decode_ahead=decode_ahead, on_video_done=on_video_done,
                   max_pool_age_s=max_pool_age_s, inflight=inflight,
                   decode_workers=decode_workers)


    def _maybe_concat_streams(self, feats_dict: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
        """rgb||flow → single (T, 2C) array under 'rgb' when configured.

        Preserves the fork's flagship output (reference
        base_extractor.py:46-50) without breaking single-stream extractors.
        """
        if self.concat_rgb_flow and 'rgb' in feats_dict and 'flow' in feats_dict:
            feats_dict = dict(feats_dict)
            flow = feats_dict.pop('flow')
            feats_dict['rgb'] = np.concatenate((feats_dict['rgb'], flow), axis=1)
        return feats_dict

    # -- output actions -----------------------------------------------------

    def action_on_extraction(self, feats_dict: Dict[str, np.ndarray], video_path: str,
                             output_path: str = None) -> None:
        """``output_path`` (default: the extractor's configured root)
        routes this one video's files elsewhere — the serving layer passes
        each request's root through a shared warm extractor."""
        out_root = output_path or self.output_path
        if self.on_extraction in ACTION_TO_EXT and \
                self.is_already_exist(video_path, output_path=out_root):
            # A concurrent worker finished this video while we extracted
            # it. obs.events, not warnings.warn: the default warnings
            # filter dedups a constant message per process, and an
            # operator watching a long-lived daemon needs EVERY
            # occurrence of this double-work race, not just the first.
            event(_logging.WARNING,
                  'extraction didnt find feature files on the 1st try '
                  'but did on the 2nd try', video=str(video_path))
            return

        for key, value in feats_dict.items():
            if self.on_extraction == 'print':
                print(key)
                print(value)
                print(f'max: {value.max():.8f}; mean: {value.mean():.8f}; min: {value.min():.8f}')
                print()
            elif self.on_extraction in ACTION_TO_EXT:
                os.makedirs(out_root, exist_ok=True)
                fpath = make_path(out_root, video_path, key,
                                  ACTION_TO_EXT[self.on_extraction])
                if key != 'fps' and len(value) == 0:
                    warnings.warn(
                        f'the value is empty for {key} @ {fpath}')
                ACTION_TO_SAVE[self.on_extraction](fpath, value)
            else:
                raise NotImplementedError(
                    f'on_extraction: {self.on_extraction} is not implemented')
        if self.on_extraction in ACTION_TO_EXT \
                and self.run_fingerprint is not None:
            # resume sidecar: records which config+weights produced these
            # files, so a later run under a DIFFERENT recipe re-extracts
            # instead of silently reusing them (is_already_exist)
            write_fingerprint(out_root, video_path, self.run_fingerprint)

    def is_already_exist(self, video_path: Union[str, Path],
                         output_path: str = None) -> bool:
        """True iff every output file exists and loads cleanly (resume contract)."""
        if self.on_extraction not in ACTION_TO_EXT:
            return False

        out_root = output_path or self.output_path
        keys = self._saved_feat_keys()
        for key in keys:
            fpath = make_path(out_root, video_path, key,
                              ACTION_TO_EXT[self.on_extraction])
            if not Path(fpath).exists():
                return False
            try:
                ACTION_TO_LOAD[self.on_extraction](fpath)
            except Exception:
                # Corrupted (e.g. a worker died mid-write) → re-extract;
                # SAY so — a silently re-extracting resume loop hides
                # recurring corruption (bad disk, torn writers)
                event(_logging.WARNING,
                      'existing output failed to load; re-extracting',
                      exc_info=True, video=str(video_path),
                      path=str(fpath))
                return False
        if self.run_fingerprint is not None:
            recorded = read_fingerprint(out_root, video_path)
            if recorded is not None and recorded != self.run_fingerprint:
                # config-aware resume: these files came from a DIFFERENT
                # config/checkpoint recipe — reusing them would hand the
                # caller features from a run they didn't ask for.
                # warnings.warn (stderr), not print: with
                # on_extraction=print the feature stream owns stdout
                warnings.warn(
                    f'Existing outputs for {video_path} in '
                    f'{Path(out_root).absolute()}/ were produced under a '
                    f'different config/checkpoint (fingerprint '
                    f'{recorded[:12]} != {self.run_fingerprint[:12]}) — '
                    're-extracting instead of reusing them')
                return False
            # no sidecar: pre-fingerprint outputs keep the legacy skip
            # (absence can't prove staleness)
        # reference-parity resume line (pinned by the CLI-equivalence
        # tests); save-mode only — is_already_exist returns False up top
        # for on_extraction=print, so this never touches the stream
        # vft-lint: ok=stdout-purity — save-mode-only progress line
        print(f'Features for {video_path} already exist in '
              f'{Path(out_root).absolute()}/ - skipping..')
        return True

    def _saved_feat_keys(self) -> List[str]:
        """Keys that actually reach disk, accounting for the concat folding 'flow' into 'rgb'."""
        keys = list(self.output_feat_keys)
        if self.concat_rgb_flow and 'rgb' in keys and 'flow' in keys:
            keys.remove('flow')
        return keys


class StackPackingMixin:
    """Shared packed hooks for stack families that window RAW decode
    frames into ``stack_batch``-sized device batches (r21d, s3d — i3d
    differs: host resize transform, stack_size+1 windows, multi-stream
    output). One window = one (stack_size, H, W, 3) frame stack; the
    subclass supplies ``packed_step`` and ``packed_feat_dim``."""

    supports_packing = True
    packed_feat_dim: int = 0          # subclasses set the feature width

    def packed_batch_size(self) -> int:
        return int(self.stack_batch)

    def _packed_setup(self) -> None:
        if self.data_parallel:
            self._ensure_mesh('stack_batch')

    def _make_loader(self, video_path: str):
        from video_features_tpu.io.video import VideoLoader
        return VideoLoader(
            video_path, batch_size=64,
            fps=self.extraction_fps, tmp_path=self.tmp_path,
            keep_tmp=self.keep_tmp_files,
            backend=self.decode_backend)

    def packed_windows(self, task):
        from video_features_tpu.extract.streaming import (
            segment_frame_range, stream_windows,
        )
        loader = self._make_loader(task.path)
        # deterministic close (segment early-stop abandons the stream
        # mid-decode; GC-timed release would strand codec contexts and
        # re-encode temps in a long-lived serve worker)
        try:
            for window in stream_windows(
                    loader, self.stack_size, self.step_size,
                    frame_range=segment_frame_range(task.segment,
                                                    loader.fps)):
                yield window, None
        finally:
            loader.close()

    def live_window_spec(self):
        # raw-frame stacks: live frames window exactly like decoded ones
        return (self.stack_size, self.step_size, None, False)

    def packed_result(self, task) -> Dict[str, np.ndarray]:
        rows = task.rows.get(self.feature_type, [])
        return {self.feature_type: (np.stack(rows) if rows
                                    else np.zeros((0, self.packed_feat_dim),
                                                  np.float32))}

    def farm_recipe(self):
        """The stack families decode RAW frames (no host transform), so
        the farm recipe is fully described by the window geometry plus
        the loader knobs ``_make_loader`` passes."""
        from video_features_tpu.farm.recipes import StackRecipe
        return StackRecipe(
            win=self.stack_size, step=self.step_size, batch_size=64,
            fps=self.extraction_fps, total=None, tmp_path=self.tmp_path,
            keep_tmp=self.keep_tmp_files, backend=self.decode_backend,
            transform=None)
