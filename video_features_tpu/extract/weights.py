"""Checkpoint resolution with a loud failure when weights are missing.

The reference always runs real weights — every extractor self-provisions
them (reference models/i3d/extract_i3d.py:180-183 loads bundled .pt files,
models/resnet/extract_resnet.py:38-40 uses torchvision's pretrained enums,
models/r21d/extract_r21d.py:109-118 torch.hub). This framework reads local
checkpoint files instead (TPU hosts are often torch-free and air-gapped), so
a *missing* path must be a hard error: silently falling back to random
weights would hand the user plausible-looking garbage features.

Escape hatches for tests/benches that intentionally run random weights:
  * config: ``allow_random_weights=true``
  * env:    ``VFT_ALLOW_RANDOM_WEIGHTS=1`` (set by the test suite's conftest)

``tools/fetch_checkpoints.py`` provisions real weights from the same sources
the reference downloads from.
"""
from __future__ import annotations

import os
import sys
from typing import Any, Callable, Dict, Optional

ENV_FLAG = 'VFT_ALLOW_RANDOM_WEIGHTS'

# Families tools/fetch_checkpoints.py can provision (its SOURCES keys;
# test_fetch_checkpoints.test_registry_covers_every_family keeps the two in
# sync). Families outside this set (timm) get their weights elsewhere, so
# the missing-checkpoint remediation text must not point at the tool.
FETCHABLE_FAMILIES = frozenset(
    {'clip', 'resnet', 'r21d', 'vggish', 'i3d', 'raft', 's3d'})


class MissingCheckpointError(ValueError):
    """No checkpoint configured and random weights were not explicitly allowed."""


def _get(args: Any, key: str, default: Any = None) -> Any:
    if hasattr(args, 'get'):
        return args.get(key, default)
    return getattr(args, key, default)


def random_weights_allowed(args: Any) -> bool:
    if _get(args, 'allow_random_weights'):
        return True
    return os.environ.get(ENV_FLAG, '').lower() not in ('', '0', 'false')


def require_checkpoint(args: Any, key: str, *, feature_type: str,
                       what: Optional[str] = None) -> Optional[str]:
    """Return ``args[key]``; raise if absent unless random weights are allowed.

    Returns None ONLY when the caller may proceed with random init (the
    explicit escape hatch was set). ``what`` names the weights in messages
    (defaults to the feature type).
    """
    ckpt = _get(args, key)
    if ckpt:
        return str(ckpt)
    what = what or feature_type
    if not random_weights_allowed(args):
        if feature_type in FETCHABLE_FAMILIES:
            provision = (f'Provision real weights with `python '
                         f'tools/fetch_checkpoints.py {feature_type}` '
                         f'(see docs/checkpoints.md).')
        else:
            # timm (and any future bridge-fed family): weights come from
            # pip-timm via the bridge or a user-supplied converted file,
            # not from the fetch tool
            provision = (f'`{feature_type}` weights are not served by '
                         f'tools/fetch_checkpoints.py — export them from a '
                         f'host with pip timm installed, or convert a '
                         f'HuggingFace checkpoint for the native families '
                         f'(`python tools/convert_checkpoint.py '
                         f'--hf-family ...`), then pass the converted .npz '
                         f'via `{key}` (see docs/checkpoints.md).')
        raise MissingCheckpointError(
            f'No checkpoint configured for {what}: set `{key}=<path to a '
            f'.pt/.pth/.npz checkpoint>` (feature_type={feature_type}). '
            f'{provision} To intentionally run RANDOM weights '
            f'(tests/benchmarks only — features will be meaningless), set '
            f'`allow_random_weights=true`.')
    # stderr: diagnostics must never pollute machine-read stdout (the CLI
    # print path and bench.py's one-JSON-line contract)
    print(f'WARNING: {what}: no `{key}` configured — running RANDOM weights '
          f'(allow_random_weights is set). Extracted features are '
          f'meaningless for downstream use.', file=sys.stderr)
    return None


def load_or_init(args: Any, key: str, init_fn: Callable[[], Dict[str, Any]],
                 *, feature_type: str, what: Optional[str] = None,
                 load: Optional[Callable[[str], Dict[str, Any]]] = None,
                 dtype: Any = None,
                 ) -> Dict[str, Any]:
    """Transplanted params from ``args[key]``, or gated random init.

    ``load`` overrides the default :func:`load_torch_checkpoint` for
    families with special checkpoint handling. ``dtype`` is the STORAGE
    dtype floating params are cast to at transplant time (the fast
    lanes' seam — ``compute_dtype=bfloat16`` extractors pass
    ``ml_dtypes.bfloat16`` here so params are bf16 in HBM from the first
    ``device_put``, never cast per-step; ``compute_dtype=int8``
    extractors pass ``np.int8``, which the transplant layer treats as
    "quantize eligible conv/linear weights per-output-channel, float32
    for the rest" — ops/quant.py — consuming any pinned
    ``<ckpt>.int8-scales.npz`` calibration table automatically); None
    keeps the historical float32 default.
    """
    from video_features_tpu.transplant.torch2jax import (
        load_torch_checkpoint, transplant,
    )
    ckpt = require_checkpoint(args, key, feature_type=feature_type, what=what)
    if ckpt:
        if load is not None:
            return load(ckpt)
        return (load_torch_checkpoint(ckpt) if dtype is None
                else load_torch_checkpoint(ckpt, dtype=dtype))
    return transplant(init_fn(), dtype=dtype)
