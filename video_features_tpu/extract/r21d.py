"""R(2+1)D extractor (reference models/r21d/extract_r21d.py behavior).

TPU-first data path: frames stream off the decoder into stack windows
(extract.streaming — bounded memory, decode overlapped with compute via a
prefetch thread), and the jit-compiled step transforms + runs a FIXED-shape
batch of stacks per call (ragged tails padded and masked) so XLA compiles
exactly once per video geometry. The reference instead loads the ENTIRE
video into RAM (extract_r21d.py:72-74) and loops python-side one stack at a
time (extract_r21d.py:81-85).
"""
from __future__ import annotations

from functools import partial
from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np

from video_features_tpu.extract.base import BaseExtractor, StackPackingMixin
from video_features_tpu.models import r21d as r21d_model
from video_features_tpu.ops.transforms import (
    center_crop, normalize, resize_bilinear, to_float_zero_one,
)
from video_features_tpu.utils.device import jax_device

# model_name -> (arch, native stack, native step, pred dataset)
MODEL_CFGS = {
    'r2plus1d_18_16_kinetics': dict(arch='r2plus1d_18', stack_size=16,
                                    step_size=16, dataset='kinetics'),
    'r2plus1d_34_32_ig65m_ft_kinetics': dict(arch='r2plus1d_34', stack_size=32,
                                             step_size=32, dataset='kinetics'),
    'r2plus1d_34_8_ig65m_ft_kinetics': dict(arch='r2plus1d_34', stack_size=8,
                                            step_size=8, dataset='kinetics'),
}

# stacks per device step; tails are padded to this and masked out
STACK_BATCH = 4


class ExtractR21D(StackPackingMixin, BaseExtractor):

    def __init__(self, args) -> None:
        super().__init__(
            feature_type=args.feature_type,
            on_extraction=args.on_extraction,
            tmp_path=args.tmp_path,
            output_path=args.output_path,
            keep_tmp_files=args.keep_tmp_files,
            device=args.device,
            profile=args.get('profile', False),
            precision=args.get('precision', 'highest'),
            inflight=args.get('inflight', 2),
            compute_dtype=args.get('compute_dtype', 'float32'),
        )
        self.model_name = args.model_name
        self.model_def = MODEL_CFGS[self.model_name]
        self.extraction_fps = args.extraction_fps
        self.stack_size = args.stack_size or self.model_def['stack_size']
        self.step_size = args.step_size or self.model_def['step_size']
        self.show_pred = args.show_pred
        self.output_feat_keys = [self.feature_type]
        # stacks per device step (the reference runs one at a time,
        # extract_r21d.py:81-85); with data_parallel this is the global batch
        self.stack_batch = args.get('batch_size') or STACK_BATCH
        # data_parallel=true shards stack batches over all local devices
        # (params replicated, batch data-sharded — same scheme as framewise)
        self.decode_backend = args.get('decode_backend', 'auto')
        self.data_parallel = args.get('data_parallel', False)
        self._device = jax_device(self.device)
        self.params = jax.device_put(self.load_params(args), self._device)
        # dtype rides the partial as a trace-time constant: the float32
        # lane's jitted program is byte-identical to the pre-knob graph
        self._step = jax.jit(
            partial(self._forward_batch, arch=self.model_def['arch'],
                    dtype=self.compute_jnp_dtype))

    # -- model --------------------------------------------------------------

    def load_params(self, args):
        """Transplanted torch checkpoint; missing path is a hard error unless
        random weights are explicitly allowed (extract.weights)."""
        from video_features_tpu.extract.weights import load_or_init
        return load_or_init(
            args, 'checkpoint_path',
            partial(r21d_model.init_state_dict, arch=self.model_def['arch']),
            feature_type='r21d', dtype=self.param_dtype)

    @staticmethod
    def _forward_batch(params, stacks, arch, dtype=None):
        """(B, stack, H, W, 3) uint8 → (B, 512) features.

        Transform chain parity (reference extract_r21d.py:102-107):
        ToFloatTensorInZeroOne → Resize(128, 171) → Normalize → CenterCrop(112).
        ``dtype`` is the bf16 fast lane's activation dtype (trace-time
        constant; None ≡ float32, the byte-identical default graph) —
        features always leave as float32.
        """
        from video_features_tpu.ops.precision import features_to_f32
        x = to_float_zero_one(stacks, dtype)
        x = resize_bilinear(x, (128, 171))
        x = normalize(x, r21d_model.MEAN, r21d_model.STD)
        x = center_crop(x, (112, 112))
        return features_to_f32(
            r21d_model.forward(params, x, arch=arch, features=True))

    # -- packed corpus mode: hooks from StackPackingMixin -------------------

    packed_feat_dim = 512

    def program_specs(self, mesh=None):
        """vft-programs abstract step spec: raw uint8 decode-geometry
        stacks into the one jitted step (in-graph resize/normalize/crop
        + the R(2+1)D forward)."""
        from video_features_tpu.analysis.programs import ProgramSpec
        h, w = self.PROGRAM_DECODE_HW
        batch = self._abstract_batch(
            (self._program_batch_slots(mesh), self.stack_size, h, w, 3),
            np.uint8, mesh)
        return [ProgramSpec('step', self._step,
                            (self._abstract_params(mesh), batch))]

    def packed_step(self, stacks):
        # dispatch only (device array out); the scheduler's deferred
        # fetch_outputs owns the D2H readback. aot_call routes through a
        # resident/store-loaded executable when the aot store is on
        # (byte-identical either way), else it IS the jit call.
        return {self.feature_type:
                self.aot_call('step', self._step, self.params, stacks)}

    # -- extraction ---------------------------------------------------------

    def extract(self, video_path: str) -> Dict[str, np.ndarray]:
        from video_features_tpu.extract.streaming import stream_windows

        if self.data_parallel:
            self._ensure_mesh('stack_batch')
        loader = self._make_loader(video_path)
        windows = stream_windows(loader, self.stack_size, self.step_size,
                                 self.tracer, 'decode')

        from video_features_tpu.extract.streaming import (
            iter_batched_windows, overlap_fetch, transfer_batches,
        )

        feats: list = []
        depth = 1 if self.show_pred else self.inflight

        def dispatched():
            # decode thread assembles + transfers stack batch k+1 while
            # the device runs k (see streaming.transfer_batches); 'model'
            # is dispatch only, the deferred readback is the 'd2h' stage
            for stacks, _, valid, window_idx in transfer_batches(
                    iter_batched_windows(windows, self.stack_batch),
                    self.put_input, tracer=self.tracer):
                with self.tracer.stage('model'):
                    dev = self.aot_call('step', self._step,
                                        self.params, stacks)
                yield dev, valid, window_idx

        with self.precision_scope():
            for out, valid, window_idx in overlap_fetch(
                    dispatched(), self.fetch_outputs, depth, self.tracer):
                out = out[:valid]
                feats.append(out)
                if self.show_pred:
                    for k in range(valid):
                        start = (window_idx + k) * self.step_size
                        self.maybe_show_pred(out[k:k + 1], start,
                                             start + self.stack_size)

        feats = (np.concatenate(feats, axis=0) if feats
                 else np.zeros((0, 512), np.float32))
        return {self.feature_type: feats}

    def maybe_show_pred(self, visual_feats: np.ndarray, start_idx: int, end_idx: int):
        if self.show_pred:
            from video_features_tpu.ops.nn import linear
            from video_features_tpu.utils.preds import show_predictions_on_dataset
            logits = np.asarray(linear(jnp.asarray(visual_feats), self.params['fc']))
            # vft-lint: ok=stdout-purity — show_pred narration surface
            print(f'At frames ({start_idx}, {end_idx})')
            show_predictions_on_dataset(logits, self.model_def['dataset'])
