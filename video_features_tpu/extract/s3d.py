"""S3D extractor (reference models/s3d/extract_s3d.py behavior).

Transform parity (reference extract_s3d.py:30-35 — kylemin/S3D convention,
deliberately NO normalization): ToFloatTensorInZeroOne → Resize(224,
short side, torch bilinear) → CenterCrop(224). Default extraction_fps=25,
stack/step 64 (configs/s3d.yml). Partial final stacks are dropped.
"""
from __future__ import annotations

from functools import partial
from typing import Dict

import jax
import numpy as np

from video_features_tpu.extract.base import BaseExtractor, StackPackingMixin
from video_features_tpu.models import s3d as s3d_model
from video_features_tpu.ops.transforms import (
    center_crop, resize_bilinear_scale, to_float_zero_one,
)
from video_features_tpu.utils.device import jax_device

STACK_BATCH = 1  # 64-frame stacks are large; one per device step


class ExtractS3D(StackPackingMixin, BaseExtractor):

    def __init__(self, args) -> None:
        super().__init__(
            feature_type=args.feature_type,
            on_extraction=args.on_extraction,
            tmp_path=args.tmp_path,
            output_path=args.output_path,
            keep_tmp_files=args.keep_tmp_files,
            device=args.device,
            profile=args.get('profile', False),
            precision=args.get('precision', 'highest'),
            inflight=args.get('inflight', 2),
            compute_dtype=args.get('compute_dtype', 'float32'),
        )
        self.stack_size = args.stack_size
        self.step_size = args.step_size
        self.extraction_fps = args.extraction_fps
        self.show_pred = args.show_pred
        self.output_feat_keys = [self.feature_type]
        # stacks per device step; 64-frame stacks are large, so default 1
        self.stack_batch = args.get('batch_size') or STACK_BATCH
        self.decode_backend = args.get('decode_backend', 'auto')
        self.data_parallel = args.get('data_parallel', False)
        self._device = jax_device(self.device)
        self.params = jax.device_put(self.load_params(args), self._device)
        # the jit step is static per decode geometry (the short-side-224
        # resize scale); cache one executable per (h, w) so a corpus of
        # same-geometry videos compiles exactly once
        self._geom_steps: dict = {}

    def load_params(self, args):
        from video_features_tpu.extract.weights import load_or_init
        return load_or_init(args, 'checkpoint_path', s3d_model.init_state_dict,
                            feature_type='s3d', dtype=self.param_dtype)

    @staticmethod
    def _forward(params, stacks, resize_hw, resize_scale, dtype=None):
        from video_features_tpu.ops.precision import features_to_f32
        x = to_float_zero_one(stacks, dtype)
        # the reference's short-side Resize(224) interpolates at the GIVEN
        # scale 224/min(h, w), not out/in (reference models/transforms.py:
        # 76-96, scale_factor + recompute_scale_factor=False)
        x = resize_bilinear_scale(x, resize_hw, resize_scale)
        x = center_crop(x, (224, 224))
        return features_to_f32(s3d_model.forward(params, x, features=True))

    def _geometry_step(self, h: int, w: int):
        """(jitted step, resize_hw, scale) for decode geometry (h, w).

        Short-side 224 at the GIVEN scale 224/min(h, w): BOTH the output
        sizes and the sampling grid follow torch's
        F.interpolate(scale_factor=s, recompute_scale_factor=False) —
        sizes are floor(dim * s) with the exact float s (e.g.
        floor(480 * (224/336)) = 319, and a 107px short side floors to
        223, not 224 — the subsequent CenterCrop then behaves exactly
        like the reference's). Cached per (h, w) so a whole corpus of
        same-geometry videos compiles once.
        """
        cached = self._geom_steps.get((h, w))
        if cached is None:
            import math
            # bound the executable cache: each entry retains a compiled
            # XLA program + buffers, and a long heterogeneous corpus must
            # not accumulate them without limit (FIFO eviction trades a
            # recompile for bounded memory; real corpora cluster into a
            # handful of aspect ratios, so evictions are rare)
            if len(self._geom_steps) >= 16:
                self._geom_steps.pop(next(iter(self._geom_steps)))
                # the evicted geometry's resident AOT executable must
                # retire with its jitted step (the cap bounds live
                # executables, and the aot table is per-geometry too)
                self._aot_invalidate()
            scale = 224.0 / min(h, w)
            resize_hw = (math.floor(h * scale), math.floor(w * scale))
            step = jax.jit(partial(self._forward, resize_hw=resize_hw,
                                   resize_scale=scale,
                                   dtype=self.compute_jnp_dtype))
            cached = self._geom_steps[(h, w)] = (step, resize_hw, scale)
        return cached

    # -- packed corpus mode: hooks from StackPackingMixin -------------------

    packed_feat_dim = s3d_model.FEAT_DIM

    def program_specs(self, mesh=None):
        """vft-programs abstract step spec: the per-geometry jitted step
        at the canonical lock geometry (one executable per (h, w) — the
        lock pins the count at ONE geometry; the per-shape cache is the
        family's own executable-growth bound)."""
        from video_features_tpu.analysis.programs import ProgramSpec
        h, w = self.PROGRAM_DECODE_HW
        step, _, _ = self._geometry_step(h, w)
        batch = self._abstract_batch(
            (self._program_batch_slots(mesh), self.stack_size, h, w, 3),
            np.uint8, mesh)
        return [ProgramSpec('step', step,
                            (self._abstract_params(mesh), batch))]

    def packed_step(self, stacks):
        # dispatch only (device array out); the scheduler's deferred
        # fetch_outputs owns the D2H readback. aot_call's dispatch key
        # includes the batch geometry, so each per-(h, w) jitted step
        # resolves to its own resident/store-loaded executable.
        step, _, _ = self._geometry_step(*stacks.shape[2:4])
        return {self.feature_type:
                self.aot_call('step', step, self.params, stacks)}

    def extract(self, video_path: str) -> Dict[str, np.ndarray]:
        from video_features_tpu.extract.streaming import stream_windows

        if self.data_parallel:
            self._ensure_mesh('stack_batch')
        loader = self._make_loader(video_path)
        windows = stream_windows(loader, self.stack_size, self.step_size,
                                 self.tracer, 'decode')

        from video_features_tpu.extract.streaming import (
            iter_batched_windows, overlap_fetch, transfer_batches,
        )

        feats: list = []
        depth = 1 if self.show_pred else self.inflight

        def dispatched():
            # decode thread assembles + transfers stack batch k+1 while
            # the device runs k; the host batch rides along for show_pred
            # (see streaming.transfer_batches). 'model' is dispatch only;
            # the deferred readback is the 'd2h' stage in overlap_fetch.
            for stacks, host_stacks, valid, window_idx in transfer_batches(
                    iter_batched_windows(windows, self.stack_batch),
                    self.put_input, keep_host=self.show_pred,
                    tracer=self.tracer):
                step, resize_hw, scale = \
                    self._geometry_step(*stacks.shape[2:4])
                with self.tracer.stage('model'):
                    dev = self.aot_call('step', step, self.params, stacks)
                yield dev, host_stacks, valid, window_idx, resize_hw, scale

        with self.precision_scope():
            for out, host_stacks, valid, window_idx, resize_hw, scale in \
                    overlap_fetch(dispatched(), self.fetch_outputs, depth,
                                  self.tracer):
                out = out[:valid]
                feats.append(out)
                if self.show_pred:
                    for k in range(valid):
                        start = (window_idx + k) * self.step_size
                        self.maybe_show_pred(host_stacks[k:k + 1], start,
                                             start + self.stack_size,
                                             resize_hw, scale)

        feats = (np.concatenate(feats, axis=0) if feats
                 else np.zeros((0, s3d_model.FEAT_DIM), np.float32))
        return {self.feature_type: feats}

    def maybe_show_pred(self, stacks, start_idx, end_idx, resize_hw, scale):
        import jax.numpy as jnp
        from video_features_tpu.utils.preds import show_predictions_on_dataset
        x = to_float_zero_one(jnp.asarray(stacks))
        x = resize_bilinear_scale(x, resize_hw, scale)
        x = center_crop(x, (224, 224))
        logits = np.asarray(s3d_model.forward(self.params, x, features=False))
        # vft-lint: ok=stdout-purity — show_pred narration surface
        print(f'At frames ({start_idx}, {end_idx})')
        show_predictions_on_dataset(logits, 'kinetics')
