"""CLIP frame-wise extractor (reference models/clip/extract_clip.py).

Transform parity with the reference's `_transform` (reference
clip_src/clip.py: Resize(n_px, BICUBIC) → CenterCrop(n_px) → ToTensor →
Normalize(CLIP mean/std)): the resize+crop runs on the host (PIL bicubic),
scale+normalize are fused into the jitted encode_image step.

``show_pred`` is zero-shot classification: cosine-similarity logits against
Kinetics-400 ``"a photo of {label}"`` prompts or user ``pred_texts``
(reference extract_clip.py:32-40,86-108). Text features are encoded once
per run and cached.
"""
from __future__ import annotations

from functools import partial
from typing import List, Optional

import jax
import numpy as np

from video_features_tpu.extract.framewise import BaseFrameWiseExtractor
from video_features_tpu.models import clip as clip_model
from video_features_tpu.ops.transforms import (
    center_crop_host, normalize, resize_pil, to_float_zero_one,
)
from video_features_tpu.utils.device import jax_device


class ExtractCLIP(BaseFrameWiseExtractor):

    def __init__(self, args) -> None:
        self.model_name = args.model_name
        if (self.model_name != 'custom'
                and self.model_name not in clip_model.VISUAL_CFGS):
            raise NotImplementedError(
                f'model_name {self.model_name!r}; known: '
                f'{", ".join(clip_model.VISUAL_CFGS)} or "custom"')
        state_dict, params = self._load_state_dict(args)
        if self.model_name != 'custom':
            self.arch = self.model_name
        elif params is not None:  # pre-transplanted .npz: infer from pytree
            self.arch = clip_model.infer_model_name_from_params(params)
        else:
            self.arch = clip_model.infer_model_name(state_dict)
        cfg = clip_model.VISUAL_CFGS[self.arch]
        super().__init__(args, feat_dim=cfg['embed_dim'])
        self.input_resolution = cfg['input_resolution']
        self.pred_texts: Optional[List[str]] = (
            list(args.pred_texts) if args.get('pred_texts') else None)
        self._device = jax_device(self.device)
        if params is None:
            from video_features_tpu.transplant.torch2jax import transplant
            # param_dtype: float32 upcast of the fp16 OpenAI checkpoints
            # by default; the bf16 fast lane stores bf16 in HBM instead
            params = transplant(state_dict,
                                no_transpose=set(clip_model.NO_TRANSPOSE),
                                dtype=self.param_dtype)
        self.params = jax.device_put(params, self._device)
        self._step = jax.jit(partial(self._forward, arch=self.arch,
                                     dtype=self.compute_jnp_dtype))
        self._text_feats: Optional[np.ndarray] = None

    def _load_state_dict(self, args):
        """Checkpoint sources → (torch_state_dict, transplanted_params);
        exactly one is non-None. Sources: explicit path (a torch .pt/.pth,
        or a pre-transplanted .npz for torch-free hosts — see
        docs/checkpoints.md), or 'custom' → CLIP-custom.pth (reference
        extract_clip.py:55-61). OpenAI URL download needs network — a local
        path must be provided in this environment."""
        ckpt = args.get('checkpoint_path')
        if self.model_name == 'custom' and not ckpt:
            ckpt = './checkpoints/CLIP-custom.pth'
        if not ckpt:
            # hard error unless random weights are explicitly allowed —
            # the reference always downloads real CLIP weights
            # (clip_src/clip.py:32-74)
            from video_features_tpu.extract.weights import require_checkpoint
            require_checkpoint(args, 'checkpoint_path', feature_type='clip',
                               what=f'clip ({self.model_name})')
        if ckpt and str(ckpt).endswith('.npz'):
            # via load_torch_checkpoint for the same float32 upcast the
            # .pt path (and every other extractor) applies — or the bf16
            # storage cast / int8 weight quantization when a fast lane is
            # on. args because this runs before super().__init__ sets
            # self.compute_dtype.
            from video_features_tpu.ops.precision import param_np_dtype
            from video_features_tpu.transplant.torch2jax import (
                load_torch_checkpoint,
            )
            return None, load_torch_checkpoint(
                ckpt, dtype=param_np_dtype(
                    args.get('compute_dtype', 'float32')))
        if ckpt:
            import torch
            sd = torch.load(ckpt, map_location='cpu', weights_only=False)
            if hasattr(sd, 'state_dict'):  # jit-archived OpenAI models
                sd = sd.state_dict()
            if isinstance(sd, dict) and 'state_dict' in sd:
                sd = sd['state_dict']
            return sd, None
        return clip_model.init_state_dict(model_name=args.model_name), None

    @staticmethod
    def _forward(params, batch, arch, dtype=None):
        from video_features_tpu.ops.precision import features_to_f32
        from video_features_tpu.ops.quant import dequantize_tree
        # int8 lane: expand QuantizedTensor weights in-graph; structural
        # identity (same StableHLO) on the fp32/bf16 lanes' plain trees
        params = dequantize_tree(params, dtype)
        x = to_float_zero_one(batch, dtype)
        x = normalize(x, clip_model.MEAN, clip_model.STD)
        return features_to_f32(clip_model.encode_image(params, x, arch))

    def host_transform(self, frame: np.ndarray) -> np.ndarray:
        n_px = self.input_resolution
        frame = resize_pil(frame, n_px, interpolation='bicubic')
        return center_crop_host(frame, n_px)

    def host_transform_spec(self):
        n_px = self.input_resolution
        return ('edge_resize_crop', n_px, n_px, 'bicubic')

    def device_step(self, batch: np.ndarray) -> jax.Array:
        # aot_call: resident/store-loaded executable when the aot store
        # is on (byte-identical), else exactly the jit call
        return self.aot_call('step', self._step, self.params, batch)

    # -- zero-shot show_pred -------------------------------------------------

    def _get_text_feats(self):
        if getattr(self, '_text_feats_resolved', False):
            return self._text_feats, self._classes
        self._text_feats_resolved = True
        from video_features_tpu.utils.clip_tokenizer import tokenize
        from video_features_tpu.utils.preds import load_label_map
        if self.pred_texts is not None:
            self._classes = self.pred_texts
        else:
            labels = load_label_map('kinetics')
            if labels is None:
                # vft-lint: ok=stdout-purity — show_pred narration surface
                print('show_pred: no Kinetics label map available — skipping')
                self._classes = None
                return None, None
            self._classes = [f'a photo of {label}' for label in labels]
        tokens = tokenize(self._classes)
        # one-shot narration path: dequantize eagerly for the int8 lane
        # (identity otherwise) — the text tower reads raw weight arrays
        from video_features_tpu.ops.quant import dequantize_tree
        feats = jax.jit(partial(clip_model.encode_text, model_name=self.arch))(
            dequantize_tree(self.params), tokens)
        self._text_feats = feats
        return self._text_feats, self._classes

    def maybe_show_pred(self, feats: np.ndarray) -> None:
        from video_features_tpu.utils.preds import show_predictions_on_dataset
        try:
            text_feats, classes = self._get_text_feats()
        except FileNotFoundError as e:
            # vft-lint: ok=stdout-purity — show_pred narration surface
            print(f'show_pred unavailable: {e}')
            return
        if text_feats is None:
            return
        logits = clip_model.zero_shot_logits(
            self.params, jax.numpy.asarray(feats), text_feats)
        show_predictions_on_dataset(np.asarray(logits), classes)
