"""RAFT flow extractor (reference models/raft/extract_raft.py +
models/_base/base_flow_extractor.py behavior).

Contract parity:
  * consecutive-pair batching: the loader yields ``batch_size + 1`` frames
    with overlap 1, producing ``batch_size`` flows per step (reference
    base_flow_extractor.py:76-84);
  * optional host-side PIL edge resize (``side_size`` /
    ``resize_to_smaller_edge``), else raw float frames (:50-58);
  * pad to /8 (sintel replicate padding), flow computed on padded frames,
    unpadded before collection (:104-115);
  * outputs {'raft': (T-1, 2, H, W), 'fps', 'timestamps_ms'} where
    timestamps keep every decoded frame (first batch whole, later batches
    minus the overlapped head) (:92-101) — note the reference stores flow
    channels-first; we keep that on-disk layout for drop-in compatibility.

TPU-first: one jit step per video geometry — the padded (B+1, H, W, 3)
batch maps to B frame pairs computed in a single compiled RAFT call; ragged
tails are padded to the compiled shape and masked.
"""
from __future__ import annotations

from functools import partial
from pathlib import Path
from typing import Dict

import jax
import numpy as np

from video_features_tpu.extract.base import BaseExtractor
from video_features_tpu.extract.streaming import transfer_batches
from video_features_tpu.io.video import VideoLoader
from video_features_tpu.models import raft as raft_model
from video_features_tpu.ops.transforms import resize_pil
from video_features_tpu.utils.device import jax_device

FINETUNED_CKPTS = ('sintel', 'kitti')


class ExtractRAFT(BaseExtractor):

    def __init__(self, args) -> None:
        super().__init__(
            feature_type=args.feature_type,
            on_extraction=args.on_extraction,
            tmp_path=args.tmp_path,
            output_path=args.output_path,
            keep_tmp_files=args.keep_tmp_files,
            device=args.device,
            profile=args.get('profile', False),
            precision=args.get('precision', 'highest'),
        )
        self.batch_size = args.batch_size
        self.decode_workers = int(args.get('decode_workers', 1))
        self.decode_backend = args.get('decode_backend', 'auto')
        self.side_size = args.get('side_size')
        self.resize_to_smaller_edge = args.get('resize_to_smaller_edge', True)
        self.extraction_fps = args.get('extraction_fps')
        self.extraction_total = args.get('extraction_total')
        self.finetuned_on = args.get('finetuned_on', 'sintel')
        assert self.finetuned_on in FINETUNED_CKPTS, \
            f'finetuned_on must be one of {FINETUNED_CKPTS}'
        # Shapes are static per jit: every distinct padded geometry is a
        # fresh multi-minute compile (docs/design.md "one jit step per
        # video geometry"). bucket_multiple > 8 rounds the replicate-pad
        # up to coarser buckets so a heterogeneous corpus shares
        # executables (e.g. 64 → 256×342 and 256×344 both run 256×384).
        # Opt-in because wider replicate pads ARE visible to the flow
        # numerics near borders (the padding participates in correlation
        # and context) — measured in tests/test_raft_extractor.py.
        self.bucket_multiple = int(args.get('bucket_multiple', 8))
        assert self.bucket_multiple % 8 == 0 and self.bucket_multiple > 0, \
            'bucket_multiple must be a positive multiple of 8'
        self.show_pred = args.show_pred
        self.output_feat_keys = [self.feature_type, 'fps', 'timestamps_ms']
        # data_parallel=true spreads the B consecutive-pair flows over all
        # local devices: the host hands each device its own run of k+1
        # frames (k = B / n_devices; the one-frame halo at shard boundaries
        # is duplicated host-side), and a shard_map'd forward_consecutive
        # encodes each device's frames ONCE — interior frames share their
        # fnet encoding between their two pairs exactly like the
        # single-device path, and no in-graph halo exchange is needed.
        self.data_parallel = args.get('data_parallel', False)
        # refinement-depth knob; 20 = the fork's pin = full parity
        self.raft_iters = raft_model.resolve_iters(args.get('raft_iters'))
        self._device = jax_device(self.device)
        self.params = jax.device_put(self.load_params(args), self._device)
        # thread the resolved device's platform so the corr-lookup dispatch
        # matches where the operands actually live, not the process default
        self._step = jax.jit(partial(self._flow_batch,
                                     platform=self._device.platform,
                                     pins=self.precision_pins,
                                     iters=self.raft_iters))

    def load_params(self, args):
        # RAFT checkpoints were saved from nn.DataParallel — prefixes are
        # stripped by the transplant layer
        from video_features_tpu.extract.weights import load_or_init
        return load_or_init(args, 'checkpoint_path', raft_model.init_state_dict,
                            feature_type='raft')

    @staticmethod
    def _flow_batch(params, frames, platform=None, pins=None,
                    iters=raft_model.ITERS):
        """(B+1, Hp, Wp, 3) padded frames → (B, Hp, Wp, 2) flows; interior
        frames are fnet-encoded once (forward_consecutive), not twice."""
        return raft_model.forward_consecutive(params, frames, iters=iters,
                                              platform=platform, pins=pins)

    def _build_dp_step(self):
        """shard_map'd per-device forward_consecutive over the data axis.

        Input is the host-assembled halo layout (n·(k+1), Hp, Wp, 3):
        device d's shard holds frames [d·k, d·k + k] inclusive, so its k
        flows concatenate to the global (B, Hp, Wp, 2) result in order.
        """
        from video_features_tpu.utils.device import shard_map
        from jax.sharding import PartitionSpec as P
        return jax.jit(shard_map(
            partial(raft_model.forward_consecutive,
                    iters=self.raft_iters,
                    platform=self._device.platform,
                    pins=self.precision_pins),
            mesh=self._mesh, in_specs=(P(), P('data')), out_specs=P('data')))

    def _halo_shards(self, padded: np.ndarray) -> np.ndarray:
        """(B+1, ...) frames → (n·(k+1), ...) per-device runs with the
        boundary frame duplicated; fnet cost is B + n frame encodes instead
        of the pair form's 2·B."""
        n = self._mesh.shape['data']
        k = (padded.shape[0] - 1) // n
        halo = np.stack([padded[d * k: d * k + k + 1] for d in range(n)])
        return halo.reshape((n * (k + 1),) + padded.shape[1:])

    def program_specs(self, mesh=None):
        """vft-programs abstract step specs. Single-device: the
        consecutive-pair flow step over (B+1, Hp, Wp, 3) padded frames.
        Mesh variant: the family's REAL data-parallel program is the
        shard_map'd halo layout (each device gets its own k+1 frame run,
        boundary frame duplicated host-side) — n·(k+1) rows, evenly
        shardable by construction, unlike the B+1 pair form."""
        from video_features_tpu.analysis.programs import ProgramSpec
        h, w = self.PROGRAM_DECODE_HW           # already /8-aligned
        if mesh is None:
            batch = self._abstract_batch(
                (self.batch_size + 1, h, w, 3), np.uint8)
            return [ProgramSpec('flow_step', self._step,
                                (self._abstract_params(), batch))]
        prev_mesh = self._mesh
        self._mesh = mesh
        try:
            dp_step = self._build_dp_step()
        finally:
            self._mesh = prev_mesh
        n = mesh.shape['data']
        k = max(int(self.batch_size), 1)
        batch = self._abstract_batch((n * (k + 1), h, w, 3), np.uint8,
                                     mesh)
        return [ProgramSpec('flow_step_dp', dp_step,
                            (self._abstract_params(mesh), batch))]

    def host_transform(self, frame: np.ndarray) -> np.ndarray:
        # uint8 until on-device (RAFT normalizes in-graph): the values are
        # exact integers either way and the H2D transfer is 4x smaller
        if self.side_size is not None:
            frame = resize_pil(frame, self.side_size, self.resize_to_smaller_edge)
        return frame

    def extract(self, video_path: str) -> Dict[str, np.ndarray]:
        if self.data_parallel and self._mesh is None:
            self._ensure_mesh('batch_size')
            self._dp_step = self._build_dp_step()
        self._viz_stem, self._viz_count = Path(video_path).stem, 0
        loader = VideoLoader(
            video_path,
            batch_size=self.batch_size + 1,
            fps=self.extraction_fps,
            total=self.extraction_total,
            tmp_path=self.tmp_path,
            keep_tmp=self.keep_tmp_files,
            transform=self.host_transform,
            transform_workers=self.decode_workers,
            backend=self.decode_backend,
            overlap=1,
        )
        flows, timestamps = [], []

        def assembled():
            # stack + tail-pad + /8-pad on the producer thread; 'model'
            # stage stays pure device time
            first = True
            for batch, times, _ in self.tracer.wrap_iter(
                    'decode+preprocess', loader):
                batch = np.stack(batch)                      # (n, H, W, 3)
                ts = times if first else times[1:]
                first = False
                if batch.shape[0] < 2:
                    yield None, None, 0, ts   # timestamps only, no pairs
                    continue
                valid = batch.shape[0] - 1
                if batch.shape[0] < self.batch_size + 1:
                    pad = np.repeat(
                        batch[-1:], self.batch_size + 1 - batch.shape[0],
                        axis=0)
                    batch = np.concatenate([batch, pad], axis=0)
                padded, pads = raft_model.pad_to_multiple(
                    batch, mode=self.finetuned_on,
                    multiple=self.bucket_multiple)
                yield padded, pads, valid, ts

        def put(padded):
            if padded is None:
                return None
            if self._mesh is not None:
                # dp feeds per-device frame runs (host-duplicated one-frame
                # halo) so each device fnet-encodes its frames once
                return self._put_batch(self._halo_shards(padded))
            return self.put_input(padded)

        with self.precision_scope():
            # transfer of batch k+1 overlaps the device running batch k
            for dev, _, pads, valid, ts in transfer_batches(
                    assembled(), put, tracer=self.tracer):
                timestamps.extend(ts)
                if dev is None:
                    continue
                with self.tracer.stage('model'):
                    # aot_call on the single-device path only: the dp
                    # shard_map program keeps its direct jit dispatch
                    flow = (self._dp_step(self.params, dev)
                            if self._mesh is not None
                            else self.aot_call('flow_step', self._step,
                                               self.params, dev))
                    flow = np.asarray(raft_model.unpad(flow, pads))[:valid]
                flows.append(flow)
                if self.show_pred:
                    self.maybe_show_pred(flow)

        if flows:
            features = np.concatenate(flows, axis=0).transpose(0, 3, 1, 2)
        else:
            # Empty fallback must match the geometry normal outputs would
            # have — i.e. AFTER the host resize, not the raw video dims.
            h, w = self.host_transform(
                np.zeros((loader.height, loader.width, 3), np.uint8)).shape[:2]
            features = np.zeros((0, 2, h, w), np.float32)
        return {
            self.feature_type: features,
            'fps': np.array(loader.fps),
            'timestamps_ms': np.array(timestamps),
        }

    def maybe_show_pred(self, flows: np.ndarray) -> None:
        """Render flow frames via the Middlebury wheel (headless-safe).

        The reference opens cv2 windows per frame (reference
        base_flow_extractor.py:134-149); TPU hosts are headless, so the
        rendered image is preserved as a PNG artifact under
        ``<output_path>/flow_debug/`` instead (one per device batch).
        """
        from video_features_tpu.utils.flow_viz import flow_to_image
        for flow in flows[:1]:
            img = flow_to_image(flow)
            # vft-lint: ok=stdout-purity — show_pred narration surface
            print(f'[flow viz] frame rendered: shape={img.shape}, '
                  f'mean_mag={np.linalg.norm(flow, axis=-1).mean():.3f}')
            try:
                import cv2
                out_dir = Path(self.output_path) / 'flow_debug'
                out_dir.mkdir(parents=True, exist_ok=True)
                path = out_dir / f'{self._viz_stem}_{self._viz_count:06d}.png'
                cv2.imwrite(str(path), img[..., ::-1])  # RGB → BGR on disk
                self._viz_count += 1
            except Exception:  # debug surface: never fail extraction
                import logging as _logging

                from video_features_tpu.obs.events import event
                event(_logging.WARNING, 'flow viz PNG write skipped',
                      exc_info=True, subsystem='raft')
