"""I3D two-stream extractor — the flagship fused RAFT→I3D pipeline.

Behavior parity with reference models/i3d/extract_i3d.py:
  * frames host-resized to short side 256 (PIL, ResizeImproved numerics,
    :43-48) and accumulated into stacks of ``stack_size + 1`` frames — B+1
    frames give B flow pairs, and the rgb stream uses the first B frames so
    both streams have equal length (:115-123, :150-160);
  * flow stream: RAFT on /8-padded consecutive pairs; the center crop is
    taken from the PADDED flow exactly like the reference (which never
    unpads before TensorCenterCrop, :156-164);
  * transforms: rgb = crop224 → 2x/255-1; flow = crop224 → clamp(±20) →
    uint8 quantize → 2x/255-1 (:49-62);
  * ``step_size`` < ``stack_size`` overlaps windows; partial final stacks
    are dropped (:126-129); streams configurable ('rgb'/'flow'/both).

TPU-first: the whole stack→flow→transform→two-I3D graph is ONE jit-compiled
function; stacks are gathered with a vectorized index array and batched
``batch_size`` windows per device step (padded + masked at the tail). The
reference instead runs a python frame loop with per-stack device round trips.
"""
from __future__ import annotations

from functools import partial
from pathlib import Path
from typing import Dict, List

import jax
import jax.numpy as jnp
import numpy as np

from video_features_tpu.extract.base import BaseExtractor
from video_features_tpu.io.video import VideoLoader
from video_features_tpu.models import i3d as i3d_model
from video_features_tpu.models import raft as raft_model
from video_features_tpu.ops.transforms import (
    center_crop, flow_to_uint8_levels, resize_pil, scale_to_pm1,
)
from video_features_tpu.utils.device import jax_device
from video_features_tpu.utils.tracing import NULL_TRACER

MIN_SIDE_SIZE = 256
CROP_SIZE = 224


def rgb_stream_input(stacks, crop_size):
    """(B, S+1, H, W, 3) frames → rgb I3D input: first S frames, center
    crop, 2x/255-1 rescale (reference extract_i3d.py:49-55)."""
    return scale_to_pm1(center_crop(stacks[:, :-1], crop_size))


def flow_stream_input(raft_params, stacks, pads, crop_size,
                      constrain_pairs=None, platform=None, pins=None,
                      raft_iters=raft_model.ITERS):
    """(B, S+1, H, W, 3) frames → quantized flow I3D input (B, S, c, c, 2).

    RAFT on /8-padded consecutive pairs (each interior frame's fnet
    encoding shared between its two pairs — raft.forward_stack_pairs), then
    the kinetics-i3d flow recipe: crop the PADDED flow (the reference never
    unpads before TensorCenterCrop, extract_i3d.py:156-164) → clamp ±20 →
    uint8 levels → ±1 rescale. ``raft_iters`` trades refinement quality for
    speed (the reference's own RAFT default was 12 before the fork pinned
    20, raft_src/raft.py:117-118).
    """
    t, b, l, r = pads
    padded = jnp.pad(stacks, [(0, 0), (0, 0), (t, b), (l, r), (0, 0)],
                     mode='edge')
    flow = raft_model.forward_stack_pairs(raft_params, padded,
                                          iters=raft_iters,
                                          constrain=constrain_pairs,
                                          platform=platform, pins=pins)
    flow = center_crop(flow, crop_size)
    return scale_to_pm1(flow_to_uint8_levels(flow, 20.0))


def _pil_short_side_geometry(h, w, size):
    """PIL's short-side resize target for (h, w), or None when resize_pil
    would no-op — delegates to the one home of the arithmetic
    (ops.transforms.pil_edge_resize_geometry)."""
    from video_features_tpu.ops.transforms import pil_edge_resize_geometry
    return pil_edge_resize_geometry(h, w, size)


def _device_resize_stacks(stacks, resize_to):
    """(B, S, H, W, 3) → (B, S, H', W', 3) BIT-EXACT Pillow bilinear
    resize in-graph (ops.transforms.pil_resize_bilinear_device) — the
    ONE in-graph resize both the fused step and the show_pred debug path
    apply. Because it reproduces PIL's fixed-point arithmetic exactly,
    device_resize=true yields the IDENTICAL pixels the host resize_pil
    path produces — zero feature drift, so the host decode wall can be
    escaped at full parity (VERDICT r4 task 1)."""
    from video_features_tpu.ops.transforms import pil_resize_bilinear_device
    return jnp.asarray(
        pil_resize_bilinear_device(stacks, tuple(resize_to)), stacks.dtype)


def fused_two_stream_step(params, stacks, pads, streams, constrain_pairs=None,
                          crop_size=CROP_SIZE, platform=None, pins=None,
                          raft_iters=raft_model.ITERS, resize_to=None):
    """(B, stack+1, H, W, 3) float frames → {stream: (B, 1024)}.

    The full two-stream graph — RAFT flow, quantization, both I3D towers —
    compiles into a single XLA executable. ``constrain_pairs`` optionally
    applies a sharding constraint to the leading-flattened tensors feeding
    RAFT's heavy sub-graphs (unique frames, fmap pairs, cnet input) so they
    spread over a (data, time) mesh (sequence parallelism over temporal
    pairs — see parallel.mesh). ``pins`` selects per-sub-graph matmul
    precision (ops/precision.py: 'encoder'/'corr'/'iter'/'upsample' inside
    RAFT, 'i3d' for both towers) — the precision='mixed' fast-parity mode.

    ``resize_to=(H', W')`` moves the short-side resize into the graph
    (``device_resize=true``): raw decode-geometry frames in, BIT-EXACT
    Pillow bilinear resample on device (ops.transforms.
    pil_resize_bilinear_device) — identical pixels to the host resize_pil
    path, zero feature cost (tests/test_device_resize.py asserts it).
    """
    from video_features_tpu.ops.precision import pin_scope
    if resize_to is not None:
        stacks = _device_resize_stacks(stacks, resize_to)
    out = {}
    if 'rgb' in streams:
        rgb = rgb_stream_input(stacks, crop_size)
        with pin_scope(pins, 'i3d'):
            out['rgb'] = i3d_model.forward(params['rgb'], rgb, features=True)
    if 'flow' in streams:
        flow = flow_stream_input(params['raft'], stacks, pads, crop_size,
                                 constrain_pairs, platform=platform,
                                 pins=pins, raft_iters=raft_iters)
        with pin_scope(pins, 'i3d'):
            out['flow'] = i3d_model.forward(params['flow'], flow,
                                            features=True)
    return out


@partial(jax.jit, static_argnames=('stream', 'pads', 'crop_size', 'platform'))
def _pred_logits(params, stacks, stream, pads, crop_size, platform=None):
    """Classifier logits for one stream — the show_pred debug surface,
    compiled so it doesn't pay eager dispatch per displayed batch."""
    if stream == 'rgb':
        x = rgb_stream_input(stacks, crop_size)
    else:
        x = flow_stream_input(params['raft'], stacks, pads, crop_size,
                              platform=platform)
    return i3d_model.forward(params[stream], x, features=False)[1]


@partial(jax.jit, static_argnames=('pads', 'crop_size', 'platform'))
def _debug_flow(raft_params, stacks, pads, crop_size, platform=None):
    """Cropped un-quantized flow of the FIRST pair of the first stack —
    the frame the reference renders in its cv2 window
    (base_flow_extractor.py:134-149). Debug surface only."""
    t, b, l, r = pads
    pair = jnp.pad(stacks[:1, :2], [(0, 0), (0, 0), (t, b), (l, r), (0, 0)],
                   mode='edge')
    flow = raft_model.forward_stack_pairs(raft_params, pair,
                                          platform=platform)
    return center_crop(flow, crop_size)[0, 0]


class ExtractI3D(BaseExtractor):

    def __init__(self, args) -> None:
        super().__init__(
            feature_type=args.feature_type,
            on_extraction=args.on_extraction,
            tmp_path=args.tmp_path,
            output_path=args.output_path,
            keep_tmp_files=args.keep_tmp_files,
            device=args.device,
            concat_rgb_flow=args.get('concat_rgb_flow', False),
            profile=args.get('profile', False),
            precision=args.get('precision', 'highest'),
            inflight=args.get('inflight', 2),
        )
        self.streams: List[str] = (['rgb', 'flow'] if args.streams is None
                                   else [args.streams])
        for s in self.streams:
            assert s in ('rgb', 'flow'), f'unknown stream {s}'
        if args.flow_type != 'raft':
            raise NotImplementedError('only flow_type=raft is supported')
        self.stack_size = 64 if args.stack_size is None else args.stack_size
        self.step_size = 64 if args.step_size is None else args.step_size
        # refinement-depth knob; 20 = the fork's pin = full parity
        self.raft_iters = raft_model.resolve_iters(args.get('raft_iters'))
        self.extraction_fps = args.extraction_fps
        self.batch_size = args.get('batch_size', 1)
        self.decode_workers = int(args.get('decode_workers', 1))
        self.decode_backend = args.get('decode_backend', 'auto')
        # device_resize=true ships RAW decode-geometry uint8 frames and
        # runs the short-side-256 resize inside the fused graph — lifting
        # the host's per-frame PIL work (the measured host wall,
        # docs/benchmarks.md) onto the MXU. The in-graph resample is
        # bit-exact Pillow arithmetic, so the features are identical to
        # the host path's (tests/test_device_resize.py)
        self.device_resize = bool(args.get('device_resize', False))
        self.show_pred = args.show_pred
        self.output_feat_keys = list(self.streams)
        # decode-geometry (H, W) -> (pads, resize_to): shared by the
        # per-video and packed paths so a corpus of same-geometry videos
        # derives its RAFT padding / device-resize target exactly once
        self._geom_cache: Dict[tuple, tuple] = {}
        self._device = jax_device(self.device)
        # data_parallel=true shards stack batches over ALL local devices with
        # one pjit program (params replicated, RAFT pairs spread over the
        # time axis) — the reference's only scale-out is launching one
        # process per GPU (reference README.md:70-84)
        self.data_parallel = args.get('data_parallel', False)
        if self.data_parallel:
            from video_features_tpu.parallel import (
                build_sharded_two_stream_step, make_mesh, put_batch,
                put_replicated, round_batch_to_data_axis,
            )
            from video_features_tpu.utils.device import jax_devices_all
            # self._mesh keeps the one-flag-per-extractor invariant from
            # BaseExtractor; self.mesh stays the public name
            self.mesh = self._mesh = make_mesh(
                devices=jax_devices_all(self.device))
            # batch_size is the global batch; round up to fill the data axis
            self.batch_size = round_batch_to_data_axis(self.batch_size,
                                                       self.mesh)
            self.params = put_replicated(self.mesh, self.load_params(args))
            self._put_batch = partial(put_batch, self.mesh)
            sharded = build_sharded_two_stream_step(
                self.mesh, streams=tuple(self.streams),
                pins=self.precision_pins, raft_iters=self.raft_iters)

            def _step(params, stacks, pads, streams, resize_to=None):
                return sharded(params, stacks, pads,
                               resize_to=tuple(resize_to)
                               if resize_to is not None else None)

            self._step = _step
        else:
            self.params = jax.device_put(self.load_params(args), self._device)
            # pads/streams are static so one executable serves each geometry;
            # the resolved device's platform drives the RAFT corr-lookup
            # dispatch (not the process default backend)
            self._step = jax.jit(
                partial(self._stack_batch, platform=self._device.platform,
                        pins=self.precision_pins,
                        raft_iters=self.raft_iters),
                static_argnames=('pads', 'streams', 'resize_to'))

    def load_params(self, args):
        """{'rgb': i3d params, 'flow': i3d params, 'raft': raft params}.

        Missing checkpoint paths are a hard error unless random weights are
        explicitly allowed (extract.weights; the reference always loads real
        weights, extract_i3d.py:180-183).
        """
        from video_features_tpu.extract.weights import load_or_init
        params = {}
        if 'rgb' in self.streams:
            params['rgb'] = load_or_init(
                args, 'i3d_rgb_checkpoint_path',
                partial(i3d_model.init_state_dict, modality='rgb'),
                feature_type='i3d', what='i3d rgb stream')
        if 'flow' in self.streams:
            params['flow'] = load_or_init(
                args, 'i3d_flow_checkpoint_path',
                partial(i3d_model.init_state_dict, modality='flow'),
                feature_type='i3d', what='i3d flow stream')
            params['raft'] = load_or_init(
                args, 'raft_checkpoint_path', raft_model.init_state_dict,
                feature_type='i3d', what='i3d flow stream (raft)')
        return params

    # -- the fused device step ----------------------------------------------

    _stack_batch = staticmethod(fused_two_stream_step)

    # -- extraction ---------------------------------------------------------

    def _stream_windows(self, loader, tracer=None, frame_range=None):
        """(stack_size+1)-frame windows (B+1 frames → B flow pairs) streamed
        off the decoder; see extract.streaming for the semantics."""
        from video_features_tpu.extract.streaming import stream_windows
        tracer = self.tracer if tracer is None else tracer
        return stream_windows(loader, self.stack_size + 1, self.step_size,
                              tracer, 'decode+preprocess',
                              frame_range=frame_range)

    def _make_loader(self, video_path: str) -> VideoLoader:
        # frames stay uint8 until they are on the device: values are exact
        # integers either way, and a (B, S+1, 256, W, 3) float32 stack batch
        # is 4x the host->device bytes of the uint8 one — H2D bandwidth is
        # the CLI's bottleneck ahead of the fused compute.
        # device_resize lifts the PIL resize into the fused graph: raw
        # decode frames ship as-is and the jitted step resizes them
        # (resize_to computed per geometry with PIL's own edge rule).
        return VideoLoader(
            video_path, batch_size=64,
            fps=self.extraction_fps, tmp_path=self.tmp_path,
            keep_tmp=self.keep_tmp_files,
            transform=(None if self.device_resize
                       else lambda f: resize_pil(f, MIN_SIDE_SIZE)),
            transform_workers=self.decode_workers,
            backend=self.decode_backend)

    def _geometry(self, h: int, w: int) -> tuple:
        """(pads, resize_to) for decode geometry (h, w), cached per shape."""
        geom = self._geom_cache.get((h, w))
        if geom is None:
            # every distinct geometry also specializes the jitted step
            # (static pads/resize_to); bound that executable growth on
            # long heterogeneous corpora by dropping ALL specializations
            # past 16 geometries (coarser than s3d's per-entry FIFO —
            # jit's internal cache is all-or-nothing — but real corpora
            # cluster into a handful of aspect ratios, so this never
            # fires in practice; the data_parallel wrapper has no
            # clear_cache and keeps jit's unbounded default)
            if len(self._geom_cache) >= 16:
                getattr(self._step, 'clear_cache', lambda: None)()
                self._geom_cache.clear()
                # resident AOT executables are per-geometry too: the
                # bound exists to cap live executables, so drop both
                self._aot_invalidate()
            resize_to = None
            gh, gw = h, w
            if self.device_resize:
                resize_to = _pil_short_side_geometry(gh, gw, MIN_SIDE_SIZE)
                if resize_to is not None:
                    gh, gw = resize_to
            pads = tuple(raft_model.pad_to_multiple(
                np.zeros((1, gh, gw, 1), np.float32))[1])
            geom = self._geom_cache[(h, w)] = (pads, resize_to)
        return geom

    def extract(self, video_path: str) -> Dict[str, np.ndarray]:
        from video_features_tpu.extract.streaming import (
            iter_batched_windows, overlap_fetch, transfer_batches,
        )

        loader = self._make_loader(video_path)
        feats: Dict[str, list] = {s: [] for s in self.streams}
        # show_pred narrates windows as they compute (and needs the input
        # batch alive at fetch time) — keep the debug surface synchronous
        depth = 1 if self.show_pred else self.inflight

        def dispatched():
            # decode thread assembles + transfers batch k+1 while the
            # device runs batch k (see streaming.transfer_batches); the
            # 'model' stage is DISPATCH only — the deferred readback is
            # its own 'd2h' stage inside overlap_fetch
            for stacks, _, valid, window_idx in transfer_batches(
                    iter_batched_windows(self._stream_windows(loader),
                                         self.batch_size),
                    self.put_input, tracer=self.tracer):
                pads, resize_to = self._geometry(*stacks.shape[2:4])
                with self.tracer.stage('model'):
                    out = self.aot_call('step', self._step,
                                        self.params, stacks, pads=pads,
                                        streams=tuple(self.streams),
                                        resize_to=resize_to)
                # carry the input batch only for show_pred — holding it
                # across the in-flight window would pin input HBM
                yield (out, stacks if self.show_pred else None,
                       valid, window_idx, pads, resize_to)

        with self.precision_scope():
            for out, stacks, valid, window_idx, pads, resize_to in \
                    overlap_fetch(dispatched(), self.fetch_outputs, depth,
                                  self.tracer):
                for s in self.streams:
                    feats[s].append(out[s][:valid])
                if self.show_pred:
                    self.maybe_show_pred(stacks[:valid], pads, window_idx,
                                         resize_to)

        return {
            s: (np.concatenate(v, axis=0) if v
                else np.zeros((0, i3d_model.FEAT_DIM), np.float32))
            for s, v in feats.items()
        }

    # -- packed corpus mode (see extract.base / parallel.packing) -----------

    supports_packing = True

    def packed_windows(self, task):
        from video_features_tpu.extract.streaming import segment_frame_range
        loader = self._make_loader(task.path)
        # deterministic close (segment early-stop abandons the stream
        # mid-decode; GC-timed release would strand codec contexts and
        # re-encode temps in a long-lived serve worker)
        try:
            for window in self._stream_windows(
                    loader, tracer=NULL_TRACER,
                    frame_range=segment_frame_range(task.segment,
                                                    loader.fps)):
                yield window, None
        finally:
            loader.close()

    def live_window_spec(self):
        # B+1 raw frames → B flow pairs; the host short-side resize
        # applies per frame unless device_resize lifted it in-graph
        return (self.stack_size + 1, self.step_size,
                (None if self.device_resize
                 else lambda f: resize_pil(f, MIN_SIDE_SIZE)), False)

    def program_specs(self, mesh=None):
        """vft-programs abstract step spec: the fused two-stream program
        (RAFT flow + quantization + both I3D towers in ONE executable)
        at the canonical decode geometry — post-host-resize unless
        ``device_resize`` lifted the resize in-graph, exactly what the
        hot path feeds ``_step``."""
        from video_features_tpu.analysis.programs import ProgramSpec
        h, w = self.PROGRAM_DECODE_HW
        if not self.device_resize:
            geom = _pil_short_side_geometry(h, w, MIN_SIDE_SIZE)
            if geom is not None:
                h, w = geom
        pads, resize_to = self._geometry(h, w)
        batch = self._abstract_batch(
            (self._program_batch_slots(mesh), self.stack_size + 1, h, w,
             3), np.uint8, mesh)
        return [ProgramSpec(
            'step', self._step, (self._abstract_params(mesh), batch),
            kwargs=dict(pads=pads, streams=tuple(self.streams),
                        resize_to=resize_to))]

    def packed_step(self, stacks):
        # device arrays out — dispatch only; the scheduler materializes
        # results k batches later (fetch_outputs), overlapping D2H +
        # scatter + save with device compute
        pads, resize_to = self._geometry(*stacks.shape[2:4])
        # aot_call keys on the static kwargs too: each (pads, resize_to)
        # specialization resolves to its own resident executable
        out = self.aot_call('step', self._step, self.params, stacks,
                            pads=pads, streams=tuple(self.streams),
                            resize_to=resize_to)
        return {s: out[s] for s in self.streams}

    def packed_result(self, task):
        return {
            s: (np.stack(task.rows[s]) if task.rows.get(s)
                else np.zeros((0, i3d_model.FEAT_DIM), np.float32))
            for s in self.streams
        }

    def farm_recipe(self):
        # one extra frame per window (B+1 frames → B flow pairs); the
        # host short-side resize rides as a spec unless device_resize
        # lifted it into the fused graph (raw frames ship then)
        from video_features_tpu.farm.recipes import StackRecipe
        return StackRecipe(
            win=self.stack_size + 1, step=self.step_size, batch_size=64,
            fps=self.extraction_fps, total=None, tmp_path=self.tmp_path,
            keep_tmp=self.keep_tmp_files, backend=self.decode_backend,
            transform=(None if self.device_resize
                       else ('edge_resize', MIN_SIDE_SIZE, 'bilinear')))

    def maybe_show_pred(self, stacks, pads, stack_counter, resize_to=None):
        """Kinetics top-5 per STREAM, like the reference (extract_i3d.py:
        212-216 runs the classifier head on each stream's transformed
        slice). Debug surface only — the flow recompute happens outside the
        fused hot path. Under device_resize the raw stacks are resized
        here first (same graph-side resize the fused step applies)."""
        from video_features_tpu.utils.preds import show_predictions_on_dataset
        if resize_to is not None:
            stacks = np.asarray(_device_resize_stacks(
                jnp.asarray(stacks, jnp.float32), resize_to))
        crop = min(CROP_SIZE, stacks.shape[2], stacks.shape[3])
        for stream in self.streams:
            logits = _pred_logits(self.params, jnp.asarray(stacks),
                                  stream=stream, pads=tuple(pads),
                                  crop_size=crop,
                                  platform=self._device.platform)
            # vft-lint: ok=stdout-purity — show_pred narration surface
            print(f'At stack {stack_counter} ({stream} stream)')
            show_predictions_on_dataset(np.asarray(logits), 'kinetics')
        if 'flow' in self.streams:
            # headless counterpart of the reference's cv2 flow window:
            # write the Middlebury-rendered first flow frame as a PNG
            try:
                import cv2

                from video_features_tpu.utils.flow_viz import flow_to_image
                flow = np.asarray(_debug_flow(
                    self.params['raft'], jnp.asarray(stacks),
                    pads=tuple(pads), crop_size=crop,
                    platform=self._device.platform))
                out_dir = Path(self.output_path) / 'flow_debug'
                out_dir.mkdir(parents=True, exist_ok=True)
                path = out_dir / f'stack_{stack_counter:06d}.png'
                cv2.imwrite(str(path), flow_to_image(flow)[..., ::-1])
            except Exception:  # debug surface: never fail extraction
                import logging as _logging

                from video_features_tpu.obs.events import event
                event(_logging.WARNING, 'flow viz PNG write skipped',
                      exc_info=True, subsystem='i3d')
