"""Frame-wise extractor base (ResNet / CLIP / timm-style backbones).

Re-design of reference models/_base/base_framewise_extractor.py (90 LoC):
the host prepares fixed-size uint8 frames (PIL short-side resize + center
crop), batches are padded to the compiled batch size and masked, and one
jit-compiled step does float conversion + normalization + the backbone
forward — so every batch reuses a single XLA executable per video geometry.

Returns {feature_type: (T, D), 'fps': scalar, 'timestamps_ms': (T,)} exactly
like the reference (:75-79).
"""
from __future__ import annotations

from typing import Dict

import jax
import numpy as np

from video_features_tpu.extract.base import BaseExtractor
from video_features_tpu.extract.streaming import (
    overlap_fetch, transfer_batches,
)
from video_features_tpu.io.video import VideoLoader


class BaseFrameWiseExtractor(BaseExtractor):

    def __init__(self, args, feat_dim: int) -> None:
        super().__init__(
            feature_type=args.feature_type,
            on_extraction=args.on_extraction,
            tmp_path=args.tmp_path,
            output_path=args.output_path,
            keep_tmp_files=args.keep_tmp_files,
            device=args.device,
            profile=args.get('profile', False),
            precision=args.get('precision', 'highest'),
            inflight=args.get('inflight', 2),
            compute_dtype=args.get('compute_dtype', 'float32'),
        )
        self.batch_size = args.batch_size
        self.decode_workers = int(args.get('decode_workers', 1))
        self.decode_backend = args.get('decode_backend', 'auto')
        # data_parallel=true shards frame batches over ALL local devices:
        # params are re-placed replicated and batches arrive with a
        # data-axis sharding, so the subclass's jitted step compiles into
        # one pjit program with XLA-inserted collectives (reference
        # scale-out is one process per GPU, README.md:70-84)
        self.data_parallel = args.get('data_parallel', False)
        self.extraction_fps = args.get('extraction_fps')
        self.extraction_total = args.get('extraction_total')
        self.show_pred = args.show_pred
        self.feat_dim = feat_dim
        self.output_feat_keys = [self.feature_type, 'fps', 'timestamps_ms']

    # subclasses provide:
    def host_transform(self, frame: np.ndarray) -> np.ndarray:
        """HWC uint8 RGB frame → fixed-size HWC uint8 (resize + crop)."""
        raise NotImplementedError

    def device_step(self, batch: np.ndarray) -> jax.Array:
        """(B, H, W, 3) uint8 → (B, D) features. Must be jit-compiled."""
        raise NotImplementedError

    def maybe_show_pred(self, feats: np.ndarray) -> None:
        pass

    def _make_loader(self, video_path: str) -> VideoLoader:
        return VideoLoader(
            video_path,
            batch_size=self.batch_size,
            fps=self.extraction_fps,
            total=self.extraction_total,
            tmp_path=self.tmp_path,
            keep_tmp=self.keep_tmp_files,
            transform=self.host_transform,
            transform_workers=self.decode_workers,
            backend=self.decode_backend,
        )

    # -- packed corpus mode (see extract.base / parallel.packing) -----------
    #
    # One packed "window" is a single host-transformed frame; the packer
    # fills frame batches across video boundaries — at corpus scale the
    # per-video tail batch (up to batch_size - 1 padded slots, paid per
    # video today) collapses into one tail batch per corpus.

    supports_packing = True

    def _packed_setup(self) -> None:
        if self.data_parallel:
            self._ensure_mesh('batch_size')

    def packed_windows(self, task):
        from video_features_tpu.extract.streaming import (
            framewise_segment_windows, segment_frame_range,
        )
        loader = self._make_loader(task.path)
        task.info['fps'] = loader.fps
        # deterministic close (segment early-stop abandons the loader
        # mid-decode; GC-timed release would strand codec contexts and
        # re-encode temps in a long-lived serve worker)
        try:
            yield from framewise_segment_windows(
                loader, segment_frame_range(task.segment, loader.fps))
        finally:
            loader.close()

    def live_window_spec(self):
        # one window = one host-transformed frame; meta is a timestamp
        # (the live layer synthesizes it from the session's declared fps)
        return (1, 1, self.host_transform, True)

    def host_transform_spec(self):
        """Named-spec form of :meth:`host_transform` (``farm/recipes.py``
        vocabulary), or None when the transform can't be specced — which
        disables the decode farm for this extractor (in-process decode
        keeps working). Subclasses whose ``host_transform`` is the
        standard edge-resize + center-crop pair override this."""
        return None

    def farm_recipe(self):
        spec = self.host_transform_spec()
        if spec is None:
            return None
        from video_features_tpu.farm.recipes import FramewiseRecipe
        return FramewiseRecipe(
            batch_size=self.batch_size, fps=self.extraction_fps,
            total=self.extraction_total, tmp_path=self.tmp_path,
            keep_tmp=self.keep_tmp_files, backend=self.decode_backend,
            transform=spec)

    def fused_decode_signature(self):
        """Frame-wise families fuse when everything upstream of the
        per-frame transform matches: same retiming (fps/total) and same
        decode backend produce the same raw frame stream, and the
        per-family transform is a pure per-frame call over it
        (``io.video.VideoLoader``) — so one shared decode branched into
        N spec transforms is byte-identical to N separate decodes. A
        family whose transform can't be specced can't branch off a
        shared raw stream, so it stays unfused (None)."""
        if self.host_transform_spec() is None:
            return None
        return ('framewise', self.extraction_fps, self.extraction_total,
                self.decode_backend)

    def packed_step(self, batch) -> Dict:
        # dispatch only (device array out); the scheduler's deferred
        # fetch_outputs owns the D2H readback
        return {self.feature_type: self.device_step(batch)}

    def program_specs(self, mesh=None):
        """vft-programs abstract step spec, shared by every frame-wise
        family (resnet/clip/timm): the REAL ``host_transform`` discovers
        the compiled input geometry (run once on a zero frame at the
        canonical decode shape), so the spec can never drift from the
        preprocessing that actually feeds the step."""
        import numpy as np

        from video_features_tpu.analysis.programs import ProgramSpec
        h, w = self.PROGRAM_DECODE_HW
        ch, cw = self.host_transform(
            np.zeros((h, w, 3), np.uint8)).shape[:2]
        batch = self._abstract_batch(
            (self._program_batch_slots(mesh), ch, cw, 3), np.uint8, mesh)
        return [ProgramSpec('step', self._step,
                            (self._abstract_params(mesh), batch))]

    def packed_result(self, task) -> Dict[str, np.ndarray]:
        rows = task.rows.get(self.feature_type, [])
        return {
            self.feature_type: (np.stack(rows) if rows
                                else np.zeros((0, self.feat_dim),
                                              np.float32)),
            'fps': np.array(task.info.get('fps', 0.0)),
            'timestamps_ms': np.array(task.meta_rows),
        }

    def extract(self, video_path: str) -> Dict[str, np.ndarray]:
        if self.data_parallel:
            self._ensure_mesh('batch_size')
        loader = self._make_loader(video_path)
        feats, timestamps = [], []

        def assembled():
            # pad tails to the compiled batch shape on the producer thread
            for batch, times, _ in self.tracer.wrap_iter(
                    'decode+preprocess', loader):
                batch = np.stack(batch)
                valid = batch.shape[0]
                if valid < self.batch_size:
                    pad = np.repeat(batch[-1:], self.batch_size - valid,
                                    axis=0)
                    batch = np.concatenate([batch, pad], axis=0)
                yield batch, valid, times

        depth = 1 if self.show_pred else self.inflight

        def dispatched():
            # transfer of batch k+1 overlaps the device running batch k
            # (see streaming.transfer_batches); 'model' is dispatch only,
            # the deferred readback is the 'd2h' stage in overlap_fetch
            for batch, _, valid, times in transfer_batches(
                    assembled(), self.put_input, tracer=self.tracer):
                with self.tracer.stage('model'):
                    dev = self.device_step(batch)
                yield dev, valid, times

        with self.precision_scope():
            for out, valid, times in overlap_fetch(
                    dispatched(), self.fetch_outputs, depth, self.tracer):
                out = out[:valid]
                feats.append(out)
                timestamps.extend(times)
                if self.show_pred:
                    self.maybe_show_pred(out)

        features = (np.concatenate(feats, axis=0) if feats
                    else np.zeros((0, self.feat_dim), np.float32))
        return {
            self.feature_type: features,
            'fps': np.array(loader.fps),
            'timestamps_ms': np.array(timestamps),
        }
