"""Config system: per-feature YAML defaults merged with dotlist CLI overrides.

Behavior parity with the reference's OmegaConf pipeline (main.py:9-10,
utils/utils.py:77-135) without the OmegaConf dependency: flat key=value YAML
files, CLI ``key=value`` dotlist wins over YAML, then an imperative
``sanity_check`` that validates combinations and rewrites output/tmp paths.
"""
from __future__ import annotations

import os
import random
import warnings
from pathlib import Path
from typing import Any, Dict, Iterable, List, Optional, Tuple, Union

import yaml

CONFIG_DIR = Path(__file__).parent / 'configs'

# The one registry of feature families. Also the coverage set the
# vft-programs contract checker pins PROGRAMS.lock.json against
# (analysis/programs.py) — adding a family here obliges an abstract
# step spec (BaseExtractor.program_specs) and a lock re-pin.
KNOWN_FEATURE_TYPES = ('i3d', 'r21d', 's3d', 'vggish', 'resnet', 'raft', 'clip', 'timm')

# -- content-addressed feature cache (cache/; docs/caching.md) ---------------
# Injected into every merged config (CLI dotlist wins, as always) rather
# than copied into each per-feature YAML: one source of truth for the
# namespace, and older user YAMLs pick the knobs up automatically.
CACHE_DEFAULTS: Dict[str, Any] = {
    # consult/publish the content-addressed result store: the second
    # request for any (video content, config, checkpoint) becomes an
    # O(read) hit that skips decode + inference, with byte-identical
    # outputs. Off by default — today's behavior exactly.
    'cache_enabled': False,
    # where entries live (manifest.jsonl + objects/); shared across
    # processes/workers on one host
    'cache_dir': '~/.cache/video_features_tpu/features',
    # LRU size bound in bytes (null = unbounded); enforced inline on
    # publish and offline via tools/cache_gc.py
    'cache_max_bytes': None,
    # fleet shared tier (fleet/tier.py; docs/fleet.md): a directory
    # every fleet host mounts. When set, cache_dir becomes the local L1
    # and this the L2 — puts replicate here, an L1 miss a peer already
    # extracted serves from here byte-identically (no decode) and
    # promotes into L1. null = single-host behavior exactly.
    'cache_l2_dir': None,
}

# -- device-loop pipelining (parallel/packing.py; docs/benchmarks.md) --------
# Same injection policy as CACHE_DEFAULTS: one source of truth, older
# user YAMLs pick the knobs up automatically, CLI dotlist wins.
PIPELINE_DEFAULTS: Dict[str, Any] = {
    # in-flight device batches on the output side of the device loop:
    # batch k-1's results are only materialized (D2H + row scatter +
    # save) AFTER batch k has been dispatched, so readback and host
    # finalization overlap device compute. 1 = fully synchronous
    # (today's behavior); each extra unit keeps one more output batch
    # resident on device. Outputs are byte-identical at any depth.
    'inflight': 2,
    # mesh-sharded packed execution (parallel/mesh.py): the packed
    # worklist / serve device loop plans batches at capacity × ndev and
    # shards each stacked batch over the data axis of an N-device mesh
    # (params replicated per chip). 1 = single-device (today's loop);
    # 0 = auto-detect every local device of the platform; N = exactly N
    # chips (a clear error if fewer exist). Outputs are byte-identical
    # at any device count; per-video fault isolation is unchanged. The
    # knob only drives the PACKED paths (pack_across_videos / serve) —
    # the per-video loop keeps data_parallel for in-graph DP.
    'mesh_devices': 1,
    # the precision ladder (ops/precision.py, docs/benchmarks.md
    # "precision ladder"): 'float32' (default) is exactly today's
    # numerics; 'bfloat16' casts params to bf16 at transplant time (half
    # the HBM residency + H2D bytes) and runs bf16 activations with fp32
    # accumulation islands; 'int8' quantizes conv/linear weights
    # per-output-channel symmetric int8 at transplant time (a QUARTER of
    # the fp32 param bytes, ops/quant.py) with in-graph dequant and fp32
    # activations. Each lane sits under a measured per-family rel-L2
    # bound (tests/test_precision.py). Orthogonal to the matmul
    # `precision=` knob. Families without a pinned bound REFUSE a lane
    # with a structured build-time error (registry.BF16_FEATURES /
    # registry.INT8_FEATURES); outputs are NOT byte-identical across
    # lanes, so the knob is classified 'both' — artifacts from different
    # lanes never share a cache entry or a warm serve program.
    'compute_dtype': 'float32',
}

# -- decode farm (farm/; docs/decode_farm.md) --------------------------------
# Same injection policy as CACHE_DEFAULTS: one source of truth, older
# user YAMLs pick the knobs up automatically, CLI dotlist wins. Families
# whose YAML already carries decode_workers (i3d ships 2) keep their
# tuned value.
FARM_DEFAULTS: Dict[str, Any] = {
    # host decode/preprocess parallelism. 1 = in-process decode exactly
    # as before. >1 on the per-video loop = the in-process transform
    # thread pool; >1 on the packed/serve paths = the multi-process
    # decode farm (N worker processes feeding the packer over
    # shared-memory rings — GIL- and swscale-unbound). Outputs are
    # byte-identical at any value.
    'decode_workers': 1,
    # per-worker shared-memory ring size (MiB): bounds decoded bytes in
    # flight per worker; a slow consumer stalls decode instead of
    # growing memory. See docs/decode_farm.md for sizing.
    'decode_farm_ring_mb': 64,
}

# -- persistent executable store (aot/; docs/serving.md "Zero cold start") ---
# Same injection policy as CACHE_DEFAULTS: one source of truth, older
# user YAMLs pick the knobs up automatically, CLI dotlist wins.
AOT_DEFAULTS: Dict[str, Any] = {
    # consult/publish the persistent compiled-executable store: the
    # second process running an unchanged program set LOADS executables
    # (PJRT deserialization, milliseconds) instead of paying XLA
    # compilation. Keyed by the StableHLO identity PROGRAMS.lock.json
    # pins + jax version + backend/device kind + device ids — any
    # mismatch is a silent compile-on-miss, never an error. Outputs of
    # loaded executables are byte-identical to freshly compiled ones
    # (tests/test_aot.py), so these knobs stay out of the cache
    # fingerprint. Off by default — today's behavior exactly.
    'aot_enabled': False,
    # where serialized executables live (manifest.jsonl + objects/);
    # shared across processes on one host. NOTE: on the CPU backend the
    # payloads record the compiling host's ISA, so a network-shared dir
    # only pays off for accelerator backends (same caveat as jax's own
    # compilation cache — utils/device.enable_compilation_cache). TRUST:
    # payloads restore via pickle-based PJRT machinery — whoever can
    # write this dir can run code in every loading process, so keep it
    # writable only by the principals that run the extractors
    # (docs/serving.md "Zero cold start" § trust model).
    'aot_dir': '~/.cache/video_features_tpu/executables',
    # LRU size bound in bytes (null = unbounded); enforced inline on
    # publish and offline via tools/aot_gc.py
    'aot_max_bytes': None,
    # fleet shared artifact tier (fleet/artifacts.py; docs/fleet.md):
    # when set, aot_dir becomes the local L1 and this a shared
    # publish-on-compile / pull-on-miss tier — a freshly provisioned
    # host loads executables a peer compiled and boots compile-free.
    # Same ISA/trust caveats as a network-shared aot_dir (above).
    # null = single-host behavior exactly.
    'aot_l2_dir': None,
}

# -- feature index (index/; docs/feature_index.md) ---------------------------
# Same injection policy as CACHE_DEFAULTS: one source of truth, older
# user YAMLs pick the knobs up automatically, CLI dotlist wins.
INDEX_DEFAULTS: Dict[str, Any] = {
    # serve-side feature index: an ingest worker tails the cache
    # manifest and folds every published framewise feature object into
    # searchable embedding shards (POST /v1/search, loopback 'search').
    # Requires cache_enabled. Off by default — today's behavior exactly.
    'index_enabled': False,
    # where shards + row manifest live; null = <cache_dir>/index (beside
    # the objects the rows point into, outside objects/ so cache GC's
    # orphan sweep never touches it)
    'index_dir': None,
    # shard-file row bound: every shard pads to exactly this many rows
    # at query time, so the AOT store holds ONE query executable per
    # embedding dim regardless of corpus size
    'index_shard_rows': 1024,
    # ingest-poll cadence (seconds) when the cursor has caught up with
    # the cache manifest; behind, the worker re-polls immediately
    'index_poll_s': 0.5,
    # query-batch quantization: query vectors pad to multiples of this,
    # bounding executable geometries on the query side like
    # index_shard_rows does on the shard side
    'index_query_block': 8,
    # the STATIC k the query program compiles with (lax.top_k); requests
    # asking for less get a slice, more is clamped
    'index_k_max': 10,
}

# -- flight recorder (obs/; docs/observability.md) ---------------------------
# Same injection policy as CACHE_DEFAULTS: one source of truth, older
# user YAMLs pick the knobs up automatically, CLI dotlist wins.
OBS_DEFAULTS: Dict[str, Any] = {
    # Chrome trace-event JSON export of the run's span timeline (open in
    # Perfetto / chrome://tracing; validate with tools/trace_view.py).
    # Works on all three paths: one-shot CLI, packed worklists, serve
    # (base override; each worker exports on drain). null = off.
    'trace_out': None,
    # span ring-buffer bound (events): the recorder keeps the most
    # recent window and stamps how many older events were dropped
    'trace_capacity': 200_000,
    # per-run JSON manifest: merged config + config/weights fingerprints,
    # per-video outcomes, aggregate stage table, XLA compile time, and
    # per-executable-identity cost analysis. null = off.
    'manifest_out': None,
    # -- vft-flight (obs/blackbox.py, obs/watchdog.py) -------------------
    # crash-dump black box: on unhandled worker crash, fatal signal, or
    # watchdog trip, a bounded post-mortem bundle (recent spans, event
    # tail, metrics snapshot, manifest fragment) lands here. null = off.
    'postmortem_dir': None,
    # size cap for the whole postmortem/ dir: oldest bundles GC first,
    # the newest always survives
    'postmortem_max_bytes': 64 * (1 << 20),
    # stall watchdog: a worker holding queued work longer than this many
    # seconds without a single stage advance trips a structured event +
    # vft_watchdog_stalls_total{stage} + a black-box dump. null = off.
    'watchdog_stall_s': None,
    # -- vft-scope SLOs (obs/slo.py) -------------------------------------
    # declarative objectives; setting either turns on multi-window 5m/1h
    # burn-rate evaluation over the serve request families, vft_slo_*
    # gauges, and structured obs/events alerts. null = off.
    # "99% of requests complete within this many seconds":
    'slo_latency_p99_s': None,
    # request success-rate objective in (0, 1), e.g. 0.999:
    'slo_availability': None,
}


# -- knob classification registry (vft-lint: knob-classification) -----------
# The ONE declarative answer to "what does this config key change?" along
# the two identity axes consumers key on:
#
#   * the cache CONFIG FINGERPRINT (cache/key.py): does the knob change
#     the extracted BYTES? Excluded knobs don't fragment the cache key
#     space; anything NOT listed here stays IN the fingerprint
#     (fail-closed: an unknown future knob costs a redundant miss, never
#     a wrong hit).
#   * the serve POOL KEY (serve/server.py): does the knob change the
#     compiled program / weights / residency, or the worker's run
#     behavior? Excluded knobs share a warm entry (the FIRST builder's
#     setting wins); anything NOT listed stays IN the key (fail-closed:
#     an unknown knob builds a redundant entry, never shares a wrong one).
#
# Classes:
#   'neither'          — changes neither the bytes nor the program:
#                        excluded from fingerprint AND pool key
#   'pool_only'        — changes the program/residency/run behavior but
#                        never the bytes: excluded from the fingerprint,
#                        IN the pool key
#   'fingerprint_only' — (unused today; supported for completeness)
#   'both'             — relevant everywhere (same as not listing it,
#                        but explicit for injected knobs)
#
# Consumers derive their exclusion sets via knob_exclude() — there are
# deliberately NO hand-maintained copies of these lists anywhere else;
# vft-lint (analysis/, rule 'knob-registry') rejects any that reappear,
# and rule 'knob-classification' rejects any injected *_DEFAULTS knob
# missing from this table. PRs 5-8 each re-fixed a drift between the
# three hand-synced copies this replaces.
KNOB_CLASSIFICATION: Dict[str, str] = {
    # payload / routing: the work list and where outputs land are
    # per-request concerns, never identity
    'video_paths': 'neither',
    'file_with_video_paths': 'neither',
    # the fused-worklist family list (`features=[resnet,clip,...]`) is
    # pure routing: each family still resolves its OWN merged config
    # (resolve_fused_features strips the key before load_config), so it
    # must never fragment a family's fingerprint or pool key — a fused
    # run's cache keys are identical to N sequential runs' by contract
    'features': 'neither',
    'output_path': 'neither',
    # tmp_path is pool-key relevant: loaders read the ENTRY's tmp root,
    # so a request with a different tmp_path must get its own entry
    # rather than silently writing re-encode temps under another
    # request's root
    'tmp_path': 'pool_only',
    'keep_tmp_files': 'pool_only',
    # device & parallelism: where the program runs, not what it computes
    # (numerics are pinned by `precision`, which stays IN both keys)
    'device': 'pool_only',
    'device_ids': 'pool_only',
    'data_parallel': 'pool_only',
    'multihost': 'pool_only',
    'coordinator_address': 'pool_only',
    'num_processes': 'pool_only',
    'process_id': 'pool_only',
    'pack_across_videos': 'pool_only',
    'pack_decode_ahead': 'pool_only',
    # mesh-sharded packed execution: how many chips the batch spreads
    # over, never what each row computes (byte-identical at any device
    # count — tests/test_mesh_packed.py pins it). Pool-key RELEVANT: it
    # changes the compiled program's sharding and how many chips the
    # entry is resident on, so a 1-chip and a 4-chip request each get
    # their own warm entry.
    'mesh_devices': 'pool_only',
    # the bf16 fast lane changes BOTH identities: bf16 features are
    # numerically different bytes (within the pinned bound — a bf16 run
    # must never serve an fp32 cache entry or vice versa), and a bf16
    # entry is a different compiled program with half the params HBM —
    # fp32 and bf16 warm pool entries must coexist, not collide
    'compute_dtype': 'both',
    'compilation_cache_dir': 'pool_only',
    # input-side decode parallelism (decode farm): where decode runs,
    # never the bytes produced (tests/test_farm.py pins byte-identity);
    # the FIRST builder's farm settings win for a shared warm entry
    'decode_workers': 'neither',
    'decode_farm_ring_mb': 'neither',
    # output-side pipelining depth (async device loop): how deep D2H
    # defers behind dispatch, never what the step computes
    # (tests/test_packing.py pins byte-identity); FIRST builder wins
    'inflight': 'neither',
    # observability / debug surfaces: telemetry can't change the bytes,
    # and fragmenting the executable key space on trace settings would
    # transplant + compile twice for a trace_out difference. show_pred
    # and profile change the worker's RUN behavior → pool-key relevant
    # is deliberately NOT claimed for trace knobs, but profile is forced
    # on for the serve metrics surface → excluded from the pool key too.
    'profile': 'neither',
    'profile_dir': 'neither',
    'show_pred': 'pool_only',
    'trace_out': 'neither',
    'trace_capacity': 'neither',
    'manifest_out': 'neither',
    # vft-flight telemetry (black box + watchdog): where crash dumps
    # land and when liveness trips can't change the extracted bytes,
    # and fragmenting the executable key space on a postmortem path
    # would transplant twice for a telemetry difference — same policy
    # as the trace knobs above
    'postmortem_dir': 'neither',
    'postmortem_max_bytes': 'neither',
    'watchdog_stall_s': 'neither',
    # vft-scope SLOs: burn-rate evaluation reads metrics the serving
    # path already records — an objective can't change extracted bytes
    # or executable identity
    'slo_latency_p99_s': 'neither',
    'slo_availability': 'neither',
    # the cache's own namespace must not fragment its key space; pool-key
    # RELEVANT: a worker's extractor publishes/consults the cache
    # configured at build time, so requests with different cache
    # settings must not share an entry
    'cache_enabled': 'pool_only',
    'cache_dir': 'pool_only',
    'cache_max_bytes': 'pool_only',
    # the L2 is part of WHICH store the worker publishes/consults —
    # same pool-key reasoning as cache_dir; and like cache_dir it can
    # never change the bytes an extractor computes
    'cache_l2_dir': 'pool_only',
    # executable store (aot/): where compiled programs are LOADED from
    # can never change the bytes they compute (loaded executables are
    # byte-identical to fresh compiles — tests/test_aot.py pins it), so
    # the fingerprint excludes all three; pool-key RELEVANT for the
    # same reason as cache_*: a worker consults/publishes the store it
    # was built with, so requests naming different stores must not
    # share an entry
    'aot_enabled': 'pool_only',
    'aot_dir': 'pool_only',
    'aot_max_bytes': 'pool_only',
    # same reasoning as aot_dir: names WHERE executables come from,
    # never what they compute
    'aot_l2_dir': 'pool_only',
    # feature index (index/): a serving-side consumer of ALREADY
    # published cache objects — ingest and query never touch what an
    # extractor computes, and no worker binds to these knobs at build
    # time (the IndexService reads them once at boot), so they fragment
    # neither the cache key space nor the warm pool
    'index_enabled': 'neither',
    'index_dir': 'neither',
    'index_shard_rows': 'neither',
    'index_poll_s': 'neither',
    'index_query_block': 'neither',
    'index_k_max': 'neither',
    # covered by the weights fingerprint (checkpoint CONTENT is hashed)
    'allow_random_weights': 'pool_only',
    # serve-side per-request plumbing
    'timeout_s': 'neither',
    'config': 'pool_only',
}

_KNOB_AXIS_EXCLUDES = {
    'fingerprint': ('neither', 'pool_only'),
    'pool_key': ('neither', 'fingerprint_only'),
}


def knob_exclude(axis: str) -> frozenset:
    """The keys excluded from ``axis`` (``'fingerprint'`` |
    ``'pool_key'``), derived from :data:`KNOB_CLASSIFICATION`."""
    excluded_classes = _KNOB_AXIS_EXCLUDES[axis]
    return frozenset(k for k, cls in KNOB_CLASSIFICATION.items()
                     if cls in excluded_classes)


class Config(dict):
    """A flat dict with attribute access — the shape every extractor consumes.

    The reference accepts "any object with the right attributes" (its tests
    patch OmegaConf dicts programmatically, tests/utils.py:51-56); this class
    keeps that duck-typed contract.
    """

    def __getattr__(self, key: str) -> Any:
        try:
            return self[key]
        except KeyError:
            raise AttributeError(key)

    def __setattr__(self, key: str, value: Any) -> None:
        self[key] = value

    def __delattr__(self, key: str) -> None:
        try:
            del self[key]
        except KeyError:
            raise AttributeError(key)

    def copy(self) -> 'Config':
        return Config(self)


def build_cfg_path(feature_type: str) -> Path:
    """Default YAML path for a feature family (reference utils/utils.py:229-240)."""
    return CONFIG_DIR / f'{feature_type}.yml'


def _parse_value(raw: str) -> Any:
    """Parse one CLI value with YAML scalar/list semantics (OmegaConf-like).

    ``null``→None, ``true``→bool, ``3``→int, ``'[a,b]'``→list, else str.
    """
    try:
        return yaml.safe_load(raw)
    except yaml.YAMLError:
        return raw


def parse_dotlist(dotlist: Iterable[str]) -> Config:
    """Parse ``['key=value', ...]`` CLI args into a Config."""
    cfg = Config()
    for item in dotlist:
        if '=' not in item:
            raise ValueError(f'Malformed CLI argument (expected key=value): {item!r}')
        key, _, raw = item.partition('=')
        cfg[key.strip()] = _parse_value(raw)
    return cfg


def load_yaml(path: Union[str, os.PathLike]) -> Config:
    with open(path) as f:
        data = yaml.safe_load(f) or {}
    if not isinstance(data, dict):
        raise ValueError(f'Config file {path} must contain a flat mapping')
    return Config(data)


def load_config(
    feature_type: Optional[str] = None,
    overrides: Optional[Dict[str, Any]] = None,
    run_sanity_check: bool = True,
) -> Config:
    """YAML defaults ← overrides (overrides win), then sanity_check.

    Mirrors reference main.py:9-11: ``OmegaConf.merge(args_yml, args_cli)``
    with CLI priority, followed by ``sanity_check``.
    """
    overrides = dict(overrides or {})
    feature_type = feature_type or overrides.get('feature_type')
    if feature_type is None:
        raise ValueError('feature_type must be given (CLI: feature_type=<name>)')
    cfg_path = build_cfg_path(feature_type)
    if not cfg_path.exists():
        raise NotImplementedError(
            f'Extractor {feature_type!r} is not implemented. '
            f'Known: {", ".join(KNOWN_FEATURE_TYPES)}')
    args = load_yaml(cfg_path)
    for key, value in CACHE_DEFAULTS.items():
        args.setdefault(key, value)
    for key, value in AOT_DEFAULTS.items():
        args.setdefault(key, value)
    for key, value in INDEX_DEFAULTS.items():
        args.setdefault(key, value)
    for key, value in OBS_DEFAULTS.items():
        args.setdefault(key, value)
    for key, value in PIPELINE_DEFAULTS.items():
        args.setdefault(key, value)
    for key, value in FARM_DEFAULTS.items():
        args.setdefault(key, value)
    args.update(overrides)
    if run_sanity_check:
        sanity_check(args)
    return args


def resolve_fused_features(value: Union[str, Iterable[str]]) -> List[str]:
    """Normalize + validate a fused-worklist ``features`` value.

    Accepts a list (the YAML-parsed CLI form ``features=[resnet,clip]``)
    or a comma-separated string; returns the de-duplicated family list in
    user order. Every family must be in :data:`KNOWN_FEATURE_TYPES` —
    ValueError (not assert: user-facing, must survive ``python -O``)
    names the offender. A single-family list is legal and simply routes
    to the ordinary single-family path.
    """
    if isinstance(value, str):
        items = [s.strip() for s in value.split(',') if s.strip()]
    elif isinstance(value, (list, tuple)):
        items = [str(s).strip() for s in value if str(s).strip()]
    else:
        raise ValueError(
            f'features must be a list of family names or a comma-separated '
            f'string (e.g. features=[resnet,clip,timm]); got {value!r}')
    if not items:
        raise ValueError('features must name at least one feature family')
    families: List[str] = []
    for fam in items:
        if fam not in KNOWN_FEATURE_TYPES:
            raise ValueError(
                f'features names unknown family {fam!r} '
                f'(known: {", ".join(KNOWN_FEATURE_TYPES)})')
        if fam not in families:
            families.append(fam)
    return families


def split_fused_overrides(
    overrides: Dict[str, Any], families: Iterable[str],
) -> Tuple[Config, Dict[str, Config]]:
    """Split a fused-run dotlist into (shared, per-family) overrides.

    ``<family>.<knob>=value`` keys (``parse_dotlist`` keeps the dot) are
    family-SCOPED: they reach only that family's merged config — the
    escape hatch for knobs that must differ per family (``timm.
    model_name=vit_base_patch16_224`` while resnet keeps its YAML
    default). The routing keys ``features``/``feature_type`` are dropped
    from the shared set: each family's config is resolved with its own
    ``feature_type``, and ``features`` leaking into a merged config would
    fragment its cache fingerprint vs a sequential run (fail-closed
    unknown keys stay IN the fingerprint).
    """
    fams = list(families)
    shared, scoped = Config(), {f: Config() for f in fams}
    for key, value in dict(overrides or {}).items():
        if key in ('features', 'feature_type'):
            continue
        head, dot, rest = key.partition('.')
        if dot and head in scoped and rest:
            scoped[head][rest] = value
        else:
            shared[key] = value
    return shared, scoped


def load_fused_configs(
    features: Union[str, Iterable[str]],
    overrides: Optional[Dict[str, Any]] = None,
    run_sanity_check: bool = True,
) -> 'Dict[str, Config]':
    """One merged per-family config per requested family, in user order.

    Each family resolves exactly as a sequential ``load_config(family,
    shared + family-scoped overrides)`` run would — same YAML defaults,
    same injected knob defaults, same sanity_check path rewriting
    (``output_path/<family>[/<model_name>]``) — so per-``(family,
    video)`` cache keys, resume sidecars, and output naming are
    byte-for-byte those of N sequential runs. Validation is all-or-
    nothing: any invalid family or per-family config rejects the whole
    fused request before any work starts.
    """
    families = resolve_fused_features(features)
    shared, scoped = split_fused_overrides(dict(overrides or {}), families)
    configs: Dict[str, Config] = {}
    for fam in families:
        fam_overrides = Config(shared)
        fam_overrides.update(scoped[fam])
        configs[fam] = load_config(fam, overrides=fam_overrides,
                                   run_sanity_check=run_sanity_check)
    return configs


def resolve_device(device: str) -> str:
    """Map a user device string onto a JAX platform.

    The reference accepts torch strings ('cuda:0', 'cpu'); we keep accepting
    them for drop-in compatibility (reference utils/utils.py:83-92 maps
    unavailable CUDA → CPU): 'cuda*'/'tpu' → the accelerator platform if one
    is present, else 'cpu'.
    """
    import jax

    from video_features_tpu.utils.device import pin_cpu_platform

    device = str(device).lower()
    if device == 'cpu':
        # Pin before backends initialize: probing for accelerators here
        # would spin up every registered plugin (a remote-TPU tunnel can
        # block a pure-CPU run for minutes).
        pin_cpu_platform()
        return 'cpu'
    platforms = {d.platform for d in jax.devices()}
    accel = next((p for p in platforms if p != 'cpu'), None)
    if device.startswith(('cuda', 'tpu', 'gpu', 'accel')):
        if accel is not None:
            return accel
        # warnings.warn (→ stderr), not print: with on_extraction=print
        # the feature stream owns stdout (vft-lint: stdout-purity)
        warnings.warn('An accelerator was requested but the system does '
                      'not have one. Going to use CPU...')
        return 'cpu'
    return 'cpu'


def sanity_check(args: Config) -> None:
    """Validate the merged config and rewrite output/tmp paths.

    Check-for-check parity with reference utils/utils.py:77-135:
      * legacy ``device_ids`` → single-device warning (:83-89);
      * unavailable accelerator degrades to CPU (:90-92);
      * paths required; unique video stems (:93-95, upstream issue #54);
      * output_path != tmp_path (:96);
      * i3d stack_size >= 10 (:103-106); pwc removed (:107-109);
      * timm model_name required (:113-115); batch_size not None (:116-117);
      * extraction_fps xor extraction_total (:118-120);
      * append ``<feature_type>[/<model_name>]`` ('/'→'_') to output/tmp
        paths (:122-135).
    """
    if 'device_ids' in args:
        warnings.warn(
            'multi-device single-process extraction is not supported. '
            'Scale out by sharding the video list across workers/hosts '
            f'(device_ids={args["device_ids"]} ignored; using one '
            'accelerator).')
        args['device'] = 'tpu'
    args['device'] = resolve_device(args.get('device', 'cpu'))

    from video_features_tpu.utils.device import MATMUL_PRECISIONS
    prec = args.get('precision', 'highest')
    # ValueError, not assert: user-facing validation must survive `python -O`
    # (an invalid value would otherwise surface later as an opaque
    # jax.default_matmul_precision error inside the per-video loop)
    if prec not in MATMUL_PRECISIONS:
        raise ValueError(
            f'precision must be one of {MATMUL_PRECISIONS}; got {prec!r}')
    backend = args.get('decode_backend', 'auto')
    if backend not in ('auto', 'native', 'cv2'):
        raise ValueError(
            f"decode_backend must be 'auto', 'native', or 'cv2'; "
            f'got {backend!r}')

    # bf16 fast lane (ops/precision.py): validate the value AND the
    # family's acceptance at config time — a family without a pinned
    # parity bound refuses the knob with a structured error here, so a
    # serve submit fails its build with the bound named instead of a
    # worker shipping out-of-bound features. ComputeDtypeError is a
    # ValueError — same surface as every other knob rejection.
    from video_features_tpu.ops.precision import check_compute_dtype
    args['compute_dtype'] = check_compute_dtype(
        args.get('feature_type'),
        str(args.get('compute_dtype') or 'float32'))
    if args.get('cache_enabled'):
        if not args.get('cache_dir'):
            raise ValueError('cache_enabled=true requires cache_dir '
                             '(see docs/caching.md)')
        if args.get('cache_max_bytes') is not None:
            args['cache_max_bytes'] = int(args['cache_max_bytes'])
            if args['cache_max_bytes'] < 0:
                raise ValueError('cache_max_bytes must be >= 0 or null; '
                                 f'got {args["cache_max_bytes"]}')
        if args.get('on_extraction') == 'print':
            # nothing reaches disk, so there is nothing to address by
            # content — warn-and-disable (same policy as the packing knob)
            warnings.warn('cache_enabled has no effect with '
                          'on_extraction=print — disabling the cache')
            args['cache_enabled'] = False
    if args.get('cache_l2_dir') is not None:
        # the shared tier rides on the cache: without a local L1 store
        # there is nothing to tier
        args['cache_l2_dir'] = str(args['cache_l2_dir'])
        if not args.get('cache_enabled'):
            raise ValueError('cache_l2_dir requires cache_enabled=true '
                             '(see docs/fleet.md)')

    # executable-store knobs (aot/): the dir coerces to str, the size
    # bound must be a non-negative int. ValueError, not assert —
    # survives `python -O` like every other knob rejection.
    if args.get('aot_enabled'):
        if not args.get('aot_dir'):
            raise ValueError('aot_enabled=true requires aot_dir '
                             '(see docs/serving.md "Zero cold start")')
    if args.get('aot_dir') is not None:
        args['aot_dir'] = str(args['aot_dir'])
    if args.get('aot_max_bytes') is not None:
        args['aot_max_bytes'] = int(args['aot_max_bytes'])
        if args['aot_max_bytes'] < 0:
            raise ValueError('aot_max_bytes must be >= 0 or null; '
                             f'got {args["aot_max_bytes"]}')
    if args.get('aot_l2_dir') is not None:
        args['aot_l2_dir'] = str(args['aot_l2_dir'])
        if not args.get('aot_enabled'):
            raise ValueError('aot_l2_dir requires aot_enabled=true '
                             '(see docs/fleet.md)')

    # feature-index knobs (index/): the ingest worker tails the CACHE
    # manifest, so the index requires the cache; geometry knobs must be
    # positive ints (they size compiled programs). ValueError, not
    # assert — survives `python -O`.
    if args.get('index_enabled'):
        if not args.get('cache_enabled'):
            raise ValueError('index_enabled=true requires '
                             'cache_enabled=true — the index ingests '
                             'published cache objects '
                             '(see docs/feature_index.md)')
    if args.get('index_dir') is not None:
        args['index_dir'] = str(args['index_dir'])
    for key in ('index_shard_rows', 'index_query_block', 'index_k_max'):
        if args.get(key) is not None:
            args[key] = int(args[key])
            if args[key] < 1:
                raise ValueError(f'{key} must be >= 1; got {args[key]}')
    if args.get('index_poll_s') is not None:
        args['index_poll_s'] = float(args['index_poll_s'])
        if args['index_poll_s'] <= 0:
            raise ValueError('index_poll_s must be > 0 (seconds between '
                             'ingest polls when caught up); got '
                             f'{args["index_poll_s"]}')

    # device-loop pipelining: the in-flight depth must be a positive int
    # (1 = synchronous; each extra unit pins one more output batch on
    # device). ValueError, not assert — survives `python -O`.
    if args.get('inflight') is not None:
        args['inflight'] = int(args['inflight'])
        if args['inflight'] < 1:
            raise ValueError(
                f'inflight must be >= 1 (1 = synchronous device loop); '
                f'got {args["inflight"]}')

    # mesh-sharded packed execution: device count must be a non-negative
    # int (0 = auto-detect, 1 = single device). data_parallel owns its
    # own mesh (per-extractor in-graph DP with batch rounding), so the
    # two knobs must not both claim the device set — data_parallel wins
    # as the legacy spelling and mesh_devices degrades with a warning.
    if args.get('mesh_devices') is not None:
        args['mesh_devices'] = int(args['mesh_devices'])
        if args['mesh_devices'] < 0:
            raise ValueError(
                'mesh_devices must be >= 0 (0 = auto-detect local '
                f'devices, 1 = single device); got {args["mesh_devices"]}')
        if args['mesh_devices'] != 1 and args.get('data_parallel'):
            warnings.warn(
                'mesh_devices and data_parallel both requested — '
                'data_parallel already owns the device mesh, so '
                'mesh_devices is ignored (running mesh_devices=1)')
            args['mesh_devices'] = 1

    # decode-farm knobs (farm/): worker count and per-worker SHM ring
    # size must be positive ints. ValueError, not assert — survives -O.
    if args.get('decode_workers') is not None:
        args['decode_workers'] = int(args['decode_workers'])
        if args['decode_workers'] < 1:
            raise ValueError(
                f'decode_workers must be >= 1 (1 = in-process decode); '
                f'got {args["decode_workers"]}')
    if args.get('decode_farm_ring_mb') is not None:
        args['decode_farm_ring_mb'] = int(args['decode_farm_ring_mb'])
        if args['decode_farm_ring_mb'] < 1:
            raise ValueError(
                'decode_farm_ring_mb must be >= 1 (MiB per worker ring); '
                f'got {args["decode_farm_ring_mb"]}')

    # flight-recorder knobs (obs/): paths coerce to str; the ring-buffer
    # bound must be a positive int or the recorder silently records nothing
    for key in ('trace_out', 'manifest_out'):
        if args.get(key) is not None:
            args[key] = str(args[key])
    if args.get('trace_capacity') is not None:
        args['trace_capacity'] = int(args['trace_capacity'])
        if args['trace_capacity'] < 1:
            raise ValueError('trace_capacity must be >= 1; got '
                             f'{args["trace_capacity"]}')

    # vft-flight knobs (obs/blackbox.py, obs/watchdog.py): the dump dir
    # coerces to str, the size cap and stall deadline must be positive
    # (ValueError, not assert — survives `python -O`)
    if args.get('postmortem_dir') is not None:
        args['postmortem_dir'] = str(args['postmortem_dir'])
    if args.get('postmortem_max_bytes') is not None:
        args['postmortem_max_bytes'] = int(args['postmortem_max_bytes'])
        if args['postmortem_max_bytes'] < 1:
            raise ValueError('postmortem_max_bytes must be >= 1; got '
                             f'{args["postmortem_max_bytes"]}')
    if args.get('watchdog_stall_s') is not None:
        args['watchdog_stall_s'] = float(args['watchdog_stall_s'])
        if args['watchdog_stall_s'] <= 0:
            raise ValueError('watchdog_stall_s must be > 0 (seconds '
                             'without a stage advance before a stall '
                             f'trips); got {args["watchdog_stall_s"]}')

    # vft-scope SLO knobs (obs/slo.py): a latency objective is a positive
    # deadline; availability is a success-rate target strictly inside
    # (0, 1) — 1.0 means a zero error budget and every failure divides
    # by it
    if args.get('slo_latency_p99_s') is not None:
        args['slo_latency_p99_s'] = float(args['slo_latency_p99_s'])
        if args['slo_latency_p99_s'] <= 0:
            raise ValueError('slo_latency_p99_s must be > 0 (the p99 '
                             'latency objective in seconds); got '
                             f'{args["slo_latency_p99_s"]}')
    if args.get('slo_availability') is not None:
        args['slo_availability'] = float(args['slo_availability'])
        if not 0 < args['slo_availability'] < 1:
            raise ValueError('slo_availability must be in (0, 1), e.g. '
                             f'0.999; got {args["slo_availability"]}')

    assert args.get('file_with_video_paths') or args.get('video_paths'), \
        '`video_paths` or `file_with_video_paths` must be specified'
    filenames = [Path(p).stem for p in form_list_from_user_input(
        args.get('video_paths'), args.get('file_with_video_paths'), to_shuffle=False)]
    assert len(filenames) == len(set(filenames)), \
        'Non-unique video filenames (stems collide in the flat output dir)'
    assert os.path.relpath(str(args['output_path'])) != os.path.relpath(str(args['tmp_path'])), \
        'The same path for out & tmp'

    ft = args.get('feature_type')
    if args.get('show_pred') and ft == 'vggish':
        warnings.warn('Showing class predictions is not implemented '
                      'for VGGish')
    if args.get('data_parallel'):
        from video_features_tpu.registry import DATA_PARALLEL_FEATURES
        if ft not in DATA_PARALLEL_FEATURES:
            warnings.warn(
                f'data_parallel is not implemented for {ft} — running '
                'single-device (scale out with multihost=true / sharded '
                'worklists instead)')
            args['data_parallel'] = False
    if args.get('pack_across_videos'):
        from video_features_tpu.registry import PACKED_FEATURES
        # warnings.warn (→ stderr), NOT print: with on_extraction=print the
        # features themselves go to stdout and a WARNING line interleaved
        # there breaks downstream parsers of the feature stream
        if ft not in PACKED_FEATURES:
            warnings.warn(
                f'pack_across_videos is not implemented for {ft} — running '
                'the per-video loop')
            args['pack_across_videos'] = False
        elif args.get('show_pred'):
            # show_pred is a per-video debug surface (it narrates windows in
            # video order); a packed batch interleaves videos
            warnings.warn(
                'show_pred is incompatible with pack_across_videos — '
                'running the per-video loop')
            args['pack_across_videos'] = False
    if ft == 'i3d' and args.get('stack_size') is not None:
        assert args['stack_size'] >= 10, (
            f'I3D does not support inputs shorter than 10 timestamps. '
            f'You have: {args["stack_size"]}')
    if ft == 'pwc' or (ft == 'i3d' and args.get('flow_type') == 'pwc'):
        raise NotImplementedError('PWC flow is not supported; use flow_type=raft')
    if ft == 'timm':
        assert args.get('model_name') is not None, \
            'Please specify `model_name` for timm-style models; e.g. `vit_base_patch16_224`'
    if 'batch_size' in args:
        assert args['batch_size'] is not None, \
            f'Please specify `batch_size`. It is {args["batch_size"]} now'
    if 'extraction_fps' in args and 'extraction_total' in args:
        assert not (args['extraction_fps'] is not None and args['extraction_total'] is not None), \
            '`extraction_fps` and `extraction_total` are mutually exclusive'

    # Append <feature_type>[/<model_name>] to output & tmp paths ('/' → '_').
    subs = [ft] if ft else []
    if args.get('model_name') is not None:
        subs.append(str(args['model_name']))
    out, tmp = str(args['output_path']), str(args['tmp_path'])
    for p in subs:
        out = os.path.join(out, p.replace('/', '_'))
        tmp = os.path.join(tmp, p.replace('/', '_'))
    args['output_path'] = out
    args['tmp_path'] = tmp


# -- serving (python -m video_features_tpu serve) ---------------------------

# Server-level knobs (everything else on the serve command line becomes a
# BASE OVERRIDE merged under every request's config — e.g. device=tpu
# allow_random_weights=true output_path=...). One flat namespace so the
# serve CLI stays the same dotlist as extraction.
SERVE_DEFAULTS: Dict[str, Any] = {
    # local JSON-lines endpoint (requests + metrics); port 0 = ephemeral,
    # printed at startup
    'serve_host': '127.0.0.1',
    'serve_port': 0,
    # admission control: max videos queued-or-in-flight across the server;
    # submits that would exceed it are REJECTED (backpressure), not queued
    'serve_queue_depth': 64,
    # warm-pool bound: distinct (feature_type, geometry, …) executables
    # kept resident; LRU-evicted (gracefully drained) beyond this
    'serve_warm_pool_size': 4,
    # arrival-lull flush: when a worker's request feed is idle this long
    # with windows still pooled, partial batches flush padded so a lone
    # request's tail latency is bounded by this + one device step
    'serve_idle_flush_s': 0.05,
    # liveness bound under CONTINUOUS traffic: even with the queue never
    # idle, partial geometry pools flush at least this often — a lone
    # odd-geometry request can't starve behind a stream of other
    # geometries (trade: more padded slots as this shrinks)
    'serve_max_batch_wait_s': 2.0,
    # default per-request deadline (seconds, null = none): requests whose
    # deadline passes before a video STARTS decoding expire unstarted
    'serve_default_timeout_s': None,
    # optional metrics mirror: the live metrics JSON is atomically
    # rewritten here on every request completion (scrape without a socket)
    'serve_metrics_path': None,
    # priority-class admission (protocol 'priority' field / ingress
    # tenant classes): 'batch' requests only see this fraction of
    # serve_queue_depth, so a saturated queue sheds batch before
    # interactive. 1.0 = no distinction.
    'serve_batch_shed_fraction': 0.5,
    # zero cold start (aot/; docs/serving.md "Zero cold start"): build
    # these warm-pool entries at BOOT, before the first request —
    # a list of 'family' or 'family@lane' specs (e.g.
    # '[resnet,resnet@bfloat16]'), each resolved against the base
    # overrides exactly like a cold submit. With aot_enabled=true in
    # the base overrides, an unchanged program set makes the boot
    # compile-free: every pre-warmed program LOADS from the executable
    # store (builds_loaded in pool stats) instead of compiling. null =
    # no pre-warm (today's behavior: the first request pays the build).
    'serve_prewarm': None,
    # -- ingress (ingress/; docs/ingress.md): the network front door ----
    # HTTP/1.1 + chunked endpoint port: null = DISABLED (loopback-only
    # server, today's behavior), 0 = ephemeral (printed at startup)
    'serve_ingress_port': None,
    'serve_ingress_host': '127.0.0.1',
    # API-key file (JSON/YAML: key → {tenant, priority, rate_rps, burst,
    # max_concurrent}) — REQUIRED when the ingress is enabled; there is
    # deliberately no anonymous mode on a network-facing endpoint
    'serve_ingress_auth_file': None,
    # request-body bound (MiB): oversized bodies get a structured
    # 413-style rejection instead of crashing (or OOMing) the reader
    'serve_ingress_max_body_mb': 64,
    # concurrent-connection bound: excess connects get an immediate 503
    'serve_ingress_max_connections': 64,
}


def split_serve_config(cli_args: Dict[str, Any]) -> Tuple[Config, Config]:
    """Split a serve-command dotlist into (server knobs, base overrides).

    ``serve_*`` keys must be known (a typo'd knob silently becoming a
    per-request override would be maddening to debug); everything else is
    merged under every request's per-feature config via ``load_config``.
    """
    serve, base = Config(SERVE_DEFAULTS), Config()
    for key, value in dict(cli_args).items():
        if key.startswith('serve_'):
            if key not in SERVE_DEFAULTS:
                raise ValueError(
                    f'Unknown serve option {key!r}. '
                    f'Known: {", ".join(sorted(SERVE_DEFAULTS))}')
            serve[key] = value
        else:
            base[key] = value
    for key in ('serve_queue_depth', 'serve_warm_pool_size'):
        serve[key] = int(serve[key])
        if serve[key] < 1:
            raise ValueError(f'{key} must be >= 1; got {serve[key]}')
    serve['serve_port'] = int(serve['serve_port'])
    for key in ('serve_idle_flush_s', 'serve_max_batch_wait_s'):
        serve[key] = float(serve[key])
        if serve[key] <= 0:
            raise ValueError(f'{key} must be > 0')
    if serve['serve_default_timeout_s'] is not None:
        serve['serve_default_timeout_s'] = \
            float(serve['serve_default_timeout_s'])
    if serve['serve_prewarm'] is not None:
        # one spec or a list of 'family[@lane]' specs; validated here so
        # a typo'd family fails the BOOT, not the first request
        specs = serve['serve_prewarm']
        if isinstance(specs, str):
            specs = [specs]
        if not isinstance(specs, (list, tuple)) or not all(
                isinstance(s, str) and s.strip() for s in specs):
            raise ValueError(
                "serve_prewarm must be a 'family[@lane]' spec or a list "
                f'of them (e.g. [resnet,resnet@bfloat16]); got '
                f'{serve["serve_prewarm"]!r}')
        specs = [s.strip() for s in specs]
        # validated against the SERVEABLE set, not KNOWN_FEATURE_TYPES:
        # a family without packed/serving support (vggish, raft) would
        # pass the build but occupy a pool slot no request can reach —
        # the same gate the submit path applies, moved to the boot
        from video_features_tpu.registry import PACKED_FEATURES
        for spec in specs:
            family = spec.split('@', 1)[0]
            # 'index' is the one non-extractor spec: it warms the
            # feature index's query program instead of a pool entry
            if family == 'index':
                continue
            if family not in PACKED_FEATURES:
                raise ValueError(
                    f'serve_prewarm names unknown or unserveable family '
                    f'{family!r} (serveable: index, '
                    f'{", ".join(sorted(PACKED_FEATURES))})')
        serve['serve_prewarm'] = specs
    serve['serve_batch_shed_fraction'] = \
        float(serve['serve_batch_shed_fraction'])
    if not (0 < serve['serve_batch_shed_fraction'] <= 1):
        raise ValueError('serve_batch_shed_fraction must be in (0, 1]; '
                         f'got {serve["serve_batch_shed_fraction"]}')
    if serve['serve_ingress_port'] is not None:
        serve['serve_ingress_port'] = int(serve['serve_ingress_port'])
        if not serve['serve_ingress_auth_file']:
            raise ValueError(
                'serve_ingress_port requires serve_ingress_auth_file '
                '(an API-key file; see docs/ingress.md) — the network '
                'front door has no anonymous mode')
    for key in ('serve_ingress_max_body_mb',
                'serve_ingress_max_connections'):
        serve[key] = int(serve[key])
        if serve[key] < 1:
            raise ValueError(f'{key} must be >= 1; got {serve[key]}')
    return serve, base


# -- fleet router (fleet/; docs/fleet.md) ------------------------------------
# Router-process knobs, NOT extraction config: the `fleet` command takes
# ONLY these (backends own their extraction/serve config), so unlike the
# *_DEFAULTS families above they never merge into per-feature args and
# carry no fingerprint/pool-key classification.
FLEET_DEFAULTS: Dict[str, Any] = {
    # static backend membership: a list of host:port serve daemons
    # (bare ports mean loopback — the simulation/test form). LIVENESS
    # is probed, not configured: unhealthy or draining hosts leave the
    # eligible set without a config change.
    'fleet_hosts': None,
    # the router's own loopback JSON-lines listener (0 = ephemeral)
    'fleet_port': 9310,
    'fleet_host': '127.0.0.1',
    # optional HTTP front door (ingress transport); null = loopback only
    'fleet_http_port': None,
    'fleet_http_host': '127.0.0.1',
    # API-key file for the HTTP front door (required when it's on —
    # same no-anonymous-mode policy as serve_ingress_auth_file)
    'fleet_auth_file': None,
    # health-probe cadence; the probe also reads each backend's
    # `draining` flag for drain-aware membership
    'fleet_probe_interval_s': 2.0,
    # failover bound: how many ring hosts one request may try
    'fleet_max_attempts': 3,
    # backoff between ring hosts (doubles per attempt, capped)
    'fleet_backoff_base_s': 0.05,
    # per-backend connect deadline on the request path
    'fleet_connect_timeout_s': 2.0,
    # virtual nodes per host on the consistent-hash ring
    'fleet_ring_replicas': 64,
    # fleet-level SLOs (obs/slo.py evaluated over the router's routed-
    # request families): always on at the router — /metrics is one
    # scrape target for the whole fleet, so the vft_slo_* gauges must
    # always render. Defaults are generous (video extraction is
    # minutes-scale); tighten per deployment.
    'fleet_slo_latency_p99_s': 30.0,
    'fleet_slo_availability': 0.999,
}


def split_fleet_config(cli_args: Dict[str, Any]) -> Tuple[Config, Config]:
    """Split a fleet-command dotlist into (router knobs, leftovers).

    Same typo discipline as :func:`split_serve_config`; leftovers are
    returned (not merged anywhere) so ``fleet_main`` can refuse them —
    the router forwards requests, it does not own extraction config.
    """
    fleet, extra = Config(FLEET_DEFAULTS), Config()
    for key, value in dict(cli_args).items():
        if key.startswith('fleet_'):
            if key not in FLEET_DEFAULTS:
                raise ValueError(
                    f'Unknown fleet option {key!r}. '
                    f'Known: {", ".join(sorted(FLEET_DEFAULTS))}')
            fleet[key] = value
        else:
            extra[key] = value
    if fleet['fleet_hosts'] is not None:
        hosts = fleet['fleet_hosts']
        if isinstance(hosts, (str, int)):
            hosts = [hosts]
        if not isinstance(hosts, (list, tuple)) or not hosts:
            raise ValueError(
                'fleet_hosts must be a host:port (or bare-port) list, '
                f'e.g. [127.0.0.1:9301,127.0.0.1:9302]; got '
                f'{fleet["fleet_hosts"]!r}')
        fleet['fleet_hosts'] = [str(h) for h in hosts]
    for key in ('fleet_port', 'fleet_max_attempts', 'fleet_ring_replicas'):
        fleet[key] = int(fleet[key])
    if fleet['fleet_port'] < 0:
        raise ValueError(f'fleet_port must be >= 0; got {fleet["fleet_port"]}')
    for key in ('fleet_max_attempts', 'fleet_ring_replicas'):
        if fleet[key] < 1:
            raise ValueError(f'{key} must be >= 1; got {fleet[key]}')
    for key in ('fleet_probe_interval_s', 'fleet_backoff_base_s',
                'fleet_connect_timeout_s', 'fleet_slo_latency_p99_s'):
        fleet[key] = float(fleet[key])
        if fleet[key] <= 0:
            raise ValueError(f'{key} must be > 0; got {fleet[key]}')
    fleet['fleet_slo_availability'] = float(fleet['fleet_slo_availability'])
    if not 0 < fleet['fleet_slo_availability'] < 1:
        raise ValueError('fleet_slo_availability must be in (0, 1), '
                         f'e.g. 0.999; got {fleet["fleet_slo_availability"]}')
    if fleet['fleet_http_port'] is not None:
        fleet['fleet_http_port'] = int(fleet['fleet_http_port'])
        if not fleet['fleet_auth_file']:
            raise ValueError(
                'fleet_http_port requires fleet_auth_file (an API-key '
                'file; see docs/ingress.md) — the fleet front door has '
                'no anonymous mode either')
    return fleet, extra


def form_list_from_user_input(
    video_paths: Union[str, List[str], None] = None,
    file_with_video_paths: Optional[str] = None,
    to_shuffle: bool = True,
) -> List[str]:
    """Normalize user-specified paths into a list (reference utils/utils.py:138-178).

    A file lists one path per line (blank lines dropped). Shuffling randomizes
    the work order so independent shared-filesystem workers rarely collide on
    the same video — the reference's whole multi-worker story (:151-152).
    """
    if file_with_video_paths is None:
        if video_paths is None:
            path_list: List[str] = []
        elif isinstance(video_paths, str):
            path_list = [video_paths]
        else:
            path_list = [str(p) for p in video_paths]
    else:
        with open(file_with_video_paths) as f:
            path_list = [line.strip() for line in f if line.strip()]

    for path in path_list:
        # '.live' paths are VIRTUAL — live-session pseudo-identities
        # (serve/server.submit_live); nothing exists (or should) at them
        if not path.endswith('.live') and not Path(path).exists():
            # obs.events (→ stderr), not print or warnings.warn: the
            # feature stream owns stdout, and this also runs inside
            # serve request handling — where the default warnings
            # filter would dedupe a repeated bad path to ONE report per
            # process, hiding every later tenant's mistake
            import logging

            from video_features_tpu.obs.events import event
            event(logging.WARNING, 'path does not exist',
                  video=str(path))

    if to_shuffle:
        random.shuffle(path_list)
    return path_list
