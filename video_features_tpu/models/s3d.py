"""S3D (separable 3-D inception net, kylemin/S3D layout).

Functional re-implementation of the architecture behind the reference s3d
extractor (reference models/s3d/s3d_src/s3d.py, 356 LoC): SepConv3d =
spatial (1,k,k) conv→BN→ReLU then temporal (k,1,1) conv→BN→ReLU (:66-87),
BasicConv3d 1×1×1 conv→BN→ReLU with BN eps 1e-3 (:51-63), inception blocks
Mixed_3b…Mixed_5c (:90-349), head = avg_pool (2,H,W) stride 1 → 1×1×1 conv
(classification only) → time mean (:35-48).

Params mirror the torch state_dict: ``base.<idx>.<sub>`` sequential naming.
Layout NDHWC.
"""
from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np

from video_features_tpu.ops.nn import avg_pool, batch_norm, conv, max_pool, relu

Params = Dict[str, Any]

BN_EPS = 1e-3
FEAT_DIM = 1024

# Mixed block channel table: in, b0, (b1_mid, b1_out), (b2_mid, b2_out), b3
MIXED_CFGS = {
    ' 3b': (192, 64, (96, 128), (16, 32), 32),
    ' 3c': (256, 128, (128, 192), (32, 96), 64),
    ' 4b': (480, 192, (96, 208), (16, 48), 64),
    ' 4c': (512, 160, (112, 224), (24, 64), 64),
    ' 4d': (512, 128, (128, 256), (24, 64), 64),
    ' 4e': (512, 112, (144, 288), (32, 64), 64),
    ' 4f': (528, 256, (160, 320), (32, 128), 128),
    ' 5b': (832, 256, (160, 320), (32, 128), 128),
    ' 5c': (832, 384, (192, 384), (48, 128), 128),
}
# base Sequential: index -> ('sep'|'basic'|'maxpool'|'mixed', spec)
BASE_LAYOUT = [
    ('sep', dict(i=3, o=64, k=7, s=2, p=3)),
    ('maxpool', dict(k=(1, 3, 3), s=(1, 2, 2), p=(0, 1, 1))),
    ('basic', dict(i=64, o=64)),
    ('sep', dict(i=64, o=192, k=3, s=1, p=1)),
    ('maxpool', dict(k=(1, 3, 3), s=(1, 2, 2), p=(0, 1, 1))),
    ('mixed', ' 3b'),
    ('mixed', ' 3c'),
    ('maxpool', dict(k=(3, 3, 3), s=(2, 2, 2), p=(1, 1, 1))),
    ('mixed', ' 4b'),
    ('mixed', ' 4c'),
    ('mixed', ' 4d'),
    ('mixed', ' 4e'),
    ('mixed', ' 4f'),
    ('maxpool', dict(k=(2, 2, 2), s=(2, 2, 2), p=(0, 0, 0))),
    ('mixed', ' 5b'),
    ('mixed', ' 5c'),
]


def _basic(p: Params, x: jax.Array) -> jax.Array:
    x = conv(x, p['conv']['weight'])
    return relu(batch_norm(x, p['bn'], eps=BN_EPS))


def _sep(p: Params, x: jax.Array, k: int, s: int, pad: int) -> jax.Array:
    x = conv(x, p['conv_s']['weight'], stride=(1, s, s),
             padding=[(0, 0), (pad, pad), (pad, pad)])
    x = relu(batch_norm(x, p['bn_s'], eps=BN_EPS))
    x = conv(x, p['conv_t']['weight'], stride=(s, 1, 1),
             padding=[(pad, pad), (0, 0), (0, 0)])
    return relu(batch_norm(x, p['bn_t'], eps=BN_EPS))


def _mixed(p: Params, x: jax.Array) -> jax.Array:
    b0 = _basic(p['branch0']['0'], x)
    b1 = _sep(p['branch1']['1'], _basic(p['branch1']['0'], x), 3, 1, 1)
    b2 = _sep(p['branch2']['1'], _basic(p['branch2']['0'], x), 3, 1, 1)
    b3 = _basic(p['branch3']['1'], max_pool(x, (3, 3, 3), stride=1, padding=1))
    return jnp.concatenate([b0, b1, b2, b3], axis=-1)


def forward(params: Params, x: jax.Array, features: bool = True) -> jax.Array:
    """(B, T, H, W, 3) float in [0,1] → (B, 1024) features or (B, 400) logits."""
    base = params['base']
    for idx, (kind, spec) in enumerate(BASE_LAYOUT):
        p = base.get(str(idx))
        if kind == 'sep':
            x = _sep(p, x, spec['k'], spec['s'], spec['p'])
        elif kind == 'basic':
            x = _basic(p, x)
        elif kind == 'mixed':
            x = _mixed(p, x)
        else:
            x = max_pool(x, spec['k'], stride=spec['s'], padding=spec['p'])
    # head: avg over (2, H, W) window stride 1, then mean over time
    B, T, H, W, C = x.shape
    if T < 2:
        # temporal stride through the net is 8; the reference's torch
        # avg_pool3d fails the same way, just more opaquely
        raise ValueError(
            f'S3D head needs >= 2 temporal positions after downsampling '
            f'(got {T}); use stack_size >= 16')
    x = avg_pool(x, (2, H, W), stride=1)          # (B, T-1, 1, 1, C)
    if not features:
        x = conv(x, params['fc']['0']['weight'], bias=params['fc']['0']['bias'])
    return x.reshape(B, T - 1, -1).mean(axis=1)


def init_state_dict(seed: int = 0, num_classes: int = 400) -> Dict[str, np.ndarray]:
    """Random torch-layout state_dict with kylemin/S3D naming/shapes."""
    rng = np.random.RandomState(seed)
    sd: Dict[str, np.ndarray] = {}

    def bn(name, c):
        sd[f'{name}.weight'] = rng.rand(c).astype(np.float32) + 0.5
        sd[f'{name}.bias'] = rng.randn(c).astype(np.float32) * 0.1
        sd[f'{name}.running_mean'] = rng.randn(c).astype(np.float32) * 0.1
        sd[f'{name}.running_var'] = rng.rand(c).astype(np.float32) + 0.5

    def basic(name, i, o):
        sd[f'{name}.conv.weight'] = rng.randn(o, i, 1, 1, 1).astype(np.float32) * 0.05
        bn(f'{name}.bn', o)

    def sep(name, i, o, k):
        sd[f'{name}.conv_s.weight'] = rng.randn(o, i, 1, k, k).astype(np.float32) * 0.05
        bn(f'{name}.bn_s', o)
        sd[f'{name}.conv_t.weight'] = rng.randn(o, o, k, 1, 1).astype(np.float32) * 0.05
        bn(f'{name}.bn_t', o)

    for idx, (kind, spec) in enumerate(BASE_LAYOUT):
        name = f'base.{idx}'
        if kind == 'sep':
            sep(name, spec['i'], spec['o'], spec['k'])
        elif kind == 'basic':
            basic(name, spec['i'], spec['o'])
        elif kind == 'mixed':
            cin, b0, (b1m, b1o), (b2m, b2o), b3 = MIXED_CFGS[spec]
            basic(f'{name}.branch0.0', cin, b0)
            basic(f'{name}.branch1.0', cin, b1m)
            sep(f'{name}.branch1.1', b1m, b1o, 3)
            basic(f'{name}.branch2.0', cin, b2m)
            sep(f'{name}.branch2.1', b2m, b2o, 3)
            basic(f'{name}.branch3.1', cin, b3)
    sd['fc.0.weight'] = rng.randn(num_classes, FEAT_DIM, 1, 1, 1).astype(np.float32) * 0.05
    sd['fc.0.bias'] = rng.randn(num_classes).astype(np.float32) * 0.05
    return sd
