"""VGGish audio embedding net (AudioSet VGG, harritaylor/torchvggish port).

Functional re-implementation of the architecture behind the reference's
vendored net (reference models/vggish/vggish_src/vggish_slim.py:15-37,
100-111): four conv stages [64, M, 128, M, 256×2, M, 512×2, M] of 3×3/pad-1
convs + ReLU with 2×2 max pools, then FC 12288→4096→4096→128, ReLU after
EVERY linear including the last.

Layout note: the torch net flattens its (B, 512, 6, 4) feature map
channels-LAST via two transposes before the FC stack (vggish_slim.py:28-35)
— in NHWC that flatten is just reshape, one more place the TPU layout is
the natural one.

The AudioSet release's PCA-whiten + 8-bit quantize postprocessor
(vggish_slim.py:40-99) is :func:`postprocess`; the reference's default
path bypasses it (forward(post_process=False)), and so does ours.
"""
from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np

from video_features_tpu.ops.nn import conv, linear, max_pool, relu

Params = Dict[str, Any]

FEAT_DIM = 128
# Sequential indices of the conv layers in torch's make_layers()
# ([64, M, 128, M, 256, 256, M, 512, 512, M] → convs at 0,3,6,8,11,13)
CONV_LAYERS = ((0, 64), (3, 128), (6, 256), (8, 256), (11, 512), (13, 512))
POOL_AFTER = {0, 3, 8, 13}  # pool follows these convs


def forward(params: Params, x: jax.Array) -> jax.Array:
    """(B, 96, 64, 1) log-mel examples → (B, 128) embeddings."""
    feats = params['features']
    for idx, _ in CONV_LAYERS:
        p = feats[str(idx)]
        x = relu(conv(x, p['weight'], padding=1, bias=p['bias']))
        if idx in POOL_AFTER:
            x = max_pool(x, (2, 2), stride=(2, 2))
    B = x.shape[0]
    x = x.reshape(B, -1)            # NHWC flatten == torch's transposed flatten
    emb = params['embeddings']
    for i in ('0', '2', '4'):
        x = relu(linear(x, emb[i]))
    return x


def postprocess(pca_eigen_vectors: jax.Array, pca_means: jax.Array,
                embeddings: jax.Array,
                quant_min: float = -2.0, quant_max: float = 2.0) -> jax.Array:
    """AudioSet PCA-whiten + 8-bit quantization (vggish_slim.py:63-96)."""
    x = (embeddings - pca_means.reshape(1, -1)) @ pca_eigen_vectors.T
    x = jnp.clip(x, quant_min, quant_max)
    return jnp.round((x - quant_min) * (255.0 / (quant_max - quant_min)))


def init_state_dict(seed: int = 0) -> Dict[str, np.ndarray]:
    """Random torch-layout state_dict with torchvggish naming/shapes."""
    rng = np.random.RandomState(seed)
    sd: Dict[str, np.ndarray] = {}
    in_ch = 1
    for idx, out_ch in CONV_LAYERS:
        sd[f'features.{idx}.weight'] = (
            rng.randn(out_ch, in_ch, 3, 3).astype(np.float32) * 0.05)
        sd[f'features.{idx}.bias'] = rng.randn(out_ch).astype(np.float32) * 0.05
        in_ch = out_ch
    dims = [(512 * 4 * 6, 4096), (4096, 4096), (4096, 128)]
    for i, (fan_in, fan_out) in zip(('0', '2', '4'), dims):
        sd[f'embeddings.{i}.weight'] = (
            rng.randn(fan_out, fan_in).astype(np.float32) * 0.01)
        sd[f'embeddings.{i}.bias'] = rng.randn(fan_out).astype(np.float32) * 0.01
    return sd
