"""I3D (Inception-v1 inflated 3-D ConvNet, two-stream rgb/flow).

Functional re-implementation of the architecture behind the reference i3d
extractor (reference models/i3d/i3d_src/i3d_net.py, 431 LoC — a TF-port):

  * TF-SAME padding approximated as pad = max(kernel - stride, 0), split
    low = pad//2 / high = pad - low (:8-25). In JAX this is just explicit
    per-edge lax padding — no ConstantPad3d workaround needed;
  * max pools zero-pad (not -inf!) with the same rule, then pool with
    ceil_mode (:108-120) — reproduced here literally: explicit 0-pad, then
    ceil-mode high-side -inf padding;
  * 9 inception Mixed blocks, avg_pool (2,7,7) stride 1, and a
    ``features=True`` path that squeezes + means over time to 1024-d
    (:238-264); classifier head is a 1×1×1 conv with bias (:265-274).

Params mirror the torch state_dict (conv3d_1a_7x7.conv3d.weight, …).
Layout NDHWC; rgb input (B,T,224,224,3) in [-1,1], flow (B,T,224,224,2).
"""
from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from video_features_tpu.ops.nn import avg_pool, batch_norm, conv, relu

Params = Dict[str, Any]

FEAT_DIM = 1024

# Mixed blocks: name -> (in, [b0, b1_mid, b1_out, b2_mid, b2_out, b3])
MIXED_CFGS = {
    'mixed_3b': (192, [64, 96, 128, 16, 32, 32]),
    'mixed_3c': (256, [128, 128, 192, 32, 96, 64]),
    'mixed_4b': (480, [192, 96, 208, 16, 48, 64]),
    'mixed_4c': (512, [160, 112, 224, 24, 64, 64]),
    'mixed_4d': (512, [128, 128, 256, 24, 64, 64]),
    'mixed_4e': (512, [112, 144, 288, 32, 64, 64]),
    'mixed_4f': (528, [256, 160, 320, 32, 128, 128]),
    'mixed_5b': (832, [256, 160, 320, 32, 128, 128]),
    'mixed_5c': (832, [384, 192, 384, 48, 128, 128]),
}


def tf_same_pads(kernel: Tuple[int, ...], stride: Tuple[int, ...]):
    """pad = max(k - s, 0) split (lo = pad//2, hi = rest) per dim."""
    pads = []
    for k, s in zip(kernel, stride):
        p = max(k - s, 0)
        pads.append((p // 2, p - p // 2))
    return pads


def unit3d(p: Params, x: jax.Array, kernel: Tuple[int, int, int],
           stride: Tuple[int, int, int] = (1, 1, 1), use_bn: bool = True,
           activation: bool = True) -> jax.Array:
    """Unit3Dpy: SAME conv (+ bias) → BN → ReLU (reference i3d_net.py:37-105)."""
    x = conv(x, p['conv3d']['weight'], stride=stride,
             padding=tf_same_pads(kernel, stride),
             bias=p['conv3d'].get('bias'))
    if use_bn:
        x = batch_norm(x, p['batch3d'])
    if activation:
        x = relu(x)
    return x


def max_pool_tf(x: jax.Array, kernel: Tuple[int, int, int],
                stride: Tuple[int, int, int]) -> jax.Array:
    """MaxPool3dTFPadding: explicit ZERO pad (k-s rule) then ceil-mode pool.

    The zero pad (not -inf) is a quirk of the reference (:108-120); inputs are
    post-ReLU so results coincide, but we reproduce it literally.
    """
    from video_features_tpu.ops.nn import ceil_mode_padding, max_pool

    pads = tf_same_pads(kernel, stride)
    x = jnp.pad(x, [(0, 0)] + [(lo, hi) for lo, hi in pads] + [(0, 0)])
    # torch ceil_mode: windows clipped at the edge == -inf high-side padding
    extra = [ceil_mode_padding(x.shape[i + 1], k, s)
             for i, (k, s) in enumerate(zip(kernel, stride))]
    return max_pool(x, kernel, stride=stride, padding=extra)


def mixed(p: Params, x: jax.Array) -> jax.Array:
    b0 = unit3d(p['branch_0'], x, (1, 1, 1))
    b1 = unit3d(p['branch_1']['1'],
                unit3d(p['branch_1']['0'], x, (1, 1, 1)), (3, 3, 3))
    b2 = unit3d(p['branch_2']['1'],
                unit3d(p['branch_2']['0'], x, (1, 1, 1)), (3, 3, 3))
    b3 = unit3d(p['branch_3']['1'],
                max_pool_tf(x, (3, 3, 3), (1, 1, 1)), (1, 1, 1))
    return jnp.concatenate([b0, b1, b2, b3], axis=-1)


def forward(params: Params, x: jax.Array, features: bool = True):
    """(B, T, 224, 224, C) → (B, 1024) features, or (softmax, logits)."""
    x = unit3d(params['conv3d_1a_7x7'], x, (7, 7, 7), (2, 2, 2))
    x = max_pool_tf(x, (1, 3, 3), (1, 2, 2))
    x = unit3d(params['conv3d_2b_1x1'], x, (1, 1, 1))
    x = unit3d(params['conv3d_2c_3x3'], x, (3, 3, 3))
    x = max_pool_tf(x, (1, 3, 3), (1, 2, 2))
    x = mixed(params['mixed_3b'], x)
    x = mixed(params['mixed_3c'], x)
    x = max_pool_tf(x, (3, 3, 3), (2, 2, 2))
    for name in ('mixed_4b', 'mixed_4c', 'mixed_4d', 'mixed_4e', 'mixed_4f'):
        x = mixed(params[name], x)
    x = max_pool_tf(x, (2, 2, 2), (2, 2, 2))
    x = mixed(params['mixed_5b'], x)
    x = mixed(params['mixed_5c'], x)
    x = avg_pool(x, (2, x.shape[2], x.shape[3]), stride=1)   # (B, T', 1, 1, 1024)
    if features:
        return x.reshape(x.shape[0], x.shape[1], -1).mean(axis=1)
    logits = conv(x, params['conv3d_0c_1x1']['conv3d']['weight'],
                  bias=params['conv3d_0c_1x1']['conv3d']['bias'])
    logits = logits.reshape(logits.shape[0], logits.shape[1], -1).mean(axis=1)
    return jax.nn.softmax(logits, axis=-1), logits


def init_state_dict(seed: int = 0, modality: str = 'rgb',
                    num_classes: int = 400) -> Dict[str, np.ndarray]:
    """Random torch-layout state_dict with the reference I3D naming/shapes."""
    rng = np.random.RandomState(seed)
    sd: Dict[str, np.ndarray] = {}
    in_channels = 3 if modality == 'rgb' else 2

    def unit(name, i, o, k, bias=False, bn=True):
        kt, kh, kw = (k, k, k) if isinstance(k, int) else k
        sd[f'{name}.conv3d.weight'] = rng.randn(o, i, kt, kh, kw).astype(np.float32) * 0.05
        if bias:
            sd[f'{name}.conv3d.bias'] = rng.randn(o).astype(np.float32) * 0.05
        if bn:
            sd[f'{name}.batch3d.weight'] = rng.rand(o).astype(np.float32) + 0.5
            sd[f'{name}.batch3d.bias'] = rng.randn(o).astype(np.float32) * 0.1
            sd[f'{name}.batch3d.running_mean'] = rng.randn(o).astype(np.float32) * 0.1
            sd[f'{name}.batch3d.running_var'] = rng.rand(o).astype(np.float32) + 0.5

    unit('conv3d_1a_7x7', in_channels, 64, 7)
    unit('conv3d_2b_1x1', 64, 64, 1)
    unit('conv3d_2c_3x3', 64, 192, 3)
    for name, (cin, (b0, b1m, b1o, b2m, b2o, b3)) in MIXED_CFGS.items():
        unit(f'{name}.branch_0', cin, b0, 1)
        unit(f'{name}.branch_1.0', cin, b1m, 1)
        unit(f'{name}.branch_1.1', b1m, b1o, 3)
        unit(f'{name}.branch_2.0', cin, b2m, 1)
        unit(f'{name}.branch_2.1', b2m, b2o, 3)
        unit(f'{name}.branch_3.1', cin, b3, 1)
    unit('conv3d_0c_1x1', 1024, num_classes, 1, bias=True, bn=False)
    return sd
