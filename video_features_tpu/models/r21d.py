"""R(2+1)D video ResNet (torchvision `r2plus1d_18` / ig65m `r2plus1d_34`).

A TPU-native functional re-implementation of the architecture behind the
reference's r21d extractor (reference models/r21d/extract_r21d.py:109-118
loads torchvision / moabitcoin-ig65m weights; the network is torchvision's
VideoResNet with R2Plus1D stem and (2+1)D factorized blocks).

Layout: NDHWC (batch, time, height, width, channel); params pytree mirrors the
torchvision state_dict names so checkpoints transplant mechanically
(see transplant/torch2jax.py). Factorized (2+1)D conv = spatial (1,3,3) conv
→ BN → ReLU → temporal (3,1,1) conv, with the midplane count chosen to match
the parameter budget of a full 3-D conv: mid = (i*o*27) // (i*9 + 3*o).
"""
from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
import numpy as np

from video_features_tpu.ops.nn import adaptive_avg_pool, batch_norm, conv, linear, relu

Params = Dict[str, Any]

ARCHS = {
    'r2plus1d_18': {'blocks': [2, 2, 2, 2], 'num_classes': 400},
    'r2plus1d_34': {'blocks': [3, 4, 6, 3], 'num_classes': 400},
}

# ImageNet-video normalization used by the reference transform chain
# (reference models/r21d/extract_r21d.py:105).
MEAN = (0.43216, 0.394666, 0.37645)
STD = (0.22803, 0.22145, 0.216989)


def midplanes(in_planes: int, out_planes: int) -> int:
    return (in_planes * out_planes * 3 * 3 * 3) // (
        in_planes * 3 * 3 + 3 * out_planes)


def _conv2plus1d(p: Params, x: jax.Array, stride: int) -> jax.Array:
    """Sequential(spatial conv, BN, ReLU, temporal conv) — torch indices 0,1,3."""
    x = conv(x, p['0']['weight'], stride=(1, stride, stride),
             padding=[(0, 0), (1, 1), (1, 1)])
    x = relu(batch_norm(x, p['1']))
    x = conv(x, p['3']['weight'], stride=(stride, 1, 1),
             padding=[(1, 1), (0, 0), (0, 0)])
    return x


def _basic_block(p: Params, x: jax.Array, stride: int) -> jax.Array:
    identity = x
    out = relu(batch_norm(_conv2plus1d(p['conv1']['0'], x, stride), p['conv1']['1']))
    out = batch_norm(_conv2plus1d(p['conv2']['0'], out, 1), p['conv2']['1'])
    if 'downsample' in p:
        identity = conv(x, p['downsample']['0']['weight'],
                        stride=(stride, stride, stride), padding=0)
        identity = batch_norm(identity, p['downsample']['1'])
    return relu(out + identity)


def _stem(p: Params, x: jax.Array) -> jax.Array:
    x = conv(x, p['0']['weight'], stride=(1, 2, 2),
             padding=[(0, 0), (3, 3), (3, 3)])
    x = relu(batch_norm(x, p['1']))
    x = conv(x, p['3']['weight'], stride=1, padding=[(1, 1), (0, 0), (0, 0)])
    return relu(batch_norm(x, p['4']))


def forward(params: Params, x: jax.Array, arch: str = 'r2plus1d_18',
            features: bool = True) -> jax.Array:
    """(B, T, H, W, 3) normalized float video → (B, 512) features or logits."""
    blocks = ARCHS[arch]['blocks']
    x = _stem(params['stem'], x)
    for layer_idx, num_blocks in enumerate(blocks, start=1):
        layer = params[f'layer{layer_idx}']
        for block_idx in range(num_blocks):
            stride = 2 if (layer_idx > 1 and block_idx == 0) else 1
            x = _basic_block(layer[str(block_idx)], x, stride)
    x = adaptive_avg_pool(x)          # (B, 512)
    if features:
        return x
    return linear(x, params['fc'])


def init_state_dict(seed: int = 0, arch: str = 'r2plus1d_18') -> Dict[str, np.ndarray]:
    """Random torch-layout state_dict with the exact torchvision naming/shapes.

    Used by tests (torchvision is not installed here) and as the documented
    contract for which checkpoint keys the transplant consumes.
    """
    rng = np.random.RandomState(seed)
    sd: Dict[str, np.ndarray] = {}

    def conv_w(name: str, o: int, i: int, k: Tuple[int, int, int]):
        sd[name] = rng.randn(o, i, *k).astype(np.float32) * 0.05

    def bn(name: str, c: int):
        sd[f'{name}.weight'] = rng.rand(c).astype(np.float32) + 0.5
        sd[f'{name}.bias'] = rng.randn(c).astype(np.float32) * 0.1
        sd[f'{name}.running_mean'] = rng.randn(c).astype(np.float32) * 0.1
        sd[f'{name}.running_var'] = rng.rand(c).astype(np.float32) + 0.5

    conv_w('stem.0.weight', 45, 3, (1, 7, 7));  bn('stem.1', 45)
    conv_w('stem.3.weight', 64, 45, (3, 1, 1)); bn('stem.4', 64)

    blocks = ARCHS[arch]['blocks']
    planes = [64, 128, 256, 512]
    in_p = 64
    for li, (nb, out_p) in enumerate(zip(blocks, planes), start=1):
        for bi in range(nb):
            base = f'layer{li}.{bi}'
            stride = 2 if (li > 1 and bi == 0) else 1
            mid1 = midplanes(in_p, out_p)
            conv_w(f'{base}.conv1.0.0.weight', mid1, in_p, (1, 3, 3))
            bn(f'{base}.conv1.0.1', mid1)
            conv_w(f'{base}.conv1.0.3.weight', out_p, mid1, (3, 1, 1))
            bn(f'{base}.conv1.1', out_p)
            mid2 = midplanes(out_p, out_p)
            conv_w(f'{base}.conv2.0.0.weight', mid2, out_p, (1, 3, 3))
            bn(f'{base}.conv2.0.1', mid2)
            conv_w(f'{base}.conv2.0.3.weight', out_p, mid2, (3, 1, 1))
            bn(f'{base}.conv2.1', out_p)
            if stride != 1 or in_p != out_p:
                conv_w(f'{base}.downsample.0.weight', out_p, in_p, (1, 1, 1))
                bn(f'{base}.downsample.1', out_p)
            in_p = out_p

    nc = ARCHS[arch]['num_classes']
    sd['fc.weight'] = (rng.randn(nc, 512).astype(np.float32) * 0.05)
    sd['fc.bias'] = rng.randn(nc).astype(np.float32) * 0.05
    return sd
