"""MobileNetV3 image backbones (timm `mobilenetv3_*_100` state_dict layout).

The reference's timm extractor accepts any pip-timm model (reference
models/timm/extract_timm.py:48, timm==0.9.12 pinned); this module natively
implements MobileNetV3 — the mobile branch of that model space the
EfficientNet family doesn't cover: per-block activation switching
(ReLU early, hard-swish late), hard-sigmoid-gated squeeze-excite on only
SOME stages, and a head 1×1 conv applied AFTER global pooling (so the
feature dim is the head width, reference extract_timm.py:59-60 keeps it
under ``reset_classifier(0)``) — against timm 0.9.12's ``MobileNetV3``
module tree (``conv_stem``/``bn1``, ``blocks.S.B.*`` with the
efficientnet block key names, ``conv_head`` WITH bias, ``classifier``).

Per-block (kernel, stride, mid, out, act, se) tables are the literal
MobileNetV3 paper geometries (Howard et al. 2019, tables 1-2) as timm
builds them, including the make-divisible-by-8 SE widths.

TPU notes: depthwise convs lower to XLA ``feature_group_count=C``;
hard-swish/hard-sigmoid are fused elementwise ops; the post-pool head
conv is a (B,1,1,C) matmul. All shapes static.
"""
from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from video_features_tpu.ops.nn import batch_norm, conv, linear

Params = Dict[str, Any]

# timm mobilenetv3 _cfg: bilinear, crop_pct 0.875, ImageNet stats
MEAN = (0.485, 0.456, 0.406)
STD = (0.229, 0.224, 0.225)

# Per-block rows: (kind, kernel, stride, mid_chs, out_chs, act, se_chs)
# kind: 'ds' (depthwise-separable, no expand conv), 'ir' (inverted
# residual), 'cn' (plain conv-bn-act). act: 're' ReLU / 'hs' hard-swish.
# se_chs = 0 → no squeeze-excite. SE widths are timm's
# round_channels(mid * 0.25) values, written out literally.
Block = Tuple[str, int, int, int, int, str, int]

ARCHS: Dict[str, Dict[str, Any]] = {
    'mobilenetv3_large_100': dict(
        stem=16, head=1280,
        blocks=[
            [('ds', 3, 1, 16, 16, 're', 0)],
            [('ir', 3, 2, 64, 24, 're', 0),
             ('ir', 3, 1, 72, 24, 're', 0)],
            [('ir', 5, 2, 72, 40, 're', 24),
             ('ir', 5, 1, 120, 40, 're', 32),
             ('ir', 5, 1, 120, 40, 're', 32)],
            [('ir', 3, 2, 240, 80, 'hs', 0),
             ('ir', 3, 1, 200, 80, 'hs', 0),
             ('ir', 3, 1, 184, 80, 'hs', 0),
             ('ir', 3, 1, 184, 80, 'hs', 0)],
            [('ir', 3, 1, 480, 112, 'hs', 120),
             ('ir', 3, 1, 672, 112, 'hs', 168)],
            [('ir', 5, 2, 672, 160, 'hs', 168),
             ('ir', 5, 1, 960, 160, 'hs', 240),
             ('ir', 5, 1, 960, 160, 'hs', 240)],
            [('cn', 1, 1, 0, 960, 'hs', 0)],
        ]),
    'mobilenetv3_small_100': dict(
        stem=16, head=1024,
        blocks=[
            [('ds', 3, 2, 16, 16, 're', 8)],
            [('ir', 3, 2, 72, 24, 're', 0),
             ('ir', 3, 1, 88, 24, 're', 0)],
            [('ir', 5, 2, 96, 40, 'hs', 24),
             ('ir', 5, 1, 240, 40, 'hs', 64),
             ('ir', 5, 1, 240, 40, 'hs', 64)],
            [('ir', 5, 1, 120, 48, 'hs', 32),
             ('ir', 5, 1, 144, 48, 'hs', 40)],
            [('ir', 5, 2, 288, 96, 'hs', 72),
             ('ir', 5, 1, 576, 96, 'hs', 144),
             ('ir', 5, 1, 576, 96, 'hs', 144)],
            [('cn', 1, 1, 0, 576, 'hs', 0)],
        ]),
}


def feat_dim(arch: str) -> int:
    return ARCHS[arch]['head']


def _act(x: jax.Array, kind: str) -> jax.Array:
    return jax.nn.relu(x) if kind == 're' else jax.nn.hard_swish(x)


def _se(p: Params, x: jax.Array) -> jax.Array:
    """timm mobilenetv3 SqueezeExcite: mean → 1×1 reduce → ReLU → 1×1
    expand → HARD-sigmoid gate (the v3 paper's h-sigmoid)."""
    s = x.mean(axis=(1, 2), keepdims=True)
    s = jax.nn.relu(conv(s, p['conv_reduce']['weight'],
                         bias=p['conv_reduce']['bias']))
    s = conv(s, p['conv_expand']['weight'], bias=p['conv_expand']['bias'])
    return x * jax.nn.hard_sigmoid(s)


def _block(p: Params, x: jax.Array, row: Block) -> jax.Array:
    kind, k, stride, mid, out, act, se = row
    if kind == 'cn':
        return _act(batch_norm(conv(x, p['conv']['weight']), p['bn1']), act)
    cin = x.shape[-1]
    if kind == 'ds':
        h = conv(x, p['conv_dw']['weight'], stride=stride, padding=k // 2,
                 groups=cin)
        h = _act(batch_norm(h, p['bn1']), act)
        if se:
            h = _se(p['se'], h)
        h = batch_norm(conv(h, p['conv_pw']['weight']), p['bn2'])
    else:  # 'ir'
        h = _act(batch_norm(conv(x, p['conv_pw']['weight']), p['bn1']), act)
        h = conv(h, p['conv_dw']['weight'], stride=stride, padding=k // 2,
                 groups=mid)
        h = _act(batch_norm(h, p['bn2']), act)
        if se:
            h = _se(p['se'], h)
        h = batch_norm(conv(h, p['conv_pwl']['weight']), p['bn3'])
    if stride == 1 and cin == out:
        h = h + x
    return h


def forward(params: Params, x: jax.Array,
            arch: str = 'mobilenetv3_large_100',
            features: bool = True) -> jax.Array:
    """(B, H, W, 3) normalized frames → (B, head) features (or (B, 1000)
    logits with ``features=False`` and a loaded classifier). Matches
    timm's ``num_classes=0`` semantics: global pool FIRST, then the
    biased head conv + hard-swish."""
    cfg = ARCHS[arch]
    x = conv(x, params['conv_stem']['weight'], stride=2, padding=1)
    x = _act(batch_norm(x, params['bn1']), 'hs')
    for si, stage in enumerate(cfg['blocks']):
        sp = params['blocks'][str(si)]
        for bi, row in enumerate(stage):
            x = _block(sp[str(bi)], x, row)
    x = x.mean(axis=(1, 2), keepdims=True)
    x = conv(x, params['conv_head']['weight'],
             bias=params['conv_head']['bias'])
    x = jax.nn.hard_swish(x)
    x = jnp.squeeze(x, axis=(1, 2))
    if features:
        return x
    return linear(x, params['classifier'])


def init_state_dict(arch: str = 'mobilenetv3_large_100', seed: int = 0,
                    num_classes: int = 0) -> Dict[str, np.ndarray]:
    """Random torch-layout state_dict with timm 0.9.12 naming/shapes."""
    from video_features_tpu.models._seed import SeedWriter
    rng = np.random.RandomState(seed)
    cfg = ARCHS[arch]
    sd: Dict[str, np.ndarray] = {}
    w_ = SeedWriter(sd, rng)
    cw, bn = w_.conv, w_.bn

    cw('conv_stem', cfg['stem'], 3, 3)
    bn('bn1', cfg['stem'])
    cin = cfg['stem']
    for si, stage in enumerate(cfg['blocks']):
        for bi, (kind, k, stride, mid, out, act, se) in enumerate(stage):
            base = f'blocks.{si}.{bi}'
            if kind == 'cn':
                cw(f'{base}.conv', out, cin, k)
                bn(f'{base}.bn1', out)
            elif kind == 'ds':
                w_.dwconv(f'{base}.conv_dw', cin, k)
                bn(f'{base}.bn1', cin)
                if se:
                    cw(f'{base}.se.conv_reduce', se, cin, 1, bias=True)
                    cw(f'{base}.se.conv_expand', cin, se, 1, bias=True)
                cw(f'{base}.conv_pw', out, cin, 1)
                bn(f'{base}.bn2', out)
            else:
                cw(f'{base}.conv_pw', mid, cin, 1)
                bn(f'{base}.bn1', mid)
                w_.dwconv(f'{base}.conv_dw', mid, k)
                bn(f'{base}.bn2', mid)
                if se:
                    cw(f'{base}.se.conv_reduce', se, mid, 1, bias=True)
                    cw(f'{base}.se.conv_expand', mid, se, 1, bias=True)
                cw(f'{base}.conv_pwl', out, mid, 1)
                bn(f'{base}.bn3', out)
            cin = out
    cw('conv_head', cfg['head'], cin, 1, bias=True)
    if num_classes:
        w_.linear('classifier', num_classes, cfg['head'])
    return sd
