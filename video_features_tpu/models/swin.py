"""Swin Transformer image backbones (timm `swin_*` state_dict layout).

The reference's timm extractor accepts any pip-timm model (reference
models/timm/extract_timm.py:48, pinned timm==0.9.12 in conda_env.yml); this
module natively implements the Swin family — hierarchical windowed
attention, the structurally-different half of that model space the plain
ViT/CNN families don't cover — against timm 0.9.12's ``SwinTransformer``
module tree (``patch_embed.proj``, ``layers.N.downsample.{norm,reduction}``
at stage START, ``layers.N.blocks.M.{norm1,attn,norm2,mlp}``, ``norm``,
``head.fc``) so real timm checkpoints transplant mechanically.

TPU-first structure: windows are pure reshape/transpose partitions (no
gathers), the cyclic shift is ``jnp.roll`` (an XLA collective-permute-
friendly slice concat), the shifted-window attention mask and the relative-
position index are trace-time numpy constants folded into the graph, and
every window attends as one batched (B·nW, 49, 49) dense attention — MXU
shapes, static bounds. The relative-position bias is the only per-forward
gather: a (169, heads) table → (heads, 49, 49), microscopic.

Feature semantics match ``num_classes=0`` timm models: global average pool
over the final-norm NHWC map (reference models/timm/extract_timm.py:59-60).
"""
from __future__ import annotations

from functools import lru_cache
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from video_features_tpu.ops.nn import conv

Params = Dict[str, Any]

# timm swin default_cfg: 224px, bicubic, crop_pct 0.9, ImageNet stats
MEAN = (0.485, 0.456, 0.406)
STD = (0.229, 0.224, 0.225)

ARCHS = {
    'swin_tiny_patch4_window7_224': dict(
        embed_dim=96, depths=(2, 2, 6, 2), heads=(3, 6, 12, 24),
        patch=4, window=7),
    'swin_small_patch4_window7_224': dict(
        embed_dim=96, depths=(2, 2, 18, 2), heads=(3, 6, 12, 24),
        patch=4, window=7),
    'swin_base_patch4_window7_224': dict(
        embed_dim=128, depths=(2, 2, 18, 2), heads=(4, 8, 16, 32),
        patch=4, window=7),
}

LN_EPS = 1e-5  # timm swin uses the nn.LayerNorm default, not ViT's 1e-6


def _layer_norm(x: jax.Array, p: Params) -> jax.Array:
    if x.dtype == jnp.bfloat16:
        # fp32 accumulation island (bf16 fast lane, ops/nn.py contract)
        return _layer_norm(x.astype(jnp.float32), p).astype(x.dtype)
    mean = x.mean(axis=-1, keepdims=True)
    var = ((x - mean) ** 2).mean(axis=-1, keepdims=True)
    return (x - mean) / jnp.sqrt(var + LN_EPS) * p['weight'] + p['bias']


def _linear(x: jax.Array, p: Params) -> jax.Array:
    y = x @ p['weight']
    return y + p['bias'] if 'bias' in p else y


def _calc_window_shift(feat: Tuple[int, int], window: int,
                       shift: int) -> Tuple[Tuple[int, int], Tuple[int, int]]:
    """timm SwinTransformerBlock._calc_window_shift: a feature map no
    larger than the window collapses to one unshifted full-map window."""
    ws = tuple(f if f <= window else window for f in feat)
    ss = tuple(0 if f <= w else shift for f, w in zip(feat, ws))
    return ws, ss


@lru_cache(maxsize=None)
def _rel_position_index(wh: int, ww: int) -> np.ndarray:
    """(wh·ww, wh·ww) gather index into the (2wh-1)(2ww-1) bias table —
    the standard Swin relative-coordinate flattening (timm
    get_relative_position_index)."""
    coords = np.stack(np.meshgrid(np.arange(wh), np.arange(ww),
                                  indexing='ij'))           # (2, wh, ww)
    flat = coords.reshape(2, -1)
    rel = flat[:, :, None] - flat[:, None, :]               # (2, N, N)
    rel = rel.transpose(1, 2, 0).copy()
    rel[:, :, 0] += wh - 1
    rel[:, :, 1] += ww - 1
    rel[:, :, 0] *= 2 * ww - 1
    return rel.sum(-1).astype(np.int32)                     # (N, N)


@lru_cache(maxsize=None)
def _shift_attn_mask(h: int, w: int, wh: int, ww: int,
                     sh: int, sw: int) -> Optional[np.ndarray]:
    """(nW, N, N) additive mask (0 / -100) keeping shifted-window attention
    inside original neighborhoods (timm SwinTransformerBlock.__init__),
    built on the window-padded grid."""
    if not (sh or sw):
        return None
    hp = -(-h // wh) * wh
    wp = -(-w // ww) * ww
    img = np.zeros((hp, wp), np.float32)
    cnt = 0
    for hs in (slice(0, -wh), slice(-wh, -sh if sh else None),
               slice(-sh, None) if sh else slice(0, 0)):
        for ws_ in (slice(0, -ww), slice(-ww, -sw if sw else None),
                    slice(-sw, None) if sw else slice(0, 0)):
            img[hs, ws_] = cnt
            cnt += 1
    win = (img.reshape(hp // wh, wh, wp // ww, ww)
           .transpose(0, 2, 1, 3).reshape(-1, wh * ww))     # (nW, N)
    diff = win[:, None, :] - win[:, :, None]
    return np.where(diff != 0, -100.0, 0.0).astype(np.float32)


def _window_partition(x: jax.Array, wh: int, ww: int) -> jax.Array:
    """(B, H, W, C) → (B·nW, wh·ww, C), row-major windows."""
    B, H, W, C = x.shape
    x = x.reshape(B, H // wh, wh, W // ww, ww, C)
    return x.transpose(0, 1, 3, 2, 4, 5).reshape(-1, wh * ww, C)


def _window_reverse(x: jax.Array, wh: int, ww: int, H: int, W: int,
                    B: int) -> jax.Array:
    C = x.shape[-1]
    x = x.reshape(B, H // wh, W // ww, wh, ww, C)
    return x.transpose(0, 1, 3, 2, 4, 5).reshape(B, H, W, C)


def _window_attention(p: Params, x: jax.Array, num_heads: int,
                      wh: int, ww: int,
                      mask: Optional[np.ndarray]) -> jax.Array:
    """timm WindowAttention on (B·nW, N, C) windows: qkv → scaled scores +
    relative-position bias (+ shift mask) → softmax → proj."""
    Bn, N, C = x.shape
    hd = C // num_heads
    qkv = _linear(x, p['qkv']).reshape(Bn, N, 3, num_heads, hd)
    q, k, v = jnp.moveaxis(qkv, 2, 0)                       # (Bn, N, H, hd)
    q = q * (hd ** -0.5)
    scores = jnp.einsum('bnhd,bmhd->bhnm', q, k)            # (Bn, H, N, N)
    idx = _rel_position_index(wh, ww).reshape(-1)
    bias = p['relative_position_bias_table'][idx]           # (N·N, H)
    scores = scores + bias.reshape(N, N, num_heads).transpose(2, 0, 1)
    if mask is not None:
        nw = mask.shape[0]
        scores = scores.reshape(Bn // nw, nw, num_heads, N, N)
        # mask follows scores' dtype: the np.float32 shift mask would
        # otherwise promote bf16 scores to f32 mid-graph, silently
        # defeating the bf16 fast lane from the first shifted block
        scores = scores + jnp.asarray(mask, scores.dtype)[None, :, None]
        scores = scores.reshape(Bn, num_heads, N, N)
    from video_features_tpu.ops.nn import softmax
    attn = softmax(scores, axis=-1)     # fp32 island under the bf16 lane
    out = jnp.einsum('bhnm,bmhd->bnhd', attn, v).reshape(Bn, N, C)
    return _linear(out, p['proj'])


def _block(p: Params, x: jax.Array, num_heads: int, window: int,
           shift: bool) -> jax.Array:
    """timm SwinTransformerBlock on an NHWC map: (shifted-)window attention
    + MLP, both pre-norm residual."""
    B, H, W, C = x.shape
    (wh, ww), (sh, sw) = _calc_window_shift(
        (H, W), window, window // 2 if shift else 0)

    def attn_part(t):
        if sh or sw:
            t = jnp.roll(t, shift=(-sh, -sw), axis=(1, 2))
        pad_h = (wh - H % wh) % wh
        pad_w = (ww - W % ww) % ww
        if pad_h or pad_w:
            t = jnp.pad(t, [(0, 0), (0, pad_h), (0, pad_w), (0, 0)])
        Hp, Wp = H + pad_h, W + pad_w
        wins = _window_partition(t, wh, ww)
        wins = _window_attention(p['attn'], wins, num_heads, wh, ww,
                                 _shift_attn_mask(H, W, wh, ww, sh, sw))
        t = _window_reverse(wins, wh, ww, Hp, Wp, B)[:, :H, :W]
        if sh or sw:
            t = jnp.roll(t, shift=(sh, sw), axis=(1, 2))
        return t

    x = x + attn_part(_layer_norm(x, p['norm1']))
    h = _layer_norm(x, p['norm2'])
    h = _linear(h, p['mlp']['fc1'])
    h = jax.nn.gelu(h, approximate=False)
    h = _linear(h, p['mlp']['fc2'])
    return x + h


def _patch_merging(p: Params, x: jax.Array) -> jax.Array:
    """timm PatchMerging: 2×2 neighborhood → channel concat (h-major per
    column pair) → norm → bias-free halving linear."""
    B, H, W, C = x.shape
    if H % 2 or W % 2:
        x = jnp.pad(x, [(0, 0), (0, H % 2), (0, W % 2), (0, 0)])
        H, W = H + H % 2, W + W % 2
    x = x.reshape(B, H // 2, 2, W // 2, 2, C)
    x = x.transpose(0, 1, 3, 4, 2, 5).reshape(B, H // 2, W // 2, 4 * C)
    return _linear(_layer_norm(x, p['norm']), p['reduction'])


def forward(params: Params, x: jax.Array,
            arch: str = 'swin_tiny_patch4_window7_224',
            features: bool = True) -> jax.Array:
    """(B, H, W, 3) normalized frames → (B, 8·embed_dim) pooled features
    (or (B, 1000) logits with ``features=False`` and a loaded head)."""
    cfg = ARCHS[arch]
    patch, window = cfg['patch'], cfg['window']
    pe = params['patch_embed']
    x = conv(x, pe['proj']['weight'], stride=patch, bias=pe['proj']['bias'])
    x = _layer_norm(x, pe['norm'])                          # (B, H/4, W/4, C)

    for i, depth in enumerate(cfg['depths']):
        stage = params['layers'][str(i)]
        if i > 0:                                           # stage-START merge
            x = _patch_merging(stage['downsample'], x)
        for j in range(depth):
            x = _block(stage['blocks'][str(j)], x, cfg['heads'][i],
                       window, shift=bool(j % 2))

    x = _layer_norm(x, params['norm'])
    x = x.mean(axis=(1, 2))                                 # NHWC global pool
    if features or 'head' not in params or 'fc' not in params['head']:
        return x
    return _linear(x, params['head']['fc'])


def feat_dim(arch: str) -> int:
    return ARCHS[arch]['embed_dim'] * 8


def init_state_dict(arch: str = 'swin_tiny_patch4_window7_224',
                    seed: int = 0, num_classes: int = 0) -> Dict[str, np.ndarray]:
    """Random torch-layout state_dict with timm 0.9.12 swin naming/shapes
    (relative_position_index / attn_mask are non-persistent buffers there
    and deliberately absent here — they are derived constants)."""
    cfg = ARCHS[arch]
    rng = np.random.RandomState(seed)
    sd: Dict[str, np.ndarray] = {}

    def lin(name, i, o, bias=True, scale=0.04):
        sd[f'{name}.weight'] = rng.randn(o, i).astype(np.float32) * scale
        if bias:
            sd[f'{name}.bias'] = rng.randn(o).astype(np.float32) * 0.02

    def ln(name, c):
        sd[f'{name}.weight'] = (rng.rand(c).astype(np.float32) * 0.2 + 0.9)
        sd[f'{name}.bias'] = rng.randn(c).astype(np.float32) * 0.02

    C0, win = cfg['embed_dim'], cfg['window']
    sd['patch_embed.proj.weight'] = (
        rng.randn(C0, 3, cfg['patch'], cfg['patch']).astype(np.float32) * 0.05)
    sd['patch_embed.proj.bias'] = rng.randn(C0).astype(np.float32) * 0.02
    ln('patch_embed.norm', C0)

    for i, depth in enumerate(cfg['depths']):
        dim = C0 * 2 ** i
        if i > 0:
            ln(f'layers.{i}.downsample.norm', 2 * dim)
            lin(f'layers.{i}.downsample.reduction', 2 * dim, dim, bias=False)
        heads = cfg['heads'][i]
        for j in range(depth):
            base = f'layers.{i}.blocks.{j}'
            ln(f'{base}.norm1', dim)
            lin(f'{base}.attn.qkv', dim, 3 * dim)
            sd[f'{base}.attn.relative_position_bias_table'] = (
                rng.randn((2 * win - 1) ** 2, heads).astype(np.float32) * 0.02)
            lin(f'{base}.attn.proj', dim, dim)
            ln(f'{base}.norm2', dim)
            lin(f'{base}.mlp.fc1', dim, 4 * dim)
            lin(f'{base}.mlp.fc2', 4 * dim, dim)
    ln('norm', C0 * 8)
    if num_classes:
        lin('head.fc', C0 * 8, num_classes)
    return sd
