"""RAFT optical flow (princeton-vl architecture, 'basic' variant).

Functional re-implementation of the architecture behind the reference raft
extractor (reference models/raft/raft_src/ — raft.py, extractor.py, update.py,
corr.py). TPU-native design choices:

  * the 20 recurrent GRU iterations are a single ``lax.scan`` body compiled
    once (reference loops in python, raft.py:153-171), with two exact-math
    FLOP cuts: the context encoder's loop-invariant contribution to every
    GRU conv is hoisted out of the scan (see :func:`fuse_gru_params`), and
    the convex-upsample mask head runs once after the scan instead of per
    iteration (only the final mask is ever consumed);
  * the all-pairs correlation volume is one batched matmul
    (B, H·W, H·W)/√dim (corr.py:53-60) and its 4-level pyramid lives as four
    arrays closed over by the scan;
  * the (2r+1)² window lookup (corr.py:29-50) is a vectorized gather-based
    bilinear sample with ``align_corners=True`` / zeros-padding semantics
    (utils/utils.py:58-72 wraps grid_sample the same way);
  * convex 8× upsampling (raft.py:103-115) is a softmax-weighted sum over
    3×3 flow patches, channels-last.

Params mirror the torch state_dict (fnet./cnet./update_block. prefixes).
Instance norms are affine-less (torch default) and carry no params.
Input: two (B, H, W, 3) uint8/float RGB frames, H and W divisible by 8
(use :func:`pad_to_multiple`); output (B, H, W, 2) flow in pixels (x, y).
"""
from __future__ import annotations

from functools import partial
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from video_features_tpu.ops.nn import avg_pool, batch_norm, conv, instance_norm, relu

Params = Dict[str, Any]

CORR_LEVELS = 4
CORR_RADIUS = 4
HIDDEN_DIM = 128
CONTEXT_DIM = 128
ITERS = 20


def resolve_iters(value) -> int:
    """Validate a config ``raft_iters`` (None → the fork's 20-iteration
    pin). Shared by the i3d and raft extractors so 0/negative values fail
    loudly instead of silently running full-depth or returning the
    unrefined init flow."""
    if value is None:
        return ITERS
    iters = int(value)
    if iters < 1:
        raise ValueError(f'raft_iters must be >= 1 (got {iters})')
    return iters


# -- encoders ----------------------------------------------------------------

def _residual_block(p: Params, x: jax.Array, norm_fn: str, stride: int) -> jax.Array:
    def norm(name, t):
        if norm_fn == 'batch':
            return batch_norm(t, p[name])
        if norm_fn == 'instance':
            return instance_norm(t, p.get(name, {}))
        return t

    y = relu(norm('norm1', conv(x, p['conv1']['weight'], stride=stride,
                                padding=1, bias=p['conv1']['bias'])))
    y = relu(norm('norm2', conv(y, p['conv2']['weight'], padding=1,
                                bias=p['conv2']['bias'])))
    if 'downsample' in p:
        x = conv(x, p['downsample']['0']['weight'], stride=stride,
                 bias=p['downsample']['0']['bias'])
        x = norm('norm3', x)
    return relu(x + y)


def basic_encoder(p: Params, x: jax.Array, norm_fn: str) -> jax.Array:
    """(B, H, W, 3) in [-1,1] → (B, H/8, W/8, out_dim)."""
    x = conv(x, p['conv1']['weight'], stride=2, padding=3, bias=p['conv1']['bias'])
    if norm_fn == 'batch':
        x = batch_norm(x, p['norm1'])
    elif norm_fn == 'instance':
        x = instance_norm(x, p.get('norm1', {}))
    x = relu(x)
    for layer in ('layer1', 'layer2', 'layer3'):
        stride = 1 if layer == 'layer1' else 2
        x = _residual_block(p[layer]['0'], x, norm_fn, stride)
        x = _residual_block(p[layer]['1'], x, norm_fn, 1)
    return conv(x, p['conv2']['weight'], bias=p['conv2']['bias'])


# -- correlation pyramid -----------------------------------------------------

def build_corr_pyramid(fmap1: jax.Array, fmap2: jax.Array) -> List[jax.Array]:
    """All-pairs correlation pyramid.

    fmap: (B, H, W, D). Level i: (B·H·W, H/2^i, W/2^i, 1).
    """
    B, H, W, D = fmap1.shape
    f1 = fmap1.reshape(B, H * W, D)
    f2 = fmap2.reshape(B, H * W, D)
    corr = jnp.einsum('bnd,bmd->bnm', f1, f2) / jnp.sqrt(jnp.asarray(D, f1.dtype))
    corr = corr.reshape(B * H * W, H, W, 1)
    pyramid = [corr]
    for _ in range(CORR_LEVELS - 1):
        corr = avg_pool(corr, 2, stride=2)
        pyramid.append(corr)
    return pyramid


def bilinear_sample(img: jax.Array, coords: jax.Array) -> jax.Array:
    """grid_sample(align_corners=True, padding_mode='zeros') in pixel coords.

    img: (N, h, w, C); coords: (N, P, 2) as (x, y) pixel positions.
    Returns (N, P, C).
    """
    N, h, w, C = img.shape
    x, y = coords[..., 0], coords[..., 1]
    x0 = jnp.floor(x)
    y0 = jnp.floor(y)
    wx = x - x0
    wy = y - y0

    flat = img.reshape(N, h * w, C)
    batch_idx = jnp.arange(N)[:, None]

    def corner(xi, yi, weight):
        valid = (xi >= 0) & (xi <= w - 1) & (yi >= 0) & (yi <= h - 1)
        xi_c = jnp.clip(xi, 0, w - 1).astype(jnp.int32)
        yi_c = jnp.clip(yi, 0, h - 1).astype(jnp.int32)
        vals = flat[batch_idx, yi_c * w + xi_c]              # (N, P, C)
        return vals * (weight * valid)[..., None].astype(img.dtype)

    return (corner(x0, y0, (1 - wx) * (1 - wy))
            + corner(x0 + 1, y0, wx * (1 - wy))
            + corner(x0, y0 + 1, (1 - wx) * wy)
            + corner(x0 + 1, y0 + 1, wx * wy))


def lookup_corr(pyramid: List[jax.Array], coords: jax.Array,
                radius: int = CORR_RADIUS) -> jax.Array:
    """Sample a (2r+1)² window at every level around ``coords``.

    coords: (B, H, W, 2) in level-0 pixel units → (B, H, W, levels·(2r+1)²).
    """
    B, H, W, _ = coords.shape
    r = radius
    d = jnp.arange(-r, r + 1, dtype=coords.dtype)
    # torch meshgrid(dy, dx) stacked as (dy, dx) then added to (x, y) coords
    # via broadcasting of (..., 2) — delta ordering is (y, x) in the
    # reference (corr.py:38-40), but it is added to centroids whose last dim
    # is (x, y); grid points form the same set either way because the window
    # is square and symmetric, yet the *ordering* of the 81 outputs matters
    # for weight parity: reference orders dy-major with (dy,dx) added as-is.
    dy, dx = jnp.meshgrid(d, d, indexing='ij')
    delta = jnp.stack([dy, dx], axis=-1).reshape(-1, 2)      # (81, 2) (dy,dx)

    out = []
    for i, corr in enumerate(pyramid):
        centroid = coords.reshape(B * H * W, 1, 2) / (2 ** i)  # (N,1,2) (x,y)
        # reference adds delta (dy,dx) directly onto (x,y) centroids
        pts = centroid + delta[None, :, :]
        sampled = bilinear_sample(corr, pts)                  # (N, 81, 1)
        out.append(sampled.reshape(B, H, W, -1))
    return jnp.concatenate(out, axis=-1)


def lookup_corr_dense(pyramid: List[jax.Array], coords: jax.Array,
                      radius: int = CORR_RADIUS) -> jax.Array:
    """Gather-free corr-window lookup: two batched matmul contractions.

    Identical output to :func:`lookup_corr` (reference corr.py:29-50
    semantics, dy-major ordering, zeros padding) but built for the MXU: the
    window offsets are integers, so every sample in a window shares one
    bilinear fraction per axis, and the whole (2r+1)² window is

        out[n, i, j] = Σ_h Σ_w corr[n, h, w] · WY[n, j, h] · WX[n, i, w]

    where WX/WY each have two nonzeros per row ((1-f) at the floor index, f
    at floor+1; out-of-range columns are simply never matched — exactly the
    reference's zeros padding_mode). Gathers are the one access pattern TPUs
    do poorly — XLA lowers them to serialized HBM touches (~740 ms/lookup at
    28×28×64 pairs, i.e. ~15 s per 20-iteration forward) — while these two
    einsums run on the MXU in microseconds.
    """
    B, H, W, _ = coords.shape
    r = radius
    p1 = 2 * r + 1
    d = jnp.arange(-r, r + 1, dtype=jnp.int32)

    flat = coords.reshape(-1, 2)
    N = flat.shape[0]

    out = []
    for i, corr in enumerate(pyramid):
        _, h, w, _ = corr.shape
        c = flat / (2.0 ** i)                                  # (N, 2) (x, y)
        x0 = jnp.floor(c[:, 0])
        y0 = jnp.floor(c[:, 1])
        fx = (c[:, 0] - x0).astype(corr.dtype)
        fy = (c[:, 1] - y0).astype(corr.dtype)
        # window base indices per output row/col: floor + integer offset
        xi = x0.astype(jnp.int32)[:, None] + d[None, :]        # (N, p1)
        yi = y0.astype(jnp.int32)[:, None] + d[None, :]

        def weights(base, frac, extent):
            ids = jnp.arange(extent, dtype=jnp.int32)[None, None, :]
            lo = (ids == base[:, :, None]).astype(corr.dtype)
            hi = (ids == (base + 1)[:, :, None]).astype(corr.dtype)
            return lo * (1 - frac)[:, None, None] + hi * frac[:, None, None]

        wx = weights(xi, fx, w)                                # (N, p1, w)
        wy = weights(yi, fy, h)                                # (N, p1, h)
        cc = jnp.squeeze(corr, -1)                             # (N, h, w)
        t = jnp.einsum('nhw,niw->nih', cc, wx)                 # x-axis blend
        o = jnp.einsum('nih,njh->nij', t, wy)                  # y-axis blend
        # output k = i·p1 + j is the sample at (x + d[i], y + d[j]) —
        # the reference's dy-major ordering (corr.py:38-44)
        out.append(o.reshape(B, H, W, p1 * p1))
    return jnp.concatenate(out, axis=-1)


# -- update block ------------------------------------------------------------

def _conv_b(p: Params, x: jax.Array, padding=0) -> jax.Array:
    return conv(x, p['weight'], padding=padding, bias=p['bias'])


def motion_encoder(p: Params, flow: jax.Array, corr: jax.Array) -> jax.Array:
    cor = relu(_conv_b(p['convc1'], corr))
    cor = relu(_conv_b(p['convc2'], cor, padding=1))
    flo = relu(_conv_b(p['convf1'], flow, padding=3))
    flo = relu(_conv_b(p['convf2'], flo, padding=1))
    out = relu(_conv_b(p['conv'], jnp.concatenate([cor, flo], -1), padding=1))
    return jnp.concatenate([out, flow], -1)


GRU_PADS = (('1', ((0, 0), (2, 2))), ('2', ((2, 2), (0, 0))))


def fuse_gru_params(p: Params, hidden: int = HIDDEN_DIM,
                    context: int = CONTEXT_DIM) -> Params:
    """Restructure the six GRU conv weights for the scan body, once.

    Two exact-math transforms (reference math: update.py:39-77):

      * the z and r gates read the same input, so each direction's z/r
        weights stack on the OUTPUT axis — one conv computes both gates
        (independent per-output-channel reductions), halving that input's
        HBM reads;
      * every GRU conv's INPUT channels split as (h | inp | motion), and
        the ``inp`` block — the context encoder's half, reference
        raft.py:139-143 — is LOOP-INVARIANT across the 20 refinement
        iterations. Conv is linear in input channels, so the inp
        contribution is a per-pixel constant computed once before the scan
        (:func:`gru_inp_terms`); the per-iteration convs then contract 256
        channels instead of 384 — a third of the GRU FLOPs deleted from
        the scan with identical math (the q conv's input is
        ``concat(r·h, x)``: the r gate never multiplies the inp block, so
        its term is invariant too).
    """
    out = {}
    sl_h = slice(0, hidden)
    sl_i = slice(hidden, hidden + context)
    sl_m = slice(hidden + context, None)
    for suffix, _ in GRU_PADS:
        zw, rw = p[f'convz{suffix}'], p[f'convr{suffix}']
        w = jnp.concatenate([zw['weight'], rw['weight']], axis=-1)
        b = jnp.concatenate([zw['bias'], rw['bias']])
        qw = p[f'convq{suffix}']['weight']
        out[f'zr{suffix}'] = {
            'hm': jnp.concatenate([w[:, :, sl_h], w[:, :, sl_m]], axis=2),
            'inp': w[:, :, sl_i], 'bias': b}
        out[f'q{suffix}'] = {
            'hm': jnp.concatenate([qw[:, :, sl_h], qw[:, :, sl_m]], axis=2),
            'inp': qw[:, :, sl_i], 'bias': p[f'convq{suffix}']['bias']}
    return out


def gru_inp_terms(fused: Params, inp: jax.Array) -> Params:
    """The loop-invariant context contribution to all four GRU convs
    (+ their biases), computed once before the refinement scan."""
    terms = {}
    for suffix, pad in GRU_PADS:
        for gate in ('zr', 'q'):
            pp = fused[f'{gate}{suffix}']
            terms[f'{gate}{suffix}'] = conv(inp, pp['inp'], padding=list(pad),
                                            bias=pp['bias'])
    return terms


def sep_conv_gru(fused: Params, terms: Params, h: jax.Array,
                 motion: jax.Array) -> jax.Array:
    """SepConvGRU (reference update.py:39-77): 1×5 then 5×1 passes over
    :func:`fuse_gru_params`-prepared weights + precomputed context terms."""
    for suffix, pad in GRU_PADS:
        hm = jnp.concatenate([h, motion], -1)
        zr = jax.nn.sigmoid(conv(hm, fused[f'zr{suffix}']['hm'],
                                 padding=list(pad)) + terms[f'zr{suffix}'])
        z, r = jnp.split(zr, 2, axis=-1)
        q = jnp.tanh(conv(jnp.concatenate([r * h, motion], -1),
                          fused[f'q{suffix}']['hm'], padding=list(pad))
                     + terms[f'q{suffix}'])
        h = (1 - z) * h + z * q
    return h


def upsample_flow(flow: jax.Array, mask: jax.Array) -> jax.Array:
    """Convex-combination 8× upsample (reference raft.py:103-115).

    flow: (B, H, W, 2); mask: (B, H, W, 576=9·8·8) → (B, 8H, 8W, 2).
    """
    B, H, W, _ = flow.shape
    mask = mask.reshape(B, H, W, 9, 8, 8)
    mask = jax.nn.softmax(mask, axis=3)

    fp = jnp.pad(8.0 * flow, [(0, 0), (1, 1), (1, 1), (0, 0)])
    # 3×3 patches, row-major to match F.unfold ordering
    patches = jnp.stack([fp[:, i:i + H, j:j + W, :]
                         for i in range(3) for j in range(3)], axis=3)  # (B,H,W,9,2)
    up = jnp.einsum('bhwkij,bhwkc->bhwijc', mask, patches)  # (B,H,W,8,8,2)
    return up.transpose(0, 1, 3, 2, 4, 5).reshape(B, 8 * H, 8 * W, 2)


# -- full model --------------------------------------------------------------

def coords_grid(B: int, H: int, W: int, dtype=jnp.float32) -> jax.Array:
    """(B, H, W, 2) grid of (x, y) pixel coordinates."""
    y, x = jnp.meshgrid(jnp.arange(H, dtype=dtype), jnp.arange(W, dtype=dtype),
                        indexing='ij')
    return jnp.broadcast_to(jnp.stack([x, y], -1), (B, H, W, 2))


# The lanes kernel keeps one (h, w, LANES) f32 corr block per grid step in
# VMEM; past this budget (level-0 block, MiB) auto-dispatch falls back to
# dense rather than risk a Mosaic VMEM OOM on large frames.
LANES_VMEM_BUDGET_MB = 8.0


def _lookup_impl() -> str:
    """Which corr-lookup implementation to compile into the forward pass.

    ``VFT_RAFT_LOOKUP`` ∈ {'auto' (default), 'dense', 'gather', 'pallas',
    'lanes'}:
      * auto   — 'lanes' on TPU while the kernel's level-0 VMEM block fits
        ``VFT_RAFT_LANES_VMEM_MB`` (default 8 MiB); 'dense' otherwise
        (including all non-TPU backends, where the Pallas kernels would run
        interpreted);
      * dense  — :func:`lookup_corr_dense`, gather-free batched matmuls
        (measured ~300× faster than gather on TPU; also fastest on CPU);
      * gather — :func:`lookup_corr`, the XLA gather lowering (reference
        semantics oracle, kept for tests);
      * pallas — the Pallas window-slice kernel (ops/pallas_corr.py;
        interpret mode automatically off-TPU);
      * lanes  — lane-packed Pallas kernel (mask-reduce window sums, 128
        pixels per lane tile): measured 14.3 → 26.9 clips/sec/chip on the
        fused I3D two-stream bench on v5e (the lookup dominates the GRU
        scan's per-iteration cost), identical compile time.
    Legacy ``VFT_RAFT_PALLAS=1`` still selects the pallas path.
    """
    import os
    if os.environ.get('VFT_RAFT_PALLAS') == '1':
        return 'pallas'
    impl = os.environ.get('VFT_RAFT_LOOKUP', 'auto')
    assert impl in ('auto', 'dense', 'gather', 'pallas', 'lanes'), impl
    return impl


def _resolve_auto_lookup(h8: int, w8: int, platform: str) -> str:
    """'lanes' when on TPU and the level-0 (h8, w8, LANES) block fits the
    VMEM budget; 'dense' otherwise. Shapes are static at trace time, so the
    choice compiles away."""
    import os

    from video_features_tpu.ops.pallas_corr import LANES
    budget = float(os.environ.get('VFT_RAFT_LANES_VMEM_MB',
                                  LANES_VMEM_BUDGET_MB))
    block_mb = h8 * w8 * LANES * 4 / 2 ** 20
    if platform == 'tpu' and block_mb <= budget:
        return 'lanes'
    return 'dense'


def _normalize_frames(img: jax.Array) -> jax.Array:
    """0..255 RGB → ±1 (done inside forward in the reference, raft.py:121-122)."""
    return 2.0 * (jnp.asarray(img, jnp.float32) / 255.0) - 1.0


def forward(params: Params, image1: jax.Array, image2: jax.Array,
            iters: int = ITERS, platform: Optional[str] = None,
            pins=None) -> jax.Array:
    """Two (B, H, W, 3) frames (values 0..255) → (B, H, W, 2) flow.

    H, W must be divisible by 8 (reference pads with InputPadder, raft.py:30-48
    — see :func:`pad_to_multiple` / :func:`unpad`). ``platform`` selects the
    corr-lookup implementation for the platform the graph will run on (see
    :func:`_refine`); ``pins`` per-sub-graph precision (ops/precision.py).
    """
    from video_features_tpu.ops.precision import pin_scope
    image1 = _normalize_frames(image1)
    image2 = _normalize_frames(image2)
    with pin_scope(pins, 'encoder'):
        fmap1 = basic_encoder(params['fnet'], image1, 'instance')
        fmap2 = basic_encoder(params['fnet'], image2, 'instance')
        cnet = basic_encoder(params['cnet'], image1, 'batch')
    return _refine(params, fmap1, fmap2, cnet, iters, platform, pins)


def forward_consecutive(params: Params, frames: jax.Array,
                        iters: int = ITERS,
                        platform: Optional[str] = None,
                        pins=None) -> jax.Array:
    """(N, H, W, 3) consecutive frames → (N-1, H, W, 2) pairwise flows.

    Same math as :func:`forward` on ``(frames[:-1], frames[1:])`` — the
    extractors' consecutive-pair batching (reference
    base_flow_extractor.py:76-84) makes every interior frame both the
    ``image2`` of one pair and the ``image1`` of the next, so its fnet
    encoding is computed ONCE here and shared, where the reference's
    stacked-pair form encodes it twice (raft.py:84-85).
    """
    return forward_stack_pairs(params, frames[None], iters,
                               platform=platform, pins=pins)[0]


def forward_stack_pairs(params: Params, stacks: jax.Array, iters: int = ITERS,
                        constrain=None,
                        platform: Optional[str] = None,
                        pins=None) -> jax.Array:
    """(B, S+1, H, W, 3) frame stacks → (B, S, H, W, 2) within-stack flows.

    The fused I3D path's form of :func:`forward_consecutive`: fnet runs on
    the B·(S+1) unique frames instead of the 2·B·S stacked pair halves.
    ``constrain`` (optional) applies a sharding constraint to every
    leading-flattened tensor entering the heavy sub-graphs (frames, fmap
    pairs, cnet) so the sub-graphs spread over a (data, time) mesh. The
    B·(S+1) frames tensor generally does not divide the mesh evenly (the
    +1 halo); GSPMD pads the last shards, a ≤1-frame-per-shard imbalance
    on fnet that still beats sharding fnet over the data axis alone.
    """
    from video_features_tpu.ops.precision import pin_scope
    B, S1, H, W, C = stacks.shape
    S = S1 - 1
    flat = _normalize_frames(stacks.reshape(B * S1, H, W, C))
    if constrain is not None:
        flat = constrain(flat)
    with pin_scope(pins, 'encoder'):
        fmaps = basic_encoder(params['fnet'], flat, 'instance')
    h8, w8, c = fmaps.shape[1:]
    fmaps = fmaps.reshape(B, S1, h8, w8, c)
    fmap1 = fmaps[:, :-1].reshape(B * S, h8, w8, c)
    fmap2 = fmaps[:, 1:].reshape(B * S, h8, w8, c)
    first = flat.reshape(B, S1, H, W, C)[:, :-1].reshape(B * S, H, W, C)
    if constrain is not None:
        fmap1, fmap2, first = constrain(fmap1), constrain(fmap2), constrain(first)
    with pin_scope(pins, 'encoder'):
        cnet = basic_encoder(params['cnet'], first, 'batch')
    flow = _refine(params, fmap1, fmap2, cnet, iters, platform, pins)
    return flow.reshape(B, S, flow.shape[1], flow.shape[2], 2)


def _refine(params: Params, fmap1: jax.Array, fmap2: jax.Array,
            cnet: jax.Array, iters: int,
            platform: Optional[str] = None, pins=None) -> jax.Array:
    """Correlation pyramid + 20-iteration GRU refinement + 8× upsample —
    the shared core behind every forward variant (reference raft.py:118-175
    from the post-encoder point on).

    ``platform`` is the platform the compiled graph will RUN on ('tpu' /
    'cpu' / ...); it picks the corr-lookup implementation and Pallas
    interpret mode. Defaults to ``jax.default_backend()``, which is only
    correct when the operands live on the default backend — extractors
    thread their resolved device's platform instead (a CPU-committed call
    in a TPU-default process must not get the Mosaic lanes kernel).
    ``pins`` optionally overrides matmul precision per sub-graph
    (ops/precision.py): 'corr', 'iter', 'upsample'."""
    from video_features_tpu.ops.precision import pin_scope
    platform = platform or jax.default_backend()
    net, inp = jnp.split(cnet, [HIDDEN_DIM], axis=-1)
    net = jnp.tanh(net)
    inp = relu(inp)

    B, H8, W8, _ = fmap1.shape
    # + zeros_like keeps shard_map's varying-axes type: constant carry
    # inits must match the varying outputs of the scan body when _refine
    # runs inside a shard_map shard (the add folds away otherwise)
    coords0 = coords_grid(B, H8, W8) + jnp.zeros_like(fmap1[..., :2])
    up = params['update_block']

    impl = _lookup_impl()
    if impl == 'auto':
        impl = _resolve_auto_lookup(H8, W8, platform)
    if impl == 'lanes':
        # lane-layout pyramid built straight from the fmaps: the
        # (N, h, w) detour + physical transpose was the fixed phase's
        # single worst HBM pattern (see prep_pyramid_lanes_fused)
        from video_features_tpu.ops import pallas_corr
        with pin_scope(pins, 'corr'):
            prepped = pallas_corr.prep_pyramid_lanes_fused(
                fmap1, fmap2, levels=CORR_LEVELS)
        lookup = partial(pallas_corr.lookup_corr_lanes, prepped,
                         radius=CORR_RADIUS, interpret=platform != 'tpu')
    else:
        with pin_scope(pins, 'corr'):
            pyramid = build_corr_pyramid(fmap1, fmap2)
        if impl == 'pallas':
            from video_features_tpu.ops import pallas_corr
            with pin_scope(pins, 'corr'):
                prepped = pallas_corr.prep_pyramid(pyramid,
                                                   radius=CORR_RADIUS)
            lookup = partial(pallas_corr.lookup_corr, prepped,
                             radius=CORR_RADIUS,
                             interpret=platform != 'tpu')
        elif impl == 'gather':
            lookup = partial(lookup_corr, pyramid)
        else:
            lookup = partial(lookup_corr_dense, pyramid)

    fh, mk = up['flow_head'], up['mask']
    gru = fuse_gru_params(up['gru'])
    with pin_scope(pins, 'iter'):
        gru_terms = gru_inp_terms(gru, inp)

    def make_step(early_prec=None):
        """Scan body; ``early_prec`` overrides the WHOLE body's matmul
        precision (the 'iter_early' pin — see below)."""
        def step(carry, _):
            from contextlib import nullcontext
            outer = (jax.default_matmul_precision(early_prec)
                     if early_prec else nullcontext())
            with outer:
                net, coords1 = carry
                with pin_scope(pins, 'corr'):
                    corr = lookup(coords1)
                flow = coords1 - coords0
                # finer pins nest inside 'iter': an unpinned sub-component
                # inherits the 'iter' (or ambient) precision
                with pin_scope(pins, 'iter'):
                    with pin_scope(pins, 'iter_motion'):
                        motion = motion_encoder(up['encoder'], flow, corr)
                    with pin_scope(pins, 'iter_gru'):
                        net_new = sep_conv_gru(gru, gru_terms, net, motion)
                    with pin_scope(pins, 'iter_head'):
                        t = relu(_conv_b(fh['conv1'], net_new, padding=1))
                        delta = _conv_b(fh['conv2'], t, padding=1)
                    coords1_new = coords1 + delta
            return (net_new, coords1_new), None
        return step

    # 'iter_early' pin ('<precision>:<n>') runs the FIRST n refinement
    # iterations at a faster precision: RAFT is iterative refinement, so
    # early-iteration error is substantially corrected by the remaining
    # full-precision iterations (measured by tools/precision_study.py).
    early_prec, early_n = None, 0
    for name, val in (pins or ()):
        if name == 'iter_early':
            early_prec, _, n = str(val).partition(':')
            early_n = min(int(n or 0), iters)

    carry = (net, coords0)
    if early_n:
        carry, _ = lax.scan(make_step(early_prec), carry, None,
                            length=early_n)
    (net, coords1), _ = lax.scan(make_step(), carry, None,
                                 length=iters - early_n)
    # Convex-upsample mask head, ONCE after the scan: the reference
    # computes `.25·mask(net)` every iteration (update.py:139-144) but the
    # extractor consumes only the final flow (raft.py:153-175 predictions
    # [-1]) — every non-final mask is dead code, so 19/20 of the mask
    # head's FLOPs (a 3×3 128→256 + 1×1 256→576 stack) leave the scan
    # with bit-identical output.
    with pin_scope(pins, 'iter'):
        t_mask = relu(_conv_b(mk['0'], net, padding=1))
        mask = 0.25 * _conv_b(mk['2'], t_mask)
    with pin_scope(pins, 'upsample'):
        return upsample_flow(coords1 - coords0, mask)


def pad_to_multiple(x: jax.Array, mode: str = 'sintel',
                    multiple: int = 8) -> Tuple[jax.Array, Tuple[int, int, int, int]]:
    """Replicate-pad (B, H, W, C) so H, W divide ``multiple``.

    Reference InputPadder (raft.py:30-48): sintel centers the pad; kitti pads
    bottom-only in height. Returns (padded, (top, bottom, left, right)).
    numpy input pads with numpy (a ``jnp.pad`` here would silently bounce a
    host batch through the default device and back — one extra H2D+D2H round
    trip per extraction step).
    """
    H, W = x.shape[1], x.shape[2]
    pad_h = (((H // multiple) + 1) * multiple - H) % multiple
    pad_w = (((W // multiple) + 1) * multiple - W) % multiple
    if mode == 'sintel':
        pads = (pad_h // 2, pad_h - pad_h // 2, pad_w // 2, pad_w - pad_w // 2)
    else:
        pads = (0, pad_h, pad_w // 2, pad_w - pad_w // 2)
    t, b, l, r = pads
    pad_fn = np.pad if isinstance(x, np.ndarray) else jnp.pad
    x = pad_fn(x, [(0, 0), (t, b), (l, r), (0, 0)], mode='edge')
    return x, pads


def unpad(x: jax.Array, pads: Tuple[int, int, int, int]) -> jax.Array:
    t, b, l, r = pads
    H, W = x.shape[1], x.shape[2]
    return x[:, t:H - b, l:W - r, :]


# -- random init for tests ---------------------------------------------------

def init_state_dict(seed: int = 0) -> Dict[str, np.ndarray]:
    """Random torch-layout state_dict with princeton-vl RAFT naming/shapes."""
    rng = np.random.RandomState(seed)
    sd: Dict[str, np.ndarray] = {}

    def conv_w(name, o, i, kh, kw, scale=0.05):
        sd[f'{name}.weight'] = rng.randn(o, i, kh, kw).astype(np.float32) * scale
        sd[f'{name}.bias'] = rng.randn(o).astype(np.float32) * 0.05

    def bn(name, c):
        sd[f'{name}.weight'] = rng.rand(c).astype(np.float32) + 0.5
        sd[f'{name}.bias'] = rng.randn(c).astype(np.float32) * 0.1
        sd[f'{name}.running_mean'] = rng.randn(c).astype(np.float32) * 0.1
        sd[f'{name}.running_var'] = rng.rand(c).astype(np.float32) + 0.5

    def encoder(prefix, out_dim, norm_fn):
        conv_w(f'{prefix}.conv1', 64, 3, 7, 7)
        if norm_fn == 'batch':
            bn(f'{prefix}.norm1', 64)
        dims = [(64, 64, 1), (64, 96, 2), (96, 128, 2)]
        for li, (i_p, o_p, stride) in enumerate(dims, start=1):
            for bi in range(2):
                base = f'{prefix}.layer{li}.{bi}'
                cin = i_p if bi == 0 else o_p
                s = stride if bi == 0 else 1
                conv_w(f'{base}.conv1', o_p, cin, 3, 3)
                conv_w(f'{base}.conv2', o_p, o_p, 3, 3)
                if norm_fn == 'batch':
                    bn(f'{base}.norm1', o_p)
                    bn(f'{base}.norm2', o_p)
                if s != 1 or cin != o_p:
                    conv_w(f'{base}.downsample.0', o_p, cin, 1, 1)
                    if norm_fn == 'batch':
                        bn(f'{base}.norm3', o_p)
        conv_w(f'{prefix}.conv2', out_dim, 128, 1, 1)

    encoder('fnet', 256, 'instance')
    encoder('cnet', HIDDEN_DIM + CONTEXT_DIM, 'batch')

    cor_planes = CORR_LEVELS * (2 * CORR_RADIUS + 1) ** 2
    conv_w('update_block.encoder.convc1', 256, cor_planes, 1, 1)
    conv_w('update_block.encoder.convc2', 192, 256, 3, 3)
    conv_w('update_block.encoder.convf1', 128, 2, 7, 7)
    conv_w('update_block.encoder.convf2', 64, 128, 3, 3)
    conv_w('update_block.encoder.conv', 126, 256, 3, 3)
    for g in ('z', 'r', 'q'):
        conv_w(f'update_block.gru.conv{g}1', 128, 256 + 128, 1, 5)
        conv_w(f'update_block.gru.conv{g}2', 128, 256 + 128, 5, 1)
    conv_w('update_block.flow_head.conv1', 256, 128, 3, 3)
    conv_w('update_block.flow_head.conv2', 2, 256, 3, 3)
    conv_w('update_block.mask.0', 256, 128, 3, 3)
    conv_w('update_block.mask.2', 64 * 9, 256, 1, 1)
    return sd
