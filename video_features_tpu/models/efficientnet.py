"""EfficientNet image backbones (timm `efficientnet_b*` state_dict layout).

The reference's timm extractor accepts any pip-timm model (reference
models/timm/extract_timm.py:48, timm==0.9.12 pinned); this module natively
implements the EfficientNet family — the mobile-conv half of that model
space (depthwise separable convs, squeeze-excite gating, SiLU, inverted
residuals) that the ViT/Swin/ResNet/ConvNeXt families don't cover —
against timm 0.9.12's ``EfficientNet`` module tree (``conv_stem``/``bn1``,
``blocks.S.B.{conv_pw,bn1,conv_dw,bn2,se.conv_reduce,se.conv_expand,
conv_pwl,bn3}``, ``conv_head``/``bn2``, ``classifier``) so real timm
checkpoints transplant mechanically.

TPU notes: depthwise convs lower to XLA ``feature_group_count=C`` (a VPU
pattern, cheap at these sizes); squeeze-excite is a global mean + two 1×1
convs — all static shapes. Covers the native (symmetrically padded)
``efficientnet_b*`` variants; the ``tf_``-prefixed ports use asymmetric
SAME padding and remain pip-timm-bridge territory.

Feature semantics match ``num_classes=0`` timm models: global average
pool of the conv_head output (reference models/timm/extract_timm.py:59-60).
"""
from __future__ import annotations

import math
from typing import Any, Dict, List, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from video_features_tpu.ops.nn import batch_norm, conv

Params = Dict[str, Any]

# timm efficientnet default_cfg: bicubic, ImageNet stats
MEAN = (0.485, 0.456, 0.406)
STD = (0.229, 0.224, 0.225)

# Base (b0) stage table: (kernel, stride, expand, out_channels, repeats).
# Stage 0 is the DepthwiseSeparableConv stage (no expansion conv).
_BASE_STAGES: List[Tuple[int, int, int, int, int]] = [
    (3, 1, 1, 16, 1),
    (3, 2, 6, 24, 2),
    (5, 2, 6, 40, 2),
    (3, 2, 6, 80, 3),
    (5, 1, 6, 112, 3),
    (5, 2, 6, 192, 4),
    (3, 1, 6, 320, 1),
]
SE_RATIO = 0.25

ARCHS = {
    # name: (width_mult, depth_mult, input_size, crop_pct) — input/crop per
    # timm 0.9.12 default_cfgs; the b2+ cfgs moved between timm releases,
    # so the native registry carries the two stable members and larger
    # variants ride the pip-timm bridge
    'efficientnet_b0': (1.0, 1.0, 224, 0.875),
    'efficientnet_b1': (1.0, 1.1, 240, 0.882),
}


def _round_channels(c: float, mult: float, divisor: int = 8) -> int:
    """timm round_channels: scale then round to the nearest multiple of 8
    (never dropping below 90%)."""
    c *= mult
    new = max(divisor, int(c + divisor / 2) // divisor * divisor)
    if new < 0.9 * c:
        new += divisor
    return new


def _round_repeats(r: int, mult: float) -> int:
    return int(math.ceil(r * mult))


def stage_table(arch: str) -> List[Tuple[int, int, int, int, int]]:
    wm, dm, _, _ = ARCHS[arch]
    return [(k, s, e, _round_channels(c, wm), _round_repeats(r, dm))
            for k, s, e, c, r in _BASE_STAGES]


def stem_head_channels(arch: str) -> Tuple[int, int]:
    wm = ARCHS[arch][0]
    return _round_channels(32, wm), _round_channels(1280, wm)


def feat_dim(arch: str) -> int:
    return stem_head_channels(arch)[1]


def _bn_silu(x: jax.Array, p: Params) -> jax.Array:
    return jax.nn.silu(batch_norm(x, p))


def _se(p: Params, x: jax.Array) -> jax.Array:
    """Squeeze-excite: global mean → 1×1 reduce → SiLU → 1×1 expand →
    sigmoid gate (timm SqueezeExcite)."""
    s = x.mean(axis=(1, 2), keepdims=True)
    s = jax.nn.silu(conv(s, p['conv_reduce']['weight'],
                         bias=p['conv_reduce']['bias']))
    s = conv(s, p['conv_expand']['weight'], bias=p['conv_expand']['bias'])
    return x * jax.nn.sigmoid(s)


def _ds_block(p: Params, x: jax.Array, kernel: int, stride: int) -> jax.Array:
    """DepthwiseSeparableConv (stage 0): dw → bn+silu → se → pw → bn,
    residual when shapes allow."""
    shortcut = x
    c = x.shape[-1]
    h = conv(x, p['conv_dw']['weight'], stride=stride, padding=kernel // 2,
             groups=c)
    h = _bn_silu(h, p['bn1'])
    h = _se(p['se'], h)
    h = conv(h, p['conv_pw']['weight'])
    h = batch_norm(h, p['bn2'])
    if stride == 1 and h.shape[-1] == c:
        h = h + shortcut
    return h


def _ir_block(p: Params, x: jax.Array, kernel: int, stride: int) -> jax.Array:
    """InvertedResidual: pw expand → bn+silu → dw → bn+silu → se →
    pw project → bn, residual when shapes allow."""
    shortcut = x
    c = x.shape[-1]
    h = conv(x, p['conv_pw']['weight'])
    h = _bn_silu(h, p['bn1'])
    ce = h.shape[-1]
    h = conv(h, p['conv_dw']['weight'], stride=stride, padding=kernel // 2,
             groups=ce)
    h = _bn_silu(h, p['bn2'])
    h = _se(p['se'], h)
    h = conv(h, p['conv_pwl']['weight'])
    h = batch_norm(h, p['bn3'])
    if stride == 1 and h.shape[-1] == c:
        h = h + shortcut
    return h


def forward(params: Params, x: jax.Array, arch: str = 'efficientnet_b0',
            features: bool = True) -> jax.Array:
    """(B, H, W, 3) normalized frames → (B, head_ch) pooled features (or
    (B, 1000) logits with ``features=False`` and a loaded classifier)."""
    x = conv(x, params['conv_stem']['weight'], stride=2, padding=1)
    x = _bn_silu(x, params['bn1'])
    for si, (k, s, e, c, r) in enumerate(stage_table(arch)):
        stage = params['blocks'][str(si)]
        for bi in range(r):
            bp = stage[str(bi)]
            stride = s if bi == 0 else 1
            if si == 0:
                x = _ds_block(bp, x, k, stride)
            else:
                x = _ir_block(bp, x, k, stride)
    x = conv(x, params['conv_head']['weight'])
    x = _bn_silu(x, params['bn2'])
    x = x.mean(axis=(1, 2))
    if features:
        return x
    cl = params['classifier']    # KeyError on a feature-only checkpoint,
    return x @ cl['weight'] + cl['bias']  # like the other families


def init_state_dict(arch: str = 'efficientnet_b0', seed: int = 0,
                    num_classes: int = 0) -> Dict[str, np.ndarray]:
    """Random torch-layout state_dict with timm 0.9.12 naming/shapes."""
    from video_features_tpu.models._seed import SeedWriter
    rng = np.random.RandomState(seed)
    sd: Dict[str, np.ndarray] = {}
    w_ = SeedWriter(sd, rng)
    cw, bn = w_.conv, w_.bn

    stem, head = stem_head_channels(arch)
    cw('conv_stem', stem, 3, 3)
    bn('bn1', stem)
    cin = stem
    for si, (k, s, e, c, r) in enumerate(stage_table(arch)):
        for bi in range(r):
            base = f'blocks.{si}.{bi}'
            block_in = cin if bi == 0 else c
            rd = max(1, int(block_in * SE_RATIO))
            if si == 0:
                w_.dwconv(f'{base}.conv_dw', block_in, k)
                bn(f'{base}.bn1', block_in)
                cw(f'{base}.se.conv_reduce', rd, block_in, 1, bias=True)
                cw(f'{base}.se.conv_expand', block_in, rd, 1, bias=True)
                cw(f'{base}.conv_pw', c, block_in, 1)
                bn(f'{base}.bn2', c)
            else:
                ce = block_in * e
                cw(f'{base}.conv_pw', ce, block_in, 1)
                bn(f'{base}.bn1', ce)
                w_.dwconv(f'{base}.conv_dw', ce, k)
                bn(f'{base}.bn2', ce)
                cw(f'{base}.se.conv_reduce', rd, ce, 1, bias=True)
                cw(f'{base}.se.conv_expand', ce, rd, 1, bias=True)
                cw(f'{base}.conv_pwl', c, ce, 1)
                bn(f'{base}.bn3', c)
        cin = c
    cw('conv_head', head, cin, 1)
    bn('bn2', head)
    if num_classes:
        w_.linear('classifier', num_classes, head)
    return sd
