"""ResNet-family image backbones (torchvision resnet/resnext/wide layout).

Functional re-implementation of the architectures behind the reference
resnet extractor (reference models/resnet/extract_resnet.py:40 builds ANY
torchvision classification model via ``models.get_model`` with
IMAGENET1K_V1 weights and fc → Identity — the plain resnets its config
names plus the grouped ResNeXt and wide variants that ride the same code
path). Params mirror torchvision state_dict names; layout NHWC. Grouped
3×3 convs lower to XLA ``feature_group_count`` — still an MXU op per
group, batched in one conv call.
"""
from __future__ import annotations

from functools import partial
from typing import Any, Dict

import jax
import numpy as np

from video_features_tpu.ops.nn import (
    adaptive_avg_pool, batch_norm, conv, linear, max_pool, relu,
)

Params = Dict[str, Any]

# torchvision IMAGENET1K_V1 transform constants (Resize 256 → CenterCrop 224)
MEAN = (0.485, 0.456, 0.406)
STD = (0.229, 0.224, 0.225)

ARCHS = {
    'resnet18': dict(block='basic', layers=[2, 2, 2, 2], feat_dim=512),
    'resnet34': dict(block='basic', layers=[3, 4, 6, 3], feat_dim=512),
    'resnet50': dict(block='bottleneck', layers=[3, 4, 6, 3], feat_dim=2048),
    'resnet101': dict(block='bottleneck', layers=[3, 4, 23, 3], feat_dim=2048),
    'resnet152': dict(block='bottleneck', layers=[3, 8, 36, 3], feat_dim=2048),
    # grouped / wide bottlenecks (torchvision resnet.py: width =
    # planes * base_width/64 * groups on conv1/conv2, conv2 grouped)
    'resnext50_32x4d': dict(block='bottleneck', layers=[3, 4, 6, 3],
                            feat_dim=2048, groups=32, base_width=4),
    'resnext101_32x8d': dict(block='bottleneck', layers=[3, 4, 23, 3],
                             feat_dim=2048, groups=32, base_width=8),
    'resnext101_64x4d': dict(block='bottleneck', layers=[3, 4, 23, 3],
                             feat_dim=2048, groups=64, base_width=4),
    'wide_resnet50_2': dict(block='bottleneck', layers=[3, 4, 6, 3],
                            feat_dim=2048, base_width=128),
    'wide_resnet101_2': dict(block='bottleneck', layers=[3, 4, 23, 3],
                             feat_dim=2048, base_width=128),
}


def _basic_block(p: Params, x: jax.Array, stride: int) -> jax.Array:
    identity = x
    out = relu(batch_norm(conv(x, p['conv1']['weight'], stride=stride, padding=1), p['bn1']))
    out = batch_norm(conv(out, p['conv2']['weight'], stride=1, padding=1), p['bn2'])
    if 'downsample' in p:
        identity = batch_norm(conv(x, p['downsample']['0']['weight'], stride=stride),
                              p['downsample']['1'])
    return relu(out + identity)


def _bottleneck(p: Params, x: jax.Array, stride: int,
                groups: int = 1) -> jax.Array:
    identity = x
    out = relu(batch_norm(conv(x, p['conv1']['weight']), p['bn1']))
    out = relu(batch_norm(conv(out, p['conv2']['weight'], stride=stride,
                               padding=1, groups=groups), p['bn2']))
    out = batch_norm(conv(out, p['conv3']['weight']), p['bn3'])
    if 'downsample' in p:
        identity = batch_norm(conv(x, p['downsample']['0']['weight'], stride=stride),
                              p['downsample']['1'])
    return relu(out + identity)


def forward(params: Params, x: jax.Array, arch: str = 'resnet50',
            features: bool = True) -> jax.Array:
    """(B, H, W, 3) normalized image → (B, feat_dim) features or logits."""
    cfg = ARCHS[arch]
    if cfg['block'] == 'basic':
        block_fn = _basic_block
    else:
        block_fn = partial(_bottleneck, groups=cfg.get('groups', 1))
    x = conv(x, params['conv1']['weight'], stride=2, padding=3)
    x = relu(batch_norm(x, params['bn1']))
    x = max_pool(x, 3, stride=2, padding=1)
    for layer_idx, num_blocks in enumerate(cfg['layers'], start=1):
        layer = params[f'layer{layer_idx}']
        for block_idx in range(num_blocks):
            stride = 2 if (layer_idx > 1 and block_idx == 0) else 1
            x = block_fn(layer[str(block_idx)], x, stride)
    x = adaptive_avg_pool(x)
    if features:
        return x
    return linear(x, params['fc'])


def init_state_dict(seed: int = 0, arch: str = 'resnet50',
                    num_classes: int = 1000) -> Dict[str, np.ndarray]:
    """Random torch-layout state_dict with torchvision naming/shapes."""
    rng = np.random.RandomState(seed)
    cfg = ARCHS[arch]
    sd: Dict[str, np.ndarray] = {}

    def conv_w(name, o, i, k):
        sd[name] = rng.randn(o, i, k, k).astype(np.float32) * 0.03

    def bn(name, c):
        sd[f'{name}.weight'] = rng.rand(c).astype(np.float32) + 0.5
        sd[f'{name}.bias'] = rng.randn(c).astype(np.float32) * 0.1
        sd[f'{name}.running_mean'] = rng.randn(c).astype(np.float32) * 0.1
        sd[f'{name}.running_var'] = rng.rand(c).astype(np.float32) + 0.5

    conv_w('conv1.weight', 64, 3, 7); bn('bn1', 64)
    in_p = 64
    expansion = 1 if cfg['block'] == 'basic' else 4
    groups, base_width = cfg.get('groups', 1), cfg.get('base_width', 64)
    for li, (nb, planes) in enumerate(zip(cfg['layers'], [64, 128, 256, 512]), 1):
        out_p = planes * expansion
        # torchvision Bottleneck: conv1/conv2 run at `width` channels
        width = int(planes * base_width / 64) * groups
        for bi in range(nb):
            base = f'layer{li}.{bi}'
            stride = 2 if (li > 1 and bi == 0) else 1
            if cfg['block'] == 'basic':
                conv_w(f'{base}.conv1.weight', planes, in_p, 3); bn(f'{base}.bn1', planes)
                conv_w(f'{base}.conv2.weight', planes, planes, 3); bn(f'{base}.bn2', planes)
            else:
                conv_w(f'{base}.conv1.weight', width, in_p, 1); bn(f'{base}.bn1', width)
                conv_w(f'{base}.conv2.weight', width, width // groups, 3); bn(f'{base}.bn2', width)
                conv_w(f'{base}.conv3.weight', out_p, width, 1); bn(f'{base}.bn3', out_p)
            if stride != 1 or in_p != out_p:
                conv_w(f'{base}.downsample.0.weight', out_p, in_p, 1)
                bn(f'{base}.downsample.1', out_p)
            in_p = out_p
    sd['fc.weight'] = rng.randn(num_classes, cfg['feat_dim']).astype(np.float32) * 0.03
    sd['fc.bias'] = rng.randn(num_classes).astype(np.float32) * 0.03
    return sd
