"""BEiT image backbones (timm `beit_*` state_dict layout).

The reference's timm extractor accepts any pip-timm model (reference
models/timm/extract_timm.py:48, timm==0.9.12 pinned); this module natively
implements BEiT — the self-supervised ViT branch of that model space with
structure plain ViT doesn't have: NO absolute position embedding, a
PER-BLOCK relative position bias table (with 3 extra cls rows), a packed
qkv projection whose bias exists only for q and v (k bias is identically
zero), layer-scale residuals (``gamma_1``/``gamma_2``), and mean-pooled
patch tokens through a ``fc_norm`` instead of cls pooling — against timm
0.9.12's ``Beit`` module tree so real timm checkpoints transplant
mechanically.

TPU notes: the bias-table lookup is a (N+1)² gather over a ≤732-row
table — an embedding lookup XLA handles natively, computed once per
forward outside the per-head matmuls. Everything else is the standard
MXU transformer stack.
"""
from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from video_features_tpu.models.vit import layer_norm

Params = Dict[str, Any]

# timm beit _cfg: bicubic, crop_pct 0.9, "inception" 0.5 stats
MEAN = (0.5, 0.5, 0.5)
STD = (0.5, 0.5, 0.5)

ARCHS = {
    'beit_base_patch16_224': dict(width=768, layers=12, heads=12, patch=16),
    'beit_large_patch16_224': dict(width=1024, layers=24, heads=16,
                                   patch=16),
}
INPUT_RESOLUTION = 224


def num_relative_distance(window: Tuple[int, int]) -> int:
    return (2 * window[0] - 1) * (2 * window[1] - 1) + 3


def gen_relative_position_index(window: Tuple[int, int]) -> np.ndarray:
    """timm beit.py gen_relative_position_index: (N+1, N+1) int index into
    the bias table; the last 3 rows serve cls↔token and cls↔cls."""
    wh, ww = window
    n = wh * ww
    coords = np.stack(np.meshgrid(np.arange(wh), np.arange(ww),
                                  indexing='ij'))          # (2, wh, ww)
    flat = coords.reshape(2, -1)                           # (2, n)
    rel = flat[:, :, None] - flat[:, None, :]              # (2, n, n)
    rel = rel.transpose(1, 2, 0).astype(np.int64)          # (n, n, 2)
    rel[:, :, 0] += wh - 1
    rel[:, :, 1] += ww - 1
    rel[:, :, 0] *= 2 * ww - 1
    nrd = num_relative_distance(window)
    index = np.zeros((n + 1, n + 1), dtype=np.int64)
    index[1:, 1:] = rel.sum(-1)
    index[0, 0:] = nrd - 3
    index[0:, 0] = nrd - 2
    index[0, 0] = nrd - 1
    return index


def _rel_pos_bias(p: Params, index: jax.Array, heads: int) -> jax.Array:
    """(heads, N+1, N+1) additive attention bias from the block's table."""
    n = index.shape[0]
    bias = p['relative_position_bias_table'][index.reshape(-1)]
    return bias.reshape(n, n, heads).transpose(2, 0, 1)


def _attention(p: Params, x: jax.Array, num_heads: int) -> jax.Array:
    """timm beit Attention: packed qkv weight, q/v-only biases (k bias is
    zero by construction), per-head scaled dot product + the block's
    relative position bias added to the scores."""
    B, N, D = x.shape
    head_dim = D // num_heads
    qkv_bias = jnp.concatenate(
        [p['q_bias'], jnp.zeros_like(p['q_bias']), p['v_bias']])
    qkv = x @ p['qkv']['weight'] + qkv_bias
    qkv = qkv.reshape(B, N, 3, num_heads, head_dim)
    q, k, v = jnp.moveaxis(qkv, 2, 0)                      # (B, N, H, hd)
    q = q * (head_dim ** -0.5)
    scores = jnp.einsum('bnhd,bmhd->bhnm', q, k)
    scores = scores + _rel_pos_bias(p, p['relative_position_index'],
                                    num_heads)[None]
    from video_features_tpu.ops.nn import softmax
    probs = softmax(scores, axis=-1)    # fp32 island under the bf16 lane
    out = jnp.einsum('bhnm,bmhd->bnhd', probs, v).reshape(B, N, D)
    return out @ p['proj']['weight'] + p['proj']['bias']


def _block(p: Params, x: jax.Array, num_heads: int) -> jax.Array:
    """Pre-norm block with layer-scale residuals (gamma_1/gamma_2)."""
    x = x + p['gamma_1'] * _attention(p['attn'], layer_norm(x, p['norm1']),
                                      num_heads)
    h = layer_norm(x, p['norm2'])
    h = h @ p['mlp']['fc1']['weight'] + p['mlp']['fc1']['bias']
    h = jax.nn.gelu(h, approximate=False)
    h = h @ p['mlp']['fc2']['weight'] + p['mlp']['fc2']['bias']
    return x + p['gamma_2'] * h


def forward(params: Params, x: jax.Array,
            arch: str = 'beit_base_patch16_224',
            features: bool = True) -> jax.Array:
    """(B, 224, 224, 3) normalized frames → (B, width) features: mean of
    the patch tokens (cls excluded) through ``fc_norm`` — timm's
    ``use_mean_pooling`` head with ``num_classes=0``. ``features=False``
    applies a loaded ``head``."""
    cfg = ARCHS[arch]
    width, patch = cfg['width'], cfg['patch']
    # the relative-position bias tables are sized for the 224 grid; any
    # other input would fail deep inside a gather with an opaque error
    assert x.shape[1:3] == (INPUT_RESOLUTION, INPUT_RESOLUTION), (
        f'beit runs at {INPUT_RESOLUTION}px (rel-pos bias geometry); '
        f'got {x.shape}')
    B = x.shape[0]
    k = params['patch_embed']['proj']
    x = jax.lax.conv_general_dilated(
        x, k['weight'], window_strides=(patch, patch), padding='VALID',
        dimension_numbers=('NHWC', 'HWIO', 'NHWC')) + k['bias']
    x = x.reshape(B, -1, width)
    cls = jnp.broadcast_to(params['cls_token'], (B, 1, width))
    x = jnp.concatenate([cls, x], axis=1)    # no absolute pos embed
    for i in range(cfg['layers']):
        x = _block(params['blocks'][str(i)], x, cfg['heads'])
    feats = layer_norm(x[:, 1:].mean(axis=1), params['fc_norm'])
    if features:
        return feats
    return feats @ params['head']['weight'] + params['head']['bias']


def feat_dim(arch: str) -> int:
    return ARCHS[arch]['width']


def init_state_dict(arch: str = 'beit_base_patch16_224', seed: int = 0,
                    num_classes: int = 0) -> Dict[str, np.ndarray]:
    """Random torch-layout state_dict with timm 0.9.12 naming/shapes
    (incl. the integer ``relative_position_index`` buffers timm saves)."""
    cfg = ARCHS[arch]
    width, layers = cfg['width'], cfg['layers']
    side = INPUT_RESOLUTION // cfg['patch']
    window = (side, side)
    nrd = num_relative_distance(window)
    index = gen_relative_position_index(window)
    rng = np.random.RandomState(seed)

    def f32(*shape, scale=0.02):
        return (rng.randn(*shape) * scale).astype(np.float32)

    sd: Dict[str, np.ndarray] = {
        'cls_token': f32(1, 1, width),
        'patch_embed.proj.weight': f32(width, 3, cfg['patch'], cfg['patch']),
        'patch_embed.proj.bias': f32(width),
        'fc_norm.weight': np.ones(width, np.float32),
        'fc_norm.bias': np.zeros(width, np.float32),
    }
    for i in range(layers):
        b = f'blocks.{i}.'
        sd[b + 'norm1.weight'] = np.ones(width, np.float32)
        sd[b + 'norm1.bias'] = np.zeros(width, np.float32)
        sd[b + 'gamma_1'] = np.full(width, 0.1, np.float32)
        sd[b + 'gamma_2'] = np.full(width, 0.1, np.float32)
        sd[b + 'attn.qkv.weight'] = f32(3 * width, width)
        sd[b + 'attn.q_bias'] = f32(width)
        sd[b + 'attn.v_bias'] = f32(width)
        sd[b + 'attn.relative_position_bias_table'] = f32(
            nrd, cfg['heads'])
        sd[b + 'attn.relative_position_index'] = index
        sd[b + 'attn.proj.weight'] = f32(width, width)
        sd[b + 'attn.proj.bias'] = np.zeros(width, np.float32)
        sd[b + 'norm2.weight'] = np.ones(width, np.float32)
        sd[b + 'norm2.bias'] = np.zeros(width, np.float32)
        sd[b + 'mlp.fc1.weight'] = f32(4 * width, width)
        sd[b + 'mlp.fc1.bias'] = np.zeros(4 * width, np.float32)
        sd[b + 'mlp.fc2.weight'] = f32(width, 4 * width)
        sd[b + 'mlp.fc2.bias'] = np.zeros(width, np.float32)
    if num_classes:
        sd['head.weight'] = f32(num_classes, width)
        sd['head.bias'] = np.zeros(num_classes, np.float32)
    return sd
