"""ConvNeXt image backbones (timm `convnext_*` state_dict layout).

Widens the torch-free native registry behind the timm extractor (the
reference accepts any pip-timm model, reference models/timm/
extract_timm.py:48; without pip-timm we cover the workhorse families
natively). Params mirror timm's ``ConvNeXt`` naming exactly —
``stem.{0,1}``, ``stages.S.blocks.B.{conv_dw,norm,mlp.fc1,mlp.fc2,gamma}``,
``stages.S.downsample.{0,1}``, ``head.{norm,fc}`` — so real timm
checkpoints transplant mechanically.

Layout NHWC; LayerNorms normalize the trailing channel axis directly (timm
inserts NCHW permutes around nn.LayerNorm — a layout dance that does not
exist in channels-last). Inference path only: stochastic depth is identity
and layer-scale ``gamma`` multiplies the block branch.
"""
from __future__ import annotations

from typing import Any, Dict

import jax
import numpy as np

from video_features_tpu.ops.nn import conv, linear

Params = Dict[str, Any]

# timm default_cfg: 224px eval at crop_pct 0.875, bicubic, ImageNet stats
MEAN = (0.485, 0.456, 0.406)
STD = (0.229, 0.224, 0.225)

ARCHS = {
    'convnext_tiny': dict(depths=(3, 3, 9, 3), dims=(96, 192, 384, 768)),
    'convnext_small': dict(depths=(3, 3, 27, 3), dims=(96, 192, 384, 768)),
    'convnext_base': dict(depths=(3, 3, 27, 3), dims=(128, 256, 512, 1024)),
    'convnext_large': dict(depths=(3, 3, 27, 3), dims=(192, 384, 768, 1536)),
}


def layer_norm(x: jax.Array, p: Params, eps: float = 1e-6) -> jax.Array:
    if x.dtype == jax.numpy.bfloat16:
        # fp32 accumulation island (bf16 fast lane, ops/nn.py contract)
        return layer_norm(x.astype(jax.numpy.float32), p,
                          eps).astype(x.dtype)
    mean = x.mean(axis=-1, keepdims=True)
    var = ((x - mean) ** 2).mean(axis=-1, keepdims=True)
    return (x - mean) / jax.numpy.sqrt(var + eps) * p['weight'] + p['bias']


def _block(p: Params, x: jax.Array) -> jax.Array:
    """dw7x7 → LN → fc1 → GELU → fc2 → layer-scale, residual."""
    c = x.shape[-1]
    h = conv(x, p['conv_dw']['weight'], padding=3, groups=c,
             bias=p['conv_dw']['bias'])
    h = layer_norm(h, p['norm'])
    h = linear(h, p['mlp']['fc1'])
    h = jax.nn.gelu(h, approximate=False)   # timm nn.GELU = exact erf
    h = linear(h, p['mlp']['fc2'])
    if 'gamma' in p:
        h = h * p['gamma']
    return x + h


def forward(params: Params, x: jax.Array, arch: str = 'convnext_tiny',
            features: bool = True) -> jax.Array:
    """(B, H, W, 3) normalized image → (B, dims[-1]) pooled features.

    ``features=False`` additionally applies the classifier: global avg pool
    → head.norm (LN) → head.fc, timm's ``head(x)`` with default pooling.
    """
    cfg = ARCHS[arch]
    x = conv(x, params['stem']['0']['weight'], stride=4,
             bias=params['stem']['0']['bias'])
    x = layer_norm(x, params['stem']['1'])
    for s, depth in enumerate(cfg['depths']):
        stage = params['stages'][str(s)]
        if 'downsample' in stage:
            x = layer_norm(x, stage['downsample']['0'])
            x = conv(x, stage['downsample']['1']['weight'], stride=2,
                     bias=stage['downsample']['1']['bias'])
        for b in range(depth):
            x = _block(stage['blocks'][str(b)], x)
    x = x.mean(axis=(1, 2))                       # global average pool
    x = layer_norm(x, params['head']['norm'])
    if features:
        return x
    return linear(x, params['head']['fc'])


def init_state_dict(seed: int = 0, arch: str = 'convnext_tiny',
                    num_classes: int = 1000) -> Dict[str, np.ndarray]:
    """Random torch-layout state_dict (keys/shapes exactly as timm saves)."""
    cfg = ARCHS[arch]
    rng = np.random.RandomState(seed)

    def f32(*shape, scale=0.02):
        return (rng.randn(*shape) * scale).astype(np.float32)

    def ln(name, c):
        sd[f'{name}.weight'] = np.ones(c, np.float32)
        sd[f'{name}.bias'] = np.zeros(c, np.float32)

    dims = cfg['dims']
    sd: Dict[str, np.ndarray] = {
        'stem.0.weight': f32(dims[0], 3, 4, 4),
        'stem.0.bias': np.zeros(dims[0], np.float32),
    }
    ln('stem.1', dims[0])
    for s, depth in enumerate(cfg['depths']):
        if s > 0:
            ln(f'stages.{s}.downsample.0', dims[s - 1])
            sd[f'stages.{s}.downsample.1.weight'] = f32(dims[s], dims[s - 1],
                                                        2, 2)
            sd[f'stages.{s}.downsample.1.bias'] = np.zeros(dims[s],
                                                           np.float32)
        for b in range(depth):
            base = f'stages.{s}.blocks.{b}'
            sd[f'{base}.conv_dw.weight'] = f32(dims[s], 1, 7, 7)
            sd[f'{base}.conv_dw.bias'] = np.zeros(dims[s], np.float32)
            ln(f'{base}.norm', dims[s])
            sd[f'{base}.mlp.fc1.weight'] = f32(4 * dims[s], dims[s])
            sd[f'{base}.mlp.fc1.bias'] = np.zeros(4 * dims[s], np.float32)
            sd[f'{base}.mlp.fc2.weight'] = f32(dims[s], 4 * dims[s])
            sd[f'{base}.mlp.fc2.bias'] = np.zeros(dims[s], np.float32)
            sd[f'{base}.gamma'] = np.full(dims[s], 1e-6, np.float32)
    ln('head.norm', dims[-1])
    sd['head.fc.weight'] = f32(num_classes, dims[-1])
    sd['head.fc.bias'] = np.zeros(num_classes, np.float32)
    return sd
