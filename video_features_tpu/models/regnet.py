"""RegNet image backbones (timm `regnety_*`/`regnetx_*` state_dict layout).

The reference's timm extractor accepts any pip-timm model (reference
models/timm/extract_timm.py:48, timm==0.9.12 pinned); this module natively
implements the RegNet family — the design-space-derived grouped-conv
branch of that model space (per-stage quantized widths, group-width-tied
grouped 3×3 convs; the Y branch adds squeeze-excite sized from the BLOCK
INPUT width, the X branch is SE-free and dispatched off the checkpoint) —
against timm 0.9.12's ``RegNet`` module tree (``stem.{conv,bn}``,
``s{1..4}.b{1..N}.{conv1,conv2,conv3}.{conv,bn}`` + ``se.{fc1,fc2}`` +
``downsample.{conv,bn}``, ``head.fc``) so real timm checkpoints transplant
mechanically.

Per-stage (depth, width, group_width) tables are the published RegNet
configs (Radosavovic et al., "Designing Network Design Spaces";
bottle_ratio 1.0 so the bottleneck width equals the stage width). Every
stage downsamples (stride 2 on its first block); features are the global
average pool of the last stage, dim = its width.

TPU notes: grouped 3×3 convs lower to one XLA conv with
``feature_group_count``; SE is a global mean + two 1×1 convs. All shapes
static.
"""
from __future__ import annotations

from typing import Any, Dict, List, Tuple

import jax
import numpy as np

from video_features_tpu.ops.nn import batch_norm, conv, linear, relu

Params = Dict[str, Any]

# timm regnet _cfg: bicubic, crop_pct 0.875, ImageNet stats
MEAN = (0.485, 0.456, 0.406)
STD = (0.229, 0.224, 0.225)

STEM_WIDTH = 32
SE_RATIO = 0.25

# name: per-stage (depths, widths, group_width). The y variants carry
# squeeze-excite; the x variants are the published SE-free branch (the
# forward dispatches on the checkpoint's 'se' keys, so one graph serves
# both).
ARCHS: Dict[str, Tuple[List[int], List[int], int]] = {
    'regnety_004': ([1, 3, 6, 6], [48, 104, 208, 440], 8),
    'regnety_008': ([1, 3, 8, 2], [64, 128, 320, 768], 16),
    'regnety_016': ([2, 6, 17, 2], [48, 120, 336, 888], 24),
    'regnety_032': ([2, 5, 13, 1], [72, 216, 576, 1512], 24),
    'regnetx_008': ([1, 3, 7, 5], [64, 128, 288, 672], 16),
    'regnetx_016': ([2, 4, 10, 2], [72, 168, 408, 912], 24),
    'regnetx_032': ([2, 6, 15, 2], [96, 192, 432, 1008], 48),
}


def feat_dim(arch: str) -> int:
    return ARCHS[arch][1][-1]


def _conv_bn_act(p: Params, x: jax.Array, stride: int = 1, padding: int = 0,
                 groups: int = 1, act: bool = True) -> jax.Array:
    x = batch_norm(conv(x, p['conv']['weight'], stride=stride,
                        padding=padding, groups=groups), p['bn'])
    return relu(x) if act else x


def _se(p: Params, x: jax.Array) -> jax.Array:
    """timm SEModule: global mean → 1×1 reduce → ReLU → 1×1 expand →
    sigmoid gate. Reduce width comes from the checkpoint (timm sizes it
    from the block INPUT channels × se_ratio, not the bottleneck width)."""
    s = x.mean(axis=(1, 2), keepdims=True)
    s = relu(conv(s, p['fc1']['weight'], bias=p['fc1']['bias']))
    s = conv(s, p['fc2']['weight'], bias=p['fc2']['bias'])
    return x * jax.nn.sigmoid(s)


def _block(p: Params, x: jax.Array, stride: int, groups: int) -> jax.Array:
    """timm regnet Bottleneck (bottle_ratio 1): 1×1 → grouped 3×3 →
    [SE when the checkpoint carries one — RegNetY] → 1×1 (no act) +
    shortcut → ReLU."""
    shortcut = x
    h = _conv_bn_act(p['conv1'], x)
    h = _conv_bn_act(p['conv2'], h, stride=stride, padding=1, groups=groups)
    if 'se' in p:
        h = _se(p['se'], h)
    h = _conv_bn_act(p['conv3'], h, act=False)
    if 'downsample' in p:
        shortcut = _conv_bn_act(p['downsample'], x, stride=stride, act=False)
    return relu(h + shortcut)


def forward(params: Params, x: jax.Array, arch: str = 'regnety_008',
            features: bool = True) -> jax.Array:
    """(B, H, W, 3) normalized frames → (B, feat_dim) pooled features (or
    (B, 1000) logits with ``features=False`` and a loaded head)."""
    depths, widths, group_w = ARCHS[arch]
    x = _conv_bn_act(params['stem'], x, stride=2, padding=1)
    for si, (d, w) in enumerate(zip(depths, widths), start=1):
        stage = params[f's{si}']
        for bi in range(1, d + 1):
            x = _block(stage[f'b{bi}'], x, stride=2 if bi == 1 else 1,
                       groups=w // group_w)
    x = x.mean(axis=(1, 2))
    if features:
        return x
    return linear(x, params['head']['fc'])


def init_state_dict(arch: str = 'regnety_008', seed: int = 0,
                    num_classes: int = 0) -> Dict[str, np.ndarray]:
    """Random torch-layout state_dict with timm 0.9.12 naming/shapes."""
    from video_features_tpu.models._seed import SeedWriter
    rng = np.random.RandomState(seed)
    depths, widths, group_w = ARCHS[arch]
    sd: Dict[str, np.ndarray] = {}
    w_ = SeedWriter(sd, rng, conv_scale=0.08)
    cw, bn = w_.conv, w_.bn

    cw('stem.conv', STEM_WIDTH, 3, 3)
    bn('stem.bn', STEM_WIDTH)
    cin = STEM_WIDTH
    for si, (d, w) in enumerate(zip(depths, widths), start=1):
        for bi in range(1, d + 1):
            base = f's{si}.b{bi}'
            groups = w // group_w
            se_ch = max(1, int(round(cin * SE_RATIO)))
            cw(f'{base}.conv1.conv', w, cin, 1); bn(f'{base}.conv1.bn', w)
            cw(f'{base}.conv2.conv', w, w // groups, 3)
            bn(f'{base}.conv2.bn', w)
            if arch.startswith('regnety'):   # x variants carry no SE
                cw(f'{base}.se.fc1', se_ch, w, 1, bias=True)
                cw(f'{base}.se.fc2', w, se_ch, 1, bias=True)
            cw(f'{base}.conv3.conv', w, w, 1); bn(f'{base}.conv3.bn', w)
            if bi == 1:  # stride-2 first block always needs the projection
                cw(f'{base}.downsample.conv', w, cin, 1)
                bn(f'{base}.downsample.bn', w)
            cin = w
    if num_classes:
        w_.linear('head.fc', num_classes, cin)
    return sd
