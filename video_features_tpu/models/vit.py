"""Vision Transformer image backbones (timm `vit_*` state_dict layout).

The reference's timm extractor accepts any pip-timm model
(reference models/timm/extract_timm.py:48 `timm.create_model`). timm is an
optional dependency here; this module natively implements the ViT family —
the workhorse of that model space — against the exact timm
``VisionTransformer`` state_dict naming (``cls_token``, ``pos_embed``,
``patch_embed.proj``, ``blocks.N.{norm1,attn.qkv,attn.proj,norm2,mlp}``,
``norm``) so real timm checkpoints transplant mechanically, and parity can
be tested against a torch mirror without timm installed.

Feature semantics match `reset_classifier(0)` + `forward(x)`
(reference models/timm/extract_timm.py:59-60): class-token pooling after the
final norm, no head.
"""
from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np

Params = Dict[str, Any]

# timm default_cfg constants for the supported family: inputs are 224px,
# bicubic, crop_pct 0.9 → resize short side 248; "inception" 0.5 mean/std.
MEAN = (0.5, 0.5, 0.5)
STD = (0.5, 0.5, 0.5)

ARCHS = {
    'vit_tiny_patch16_224': dict(width=192, layers=12, heads=3, patch=16),
    'vit_small_patch16_224': dict(width=384, layers=12, heads=6, patch=16),
    'vit_small_patch32_224': dict(width=384, layers=12, heads=6, patch=32),
    'vit_base_patch16_224': dict(width=768, layers=12, heads=12, patch=16),
    'vit_base_patch32_224': dict(width=768, layers=12, heads=12, patch=32),
    'vit_large_patch16_224': dict(width=1024, layers=24, heads=16, patch=16),
}
INPUT_RESOLUTION = 224


def layer_norm(x: jax.Array, p: Params, eps: float = 1e-6) -> jax.Array:
    if x.dtype == jnp.bfloat16:
        # fp32 accumulation island (bf16 fast lane, ops/nn.py contract):
        # LayerNorm statistics in fp32, result cast back
        return layer_norm(x.astype(jnp.float32), p, eps).astype(x.dtype)
    mean = x.mean(axis=-1, keepdims=True)
    var = ((x - mean) ** 2).mean(axis=-1, keepdims=True)
    return (x - mean) / jnp.sqrt(var + eps) * p['weight'] + p['bias']


# Above this token count, attention switches to the blockwise online-softmax
# path (O(N·block) score memory instead of O(N²)) — irrelevant for 224px
# frames (~197 tokens) but load-bearing when a long video's temporal tokens
# are attended as one sequence.
BLOCKWISE_THRESHOLD = 2048
_BLOCK = 512


def _attention(p: Params, x: jax.Array, num_heads: int,
               attn_impl=None) -> jax.Array:
    """timm `Attention`: fused qkv linear, per-head scaled dot product.

    ``attn_impl`` overrides the core attention op (``(q, k, v) → out`` on
    (B, N, H, hd) tensors) — the sequence-parallel path injects a ring
    kernel here; default picks dense or blockwise by token count.
    """
    from video_features_tpu.ops.attention import (
        blockwise_attention, dense_attention,
    )
    B, N, D = x.shape
    head_dim = D // num_heads
    qkv = x @ p['qkv']['weight'] + p['qkv']['bias']          # (B, N, 3D)
    qkv = qkv.reshape(B, N, 3, num_heads, head_dim)
    q, k, v = jnp.moveaxis(qkv, 2, 0)                        # (B, N, H, hd)
    if attn_impl is not None:
        out = attn_impl(q, k, v)
    elif N >= BLOCKWISE_THRESHOLD:
        out = blockwise_attention(q, k, v, block_size=_BLOCK)
    else:
        out = dense_attention(q, k, v)
    out = out.reshape(B, N, D)
    return out @ p['proj']['weight'] + p['proj']['bias']


def _block(p: Params, x: jax.Array, num_heads: int,
           attn_impl=None) -> jax.Array:
    """Pre-norm transformer block with exact-erf GELU (torch nn.GELU)."""
    x = x + _attention(p['attn'], layer_norm(x, p['norm1']), num_heads,
                       attn_impl)
    h = layer_norm(x, p['norm2'])
    h = h @ p['mlp']['fc1']['weight'] + p['mlp']['fc1']['bias']
    h = jax.nn.gelu(h, approximate=False)
    h = h @ p['mlp']['fc2']['weight'] + p['mlp']['fc2']['bias']
    return x + h


def interpolate_pos_embed(pos_embed: jax.Array,
                          grid: "tuple[int, int]",
                          n_prefix: int = 1) -> jax.Array:
    """Resample a (1, n_prefix+g², D) pos embed to a new (gh, gw) grid.

    The standard timm recipe for non-native input resolutions
    (`resample_abs_pos_embed`): keep the ``n_prefix`` prefix positions
    (cls, plus dist for distilled DeiT), bicubically resize the 2-D grid
    positions. Lets 224-trained checkpoints run at higher resolutions
    (more tokens — the blockwise-attention regime).
    """
    n = pos_embed.shape[1] - n_prefix
    side = int(round(n ** 0.5))
    if (side, side) == grid:
        return pos_embed
    cls_pos = pos_embed[:, :n_prefix]
    grid_pos = pos_embed[:, n_prefix:]
    d = pos_embed.shape[-1]
    grid_pos = grid_pos.reshape(1, side, side, d)
    grid_pos = jax.image.resize(grid_pos, (1, grid[0], grid[1], d),
                                method='bicubic')
    return jnp.concatenate(
        [cls_pos, grid_pos.reshape(1, grid[0] * grid[1], d)], axis=1)


def embed(params: Params, x: jax.Array,
          arch: str = 'vit_base_patch16_224') -> jax.Array:
    """(B, H, W, 3) → (B, 1+grid², width) embedded tokens (patch conv +
    cls + resampled pos embed)."""
    cfg = ARCHS[arch]
    width, patch = cfg['width'], cfg['patch']
    B = x.shape[0]
    # patch embed: conv stride=patch, then row-major flatten (timm flattens
    # NCHW as (B, D, H', W') → (B, H'·W', D); NHWC flatten matches directly)
    k = params['patch_embed']['proj']
    x = jax.lax.conv_general_dilated(
        x, k['weight'], window_strides=(patch, patch), padding='VALID',
        dimension_numbers=('NHWC', 'HWIO', 'NHWC')) + k['bias']
    grid = (x.shape[1], x.shape[2])
    x = x.reshape(B, -1, width)
    prefix = [jnp.broadcast_to(params['cls_token'], (B, 1, width))]
    if 'dist_token' in params:      # distilled DeiT (timm deit.py)
        prefix.append(jnp.broadcast_to(params['dist_token'], (B, 1, width)))
    return jnp.concatenate(prefix + [x], axis=1) + interpolate_pos_embed(
        params['pos_embed'], grid, n_prefix=len(prefix))


def trunk(params: Params, tokens: jax.Array, arch: str,
          attn_impl=None) -> jax.Array:
    """All transformer blocks over (B, N, width) tokens (no final norm).

    Every op except attention is token-local, so under ``shard_map`` with
    the token axis sharded this runs unmodified — only ``attn_impl`` needs
    to be a sequence-parallel kernel (see forward_sequence_parallel).
    """
    cfg = ARCHS[arch]
    for i in range(cfg['layers']):
        tokens = _block(params['blocks'][str(i)], tokens, cfg['heads'],
                        attn_impl)
    return tokens


def forward(params: Params, x: jax.Array, arch: str = 'vit_base_patch16_224',
            features: bool = True) -> jax.Array:
    """(B, H, W, 3) float in model space → (B, width) cls-token features.

    With ``features=False`` and a transplanted ``head``, returns (B, 1000)
    logits (the reference's show_pred path, extract_timm.py:63-91).
    Inputs need not be the checkpoint's native 224px — the pos embed is
    bicubically resampled to the actual patch grid (timm's high-res recipe),
    and past BLOCKWISE_THRESHOLD tokens attention switches to the
    O(N·block) blockwise path.
    """
    x = trunk(params, embed(params, x, arch), arch)
    x = layer_norm(x, params['norm'])
    if 'dist_token' in params:
        # distilled DeiT inference (timm deit.py VisionTransformerDistilled):
        # features = mean of cls and dist tokens; logits = mean of the two
        # heads' outputs
        if features:
            return (x[:, 0] + x[:, 1]) / 2
        cls_logits = x[:, 0] @ params['head']['weight'] + params['head']['bias']
        dist_logits = (x[:, 1] @ params['head_dist']['weight']
                       + params['head_dist']['bias'])
        return (cls_logits + dist_logits) / 2
    feats = x[:, 0]
    if features:
        return feats
    return feats @ params['head']['weight'] + params['head']['bias']


def forward_sequence_parallel(params: Params, x: jax.Array, mesh,
                              arch: str = 'vit_base_patch16_224',
                              axis: str = 'time',
                              features: bool = True) -> jax.Array:
    """ViT forward with the TOKEN axis sharded over a mesh axis.

    The sequence-parallel production path for inputs whose token count
    exceeds one chip's memory (very high resolution / long token videos):
    tokens are zero-padded to a multiple of the axis size with a validity
    mask, every token-local op (LN, MLP, patch projection output) runs
    unchanged inside ``shard_map``, and attention is
    :func:`ops.attention.ring_attention` — KV shards rotate over ICI
    neighbor hops while each device accumulates its queries' online
    softmax; padded keys are masked out of every softmax and the mask
    rotates with its shard.
    """
    from video_features_tpu.utils.device import shard_map
    from jax.sharding import PartitionSpec as P

    from video_features_tpu.ops.attention import ring_attention

    tokens = embed(params, x, arch)
    B, N, width = tokens.shape
    n = mesh.shape[axis]
    pad = (-N) % n
    if pad:
        tokens = jnp.pad(tokens, [(0, 0), (0, pad), (0, 0)])
    valid = jnp.arange(N + pad) < N

    def shard_fn(p, tok, val):
        def attn(q, k, v):
            return ring_attention(q, k, v, axis_name=axis, kv_valid=val)
        return trunk(p, tok, arch, attn_impl=attn)

    out = shard_map(
        shard_fn, mesh=mesh,
        in_specs=(P(), P(None, axis, None), P(axis)),
        out_specs=P(None, axis, None),
    )(params, tokens, valid)
    x = layer_norm(out[:, :N], params['norm'])
    # same head dispatch as forward() — a distilled checkpoint must yield
    # identical features on the single-chip and sequence-parallel paths
    if 'dist_token' in params:
        if features:
            return (x[:, 0] + x[:, 1]) / 2
        cls_logits = x[:, 0] @ params['head']['weight'] + params['head']['bias']
        dist_logits = (x[:, 1] @ params['head_dist']['weight']
                       + params['head_dist']['bias'])
        return (cls_logits + dist_logits) / 2
    feats = x[:, 0]
    if features:
        return feats
    return feats @ params['head']['weight'] + params['head']['bias']


def init_state_dict(seed: int = 0, arch: str = 'vit_base_patch16_224',
                    num_classes: int = 1000,
                    distilled: bool = False) -> Dict[str, np.ndarray]:
    """Random torch-layout state_dict (keys/shapes as timm saves them);
    ``distilled`` adds DeiT's dist_token / head_dist / extra pos slot."""
    cfg = ARCHS[arch]
    width, patch, layers = cfg['width'], cfg['patch'], cfg['layers']
    n_tokens = (2 if distilled else 1) + (INPUT_RESOLUTION // patch) ** 2
    rng = np.random.RandomState(seed)

    def f32(*shape, scale=0.02):
        return (rng.randn(*shape) * scale).astype(np.float32)

    sd = {
        'cls_token': f32(1, 1, width),
        'pos_embed': f32(1, n_tokens, width),
        'patch_embed.proj.weight': f32(width, 3, patch, patch),
        'patch_embed.proj.bias': f32(width),
        'norm.weight': np.ones(width, np.float32),
        'norm.bias': np.zeros(width, np.float32),
        'head.weight': f32(num_classes, width),
        'head.bias': np.zeros(num_classes, np.float32),
    }
    if distilled:
        sd['dist_token'] = f32(1, 1, width)
        sd['head_dist.weight'] = f32(num_classes, width)
        sd['head_dist.bias'] = np.zeros(num_classes, np.float32)
    for i in range(layers):
        b = f'blocks.{i}.'
        sd[b + 'norm1.weight'] = np.ones(width, np.float32)
        sd[b + 'norm1.bias'] = np.zeros(width, np.float32)
        sd[b + 'attn.qkv.weight'] = f32(3 * width, width)
        sd[b + 'attn.qkv.bias'] = np.zeros(3 * width, np.float32)
        sd[b + 'attn.proj.weight'] = f32(width, width)
        sd[b + 'attn.proj.bias'] = np.zeros(width, np.float32)
        sd[b + 'norm2.weight'] = np.ones(width, np.float32)
        sd[b + 'norm2.bias'] = np.zeros(width, np.float32)
        sd[b + 'mlp.fc1.weight'] = f32(4 * width, width)
        sd[b + 'mlp.fc1.bias'] = np.zeros(4 * width, np.float32)
        sd[b + 'mlp.fc2.weight'] = f32(width, 4 * width)
        sd[b + 'mlp.fc2.bias'] = np.zeros(width, np.float32)
    return sd
