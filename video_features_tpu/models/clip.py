"""CLIP (OpenAI) — ViT and ModifiedResNet visual towers + text transformer.

Functional re-implementation of the architecture behind the reference's
vendored CLIP (reference models/clip/clip_src/model.py, 436 LoC): QuickGELU
MLPs (:166-168), pre-norm residual attention blocks, ViT class-token pooling
with a final projection matrix (:213-221), ModifiedResNet with avgpool
anti-aliased striding (:94-143) and an AttentionPool2d head (:58-91), and a
causal text transformer pooled at the argmax (EOT) token.

Params mirror the OpenAI checkpoint state_dict. Notable layout facts:
  * ``visual.proj`` / ``text_projection`` are raw matmul params (used as
    ``x @ W`` in torch) — the transplant leaves them untouched;
  * ``attn.in_proj_weight`` is a fused (3d, d) F.linear weight — consumed
    here with an explicit transpose;
  * ``token_embedding.weight`` must NOT be transposed (gather table) — pass
    ``no_transpose`` to the transplant.
"""
from __future__ import annotations

from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from video_features_tpu.ops.nn import avg_pool, batch_norm, conv, relu

Params = Dict[str, Any]

# OpenAI CLIP preprocessing constants (reference clip_src/clip.py transform)
MEAN = (0.48145466, 0.4578275, 0.40821073)
STD = (0.26862954, 0.26130258, 0.27577711)

# state_dict entries the generic transplant must leave un-transposed
NO_TRANSPOSE = ('token_embedding.weight',)

VISUAL_CFGS = {
    'ViT-B/32': dict(kind='vit', width=768, layers=12, heads=12, patch=32,
                     input_resolution=224, embed_dim=512),
    'ViT-B/16': dict(kind='vit', width=768, layers=12, heads=12, patch=16,
                     input_resolution=224, embed_dim=512),
    'RN50': dict(kind='resnet', width=64, layers=(3, 4, 6, 3), heads=32,
                 input_resolution=224, embed_dim=1024),
    'RN101': dict(kind='resnet', width=64, layers=(3, 4, 23, 3), heads=32,
                  input_resolution=224, embed_dim=512),
    'RN50x4': dict(kind='resnet', width=80, layers=(4, 6, 10, 6), heads=40,
                   input_resolution=288, embed_dim=640),
    'RN50x16': dict(kind='resnet', width=96, layers=(6, 8, 18, 8), heads=48,
                    input_resolution=384, embed_dim=768),
    'RN50x64': dict(kind='resnet', width=128, layers=(3, 15, 36, 10), heads=64,
                    input_resolution=448, embed_dim=1024),
    'ViT-L/14': dict(kind='vit', width=1024, layers=24, heads=16, patch=14,
                     input_resolution=224, embed_dim=768),
    'ViT-L/14@336px': dict(kind='vit', width=1024, layers=24, heads=16,
                           patch=14, input_resolution=336, embed_dim=768),
}

TEXT_CFG = dict(context_length=77, vocab_size=49408)


def quick_gelu(x: jax.Array) -> jax.Array:
    return x * jax.nn.sigmoid(1.702 * x)


def layer_norm(x: jax.Array, p: Params, eps: float = 1e-5) -> jax.Array:
    if x.dtype == jnp.bfloat16:
        # fp32 accumulation island (bf16 fast lane, ops/nn.py contract)
        return layer_norm(x.astype(jnp.float32), p, eps).astype(x.dtype)
    mean = x.mean(-1, keepdims=True)
    var = x.var(-1, keepdims=True)
    out = (x - mean) * jax.lax.rsqrt(var + jnp.asarray(eps, x.dtype))
    return out * p['weight'].astype(x.dtype) + p['bias'].astype(x.dtype)


def multi_head_attention(p: Params, x: jax.Array, num_heads: int,
                         mask: Optional[jax.Array] = None) -> jax.Array:
    """torch nn.MultiheadAttention with fused in_proj, self-attention case.

    x: (B, L, D). in_proj_weight (3D, D) is an F.linear weight → x @ W.T.
    """
    B, L, D = x.shape
    qkv = x @ p['in_proj_weight'].astype(x.dtype).T + p['in_proj_bias'].astype(x.dtype)
    q, k, v = jnp.split(qkv, 3, axis=-1)
    head_dim = D // num_heads

    def split_heads(t):
        return t.reshape(B, L, num_heads, head_dim).transpose(0, 2, 1, 3)

    q, k, v = split_heads(q), split_heads(k), split_heads(v)
    attn = (q @ k.transpose(0, 1, 3, 2)) * (head_dim ** -0.5)
    if mask is not None:
        attn = attn + mask.astype(attn.dtype)
    from video_features_tpu.ops.nn import softmax
    attn = softmax(attn, axis=-1)       # fp32 island under the bf16 lane
    out = (attn @ v).transpose(0, 2, 1, 3).reshape(B, L, D)
    return out @ p['out_proj']['weight'].astype(x.dtype) + p['out_proj']['bias'].astype(x.dtype)


def residual_attention_block(p: Params, x: jax.Array, num_heads: int,
                             mask: Optional[jax.Array] = None) -> jax.Array:
    x = x + multi_head_attention(p['attn'], layer_norm(x, p['ln_1']), num_heads, mask)
    h = layer_norm(x, p['ln_2'])
    h = quick_gelu(h @ p['mlp']['c_fc']['weight'].astype(x.dtype)
                   + p['mlp']['c_fc']['bias'].astype(x.dtype))
    h = h @ p['mlp']['c_proj']['weight'].astype(x.dtype) + p['mlp']['c_proj']['bias'].astype(x.dtype)
    return x + h


def transformer(p: Params, x: jax.Array, num_heads: int,
                mask: Optional[jax.Array] = None) -> jax.Array:
    blocks = p['resblocks']
    for i in range(len(blocks)):
        x = residual_attention_block(blocks[str(i)], x, num_heads, mask)
    return x


# -- ViT visual tower --------------------------------------------------------

def encode_image_vit(params: Params, x: jax.Array, model_name: str) -> jax.Array:
    """(B, H, W, 3) normalized → (B, embed_dim) image features."""
    cfg = VISUAL_CFGS[model_name]
    p = params['visual']
    x = conv(x, p['conv1']['weight'], stride=cfg['patch'])      # (B, g, g, width)
    B = x.shape[0]
    x = x.reshape(B, -1, cfg['width'])
    cls = jnp.broadcast_to(p['class_embedding'].astype(x.dtype), (B, 1, cfg['width']))
    x = jnp.concatenate([cls, x], axis=1)
    x = x + p['positional_embedding'].astype(x.dtype)
    x = layer_norm(x, p['ln_pre'])
    x = transformer(p['transformer'], x, cfg['heads'] if cfg['kind'] == 'vit' else 12)
    x = layer_norm(x[:, 0, :], p['ln_post'])
    return x @ p['proj'].astype(x.dtype)


# -- ModifiedResNet visual tower --------------------------------------------

def _clip_bottleneck(p: Params, x: jax.Array, stride: int) -> jax.Array:
    out = relu(batch_norm(conv(x, p['conv1']['weight']), p['bn1']))
    out = relu(batch_norm(conv(out, p['conv2']['weight'], padding=1), p['bn2']))
    if stride > 1:
        out = avg_pool(out, stride)
    out = batch_norm(conv(out, p['conv3']['weight']), p['bn3'])
    if 'downsample' in p:
        identity = avg_pool(x, stride) if stride > 1 else x
        identity = batch_norm(conv(identity, p['downsample']['0']['weight']),
                              p['downsample']['1'])
    else:
        identity = x
    return relu(out + identity)


def _attention_pool(p: Params, x: jax.Array, num_heads: int) -> jax.Array:
    """AttentionPool2d (reference model.py:58-91): mean-token query attention."""
    B, H, W, C = x.shape
    x = x.reshape(B, H * W, C)
    x = jnp.concatenate([x.mean(axis=1, keepdims=True), x], axis=1)  # (B,HW+1,C)
    x = x + p['positional_embedding'].astype(x.dtype)
    L = x.shape[1]
    q_w = p['q_proj']['weight'].astype(x.dtype)   # transplanted to (I, O)
    k_w = p['k_proj']['weight'].astype(x.dtype)
    v_w = p['v_proj']['weight'].astype(x.dtype)
    q = x[:, :1] @ q_w + p['q_proj']['bias'].astype(x.dtype)
    k = x @ k_w + p['k_proj']['bias'].astype(x.dtype)
    v = x @ v_w + p['v_proj']['bias'].astype(x.dtype)
    head_dim = C // num_heads
    q = q.reshape(B, 1, num_heads, head_dim).transpose(0, 2, 1, 3)
    k = k.reshape(B, L, num_heads, head_dim).transpose(0, 2, 1, 3)
    v = v.reshape(B, L, num_heads, head_dim).transpose(0, 2, 1, 3)
    from video_features_tpu.ops.nn import softmax
    attn = softmax((q @ k.transpose(0, 1, 3, 2)) * (head_dim ** -0.5),
                   axis=-1)             # fp32 island under the bf16 lane
    out = (attn @ v).transpose(0, 2, 1, 3).reshape(B, C)
    return out @ p['c_proj']['weight'].astype(x.dtype) + p['c_proj']['bias'].astype(x.dtype)


def encode_image_resnet(params: Params, x: jax.Array, model_name: str) -> jax.Array:
    cfg = VISUAL_CFGS[model_name]
    p = params['visual']
    # 3-conv stem, each stride-1 except conv1 (stride 2), then avgpool 2
    x = relu(batch_norm(conv(x, p['conv1']['weight'], stride=2, padding=1), p['bn1']))
    x = relu(batch_norm(conv(x, p['conv2']['weight'], padding=1), p['bn2']))
    x = relu(batch_norm(conv(x, p['conv3']['weight'], padding=1), p['bn3']))
    x = avg_pool(x, 2)
    for li, nb in enumerate(cfg['layers'], start=1):
        layer = p[f'layer{li}']
        for bi in range(nb):
            stride = 2 if (li > 1 and bi == 0) else 1
            x = _clip_bottleneck(layer[str(bi)], x, stride)
    return _attention_pool(p['attnpool'], x, cfg['heads'])


def encode_image(params: Params, x: jax.Array, model_name: str) -> jax.Array:
    if VISUAL_CFGS[model_name]['kind'] == 'vit':
        return encode_image_vit(params, x, model_name)
    return encode_image_resnet(params, x, model_name)


# -- text tower --------------------------------------------------------------

def encode_text(params: Params, tokens: jax.Array, model_name: str) -> jax.Array:
    """(B, 77) int tokens → (B, embed_dim) text features."""
    emb = params['token_embedding']['weight']
    x = emb[tokens]                                   # (B, L, D)
    x = x + params['positional_embedding'].astype(x.dtype)
    L = x.shape[1]
    mask = jnp.triu(jnp.full((L, L), -jnp.inf), k=1)
    # text transformer head count: width // 64 per OpenAI build_model
    heads = x.shape[-1] // 64
    x = transformer(params['transformer'], x, heads, mask)
    x = layer_norm(x, params['ln_final'])
    eot = jnp.argmax(tokens, axis=-1)
    x = x[jnp.arange(x.shape[0]), eot]
    return x @ params['text_projection'].astype(x.dtype)


def zero_shot_logits(params: Params, image_feats: jax.Array,
                     text_feats: jax.Array) -> jax.Array:
    """Cosine-similarity logits with learned temperature (reference :362-368)."""
    img = image_feats / jnp.linalg.norm(image_feats, axis=-1, keepdims=True)
    txt = text_feats / jnp.linalg.norm(text_feats, axis=-1, keepdims=True)
    scale = jnp.exp(params['logit_scale'])
    return scale * img @ txt.T


def _match_visual_cfg(kind: str, width: int, layers, patch=None,
                      grid=None) -> str:
    """Map extracted tower dimensions onto a VISUAL_CFGS key.

    ``grid`` (ViT positional-embedding side length) disambiguates variants
    that differ only in input resolution (ViT-L/14 vs ViT-L/14@336px).
    """
    for name, cfg in VISUAL_CFGS.items():
        if cfg['kind'] != kind or cfg['width'] != width:
            continue
        if kind == 'vit' and cfg['patch'] == patch and cfg['layers'] == layers:
            if grid is None or cfg['input_resolution'] // cfg['patch'] == grid:
                return name
        if kind == 'resnet' and tuple(cfg['layers']) == tuple(layers):
            return name
    raise NotImplementedError(
        f'unrecognized {kind}: width={width} patch={patch} layers={layers} '
        f'grid={grid}')


def infer_model_name(state_dict) -> str:
    """Detect the architecture from a raw torch state_dict, the way the
    reference's build_model does (reference clip_src/model.py:399-417), and
    map it onto a known VISUAL_CFGS key (for ``model_name: custom``)."""
    def shape(k):
        return tuple(state_dict[k].shape)

    if 'visual.proj' in state_dict:
        width = shape('visual.conv1.weight')[0]
        patch = shape('visual.conv1.weight')[-1]
        layers = len({k.split('.')[3] for k in state_dict
                      if k.startswith('visual.transformer.resblocks.')})
        grid = int(round((shape('visual.positional_embedding')[0] - 1) ** 0.5))
        return _match_visual_cfg('vit', width, layers, patch, grid)
    width = shape('visual.layer1.0.conv1.weight')[0]
    layers = tuple(
        len({k.split('.')[2] for k in state_dict
             if k.startswith(f'visual.layer{li}.')}) for li in (1, 2, 3, 4))
    return _match_visual_cfg('resnet', width, layers)


def infer_model_name_from_params(params) -> str:
    """:func:`infer_model_name` for an already-transplanted pytree (the
    .npz checkpoint path): same detection on HWIO conv layouts."""
    visual = params['visual']
    if 'proj' in visual:  # ViT tower
        w = visual['conv1']['weight'].shape        # (patch, patch, 3, width)
        layers = len(visual['transformer']['resblocks'])
        npos = visual['positional_embedding'].shape[0]
        grid = int(round((npos - 1) ** 0.5))
        return _match_visual_cfg('vit', w[-1], layers, w[0], grid)
    width = visual['layer1']['0']['conv1']['weight'].shape[-1]
    layers = tuple(len(visual[f'layer{li}']) for li in (1, 2, 3, 4))
    return _match_visual_cfg('resnet', width, layers)


# -- random init for tests ---------------------------------------------------

def init_state_dict(seed: int = 0, model_name: str = 'ViT-B/32',
                    text_layers: int = 2, vocab_size: int = 512,
                    context_length: int = 77) -> Dict[str, np.ndarray]:
    """Random OpenAI-layout state_dict (tiny text tower option for tests)."""
    assert VISUAL_CFGS[model_name]['kind'] == 'vit', 'test init supports ViT'
    cfg = VISUAL_CFGS[model_name]
    rng = np.random.RandomState(seed)
    sd: Dict[str, np.ndarray] = {}
    w, d = cfg['width'], cfg['embed_dim']

    def f32(*shape, scale=0.02):
        return (rng.randn(*shape) * scale).astype(np.float32)

    def block(prefix, dim):
        sd[f'{prefix}.ln_1.weight'] = np.ones(dim, np.float32)
        sd[f'{prefix}.ln_1.bias'] = f32(dim)
        sd[f'{prefix}.attn.in_proj_weight'] = f32(3 * dim, dim)
        sd[f'{prefix}.attn.in_proj_bias'] = f32(3 * dim)
        sd[f'{prefix}.attn.out_proj.weight'] = f32(dim, dim)
        sd[f'{prefix}.attn.out_proj.bias'] = f32(dim)
        sd[f'{prefix}.ln_2.weight'] = np.ones(dim, np.float32)
        sd[f'{prefix}.ln_2.bias'] = f32(dim)
        sd[f'{prefix}.mlp.c_fc.weight'] = f32(4 * dim, dim)
        sd[f'{prefix}.mlp.c_fc.bias'] = f32(4 * dim)
        sd[f'{prefix}.mlp.c_proj.weight'] = f32(dim, 4 * dim)
        sd[f'{prefix}.mlp.c_proj.bias'] = f32(dim)

    grid = cfg['input_resolution'] // cfg['patch']
    sd['visual.conv1.weight'] = f32(w, 3, cfg['patch'], cfg['patch'])
    sd['visual.class_embedding'] = f32(w)
    sd['visual.positional_embedding'] = f32(grid * grid + 1, w)
    sd['visual.ln_pre.weight'] = np.ones(w, np.float32)
    sd['visual.ln_pre.bias'] = f32(w)
    for i in range(cfg['layers']):
        block(f'visual.transformer.resblocks.{i}', w)
    sd['visual.ln_post.weight'] = np.ones(w, np.float32)
    sd['visual.ln_post.bias'] = f32(w)
    sd['visual.proj'] = f32(w, d)

    # tiny text tower
    tw = d
    sd['token_embedding.weight'] = f32(vocab_size, tw)
    sd['positional_embedding'] = f32(context_length, tw)
    for i in range(text_layers):
        block(f'transformer.resblocks.{i}', tw)
    sd['ln_final.weight'] = np.ones(tw, np.float32)
    sd['ln_final.bias'] = f32(tw)
    sd['text_projection'] = f32(tw, d)
    sd['logit_scale'] = np.float32(np.log(1 / 0.07))
    return sd
