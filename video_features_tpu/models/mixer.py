"""MLP-Mixer image backbones (timm `mixer_*` state_dict layout).

The reference's timm extractor accepts any pip-timm model (reference
models/timm/extract_timm.py:48, timm==0.9.12 pinned); this module natively
implements MLP-Mixer — the attention-free branch of that model space:
each block mixes TOKENS with an MLP applied across the patch axis
(weights shaped by the 196-token grid), then channels with an ordinary
MLP — against timm 0.9.12's ``MlpMixer`` tree (``stem.proj``,
``blocks.N.{norm1,mlp_tokens,norm2,mlp_channels}``, ``norm``) so real
timm checkpoints transplant mechanically.

Token mixing is resolution-tied (fc weights are (tokens_dim, 196)), so
no ``image_size`` override — like BEiT, inputs are the checkpoint's
224 px.

TPU notes: both mixings are plain matmuls (the token mix contracts the
PATCH axis — one transpose, MXU-friendly at these shapes); no gathers,
no attention, static shapes throughout.
"""
from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np

from video_features_tpu.models.vit import layer_norm

Params = Dict[str, Any]

# timm mixer _cfg: bicubic, crop_pct 0.875, 0.5 "inception" stats
MEAN = (0.5, 0.5, 0.5)
STD = (0.5, 0.5, 0.5)

ARCHS = {
    'mixer_b16_224': dict(width=768, layers=12, patch=16),
    'mixer_l16_224': dict(width=1024, layers=24, patch=16),
}
INPUT_RESOLUTION = 224


def feat_dim(arch: str) -> int:
    return ARCHS[arch]['width']


def _mlp(p: Params, x: jax.Array) -> jax.Array:
    h = x @ p['fc1']['weight'] + p['fc1']['bias']
    h = jax.nn.gelu(h, approximate=False)
    return h @ p['fc2']['weight'] + p['fc2']['bias']


def _block(p: Params, x: jax.Array) -> jax.Array:
    """timm MixerBlock: token-mix MLP over the transposed (B, C, N)
    view, then channel-mix MLP — both residual."""
    h = layer_norm(x, p['norm1'])
    h = _mlp(p['mlp_tokens'], h.swapaxes(1, 2)).swapaxes(1, 2)
    x = x + h
    return x + _mlp(p['mlp_channels'], layer_norm(x, p['norm2']))


def forward(params: Params, x: jax.Array, arch: str = 'mixer_b16_224',
            features: bool = True) -> jax.Array:
    """(B, 224, 224, 3) normalized frames → (B, width) features: mean
    over tokens after the final norm (timm global_pool='avg',
    ``num_classes=0``). ``features=False`` applies a loaded ``head``."""
    cfg = ARCHS[arch]
    width, patch = cfg['width'], cfg['patch']
    # token-mixing MLP weights are sized for the 224 token grid; any other
    # input would fail as an opaque matmul shape error
    assert x.shape[1:3] == (INPUT_RESOLUTION, INPUT_RESOLUTION), (
        f'mixer runs at {INPUT_RESOLUTION}px (token-MLP geometry); '
        f'got {x.shape}')
    B = x.shape[0]
    k = params['stem']['proj']
    x = jax.lax.conv_general_dilated(
        x, k['weight'], window_strides=(patch, patch), padding='VALID',
        dimension_numbers=('NHWC', 'HWIO', 'NHWC')) + k['bias']
    x = x.reshape(B, -1, width)
    for i in range(cfg['layers']):
        x = _block(params['blocks'][str(i)], x)
    feats = layer_norm(x, params['norm']).mean(axis=1)
    if features:
        return feats
    return feats @ params['head']['weight'] + params['head']['bias']


def init_state_dict(arch: str = 'mixer_b16_224', seed: int = 0,
                    num_classes: int = 0) -> Dict[str, np.ndarray]:
    """Random torch-layout state_dict with timm 0.9.12 naming/shapes."""
    cfg = ARCHS[arch]
    width, layers = cfg['width'], cfg['layers']
    tokens = (INPUT_RESOLUTION // cfg['patch']) ** 2
    # timm mixer dims: tokens MLP = width/2, channels MLP = width*4
    tok_dim, ch_dim = width // 2, width * 4
    rng = np.random.RandomState(seed)

    def f32(*shape, scale=0.02):
        return (rng.randn(*shape) * scale).astype(np.float32)

    sd: Dict[str, np.ndarray] = {
        'stem.proj.weight': f32(width, 3, cfg['patch'], cfg['patch']),
        'stem.proj.bias': f32(width),
        'norm.weight': np.ones(width, np.float32),
        'norm.bias': np.zeros(width, np.float32),
    }
    for i in range(layers):
        b = f'blocks.{i}.'
        for n in ('norm1', 'norm2'):
            sd[b + n + '.weight'] = np.ones(width, np.float32)
            sd[b + n + '.bias'] = np.zeros(width, np.float32)
        sd[b + 'mlp_tokens.fc1.weight'] = f32(tok_dim, tokens)
        sd[b + 'mlp_tokens.fc1.bias'] = np.zeros(tok_dim, np.float32)
        sd[b + 'mlp_tokens.fc2.weight'] = f32(tokens, tok_dim)
        sd[b + 'mlp_tokens.fc2.bias'] = np.zeros(tokens, np.float32)
        sd[b + 'mlp_channels.fc1.weight'] = f32(ch_dim, width)
        sd[b + 'mlp_channels.fc1.bias'] = np.zeros(ch_dim, np.float32)
        sd[b + 'mlp_channels.fc2.weight'] = f32(width, ch_dim)
        sd[b + 'mlp_channels.fc2.bias'] = np.zeros(width, np.float32)
    if num_classes:
        sd['head.weight'] = f32(num_classes, width)
        sd['head.bias'] = np.zeros(num_classes, np.float32)
    return sd
