"""Shared helpers for building seeded random torch-layout state dicts.

Every native timm-layout family exposes ``init_state_dict`` so tests and
``allow_random_weights`` runs can exercise the exact checkpoint tree
without real weights. The conv/bn entry writers live here once so all
families seed the same numeric regime (BN stats deliberately non-trivial
— fresh mean=0/var=1 would hide transplant bugs in those tensors).
"""
from __future__ import annotations

from typing import Dict, Optional

import numpy as np


class SeedWriter:
    """Writes torch-named conv / batch-norm entries into a state dict."""

    def __init__(self, sd: Dict[str, np.ndarray], rng: np.random.RandomState,
                 conv_scale: float = 0.1) -> None:
        self.sd, self.rng, self.conv_scale = sd, rng, conv_scale

    def conv(self, name: str, o: int, i: int, k: int,
             bias: bool = False, scale: Optional[float] = None) -> None:
        scale = self.conv_scale if scale is None else scale
        self.sd[f'{name}.weight'] = (
            self.rng.randn(o, i, k, k) * scale).astype(np.float32)
        if bias:
            self.sd[f'{name}.bias'] = (
                self.rng.randn(o).astype(np.float32) * 0.02)

    def dwconv(self, name: str, c: int, k: int) -> None:
        """Depthwise conv weight, torch layout (C, 1, k, k)."""
        self.sd[f'{name}.weight'] = (
            self.rng.randn(c, 1, k, k) * self.conv_scale).astype(np.float32)

    def bn(self, name: str, c: int) -> None:
        r = self.rng
        self.sd[f'{name}.weight'] = (r.rand(c) * 0.2 + 0.9).astype(np.float32)
        self.sd[f'{name}.bias'] = r.randn(c).astype(np.float32) * 0.02
        self.sd[f'{name}.running_mean'] = (r.randn(c) * 0.1).astype(np.float32)
        self.sd[f'{name}.running_var'] = (r.rand(c) + 0.5).astype(np.float32)

    def linear(self, name: str, o: int, i: int) -> None:
        self.sd[f'{name}.weight'] = (
            self.rng.randn(o, i) * 0.02).astype(np.float32)
        self.sd[f'{name}.bias'] = np.zeros(o, np.float32)
