"""Structured event log: the error/warn channel for every execution path.

The reference toolkit reports per-video failures with a bare
``print(traceback)`` — on STDOUT, interleaved with the feature stream
when ``on_extraction: print``, and invisible to any log pipeline. That
is exactly how the fork's ``KeyError: 'rgb'`` broke seven of eight
extractors silently. This module replaces those prints with one
``logging`` channel:

  * everything goes to **stderr** (stdout belongs to the feature stream
    — ``on_extraction: print`` stays byte-clean by construction);
  * every record carries structured context — video path, request id,
    stage — as ``key=value`` pairs in the message AND as attributes on
    the ``LogRecord`` (``record.video`` etc.), so both humans and log
    scrapers get the fields without regex archaeology;
  * failures keep the full traceback (``exc_info``), not a one-line
    summary of it.

``get_logger()`` returns the package logger with a stderr handler
attached exactly once; it propagates, so ``pytest``'s ``caplog`` and any
root configuration the embedding application installs see the records
too.
"""
from __future__ import annotations

import logging
import sys
import threading
from typing import Any, Optional

LOGGER_NAME = 'video_features_tpu'

_FORMAT = '%(asctime)s %(levelname)s %(name)s: %(message)s'

_configured = False
_configure_lock = threading.Lock()


class _StderrHandler(logging.StreamHandler):
    """A StreamHandler that resolves ``sys.stderr`` at EMIT time.

    Binding the stream at construction would pin whatever object
    ``sys.stderr`` was when the first event fired — under pytest's
    capsys (or any stderr redirection) that object is replaced per
    scope, and a pinned handler would write into a dead capture buffer
    for the rest of the process."""

    def __init__(self) -> None:
        super().__init__(sys.stderr)

    @property
    def stream(self):
        return sys.stderr

    @stream.setter
    def stream(self, value):                   # StreamHandler.__init__ sets it
        pass


def get_logger(subsystem: Optional[str] = None) -> logging.Logger:
    """The package logger (optionally ``video_features_tpu.<subsystem>``)
    with the stderr handler installed once, lazily."""
    global _configured
    root = logging.getLogger(LOGGER_NAME)
    if not _configured:
        # under the lock: two threads logging their first event
        # concurrently must not each install a handler (every record
        # would print twice for the rest of the process)
        with _configure_lock:
            if not _configured:
                # one stderr handler on the package root; never stdout
                # (the feature stream owns it). propagate stays True so
                # caplog and application-level logging config still
                # observe the records.
                handler = _StderrHandler()
                handler.setFormatter(logging.Formatter(_FORMAT))
                root.addHandler(handler)
                if root.level == logging.NOTSET:
                    root.setLevel(logging.INFO)
                _configured = True
    return root if subsystem is None else \
        logging.getLogger(f'{LOGGER_NAME}.{subsystem}')


def event(level: int, msg: str, subsystem: Optional[str] = None,
          exc_info: bool = False, **fields: Any) -> None:
    """Log one structured event: ``msg`` plus ``key=value`` context.

    ``fields`` append to the message in deterministic order and ride on
    the record (``record.<key>``) for structured handlers; None-valued
    fields are dropped so call sites can pass optional context
    (``request_id=getattr(task, 'request', None)``) unconditionally.
    """
    fields = {k: v for k, v in fields.items() if v is not None}
    if fields:
        ctx = ' '.join(f'{k}={v}' for k, v in fields.items())
        msg = f'{msg} [{ctx}]'
    get_logger(subsystem).log(level, msg, exc_info=exc_info, extra=fields)


def log_extraction_error(video_path, request_id: Optional[str] = None,
                         stage: Optional[str] = None) -> None:
    """The one per-video failure report (fault-isolation contract):
    every loop — per-video, cross-video windower, packed finalize, serve
    worker — emits the same shape, so operators and log scrapers see one
    format. Warning level (the worklist continues), full traceback, on
    stderr — never stdout, where ``on_extraction: print`` streams
    features."""
    event(logging.WARNING,
          'extraction failed; continuing with the next video',
          exc_info=True, video=str(video_path), request_id=request_id,
          stage=stage)


def log_batch_error(video_paths, valid: int, batch: int,
                    stage: Optional[str] = None) -> None:
    """Packed device-step failure: one batch failed — at dispatch
    (``stage='model'``: a geometry that won't compile/fit) or at the
    deferred sync point (``stage='d2h'``: an asynchronously raised
    execution fault surfacing in ``fetch_outputs``) — and exactly the
    videos it carries fail while the worklist continues
    (parallel/packing.py fault isolation)."""
    event(logging.WARNING,
          'packed device step failed; failing only the videos in this '
          'batch and continuing',
          exc_info=True, videos=sorted(str(p) for p in video_paths),
          valid=valid, batch=batch, stage=stage)
