"""Structured event log: the error/warn channel for every execution path.

The reference toolkit reports per-video failures with a bare
``print(traceback)`` — on STDOUT, interleaved with the feature stream
when ``on_extraction: print``, and invisible to any log pipeline. That
is exactly how the fork's ``KeyError: 'rgb'`` broke seven of eight
extractors silently. This module replaces those prints with one
``logging`` channel:

  * everything goes to **stderr** (stdout belongs to the feature stream
    — ``on_extraction: print`` stays byte-clean by construction);
  * every record carries structured context — video path, request id,
    stage — as ``key=value`` pairs in the message AND as attributes on
    the ``LogRecord`` (``record.video`` etc.), so both humans and log
    scrapers get the fields without regex archaeology;
  * failures keep the full traceback (``exc_info``), not a one-line
    summary of it.

``get_logger()`` returns the package logger with a stderr handler
attached exactly once; it propagates, so ``pytest``'s ``caplog`` and any
root configuration the embedding application installs see the records
too.
"""
from __future__ import annotations

import logging
import sys
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

LOGGER_NAME = 'video_features_tpu'

_FORMAT = '%(asctime)s %(levelname)s %(name)s: %(message)s'

_configured = False
_configure_lock = threading.Lock()

# -- event accounting (vft-flight) -------------------------------------------
# Every structured event is (a) counted per (level, subsystem) — the
# serve metrics surface mirrors these into the vft_events_total counter
# family, making error/warn RATES scrapeable instead of only greppable —
# and (b) appended to a bounded tail ring, the black box's
# (obs/blackbox.py) record of "what was the system saying right before
# it died". Both are process-wide like the logger itself; a deque append
# and a dict bump under one lock cost nothing against the logging call
# they ride on.
EVENT_TAIL_CAPACITY = 512

_event_lock = threading.Lock()
_event_counts: Dict[Tuple[str, str], int] = {}
_event_tail: 'deque' = deque(maxlen=EVENT_TAIL_CAPACITY)


def _record_event(level: int, msg: str, subsystem: Optional[str],
                  exc_text: Optional[str],
                  fields: Dict[str, Any]) -> None:
    levelname = logging.getLevelName(level)
    rec: Dict[str, Any] = {'t_unix_s': round(time.time(), 3),
                           'level': levelname,
                           'subsystem': subsystem or 'core',
                           'msg': msg}
    if fields:
        rec['fields'] = {k: str(v) for k, v in fields.items()}
    if exc_text:
        rec['exc'] = exc_text
    with _event_lock:
        key = (levelname, subsystem or 'core')
        _event_counts[key] = _event_counts.get(key, 0) + 1
        _event_tail.append(rec)


def event_counts() -> Dict[Tuple[str, str], int]:
    """Snapshot of lifetime event counts keyed ``(level, subsystem)`` —
    the source the serve registry's ``vft_events_total`` family mirrors."""
    with _event_lock:
        return dict(_event_counts)


def events_tail(limit: Optional[int] = None) -> List[Dict[str, Any]]:
    """The most recent structured events (newest last) — the black-box
    bundle's ``events.jsonl`` section."""
    with _event_lock:
        tail = list(_event_tail)
    return tail[-int(limit):] if limit is not None else tail


class _StderrHandler(logging.StreamHandler):
    """A StreamHandler that resolves ``sys.stderr`` at EMIT time.

    Binding the stream at construction would pin whatever object
    ``sys.stderr`` was when the first event fired — under pytest's
    capsys (or any stderr redirection) that object is replaced per
    scope, and a pinned handler would write into a dead capture buffer
    for the rest of the process."""

    def __init__(self) -> None:
        super().__init__(sys.stderr)

    @property
    def stream(self):
        return sys.stderr

    @stream.setter
    def stream(self, value):                   # StreamHandler.__init__ sets it
        pass


def get_logger(subsystem: Optional[str] = None) -> logging.Logger:
    """The package logger (optionally ``video_features_tpu.<subsystem>``)
    with the stderr handler installed once, lazily."""
    global _configured
    root = logging.getLogger(LOGGER_NAME)
    if not _configured:
        # under the lock: two threads logging their first event
        # concurrently must not each install a handler (every record
        # would print twice for the rest of the process)
        with _configure_lock:
            if not _configured:
                # one stderr handler on the package root; never stdout
                # (the feature stream owns it). propagate stays True so
                # caplog and application-level logging config still
                # observe the records.
                handler = _StderrHandler()
                handler.setFormatter(logging.Formatter(_FORMAT))
                root.addHandler(handler)
                if root.level == logging.NOTSET:
                    root.setLevel(logging.INFO)
                _configured = True
    return root if subsystem is None else \
        logging.getLogger(f'{LOGGER_NAME}.{subsystem}')


def event(level: int, msg: str, subsystem: Optional[str] = None,
          exc_info: bool = False, **fields: Any) -> None:
    """Log one structured event: ``msg`` plus ``key=value`` context.

    ``fields`` append to the message in deterministic order and ride on
    the record (``record.<key>``) for structured handlers; None-valued
    fields are dropped so call sites can pass optional context
    (``request_id=getattr(task, 'request', None)``) unconditionally.
    """
    fields = {k: v for k, v in fields.items() if v is not None}
    exc_text = None
    if exc_info:
        import traceback
        exc_text = traceback.format_exc(limit=30)
    _record_event(level, msg, subsystem, exc_text, fields)
    if fields:
        ctx = ' '.join(f'{k}={v}' for k, v in fields.items())
        msg = f'{msg} [{ctx}]'
    get_logger(subsystem).log(level, msg, exc_info=exc_info, extra=fields)


def log_extraction_error(video_path, request_id: Optional[str] = None,
                         stage: Optional[str] = None) -> None:
    """The one per-video failure report (fault-isolation contract):
    every loop — per-video, cross-video windower, packed finalize, serve
    worker — emits the same shape, so operators and log scrapers see one
    format. Warning level (the worklist continues), full traceback, on
    stderr — never stdout, where ``on_extraction: print`` streams
    features."""
    event(logging.WARNING,
          'extraction failed; continuing with the next video',
          exc_info=True, video=str(video_path), request_id=request_id,
          stage=stage)


def log_batch_error(video_paths, valid: int, batch: int,
                    stage: Optional[str] = None) -> None:
    """Packed device-step failure: one batch failed — at dispatch
    (``stage='model'``: a geometry that won't compile/fit) or at the
    deferred sync point (``stage='d2h'``: an asynchronously raised
    execution fault surfacing in ``fetch_outputs``) — and exactly the
    videos it carries fail while the worklist continues
    (parallel/packing.py fault isolation)."""
    event(logging.WARNING,
          'packed device step failed; failing only the videos in this '
          'batch and continuing',
          exc_info=True, videos=sorted(str(p) for p in video_paths),
          valid=valid, batch=batch, stage=stage)
