"""Unified metrics registry: counters / gauges / histograms + Prometheus text.

One process-wide (or per-server) registry replaces the hand-rolled
counter dicts that grew per subsystem (``serve/metrics.py``'s JSON doc,
the warm pool's ints, the cache store's ints). Series are identified by
``(name, labels)`` like Prometheus families: registering the same name
with different labels extends the family; re-registering an existing
series returns the SAME object, so independent call sites can grab a
counter by name without threading references around.

Rendering follows the Prometheus text exposition format 0.0.4 —
``# HELP`` / ``# TYPE`` once per family, one sample line per series,
histograms as cumulative ``_bucket{le=...}`` + ``_sum`` + ``_count`` —
so the serve metrics socket (``metrics_prom``) and the atomic
``*.prom`` file mirror scrape directly into a Prometheus/VictoriaMetrics
agent with no adapter. ``tools/``-free validity is pinned by
``tests/test_obs.py``'s line-grammar check.

Thread safety: every mutation takes the metric's own lock (one ``inc``
is a dict-free float add; histograms bisect a static bucket list). The
registry lock guards only (de)registration.
"""
from __future__ import annotations

import math
import threading
from bisect import bisect_left
from typing import Any, Dict, Iterable, List, Optional, Tuple

# default histogram buckets: request/stage latencies from sub-10ms cache
# hits up to multi-minute cold extractions
DEFAULT_BUCKETS = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5,
                   5.0, 10.0, 30.0, 60.0, 120.0, 300.0)

LabelPairs = Tuple[Tuple[str, str], ...]


def _label_key(labels: Optional[Dict[str, str]]) -> LabelPairs:
    return tuple(sorted((str(k), str(v))
                        for k, v in (labels or {}).items()))


def _fmt_value(v: float) -> str:
    if v != v:                                    # NaN
        return 'NaN'
    if v in (math.inf, -math.inf):
        return '+Inf' if v > 0 else '-Inf'
    if float(v).is_integer() and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


def _fmt_labels(pairs: LabelPairs, extra: str = '') -> str:
    parts = [f'{k}="{_escape(v)}"' for k, v in pairs]
    if extra:
        parts.append(extra)
    return '{' + ','.join(parts) + '}' if parts else ''


def _escape(v: str) -> str:
    return str(v).replace('\\', r'\\').replace('"', r'\"').replace('\n', r'\n')


def _escape_help(v: str) -> str:
    # HELP lines escape backslash and newline but NOT double quotes —
    # the exposition format 0.0.4 rule differs from label values
    return str(v).replace('\\', r'\\').replace('\n', r'\n')


class Counter:
    """Monotonic float counter."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._value = 0.0

    def inc(self, n: float = 1.0) -> None:
        if n < 0:
            raise ValueError(f'counters only go up; inc({n})')
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        with self._lock:
            return self._value

    def _samples(self, name: str, pairs: LabelPairs) -> List[str]:
        return [f'{name}{_fmt_labels(pairs)} {_fmt_value(self.value)}']


class Gauge:
    """Set-to-current-value metric (queue depth, pool size, hit rate)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._value = 0.0

    def set(self, v: float) -> None:
        with self._lock:
            self._value = float(v)

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self._value += n

    def dec(self, n: float = 1.0) -> None:
        with self._lock:
            self._value -= n

    @property
    def value(self) -> float:
        with self._lock:
            return self._value

    def _samples(self, name: str, pairs: LabelPairs) -> List[str]:
        return [f'{name}{_fmt_labels(pairs)} {_fmt_value(self.value)}']


class Histogram:
    """Cumulative-bucket histogram (Prometheus semantics)."""

    def __init__(self, buckets: Iterable[float] = DEFAULT_BUCKETS) -> None:
        self.buckets: Tuple[float, ...] = tuple(sorted(set(buckets)))
        if not self.buckets:
            raise ValueError('histogram needs at least one bucket bound')
        self._lock = threading.Lock()
        self._counts = [0] * (len(self.buckets) + 1)   # +1 = +Inf
        self._sum = 0.0
        self._count = 0

    def observe(self, v: float) -> None:
        v = float(v)
        i = bisect_left(self.buckets, v)
        with self._lock:
            self._counts[i] += 1
            self._sum += v
            self._count += 1

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            counts = list(self._counts)
            total, s = self._count, self._sum
        cum, out = 0, []
        for bound, c in zip(self.buckets, counts):
            cum += c
            out.append((bound, cum))
        return {'buckets': out, 'count': total, 'sum': s}

    def _samples(self, name: str, pairs: LabelPairs) -> List[str]:
        snap = self.snapshot()
        lines = []
        for bound, cum in snap['buckets']:
            le = 'le="%s"' % _fmt_value(bound)
            lines.append(f'{name}_bucket{_fmt_labels(pairs, le)} {cum}')
        inf = 'le="+Inf"'
        lines.append(f'{name}_bucket{_fmt_labels(pairs, inf)} '
                     f'{snap["count"]}')
        lines.append(f'{name}_sum{_fmt_labels(pairs)} '
                     f'{_fmt_value(snap["sum"])}')
        lines.append(f'{name}_count{_fmt_labels(pairs)} {snap["count"]}')
        return lines


_TYPE_NAMES = {Counter: 'counter', Gauge: 'gauge', Histogram: 'histogram'}


class MetricsRegistry:
    """Named families of (labels → metric) with Prometheus rendering."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        # name → {'type', 'help', 'series': {label_pairs: metric}}
        self._families: 'Dict[str, Dict[str, Any]]' = {}

    def _get(self, cls, name: str, help: str,
             labels: Optional[Dict[str, str]], **kwargs):
        pairs = _label_key(labels)
        with self._lock:
            fam = self._families.get(name)
            if fam is None:
                fam = self._families[name] = {
                    'type': _TYPE_NAMES[cls], 'help': help, 'series': {}}
            elif fam['type'] != _TYPE_NAMES[cls]:
                raise ValueError(
                    f'metric {name!r} already registered as {fam["type"]}')
            metric = fam['series'].get(pairs)
            if metric is None:
                metric = fam['series'][pairs] = cls(**kwargs)
            if help and not fam['help']:
                fam['help'] = help
            return metric

    def counter(self, name: str, help: str = '',
                labels: Optional[Dict[str, str]] = None) -> Counter:
        return self._get(Counter, name, help, labels)

    def gauge(self, name: str, help: str = '',
              labels: Optional[Dict[str, str]] = None) -> Gauge:
        return self._get(Gauge, name, help, labels)

    def histogram(self, name: str, help: str = '',
                  labels: Optional[Dict[str, str]] = None,
                  buckets: Iterable[float] = DEFAULT_BUCKETS) -> Histogram:
        return self._get(Histogram, name, help, labels, buckets=buckets)

    def collect(self) -> Dict[str, Any]:
        """JSON-able snapshot: name → [{labels, value | histogram}]."""
        with self._lock:
            families = {name: (fam['type'],
                               list(fam['series'].items()))
                        for name, fam in self._families.items()}
        out: Dict[str, Any] = {}
        for name, (mtype, series) in families.items():
            rows = []
            for pairs, metric in series:
                row: Dict[str, Any] = {'labels': dict(pairs)}
                if mtype == 'histogram':
                    row.update(metric.snapshot())
                else:
                    row['value'] = metric.value
                rows.append(row)
            out[name] = {'type': mtype, 'series': rows}
        return out

    def render(self) -> str:
        """Prometheus text exposition format 0.0.4 (trailing newline)."""
        with self._lock:
            families = [(name, fam['type'], fam['help'],
                         list(fam['series'].items()))
                        for name, fam in sorted(self._families.items())]
        lines: List[str] = []
        for name, mtype, help_text, series in families:
            lines.append(f'# HELP {name} '
                         f'{_escape_help(help_text or name.replace("_", " "))}')
            lines.append(f'# TYPE {name} {mtype}')
            for pairs, metric in series:
                lines.extend(metric._samples(name, pairs))
        return '\n'.join(lines) + '\n'


#: the process-wide default registry (CLI-path metrics); servers build
#: their own so concurrent instances in one process stay isolated
REGISTRY = MetricsRegistry()
