"""Declarative SLOs + multi-window burn-rate alerts over the registry.

The metrics registry answers "what is the latency histogram NOW"; an
operator (and ROADMAP item 2's elastic membership) needs the derived
question answered: "are we burning error budget fast enough to care?"
This module is that derivation, kept deliberately dependency-free and
registry-driven so the SAME evaluator serves both deployment shapes:

  * a serve daemon points it at its own request families
    (``vft_serve_request_latency_seconds`` /
    ``vft_serve_requests_total`` — the defaults);
  * the fleet router points it at its routed-request families
    (``vft_fleet_request_latency_seconds`` /
    ``vft_fleet_requests_total``), making the router's ``/metrics`` the
    one place fleet-wide saturation is visible.

Objectives are two declarative knobs:

  * ``slo_latency_p99_s=T`` — "99% of requests complete within T
    seconds". The error budget is the 1% of requests allowed over T;
    the burn rate is (observed fraction over T) / 0.01, computed from
    the cumulative histogram buckets (the smallest bucket bound >= T
    stands in for T — conservative, never optimistic, and bucket-exact
    so no samples need retaining).
  * ``slo_availability=A`` — e.g. 0.999: the failed-request fraction's
    budget is (1 - A); the burn rate is (failed / total) / (1 - A).

Evaluation is the multi-window scheme (SRE workbook, "alerting on
SLOs"): each :meth:`SloEvaluator.tick` snapshots the cumulative
counters, and the burn rate over each window (5m and 1h by default) is
the delta between now and the sample closest to the window start. An
alert FIRES only when every window burns above the threshold
(default 14.4x — the fast-burn page: at that rate a 30-day budget is
gone in ~2 days); the long window keeps a brief spike from paging, the
short window makes the alert reset quickly once the burn stops. Ticks
piggyback on metrics assembly (every scrape/mirror is a sample), so
there is no extra thread to leak.

Outputs, all derived on tick: ``vft_slo_*`` gauges on the SAME
registry (``…_burn_rate{window=}``, ``…_alert{slo=}``), a structured
``obs/events`` record on every alert transition, and the ``slo``
section of the metrics document (:meth:`stats` — the machine-readable
saturation signal; ``tools/slo_report.py`` renders it).
"""
from __future__ import annotations

import logging
import threading
import time
from bisect import bisect_left
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

from video_features_tpu.obs.metrics import MetricsRegistry

# multi-window defaults: the short window drives fast firing/reset, the
# long window keeps one spike from paging
DEFAULT_WINDOWS_S = (300.0, 3600.0)

# burn-rate alert threshold (applies to EVERY window at once): 14.4x is
# the classic fast-burn page — a 30-day budget exhausted in ~2 days
DEFAULT_BURN_ALERT = 14.4

# the p99 objective's error budget: the fraction of requests allowed
# over the latency threshold
_LATENCY_BUDGET = 0.01


def disabled_stats() -> Dict[str, Any]:
    """The stable shape the metrics document carries when no objective
    is configured — scrapers see one schema either way (same policy as
    the ``watchdog`` / ``index`` sections)."""
    return {'enabled': False, 'objectives': {}, 'burn_rates': {},
            'alerts': {}, 'alerts_firing': 0, 'alerts_total': 0}


def window_label(seconds: float) -> str:
    """``300 -> '5m'``, ``3600 -> '1h'`` — the ``window=`` label value
    (dashboards key on these, so they must be stable and human)."""
    s = int(seconds)
    if s % 3600 == 0:
        return f'{s // 3600}h'
    if s % 60 == 0:
        return f'{s // 60}m'
    return f'{s}s'


class SloEvaluator:
    """Burn-rate evaluation of declarative objectives over one registry.

    Reads the cumulative latency histogram and outcome counters the
    serving path already maintains (no second set of probes to drift);
    every :meth:`tick` appends a timestamped snapshot, prunes history
    past the longest window, and re-derives per-window burn rates and
    alert states. Thread-safe; ``clock`` is injectable so tests can
    walk time instead of sleeping through a 5-minute window.
    """

    def __init__(self, registry: MetricsRegistry,
                 latency_p99_s: Optional[float] = None,
                 availability: Optional[float] = None,
                 latency_family: str = 'vft_serve_request_latency_seconds',
                 outcome_family: str = 'vft_serve_requests_total',
                 windows_s: Tuple[float, ...] = DEFAULT_WINDOWS_S,
                 burn_alert: float = DEFAULT_BURN_ALERT,
                 clock=time.monotonic) -> None:
        if latency_p99_s is None and availability is None:
            raise ValueError('an SloEvaluator needs at least one '
                             'objective (slo_latency_p99_s= and/or '
                             'slo_availability=)')
        if latency_p99_s is not None and float(latency_p99_s) <= 0:
            raise ValueError(f'slo_latency_p99_s must be > 0; '
                             f'got {latency_p99_s}')
        if availability is not None \
                and not (0 < float(availability) < 1):
            raise ValueError(f'slo_availability must be in (0, 1), e.g. '
                             f'0.999; got {availability}')
        self.registry = registry
        self.latency_p99_s = (None if latency_p99_s is None
                              else float(latency_p99_s))
        self.availability = (None if availability is None
                             else float(availability))
        self.windows_s = tuple(sorted(float(w) for w in windows_s))
        self.burn_alert = float(burn_alert)
        self._clock = clock
        self._lock = threading.Lock()
        # the families this evaluator derives from — registering here
        # returns the SAME series the serving path writes (re-register
        # semantics), or a zero series it grows into on a fresh router
        self._hist = registry.histogram(latency_family)
        self._completed = registry.counter(
            outcome_family, labels={'outcome': 'completed'})
        self._failed = registry.counter(
            outcome_family, labels={'outcome': 'failed'})
        # (t, requests_total, over_threshold, completed, failed) —
        # pruned to the longest window (plus one baseline sample at or
        # before the window start, so deltas span the full window)
        self._samples: 'deque[Tuple[float, int, int, float, float]]' \
            = deque()
        self._alerting: Dict[str, bool] = {}
        if self.latency_p99_s is not None:
            self._alerting['latency_p99'] = False
        if self.availability is not None:
            self._alerting['availability'] = False
        self._alerts_total = registry.counter(
            'vft_slo_alerts_total',
            'burn-rate alert FIRING transitions since start')
        # objective values as gauges: the alert rule's parameters travel
        # with the data they gate
        if self.latency_p99_s is not None:
            registry.gauge(
                'vft_slo_latency_threshold_seconds',
                'the slo_latency_p99_s objective').set(self.latency_p99_s)
        if self.availability is not None:
            registry.gauge(
                'vft_slo_availability_target',
                'the slo_availability objective').set(self.availability)

    # -- sampling ------------------------------------------------------------

    def _over_threshold(self) -> Tuple[int, int]:
        """(requests over the latency threshold, total observed) from
        the cumulative buckets: total minus the cumulative count at the
        smallest bound >= the threshold (conservative — a request in
        the straddling bucket counts as over)."""
        snap = self._hist.snapshot()
        total = snap['count']
        if self.latency_p99_s is None or not snap['buckets']:
            return 0, total
        bounds = [b for b, _ in snap['buckets']]
        i = bisect_left(bounds, self.latency_p99_s)
        within = snap['buckets'][i][1] if i < len(bounds) else \
            snap['buckets'][-1][1]
        if i >= len(bounds):
            # threshold beyond the last bound: only +Inf-bucket samples
            # are provably over, and those are total - last cumulative
            within = snap['buckets'][-1][1]
        return max(0, total - within), total

    def tick(self) -> Dict[str, Any]:
        """Take one snapshot, re-derive burn rates/alerts, update the
        ``vft_slo_*`` gauges, and return the ``slo`` document section."""
        now = self._clock()
        over, total = self._over_threshold()
        completed, failed = self._completed.value, self._failed.value
        with self._lock:
            self._samples.append((now, total, over, completed, failed))
            horizon = now - self.windows_s[-1]
            # keep ONE sample at or before the horizon as the baseline
            while len(self._samples) > 1 and self._samples[1][0] <= horizon:
                self._samples.popleft()
            burn_latency: Dict[str, float] = {}
            burn_avail: Dict[str, float] = {}
            for w in self.windows_s:
                base = self._baseline_locked(now - w)
                d_total = total - base[1]
                d_over = over - base[2]
                d_req = (completed - base[3]) + (failed - base[4])
                d_failed = failed - base[4]
                label = window_label(w)
                if self.latency_p99_s is not None:
                    frac = (d_over / d_total) if d_total > 0 else 0.0
                    burn_latency[label] = frac / _LATENCY_BUDGET
                if self.availability is not None:
                    budget = 1.0 - self.availability
                    frac = (d_failed / d_req) if d_req > 0 else 0.0
                    burn_avail[label] = frac / budget
            transitions = self._update_alerts_locked(
                burn_latency, burn_avail)
            alerts = dict(self._alerting)
        # gauges + events OUTSIDE the lock: registry/event sinks take
        # their own locks
        for label, burn in burn_latency.items():
            self.registry.gauge(
                'vft_slo_latency_burn_rate',
                'latency error-budget burn rate per window '
                '(1.0 = exactly on budget)',
                labels={'window': label}).set(burn)
        for label, burn in burn_avail.items():
            self.registry.gauge(
                'vft_slo_availability_burn_rate',
                'availability error-budget burn rate per window',
                labels={'window': label}).set(burn)
        for slo, firing in alerts.items():
            self.registry.gauge(
                'vft_slo_alert',
                '1 while the multi-window burn-rate alert fires',
                labels={'slo': slo}).set(1 if firing else 0)
        for slo, firing, burns in transitions:
            if firing:
                self._alerts_total.inc()
            from video_features_tpu.obs.events import event
            event(logging.WARNING if firing else logging.INFO,
                  f'SLO {slo} burn-rate alert '
                  f'{"FIRING" if firing else "resolved"}',
                  subsystem='slo', slo=slo,
                  burn_rates={k: round(v, 3) for k, v in burns.items()},
                  threshold=self.burn_alert)
        return {
            'enabled': True,
            'objectives': {'latency_p99_s': self.latency_p99_s,
                           'availability': self.availability},
            'windows_s': list(self.windows_s),
            'burn_alert_threshold': self.burn_alert,
            'burn_rates': {
                **({'latency': burn_latency} if burn_latency else {}),
                **({'availability': burn_avail} if burn_avail else {}),
            },
            'alerts': alerts,
            'alerts_firing': sum(1 for f in alerts.values() if f),
            'alerts_total': int(self._alerts_total.value),
        }

    # stats() is the metrics-document spelling: every assembly is a tick,
    # so scraping IS sampling and no background thread is needed
    stats = tick

    # -- internals -----------------------------------------------------------

    def _baseline_locked(self, t_start: float
                         ) -> Tuple[float, int, int, float, float]:
        """The latest sample at or before ``t_start`` (the window
        start), else the oldest held — a young process reports burn
        over the history it actually has rather than zero."""
        base = self._samples[0]
        for s in self._samples:
            if s[0] <= t_start:
                base = s
            else:
                break
        return base

    def _update_alerts_locked(self, burn_latency: Dict[str, float],
                              burn_avail: Dict[str, float]
                              ) -> List[Tuple[str, bool, Dict[str, float]]]:
        """Multi-window AND: fire only when EVERY window burns over the
        threshold. Returns the transitions to report (outside the
        lock)."""
        transitions: List[Tuple[str, bool, Dict[str, float]]] = []
        for slo, burns in (('latency_p99', burn_latency),
                           ('availability', burn_avail)):
            if slo not in self._alerting:
                continue
            firing = bool(burns) and all(b > self.burn_alert
                                         for b in burns.values())
            if firing != self._alerting[slo]:
                self._alerting[slo] = firing
                transitions.append((slo, firing, dict(burns)))
        return transitions
