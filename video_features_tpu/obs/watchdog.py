"""Stall watchdog: liveness detection for the execution workers.

The serve daemon's failure modes split cleanly: crashes (the worker's
exception handler + the black box own those) and WEDGES — a worker that
still holds queued work but has stopped advancing (a decoder hung on a
truncated file, a device call that never returns, a farm ring nobody
drains). Nothing in the ``vft_*`` surface distinguishes "idle because
empty" from "stuck with work"; ROADMAP item 3's autoscaling needs
exactly that signal.

This module keeps a **progress ledger**: per worker (serve warm-pool
entries and farm decode workers alike), the last time ANY canonical
stage advanced and which stage it was, plus how much work the worker
currently holds. A monitor thread trips when a worker has held pending
work for longer than ``watchdog_stall_s`` without a single stage
advance; a trip

  * emits a structured ERROR event (worker, stage, pending, stalled
    seconds),
  * increments ``vft_watchdog_stalls_total{stage}`` on the owning
    registry (the stage label is the LAST stage that advanced — where
    progress stopped *after*; ``admission`` when work was queued but
    nothing ever started),
  * fires ``on_stall`` (the serve daemon wires the black box here).

A tripped worker does not re-trip until it advances again (one wedge,
one page — not one page per monitor tick); an idle worker with an empty
queue never trips at all. Advances are fed from the Tracer's
``progress`` hook, so the ledger rides the SAME instrumentation sites
as the stage table and the span timeline — no fourth set of probes.
"""
from __future__ import annotations

import logging
import threading
import time
from typing import Any, Callable, Dict, List, Optional

# stage label for "work queued, nothing ever advanced"
STAGE_NOT_STARTED = 'admission'


class _WorkerLedger:
    __slots__ = ('last_advance', 'last_stage', 'pending', 'stalled')

    def __init__(self, now: float) -> None:
        self.last_advance = now
        self.last_stage = STAGE_NOT_STARTED
        self.pending = 0
        self.stalled = False


class StallWatchdog:
    """Progress ledger + monitor thread (see module docstring)."""

    def __init__(self, stall_s: float,
                 on_stall: Optional[Callable[[Dict[str, Any]], None]] = None,
                 registry=None,
                 interval_s: Optional[float] = None,
                 clock: Callable[[], float] = time.monotonic) -> None:
        self.stall_s = float(stall_s)
        if self.stall_s <= 0:
            raise ValueError(f'stall_s must be > 0; got {stall_s}')
        self.on_stall = on_stall
        self._clock = clock
        self.interval_s = (float(interval_s) if interval_s is not None
                           else max(0.05, min(self.stall_s / 4.0, 5.0)))
        self._registry = registry
        self._lock = threading.Lock()
        self._workers: Dict[str, _WorkerLedger] = {}
        self.stalls_total = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- ledger feeds (hot-ish paths: one lock, no allocation) ---------------

    def advance(self, worker: str, stage: str) -> None:
        """A canonical stage made progress for ``worker`` (fed from the
        Tracer ``progress`` hook — every timed stage completion)."""
        now = self._clock()
        with self._lock:
            rec = self._workers.get(worker)
            if rec is None:
                rec = self._workers[worker] = _WorkerLedger(now)
            rec.last_advance = now
            rec.last_stage = stage
            rec.stalled = False

    def set_pending(self, worker: str, pending: int) -> None:
        """How much queued-or-in-flight work ``worker`` holds. The
        0 → positive edge resets the advance clock: a worker idle for an
        hour must get a full ``stall_s`` after NEW work arrives, not an
        instant trip."""
        now = self._clock()
        with self._lock:
            rec = self._workers.get(worker)
            if rec is None:
                rec = self._workers[worker] = _WorkerLedger(now)
            if pending > 0 and rec.pending == 0:
                rec.last_advance = now
                rec.stalled = False
            rec.pending = int(pending)

    def forget(self, worker: str) -> None:
        """Drop a retired worker's row (pool eviction/crash retirement —
        the ledger must not grow with lifetime churn)."""
        with self._lock:
            self._workers.pop(worker, None)

    def forget_prefix(self, prefix: str) -> None:
        """Drop every row under ``prefix`` — a retired serve worker
        takes its farm sub-rows (``label/farm-wN``) with it."""
        with self._lock:
            for key in [w for w in self._workers
                        if w.startswith(prefix)]:
                del self._workers[key]

    # -- monitoring ----------------------------------------------------------

    def check(self, now: Optional[float] = None) -> List[Dict[str, Any]]:
        """One monitor pass; returns (and reports) the stalls it fired.
        Public so tests and embedders can drive it without the thread."""
        if now is None:
            now = self._clock()
        fired: List[Dict[str, Any]] = []
        with self._lock:
            for worker, rec in self._workers.items():
                if rec.pending <= 0 or rec.stalled:
                    continue
                stalled_for = now - rec.last_advance
                if stalled_for < self.stall_s:
                    continue
                rec.stalled = True
                self.stalls_total += 1
                fired.append({'worker': worker,
                              'stage': rec.last_stage,
                              'pending': rec.pending,
                              'stalled_s': round(stalled_for, 3)})
        for info in fired:
            self._report(info)
        return fired

    def _report(self, info: Dict[str, Any]) -> None:
        from video_features_tpu.obs.events import event
        event(logging.ERROR,
              'watchdog: worker stalled with queued work',
              subsystem='watchdog', worker=info['worker'],
              stage=info['stage'], pending=info['pending'],
              stalled_s=info['stalled_s'])
        if self._registry is not None:
            try:
                self._registry.counter(
                    'vft_watchdog_stalls_total',
                    'stage-stall trips: a worker held queued work past '
                    'watchdog_stall_s without a stage advance',
                    labels={'stage': info['stage']}).inc()
            except Exception:
                # vft-lint: ok=swallowed-exception — the stall is
                # already reported through the event above; a metrics
                # bump must not break the monitor thread
                pass
        if self.on_stall is not None:
            try:
                self.on_stall(info)
            except Exception:
                # vft-lint: ok=swallowed-exception — the black-box hook
                # failing must not kill the watchdog (the event above
                # already reported the stall itself)
                event(logging.WARNING, 'watchdog on_stall hook failed',
                      subsystem='watchdog', exc_info=True,
                      worker=info['worker'])

    def snapshot(self) -> Dict[str, Any]:
        """The metrics-document view: per-worker last stage / seconds
        since advance / pending, plus the lifetime trip count."""
        now = self._clock()
        with self._lock:
            workers = {
                w: {'stage': rec.last_stage,
                    'pending': rec.pending,
                    'since_advance_s': round(now - rec.last_advance, 3),
                    'stalled': rec.stalled}
                for w, rec in self._workers.items()}
            return {'enabled': True, 'stall_s': self.stall_s,
                    'stalls_total': self.stalls_total,
                    'workers': workers}

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> 'StallWatchdog':
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(target=self._run,
                                        name='vft-watchdog', daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(self.interval_s + 1.0)

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.check()
            except Exception:
                # vft-lint: ok=swallowed-exception — one broken pass
                # must not end liveness monitoring for the daemon's
                # lifetime; the next tick retries
                pass
