"""Request-scoped trace context (vft-flight): one trace_id end to end.

The span timeline (obs/spans) and the stage table answer "what happened
WHEN" for one *run*; this module gives every *request* an identity that
survives the run's seams — accepted from a W3C ``traceparent`` header at
ingress (minted when absent), carried on the loopback protocol, stamped
onto every :class:`parallel.packing.VideoTask`, threaded through the
packed scheduler's span attrs, and shipped across the decode-farm
process boundary — so "show me everything that happened to request
r-123" is one filter over the merged timeline
(``GET /v1/requests/<id>/trace``, ``tools/trace_view.py --trace-id``).

Identifiers follow the W3C Trace Context recommendation: a 16-byte
``trace_id`` and an 8-byte ``span_id``, lowercase hex, all-zero values
invalid. Only the ``traceparent`` header is consumed (``tracestate`` is
vendor baggage this system neither reads nor forwards); an unparseable
header degrades to a freshly minted context — a malformed client header
must never fail admission.
"""
from __future__ import annotations

import os
import re
from typing import Any, Dict, Optional

# version "00" traceparent: version-trace_id-parent_id-flags
_TRACEPARENT_RE = re.compile(
    r'^([0-9a-f]{2})-([0-9a-f]{32})-([0-9a-f]{16})-([0-9a-f]{2})$')


class TraceContext:
    """One (trace_id, span_id) pair. Immutable by convention: derive
    child spans with :meth:`child` rather than mutating in place — the
    parent's span_id keeps naming the parent."""

    __slots__ = ('trace_id', 'span_id')

    def __init__(self, trace_id: str, span_id: str) -> None:
        self.trace_id = trace_id
        self.span_id = span_id

    def child(self) -> 'TraceContext':
        """A new span under the same trace (per-video task spans under
        one request's trace)."""
        return TraceContext(self.trace_id, new_span_id())

    def traceparent(self) -> str:
        """The W3C wire form (sampled flag always set — this system
        records everything it traces)."""
        return f'00-{self.trace_id}-{self.span_id}-01'

    def attrs(self) -> Dict[str, str]:
        """Span-args projection: the two keys every trace-scoped span
        carries (``tools/trace_view.py`` validates the pairing)."""
        return {'trace_id': self.trace_id, 'span_id': self.span_id}

    def __repr__(self) -> str:
        return f'TraceContext({self.traceparent()!r})'


def new_trace_id() -> str:
    """16 random bytes, lowercase hex; never all-zero (invalid per W3C)."""
    while True:
        tid = os.urandom(16).hex()
        if tid != '0' * 32:
            return tid


def new_span_id() -> str:
    """8 random bytes, lowercase hex; never all-zero."""
    while True:
        sid = os.urandom(8).hex()
        if sid != '0' * 16:
            return sid


def mint() -> TraceContext:
    """A fresh root context (no inbound ``traceparent``)."""
    return TraceContext(new_trace_id(), new_span_id())


def parse_traceparent(header: Optional[str]) -> Optional[TraceContext]:
    """The context a W3C ``traceparent`` header carries, or None when
    the header is absent/malformed/all-zero (callers mint instead —
    accepting garbage ids would poison every downstream filter)."""
    if not header or not isinstance(header, str):
        return None
    m = _TRACEPARENT_RE.match(header.strip().lower())
    if m is None:
        return None
    version, trace_id, span_id = m.group(1), m.group(2), m.group(3)
    if version == 'ff' or trace_id == '0' * 32 or span_id == '0' * 16:
        return None
    # the inbound parent becomes OUR parent: keep its trace, start a new
    # span under it so this hop is distinguishable from the caller's
    return TraceContext(trace_id, new_span_id())


def accept_traceparent(header: Optional[str]) -> TraceContext:
    """Parse-or-mint: the ingress/admission entry points always leave
    with a valid context."""
    return parse_traceparent(header) or mint()


def trace_attrs(task: Any) -> Dict[str, str]:
    """The span-args for a task-carrying instrumentation site: the
    task's :class:`TraceContext` attrs, or ``{}`` for legacy/CLI tasks
    without one — call sites can splat it unconditionally."""
    ctx = getattr(task, 'trace', None)
    return ctx.attrs() if ctx is not None else {}


def trace_ids_of(tasks: Any) -> list:
    """The sorted distinct trace ids an iterable of tasks carries —
    batch-level spans (pack/model/d2h) serve several requests at once
    and annotate the SET so a per-request trace filter still finds the
    shared work. One implementation for every batch-span site."""
    return sorted({t.trace.trace_id for t in tasks
                   if getattr(t, 'trace', None) is not None})
