"""Flight recorder: one coherent telemetry layer for all three paths.

The toolkit grew three execution paths (one-shot CLI, cross-video
packing, the warm-pool serve daemon) plus a content cache, and each grew
its own telemetry: ``utils/tracing.py`` aggregates stage wall-clock,
``serve/metrics.py`` hand-rolled a JSON dict, and failures went through
raw ``print``s — the reference's bare ``except``+print is exactly what
silently ate the ``KeyError: 'rgb'`` that broke seven of eight
extractors in the fork. This package unifies everything behind three
exports:

  * **Span timeline** (``obs.spans``): per-video / per-request span
    events, recorded by a low-overhead bounded ring buffer that the
    production :class:`utils.tracing.Tracer` feeds (the stage table is a
    view over the same events), exported as Chrome trace-event JSON
    viewable in Perfetto via the ``trace_out`` knob — all three paths.
  * **Metrics registry** (``obs.metrics``): counters / gauges /
    histograms with Prometheus text exposition; ``serve/metrics.py``'s
    ad-hoc dict is now a view over one registry, and the CLI writes a
    per-run JSON **run manifest** (``obs.manifest``) carrying config +
    weights fingerprints, the per-stage table, per-video outcomes,
    compile time, and XLA cost analysis per executable identity.
  * **Structured event log** (``obs.events``): a ``logging``-based
    error/warn channel (video path, request id, full traceback) that
    replaces the swallowed-error prints while keeping
    ``on_extraction: print`` stdout byte-clean — the feature stream owns
    stdout; telemetry owns stderr.

See ``docs/observability.md`` for the operator workflow.
"""
from video_features_tpu.obs.events import event, get_logger, log_extraction_error
from video_features_tpu.obs.metrics import (
    Counter, Gauge, Histogram, MetricsRegistry, REGISTRY,
)
from video_features_tpu.obs.spans import NULL_RECORDER, SpanRecorder

__all__ = [
    'Counter', 'Gauge', 'Histogram', 'MetricsRegistry', 'REGISTRY',
    'NULL_RECORDER', 'SpanRecorder',
    'event', 'get_logger', 'log_extraction_error',
]
