"""Per-run JSON run manifest: what ran, on what, and what it cost.

A benchmark number without its recipe is a rumor. The manifest is the
CLI's durable run record (``manifest_out=<path>``): one JSON document
carrying

  * the merged **config** plus the config / weights / run
    **fingerprints** (``cache/key.py`` — the same identities that key
    the content-addressed cache and config-aware resume, so a manifest
    provably names the recipe that produced a directory of features);
  * the aggregate per-**stage** table (``Tracer.report`` folded across
    every video with ``merge_reports`` — identical semantics to the
    serve metrics fleet view);
  * per-**video outcomes** (saved / skipped / cached / failed /
    printed), the honest completion record a 20K-video run needs;
  * **compile** wall time, captured from ``jax.monitoring``'s
    backend-compile duration events (the real XLA compile cost, not a
    first-call-minus-steady estimate);
  * **executables**: per executable identity (feature family × input
    geometry × dtype), the XLA ``cost_analysis`` FLOPs / bytes-accessed
    of the compiled step where the extractor's step function supports
    AOT lowering — the denominator for MFU math.

Collection is push-based: the extraction loops call ``video_done`` /
``fold_stages`` / ``note_executable`` as they go; ``write`` publishes
atomically. Every collector degrades to a no-op on failure — telemetry
must never fail a run.
"""
from __future__ import annotations

import threading
import time
from typing import Any, Dict, Mapping, Optional

from video_features_tpu.obs.spans import _jsonable
from video_features_tpu.utils.tracing import merge_reports

# jax.monitoring event keys that measure XLA compilation; matched by
# substring so minor renames across jax versions degrade to "unattributed"
# rather than KeyError
_COMPILE_EVENT_MARKERS = ('compile',)

_listener_lock = threading.Lock()
_listener_installed = False
_compile_events: Dict[str, Dict[str, float]] = {}


def _on_event_duration(name: str, secs: float, **kwargs) -> None:
    if not any(m in name for m in _COMPILE_EVENT_MARKERS):
        return
    with _listener_lock:
        rec = _compile_events.setdefault(name, {'count': 0, 'total_s': 0.0})
        rec['count'] += 1
        rec['total_s'] += float(secs)


def _install_compile_listener() -> None:
    """Register the jax.monitoring duration listener once per process.
    Listeners cannot be unregistered individually, so the manifest reads
    deltas against the snapshot taken at its construction."""
    global _listener_installed
    with _listener_lock:
        if _listener_installed:
            return
        _listener_installed = True
    try:
        import jax.monitoring
        jax.monitoring.register_event_duration_secs_listener(
            _on_event_duration)
    except Exception:
        # vft-lint: ok=swallowed-exception — telemetry never fails the
        # run: the manifest carries an empty compile section on runtimes
        # without jax.monitoring
        pass


def _compile_snapshot() -> Dict[str, Dict[str, float]]:
    with _listener_lock:
        return {k: dict(v) for k, v in _compile_events.items()}


def xla_cost_analysis(jitted, *args, **kwargs) -> Optional[Dict[str, float]]:
    """Best-effort FLOPs / bytes-accessed for one compiled executable.

    AOT-lowers ``jitted`` at the given abstract shapes — through the ONE
    ``jitted.lower(...)`` seam shared with the vft-programs contract
    checker (``analysis.programs.abstract_lowering``) — and reads the
    compiled module's ``cost_analysis()``. With the persistent
    compilation cache on (``enable_compilation_cache``) the second
    compile is a cache read, not a recompile. Returns None when the
    backend/step doesn't support it — cost analysis is an optimization
    report, never a requirement."""
    try:
        from video_features_tpu.analysis.programs import abstract_lowering
        cost = abstract_lowering(jitted, *args,
                                 **kwargs).compile().cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0] if cost else None
        if not cost:
            return None
        out = {}
        for key in ('flops', 'bytes accessed'):
            if key in cost:
                out[key.replace(' ', '_')] = float(cost[key])
        return out or None
    except Exception:
        # vft-lint: ok=swallowed-exception — cost analysis is an
        # optimization report, never a requirement (docstring contract)
        return None


class RunManifest:
    """Accumulates one run's outcomes/stages/costs; writes atomic JSON."""

    def __init__(self, args: Mapping[str, Any]) -> None:
        self._lock = threading.Lock()
        self._t0 = time.time()
        self._t0_perf = time.perf_counter()
        self.config: Dict[str, Any] = {k: _jsonable(v)
                                       for k, v in dict(args).items()}
        self.fingerprints = self._fingerprints(args)
        self.videos: Dict[str, Dict[str, Any]] = {}
        self.stages: Dict[str, Dict[str, float]] = {}
        self.executables: Dict[str, Dict[str, Any]] = {}
        self.farm: Dict[str, Any] = {}
        self.mesh: Dict[str, Any] = {}
        self.ingress: Dict[str, Any] = {}
        self.programs_lock: Dict[str, Any] = {}
        self.aot: Dict[str, Any] = {}
        self.index: Dict[str, Any] = {}
        self.slo: Dict[str, Any] = {}
        self._compile0 = _compile_snapshot()
        _install_compile_listener()

    @staticmethod
    def _fingerprints(args: Mapping[str, Any]) -> Dict[str, Optional[str]]:
        """The same identities the cache and config-aware resume key on;
        each is best-effort (e.g. an unreadable checkpoint path must not
        fail the manifest — the build itself reports that error)."""
        out: Dict[str, Optional[str]] = {
            'config': None, 'weights': None, 'run': None}
        from video_features_tpu.cache.key import (
            config_fingerprint, run_fingerprint, weights_fingerprint,
        )
        for name, fn in (('config', config_fingerprint),
                         ('weights', weights_fingerprint),
                         ('run', run_fingerprint)):
            try:
                out[name] = fn(args)
            except Exception:
                # vft-lint: ok=swallowed-exception — best-effort identity:
                # an unreadable checkpoint fails the BUILD with its own
                # error; the manifest records null rather than masking it
                pass
        return out

    # -- collectors (called from the extraction loops) -----------------------

    def video_done(self, video_path: str, outcome: str) -> None:
        """Record one video's terminal state (saved / skipped / cached /
        failed / printed / expired)."""
        with self._lock:
            self.videos[str(video_path)] = {'outcome': outcome}

    def fold_stages(self, report: Dict[str, Dict[str, float]]) -> None:
        """Merge one ``Tracer.report()`` into the run-wide stage table
        (the per-video loop resets its tracer per video; the manifest
        keeps the whole-run aggregate)."""
        if not report:
            return
        with self._lock:
            self.stages = merge_reports([self.stages, report])

    def note_executable(self, identity: str,
                        info: Dict[str, Any]) -> None:
        """Attach cost/compile info for one executable identity (feature
        family × batch geometry × dtype). Later notes for the same
        identity merge over earlier ones."""
        with self._lock:
            self.executables.setdefault(identity, {}).update(
                {k: _jsonable(v) for k, v in info.items()})

    def note_farm(self, info: Dict[str, Any]) -> None:
        """Record the decode farm's configuration + lifetime stats
        (worker count, ring sizing, windows/bytes shipped, respawns) for
        a farm-backed packed run; the section stays ``{}`` on in-process
        runs. Later notes merge over earlier ones (a serve worker's farm
        persists across request waves)."""
        with self._lock:
            self.farm.update({k: _jsonable(v) for k, v in info.items()})

    def note_ingress(self, info: Dict[str, Any]) -> None:
        """Record the ingress view of a run (per-tenant request/shed
        counts, live sessions) — written by tooling that drives a run
        THROUGH the front door (the ingress smoke/bench); the section
        stays ``{}`` on loopback/CLI runs. Later notes merge over
        earlier ones."""
        with self._lock:
            self.ingress.update({k: _jsonable(v) for k, v in info.items()})

    def note_programs_lock(self, info: Dict[str, Any]) -> None:
        """Record which PINNED programs this run's families map to:
        ``{family: {mesh<n>: {program: stablehlo_sha256}}}`` from the
        committed ``PROGRAMS.lock.json`` (``analysis/programs.py``) —
        so a production trace names exactly which contract-checked
        program ran, and a trace from BEFORE a re-pin is attributable
        to the old program. ``{}`` when the lock is absent or the
        family unpinned. Later notes merge over earlier ones."""
        with self._lock:
            self.programs_lock.update(
                {k: _jsonable(v) for k, v in info.items()})

    def note_aot(self, info: Dict[str, Any]) -> None:
        """Record the persistent-executable-store view of a run
        (``BaseExtractor.aot_snapshot``): which path each resident
        program took — ``'loaded'`` from the store vs ``'compiled'``
        fresh — with its StableHLO identity, so a run's manifest PROVES
        whether its boot was compile-free instead of implying it. The
        section stays ``{}`` without ``aot_enabled``. Later notes merge
        over earlier ones."""
        with self._lock:
            self.aot.update({k: _jsonable(v) for k, v in info.items()})

    def note_index(self, info: Dict[str, Any]) -> None:
        """Record the feature-index view of a run (``IndexService.stats``
        / ``IndexStore.stats``: rows, shards, ingest lag, query-program
        path) — written by runs that build or query the sharded
        embedding index (the offline ``index`` CLI, the index smoke);
        the section stays ``{}`` otherwise. Later notes merge over
        earlier ones."""
        with self._lock:
            self.index.update({k: _jsonable(v) for k, v in info.items()})

    def note_slo(self, info: Dict[str, Any]) -> None:
        """Record the SLO evaluation view (``SloEvaluator.stats()``:
        objectives, per-window burn rates, alert states) — written by
        servers running with ``slo_latency_p99_s=`` /
        ``slo_availability=``; the section stays ``{}`` otherwise.
        Later notes merge over earlier ones."""
        with self._lock:
            self.slo.update({k: _jsonable(v) for k, v in info.items()})

    def note_mesh(self, info: Dict[str, Any]) -> None:
        """Record the device mesh a mesh-sharded packed run executed on
        (``mesh_devices``, the (data, time) shape, per-device labels,
        per-device capacity vs global batch); the section stays ``{}``
        on single-device runs. Later notes merge over earlier ones."""
        with self._lock:
            self.mesh.update({k: _jsonable(v) for k, v in info.items()})

    # -- publication ---------------------------------------------------------

    def document(self) -> Dict[str, Any]:
        compile_now = _compile_snapshot()
        compile_delta: Dict[str, Dict[str, float]] = {}
        for name, rec in compile_now.items():
            base = self._compile0.get(name, {'count': 0, 'total_s': 0.0})
            d_count = rec['count'] - base['count']
            if d_count > 0:
                compile_delta[name] = {
                    'count': int(d_count),
                    'total_s': round(rec['total_s'] - base['total_s'], 6)}
        with self._lock:
            videos = {p: dict(v) for p, v in self.videos.items()}
            stages = {k: dict(v) for k, v in self.stages.items()}
            executables = {k: dict(v) for k, v in self.executables.items()}
            farm = dict(self.farm)
            mesh = dict(self.mesh)
            ingress = dict(self.ingress)
            programs_lock = dict(self.programs_lock)
            aot = dict(self.aot)
            index = dict(self.index)
            slo = dict(self.slo)
        outcomes: Dict[str, int] = {}
        for v in videos.values():
            outcomes[v['outcome']] = outcomes.get(v['outcome'], 0) + 1
        from video_features_tpu import __version__
        return {
            'schema': 'video_features_tpu.run_manifest/1',
            'version': __version__,
            'started_at_unix_s': round(self._t0, 3),
            'wall_s': round(time.perf_counter() - self._t0_perf, 3),
            'config': self.config,
            'fingerprints': self.fingerprints,
            'videos': videos,
            'outcomes': outcomes,
            'stages': stages,
            'compile': compile_delta,
            'executables': executables,
            # decode farm (farm/): config + lifetime stats for
            # farm-backed runs, {} on in-process decode
            'farm': farm,
            # mesh-sharded packed execution (mesh_devices > 1): the
            # device mesh the run executed on, {} single-device
            'mesh': mesh,
            # network front door (ingress/): per-tenant request/shed
            # view for runs driven through it, {} otherwise
            'ingress': ingress,
            # program contract lock (analysis/programs.py): the pinned
            # StableHLO hashes this run's families map to, {} when the
            # lock is absent or the family unpinned
            'programs_lock': programs_lock,
            # persistent executable store (aot/): which path each
            # program took (loaded vs compiled) + its StableHLO
            # identity, {} without aot_enabled
            'aot': aot,
            # sharded feature index (index/): rows/shards/ingest-lag +
            # query-program path for runs that build or query it, {}
            # otherwise
            'index': index,
            # SLO burn-rate evaluation (obs/slo): objectives + alert
            # states for runs with slo_* knobs, {} otherwise
            'slo': slo,
        }

    def write(self, path: str) -> str:
        import json
        import os

        from video_features_tpu.utils.output import atomic_write
        doc = self.document()
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        atomic_write(path, lambda f: f.write(
            json.dumps(doc, sort_keys=True, indent=1).encode('utf-8')))
        return path
