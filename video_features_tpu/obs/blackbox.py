"""Crash-dump black box: a bounded post-mortem bundle on the way down.

Span export fires on a clean ``trace_out=`` finish; the metrics mirror
rewrites on request completions. Neither helps when the daemon crashes
or wedges — exactly the moments an operator needs the flight recorder
most. This module dumps what the process knows RIGHT NOW into a
size-capped ``postmortem/`` directory:

  * ``meta.json``    — reason, wall/monotonic time, pid, caller extras
    (worker label, trace_id, watchdog ledger, ...);
  * ``spans.json``   — the merged recent span timeline (Chrome
    trace-event JSON, bounded per recorder via ``snapshot(limit=)`` so a
    dump never serializes the full 200K-event ring), viewable in
    Perfetto and validated by ``tools/trace_view.py``;
  * ``events.jsonl`` — the tail of the structured event log
    (``obs.events.events_tail``): what the system was saying before it
    died;
  * ``metrics.prom`` / ``metrics.json`` — a point-in-time metrics
    snapshot, when the owner wired one in;
  * ``manifest.json`` — the run-manifest fragment, when one exists.

Discipline: every write is atomic (a dump torn by the very crash it
documents must not masquerade as a complete bundle — ``meta.json`` is
written LAST and is the bundle's validity marker), every section is
best-effort (one broken collector must not lose the others), the whole
dump path never raises, dumps are rate-limited (a crash loop must not
spend its last breath writing the same bundle in a busy loop), and the
directory is GC'd oldest-bundle-first under ``postmortem_max_bytes``.
Nothing here runs on the request hot path: callers are crash handlers,
signal handlers, and the watchdog's monitor thread.
"""
from __future__ import annotations

import json
import os
import shutil
import threading
import time
from typing import Any, Callable, Dict, Iterable, List, Optional

# per-recorder span bound for one bundle: recent-history window, far
# beyond any single request, far below the full ring
SPAN_DUMP_LIMIT = 20_000

# default size cap for the whole postmortem/ dir (config OBS_DEFAULTS
# carries the knob; this is the fallback for direct construction)
DEFAULT_MAX_BYTES = 64 * (1 << 20)

# two dumps closer together than this collapse to one (crash loops,
# watchdog re-trips): the first bundle already holds the history
MIN_DUMP_INTERVAL_S = 2.0


class BlackBox:
    """One dump target: a directory, a byte budget, and the collectors
    that know where the telemetry lives."""

    def __init__(self, postmortem_dir: str,
                 max_bytes: Optional[int] = None,
                 recorders: Optional[Callable[[], Iterable]] = None,
                 metrics_fn: Optional[Callable[[], Any]] = None,
                 prom_fn: Optional[Callable[[], str]] = None,
                 manifest_fn: Optional[Callable[[], Dict]] = None,
                 min_interval_s: float = MIN_DUMP_INTERVAL_S) -> None:
        self.postmortem_dir = str(postmortem_dir)
        self.max_bytes = int(max_bytes if max_bytes is not None
                             else DEFAULT_MAX_BYTES)
        # collectors are CALLABLES, not snapshots: the black box holds
        # no live references of its own, it asks at dump time
        self._recorders = recorders
        self._metrics_fn = metrics_fn
        self._prom_fn = prom_fn
        self._manifest_fn = manifest_fn
        self.min_interval_s = float(min_interval_s)
        self._lock = threading.Lock()
        self._last_dump_t = 0.0
        self._seq = 0
        self.dumps = 0                # bundles written (telemetry)
        self.suppressed = 0           # rate-limited dump requests

    # -- the one entry point -------------------------------------------------

    def dump(self, reason: str, **extra: Any) -> Optional[str]:
        """Write one bundle; returns its directory path, or None when
        rate-limited or when even the meta write failed. NEVER raises —
        this runs on crash paths where a telemetry error must not mask
        (or re-enter) the original failure."""
        try:
            return self._dump(reason, extra)
        except Exception:
            # vft-lint: ok=swallowed-exception — the black box is the
            # last thing standing on a crash path: a dump failure has
            # nowhere better to go than stderr-best-effort below
            try:
                import logging

                from video_features_tpu.obs.events import event
                event(logging.ERROR, 'black-box dump failed',
                      subsystem='obs', exc_info=True, reason=reason)
            except Exception:
                # vft-lint: ok=swallowed-exception — even the reporter
                # failed; the process is likely dying, nothing to do
                pass
            return None

    def _dump(self, reason: str, extra: Dict[str, Any]) -> Optional[str]:
        now = time.monotonic()
        with self._lock:
            if now - self._last_dump_t < self.min_interval_s:
                self.suppressed += 1
                return None
            self._last_dump_t = now
            self._seq += 1
            seq = self._seq
        safe_reason = ''.join(c if c.isalnum() or c in '-_' else '_'
                              for c in str(reason))[:48] or 'unknown'
        stamp = time.strftime('%Y%m%dT%H%M%S', time.gmtime())
        bundle = os.path.join(self.postmortem_dir,
                              f'{stamp}.{seq:03d}-{safe_reason}')
        os.makedirs(bundle, exist_ok=True)

        sections: Dict[str, Any] = {}
        sections['spans'] = self._write_spans(bundle)
        sections['events'] = self._write_events(bundle)
        sections['metrics'] = self._write_metrics(bundle)
        sections['manifest'] = self._write_manifest(bundle)

        # meta LAST: its presence marks a complete bundle (validators
        # and the dryrun key on it)
        meta = {
            'schema': 'video_features_tpu.postmortem/1',
            'reason': str(reason),
            'time_unix_s': round(time.time(), 3),
            'pid': os.getpid(),
            'sections': sections,
        }
        if extra:
            from video_features_tpu.obs.spans import _jsonable
            meta['extra'] = {k: _jsonable(v) for k, v in extra.items()}
        self._write_json(os.path.join(bundle, 'meta.json'), meta)
        with self._lock:
            self.dumps += 1
        self._gc()
        import logging

        from video_features_tpu.obs.events import event
        event(logging.ERROR, 'black-box bundle written',
              subsystem='obs', reason=str(reason), path=bundle)
        return bundle

    # -- sections (each best-effort) -----------------------------------------

    @staticmethod
    def _write_json(path: str, doc: Any) -> None:
        from video_features_tpu.utils.output import atomic_write
        atomic_write(path, lambda f: f.write(
            json.dumps(doc, sort_keys=True).encode('utf-8')))

    def _write_spans(self, bundle: str) -> bool:
        if self._recorders is None:
            return False
        try:
            from video_features_tpu.obs.spans import merge_traces
            recorders = [r for r in self._recorders() if r is not None]
            if not recorders:
                return False
            doc = {
                'traceEvents': merge_traces(recorders,
                                            limit=SPAN_DUMP_LIMIT),
                'displayTimeUnit': 'ms',
                'otherData': {
                    'tool': 'video_features_tpu',
                    'recorders_merged': len(recorders),
                    'events_dropped': sum(r.dropped for r in recorders),
                },
            }
            self._write_json(os.path.join(bundle, 'spans.json'), doc)
            return True
        except Exception:
            # vft-lint: ok=swallowed-exception — best-effort section:
            # a broken recorder must not lose the events/metrics dumps
            return False

    def _write_events(self, bundle: str) -> bool:
        try:
            from video_features_tpu.obs.events import events_tail
            tail = events_tail()
            from video_features_tpu.utils.output import atomic_write
            payload = ''.join(json.dumps(rec, sort_keys=True) + '\n'
                              for rec in tail)
            atomic_write(os.path.join(bundle, 'events.jsonl'),
                         lambda f: f.write(payload.encode('utf-8')))
            return bool(tail)
        except Exception:
            # vft-lint: ok=swallowed-exception — best-effort section
            return False

    def _write_metrics(self, bundle: str) -> bool:
        wrote = False
        if self._metrics_fn is not None:
            try:
                self._write_json(os.path.join(bundle, 'metrics.json'),
                                 self._metrics_fn())
                wrote = True
            except Exception:
                # vft-lint: ok=swallowed-exception — best-effort section
                pass
        if self._prom_fn is not None:
            try:
                from video_features_tpu.utils.output import atomic_write
                text = self._prom_fn()
                atomic_write(os.path.join(bundle, 'metrics.prom'),
                             lambda f: f.write(text.encode('utf-8')))
                wrote = True
            except Exception:
                # vft-lint: ok=swallowed-exception — best-effort section
                pass
        return wrote

    def _write_manifest(self, bundle: str) -> bool:
        if self._manifest_fn is None:
            return False
        try:
            doc = self._manifest_fn()
            if not doc:
                return False
            self._write_json(os.path.join(bundle, 'manifest.json'), doc)
            return True
        except Exception:
            # vft-lint: ok=swallowed-exception — best-effort section
            return False

    # -- retention -----------------------------------------------------------

    def _gc(self) -> None:
        """Oldest-bundle-first GC under ``max_bytes``. The NEWEST bundle
        always survives (a cap smaller than one bundle must not erase
        the only evidence); bundle dirs sort chronologically by name
        (UTC stamp + sequence)."""
        try:
            root = self.postmortem_dir
            bundles = sorted(
                d for d in os.listdir(root)
                if os.path.isdir(os.path.join(root, d)))
        except OSError:
            return
        sizes: Dict[str, int] = {}
        for d in bundles:
            total = 0
            for base, _, files in os.walk(os.path.join(root, d)):
                for f in files:
                    try:
                        total += os.path.getsize(os.path.join(base, f))
                    except OSError:
                        pass
            sizes[d] = total
        overall = sum(sizes.values())
        for d in bundles[:-1]:                 # newest always survives
            if overall <= self.max_bytes:
                break
            shutil.rmtree(os.path.join(self.postmortem_dir, d),
                          ignore_errors=True)
            overall -= sizes[d]


def validate_bundle(bundle_dir: str) -> List[str]:
    """All violations found in one bundle (empty list = valid): meta
    present and well-formed, the spans section (when meta claims it)
    a valid trace-event document. Used by tests and the dryrun."""
    errors: List[str] = []
    meta_path = os.path.join(bundle_dir, 'meta.json')
    try:
        with open(meta_path) as f:
            meta = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        return [f'meta.json unreadable: {e}']
    if meta.get('schema') != 'video_features_tpu.postmortem/1':
        errors.append(f'bad schema {meta.get("schema")!r}')
    for key in ('reason', 'time_unix_s', 'pid', 'sections'):
        if key not in meta:
            errors.append(f'meta.json missing {key!r}')
    if (meta.get('sections') or {}).get('spans'):
        try:
            with open(os.path.join(bundle_dir, 'spans.json')) as f:
                doc = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            return errors + [f'spans.json unreadable: {e}']
        events = doc.get('traceEvents')
        if not isinstance(events, list):
            errors.append('spans.json: traceEvents is not a list')
        else:
            try:
                # the full trace-event grammar check when the repo's
                # tools/ are importable (tests, dryruns); the structural
                # check above still ran either way
                from tools.trace_view import validate_events
                errors += [f'spans.json: {e}'
                           for e in validate_events(events)]
            except ImportError:
                pass
    return errors


def install_signal_dump(blackbox: BlackBox, signals=None) -> None:
    """Chain a black-box dump onto fatal signals the process can still
    observe (SIGQUIT/SIGABRT — SIGKILL/SIGSEGV are not catchable from
    Python; the farm supervisor covers worker SIGKILLs from the parent
    side). Previously installed handlers still run afterwards, so this
    composes with the serve daemon's drain-on-SIGTERM."""
    import signal as signal_mod
    if signals is None:
        signals = tuple(
            s for s in (getattr(signal_mod, 'SIGQUIT', None),
                        getattr(signal_mod, 'SIGABRT', None))
            if s is not None)
    for sig in signals:
        prev = signal_mod.getsignal(sig)

        def _handler(signum, frame, _prev=prev):
            blackbox.dump(f'signal_{signum}')
            if callable(_prev):
                _prev(signum, frame)
            elif _prev == signal_mod.SIG_DFL:
                signal_mod.signal(signum, signal_mod.SIG_DFL)
                signal_mod.raise_signal(signum)

        try:
            signal_mod.signal(sig, _handler)
        except (OSError, ValueError):
            # vft-lint: ok=swallowed-exception — e.g. not the main
            # thread, or the platform refuses: the black box still fires
            # on crash/watchdog paths, signal coverage is best-effort
            pass
