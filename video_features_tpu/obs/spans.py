"""Span timeline: a low-overhead ring buffer of trace events + Perfetto export.

The per-stage :class:`utils.tracing.Tracer` answers "where does wall
time go in aggregate"; this module answers "what happened WHEN" — the
question that aggregate tables cannot: did decode stall behind a cold
geometry pool, did one request's save serialize behind another's device
step, how long did the lone odd-geometry window sit pooled before the
age-out flushed it. Every ``Tracer.stage``/``add`` call forwards its
(start, duration, attrs) here when a recorder is attached, so the stage
table and the timeline are two views over the SAME instrumentation
sites — there is no second set of probes to drift out of sync.

Recording is a bounded ``deque`` append under one lock (no allocation
beyond the event tuple, no I/O, no string formatting): cheap enough to
leave on for whole packed worklists and serve sessions. When the buffer
wraps, the OLDEST events drop and ``dropped`` counts them — a flight
recorder keeps the most recent window, and the export stamps how much
history was lost rather than silently truncating.

Export is Chrome trace-event JSON (the ``traceEvents`` array format):
load it at https://ui.perfetto.dev or ``chrome://tracing``. Complete
events (``ph='X'``) carry ``ts``/``dur`` in microseconds; instant events
(``ph='i'``) mark lifecycle points (video start/done, request admitted);
metadata events name the recording threads. ``tools/trace_view.py``
validates an export and prints a per-span summary.
"""
from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Any, Dict, Iterable, List, Optional

# one clock for every span so cross-thread timelines line up; the same
# clock Tracer uses, so durations agree with the stage table
CLOCK = time.perf_counter

# ring-buffer default: ~200K events ≈ a few tens of MB resident and far
# beyond a worklist run; serve daemons wrap and keep the recent window
DEFAULT_CAPACITY = 200_000


class SpanRecorder:
    """Thread-safe bounded recorder of span / instant trace events."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY,
                 enabled: bool = True) -> None:
        self.enabled = enabled
        self.capacity = int(capacity)
        self._lock = threading.Lock()
        # (ph, name, t_start_s, dur_s, tid, attrs|None, pid|None)
        # pid/tid overrides carry CROSS-PROCESS spans (decode-farm
        # workers): the worker measures, the parent records, and the
        # export shows the span under the worker's own pid lane
        self._events: 'deque' = deque(maxlen=self.capacity)
        self._appended = 0
        self._thread_names: Dict[int, str] = {}
        # epoch: perf_counter origin for ts=0 plus the wall clock at that
        # origin, so exports can be correlated with log timestamps
        self._t0 = CLOCK()
        self._wall0 = time.time()
        # incremental minimum of every start timestamp ever appended:
        # origin() must be O(1) — the /trace route calls it per recorder
        # on a request path, and a full O(capacity) ring scan under the
        # lock would stall the hot span-append path. Never reset on
        # ring eviction: a conservatively-old origin only shifts ts
        # later, it can never go negative.
        self._min_ts = self._t0

    # -- recording -----------------------------------------------------------

    def span(self, name: str, t_start: float, t_end: float,
             pid: Optional[int] = None, tid: Optional[int] = None,
             **attrs: Any) -> None:
        """Record one complete ('X') span. ``t_start``/``t_end`` are
        ``CLOCK()`` readings; ``attrs`` become the event's ``args``
        (video path, request id, trace/span ids, batch occupancy, ...).
        ``pid``/``tid`` override the recording process/thread identity —
        the decode farm records spans its WORKER processes measured
        (clock-calibrated), and the export must show them under the
        worker's own lane, not the parent drain thread's."""
        if not self.enabled:
            return
        own_thread = tid is None
        if own_thread:
            tid = threading.get_ident()
        with self._lock:
            if own_thread and tid not in self._thread_names:
                self._thread_names[tid] = threading.current_thread().name
            if t_start < self._min_ts:
                self._min_ts = t_start
            self._events.append(('X', name, t_start, t_end - t_start,
                                 int(tid), attrs or None, pid))
            self._appended += 1

    def instant(self, name: str, **attrs: Any) -> None:
        """Record an instant ('i') lifecycle marker at now."""
        if not self.enabled:
            return
        tid = threading.get_ident()
        with self._lock:
            if tid not in self._thread_names:
                self._thread_names[tid] = threading.current_thread().name
            self._events.append(('i', name, CLOCK(), 0.0, tid,
                                 attrs or None, None))
            self._appended += 1

    # -- export --------------------------------------------------------------

    @property
    def dropped(self) -> int:
        """Events lost to ring-buffer wrap (oldest-first)."""
        with self._lock:
            return max(0, self._appended - len(self._events))

    def origin(self) -> float:
        """This recorder's ts=0 reference: its epoch or the earliest
        start ever recorded, whichever is older — a span timed just
        before the recorder attached must not export a negative
        timestamp. O(1): the minimum is tracked at append time (the
        /trace route calls this per recorder on a request path)."""
        with self._lock:
            return min(self._t0, self._min_ts)

    def snapshot(self, origin: Optional[float] = None,
                 limit: Optional[int] = None) -> List[Dict[str, Any]]:
        """The buffered events as Chrome trace-event dicts, ts-sorted.

        ``origin`` overrides the ts=0 reference — multi-recorder merges
        (``merge_traces``) pass one common origin so recorders created
        at different times stay aligned on one timeline (CLOCK is the
        shared process-wide ``perf_counter``).

        ``limit`` bounds the snapshot to the MOST RECENT ``limit``
        events: on-demand consumers (the serve ``/trace`` route, the
        black-box dumper) must never serialize the full 200K-event ring
        under the recorder lock on a request path."""
        with self._lock:
            if limit is not None and limit < len(self._events):
                from itertools import islice
                events = list(islice(self._events,
                                     len(self._events) - int(limit),
                                     len(self._events)))
            else:
                events = list(self._events)
            names = dict(self._thread_names)
            if origin is None:
                origin = min(self._t0, self._min_ts)
        own_pid = os.getpid()
        out: List[Dict[str, Any]] = []
        for tid, tname in sorted(names.items()):
            out.append({'name': 'thread_name', 'ph': 'M', 'ts': 0,
                        'pid': own_pid, 'tid': tid,
                        'args': {'name': tname}})
        body = []
        for ph, name, ts, dur, tid, attrs, pid in events:
            ev: Dict[str, Any] = {
                'name': name, 'ph': ph,
                'pid': pid if pid is not None else own_pid, 'tid': tid,
                'ts': round((ts - origin) * 1e6, 3),
            }
            if ph == 'X':
                ev['dur'] = round(dur * 1e6, 3)
            else:
                ev['s'] = 't'           # instant scope: this thread
            if attrs:
                ev['args'] = {k: _jsonable(v) for k, v in attrs.items()}
            body.append(ev)
        # viewers tolerate unsorted events but the validator contract is
        # monotonic timestamps; one sort at export keeps recording cheap
        body.sort(key=lambda e: e['ts'])
        return out + body

    def export(self, path: str) -> str:
        """Atomically write the Chrome trace JSON document to ``path``."""
        from video_features_tpu.utils.output import atomic_write
        doc = {
            'traceEvents': self.snapshot(),
            'displayTimeUnit': 'ms',
            'otherData': {
                'tool': 'video_features_tpu',
                'wall_epoch_s': self._wall0,
                'events_dropped': self.dropped,
            },
        }
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        atomic_write(path, lambda f: f.write(
            json.dumps(doc).encode('utf-8')))
        return path


# bytes attrs render at most this many bytes: a span arg is provenance,
# not payload — an accidental frame buffer must not balloon the export
_BYTES_RENDER_CAP = 256


def _jsonable(v: Any) -> Any:
    """JSON-safe projection shared by span args and the run manifest
    (obs/manifest imports this — one implementation to drift)."""
    if isinstance(v, (str, int, float, bool)) or v is None:
        return v
    if isinstance(v, (bytes, bytearray)):
        # ASCII-safe decode, NOT str(): repr would export "b'...'"
        # wrappers into traces/manifests, and a stray binary blob would
        # export escape soup of unbounded size — cap and say so
        head = bytes(v[:_BYTES_RENDER_CAP])
        text = head.decode('ascii', 'backslashreplace')
        if len(v) > _BYTES_RENDER_CAP:
            text += f'...(+{len(v) - _BYTES_RENDER_CAP} bytes)'
        return text
    if isinstance(v, (list, tuple, set, frozenset)):
        return [_jsonable(x) for x in v]
    if isinstance(v, dict):
        return {str(k): _jsonable(x) for k, x in v.items()}
    return str(v)


#: disabled singleton — instrumentation sites can hold it unconditionally
NULL_RECORDER = SpanRecorder(capacity=1, enabled=False)


def merge_traces(recorders: Iterable[SpanRecorder],
                 limit: Optional[int] = None) -> List[Dict[str, Any]]:
    """One ts-sorted event list over several recorders (the serve daemon
    stitches every warm-pool worker's recorder into one drain export —
    ``export_merged`` below). All recorders share CLOCK, so one common
    origin (the oldest) keeps workers created hours apart correctly
    offset on the merged timeline instead of each re-basing to 0.
    ``limit`` bounds each recorder's contribution to its most recent
    events (request-path consumers: the ``/trace`` route, black-box
    dumps)."""
    recorders = list(recorders)
    if not recorders:
        return []
    origin = min(rec.origin() for rec in recorders)
    events: List[Dict[str, Any]] = []
    for rec in recorders:
        events.extend(rec.snapshot(origin=origin, limit=limit))
    events.sort(key=lambda e: (e['ph'] != 'M', e['ts']))
    return events


def export_merged(recorders: Iterable[SpanRecorder], path: str) -> str:
    """Atomically write one Chrome trace document stitching several
    recorders (serve drain: a shared ``trace_out`` base override must
    carry EVERY worker's spans, not whichever worker exported last)."""
    from video_features_tpu.utils.output import atomic_write
    recorders = [r for r in recorders if r is not None]
    doc = {
        'traceEvents': merge_traces(recorders),
        'displayTimeUnit': 'ms',
        'otherData': {
            'tool': 'video_features_tpu',
            'recorders_merged': len(recorders),
            'events_dropped': sum(r.dropped for r in recorders),
        },
    }
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    atomic_write(path, lambda f: f.write(json.dumps(doc).encode('utf-8')))
    return path
