"""The content-addressed feature store behind ``cache_enabled: true``.

Layout under ``cache_dir``::

    manifest.jsonl                  append-only op log (put / touch / del)
    objects/<k2>/<key>/<name>       the stored feature files, verbatim

An entry holds the EXACT bytes the cold extraction published (the files
``action_on_extraction`` wrote), so a hit materializes byte-identical
outputs by copying — never by re-serializing, which could drift across
numpy/pickle versions.

Durability model:

  * stored object files and all full-manifest rewrites go through
    ``utils.output.atomic_write`` (tmp + ``os.replace``) — a reader never
    sees a torn file;
  * incremental manifest updates are single-``write`` appended JSON
    lines; a crash can tear at most the LAST line, and the loader skips
    undecodable lines instead of failing the whole cache;
  * later records win on replay, so concurrent processes appending to a
    shared manifest converge (content-addressed keys make double-puts
    idempotent).

Integrity: ``fetch_to`` stat-checks every stored file against its
recorded size before serving and EVICTS (rather than serves) an entry
that is missing, truncated, or resized; ``gc(verify=True)`` re-hashes
content against the recorded SHA-256 (the offline ``tools/cache_gc.py``
surface). Eviction order under ``max_bytes`` pressure is LRU by
last-fetch time.

Instances are process-global per directory (:meth:`FeatureCache.get`) so
the CLI loop, the packed scheduler, and every serve worker sharing a
``cache_dir`` share one index, one lock, and one set of counters.
"""
from __future__ import annotations

import json
import os
import shutil
import threading
import time
from hashlib import sha256
from pathlib import Path
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple

from video_features_tpu.utils.output import (
    atomic_write, make_path, write_fingerprint,
)

MANIFEST = 'manifest.jsonl'
OBJECTS = 'objects'


def _copy_hashed(src: str, dest: str) -> Tuple[int, str]:
    """Atomically copy ``src`` → ``dest``; returns (size, sha256 hex)."""
    h = sha256()
    size = 0

    def _write(out):
        nonlocal size
        with open(src, 'rb') as f:
            while True:
                chunk = f.read(1 << 20)
                if not chunk:
                    break
                h.update(chunk)
                size += len(chunk)
                out.write(chunk)

    atomic_write(dest, _write)
    return size, h.hexdigest()


class FeatureCache:
    """One cache directory: index, manifest, objects, counters."""

    _instances: Dict[str, 'FeatureCache'] = {}
    _instances_lock = threading.Lock()

    @classmethod
    def get(cls, cache_dir: str,
            max_bytes: Optional[int] = None) -> 'FeatureCache':
        """The process-wide instance for ``cache_dir`` (created on first
        use). A non-null ``max_bytes`` updates the shared bound — last
        writer wins, which matches "the most recent config speaks for
        the operator"."""
        norm = os.path.abspath(os.path.expanduser(str(cache_dir)))
        with cls._instances_lock:
            inst = cls._instances.get(norm)
            if inst is None:
                inst = cls._instances[norm] = cls(norm, max_bytes=max_bytes)
            elif max_bytes is not None:
                inst.max_bytes = int(max_bytes)
            return inst

    def __init__(self, cache_dir: str,
                 max_bytes: Optional[int] = None) -> None:
        self.cache_dir = os.path.abspath(os.path.expanduser(str(cache_dir)))
        self.max_bytes = int(max_bytes) if max_bytes is not None else None
        self._lock = threading.RLock()
        # key → {'files': {output_key: {'name','ext','size','sha256'}},
        #        'last_used': float, 'bytes': int}
        self._index: Dict[str, Dict[str, Any]] = {}
        self._total_bytes = 0
        self.hits = 0
        self.misses = 0
        self.puts = 0
        self.evictions = 0
        self.corrupt_evicted = 0
        self.bytes_saved = 0
        # eviction subscribers: ``fn(key, corrupt)`` fires for EVERY
        # entry leaving the store (LRU pressure, corrupt eviction,
        # offline GC) — the seam the feature index uses to tombstone
        # rows whose backing object is gone. Callbacks fire AFTER the
        # store lock is released (queued by ``_evict_locked``, drained
        # by ``_notify_evictions``): the index ingest thread re-enters
        # the store from its callback, and firing under ``self._lock``
        # would order cache-lock → subscriber-lock against the ingest
        # thread's subscriber-lock → cache-lock — a deadlock once a
        # second lock (the L2 tier's) joins the graph. The del record
        # still lands before the notice, so a subscriber observing the
        # evict always sees the manifest already agreeing.
        self.on_evict: List[Callable[[str, bool], None]] = []
        # (key, corrupt) notices queued under the lock, fired outside it
        self._pending_evict_notices: List[Tuple[str, bool]] = []
        os.makedirs(os.path.join(self.cache_dir, OBJECTS), exist_ok=True)
        self._load_manifest()

    # -- manifest ------------------------------------------------------------

    @property
    def manifest_path(self) -> str:
        return os.path.join(self.cache_dir, MANIFEST)

    def _entry_dir(self, key: str) -> str:
        return os.path.join(self.cache_dir, OBJECTS, key[:2], key)

    def _load_manifest(self) -> None:
        try:
            with open(self.manifest_path, 'rb') as f:
                lines = f.read().splitlines()
        except FileNotFoundError:
            return
        for line in lines:
            try:
                rec = json.loads(line)
            except (ValueError, UnicodeDecodeError):
                continue              # torn tail line from a crash: skip
            op, key = rec.get('op'), rec.get('key')
            if not key:
                continue
            if op == 'put' and isinstance(rec.get('files'), dict):
                total = sum(int(f.get('size', 0))
                            for f in rec['files'].values())
                old = self._index.get(key)
                if old is not None:
                    self._total_bytes -= old['bytes']
                self._index[key] = {
                    'files': rec['files'],
                    'last_used': float(rec.get('t', 0.0)),
                    'bytes': total,
                }
                self._total_bytes += total
            elif op == 'touch' and key in self._index:
                self._index[key]['last_used'] = float(rec.get('t', 0.0))
            elif op == 'del':
                old = self._index.pop(key, None)
                if old is not None:
                    self._total_bytes -= old['bytes']

    def _append(self, rec: Dict[str, Any]) -> None:
        """One JSON line, one ``write`` call — a crash tears at most the
        final line, which the loader tolerates."""
        with open(self.manifest_path, 'a', encoding='utf-8') as f:
            f.write(json.dumps(rec, sort_keys=True) + '\n')

    def _rewrite_manifest_locked(self) -> None:
        """Compaction: one put line per live entry (atomic rewrite)."""
        def _write(f):
            for key, e in self._index.items():
                f.write((json.dumps(
                    {'op': 'put', 'key': key, 'files': e['files'],
                     't': e['last_used']}, sort_keys=True) + '\n')
                    .encode('utf-8'))
        atomic_write(self.manifest_path, _write)

    # -- core operations -----------------------------------------------------

    def contains(self, key: str) -> bool:
        with self._lock:
            return key in self._index

    def entry_exts(self, key: str) -> Optional[Dict[str, str]]:
        """Output key → file extension for a stored entry (None when
        absent) — the fleet tier (``fleet/tier.py``) uses this to
        re-publish a peer-served L2 entry into the local L1 without
        knowing anything about the family that produced it."""
        with self._lock:
            entry = self._index.get(key)
            if entry is None:
                return None
            return {okey: f['ext'] for okey, f in entry['files'].items()}

    def fetch_to(self, key: str, out_root: str, video_path: str,
                 fingerprint: Optional[str] = None) -> bool:
        """Materialize entry ``key`` as ``video_path``'s output files
        under ``out_root`` (byte-identical atomic copies, plus the resume
        fingerprint sidecar when ``fingerprint`` is given). Returns True
        on a served hit; a missing entry counts a miss, and a stored file
        that fails its size check evicts the whole entry (corrupt) and
        counts a miss — the cache never serves bytes it can't vouch for.

        The copies run OUTSIDE the lock (a multi-MB materialization must
        not stall the serve daemon's admission path or metrics behind
        disk I/O); an eviction racing the copy surfaces as an OSError
        and degrades to a miss.
        """
        with self._lock:
            entry = self._index.get(key)
            if entry is None:
                self.misses += 1
                return False
            files = dict(entry['files'])     # snapshot for lock-free I/O
        edir = self._entry_dir(key)
        ok = True
        try:
            for f in files.values():
                if os.path.getsize(os.path.join(edir, f['name'])) \
                        != int(f['size']):
                    ok = False
                    break
            if ok:
                os.makedirs(out_root, exist_ok=True)
                for okey, f in files.items():
                    dest = make_path(out_root, video_path, okey, f['ext'])
                    src = os.path.join(edir, f['name'])

                    def _copy(out, _src=src):
                        with open(_src, 'rb') as fh:
                            shutil.copyfileobj(fh, out)

                    atomic_write(dest, _copy)
        except OSError:
            ok = False
        if not ok:
            with self._lock:
                # evict only if the slot still holds the snapshot we
                # failed on — a concurrent evict/re-put must not be
                # double-punished
                current = self._index.get(key)
                if current is not None and current['files'] == files:
                    self._evict_locked(key, corrupt=True)
                self.misses += 1
            self._notify_evictions()
            return False
        if fingerprint is not None:
            write_fingerprint(out_root, video_path, fingerprint)
        with self._lock:
            current = self._index.get(key)
            now = time.time()
            if current is not None:
                current['last_used'] = now
                self._append({'op': 'touch', 'key': key, 't': now})
            self.hits += 1
            self.bytes_saved += sum(int(f['size']) for f in files.values())
        return True

    def put(self, key: str, files: Dict[str, Tuple[str, str]],
            meta: Optional[Dict[str, Any]] = None) -> None:
        """Publish one video's freshly saved outputs under ``key``.

        ``files`` maps output key → ``(source path, extension)`` — the
        exact files ``action_on_extraction`` just wrote. Idempotent: a
        key already present only refreshes recency (two workers racing a
        publish store identical bytes by construction; durable via a
        touch record so the refresh survives a manifest replay).
        Triggers inline LRU eviction when ``max_bytes`` is exceeded.
        The object copies run OUTSIDE the lock (same reasoning as
        :meth:`fetch_to`); racing writers converge because every copy is
        an atomic replace of identical bytes.
        """
        def _touch_locked():
            now = time.time()
            self._index[key]['last_used'] = now
            self._append({'op': 'touch', 'key': key, 't': now})

        with self._lock:
            if key in self._index:
                _touch_locked()
                return
        edir = self._entry_dir(key)
        os.makedirs(edir, exist_ok=True)
        recorded: Dict[str, Dict[str, Any]] = {}
        total = 0
        for okey, (src, ext) in files.items():
            name = f'{okey}{ext}'
            size, digest = _copy_hashed(src, os.path.join(edir, name))
            recorded[okey] = {'name': name, 'ext': ext, 'size': size,
                              'sha256': digest}
            total += size
        with self._lock:
            if key in self._index:       # lost a racing publish: adopt it
                _touch_locked()
                return
            now = time.time()
            rec: Dict[str, Any] = {'op': 'put', 'key': key,
                                   'files': recorded, 't': now}
            if meta:
                rec['meta'] = meta
            self._append(rec)
            self._index[key] = {'files': recorded, 'last_used': now,
                                'bytes': total}
            self._total_bytes += total
            self.puts += 1
            if self.max_bytes is not None \
                    and self._total_bytes > self.max_bytes:
                self._gc_locked(self.max_bytes, verify=False,
                                compact=False, orphan_sweep=False)
        self._notify_evictions()

    def _evict_locked(self, key: str, corrupt: bool = False) -> int:
        entry = self._index.pop(key, None)
        if entry is None:
            return 0
        self._total_bytes -= entry['bytes']
        shutil.rmtree(self._entry_dir(key), ignore_errors=True)
        self._append({'op': 'del', 'key': key, 't': time.time(),
                      'corrupt': bool(corrupt)})
        if corrupt:
            self.corrupt_evicted += 1
        else:
            self.evictions += 1
        # queue, don't fire: subscribers run outside the lock (see the
        # on_evict declaration) — every public entry point that can
        # reach here drains via _notify_evictions after unlocking
        self._pending_evict_notices.append((key, bool(corrupt)))
        return entry['bytes']

    def _notify_evictions(self) -> None:
        """Drain queued eviction notices and fire the subscribers with
        NO store lock held — a callback may freely call back into this
        cache (the index ingest thread does). Looped because a callback
        re-entering the store can itself queue further evictions."""
        while True:
            with self._lock:
                if not self._pending_evict_notices:
                    return
                notices = self._pending_evict_notices
                self._pending_evict_notices = []
            for key, corrupt in notices:
                for fn in list(self.on_evict):
                    try:
                        fn(key, corrupt)
                    except Exception:
                        log_cache_error(f'on_evict callback for {key}')

    # -- garbage collection --------------------------------------------------

    def gc(self, target_bytes: Optional[int] = None, verify: bool = False,
           compact: bool = True) -> Dict[str, Any]:
        """Integrity sweep + LRU eviction + manifest compaction (the
        offline / ``tools/cache_gc.py`` surface).

        ``verify=True`` re-hashes every stored file against its recorded
        SHA-256 (otherwise only existence/size is checked); entries that
        fail either way are evicted as corrupt. Then entries are evicted
        oldest-fetch-first until total size ≤ ``target_bytes`` (default:
        the instance's ``max_bytes``; None = no size pressure). Orphan
        object directories (on disk but not in the manifest — crashed
        writers) are removed if older than a grace window. Returns a
        report dict.

        Cross-process safety: the manifest is RELOADED first, so entries
        other processes appended since this instance loaded are neither
        compacted away nor swept as orphans; the orphan grace window
        covers writers mid-publish during the sweep itself.
        """
        with self._lock:
            self._reload_locked()
            report = self._gc_locked(
                self.max_bytes if target_bytes is None else target_bytes,
                verify=verify, compact=compact, orphan_sweep=True)
        self._notify_evictions()
        return report

    def _reload_locked(self) -> None:
        """Re-replay the manifest from disk (puts/touches/dels appended
        by OTHER processes since construction win over our stale view;
        our own ops are all in the manifest too, so replay converges)."""
        self._index.clear()
        self._total_bytes = 0
        self._load_manifest()

    # object dirs younger than this are never swept as orphans: their
    # writer may simply not have appended its put record yet
    _ORPHAN_GRACE_S = 300.0

    def _gc_locked(self, target_bytes: Optional[int], verify: bool,
                   compact: bool, orphan_sweep: bool) -> Dict[str, Any]:
        report = {'entries_before': len(self._index),
                  'bytes_before': self._total_bytes,
                  'corrupt_evicted': 0, 'lru_evicted': 0,
                  'orphans_removed': 0}
        for key in list(self._index):
            edir = self._entry_dir(key)
            bad = False
            for f in self._index[key]['files'].values():
                src = os.path.join(edir, f['name'])
                try:
                    if os.path.getsize(src) != int(f['size']):
                        bad = True
                    elif verify:
                        h = sha256()
                        with open(src, 'rb') as fh:
                            for chunk in iter(lambda: fh.read(1 << 20), b''):
                                h.update(chunk)
                        bad = h.hexdigest() != f['sha256']
                except OSError:
                    bad = True
                if bad:
                    break
            if bad:
                self._evict_locked(key, corrupt=True)
                report['corrupt_evicted'] += 1
        if target_bytes is not None:
            by_age = sorted(self._index,
                            key=lambda k: self._index[k]['last_used'])
            for key in by_age:
                if self._total_bytes <= target_bytes:
                    break
                self._evict_locked(key)
                report['lru_evicted'] += 1
        # orphan sweep: object dirs no put record owns (crashed writers)
        # — offline GC only (the inline publish-pressure path must never
        # touch dirs another process may be mid-publish on), and gated
        # by an age window for writers racing this very sweep
        if orphan_sweep:
            now = time.time()
            objects = Path(self.cache_dir) / OBJECTS
            for shard in objects.iterdir() if objects.is_dir() else ():
                if not shard.is_dir():
                    continue
                for edir in shard.iterdir():
                    if not edir.is_dir() or edir.name in self._index:
                        continue
                    try:
                        if now - edir.stat().st_mtime < self._ORPHAN_GRACE_S:
                            continue
                    except OSError:
                        continue
                    shutil.rmtree(edir, ignore_errors=True)
                    report['orphans_removed'] += 1
        if compact:
            self._rewrite_manifest_locked()
        report['entries_after'] = len(self._index)
        report['bytes_after'] = self._total_bytes
        return report

    # -- accounting ----------------------------------------------------------

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            total = self.hits + self.misses
            return {
                'dir': self.cache_dir,
                'entries': len(self._index),
                'bytes': self._total_bytes,
                'max_bytes': self.max_bytes,
                'hits': self.hits,
                'misses': self.misses,
                'hit_rate': (self.hits / total) if total else 0.0,
                'puts': self.puts,
                'evictions': self.evictions,
                'corrupt_evicted': self.corrupt_evicted,
                'bytes_saved': self.bytes_saved,
            }


def merge_cache_stats(stats: Iterable[Dict[str, Any]]) -> Dict[str, Any]:
    """One aggregate view over several caches' :meth:`FeatureCache.stats`
    (the serve metrics document: requests may name different cache
    dirs)."""
    merged: Dict[str, Any] = {
        'caches': 0, 'entries': 0, 'bytes': 0, 'hits': 0, 'misses': 0,
        'puts': 0, 'evictions': 0, 'corrupt_evicted': 0, 'bytes_saved': 0,
        # fleet tier counters (fleet/tier.py): zero on plain caches —
        # always present so the metrics document keeps one schema
        'peer_hits': 0, 'l2_publishes': 0,
    }
    for s in stats:
        merged['caches'] += 1
        for k in ('entries', 'bytes', 'hits', 'misses', 'puts',
                  'evictions', 'corrupt_evicted', 'bytes_saved',
                  'peer_hits', 'l2_publishes'):
            merged[k] += s.get(k, 0)
    total = merged['hits'] + merged['misses']
    merged['hit_rate'] = (merged['hits'] / total) if total else 0.0
    return merged


def log_cache_error(what: str) -> None:
    """Cache failures degrade to misses, never to failed extractions —
    but silently eating them would hide a broken cache dir forever.
    Reported through the structured event log (obs/events: warning
    level, stderr, full traceback) like every other degraded path."""
    import logging

    from video_features_tpu.obs.events import event
    event(logging.WARNING,
          f'feature cache {what} failed (continuing uncached)',
          subsystem='cache', exc_info=True)
