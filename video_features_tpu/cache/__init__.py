"""Content-addressed feature cache: dedupe + short-circuit repeated
extraction across the CLI loop, packed worklists, and the serve daemon.

For a fixed (video content, extractor, config, checkpoint) the output
features are deterministic, so the second request for any video is an
O(read) hit instead of a decode + inference. Key derivation lives in
:mod:`.key`, the store (manifest, objects, LRU GC, integrity checks) in
:mod:`.store`; ``tools/cache_gc.py`` is the offline maintenance surface
and docs/caching.md the operator guide.
"""
from video_features_tpu.cache.key import (  # noqa: F401
    CONFIG_KEY_EXCLUDE, config_fingerprint, hash_file, run_fingerprint,
    video_cache_key, weights_fingerprint,
)
from video_features_tpu.cache.store import (  # noqa: F401
    FeatureCache, log_cache_error, merge_cache_stats,
)
