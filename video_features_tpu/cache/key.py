"""Cache-key derivation: what makes two extractions "the same work".

The toolkit is inference-only over frozen models: for a fixed video,
extractor, config, and checkpoint the output features are deterministic,
so a result is fully identified by

    (video content hash, config fingerprint, weights fingerprint)

and the cache key is one SHA-256 over the three. Each part is derived
here with one goal: NEVER a false hit, and as few false misses as
practical.

  * the video hash is over file CONTENT (streaming SHA-256), not the
    path — the same clip under ten names/copies is one cache entry;
  * the config fingerprint covers only EXTRACTION-RELEVANT keys: knobs
    that cannot change the output bytes (``output_path``, ``tmp_path``,
    device/parallelism/profiling toggles, the ``cache_*`` namespace
    itself) are excluded so they don't fragment the key space, while
    anything unrecognized stays IN the fingerprint — an unknown future
    knob costs a redundant miss, never a wrong hit;
  * the weights fingerprint hashes the configured checkpoint FILES (a
    re-fetched or swapped checkpoint under the same path invalidates),
    with an explicit ``random`` marker for the allow-random-weights
    escape hatch (tests/benches; see docs/caching.md for why sharing a
    cache dir across random-weight processes is meaningless).

File hashes are memoized by ``(realpath, size, mtime_ns)`` so repeated
requests for the same corpus — the serving layer's common case — pay the
streaming read once per file version, not once per request.
"""
from __future__ import annotations

import hashlib
import json
import threading
from typing import Any, Dict, Mapping

from video_features_tpu.config import knob_exclude

_CHUNK = 1 << 20  # 1 MiB streaming-read granularity

# Keys that cannot change the extracted bytes. Everything NOT listed
# lands in the fingerprint (fail-closed: unknown knobs fragment the key
# space rather than risking a stale hit). The per-knob classification —
# with its rationale — lives in ONE place, ``config.KNOB_CLASSIFICATION``
# (the serve pool key derives its own exclusion set from the same
# registry; vft-lint rejects hand-maintained copies). Checkpoint paths
# are additionally excluded from the CONFIG fingerprint below because
# the WEIGHTS fingerprint covers their content (a path string is not an
# identity — the file behind it can change).
CONFIG_KEY_EXCLUDE = knob_exclude('fingerprint')

# (realpath, size, mtime_ns) → hex digest; bounded so a week-long serving
# process over a rotating corpus can't grow it without limit
_HASH_MEMO: Dict[tuple, str] = {}
_HASH_MEMO_MAX = 65536
_MEMO_LOCK = threading.Lock()

# process-wide streaming-pass accounting: 'passes' counts ACTUAL
# streaming sha256 reads, 'memo_hits' counts stat-memo answers. The
# fused-worklist amortization contract ("one sha256 pass per video, no
# matter how many families") is asserted against these counters in
# tests — a regression that re-hashes per family shows up as passes >
# videos, not as a silent corpus-scale slowdown.
_HASH_STATS = {'passes': 0, 'memo_hits': 0}


def hash_file_stats() -> Dict[str, int]:
    """Snapshot of the process-wide streaming-hash counters."""
    with _MEMO_LOCK:
        return dict(_HASH_STATS)


def reset_hash_file_stats() -> None:
    """Zero the counters (test isolation; the memo itself is kept —
    clearing it would force real re-reads and skew what the counters
    measure next)."""
    with _MEMO_LOCK:
        _HASH_STATS['passes'] = 0
        _HASH_STATS['memo_hits'] = 0


def hash_file(path: str) -> str:
    """Streaming SHA-256 of a file's content, memoized by stat identity.

    The memo key includes size AND mtime_ns, so an overwritten file
    (re-fetched checkpoint, re-encoded clip) re-hashes; a merely re-read
    one doesn't.
    """
    import os

    real = os.path.realpath(path)
    st = os.stat(real)
    memo_key = (real, st.st_size, st.st_mtime_ns)
    with _MEMO_LOCK:
        hit = _HASH_MEMO.get(memo_key)
        if hit is not None:
            _HASH_STATS['memo_hits'] += 1
    if hit is not None:
        return hit
    h = hashlib.sha256()
    with open(real, 'rb') as f:
        while True:
            chunk = f.read(_CHUNK)
            if not chunk:
                break
            h.update(chunk)
    digest = h.hexdigest()
    with _MEMO_LOCK:
        _HASH_STATS['passes'] += 1
        if len(_HASH_MEMO) >= _HASH_MEMO_MAX:
            _HASH_MEMO.clear()
        _HASH_MEMO[memo_key] = digest
    return digest


def _canonical(obj: Any) -> str:
    """Deterministic serialization for fingerprint material. ``repr`` for
    non-JSON values keeps the function total; sort_keys keeps dict order
    out of the identity."""
    return json.dumps(obj, sort_keys=True, default=repr)


def config_fingerprint(args: Mapping[str, Any]) -> str:
    """SHA-256 over the extraction-relevant subset of a merged config."""
    relevant = {k: v for k, v in args.items()
                if k not in CONFIG_KEY_EXCLUDE
                and 'checkpoint_path' not in k}
    return hashlib.sha256(_canonical(relevant).encode()).hexdigest()


def _null_checkpoint_marker(args: Mapping[str, Any]) -> str:
    """Identity for a NULL checkpoint key — which is not always random:
    two families load real weights without a configured path, and each
    must key on what it actually loads or different weight sets alias.

      * timm (extract/timm.py): pip-timm pulls pretrained weights when
        importable (``pretrained`` not disabled) → key on the timm
        package version; a host without pip-timm degrades to the random
        marker, so its entries can never serve a pretrained run's key;
      * clip model_name=custom (extract/clip.py): the implicit
        ``./checkpoints/CLIP-custom.pth`` → key on that file's content.

    Everything else with a null path runs the gated seeded random init
    (deterministic per code version) → the ``random`` marker.
    """
    import os

    ft = args.get('feature_type')
    if ft == 'timm' and args.get('pretrained', True):
        try:
            import timm
            return f'timm-pretrained:{timm.__version__}'
        except ImportError:
            pass
    if ft == 'clip' and args.get('model_name') == 'custom':
        implicit = './checkpoints/CLIP-custom.pth'
        if os.path.exists(implicit):
            return f'file:{hash_file(implicit)}'
    return 'random'


def weights_fingerprint(args: Mapping[str, Any]) -> str:
    """SHA-256 over the CONTENT of every configured checkpoint file.

    A null checkpoint key contributes :func:`_null_checkpoint_marker`
    (usually ``random`` — the escape hatch seeds its init
    deterministically — but timm/clip implicit-weight loads key on their
    real provenance). A configured-but-unreadable checkpoint raises —
    the extractor build would fail on it anyway, and a silent fallback
    here could alias two different weight sets.
    """
    material: Dict[str, str] = {}
    for k in sorted(args):
        if 'checkpoint_path' not in k:
            continue
        v = args[k]
        material[k] = (f'file:{hash_file(str(v))}' if v
                       else _null_checkpoint_marker(args))
    return hashlib.sha256(_canonical(material).encode()).hexdigest()


def run_fingerprint(args: Mapping[str, Any]) -> str:
    """The one identity string for "this exact extraction recipe":
    config fingerprint + weights fingerprint. This is what resume
    sidecars record and what the video hash combines with."""
    return hashlib.sha256(
        f'cfg:{config_fingerprint(args)}|w:{weights_fingerprint(args)}'
        .encode()).hexdigest()


def video_cache_key(video_path: str, fingerprint: str,
                    segment=None) -> str:
    """The content-addressed store key for one (video, recipe) pair.

    ``segment`` is an optional ``(start_s, end_s)`` time range (ingress
    segment queries): a partial-range extraction is DIFFERENT work from
    the full video, so the range is part of the key — a full extraction
    can never answer a segment query (or vice versa) from the cache.
    Millisecond-quantized, matching the output-file naming
    (``parallel.packing.segment_name``), so two requests for the same
    range always share one entry.
    """
    seg = ''
    if segment is not None:
        start_s, end_s = segment
        seg = (f'|seg:{int(round(float(start_s) * 1000))}'
               f'-{int(round(float(end_s) * 1000))}')
    return hashlib.sha256(
        f'{fingerprint}|video:{hash_file(video_path)}{seg}'
        .encode()).hexdigest()
