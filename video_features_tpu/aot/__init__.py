"""vft-aot: zero cold start — a persistent compiled-executable store.

Every serve boot and every fresh CLI batch job used to re-trace and
re-compile its programs before the first feature was fast; serve's
per-key build locks merely serialized the pain. This package keeps the
COMPILED XLA executables on disk between processes, keyed by the same
byte-deterministic StableHLO identity ``PROGRAMS.lock.json`` pins
(``analysis/programs.py``), so a boot against an unchanged program set
LOADS executables instead of compiling them.

Two layers:

  * :mod:`aot.store` — the jax-free persistent byte store (atomic
    writes, integrity verification that EVICTS corrupt entries instead
    of serving them, size-bounded LRU GC; mirrors ``cache/store.py``);
  * :mod:`aot.runtime` — the jax seam: serialize/deserialize compiled
    executables (``jax.experimental.serialize_executable``, PJRT-level)
    and ``ensure_program`` (trace → StableHLO sha → load-or-compile →
    republish), the one function both the lazy dispatch path
    (``BaseExtractor.aot_call``) and the serve pre-warm
    (``BaseExtractor.aot_warm``) go through.

A jax-version / backend / device-kind mismatch is by construction a
SILENT MISS (the key includes all three): the program recompiles and
republishes under its own key — never an error. Outputs of a loaded
executable are byte-identical to a freshly compiled one's
(tests/test_aot.py pins it), which is why the ``aot_*`` knobs are
excluded from the cache fingerprint (docs/serving.md "Zero cold
start").
"""
from video_features_tpu.aot.store import (  # noqa: F401
    ExecStore, log_aot_error, merge_exec_stats,
)
