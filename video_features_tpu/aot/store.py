"""The persistent executable store behind ``aot_enabled: true``.

Layout under ``aot_dir``::

    manifest.jsonl                  append-only op log (put / touch / del)
    objects/<k2>/<digest>/exec.bin  one serialized compiled executable

An entry holds the EXACT serialized-executable bytes the compiling
process published (``aot/runtime.py``: a pickled
``jax.experimental.serialize_executable`` payload), so a load
reconstructs the very executable that was compiled — never a re-lower,
which would just be a slower compile.

Deliberately jax-free: the store moves bytes; what the bytes mean lives
in :mod:`aot.runtime`. The durability/integrity model mirrors
``cache/store.py`` (the content-addressed feature cache):

  * object files and full-manifest rewrites go through
    ``utils.output.atomic_write`` (tmp + ``os.replace``) — a reader
    never sees a torn payload;
  * incremental manifest updates are single-``write`` appended JSON
    lines; a crash tears at most the LAST line, which the loader skips;
  * later records win on replay, so concurrent processes sharing one
    ``aot_dir`` converge (digest keys make double-puts idempotent);
  * ``fetch`` stat-checks the payload size before serving and EVICTS
    (rather than serves) a missing/truncated/resized entry; callers
    that fail to DESERIALIZE a served payload report back through
    :meth:`evict_corrupt` so bit-rot below the size check is also
    purged; ``gc(verify=True)`` re-hashes payloads against their
    recorded SHA-256 (the offline ``tools/aot_gc.py`` surface);
  * eviction under ``max_bytes`` pressure is LRU by last-fetch time.

Instances are process-global per directory (:meth:`ExecStore.get`) so
every serve worker and packed run sharing an ``aot_dir`` shares one
index, one lock, and one set of counters.
"""
from __future__ import annotations

import json
import os
import shutil
import threading
import time
from hashlib import sha256
from pathlib import Path
from typing import Any, Dict, Iterable, Optional

from video_features_tpu.utils.output import atomic_write

MANIFEST = 'manifest.jsonl'
OBJECTS = 'objects'
PAYLOAD = 'exec.bin'


def exec_digest(components: Dict[str, Any]) -> str:
    """The store key: sha256 over the canonical JSON of the identity
    components — the program's StableHLO sha256 (the same identity
    ``PROGRAMS.lock.json`` pins), the ``mesh<n>[@dtype]`` lane, the jax
    version, backend platform, device kind, host ISA, and the device
    ids the executable is bound to. ANY component changing is a silent
    miss by construction: the new identity simply hashes elsewhere."""
    return sha256(json.dumps(components, sort_keys=True).encode()).hexdigest()


class ExecStore:
    """One executable-store directory: index, manifest, payloads, counters."""

    _instances: Dict[str, 'ExecStore'] = {}
    _instances_lock = threading.Lock()

    @classmethod
    def get(cls, aot_dir: str,
            max_bytes: Optional[int] = None) -> 'ExecStore':
        """The process-wide instance for ``aot_dir`` (created on first
        use). A non-null ``max_bytes`` updates the shared bound — last
        writer wins (same policy as ``FeatureCache.get``)."""
        norm = os.path.abspath(os.path.expanduser(str(aot_dir)))
        with cls._instances_lock:
            inst = cls._instances.get(norm)
            if inst is None:
                inst = cls._instances[norm] = cls(norm, max_bytes=max_bytes)
            elif max_bytes is not None:
                inst.max_bytes = int(max_bytes)
            return inst

    def __init__(self, aot_dir: str,
                 max_bytes: Optional[int] = None) -> None:
        self.aot_dir = os.path.abspath(os.path.expanduser(str(aot_dir)))
        self.max_bytes = int(max_bytes) if max_bytes is not None else None
        self._lock = threading.RLock()
        # digest → {'size': int, 'sha256': hex, 'meta': {...},
        #           'last_used': float}
        self._index: Dict[str, Dict[str, Any]] = {}
        self._total_bytes = 0
        self.hits = 0
        self.misses = 0
        self.puts = 0
        self.evictions = 0
        self.corrupt_evicted = 0
        os.makedirs(os.path.join(self.aot_dir, OBJECTS), exist_ok=True)
        self._load_manifest()

    # -- manifest ------------------------------------------------------------

    @property
    def manifest_path(self) -> str:
        return os.path.join(self.aot_dir, MANIFEST)

    def _entry_dir(self, digest: str) -> str:
        return os.path.join(self.aot_dir, OBJECTS, digest[:2], digest)

    def _payload_path(self, digest: str) -> str:
        return os.path.join(self._entry_dir(digest), PAYLOAD)

    def _load_manifest(self) -> None:
        try:
            with open(self.manifest_path, 'rb') as f:
                lines = f.read().splitlines()
        except FileNotFoundError:
            return
        for line in lines:
            try:
                rec = json.loads(line)
            except (ValueError, UnicodeDecodeError):
                continue              # torn tail line from a crash: skip
            op, digest = rec.get('op'), rec.get('key')
            if not digest:
                continue
            if op == 'put' and isinstance(rec.get('size'), int):
                old = self._index.get(digest)
                if old is not None:
                    self._total_bytes -= old['size']
                self._index[digest] = {
                    'size': int(rec['size']),
                    'sha256': rec.get('sha256', ''),
                    'meta': rec.get('meta') or {},
                    'last_used': float(rec.get('t', 0.0)),
                }
                self._total_bytes += int(rec['size'])
            elif op == 'touch' and digest in self._index:
                self._index[digest]['last_used'] = float(rec.get('t', 0.0))
            elif op == 'del':
                old = self._index.pop(digest, None)
                if old is not None:
                    self._total_bytes -= old['size']

    def _append(self, rec: Dict[str, Any]) -> None:
        """One JSON line, one ``write`` call — a crash tears at most the
        final line, which the loader tolerates."""
        with open(self.manifest_path, 'a', encoding='utf-8') as f:
            f.write(json.dumps(rec, sort_keys=True) + '\n')

    def _rewrite_manifest_locked(self) -> None:
        """Compaction: one put line per live entry (atomic rewrite)."""
        def _write(f):
            for digest, e in self._index.items():
                f.write((json.dumps(
                    {'op': 'put', 'key': digest, 'size': e['size'],
                     'sha256': e['sha256'], 'meta': e['meta'],
                     't': e['last_used']}, sort_keys=True) + '\n')
                    .encode('utf-8'))
        atomic_write(self.manifest_path, _write)

    # -- core operations -----------------------------------------------------

    def contains(self, digest: str) -> bool:
        with self._lock:
            return digest in self._index

    def meta_for(self, digest: str) -> Optional[Dict[str, Any]]:
        """The recorded ``meta`` of one entry (None when absent) — the
        fleet artifact tier (``fleet/artifacts.py``) re-publishes a
        peer-compiled payload locally under the SAME meta so
        environment-drift diagnostics stay truthful on the pulling
        host."""
        with self._lock:
            entry = self._index.get(digest)
            if entry is None:
                return None
            return dict(entry['meta'])

    def metas_for(self, program_sha: str) -> list:
        """The recorded ``meta`` of every entry publishing
        ``program_sha`` — the runtime's environment-drift diagnostics
        surface (a miss for a program the store holds under a DIFFERENT
        environment names the drifted component)."""
        with self._lock:
            return [dict(e['meta']) for e in self._index.values()
                    if e.get('meta', {}).get('program_sha') == program_sha]

    def fetch(self, digest: str) -> Optional[bytes]:
        """The serialized executable for ``digest``, or None (a miss).
        The payload is size-checked against the manifest record before
        serving; a missing/truncated/resized payload evicts the entry as
        corrupt and reads as a miss — the store never serves bytes it
        can't vouch for. The file read runs OUTSIDE the lock (a multi-MB
        payload read must not stall a concurrent publish)."""
        with self._lock:
            entry = self._index.get(digest)
            if entry is None:
                self.misses += 1
                return None
            size = entry['size']
        path = self._payload_path(digest)
        try:
            if os.path.getsize(path) != size:
                raise OSError(f'size mismatch for {digest}')
            with open(path, 'rb') as f:
                payload = f.read()
            if len(payload) != size:
                raise OSError(f'short read for {digest}')
        except OSError:
            self.evict_corrupt(digest)
            with self._lock:
                self.misses += 1
            return None
        with self._lock:
            current = self._index.get(digest)
            now = time.time()
            if current is not None:
                current['last_used'] = now
                self._append({'op': 'touch', 'key': digest, 't': now})
            self.hits += 1
        return payload

    def put(self, digest: str, payload: bytes,
            meta: Optional[Dict[str, Any]] = None) -> None:
        """Publish one freshly serialized executable under ``digest``.

        Idempotent: a digest already present only refreshes recency (two
        processes racing a publish store identical bytes by construction
        — the digest IS the program identity). Triggers inline LRU
        eviction when ``max_bytes`` is exceeded. The payload write runs
        OUTSIDE the lock; racing writers converge because every write is
        an atomic replace of identical bytes."""
        def _touch_locked():
            now = time.time()
            self._index[digest]['last_used'] = now
            self._append({'op': 'touch', 'key': digest, 't': now})

        with self._lock:
            if digest in self._index:
                _touch_locked()
                return
        os.makedirs(self._entry_dir(digest), exist_ok=True)
        atomic_write(self._payload_path(digest),
                     lambda f: f.write(payload))
        recorded_sha = sha256(payload).hexdigest()
        with self._lock:
            if digest in self._index:    # lost a racing publish: adopt it
                _touch_locked()
                return
            now = time.time()
            rec: Dict[str, Any] = {'op': 'put', 'key': digest,
                                   'size': len(payload),
                                   'sha256': recorded_sha, 't': now}
            if meta:
                rec['meta'] = meta
            self._append(rec)
            self._index[digest] = {'size': len(payload),
                                   'sha256': recorded_sha,
                                   'meta': dict(meta or {}),
                                   'last_used': now}
            self._total_bytes += len(payload)
            self.puts += 1
            if self.max_bytes is not None \
                    and self._total_bytes > self.max_bytes:
                self._gc_locked(self.max_bytes, verify=False)

    def evict_corrupt(self, digest: str) -> None:
        """Purge an entry whose payload failed integrity — either the
        store's own size check or the caller's DESERIALIZE (the runtime
        layer reports bit-rot below the size check here, so a poisoned
        entry is purged instead of failing every future boot)."""
        with self._lock:
            self._evict_locked(digest, corrupt=True)

    def _evict_locked(self, digest: str, corrupt: bool = False) -> int:
        entry = self._index.pop(digest, None)
        if entry is None:
            return 0
        self._total_bytes -= entry['size']
        shutil.rmtree(self._entry_dir(digest), ignore_errors=True)
        self._append({'op': 'del', 'key': digest, 't': time.time(),
                      'corrupt': bool(corrupt)})
        if corrupt:
            self.corrupt_evicted += 1
        else:
            self.evictions += 1
        return entry['size']

    # -- garbage collection --------------------------------------------------

    def gc(self, target_bytes: Optional[int] = None, verify: bool = False,
           compact: bool = True) -> Dict[str, Any]:
        """Integrity sweep + LRU eviction + manifest compaction (the
        offline / ``tools/aot_gc.py`` surface).

        ``verify=True`` re-hashes every payload against its recorded
        SHA-256 (otherwise only existence/size is checked); entries that
        fail either way are evicted as corrupt — a store must never keep
        an executable it would refuse to serve. Then entries are evicted
        oldest-fetch-first until total size ≤ ``target_bytes`` (default:
        the instance's ``max_bytes``; None = no size pressure). Orphan
        object directories (crashed writers) older than a grace window
        are removed. The manifest is RELOADED first so entries other
        processes appended since this instance loaded are neither
        compacted away nor swept as orphans."""
        with self._lock:
            self._index.clear()
            self._total_bytes = 0
            self._load_manifest()
            report = self._gc_locked(
                self.max_bytes if target_bytes is None else target_bytes,
                verify=verify, orphan_sweep=True)
            if compact:
                # adopt puts concurrent processes appended WHILE the
                # (possibly minutes-long) verify sweep ran: the
                # compaction rewrite below replaces the manifest
                # wholesale, and dropping a record whose payload a live
                # daemon is serving would turn a later orphan sweep
                # into data loss — only entries this sweep explicitly
                # evicted stay gone
                self._adopt_new_puts_locked(report.pop('_evicted'))
                self._rewrite_manifest_locked()
            else:
                report.pop('_evicted')
            report['entries_after'] = len(self._index)
            report['bytes_after'] = self._total_bytes
            return report

    def _adopt_new_puts_locked(self, evicted: set) -> None:
        """Re-replay the on-disk manifest and index any put that landed
        after this sweep's load — skipping digests the sweep itself
        evicted (their del records may not order after the racing put,
        but an evicted payload is gone either way)."""
        fresh = ExecStore.__new__(ExecStore)
        fresh.aot_dir = self.aot_dir
        fresh._index = {}
        fresh._total_bytes = 0
        fresh._load_manifest()
        for digest, entry in fresh._index.items():
            if digest in self._index or digest in evicted:
                continue
            self._index[digest] = entry
            self._total_bytes += entry['size']

    # object dirs younger than this are never swept as orphans: their
    # writer may simply not have appended its put record yet
    _ORPHAN_GRACE_S = 300.0

    def _gc_locked(self, target_bytes: Optional[int], verify: bool,
                   orphan_sweep: bool = False) -> Dict[str, Any]:
        """The sweep itself; compaction is the CALLER's step (``gc``)
        so it can reconcile concurrent puts first. ``_evicted`` in the
        report is internal bookkeeping for that reconciliation."""
        report: Dict[str, Any] = {
            'entries_before': len(self._index),
            'bytes_before': self._total_bytes,
            'corrupt_evicted': 0, 'lru_evicted': 0,
            'orphans_removed': 0, '_evicted': set()}
        for digest in list(self._index):
            entry = self._index[digest]
            path = self._payload_path(digest)
            bad = False
            try:
                if os.path.getsize(path) != entry['size']:
                    bad = True
                elif verify:
                    h = sha256()
                    with open(path, 'rb') as f:
                        for chunk in iter(lambda: f.read(1 << 20), b''):
                            h.update(chunk)
                    bad = h.hexdigest() != entry['sha256']
            except OSError:
                bad = True
            if bad:
                self._evict_locked(digest, corrupt=True)
                report['corrupt_evicted'] += 1
                report['_evicted'].add(digest)
        if target_bytes is not None:
            by_age = sorted(self._index,
                            key=lambda k: self._index[k]['last_used'])
            for digest in by_age:
                if self._total_bytes <= target_bytes:
                    break
                self._evict_locked(digest)
                report['lru_evicted'] += 1
                report['_evicted'].add(digest)
        if orphan_sweep:
            now = time.time()
            objects = Path(self.aot_dir) / OBJECTS
            for shard in objects.iterdir() if objects.is_dir() else ():
                if not shard.is_dir():
                    continue
                for edir in shard.iterdir():
                    if not edir.is_dir() or edir.name in self._index:
                        continue
                    try:
                        if now - edir.stat().st_mtime < self._ORPHAN_GRACE_S:
                            continue
                    except OSError:
                        continue
                    shutil.rmtree(edir, ignore_errors=True)
                    report['orphans_removed'] += 1
        report['entries_after'] = len(self._index)
        report['bytes_after'] = self._total_bytes
        return report

    # -- accounting ----------------------------------------------------------

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            total = self.hits + self.misses
            return {
                'dir': self.aot_dir,
                'entries': len(self._index),
                'bytes': self._total_bytes,
                'max_bytes': self.max_bytes,
                'hits': self.hits,
                'misses': self.misses,
                'hit_rate': (self.hits / total) if total else 0.0,
                'puts': self.puts,
                'evictions': self.evictions,
                'corrupt_evicted': self.corrupt_evicted,
            }


def merge_exec_stats(stats: Iterable[Dict[str, Any]]) -> Dict[str, Any]:
    """One aggregate view over several stores' :meth:`ExecStore.stats`
    (the serve metrics document: requests may name different aot
    dirs)."""
    merged: Dict[str, Any] = {
        'stores': 0, 'entries': 0, 'bytes': 0, 'hits': 0, 'misses': 0,
        'puts': 0, 'evictions': 0, 'corrupt_evicted': 0,
        # fleet artifact-tier counters (fleet/artifacts.py): zero on
        # plain stores — always present so vft_aot_* keeps one schema
        'pulled': 0, 'published': 0,
    }
    for s in stats:
        merged['stores'] += 1
        for k in ('entries', 'bytes', 'hits', 'misses', 'puts',
                  'evictions', 'corrupt_evicted', 'pulled', 'published'):
            merged[k] += s.get(k, 0)
    total = merged['hits'] + merged['misses']
    merged['hit_rate'] = (merged['hits'] / total) if total else 0.0
    return merged


def log_aot_error(what: str) -> None:
    """Executable-store failures degrade to compile-on-miss, never to a
    failed build or video — but silently eating them would hide a broken
    store dir (or a poisoned payload) forever. Reported through the
    structured event log like every other degraded path."""
    import logging

    from video_features_tpu.obs.events import event
    event(logging.WARNING,
          f'executable store {what} failed (continuing with compile)',
          subsystem='aot', exc_info=True)
