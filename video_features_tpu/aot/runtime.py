"""The jax seam of the executable store: serialize, load, or compile.

``ensure_program`` is the ONE path every consumer goes through — the
lazy per-geometry dispatch (``BaseExtractor.aot_call``), the serve
pre-warm (``BaseExtractor.aot_warm``), and the tests. It traces the
ACTUAL jitted callable the hot path dispatches (the same discipline as
``analysis/programs.py`` — the program identity is the lowering of the
real callable, closures and ambient matmul-precision context included),
takes the StableHLO sha256 of that lowering as the program identity,
and then either

  * **loads** a previously published executable from the
    :class:`aot.store.ExecStore` (PJRT-level deserialization — no XLA
    optimization pass runs; measured ~30x cheaper than a compile on
    CPU, far more on accelerators), or
  * **compiles** the lowering and republishes the serialized executable
    so every future process loads instead.

The store key (``aot.store.exec_digest``) is the program sha plus the
full runtime environment — ``mesh<n>[@dtype]`` lane, jax version,
backend platform, device kind, host ISA, and the exact device ids the
executable is bound to. Any component differing is a SILENT MISS by
construction: a jax upgrade, a different chip generation, or a
placement on different silicon recompiles and republishes under its own
key, never errors. When a miss finds the SAME program published under a
different environment, a structured event names the drift so operators
can see why a boot stopped being compile-free.

Loaded executables produce byte-identical outputs to freshly compiled
ones (same StableHLO, same backend — pinned by tests/test_aot.py),
which is the contract that lets the ``aot_*`` knobs stay out of the
cache fingerprint.
"""
from __future__ import annotations

import logging
import pickle
from typing import Any, Dict, Optional, Tuple

from video_features_tpu.aot.store import ExecStore, exec_digest
from video_features_tpu.obs.events import event

# bump when the payload framing (NOT the executable format — jax/PJRT
# own that, and their versions are in the key) changes incompatibly
PAYLOAD_VERSION = 1


def runtime_environment(devices: Tuple[int, ...]) -> Dict[str, Any]:
    """The environment components of the store key. ``devices`` is the
    sorted tuple of device ids the program's args are committed to —
    PJRT deserialization rebinds by id, so an executable serialized for
    chip d1 must never answer a lookup for chip d0."""
    import platform as _host

    import jax
    dev = jax.devices()[0]
    return {
        'jax': jax.__version__,
        'platform': dev.platform,
        'device_kind': dev.device_kind,
        # XLA:CPU AOT artifacts record the compiling host's CPU feature
        # list (see utils/device.enable_compilation_cache); the ISA in
        # the key keeps a shared aot_dir from serving one host's CPU
        # executable to a different microarchitecture
        'machine': _host.machine(),
        'devices': list(devices),
        'payload_v': PAYLOAD_VERSION,
    }


def arg_device_ids(args) -> Tuple[int, ...]:
    """Sorted device ids across every array leaf of ``args`` — committed
    ``jax.Array`` leaves and sharded ``ShapeDtypeStruct``s both count;
    plain numpy leaves (uncommitted) contribute nothing. Empty means
    'backend default device'."""
    import jax
    ids = set()
    for leaf in jax.tree_util.tree_leaves(args):
        sharding = getattr(leaf, 'sharding', None)
        device_set = getattr(sharding, 'device_set', None)
        if device_set:
            ids.update(d.id for d in device_set)
    if not ids:
        ids.add(jax.devices()[0].id)
    return tuple(sorted(ids))


def serialize_compiled(compiled) -> bytes:
    """One self-contained payload for a ``jax.stages.Compiled``: the
    PJRT-serialized executable plus the in/out pytree structure
    (``serialize_executable`` returns the trees separately because
    PyTreeDefs aren't its problem; they pickle fine and the payload
    must be one blob on disk)."""
    from jax.experimental import serialize_executable as se
    payload, in_tree, out_tree = se.serialize(compiled)
    return pickle.dumps((PAYLOAD_VERSION, payload, in_tree, out_tree))


def deserialize_compiled(blob: bytes):
    """Inverse of :func:`serialize_compiled`; raises on any mismatch
    (version skew, foreign pickle, truncation) — callers treat every
    raise as a corrupt entry to evict + a compile to fall back on."""
    version, payload, in_tree, out_tree = pickle.loads(blob)
    if version != PAYLOAD_VERSION:
        raise ValueError(f'aot payload version {version} != '
                         f'{PAYLOAD_VERSION}')
    from jax.experimental import serialize_executable as se
    return se.deserialize_and_load(payload, in_tree, out_tree)


class AotProgram:
    """One resident executable + the call convention to reach it.

    ``Compiled`` objects are called with the ARRAY args only — static
    kwargs were baked at trace time — so the program remembers which
    statics it was specialized for (``aot_call`` keys its dispatch
    table on them) and drops them at call time.
    """

    __slots__ = ('name', 'compiled', 'program_sha', 'source')

    def __init__(self, name: str, compiled, program_sha: str,
                 source: str) -> None:
        self.name = name
        self.compiled = compiled
        self.program_sha = program_sha
        self.source = source              # 'loaded' | 'compiled'

    def __call__(self, *arrays):
        return self.compiled(*arrays)


def ensure_program(store: ExecStore, name: str, jitted, args: tuple,
                   statics: Optional[Dict[str, Any]] = None, *,
                   lane: str, feature_type: str = '?',
                   ) -> Tuple[AotProgram, str]:
    """Trace ``jitted`` at ``args``/``statics``, then load-or-compile.

    Returns ``(program, path)`` with ``path`` one of ``'loaded'`` /
    ``'compiled'``. Raises only on a genuine COMPILE failure (the same
    error the jit path would hit); every store-side failure — unreadable
    dir, corrupt payload, failed publish — degrades to the compile path
    with a structured report.
    """
    statics = dict(statics or {})
    lowered = jitted.trace(*args, **statics).lower()
    from video_features_tpu.analysis.programs import stablehlo_sha256
    program_sha = stablehlo_sha256(lowered.as_text())
    components = {'program_sha': program_sha, 'lane': lane}
    components.update(runtime_environment(arg_device_ids(args)))
    digest = exec_digest(components)

    blob = store.fetch(digest)
    if blob is not None:
        try:
            compiled = deserialize_compiled(blob)
            return (AotProgram(name, compiled, program_sha, 'loaded'),
                    'loaded')
        except Exception:
            # bit-rot below the size check, or an environment the key
            # failed to capture: purge so the next boot doesn't re-fail,
            # and recompile — never serve (or crash on) a bad payload
            store.evict_corrupt(digest)
            event(logging.WARNING,
                  'stored executable failed to deserialize; evicted '
                  'and recompiling', subsystem='aot', exc_info=True,
                  feature_type=feature_type, program=name, lane=lane)
    else:
        _report_environment_miss(store, program_sha, components,
                                 feature_type, name, lane)

    compiled = lowered.compile()
    try:
        store.put(digest, serialize_compiled(compiled),
                  meta={'feature_type': feature_type, 'program': name,
                        **components})
    except Exception:
        from video_features_tpu.aot.store import log_aot_error
        log_aot_error(f'publish for {feature_type}/{name}')
    return AotProgram(name, compiled, program_sha, 'compiled'), 'compiled'


def _report_environment_miss(store: ExecStore, program_sha: str,
                             components: Dict[str, Any],
                             feature_type: str, name: str,
                             lane: str) -> None:
    """A miss for a program the store DOES hold under a different
    environment is the invalidation semantics working as designed (jax
    upgraded, different device kind/ids, host ISA changed) — but an
    operator reading "boot stopped being compile-free" needs the reason
    named, so it gets a structured event instead of indistinguishable
    silence. Never raises; never fires for plain cold stores."""
    try:
        for meta in store.metas_for(program_sha):
            drift = {k: (meta.get(k), components.get(k))
                     for k in ('jax', 'platform', 'device_kind',
                               'machine', 'devices', 'lane', 'payload_v')
                     if meta.get(k) != components.get(k)}
            if drift:
                event(logging.INFO,
                      'executable present under a different runtime '
                      'environment — recompiling (silent-miss '
                      'invalidation)', subsystem='aot',
                      feature_type=feature_type, program=name, lane=lane,
                      drift={k: {'stored': a, 'live': b}
                             for k, (a, b) in drift.items()})
                return
    except Exception:
        # vft-lint: ok=swallowed-exception — best-effort diagnostics on
        # the compile path; the miss itself is already being handled
        pass
