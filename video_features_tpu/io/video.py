"""Video decode & batching layer.

Re-design of reference utils/io.py (VideoLoader, 176 LoC) for a TPU pipeline:

  * frames are yielded as **stacked NumPy arrays** (B, H, W, 3) ready for a
    single host→HBM transfer, not Python lists of per-frame tensors;
  * fps retargeting has two backends — an exact ffmpeg re-encode (reference
    io.py:14-36) used when an ffmpeg binary exists, and a pure
    frame-index-resampling path (ffmpeg's ``fps=`` filter semantics: for each
    output slot at time k/fps pick the nearest source frame) used otherwise;
  * the decode backend is pluggable: cv2 today, the native C++ libav service
    later, behind the same ``FrameDecoder`` protocol.

Contract parity with the reference loader:
  * iteration yields ``(batch, times_ms, indices)``;
  * ``timestamp_ms = index / fps * 1000`` (reference io.py:132);
  * first batch has ``batch_size`` frames, later ones read
    ``batch_size - overlap`` new frames and reuse ``overlap`` cached ones
    (reference io.py:109-154); the final batch may be short;
  * ``len(loader)`` is the total frame count;
  * temporary re-encodes are deleted unless ``keep_tmp`` (reference io.py:159-165).
"""
from __future__ import annotations

import hashlib
import itertools
import logging
import os
import shutil
import subprocess
from pathlib import Path
from typing import Callable, Dict, Iterator, List, Optional, Tuple, Union

import cv2
import numpy as np

# memoized which_ffmpeg result; None = not probed yet ('' = no binary).
# Reset to None in tests that monkeypatch the PATH.
_FFMPEG_PATH: Optional[str] = None

_REENCODE_SEQ = itertools.count()


def reencode_out_path(video_path: Union[str, os.PathLike],
                      tmp_path: Union[str, os.PathLike]) -> str:
    """Collision-free re-encode target in ``tmp_path``. The stem alone
    is not enough: decode-farm worker processes (and the threaded
    decode-ahead pool) re-encode CONCURRENTLY into one shared tmp_path,
    so same-stem videos — or the same video open in two processes —
    would clobber each other's tmp file mid-read and delete each
    other's on close(). Path digest separates same-stem sources; pid +
    a per-process counter separate concurrent opens of one source."""
    digest = hashlib.sha1(
        os.path.abspath(os.fspath(video_path)).encode()).hexdigest()[:8]
    return os.path.join(
        os.fspath(tmp_path),
        f'{Path(video_path).stem}_{digest}_{os.getpid()}'
        f'_{next(_REENCODE_SEQ)}_new_fps.mp4')


def which_ffmpeg() -> str:
    """Path to an ffmpeg binary, or '' (reference utils/utils.py:181-194).

    ``shutil.which``, memoized: the old ``subprocess.run(['which', ...])``
    probe spawned a process per VideoLoader (twice when fps retiming was
    requested) and broke on hosts without a ``which`` binary.
    """
    global _FFMPEG_PATH
    if _FFMPEG_PATH is None:
        _FFMPEG_PATH = shutil.which('ffmpeg') or ''
    return _FFMPEG_PATH


def get_video_props(path: Union[str, os.PathLike]) -> Dict[str, float]:
    """fps / num_frames / height / width via cv2 (reference io.py:167-176)."""
    cap = cv2.VideoCapture(str(path))
    try:
        props = dict(
            fps=cap.get(cv2.CAP_PROP_FPS),
            num_frames=int(cap.get(cv2.CAP_PROP_FRAME_COUNT)),
            height=int(cap.get(cv2.CAP_PROP_FRAME_HEIGHT)),
            width=int(cap.get(cv2.CAP_PROP_FRAME_WIDTH)),
        )
    finally:
        cap.release()
    return props


def reencode_video_with_diff_fps(video_path: str, tmp_path: str,
                                 extraction_fps: float) -> str:
    """ffmpeg CFR re-encode to ``extraction_fps`` (reference io.py:14-36).

    Raises ``RuntimeError`` when ffmpeg exits non-zero or writes no
    output — the old ``subprocess.call`` ignored the exit code and the
    missing file surfaced later as an opaque cv2 probe error; the caller
    (``VideoLoader``) degrades to index resampling instead.
    """
    ffmpeg = which_ffmpeg()
    assert ffmpeg != '', 'ffmpeg is not installed'
    os.makedirs(tmp_path, exist_ok=True)
    new_path = reencode_out_path(video_path, tmp_path)
    cmd = [ffmpeg, '-hide_banner', '-loglevel', 'panic', '-y', '-i', video_path,
           '-filter:v', f'fps=fps={extraction_fps}', new_path]
    rc = subprocess.call(cmd)
    if rc != 0 or not os.path.isfile(new_path):
        raise RuntimeError(
            f'ffmpeg re-encode of {video_path} exited {rc} '
            f'({"no output written" if not os.path.isfile(new_path) else new_path})')
    return new_path


def resample_frame_indices(num_src_frames: int, src_fps: float,
                           target_fps: float) -> np.ndarray:
    """Source-frame index per output slot for CFR retiming to ``target_fps``.

    Pure-host equivalent of ffmpeg's ``fps=`` filter with 'near' rounding:
    output slot k sits at time k/target_fps and takes the nearest source
    frame, duplicating (upsampling) or dropping (downsampling) as needed.
    """
    if num_src_frames <= 0:
        return np.zeros((0,), dtype=np.int64)
    duration = num_src_frames / src_fps
    num_out = max(int(round(duration * target_fps)), 1)
    k = np.arange(num_out)
    src_idx = np.round(k * src_fps / target_fps).astype(np.int64)
    return np.clip(src_idx, 0, num_src_frames - 1)


class Cv2FrameDecoder:
    """Sequential RGB frame decoder over cv2.VideoCapture.

    Yields (source_index, frame HWC uint8 RGB). Handles the cv2 quirk where
    frame 0 occasionally fails to decode (reference io.py:99-107).
    """

    def __init__(self, path: str):
        self.path = path
        self.cap: Optional[cv2.VideoCapture] = None

    def __iter__(self) -> Iterator[Tuple[int, np.ndarray]]:
        self.cap = cv2.VideoCapture(self.path)
        ok, first = self.cap.read()
        if ok:
            # frame 0 decodes fine → restart from the beginning
            self.cap.release()
            self.cap = cv2.VideoCapture(self.path)
        else:
            # structured channel, not print: decode chatter must never
            # interleave with the on_extraction=print feature stream
            from video_features_tpu.obs.events import event
            event(logging.WARNING,
                  'first frame failed to decode (cv2 missing-frame '
                  'quirk); continuing from the next readable frame',
                  video=self.path)
        idx = 0
        while True:
            ok, bgr = self.cap.read()
            if not ok:
                break
            yield idx, cv2.cvtColor(bgr, cv2.COLOR_BGR2RGB)
            idx += 1
        self.release()

    def release(self) -> None:
        if self.cap is not None:
            self.cap.release()
            self.cap = None


class VideoLoader:
    """Batched streaming frame iterator.

    Args:
        path: video file path.
        batch_size: frames per yielded batch.
        fps: retarget to this frame rate (mutually exclusive with ``total``).
        total: retarget so the whole video yields ~``total`` frames.
        tmp_path: where ffmpeg re-encodes land (ffmpeg backend only).
        keep_tmp: keep the re-encoded temp file.
        transform: per-frame callable (HWC uint8 RGB → anything). When None,
            raw frames are returned and batches arrive stacked as one
            (B, H, W, 3) uint8 array.
        transform_workers: >1 runs the transform over a thread pool,
            pipelined ahead of the consumer (PIL/cv2 release the GIL in
            their core loops, so host preprocessing scales with threads —
            it is the usual bottleneck once the device is fast).
        overlap: frames shared between consecutive batches (flow pairing).
        use_ffmpeg: force (True)/forbid (False) the ffmpeg-binary re-encode
            backend. Default (None): the binary when present (exact
            reference parity) → the in-process native re-encoder (same
            fps-filter + libx264-default semantics, no binary needed) →
            pure index resampling.
        backend: frame decode backend — 'native' (C++ libav service),
            'cv2', or 'auto' (native when buildable, else cv2).
    """

    def __init__(
        self,
        path: Union[str, os.PathLike],
        batch_size: int = 1,
        fps: Optional[float] = None,
        total: Optional[int] = None,
        tmp_path: Union[str, os.PathLike] = 'tmp',
        keep_tmp: bool = False,
        transform: Optional[Callable] = None,
        transform_workers: int = 1,
        overlap: int = 0,
        use_ffmpeg: Optional[bool] = None,
        backend: str = 'auto',
    ):
        assert isinstance(batch_size, int) and batch_size > 0
        assert isinstance(overlap, int) and 0 <= overlap < batch_size
        assert isinstance(transform_workers, int) and transform_workers >= 1
        if fps is not None and total is not None:
            raise ValueError("'fps' and 'total' are mutually exclusive")

        assert backend in ('auto', 'native', 'cv2'), backend
        self.batch_size = batch_size
        self.transform = transform
        self.transform_workers = transform_workers if transform else 1
        self.overlap = overlap
        self.keep_tmp = keep_tmp
        self.backend = backend
        self._tmp_file: Optional[str] = None

        path = str(path)
        if not os.path.isfile(path):
            # probe failures otherwise surface as opaque downstream errors
            # (e.g. cv2 reporting negative frame counts)
            raise FileNotFoundError(f'video does not exist: {path}')
        props = self._probe_props(path)
        self.height, self.width = props['height'], props['width']
        src_fps, src_frames = props['fps'], props['num_frames']

        if total is not None:
            fps = total * src_fps / max(src_frames, 1)

        # Retiming backend resolution: the ffmpeg binary when present
        # (exact reference parity), else the in-process native re-encoder
        # (same fps-filter semantics + libx264 at the CLI defaults —
        # native/vfdecode.cc vf_reencode_fps), else pure index resampling.
        native_reencode = False
        if use_ffmpeg is None:
            use_ffmpeg = which_ffmpeg() != ''
            if not use_ffmpeg:
                from video_features_tpu.io import native as native_mod
                native_reencode = native_mod.available()

        self._index_map: Optional[np.ndarray] = None
        self._decoder = None
        reencoded = None
        if fps is not None and use_ffmpeg:
            # a failed ffmpeg run (non-zero exit, no output) degrades to
            # index resampling like a host without the binary would —
            # the old code ignored the exit code and the missing output
            # surfaced downstream as an opaque cv2 probe error
            try:
                reencoded = reencode_video_with_diff_fps(
                    path, str(tmp_path), fps)
            except (RuntimeError, OSError) as e:
                from video_features_tpu.obs.events import event
                event(logging.WARNING,
                      f'ffmpeg fps re-encode failed ({e}); falling back '
                      'to index resampling', video=str(path))
        elif fps is not None and native_reencode:
            # The native encoder hard-rejects inputs it can't handle (e.g.
            # non-yuv420p); degrade to index resampling like a host with
            # neither backend would, rather than killing extraction.
            from video_features_tpu.io.native import reencode_fps_native
            try:
                reencoded = reencode_fps_native(path, str(tmp_path), fps)
            except (RuntimeError, OSError) as e:
                from video_features_tpu.obs.events import event
                event(logging.WARNING,
                      f'native fps re-encode failed ({e}); falling back '
                      'to index resampling', video=str(path))
        if fps is None:
            self.path = path
            self.fps = src_fps
            self.num_frames = src_frames
        elif reencoded is not None:
            self.path = reencoded
            self._tmp_file = self.path
            new_props = get_video_props(self.path)
            self.fps = new_props['fps']
            self.num_frames = new_props['num_frames']
            self.height, self.width = new_props['height'], new_props['width']
        else:
            self.path = path
            self.fps = fps
            self._index_map = resample_frame_indices(src_frames, src_fps, fps)
            self.num_frames = len(self._index_map)

    # -- iteration ----------------------------------------------------------

    def __iter__(self):
        self._frames = self._retimed_frames()
        self._pre_transformed = False
        if self.transform_workers > 1:
            self._frames = _parallel_map(self.transform, self._frames,
                                         self.transform_workers)
            self._pre_transformed = True
        self._cache: List = []
        self._cache_times: List[float] = []
        self._cache_indices: List[int] = []
        self._out_idx = 0
        self._exhausted = False
        return self

    def _probe_props(self, path: str) -> Dict[str, float]:
        """Stream properties from whichever probe understands the file:
        the native service first (when selected), cv2 otherwise — each can
        demux containers the other's build may lack."""
        if self.backend != 'cv2':
            from video_features_tpu.io import native
            props = native.get_video_props_native(path)
            if props is not None and props['num_frames'] > 0:
                return props
            if self.backend == 'native' and props is None and \
                    not native.available():
                raise RuntimeError('native decode backend unavailable '
                                   '(libvfdecode.so failed to build/load)')
        return get_video_props(path)

    def _make_decoder(self):
        if self.backend != 'cv2':
            from video_features_tpu.io import native
            if native.available():
                decoder = native.NativeFrameDecoder(self.path)
                if self.backend == 'native':
                    return decoder
                try:  # auto: per-file fallback — libav may lack a demuxer
                    return decoder.open()
                except IOError:
                    pass
            elif self.backend == 'native':
                raise RuntimeError('native decode backend unavailable '
                                   '(libvfdecode.so failed to build/load)')
        return Cv2FrameDecoder(self.path)

    def _retimed_frames(self) -> Iterator[np.ndarray]:
        """Decoded frames in output order, honoring the index map (dup/drop).

        try/finally, not an exhausted-path-only ``release()``: a consumer
        that abandons iteration mid-stream (generator ``close()`` or GC)
        must still release the decoder handle, or every early-stopped
        video leaks a demuxer/codec context until interpreter exit.
        """
        decoder = self._make_decoder()
        self._decoder = decoder
        try:
            if self._index_map is None:
                for _, frame in decoder:
                    yield frame
                return
            # index map is sorted; stream the source once, dup/dropping.
            pos = 0
            n = len(self._index_map)
            for src_idx, frame in decoder:
                while pos < n and self._index_map[pos] == src_idx:
                    yield frame
                    pos += 1
                if pos >= n:
                    return
        finally:
            decoder.release()
            self._decoder = None

    def __next__(self):
        if self._exhausted:
            raise StopIteration

        batch = list(self._cache)
        times = list(self._cache_times)
        indices = list(self._cache_indices)

        new_frames = 0
        while len(batch) < self.batch_size:
            try:
                frame = next(self._frames)
            except StopIteration:
                self._exhausted = True
                break
            idx = self._out_idx
            self._out_idx += 1
            times.append(idx / self.fps * 1000)
            indices.append(idx)
            if self.transform is not None and not self._pre_transformed:
                frame = self.transform(frame)
            batch.append(frame)
            new_frames += 1

        # a batch of only cached overlap frames carries no new information
        if new_frames == 0:
            raise StopIteration

        if self.overlap:
            self._cache = batch[-self.overlap:]
            self._cache_times = times[-self.overlap:]
            self._cache_indices = indices[-self.overlap:]

        if self.transform is None:
            return np.stack(batch), times, indices
        return batch, times, indices

    def __len__(self) -> int:
        return self.num_frames

    # -- lifecycle ----------------------------------------------------------

    def close(self) -> None:
        """Release the decoder handle and delete the re-encode temp file.

        Idempotent and safe at any point of iteration; ``with
        VideoLoader(...) as loader:`` and the decode-farm workers call it
        deterministically instead of waiting on ``__del__`` (GC timing is
        an unreliable place to hold codec contexts and tmp-file cleanup).
        """
        frames = getattr(self, '_frames', None)
        if frames is not None and hasattr(frames, 'close'):
            # runs the generator's finally → decoder.release()
            frames.close()
            self._frames = None
        decoder = getattr(self, '_decoder', None)
        if decoder is not None:
            decoder.release()
            self._decoder = None
        if getattr(self, '_tmp_file', None) and not self.keep_tmp:
            try:
                os.remove(self._tmp_file)
            except OSError:
                pass
            self._tmp_file = None

    def __enter__(self) -> 'VideoLoader':
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def __del__(self):
        try:
            self.close()
        except Exception:
            # vft-lint: ok=swallowed-exception — context-exit close is
            # best-effort; decode errors already surfaced on the iterator
            pass


def iter_frame_batches(loader: VideoLoader) -> Iterator[Tuple[np.ndarray, List[float], List[int]]]:
    """Convenience: iterate a loader yielding stacked (B,H,W,3) uint8 batches."""
    for batch, times, indices in loader:
        if isinstance(batch, list):
            batch = np.stack(batch)
        yield batch, times, indices


def _parallel_map(fn, iterable, workers: int):
    """Ordered parallel map with bounded lookahead (host preprocessing).

    Keeps ``2·workers`` frames in flight on a thread pool; PIL/cv2 release
    the GIL in their core loops so per-frame transforms scale with threads.
    """
    from collections import deque
    from concurrent.futures import ThreadPoolExecutor

    with ThreadPoolExecutor(max_workers=workers) as pool:
        pending = deque()
        for item in iterable:
            pending.append(pool.submit(fn, item))
            if len(pending) > 2 * workers:
                yield pending.popleft().result()
        while pending:
            yield pending.popleft().result()


def prefetch_across_videos(window_stream, max_windows: int):
    """Bounded N-video decode-ahead for the packed corpus pipeline.

    ``window_stream`` is a cross-video window iterator (see
    ``extract.streaming.stream_windows_across_videos``): running it on the
    prefetch producer thread means the decoder keeps working ACROSS video
    boundaries — while the device finishes video k's last packed batch, the
    host is already decoding videos k+1, k+2, … until ``max_windows``
    windows are buffered. Memory is strictly bounded at
    ``max_windows × window_bytes`` regardless of how many videos the
    lookahead spans (a corpus of 1-window shorts prefetches many videos
    deep; a long video fills the buffer by itself), which is what makes
    corpus-scale runs safe on fixed-RAM hosts.
    """
    return prefetch(window_stream, depth=max(int(max_windows), 1))


def prefetch(iterable, depth: int = 2):
    """Run ``iterable`` on a background thread, buffering ``depth`` items.

    Host-side software pipelining (SURVEY.md §7 design stance 2): while the
    device computes on batch k, the decode thread fills batch k+1 — the
    single-host analog of a double-buffered infeed. Exceptions from the
    producer re-raise at the consuming site; the thread shuts down with the
    iterator (``close()`` or garbage collection of the generator).
    """
    import queue
    import threading

    q: 'queue.Queue' = queue.Queue(maxsize=max(depth, 1))
    _END = object()
    stop = threading.Event()

    def put_or_abort(item) -> bool:
        """Blocking put that gives up once the consumer is gone."""
        while not stop.is_set():
            try:
                q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def producer():
        try:
            for item in iterable:
                if not put_or_abort(item):
                    return
            put_or_abort(_END)
        # vft-lint: ok=swallowed-exception — shipped, not swallowed:
        # the consumer re-raises whatever the producer thread posts
        except BaseException as e:  # re-raised by the consumer
            put_or_abort(e)

    thread = threading.Thread(target=producer, daemon=True)
    thread.start()
    try:
        while True:
            item = q.get()
            if item is _END:
                return
            if isinstance(item, BaseException):
                raise item
            yield item
    finally:
        stop.set()
