"""Subprocess entry point for the native CFR re-encode.

``python -m video_features_tpu.io.reencode_cli <in> <out> <fps>`` loads
libvfdecode and runs one ``vf_reencode_fps`` call, then exits.

Why a subprocess: libx264's rate control makes (stably) different
float-path decisions depending on process-global state — measured in this
repo as a different bitstream for identical YUV input after XLA:CPU's jit
initialization ran in the host process (encoder input hashes identical,
x264 banner identical, MXCSR unchanged; the precise mechanism is inside
x264). A fresh process always encodes identically (verified across
processes), which is exactly the execution model of the reference's
``ffmpeg`` CLI invocation (reference utils/io.py:14-36) — so the
production path runs the encode out-of-process and stays byte-
deterministic no matter what the host process has loaded or run.
"""
from __future__ import annotations

import sys


def main(argv) -> int:
    if len(argv) != 3:
        print('usage: reencode_cli <in> <out> <fps>', file=sys.stderr)
        return 2
    in_path, out_path, fps = argv[0], argv[1], float(argv[2])
    from video_features_tpu.io.native import load_library

    lib = load_library()
    if lib is None:
        print('native library unavailable', file=sys.stderr)
        return 3
    ret = lib.vf_reencode_fps(str(in_path).encode(),
                              str(out_path).encode(), fps)
    if ret != 0:
        print(lib.vf_last_error().decode(errors='replace'), file=sys.stderr)
        return 1
    return 0


if __name__ == '__main__':
    raise SystemExit(main(sys.argv[1:]))
