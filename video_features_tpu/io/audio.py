"""Audio demux + wav reading for the VGGish path.

Re-design of reference utils/utils.py:197-226 (`extract_wav_from_mp4`):
the same two-stage mp4 → .aac (stream copy) → .wav contract and tmp-file
naming, but with list-argv subprocess calls (no shell-split breakage on
paths with spaces) and a stdlib `wave` reader instead of the soundfile
dependency (ffmpeg's wav output is PCM16, which `wave` handles exactly).
"""
from __future__ import annotations

import subprocess
import wave
from pathlib import Path
from typing import Tuple

import numpy as np

from video_features_tpu.io.video import which_ffmpeg


def extract_wav_from_mp4(video_path: str, tmp_path: str) -> Tuple[str, str]:
    """mp4 → aac (codec copy) → wav; returns (wav_path, aac_path)."""
    ffmpeg = which_ffmpeg()
    assert ffmpeg != '', 'ffmpeg is not installed'
    assert video_path.endswith('.mp4'), 'expected an .mp4 file'
    Path(tmp_path).mkdir(parents=True, exist_ok=True)

    stem = Path(video_path).stem
    aac_path = str(Path(tmp_path) / f'{stem}.aac')
    wav_path = str(Path(tmp_path) / f'{stem}.wav')

    for cmd in ([ffmpeg, '-hide_banner', '-loglevel', 'error', '-y',
                 '-i', video_path, '-acodec', 'copy', aac_path],
                [ffmpeg, '-hide_banner', '-loglevel', 'error', '-y',
                 '-i', aac_path, wav_path]):
        result = subprocess.run(cmd, stderr=subprocess.PIPE, text=True)
        if result.returncode != 0:
            raise RuntimeError(
                f'audio demux failed (no/unsupported audio track in '
                f'{video_path}?): {" ".join(cmd)}\n{result.stderr.strip()}')
    return wav_path, aac_path


def read_wav(wav_path: str) -> Tuple[np.ndarray, int]:
    """PCM wav → (float waveform in [-1, 1] shaped (T,) or (T, C), rate).

    Matches the reference's int16 read + /32768 scaling
    (reference vggish_src/vggish_input.py:84-88).
    """
    with wave.open(wav_path, 'rb') as f:
        rate = f.getframerate()
        n_channels = f.getnchannels()
        width = f.getsampwidth()
        raw = f.readframes(f.getnframes())
    if width == 2:
        data = np.frombuffer(raw, dtype='<i2').astype(np.float64) / 32768.0
    elif width == 4:
        data = np.frombuffer(raw, dtype='<i4').astype(np.float64) / 2147483648.0
    elif width == 1:  # unsigned 8-bit
        data = (np.frombuffer(raw, dtype=np.uint8).astype(np.float64) - 128.0) / 128.0
    else:
        raise NotImplementedError(f'unsupported wav sample width: {width}')
    if n_channels > 1:
        data = data.reshape(-1, n_channels)
    return data, rate
