"""ctypes binding for the native C++ decode service (native/vfdecode.cc).

The reference's decode path crosses a process boundary per re-encode and a
Python call per frame (reference utils/io.py:96-154 via cv2, utils/
utils.py:181-226 via ffmpeg subprocesses). The native service decodes
through the FFmpeg C libraries directly into preallocated numpy chunks —
one C call per ``CHUNK`` frames — and is the default ``VideoLoader``
backend when buildable; cv2 remains the fallback.

The shared library is compiled on first use (g++ + pkg-config, cached next
to the source); environments without a toolchain or libav dev packages
transparently fall back.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from pathlib import Path
from typing import Iterator, Optional, Tuple

import numpy as np

NATIVE_DIR = Path(__file__).resolve().parents[2] / 'native'
LIB_PATH = NATIVE_DIR / 'libvfdecode.so'

_lib = None
_lib_lock = threading.Lock()
_build_failed = False

# frames decoded per C call: amortizes FFI overhead, bounds memory
# (CHUNK × H × W × 3 bytes; 32 × 1080p ≈ 200 MB worst case, typical ≪)
CHUNK = 32


def _build() -> bool:
    try:
        proc = subprocess.run(['make', '-C', str(NATIVE_DIR)],
                              capture_output=True, timeout=120)
        return proc.returncode == 0 and LIB_PATH.exists()
    except (OSError, subprocess.TimeoutExpired):
        return False


def load_library() -> Optional[ctypes.CDLL]:
    """The bound library, building it if needed; None if unavailable."""
    global _lib, _build_failed
    if _lib is not None:
        return _lib
    with _lib_lock:
        if _lib is not None or _build_failed:
            return _lib
        # Always run make: a no-op when the cached .so is fresh, a rebuild
        # when vfdecode.cc is newer (stale libs would otherwise miss newer
        # symbols). If make is unavailable but a prebuilt .so exists, still
        # try it.
        if not _build() and not LIB_PATH.exists():
            _build_failed = True
            return None
        try:
            lib = ctypes.CDLL(str(LIB_PATH))
        except OSError:
            _build_failed = True
            return None
        try:
            _bind(lib)
        except AttributeError:
            # missing symbol: a stale prebuilt .so that make couldn't
            # refresh — treat as unavailable rather than crash callers
            _build_failed = True
            return None
        _lib = lib
        return _lib


def _bind(lib: ctypes.CDLL) -> None:
    lib.vf_open.restype = ctypes.c_void_p
    lib.vf_open.argtypes = [ctypes.c_char_p]
    lib.vf_last_error.restype = ctypes.c_char_p
    lib.vf_props.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_double),
        ctypes.POINTER(ctypes.c_long), ctypes.POINTER(ctypes.c_int),
        ctypes.POINTER(ctypes.c_int)]
    lib.vf_read.restype = ctypes.c_long
    lib.vf_read.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                            ctypes.c_long]
    lib.vf_rotation.restype = ctypes.c_int
    lib.vf_rotation.argtypes = [ctypes.c_void_p]
    lib.vf_close.argtypes = [ctypes.c_void_p]
    lib.vf_audio_open.restype = ctypes.c_void_p
    lib.vf_audio_open.argtypes = [ctypes.c_char_p, ctypes.c_int]
    lib.vf_audio_rate.restype = ctypes.c_int
    lib.vf_audio_rate.argtypes = [ctypes.c_void_p]
    lib.vf_audio_read.restype = ctypes.c_long
    lib.vf_audio_read.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                  ctypes.c_long]
    lib.vf_audio_close.argtypes = [ctypes.c_void_p]
    lib.vf_reencode_fps.restype = ctypes.c_int
    lib.vf_reencode_fps.argtypes = [ctypes.c_char_p, ctypes.c_char_p,
                                    ctypes.c_double]


def available() -> bool:
    return load_library() is not None


def reencode_fps_native(video_path: str, tmp_path: str,
                        extraction_fps: float) -> str:
    """CFR re-encode to ``extraction_fps`` — the reference's
    ``ffmpeg -filter:v fps=fps=N`` stage (reference utils/io.py:14-36)
    without the binary: native fps filter (round=near zero-order hold) +
    libx264 at the CLI's defaults (crf 23, preset medium). Same output
    naming contract as io.video.reencode_video_with_diff_fps.

    Runs in a short-lived subprocess (io/reencode_cli.py) so the encode
    is byte-deterministic regardless of host-process state — libx264's
    rate control measurably changes its decisions after e.g. XLA:CPU jit
    initialization in the same process; a fresh process matches the
    reference's ffmpeg-CLI execution model exactly."""
    import os
    import subprocess
    import sys
    from pathlib import Path

    if load_library() is None:   # build once here; child just dlopens
        raise RuntimeError('native decode library unavailable')
    os.makedirs(tmp_path, exist_ok=True)
    from video_features_tpu.io.video import reencode_out_path
    new_path = reencode_out_path(video_path, tmp_path)
    # The package may not be pip-installed: make the child resolve THIS
    # checkout's package regardless of the caller's cwd. Invoking the
    # entry point by file path puts the io/ dir (no package inside) at
    # sys.path[0], so the PYTHONPATH entry below deterministically wins
    # even when cwd contains a different video_features_tpu checkout.
    pkg_parent = str(Path(__file__).resolve().parents[2])
    env = dict(os.environ)
    env['PYTHONPATH'] = os.pathsep.join(
        [pkg_parent] + ([env['PYTHONPATH']] if env.get('PYTHONPATH') else []))
    proc = subprocess.run(
        [sys.executable, str(Path(__file__).with_name('reencode_cli.py')),
         str(video_path), new_path, repr(float(extraction_fps))],
        capture_output=True, text=True, env=env)
    if proc.returncode != 0:
        raise RuntimeError(
            f'native re-encode failed: {proc.stderr.strip()}')
    return new_path


class NativeFrameDecoder:
    """Sequential RGB frame decoder over the C++ service.

    Same protocol as io.video.Cv2FrameDecoder: iterating yields
    ``(source_index, frame HWC uint8 RGB)``. Frames are decoded in CHUNK-
    sized batches into a fresh numpy array per chunk; yielded frames are
    views into it, safe for callers that hold references.
    """

    def __init__(self, path: str):
        self.path = path
        self._handle: Optional[int] = None

    def open(self) -> 'NativeFrameDecoder':
        lib = load_library()
        if lib is None:
            raise RuntimeError('native decode service unavailable')
        handle = lib.vf_open(os.fsencode(self.path))
        if not handle:
            raise IOError(
                f'vfdecode: {lib.vf_last_error().decode()} ({self.path})')
        self._handle = handle
        fps = ctypes.c_double()
        n = ctypes.c_long()
        w = ctypes.c_int()
        h = ctypes.c_int()
        lib.vf_props(handle, ctypes.byref(fps), ctypes.byref(n),
                     ctypes.byref(w), ctypes.byref(h))
        self.fps = fps.value
        self.num_frames = n.value
        # display geometry: vfdecode applies display-matrix rotation (like
        # cv2's auto-rotate), so width/height already reflect it
        self.width = w.value
        self.height = h.value
        self.rotation = lib.vf_rotation(handle)
        return self

    def __iter__(self) -> Iterator[Tuple[int, np.ndarray]]:
        if self._handle is None:
            self.open()
        lib = load_library()
        idx = 0
        try:
            while True:
                chunk = np.empty((CHUNK, self.height, self.width, 3), np.uint8)
                got = lib.vf_read(self._handle, chunk.ctypes.data, CHUNK)
                if got < 0:
                    raise IOError(f'vfdecode: decode error {got} ({self.path})')
                for i in range(got):
                    yield idx, chunk[i]
                    idx += 1
                if got < CHUNK:
                    break
        finally:
            self.release()

    def release(self) -> None:
        if self._handle is not None:
            load_library().vf_close(self._handle)
            self._handle = None

    def __del__(self):
        self.release()


def get_video_props_native(path: str) -> Optional[dict]:
    """fps/num_frames/height/width via the C++ service; None if unavailable."""
    if not available():
        return None
    dec = NativeFrameDecoder(str(path))
    try:
        dec.open()
    except (IOError, RuntimeError):
        return None
    props = dict(fps=dec.fps, num_frames=dec.num_frames,
                 height=dec.height, width=dec.width)
    dec.release()
    return props


def read_audio_native(path: str, target_sr: int = 0) -> 'tuple':
    """Decode a file's audio track to mono float32 via the C++ service.

    Returns ``(waveform (T,) float32 in [-1, 1], sample_rate)``. With
    ``target_sr`` > 0 libswresample converts to that rate in-process —
    replacing the reference's mp4 → aac → wav ffmpeg-subprocess chain
    (reference utils/utils.py:197-226) with zero temp files. Raises IOError
    when the file has no audio track (matching the ffmpeg path's behavior)
    or RuntimeError when the native service is unavailable.
    """
    lib = load_library()
    if lib is None:
        raise RuntimeError('native decode service unavailable')
    handle = lib.vf_audio_open(os.fsencode(str(path)), int(target_sr))
    if not handle:
        raise IOError(f'vfdecode audio: {lib.vf_last_error().decode()} ({path})')
    try:
        rate = lib.vf_audio_rate(handle)
        chunk = 1 << 18
        buf = np.empty(chunk, np.float32)
        parts = []
        while True:
            n = lib.vf_audio_read(handle, buf.ctypes.data, chunk)
            if n < 0:
                raise IOError(f'vfdecode audio: decode error {n} ({path})')
            if n == 0:
                break
            parts.append(buf[:n].copy())
        data = (np.concatenate(parts) if parts
                else np.zeros((0,), np.float32))
        return data, rate
    finally:
        lib.vf_audio_close(handle)
