"""Reference-equivalent end-to-end I3D two-stream pipeline.

The reference's full CLI stack needs omegaconf + torchvision (absent here),
but everything that defines its *numerics* imports cleanly: the I3D net
(models/i3d/i3d_src/i3d_net.py), RAFT (models/raft/raft_src/raft.py), and
the transform classes (models/transforms.py). This module re-composes the
exact extraction loop of reference models/i3d/extract_i3d.py:95-170 from
those pieces — cv2 decode → ResizeImproved(256) → (stack_size+1)-frame
stacks → RAFT on padded consecutive pairs → per-stream transforms → I3D —
so golden end-to-end fixtures can be recorded from the reference
implementation and compared against ours at the `.npy` level.

Run with any state dicts: seeded-random ones in this environment (the
pretrained blobs are not available — see .MISSING_LARGE_BLOBS), or the real
checkpoints when present; the comparison harness is identical either way.
"""
from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np


def build_reference_nets(seed: int = 0, streams=('rgb', 'flow'),
                         flow_head_scale: float = 0.5):
    """Seeded reference torch nets {rgb, flow, raft} in eval mode.

    Requires /root/reference on sys.path (tests: the `reference_repo`
    fixture). With real checkpoints, load their state dicts into these same
    modules instead.

    ``flow_head_scale`` shapes the seeded RAFT so its flow fields have
    REALISTIC dynamics for the uint8 quantization stage downstream
    (reference transforms.py ToUInt8: flow → round(128 + 255/40·clamp)).
    Unscaled seeded weights drive ~0.05% of pixels to |flow| ≥ 20 px where
    the clamp value itself sits exactly on a rounding boundary (±20 ↦
    q = 0.5 / 255.5), so sub-1e-6 numeric differences between the two
    pipelines flip full uint8 levels there — an artifact of unrealistically
    hot random weights, not of either pipeline. Scaling the flow-head
    output conv by 0.5 yields fields with std ≈ 3 px and |flow| < 13
    (real pretrained RAFT on the sample clips is in the same regime), and
    the quantized comparison then measures what it should: pipeline
    parity. The scaling is applied to the state dict BEFORE it is saved,
    so both pipelines consume identical weights either way.
    """
    import torch

    from models.i3d.i3d_src.i3d_net import I3D
    from models.raft.raft_src.raft import RAFT

    torch.manual_seed(seed)
    nets = {}
    for stream in streams:
        if stream in ('rgb', 'flow'):
            nets[stream] = I3D(num_classes=400, modality=stream).eval()
    if 'flow' in streams:
        raft = RAFT().eval()
        if flow_head_scale != 1.0:
            with torch.no_grad():
                raft.update_block.flow_head.conv2.weight.mul_(flow_head_scale)
        nets['raft'] = raft
    return nets


def save_state_dicts(nets, out_dir) -> Dict[str, str]:
    """Write each net's state_dict as a .pt checkpoint; returns name→path."""
    import torch
    from pathlib import Path

    out_dir = Path(out_dir)
    out_dir.mkdir(parents=True, exist_ok=True)
    paths = {}
    for name, net in nets.items():
        path = out_dir / f'{name}_seeded.pt'
        torch.save(net.state_dict(), str(path))
        paths[name] = str(path)
    return paths


def run_reference_i3d(video_path: str, nets, stack_size: int = 16,
                      step_size: Optional[int] = None,
                      streams=('rgb', 'flow'),
                      min_side: int = 256,
                      crop: int = 224,
                      raft_iters: Optional[int] = None) -> Dict[str, np.ndarray]:
    """The reference extract loop, verbatim semantics, composed by hand.

    Mirrors reference models/i3d/extract_i3d.py:
      * cv2 BGR→RGB, ToPILImage→ResizeImproved(256)→PILToTensor→ToFloat
        (:43-48, :106-108);
      * stacks of stack_size+1 frames; flow = RAFT(padded[:-1], padded[1:])
        (:115-123, :156-158);
      * rgb stream uses the first stack_size frames (:160-163);
      * rgb transforms: TensorCenterCrop(224)→ScaleTo1_1;
        flow: TensorCenterCrop(224)→Clamp(±20)→ToUInt8→ScaleTo1_1 (:49-62);
      * partial final stacks are dropped (:126-129).
    """
    import cv2
    import torch
    from PIL import Image

    from models.raft.raft_src.raft import InputPadder
    from models.transforms import (
        Clamp, PILToTensor, ResizeImproved, ScaleTo1_1, TensorCenterCrop,
        ToFloat, ToUInt8,
    )

    resize_improved = ResizeImproved(min_side)
    pil_to_tensor = PILToTensor()
    to_float = ToFloat()
    t_crop = TensorCenterCrop(crop)
    t_clamp = Clamp(-20, 20)
    t_uint8 = ToUInt8()
    t_scale = ScaleTo1_1()

    if step_size is None:
        step_size = stack_size

    def preprocess(bgr):
        rgb = cv2.cvtColor(bgr, cv2.COLOR_BGR2RGB)
        t = to_float(pil_to_tensor(resize_improved(Image.fromarray(rgb))))
        return t.unsqueeze(0)

    feats: Dict[str, List] = {s: [] for s in streams}
    rgb_stack: List = []
    padder = None
    cap = cv2.VideoCapture(video_path)
    first_frame = True
    with torch.no_grad():
        while cap.isOpened():
            frame_exists, frame = cap.read()
            if first_frame:
                first_frame = False
                if frame_exists is False:
                    continue
            if not frame_exists:
                cap.release()
                break
            t = preprocess(frame)
            if padder is None:
                padder = InputPadder(t.shape)
            rgb_stack.append(t)
            if len(rgb_stack) - 1 == stack_size:
                batch = torch.cat(rgb_stack)
                for stream in streams:
                    if stream == 'flow':
                        kw = ({} if raft_iters is None
                              else {'iters': raft_iters})
                        x = nets['raft'](padder.pad(batch)[:-1],
                                         padder.pad(batch)[1:], **kw)
                        x = t_scale(t_uint8(t_clamp(t_crop(x))))
                    else:
                        x = t_scale(t_crop(batch[:-1]))
                    # PermuteAndUnsqueeze: (T, C, H, W) → (1, C, T, H, W)
                    x = x.permute(1, 0, 2, 3).unsqueeze(0)
                    feats[stream].extend(
                        nets[stream](x, features=True).numpy().tolist())
                rgb_stack = rgb_stack[step_size:]
    return {s: np.asarray(v, dtype=np.float32) for s, v in feats.items()}


def _read_frames_rgb(video_path: str) -> np.ndarray:
    """(T, H, W, 3) uint8 via cv2 — the decode stand-in shared by the
    whole-video reference recipes (decode parity with our loaders is
    covered by tests/test_video_loader.py)."""
    import cv2

    cap = cv2.VideoCapture(video_path)
    frames = []
    while True:
        ok, bgr = cap.read()
        if not ok:
            break
        frames.append(cv2.cvtColor(bgr, cv2.COLOR_BGR2RGB))
    cap.release()
    if not frames:
        raise ValueError(f'no frames decoded from {video_path}')
    return np.stack(frames)


def run_reference_r21d(video_path: str, net, stack_size: int = 16,
                       step_size: int = 16) -> np.ndarray:
    """The reference r21d extraction, verbatim semantics (BASELINE config 1).

    Mirrors reference models/r21d/extract_r21d.py:60-91: whole-video read
    (cv2 stands in for torchvision.io.read_video — same decoded frames),
    ToFloatTensorInZeroOne → Resize(128, 171) → Normalize → CenterCrop(112)
    over the WHOLE video (:102-107), `form_slices` windows (:77), one net
    forward per stack with the classifier stripped (:122-129). ``net`` must
    return FEATURES from a plain ``net(x)`` call — the mirror's default
    (tests/torch_mirrors.py), or real torchvision with
    ``model.fc = nn.Identity()`` exactly as the reference constructs it.
    """
    import torch

    from models.transforms import (
        CenterCrop, Normalize, Resize, ToFloatTensorInZeroOne,
    )

    from video_features_tpu.utils.slicing import form_slices

    rgb = torch.from_numpy(_read_frames_rgb(video_path))     # (T, H, W, C)
    rgb = ToFloatTensorInZeroOne()(rgb)                      # (C, T, H, W)
    rgb = Resize((128, 171))(rgb)
    rgb = Normalize(mean=[0.43216, 0.394666, 0.37645],
                    std=[0.22803, 0.22145, 0.216989])(rgb)
    rgb = CenterCrop((112, 112))(rgb).unsqueeze(0)           # (1, C, T, H, W)

    feats = []
    with torch.no_grad():
        for start, end in form_slices(rgb.size(2), stack_size, step_size):
            out = net(rgb[:, :, start:end])
            feats.extend(out.numpy().tolist())
    return np.asarray(feats, dtype=np.float32)


def run_reference_s3d(video_path: str, net, stack_size: int = 16,
                      step_size: int = 16) -> np.ndarray:
    """The reference s3d extraction, verbatim semantics.

    Mirrors reference models/s3d/extract_s3d.py:30-35,47-76: whole-video
    read, ToFloatTensorInZeroOne → Resize(224, short side) →
    CenterCrop(224) — deliberately NO normalization (kylemin/S3D
    convention) — then `form_slices` windows and `net(x, features=True)`.
    Run both sides at native fps (the reference's default fps-25 re-encode
    needs ffmpeg; retiming parity is covered by the VideoLoader tests).
    """
    import torch

    from models.transforms import CenterCrop, Resize, ToFloatTensorInZeroOne

    from video_features_tpu.utils.slicing import form_slices

    rgb = torch.from_numpy(_read_frames_rgb(video_path))     # (T, H, W, C)
    rgb = ToFloatTensorInZeroOne()(rgb)                      # (C, T, H, W)
    rgb = Resize(224)(rgb)
    rgb = CenterCrop((224, 224))(rgb).unsqueeze(0)           # (1, C, T, H, W)

    feats = []
    with torch.no_grad():
        for start, end in form_slices(rgb.size(2), stack_size, step_size):
            out = net(rgb[:, :, start:end], features=True)
            feats.extend(out.numpy().tolist())
    return np.asarray(feats, dtype=np.float32)


def build_reference_clip(seed: int = 0):
    """Seeded reduced-geometry reference CLIP (full ViT-B/32 visual tower,
    2-layer text transformer — encode_image is unaffected by the text
    reduction and the full text checkpoint needs real weights)."""
    import importlib.util

    import torch

    spec = importlib.util.spec_from_file_location(
        'ref_clip_model_e2e',
        '/root/reference/models/clip/clip_src/model.py')
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    torch.manual_seed(seed)
    return mod.CLIP(embed_dim=512, image_resolution=224, vision_layers=12,
                    vision_width=768, vision_patch_size=32,
                    context_length=77, vocab_size=512,
                    transformer_width=512, transformer_heads=8,
                    transformer_layers=2).eval().float()


def _framewise_reference_inputs(video_path, resize, crop, interp, mean, std):
    """Per-frame torchvision-PIL eval preprocessing (the chain shared by
    the reference's frame-wise extractors): PIL short-side resize
    (truncating long-side formula) → round-offset CenterCrop → ToTensor
    (/255) → Normalize. Yields (1, C, crop, crop) tensors."""
    import torch
    from PIL import Image

    mean = torch.tensor(mean).view(3, 1, 1)
    std = torch.tensor(std).view(3, 1, 1)
    for frame in _read_frames_rgb(video_path):
        img = Image.fromarray(frame)
        w, h = img.size
        if w < h:
            size = (resize, int(resize * h / w))   # torchvision Resize(int)
        else:
            size = (int(resize * w / h), resize)
        img = img.resize(size, interp)
        w, h = img.size
        top = int(round((h - crop) / 2.0))
        left = int(round((w - crop) / 2.0))
        img = img.crop((left, top, left + crop, top + crop))
        # np.array copies: PIL hands back a read-only buffer and
        # torch.from_numpy warns on non-writable arrays
        x = torch.from_numpy(np.array(img)).permute(2, 0, 1).float()
        yield ((x / 255.0 - mean) / std).unsqueeze(0)


def run_reference_clip(video_path: str, net) -> np.ndarray:
    """The reference CLIP frame-wise extraction, verbatim semantics.

    Mirrors reference models/clip/extract_clip.py + clip_src/clip.py
    `_transform`: per frame, PIL bicubic resize short-side → input
    resolution, CenterCrop, ToTensor (/255), Normalize(CLIP stats), then
    `encode_image` (extract_clip.py:69-84).
    """
    import torch
    from PIL import Image

    feats = []
    with torch.no_grad():
        for x in _framewise_reference_inputs(
                video_path, resize=224, crop=224, interp=Image.BICUBIC,
                mean=[0.48145466, 0.4578275, 0.40821073],
                std=[0.26862954, 0.26130258, 0.27577711]):
            feats.extend(net.encode_image(x).numpy().tolist())
    return np.asarray(feats, dtype=np.float32)


def run_reference_resnet(video_path: str, net) -> np.ndarray:
    """The reference resnet frame-wise extraction, verbatim semantics.

    Mirrors reference models/resnet/extract_resnet.py:38-50: torchvision's
    IMAGENET1K_V1 eval transform — ToPILImage → PIL bilinear resize short
    side 256 → CenterCrop(224) → ToTensor → Normalize(ImageNet stats) —
    then the fc-stripped net. ``net`` must return features from a plain
    ``net(x)`` call (the torchvision mirror's default, or real torchvision
    with ``model.fc = nn.Identity()``).
    """
    import torch
    from PIL import Image

    feats = []
    with torch.no_grad():
        for x in _framewise_reference_inputs(
                video_path, resize=256, crop=224, interp=Image.BILINEAR,
                mean=[0.485, 0.456, 0.406], std=[0.229, 0.224, 0.225]):
            feats.extend(net(x).numpy().tolist())
    return np.asarray(feats, dtype=np.float32)


def write_real_audio_wav(path: str, sr: int = 16000,
                         source_video: str = '/root/reference/sample/'
                                             'v_GGSY1Qvo990.mp4') -> str:
    """Write a 16 kHz 16-bit PCM wav with REAL audio content: the sample
    clip's soundtrack via the native decoder when built, else a synthesized
    chirp+noise mix. The single fixture builder shared by the vggish golden
    test and tools/measure_parity.py — both sides of each comparison read
    the identical file, so provenance affects realism only."""
    import wave

    from video_features_tpu.io import native

    if native.available():
        from video_features_tpu.io.native import read_audio_native
        data, got_sr = read_audio_native(source_video, sr)
        assert got_sr == sr
    else:  # pragma: no cover - env without the native decoder
        rng = np.random.RandomState(0)
        t = np.arange(sr * 10) / sr
        data = (0.4 * np.sin(2 * np.pi * (200 + 40 * t) * t)
                + 0.1 * rng.randn(len(t)))
    pcm = np.clip(np.asarray(data, np.float64) * 32768.0,
                  -32768, 32767).astype('<i2')
    with wave.open(str(path), 'wb') as f:
        f.setnchannels(1)
        f.setsampwidth(2)
        f.setframerate(sr)
        f.writeframes(pcm.tobytes())
    return str(path)


def resample_reference_literal(x: np.ndarray, sr_orig: int,
                               sr_new: int) -> np.ndarray:
    """Straight-line transcription of resampy 0.4.2's resample_f loop
    (resampy/interpn.py) + core.resample setup with the kaiser_best
    filter — the resample the reference's vggish_input.py:47-49 performs.
    resampy is not installable here, so this literal per-sample loop
    stands in for it on the reference side; the production vectorized
    implementation (ops/audio.py:resample_kaiser) is pinned against THIS
    function in tests/test_audio_resample.py.

    Everything here — filter table construction included — is written
    from resampy's published code with literal constants, sharing NO code
    with the production module, so a misreading in ops/audio.py cannot
    cancel out."""
    from fractions import Fraction

    from scipy.signal.windows import kaiser

    # resampy/filters.py sinc_window with the kaiser_best constants:
    # 64 zero crossings, 2^9 table entries per crossing,
    # beta 14.769656459379492, rolloff 0.9475937167399596
    num_table = 512
    n = num_table * 64
    rolloff = 0.9475937167399596
    sinc_right = rolloff * np.sinc(
        rolloff * np.linspace(0, 64, num=n + 1, endpoint=True))
    interp_win = kaiser(2 * n + 1, 14.769656459379492)[n:] * sinc_right

    ratio = Fraction(int(sr_new), int(sr_orig))
    sample_ratio = float(ratio)
    if sample_ratio < 1:
        interp_win = interp_win * sample_ratio
    interp_delta = np.zeros_like(interp_win)
    interp_delta[:-1] = np.diff(interp_win)
    scale = min(1.0, sample_ratio)
    index_step = int(scale * num_table)
    nwin = interp_win.shape[0]
    n_orig = x.shape[0]
    # resampy ≥0.4.0 (resampy/core.py): shape[axis] * sr_new // sr_orig —
    # integer floor, its 0.4.0 output-length rounding fix
    n_out = n_orig * int(sr_new) // int(sr_orig)
    y = np.zeros(n_out, dtype=np.float64)
    for t in range(n_out):
        time_register = t / sample_ratio
        n = int(time_register)
        frac = scale * (time_register - n)
        index_frac = frac * num_table
        offset = int(index_frac)
        eta = index_frac - offset
        i_max = min(n + 1, (nwin - offset) // index_step)
        for i in range(i_max):
            weight = (interp_win[offset + i * index_step]
                      + eta * interp_delta[offset + i * index_step])
            y[t] += weight * x[n - i]
        frac = scale - frac
        index_frac = frac * num_table
        offset = int(index_frac)
        eta = index_frac - offset
        k_max = min(n_orig - n - 1, (nwin - offset) // index_step)
        for k in range(k_max):
            weight = (interp_win[offset + k * index_step]
                      + eta * interp_delta[offset + k * index_step])
            y[t] += weight * x[n + k + 1]
    return y


def run_reference_vggish(wav_path: str, net) -> np.ndarray:
    """The reference vggish extraction, verbatim semantics, composed from
    the reference's own importable pieces.

    Mirrors reference models/vggish/extract_vggish.py:31-62 +
    vggish_src/vggish_input.py:75-99: int16 wav → /32768 → mono →
    resample to 16 kHz when needed (the reference calls resampy, which is
    not importable here — :func:`resample_reference_literal` is its
    literal transcription) → the reference's OWN
    mel_features.log_mel_spectrogram with vggish_params constants →
    mel_features.frame into (N, 96, 64) examples → the VGG net
    (postprocess is a no-op by default: the vendored Postprocessor.forward
    returns its input unless post_process=True, vggish_slim.py:150-156).
    ``net`` is the state-dict-matched torch mirror
    (tests/torch_mirrors.TorchVGGish) or the real checkpoint loaded into it.
    """
    import wave

    import torch

    from models.vggish.vggish_src import mel_features, vggish_params

    with wave.open(wav_path, 'rb') as f:
        assert f.getsampwidth() == 2, 'expected 16-bit PCM'
        sr = f.getframerate()
        raw = np.frombuffer(f.readframes(f.getnframes()), dtype='<i2')
        if f.getnchannels() > 1:
            raw = raw.reshape(-1, f.getnchannels())
    samples = raw / 32768.0                      # sf.read int16 convention
    if samples.ndim > 1:
        samples = np.mean(samples, axis=1)
    if sr != vggish_params.SAMPLE_RATE:          # vggish_input.py:47-49
        samples = resample_reference_literal(samples, sr,
                                             vggish_params.SAMPLE_RATE)

    log_mel = mel_features.log_mel_spectrogram(
        samples,
        audio_sample_rate=vggish_params.SAMPLE_RATE,
        log_offset=vggish_params.LOG_OFFSET,
        window_length_secs=vggish_params.STFT_WINDOW_LENGTH_SECONDS,
        hop_length_secs=vggish_params.STFT_HOP_LENGTH_SECONDS,
        num_mel_bins=vggish_params.NUM_MEL_BINS,
        lower_edge_hertz=vggish_params.MEL_MIN_HZ,
        upper_edge_hertz=vggish_params.MEL_MAX_HZ)
    features_sample_rate = 1.0 / vggish_params.STFT_HOP_LENGTH_SECONDS
    window = int(round(vggish_params.EXAMPLE_WINDOW_SECONDS
                       * features_sample_rate))
    hop = int(round(vggish_params.EXAMPLE_HOP_SECONDS * features_sample_rate))
    examples = mel_features.frame(log_mel, window_length=window,
                                  hop_length=hop)

    x = torch.tensor(examples)[:, None, :, :].float()
    with torch.no_grad():
        return net(x).numpy().astype(np.float32)


def build_reference_r21d_net(seed: int = 0, state_dict=None):
    """Seeded (or checkpoint-loaded) torchvision-mirror VideoResNet +
    the .pt path ingredients shared by test_golden_e2e and measure_parity."""
    import torch

    from tests.torch_mirrors import TorchVideoResNet, randomize_bn_stats

    torch.manual_seed(seed)
    net = TorchVideoResNet('r2plus1d_18').eval()
    randomize_bn_stats(net, seed=seed)
    if state_dict is not None:
        net.load_state_dict(state_dict)
    return net


R21D_OVERRIDES = {
    'device': 'cpu', 'precision': 'highest', 'decode_backend': 'cv2',
    'model_name': 'r2plus1d_18_16_kinetics', 'stack_size': 16,
    'step_size': 16,
}
