"""Content-addressed feature cache (cache/): the one invariant threaded
through every path is that a cache hit's output files are BYTE-IDENTICAL
to a cold extraction's, while skipping decode + inference entirely
(tracer-verified stage counts). Covers the CLI per-video loop, the
packed worklist (hits drop out before batch planning), the serve daemon
(hits answered before admission control), LRU eviction under size
pressure, corrupt-entry eviction, config-aware resume, and the offline
GC tool.

Fixture weight class matches tests/test_serve.py: resnet18 random
(seeded → deterministic) weights on CPU over tiny noise clips.
"""
import json
import os
from pathlib import Path

import numpy as np
import pytest

from video_features_tpu.config import load_config
from video_features_tpu.registry import create_extractor
from video_features_tpu.utils.output import make_path


from tools.make_sample_video import write_noise_clip as _write_clip  # noqa: E402

RESNET_KEYS = ('resnet', 'fps', 'timestamps_ms')


@pytest.fixture(scope='module')
def cache_clips(tmp_path_factory):
    d = tmp_path_factory.mktemp('cachevids')
    return [_write_clip(d / f'cv{i}.mp4', n, seed=i)
            for i, n in enumerate((9, 5))]


def _args(paths, out, tmp, **kw):
    over = dict(video_paths=paths, device='cpu', model_name='resnet18',
                batch_size=4, allow_random_weights=True,
                on_extraction='save_numpy', output_path=str(out),
                tmp_path=str(tmp))
    over.update(kw)
    return load_config('resnet', overrides=over)


def _extractor(paths, out, tmp, **kw):
    return create_extractor(_args(paths, out, tmp, **kw))


def _assert_identical_outputs(root_a, root_b, paths, keys=RESNET_KEYS):
    for p in paths:
        for key in keys:
            a = Path(make_path(str(root_a), p, key, '.npy'))
            b = Path(make_path(str(root_b), p, key, '.npy'))
            assert a.read_bytes() == b.read_bytes(), (p, key)


# -- key derivation (no jax, no extraction) ----------------------------------

def test_fingerprint_ignores_irrelevant_keys_and_tracks_relevant():
    from video_features_tpu.cache import config_fingerprint

    base = {'feature_type': 'resnet', 'model_name': 'resnet18',
            'batch_size': 4, 'output_path': '/a', 'tmp_path': '/b',
            'device': 'cpu', 'profile': False, 'cache_enabled': True,
            'cache_dir': '/c', 'pack_across_videos': False}
    fp = config_fingerprint(base)
    # routing/device/profiling/cache knobs must not fragment the key space
    assert config_fingerprint(dict(base, output_path='/x', tmp_path='/y',
                                   device='tpu', profile=True,
                                   cache_enabled=False, cache_dir='/z',
                                   pack_across_videos=True)) == fp
    # extraction-relevant knobs must invalidate
    assert config_fingerprint(dict(base, model_name='resnet50')) != fp
    assert config_fingerprint(dict(base, extraction_fps=5)) != fp
    # unknown future knobs stay IN the fingerprint (fail-closed)
    assert config_fingerprint(dict(base, new_knob=1)) != fp


def test_weights_fingerprint_tracks_checkpoint_content(tmp_path):
    from video_features_tpu.cache import weights_fingerprint

    ckpt = tmp_path / 'w.npz'
    ckpt.write_bytes(b'weights-v1')
    a = weights_fingerprint({'checkpoint_path': str(ckpt)})
    # same content under a different path → same identity
    copy = tmp_path / 'w_copy.npz'
    copy.write_bytes(b'weights-v1')
    assert weights_fingerprint({'checkpoint_path': str(copy)}) == a
    # swapped content under the SAME path → invalidates
    ckpt.write_bytes(b'weights-v2')
    os.utime(ckpt, ns=(1, 1))          # defeat the stat memo deliberately
    assert weights_fingerprint({'checkpoint_path': str(ckpt)}) != a
    # null checkpoint (random weights) is a distinct, stable identity
    assert weights_fingerprint({'checkpoint_path': None}) \
        == weights_fingerprint({'checkpoint_path': None})


def test_video_key_is_content_addressed(tmp_path):
    from video_features_tpu.cache import video_cache_key

    v1 = tmp_path / 'a.mp4'
    v1.write_bytes(b'same bytes')
    v2 = tmp_path / 'b.mp4'
    v2.write_bytes(b'same bytes')
    v3 = tmp_path / 'c.mp4'
    v3.write_bytes(b'other bytes')
    assert video_cache_key(str(v1), 'fp') == video_cache_key(str(v2), 'fp')
    assert video_cache_key(str(v1), 'fp') != video_cache_key(str(v3), 'fp')
    assert video_cache_key(str(v1), 'fp') != video_cache_key(str(v1), 'fp2')


# -- store mechanics (no jax) ------------------------------------------------

def _fill_store(tmp_path, n_entries, file_bytes=1000, max_bytes=None):
    from video_features_tpu.cache.store import FeatureCache

    cache = FeatureCache(str(tmp_path / 'store'), max_bytes=max_bytes)
    src_dir = tmp_path / 'srcs'
    src_dir.mkdir(exist_ok=True)
    for i in range(n_entries):
        src = src_dir / f's{i}.npy'
        src.write_bytes(bytes([i % 251]) * file_bytes)
        cache.put(f'key{i:04d}', {'feat': (str(src), '.npy')})
    return cache


def test_lru_eviction_under_size_pressure(tmp_path):
    from video_features_tpu.cache.store import FeatureCache

    cache = _fill_store(tmp_path, 4, file_bytes=1000)
    # touch entry 0 so it is the MOST recently used despite oldest insert
    out = tmp_path / 'out'
    assert cache.fetch_to('key0000', str(out), '/v/clip.mp4')
    report = cache.gc(target_bytes=2000)
    assert report['lru_evicted'] == 2
    # LRU order: 1 and 2 evicted; 0 (touched) and 3 (newest) survive
    assert cache.contains('key0000') and cache.contains('key0003')
    assert not cache.contains('key0001') and not cache.contains('key0002')
    assert cache.stats()['bytes'] <= 2000
    # a fresh instance replaying the compacted manifest agrees
    reloaded = FeatureCache(cache.cache_dir)
    assert reloaded.stats()['entries'] == 2
    assert reloaded.contains('key0000') and reloaded.contains('key0003')


def test_inline_eviction_on_publish_over_max_bytes(tmp_path):
    cache = _fill_store(tmp_path, 5, file_bytes=1000, max_bytes=3000)
    st = cache.stats()
    assert st['bytes'] <= 3000
    assert st['evictions'] >= 2
    assert cache.contains('key0004')            # the newest always survives


def test_on_evict_callback_may_reenter_the_cache(tmp_path):
    """Eviction subscribers fire OUTSIDE the store lock: a callback that
    calls back into the cache (the index ingest thread does exactly
    this) must neither deadlock nor see a stale index."""
    cache = _fill_store(tmp_path, 4, file_bytes=1000)
    seen = []

    def reentrant(key, corrupt):
        # re-enter through the locked public surface — a lock held
        # across the callback would deadlock right here
        seen.append((key, corrupt, cache.contains(key)))
        cache.stats()

    cache.on_evict.append(reentrant)
    report = cache.gc(target_bytes=2000)
    assert report['lru_evicted'] == 2
    assert len(seen) == 2
    # by notification time the entry is already gone from the index
    assert all(not present for _, _, present in seen)
    assert all(not corrupt for _, corrupt, _ in seen)


def test_corrupt_entry_evicted_not_served(tmp_path):
    cache = _fill_store(tmp_path, 2)
    edir = Path(cache.cache_dir) / 'objects' / 'ke' / 'key0000'
    (edir / 'feat.npy').write_bytes(b'short')   # truncate
    out = tmp_path / 'o'
    assert not cache.fetch_to('key0000', str(out), '/v/x.mp4')
    st = cache.stats()
    assert st['corrupt_evicted'] == 1 and not cache.contains('key0000')
    assert not Path(make_path(str(out), '/v/x.mp4', 'feat', '.npy')).exists()
    # the healthy entry still serves
    assert cache.fetch_to('key0001', str(out), '/v/y.mp4')


def test_gc_verify_catches_same_size_bit_rot(tmp_path):
    cache = _fill_store(tmp_path, 2, file_bytes=64)
    edir = Path(cache.cache_dir) / 'objects' / 'ke' / 'key0000'
    (edir / 'feat.npy').write_bytes(b'X' * 64)  # same size, wrong bytes
    assert cache.gc(verify=False)['corrupt_evicted'] == 0  # size check blind
    report = cache.gc(verify=True)
    assert report['corrupt_evicted'] == 1
    assert not cache.contains('key0000') and cache.contains('key0001')


def test_manifest_tolerates_torn_tail_line(tmp_path):
    from video_features_tpu.cache.store import FeatureCache

    cache = _fill_store(tmp_path, 2)
    with open(cache.manifest_path, 'a') as f:
        f.write('{"op": "put", "key": "torn')   # crash mid-append
    reloaded = FeatureCache(cache.cache_dir)
    assert reloaded.stats()['entries'] == 2


def test_cache_gc_tool_exit_codes_and_report(tmp_path, capsys):
    import tools.cache_gc as gc_tool

    cache = _fill_store(tmp_path, 3, file_bytes=500)
    # clean run: exit 0, JSON report on stdout
    assert gc_tool.main(['--cache-dir', cache.cache_dir]) == 0
    report = json.loads(capsys.readouterr().out.strip())
    assert report['entries_after'] == 3 and report['corrupt_evicted'] == 0
    # corrupt an entry: --verify finds it, exit 1
    edir = Path(cache.cache_dir) / 'objects' / 'ke' / 'key0001'
    (edir / 'feat.npy').write_bytes(b'Z' * 500)
    assert gc_tool.main(['--cache-dir', cache.cache_dir, '--verify']) == 1
    report = json.loads(capsys.readouterr().out.strip())
    assert report['corrupt_evicted'] == 1
    # size pressure: evict down to one entry's bytes
    assert gc_tool.main(['--cache-dir', cache.cache_dir,
                         '--target-bytes', '500']) == 0
    report = json.loads(capsys.readouterr().out.strip())
    assert report['bytes_after'] <= 500
    # usage errors: exit 2
    assert gc_tool.main(['--cache-dir', str(tmp_path / 'nope')]) == 2
    assert gc_tool.main(['--cache-dir', cache.cache_dir,
                         '--target-bytes', '-1']) == 2


def test_corrupt_output_error_raised_on_truncated_files(tmp_path):
    from video_features_tpu.utils.output import (
        CorruptOutputError, load_numpy, load_pickle, write_numpy,
        write_pickle,
    )

    npy = str(tmp_path / 'a.npy')
    write_numpy(npy, np.arange(8))
    Path(npy).write_bytes(Path(npy).read_bytes()[:20])      # truncate
    with pytest.raises(CorruptOutputError):
        load_numpy(npy)
    pkl = str(tmp_path / 'b.pkl')
    write_pickle(pkl, {'x': 1})
    Path(pkl).write_bytes(b'')                              # empty
    with pytest.raises(CorruptOutputError):
        load_pickle(pkl)
    with pytest.raises(FileNotFoundError):                  # NOT corruption
        load_numpy(str(tmp_path / 'missing.npy'))


# -- CLI per-video loop ------------------------------------------------------

def test_cli_path_hit_is_byte_identical_and_skips_compute(
        cache_clips, tmp_path):
    cache_dir = str(tmp_path / 'fc')

    def run_pass(tag):
        ex = _extractor(cache_clips, tmp_path / tag, tmp_path / 'tmp',
                        cache_enabled=True, cache_dir=cache_dir,
                        profile=True)
        ex.tracer.reset = lambda: None   # accumulate stages across videos
        for p in cache_clips:
            ex._extract(p)
        return ex, ex.tracer.report()

    ex1, rep1 = run_pass('cold')
    assert rep1['model']['count'] > 0
    assert ex1.cache.stats()['puts'] == len(cache_clips)

    ex2, rep2 = run_pass('warm')
    # the acceptance tracer check: hits ran no decode and no model step
    assert 'model' not in rep2 and 'decode+preprocess' not in rep2, rep2
    assert rep2['cache_lookup']['count'] == len(cache_clips)
    assert ex2.cache.stats()['hits'] == len(cache_clips)
    _assert_identical_outputs(ex1.output_path, ex2.output_path, cache_clips)


def test_cache_disabled_reproduces_legacy_behavior(cache_clips, tmp_path):
    """Without cache_enabled nothing consults or populates a cache and no
    cache stages appear — today's behavior exactly."""
    ex = _extractor(cache_clips, tmp_path / 'out', tmp_path / 'tmp',
                    profile=True)
    assert ex.cache is None
    ex.tracer.reset = lambda: None
    for p in cache_clips:
        ex._extract(p)
    rep = ex.tracer.report()
    assert 'cache_lookup' not in rep and 'cache_publish' not in rep
    # outputs still produced through the unchanged save path
    for p in cache_clips:
        assert Path(make_path(ex.output_path, p, 'resnet', '.npy')).exists()


def test_packed_worklist_drops_hits_before_batch_planning(
        cache_clips, tmp_path):
    cache_dir = str(tmp_path / 'fc_packed')

    def run_pass(tag):
        ex = _extractor(cache_clips, tmp_path / tag, tmp_path / 'tmp',
                        cache_enabled=True, cache_dir=cache_dir,
                        pack_across_videos=True, profile=True)
        ex.tracer.reset = lambda: None
        ex.extract_packed(cache_clips)
        return ex, ex.tracer.report()

    ex1, rep1 = run_pass('pk_cold')
    assert rep1['model']['count'] > 0
    ex2, rep2 = run_pass('pk_warm')
    # hits never produced windows: no device batch ever packed
    assert 'model' not in rep2 and 'h2d' not in rep2, rep2
    assert ex2.cache.stats()['hits'] == len(cache_clips)
    _assert_identical_outputs(ex1.output_path, ex2.output_path, cache_clips)


# -- config-aware resume (satellite) -----------------------------------------

def test_resume_reextracts_on_config_change_with_warning(
        cache_clips, tmp_path, capsys):
    out, tmp = tmp_path / 'out', tmp_path / 'tmp'
    clip = cache_clips[0]
    ex_a = _extractor([clip], out, tmp)
    ex_a._extract(clip)
    # same config skips (fingerprint sidecar matches)
    capsys.readouterr()
    ex_a2 = _extractor([clip], out, tmp)
    ex_a2._extract(clip)
    assert 'already exist' in capsys.readouterr().out

    # a different extraction recipe must NOT reuse those outputs
    feat_path = Path(make_path(str(out / 'resnet' / 'resnet18'), clip,
                               'resnet', '.npy'))
    before = feat_path.read_bytes()
    with pytest.warns(UserWarning, match='different config'):
        ex_b = _extractor([clip], out, tmp, extraction_fps=2)
        ex_b._extract(clip)
    after = feat_path.read_bytes()
    assert before != after            # re-extracted under the new recipe
    # and the sidecar now records the new fingerprint → new config skips
    capsys.readouterr()
    ex_b2 = _extractor([clip], out, tmp, extraction_fps=2)
    ex_b2._extract(clip)
    assert 'already exist' in capsys.readouterr().out


def test_resume_legacy_outputs_without_sidecar_still_skip(
        cache_clips, tmp_path, capsys):
    out, tmp = tmp_path / 'out', tmp_path / 'tmp'
    clip = cache_clips[0]
    ex = _extractor([clip], out, tmp)
    ex._extract(clip)
    # simulate pre-fingerprint outputs: drop the sidecar
    side = Path(make_path(str(out / 'resnet' / 'resnet18'), clip,
                          'fingerprint', '.json'))
    side.unlink()
    capsys.readouterr()
    ex2 = _extractor([clip], out, tmp)
    ex2._extract(clip)
    assert 'already exist' in capsys.readouterr().out   # legacy skip kept


# -- serve path --------------------------------------------------------------

def test_serve_answers_hits_before_admission(cache_clips, tmp_path):
    from video_features_tpu.serve.client import ServeClient
    from video_features_tpu.serve.server import ExtractionServer

    server = ExtractionServer(
        base_overrides={
            'device': 'cpu', 'model_name': 'resnet18', 'batch_size': 4,
            'allow_random_weights': True, 'on_extraction': 'save_numpy',
            'tmp_path': str(tmp_path / 'serve_tmp'),
            'cache_enabled': True,
            'cache_dir': str(tmp_path / 'serve_cache'),
        },
        queue_depth=8, pool_size=2).start()
    try:
        client = ServeClient(port=server.port)
        out_cold = str(tmp_path / 'cold')
        rid = client.submit('resnet', cache_clips,
                            overrides={'output_path': out_cold})
        st = client.wait(rid, timeout_s=180)
        assert st['state'] == 'done', st
        assert set(st['videos'].values()) == {'saved'}

        # warm pass: every video answered from cache, request terminal at
        # birth — no queue slot, no worker wakeup
        depth_before = server.metrics()['queue']['depth']
        out_warm = str(tmp_path / 'warm')
        rid2 = client.submit('resnet', cache_clips,
                             overrides={'output_path': out_warm})
        st2 = client.status(rid2)      # no wait: must already be terminal
        assert st2['state'] == 'done', st2
        assert set(st2['videos'].values()) == {'cached'}
        m = client.metrics()
        assert m['queue']['depth'] == depth_before   # never occupied a slot
        assert m['cache']['hits'] == len(cache_clips)
        assert m['cache']['bytes_saved'] > 0
        assert m['requests']['cached_videos'] == len(cache_clips)
        _assert_identical_outputs(
            os.path.join(out_cold, 'resnet', 'resnet18'),
            os.path.join(out_warm, 'resnet', 'resnet18'), cache_clips)

        # a mixed request: one known video (hit) + one new (extracted)
        extra = _write_clip(tmp_path / 'extra.mp4', 7, seed=9)
        out_mix = str(tmp_path / 'mix')
        rid3 = client.submit('resnet', [cache_clips[0], str(extra)],
                             overrides={'output_path': out_mix})
        st3 = client.wait(rid3, timeout_s=180)
        assert st3['state'] == 'done', st3
        assert st3['videos'][cache_clips[0]] == 'cached'
        assert st3['videos'][str(extra)] == 'saved'
    finally:
        server.drain(wait=True, grace_s=60)
