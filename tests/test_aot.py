"""vft-aot: the persistent executable store (aot/) — zero cold start.

Tier-1 budget discipline (the 870 s cap): the extractor-building
coverage shares ONE module-scoped cold fixture — a single resnet18
build whose packed run publishes the store — and every downstream test
(warm CLI repeat, serve compile-free boot) consumes that store instead
of paying its own cold build; multi-family store coverage lives in the
slow lane. Store/runtime units and the GC tool run on fabricated
stores and toy jits — no extractor builds at all.
"""
import json
import os
import sys
from pathlib import Path

import numpy as np
import pytest

REPO = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO))

from tools.make_sample_video import write_noise_clip  # noqa: E402

from video_features_tpu.aot.store import ExecStore, exec_digest  # noqa: E402


def _mkstore(tmp_path, **kw) -> ExecStore:
    # fresh instance, NOT ExecStore.get: unit tests must not share the
    # process-global registry (counters would bleed across tests)
    return ExecStore(str(tmp_path / 'store'), **kw)


# -- store units (jax-free) ---------------------------------------------------


def test_store_roundtrip_idempotent_and_replay(tmp_path):
    store = _mkstore(tmp_path)
    digest = exec_digest({'program_sha': 'abc', 'lane': 'mesh1'})
    payload = b'x' * 1024
    assert store.fetch(digest) is None           # cold miss
    store.put(digest, payload, meta={'program_sha': 'abc',
                                     'feature_type': 'toy'})
    store.put(digest, payload)                   # idempotent (touch only)
    assert store.puts == 1
    assert store.fetch(digest) == payload
    assert store.stats()['hits'] == 1 and store.stats()['entries'] == 1
    # a FRESH instance replays the manifest and serves the same bytes
    again = ExecStore(store.aot_dir)
    assert again.fetch(digest) == payload
    assert again.stats()['bytes'] == len(payload)


def test_store_truncated_payload_evicted_not_served(tmp_path):
    store = _mkstore(tmp_path)
    digest = exec_digest({'program_sha': 'corrupt-me', 'lane': 'mesh1'})
    store.put(digest, b'y' * 512)
    victim = Path(store._payload_path(digest))
    victim.write_bytes(victim.read_bytes()[:100])     # torn write / rot
    assert store.fetch(digest) is None
    st = store.stats()
    assert st['corrupt_evicted'] == 1 and st['entries'] == 0
    # a deserialize-time failure reported back also purges
    digest2 = exec_digest({'program_sha': 'poisoned', 'lane': 'mesh1'})
    store.put(digest2, b'z' * 64)
    store.evict_corrupt(digest2)
    assert store.fetch(digest2) is None
    assert store.stats()['corrupt_evicted'] == 2


def test_store_lru_gc_to_target_bytes(tmp_path):
    store = _mkstore(tmp_path)
    digests = []
    for i in range(4):
        d = exec_digest({'program_sha': f'p{i}', 'lane': 'mesh1'})
        store.put(d, bytes([i]) * 1000)
        digests.append(d)
    store.fetch(digests[0])                      # refresh oldest → MRU
    report = store.gc(target_bytes=2000)
    assert report['lru_evicted'] == 2
    assert store.fetch(digests[0]) is not None   # refreshed survivor
    assert store.fetch(digests[3]) is not None   # newest survivor
    assert store.fetch(digests[1]) is None and store.fetch(digests[2]) is None
    # inline pressure on publish: max_bytes bounds the store online too
    bounded = ExecStore(str(tmp_path / 'bounded'), max_bytes=2500)
    for i in range(3):
        bounded.put(exec_digest({'program_sha': f'b{i}', 'lane': 'm'}),
                    bytes([i]) * 1000)
    assert bounded.stats()['bytes'] <= 2500


def test_store_gc_compaction_keeps_concurrent_puts(tmp_path):
    """A put another process appends WHILE a (long) gc sweep runs must
    survive the compaction rewrite — dropping its record would turn a
    later orphan sweep into data loss for an entry a live daemon still
    serves. Simulated by publishing through a SECOND instance after the
    first instance loaded its view."""
    store = _mkstore(tmp_path)
    kept = exec_digest({'program_sha': 'kept', 'lane': 'mesh1'})
    store.put(kept, b'k' * 100)
    # a concurrent process publishes AFTER `store` loaded its view...
    other = ExecStore(store.aot_dir)
    racing = exec_digest({'program_sha': 'racing', 'lane': 'mesh1'})
    other.put(racing, b'r' * 100)
    # ...which `store`'s in-memory index has never seen; its gc reloads,
    # but the race window is between that reload and the compaction —
    # emulate it by publishing during the sweep via the reload hook
    real_load = store._load_manifest
    state = {'raced': False}

    def load_then_race():
        real_load()
        if not state['raced']:
            state['raced'] = True
            late = ExecStore(store.aot_dir)
            late.put(exec_digest({'program_sha': 'late', 'lane': 'm'}),
                     b'l' * 100)

    store._load_manifest = load_then_race
    store.gc(verify=True)
    # every entry survives the rewrite — including the one that landed
    # mid-sweep
    final = ExecStore(store.aot_dir)
    assert final.fetch(kept) is not None
    assert final.fetch(racing) is not None
    assert final.fetch(exec_digest({'program_sha': 'late',
                                    'lane': 'm'})) is not None


def test_aot_gc_tool_exit_codes(tmp_path):
    from tools.aot_gc import main as gc_main

    store = ExecStore(str(tmp_path / 'store'))
    good = exec_digest({'program_sha': 'good', 'lane': 'mesh1'})
    bad = exec_digest({'program_sha': 'bad', 'lane': 'mesh1'})
    store.put(good, b'g' * 256)
    store.put(bad, b'b' * 256)
    # same-size bit rot: only --verify's re-hash can see it
    Path(store._payload_path(bad)).write_bytes(b'B' * 256)

    assert gc_main(['--aot-dir', store.aot_dir]) == 0     # size check ok
    assert gc_main(['--aot-dir', store.aot_dir, '--verify']) == 1
    assert gc_main(['--aot-dir', store.aot_dir, '--verify']) == 0  # purged
    assert ExecStore(store.aot_dir).fetch(bad) is None
    assert ExecStore(store.aot_dir).fetch(good) is not None
    assert gc_main(['--aot-dir', str(tmp_path / 'nope')]) == 2
    assert gc_main(['--aot-dir', store.aot_dir,
                    '--target-bytes', '-1']) == 2


# -- runtime units (toy jit; no extractor builds) -----------------------------


def test_runtime_roundtrip_and_environment_miss(tmp_path, monkeypatch):
    """ensure_program: compile+publish → a fresh consult LOADS with
    byte-identical outputs; a jax-version (or device-kind) drift is a
    SILENT miss that recompiles AND names the drift in a structured
    event — never an error."""
    import jax
    import jax.numpy as jnp

    from video_features_tpu.aot import runtime

    jitted = jax.jit(lambda p, x: jnp.tanh(x @ p['w']))
    p = {'w': np.random.RandomState(0).rand(16, 8).astype(np.float32)}
    x = np.random.RandomState(1).rand(4, 16).astype(np.float32)
    store = _mkstore(tmp_path)

    prog1, path1 = runtime.ensure_program(store, 'toy', jitted, (p, x),
                                          lane='mesh1', feature_type='t')
    assert path1 == 'compiled' and store.puts == 1
    prog2, path2 = runtime.ensure_program(store, 'toy', jitted, (p, x),
                                          lane='mesh1', feature_type='t')
    assert path2 == 'loaded'
    a = np.asarray(prog1(p, x))
    b = np.asarray(prog2(p, x))
    c = np.asarray(jitted(p, x))
    assert (a == b).all() and (a == c).all()     # loaded ≡ compiled ≡ jit
    assert prog1.program_sha == prog2.program_sha

    # environment drift: same program, different jax version → miss +
    # recompile + a structured event naming the drifted component
    events = []
    monkeypatch.setattr(runtime, 'event',
                        lambda *a, **kw: events.append((a, kw)))
    real_env = runtime.runtime_environment

    def skewed_env(devices):
        env = real_env(devices)
        env['jax'] = 'not-this-jax'
        return env

    monkeypatch.setattr(runtime, 'runtime_environment', skewed_env)
    prog3, path3 = runtime.ensure_program(store, 'toy', jitted, (p, x),
                                          lane='mesh1', feature_type='t')
    assert path3 == 'compiled'                   # silent miss, no raise
    assert (np.asarray(prog3(p, x)) == a).all()
    drift_events = [kw for _, kw in events if 'drift' in kw]
    assert drift_events and 'jax' in drift_events[0]['drift']
    assert store.puts == 2                       # republished under new key


def test_runtime_corrupt_payload_recompiles(tmp_path):
    """A payload that passes the size check but fails DESERIALIZE is
    evicted and recompiled — a poisoned entry must not fail every boot."""
    import jax
    import jax.numpy as jnp

    from video_features_tpu.aot import runtime

    jitted = jax.jit(lambda p, x: x * p)
    p = np.float32(2.0)
    x = np.arange(4, dtype=np.float32)
    store = _mkstore(tmp_path)
    _, path1 = runtime.ensure_program(store, 'toy', jitted, (p, x),
                                      lane='mesh1', feature_type='t')
    assert path1 == 'compiled'
    # same-size garbage: fetch serves it, deserialize must reject it
    digest = next(iter(store._index))
    size = store._index[digest]['size']
    Path(store._payload_path(digest)).write_bytes(b'\x00' * size)
    prog, path2 = runtime.ensure_program(store, 'toy', jitted, (p, x),
                                         lane='mesh1', feature_type='t')
    assert path2 == 'compiled'
    assert store.corrupt_evicted == 1
    assert (np.asarray(prog(p, x)) == np.asarray(jitted(p, x))).all()


def test_knob_classification_and_config_validation():
    """The aot_* knobs are classified (vft-lint: knob-classification):
    excluded from the cache fingerprint (outputs byte-identical by
    contract) but pool-key relevant (a worker consults the store it was
    built with); sanity_check validates the values."""
    from video_features_tpu.config import (
        AOT_DEFAULTS, KNOB_CLASSIFICATION, knob_exclude, load_config,
    )
    for knob in AOT_DEFAULTS:
        assert KNOB_CLASSIFICATION[knob] == 'pool_only'
        assert knob in knob_exclude('fingerprint')
        assert knob not in knob_exclude('pool_key')
    with pytest.raises(ValueError, match='aot_dir'):
        load_config('resnet', overrides={
            'video_paths': ['v.live'], 'aot_enabled': True,
            'aot_dir': None})
    with pytest.raises(ValueError, match='aot_max_bytes'):
        load_config('resnet', overrides={
            'video_paths': ['v.live'], 'aot_max_bytes': -5})
    from video_features_tpu.config import split_serve_config
    with pytest.raises(ValueError, match='serve_prewarm'):
        split_serve_config({'serve_prewarm': ['nosuchfamily']})
    # known but NOT serveable (no packed/serving support): pre-warming
    # it would burn a pool slot no request can reach — fails the boot
    with pytest.raises(ValueError, match='unserveable'):
        split_serve_config({'serve_prewarm': ['vggish']})


# -- extractor round trip (ONE shared cold build publishes the store) ---------


RESNET_OVERRIDES = dict(
    device='cpu', model_name='resnet18', batch_size=4,
    allow_random_weights=True, on_extraction='save_numpy',
    pack_across_videos=True)


def _npy_bytes(root) -> dict:
    return {f.name: f.read_bytes() for f in sorted(Path(root).rglob('*.npy'))}


@pytest.fixture(scope='module')
def aot_clips(tmp_path_factory):
    vids = tmp_path_factory.mktemp('aot_vids')
    return [str(write_noise_clip(vids / f'c{i}.mp4', n, seed=i))
            for i, n in enumerate((6, 4))]


@pytest.fixture(scope='module')
def cold_run(tmp_path_factory, aot_clips):
    """THE one cold extractor build: packed resnet run that compiles and
    publishes the store every other extractor-level test loads from."""
    from video_features_tpu.config import load_config
    from video_features_tpu.registry import create_extractor

    td = tmp_path_factory.mktemp('aot_cold')
    store_dir = str(td / 'exec_store')
    args = load_config('resnet', overrides=dict(
        RESNET_OVERRIDES, video_paths=aot_clips,
        output_path=str(td / 'out'), tmp_path=str(td / 'tmp'),
        aot_enabled=True, aot_dir=store_dir))
    ex = create_extractor(args)
    ex.extract_packed(aot_clips)
    return {'ex': ex, 'store_dir': store_dir,
            'out': _npy_bytes(td / 'out')}


def test_cli_repeat_loads_and_is_byte_identical(tmp_path_factory,
                                                aot_clips, cold_run):
    """The compile-free CLI repeat: a SECOND build against the published
    store resolves its program by LOADING (zero compiles) and its
    features are byte-identical to the cold run's."""
    from video_features_tpu.config import load_config
    from video_features_tpu.registry import create_extractor

    assert cold_run['ex'].aot_stats['compiled'] >= 1
    assert cold_run['ex'].aot_stats['loaded'] == 0
    td = tmp_path_factory.mktemp('aot_warm')
    args = load_config('resnet', overrides=dict(
        RESNET_OVERRIDES, video_paths=aot_clips,
        output_path=str(td / 'out'), tmp_path=str(td / 'tmp'),
        aot_enabled=True, aot_dir=cold_run['store_dir']))
    ex = create_extractor(args)
    ex.extract_packed(aot_clips)
    assert ex.aot_stats['loaded'] >= 1, ex.aot_stats
    assert ex.aot_stats['compiled'] == 0, ex.aot_stats
    assert _npy_bytes(td / 'out') == cold_run['out']
    # the manifest-facing snapshot names the path each program took
    snap = ex.aot_snapshot()
    assert snap['enabled'] and snap['loaded'] >= 1
    assert all(p['path'] == 'loaded' for p in snap['programs'].values())


def test_serve_boot_compile_free_against_published_store(
        tmp_path_factory, aot_clips, cold_run):
    """The acceptance pin (ISSUE 14): on an unchanged program set, a
    serve boot pre-warming from the store is COMPILE-FREE —
    ``builds_loaded`` == entries pre-warmed, ``builds_compiled == 0``,
    visible in pool stats and the metrics document — and the features
    it serves are byte-identical to the cold CLI run's."""
    from video_features_tpu.serve.client import ServeClient
    from video_features_tpu.serve.server import ExtractionServer

    td = tmp_path_factory.mktemp('aot_serve')
    server = ExtractionServer(
        base_overrides=dict(RESNET_OVERRIDES,
                            tmp_path=str(td / 'tmp'),
                            aot_enabled=True,
                            aot_dir=cold_run['store_dir']),
        queue_depth=8, pool_size=2).start()
    try:
        pre = server.prewarm(['resnet'])
        assert pre['entries'] == 1, pre
        assert pre['programs_compiled'] == 0, pre
        assert pre['programs_loaded'] >= 1, pre
        client = ServeClient(port=server.port)
        rid = client.submit('resnet', aot_clips,
                            overrides={'output_path': str(td / 'out')})
        assert client.wait(rid, timeout_s=300)['state'] == 'done'
        m = client.metrics()
        assert m['warm_pool']['builds_compiled'] == 0, m['warm_pool']
        assert m['warm_pool']['builds_loaded'] == pre['entries'] == 1
        # the pre-warmed entry answered the request (no second build)
        assert m['warm_pool']['hits'] == 1, m['warm_pool']
        assert m['aot']['programs_loaded'] >= 1
        assert m['aot']['programs_compiled'] == 0
    finally:
        server.drain(wait=True, grace_s=60)
    assert _npy_bytes(td / 'out') == cold_run['out']


def test_bench_diff_boot_rung_direction():
    """The zero-cold-start rungs are latency-direction
    (lower-is-better); the program hit rate gates like a throughput."""
    import tools.bench_diff as bd
    assert bd.lower_is_better('serve_boot_first_feature_s')
    assert bd.lower_is_better('serve_boot_first_feature_cold_s')
    assert not bd.lower_is_better('aot_hit_rate')


# -- slow lane: multi-family store coverage -----------------------------------


@pytest.mark.slow
def test_multi_family_store_roundtrip(tmp_path_factory):
    """A stack family (r21d: raw decode-geometry windows, its own
    program shape) through the same store: cold build compiles +
    publishes, a fresh build LOADS with byte-identical packed outputs —
    the store generalizes beyond the framewise fixture family."""
    from video_features_tpu.config import load_config
    from video_features_tpu.registry import create_extractor

    vids = tmp_path_factory.mktemp('mf_vids')
    clips = [str(write_noise_clip(vids / f'm{i}.mp4', n, seed=10 + i))
             for i, n in enumerate((20, 18))]
    td = tmp_path_factory.mktemp('mf_store')
    store_dir = str(td / 'exec_store')

    def run(tag):
        args = load_config('r21d', overrides=dict(
            video_paths=clips, device='cpu',
            model_name='r2plus1d_18_16_kinetics', stack_size=4,
            step_size=4, batch_size=2, allow_random_weights=True,
            on_extraction='save_numpy', pack_across_videos=True,
            output_path=str(td / f'out_{tag}'),
            tmp_path=str(td / f'tmp_{tag}'),
            aot_enabled=True, aot_dir=store_dir))
        ex = create_extractor(args)
        ex.extract_packed(clips)
        return ex, _npy_bytes(td / f'out_{tag}')

    ex1, out1 = run('cold')
    assert ex1.aot_stats['compiled'] >= 1 and out1
    ex2, out2 = run('warm')
    assert ex2.aot_stats['loaded'] >= 1 and ex2.aot_stats['compiled'] == 0
    assert out1 == out2


@pytest.mark.slow
def test_manifest_records_aot_section(tmp_path_factory, aot_clips,
                                      cold_run):
    """A manifest-enabled run against the warm store records the 'aot'
    section: enabled, per-program 'loaded' paths, StableHLO identities."""
    from video_features_tpu.config import load_config
    from video_features_tpu.registry import create_extractor

    td = tmp_path_factory.mktemp('aot_manifest')
    manifest = str(td / 'manifest.json')
    args = load_config('resnet', overrides=dict(
        RESNET_OVERRIDES, video_paths=aot_clips,
        output_path=str(td / 'out'), tmp_path=str(td / 'tmp'),
        aot_enabled=True, aot_dir=cold_run['store_dir'],
        manifest_out=manifest))
    ex = create_extractor(args)
    ex.extract_packed(aot_clips)
    ex.finish_obs()
    man = json.loads(Path(manifest).read_text())
    assert man['aot']['enabled'] is True
    assert man['aot']['loaded'] >= 1 and man['aot']['compiled'] == 0
    progs = man['aot']['programs']
    assert progs and all(p['path'] == 'loaded' for p in progs.values())
    assert all(len(p['stablehlo_sha256']) == 64 for p in progs.values())
