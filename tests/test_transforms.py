"""Transform numerics parity vs torch (CPU reference semantics)."""
import numpy as np
import pytest
import torch
import torch.nn.functional as F

from video_features_tpu.ops.transforms import (
    center_crop, flow_to_uint8_levels, normalize, resize_bilinear,
    scale_to_pm1, to_float_zero_one,
)


def test_resize_matches_torch_interpolate():
    rng = np.random.RandomState(0)
    x = rng.rand(2, 60, 80, 3).astype(np.float32)
    ours = np.asarray(resize_bilinear(x, (128, 171)))
    # torch works channels-first
    xt = torch.from_numpy(x).permute(0, 3, 1, 2)
    ref = F.interpolate(xt, size=(128, 171), mode='bilinear',
                        align_corners=False).permute(0, 2, 3, 1).numpy()
    np.testing.assert_allclose(ours, ref, atol=1e-5)


def test_resize_downscale_matches_torch():
    rng = np.random.RandomState(1)
    x = rng.rand(1, 240, 320, 3).astype(np.float32)
    ours = np.asarray(resize_bilinear(x, (128, 171)))
    xt = torch.from_numpy(x).permute(0, 3, 1, 2)
    ref = F.interpolate(xt, size=(128, 171), mode='bilinear',
                        align_corners=False).permute(0, 2, 3, 1).numpy()
    np.testing.assert_allclose(ours, ref, atol=1e-5)


def test_center_crop_matches_torch_offsets():
    # torch center_crop on (..., H, W): top = int(round((H - th) / 2.))
    x = np.arange(10 * 9 * 1, dtype=np.float32).reshape(1, 10, 9, 1)
    out = np.asarray(center_crop(x, (4, 4)))
    assert out.shape == (1, 4, 4, 1)
    # reference models/transforms.py:14-17: i = round((h - th) / 2.)
    i, j = int(round((10 - 4) / 2.0)), int(round((9 - 4) / 2.0))
    np.testing.assert_array_equal(out[0, :, :, 0], x[0, i:i + 4, j:j + 4, 0])


def test_to_float_zero_one():
    x = np.array([0, 128, 255], np.uint8).reshape(1, 1, 3, 1)
    out = np.asarray(to_float_zero_one(x))
    np.testing.assert_allclose(out.ravel(), [0, 128 / 255, 1.0], atol=1e-7)


def test_scale_to_pm1():
    x = np.array([0.0, 127.5, 255.0], np.float32)
    np.testing.assert_allclose(np.asarray(scale_to_pm1(x)), [-1, 0, 1], atol=1e-6)


def test_normalize():
    x = np.ones((1, 2, 2, 3), np.float32)
    out = np.asarray(normalize(x, [1, 1, 1], [2, 2, 2]))
    np.testing.assert_allclose(out, 0)


def test_flow_uint8_quantization_matches_reference_recipe(reference_repo):
    """Bit-match the reference's ACTUAL ToUInt8 (transforms.py:175:
    round(128 + 255/40·x) — offset 128, NOT the symmetric 127.5 its own
    docstring suggests; a 127.5 offset shifts ~half of all pixels one
    level and cost ~3e-3 E2E flow-feature drift before round 3 caught it).
    Probe values sit just off half-level boundaries where the two offsets
    disagree, plus the exact clamp edges."""
    import torch

    from models.transforms import Clamp, ToUInt8

    rng = np.random.RandomState(0)
    flow = np.concatenate([
        np.array([-25.0, -20.0, 0.0, 10.0, 20.0, 30.0], np.float32),
        (rng.rand(4096).astype(np.float32) * 50 - 25),
        # values whose 6.375·x fraction is near 0.5 (offset-sensitive)
        (np.arange(-127, 128) + 0.499).astype(np.float32) * (40 / 255.0),
    ])
    out = np.asarray(flow_to_uint8_levels(flow, 20.0))
    with torch.no_grad():
        expected = ToUInt8()(Clamp(-20, 20)(torch.from_numpy(flow))).numpy()
    np.testing.assert_array_equal(out, expected)


def test_resize_bilinear_scale_matches_torch_scale_factor():
    """The reference's short-side Resize(int) interpolates at the GIVEN
    scale (F.interpolate(scale_factor=s, recompute_scale_factor=False)),
    whose grid differs from size-based out/in on the non-short axis —
    resize_bilinear_scale must match torch exactly."""
    import torch
    import torch.nn.functional as F

    from video_features_tpu.ops.transforms import resize_bilinear_scale

    rng = np.random.RandomState(0)
    x = rng.rand(2, 240, 320, 3).astype(np.float32)
    scale = 224.0 / 240.0

    ref = F.interpolate(torch.from_numpy(x).permute(0, 3, 1, 2),
                        scale_factor=scale, mode='bilinear',
                        align_corners=False, recompute_scale_factor=False)
    ref = ref.permute(0, 2, 3, 1).numpy()            # (2, 224, 298, 3)

    got = np.asarray(resize_bilinear_scale(x, ref.shape[1:3], scale))
    assert got.shape == ref.shape
    # matmul-lerp vs scalar-lerp fp32 accumulation: ~2.5e-5 abs noise; a
    # grid mismatch (the bug this guards) shows up at the 1e-2 level
    np.testing.assert_allclose(got, ref, atol=1e-4)
    # and the size-based grid must NOT match (the non-short axis differs)
    from video_features_tpu.ops.transforms import resize_bilinear
    size_based = np.asarray(resize_bilinear(x, ref.shape[1:3]))
    assert np.abs(size_based - ref).max() > 1e-3
