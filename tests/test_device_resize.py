"""device_resize: the in-graph short-side resize vs the host PIL path.

`device_resize=true` ships raw decode-geometry frames and runs the
short-side-256 resize inside the fused i3d graph. Since round 5 that
resize is ops.transforms.pil_resize_bilinear_device — a BIT-EXACT
reproduction of Pillow's fixed-point bilinear resample (coefficient
quantization to 2^22, horizontal-then-vertical pass order, uint8
intermediate) — so the device path sees the identical pixels the host
resize_pil path produces and the feature-level cost is ZERO. These tests
pin (1) the geometry arithmetic, (2) pixel-level bit-exactness against
PIL itself across geometries, and (3) the end-to-end feature identity.
"""
from __future__ import annotations

import numpy as np
import pytest

from video_features_tpu.config import load_config
from video_features_tpu.extract.i3d import _pil_short_side_geometry
from video_features_tpu.ops.transforms import (
    pil_resize_bilinear_device, resize_pil,
)
from video_features_tpu.registry import create_extractor


@pytest.mark.parametrize('h,w', [(240, 320), (256, 340), (1080, 1920),
                                 (320, 240), (256, 256), (200, 256)])
def test_geometry_matches_pil(h, w):
    """_pil_short_side_geometry reproduces resize_pil's output geometry
    (including its no-op condition) for every aspect/orientation."""
    frame = np.zeros((h, w, 3), np.uint8)
    out = resize_pil(frame, 256)
    geom = _pil_short_side_geometry(h, w, 256)
    if geom is None:
        assert out.shape == (h, w, 3), 'no-op expected'
    else:
        assert out.shape == geom + (3,), (out.shape, geom)


@pytest.mark.parametrize('h,w,oh,ow', [
    (240, 320, 256, 341),    # upscale (the 240px sample's real geometry)
    (360, 480, 256, 341),    # downscale
    (123, 77, 45, 200),      # mixed down/up
    (256, 344, 256, 344),    # identity
    (100, 100, 256, 256),    # pure upscale
])
def test_device_resize_bitexact_vs_pil(h, w, oh, ow):
    """The in-graph resample IS Pillow's: bit-equal output on random
    uint8 images, jitted, including the batched layout the fused step
    uses."""
    import jax
    from PIL import Image

    rng = np.random.RandomState(0)
    img = rng.randint(0, 256, (h, w, 3), np.uint8)
    ref = np.asarray(Image.fromarray(img).resize((ow, oh), Image.BILINEAR))
    got = np.asarray(jax.jit(
        lambda a: pil_resize_bilinear_device(a, (oh, ow)))(img))
    np.testing.assert_array_equal(got, ref)
    # batched (B, S, H, W, C), float32-holding-integers input dtype
    batch = rng.randint(0, 256, (2, 3, h, w, 3), np.uint8)
    gotb = np.asarray(jax.jit(
        lambda a: pil_resize_bilinear_device(a, (oh, ow)))(
            batch.astype(np.float32)))
    refb = np.stack([[np.asarray(Image.fromarray(f).resize(
        (ow, oh), Image.BILINEAR)) for f in b] for b in batch])
    np.testing.assert_array_equal(gotb, refb)


@pytest.fixture(scope='module')
def clip17(tmp_path_factory):
    """17 frames of the 240px sample (one stack at stack_size=16) — a
    geometry where the short-side-256 resize is REAL (an upscale), unlike
    the 256px test clips where it would no-op."""
    import cv2

    src = '/root/reference/sample/v_GGSY1Qvo990.mp4'
    import os
    if not os.path.exists(src):
        pytest.skip('sample video unavailable')
    out = str(tmp_path_factory.mktemp('dres') / 'clip17.mp4')
    cap = cv2.VideoCapture(src)
    fps = cap.get(cv2.CAP_PROP_FPS)
    w = int(cap.get(cv2.CAP_PROP_FRAME_WIDTH))
    h = int(cap.get(cv2.CAP_PROP_FRAME_HEIGHT))
    wr = cv2.VideoWriter(out, cv2.VideoWriter_fourcc(*'mp4v'), fps, (w, h))
    written = 0
    for _ in range(17):
        ok, f = cap.read()
        if not ok:
            break
        wr.write(f)
        written += 1
    wr.release()
    cap.release()
    if written < 17:
        pytest.skip(f'sample yielded only {written} frames')
    return out


@pytest.mark.slow
def test_device_resize_feature_identity(reference_repo, clip17, tmp_path):
    """Fused i3d features with device_resize=true vs the (golden-verified)
    host-PIL path on the same video + seeded weights: the resized pixels
    are bit-identical, so both streams must agree to float-noise level —
    including flow, whose uint8 quantization cliff amplified the old
    approximate resize to 3.7e-3."""
    import torch

    from tests.reference_pipeline import build_reference_nets, \
        save_state_dicts

    torch.manual_seed(0)
    ckpts = save_state_dicts(build_reference_nets(seed=0),
                             tmp_path / 'ckpts')

    def run(device_resize):
        args = load_config('i3d', overrides={
            'video_paths': clip17, 'device': 'cpu',
            'precision': 'highest', 'decode_backend': 'cv2',
            'stack_size': 16, 'step_size': 16, 'raft_iters': 2,
            'device_resize': device_resize,
            'i3d_rgb_checkpoint_path': str(ckpts['rgb']),
            'i3d_flow_checkpoint_path': str(ckpts['flow']),
            'raft_checkpoint_path': str(ckpts['raft']),
            'output_path': str(tmp_path / f'o{device_resize}'),
            'tmp_path': str(tmp_path / f't{device_resize}'),
        })
        return create_extractor(args).extract(clip17)

    host = run(False)
    dev = run(True)
    rels = {}
    for s in ('rgb', 'flow'):
        assert dev[s].shape == host[s].shape == (1, 1024)
        rels[s] = (np.linalg.norm(dev[s] - host[s])
                   / np.linalg.norm(host[s]))
    print(f'[device_resize] feature rel L2 vs host PIL path: {rels}')
    assert rels['rgb'] < 1e-6, rels
    assert rels['flow'] < 1e-6, rels
