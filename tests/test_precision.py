"""ops/precision pins + the precision='mixed' extraction mode + the
compute_dtype fast lanes' pinned parity bounds (PARITY.md-style: the
bounds tables live in ops/precision.BF16_REL_L2_BOUNDS /
INT8_REL_L2_BOUNDS; this module asserts the measured drift of every
accepting family's REAL jitted step stays under them — build-free
numeric gates in tier-1, the full real-build ladders in the slow lane,
with ONE module-scoped fp32 reference build per family shared across
both fast-lane ladders)."""
import numpy as np
import pytest

from video_features_tpu.ops.precision import (
    BF16_REL_L2_BOUNDS, COMPUTE_DTYPES, ComputeDtypeError,
    INT8_REL_L2_BOUNDS, MIXED_PINS, check_compute_dtype, normalize_pins,
    param_np_dtype, pin_scope, rel_l2,
)


def test_normalize_pins():
    assert normalize_pins(None) is None
    assert normalize_pins({'b': 'high', 'a': 'highest'}) == (
        ('a', 'highest'), ('b', 'high'))
    assert normalize_pins((('a', 'x'),)) == (('a', 'x'),)


def test_pin_scope_null_when_unpinned():
    from contextlib import nullcontext
    assert isinstance(pin_scope(None, 'corr'), nullcontext)
    assert isinstance(pin_scope((('iter', 'high'),), 'corr'), nullcontext)
    assert not isinstance(pin_scope((('iter', 'high'),), 'iter'),
                          nullcontext)
    # the tuned 'mixed' policy is ambient-only (no sub-graph survives
    # 1-pass bf16 — see ops/precision.py); pins stay empty
    assert MIXED_PINS == ()


def test_pin_scope_sets_matmul_precision():
    import jax

    from jax._src import config as jax_config
    with pin_scope((('corr', 'high'),), 'corr'):
        assert jax_config.default_matmul_precision.value == 'high'
    # sanity: jax accepts the context in a traced function
    @jax.jit
    def f(x):
        with pin_scope((('corr', 'highest'),), 'corr'):
            return x @ x
    np.testing.assert_allclose(np.asarray(f(np.eye(4, dtype=np.float32))),
                               np.eye(4))


def test_mixed_mode_extractor_runs_and_matches_on_cpu(tmp_path):
    """precision='mixed' compiles and runs; on CPU every precision executes
    fp32, so mixed must be bit-identical to highest — this checks the pin
    plumbing doesn't alter the graph structure. ONE i3d build serves
    both precisions (mixed's pins are empty, so the jitted step is the
    same callable — only the ambient matmul-precision context differs,
    which is exactly the knob under test); the second transplant the old
    two-build version paid bought nothing but tier-1 wall clock."""
    import jax

    from video_features_tpu.config import load_config
    from video_features_tpu.registry import create_extractor

    args = load_config('i3d', overrides={
        'video_paths': 'v.mp4', 'device': 'cpu',
        'precision': 'mixed', 'stack_size': 10, 'step_size': 10,
        'allow_random_weights': True,
        'output_path': str(tmp_path / 'o'),
        'tmp_path': str(tmp_path / 't'),
    })
    ex = create_extractor(args)
    assert ex.precision == 'mixed' and ex.precision_pins == ()

    stacks = np.random.RandomState(0).randint(
        0, 255, (1, 11, 64, 64, 3)).astype(np.float32)
    outs = {}
    scopes = {'mixed': ex.precision_scope(),
              'highest': jax.default_matmul_precision('highest')}
    for precision, scope in scopes.items():
        with scope:
            out = ex._step(ex.params, jax.device_put(stacks),
                           pads=(0, 0, 0, 0), streams=('rgb', 'flow'))
        outs[precision] = {k: np.asarray(v) for k, v in out.items()}
    for k in ('rgb', 'flow'):
        np.testing.assert_array_equal(outs['mixed'][k], outs['highest'][k])


# -- the compute_dtype fast lanes (bfloat16 / int8) ---------------------------
#
# One extractor per (family, lane) serves ALL of a family's assertions
# (parity, census, output dtype — the PR 11 reuse pattern: builds are
# the expensive part); the fp32 reference and the fast-lane candidate
# see IDENTICAL uint8 inputs, so every diff is the lane's. The builds
# live in the SLOW lane (tier-1's 870 s budget has no room for the
# extractor pairs), and the fp32 REFERENCE build+run is module-scoped
# (`_f32_reference`) so the bf16 and int8 ladders share it instead of
# each paying a second fp32 build per family; tier-1 keeps the
# build-free numerics + identity gates below plus the lock-census gate
# in test_programs.

# family → (config overrides, input batch builder). Geometries are the
# smallest each family compiles quickly at on CPU; the bound is rel-L2,
# stable across geometry/weights (max-abs scales with feature magnitude).
_BF16_CASES = {
    'vggish': ({}, lambda: np.random.RandomState(0)
               .rand(4, 96, 64, 1).astype(np.float32)),
    'r21d': ({'stack_size': 10, 'step_size': 10},
             lambda: np.random.RandomState(0)
             .randint(0, 255, (1, 10, 64, 86, 3)).astype(np.uint8)),
    's3d': ({'stack_size': 16, 'step_size': 16},
            lambda: np.random.RandomState(0)
            .randint(0, 255, (1, 16, 64, 86, 3)).astype(np.uint8)),
    'resnet': ({'model_name': 'resnet18', 'batch_size': 2},
               lambda: np.random.RandomState(0)
               .randint(0, 255, (2, 224, 224, 3)).astype(np.uint8)),
    'clip': ({'model_name': 'ViT-B/32', 'batch_size': 2},
             lambda: np.random.RandomState(0)
             .randint(0, 255, (2, 224, 224, 3)).astype(np.uint8)),
    'timm': ({'model_name': 'vit_base_patch16_224', 'batch_size': 2,
              'pretrained': False},
             lambda: np.random.RandomState(0)
             .randint(0, 255, (2, 224, 224, 3)).astype(np.uint8)),
}


def _build_lane(ft, compute_dtype, tmp_root):
    from video_features_tpu.config import load_config
    from video_features_tpu.registry import create_extractor
    overrides = {
        'video_paths': 'v.mp4', 'device': 'cpu',
        'allow_random_weights': True, 'compute_dtype': compute_dtype,
        'output_path': f'{tmp_root}/out_{ft}_{compute_dtype}',
        'tmp_path': f'{tmp_root}/tmp_{ft}_{compute_dtype}',
    }
    overrides.update(_BF16_CASES[ft][0])
    return create_extractor(load_config(ft, overrides=overrides))


def _run_step(ex, ft, batch):
    """One device step on the REAL jitted callable the hot path
    dispatches (not a re-wrap), family quirks included."""
    import jax
    x = batch
    if ft == 'vggish' and ex.compute_dtype == 'bfloat16':
        x = x.astype(ex.param_dtype)       # the _run_batched edge cast
    if ft == 's3d':
        step, _, _ = ex._geometry_step(*batch.shape[2:4])
        return np.asarray(step(ex.params, jax.device_put(x)))
    return np.asarray(ex._step(ex.params, jax.device_put(x)))


@pytest.fixture(scope='module')
def _f32_reference(tmp_path_factory):
    """Per-family fp32 reference features, built ONCE per module run and
    shared by the bf16 AND int8 slow ladders (the input builders are
    seeded, so every lane sees byte-identical batches). Builds are the
    expensive part — this keeps the two-ladder suite at one fp32 build
    per family instead of two."""
    cache = {}

    def get(ft):
        if ft not in cache:
            root = str(tmp_path_factory.mktemp(f'ref_{ft}'))
            ex = _build_lane(ft, 'float32', root)
            cache[ft] = _run_step(ex, ft, _BF16_CASES[ft][1]())
        return cache[ft]
    return get


def _assert_lane_contract(ft, lane, tmp_root, ref):
    import jax
    bounds = (BF16_REL_L2_BOUNDS if lane == 'bfloat16'
              else INT8_REL_L2_BOUNDS)
    ex = _build_lane(ft, lane, tmp_root)
    fast = _run_step(ex, ft, _BF16_CASES[ft][1]())
    # the lane actually computed differently...
    assert np.abs(ref - fast).max() > 0, f'{ft}: lanes identical?'
    # ...features still leave the device as float32 (on-disk contract)...
    assert fast.dtype == np.float32
    # ...within the family's pinned parity bound...
    err = rel_l2(ref, fast)
    assert err <= bounds[ft], (
        f'{ft}: {lane} lane rel-L2 {err:.3e} over the pinned bound '
        f'{bounds[ft]:.1e}')
    # ...and the storage transform reached the params (the PROGRAMS.lock
    # census holds the same line per lane)
    by_dtype = {}
    for leaf in jax.tree_util.tree_leaves(ex.params):
        if hasattr(leaf, 'dtype'):
            by_dtype[str(leaf.dtype)] = (by_dtype.get(str(leaf.dtype), 0)
                                         + leaf.nbytes)
    if lane == 'bfloat16':
        # the cast reached EVERY param: zero fp32 survivors
        assert set(by_dtype) == {'bfloat16'}, (ft, by_dtype)
    else:
        # int8 weight payloads dominate; fp32 is the declared minority
        # (per-channel scales, biases, norm params, embedding tables)
        assert 'int8' in by_dtype, (ft, by_dtype)
        assert by_dtype.get('float32', 0) < by_dtype['int8'], (ft, by_dtype)


def test_bounds_tables_are_pinned():
    """PARITY.md-style pin: the bounds (and who accepts each lane) are an
    intentional, test-visible contract — moving one is a review event,
    not a drive-by edit."""
    from video_features_tpu.registry import BF16_FEATURES, INT8_FEATURES
    assert BF16_REL_L2_BOUNDS == {
        'r21d': 1.5e-2, 's3d': 2e-2, 'resnet': 2e-2,
        'clip': 3e-2, 'timm': 5e-2, 'vggish': 2.5e-2,
    }
    assert set(BF16_REL_L2_BOUNDS) == BF16_FEATURES
    assert INT8_REL_L2_BOUNDS == {
        'resnet': 5e-2, 'clip': 3.5e-2, 'timm': 7.5e-2,
    }
    assert set(INT8_REL_L2_BOUNDS) == INT8_FEATURES
    # int8 accepts a strict subset of bf16's families: every int8 lane
    # rung sits below an existing bf16 rung on the ladder
    assert INT8_FEATURES < BF16_FEATURES
    assert COMPUTE_DTYPES == ('float32', 'bfloat16', 'int8')


def test_refusal_is_structured_and_echoes_the_requested_dtype():
    """Refusals name the family, the parity bound, the remediation — and
    the REQUESTED dtype (the pre-int8 message hardcoded
    'compute_dtype=bfloat16' whatever was asked)."""
    for lane in ('bfloat16', 'int8'):
        for ft in ('i3d', 'raft'):
            with pytest.raises(ComputeDtypeError) as e:
                check_compute_dtype(ft, lane)
            msg = str(e.value)
            assert f'compute_dtype={lane} is refused' in msg
            assert ft in msg and '1e-3' in msg and 'precision=mixed' in msg
    # families with a bf16 bound but NO int8 bound refuse int8 with the
    # generic opt-in message naming the right registry set
    with pytest.raises(ComputeDtypeError) as e:
        check_compute_dtype('vggish', 'int8')
    assert 'compute_dtype=int8 is refused' in str(e.value)
    assert 'INT8_FEATURES' in str(e.value)
    with pytest.raises(ComputeDtypeError):
        check_compute_dtype('resnet', 'float16')    # unknown value
    # fp8: structured not-yet naming backend support as the gate
    with pytest.raises(ComputeDtypeError) as e:
        check_compute_dtype('resnet', 'float8_e4m3fn')
    assert 'backend' in str(e.value) and 'int8' in str(e.value)
    assert check_compute_dtype('i3d', 'float32') == 'float32'
    assert check_compute_dtype('resnet', 'bfloat16') == 'bfloat16'
    assert check_compute_dtype('resnet', 'int8') == 'int8'
    assert check_compute_dtype('vggish', 'bfloat16') == 'bfloat16'


def test_param_np_dtype():
    import ml_dtypes
    assert param_np_dtype('float32') == np.dtype(np.float32)
    assert param_np_dtype('bfloat16') == np.dtype(ml_dtypes.bfloat16)
    assert param_np_dtype('int8') == np.dtype(np.int8)
    # exhaustive dispatch: an unrecognized lane raises instead of the
    # old silent float32 fall-through
    for bad in ('float16', 'int4', 'fp8', ''):
        with pytest.raises(ComputeDtypeError):
            param_np_dtype(bad)


def test_compute_dtype_is_identity_on_both_axes():
    """The KNOB_CLASSIFICATION 'both' contract, pinned via the two REAL
    consumers: runs of the same video on any two lanes must produce
    distinct cache fingerprints (never share a cache entry) and
    distinct serve pool keys (never share a warm program)."""
    from video_features_tpu.cache.key import config_fingerprint
    from video_features_tpu.config import KNOB_CLASSIFICATION, Config
    from video_features_tpu.serve.server import pool_key
    assert KNOB_CLASSIFICATION['compute_dtype'] == 'both'
    base = dict(feature_type='resnet', model_name='resnet18',
                batch_size=8, device='cpu', output_path='/o',
                tmp_path='/t')
    cfgs = [Config(base, compute_dtype=lane) for lane in COMPUTE_DTYPES]
    fps = [config_fingerprint(c) for c in cfgs]
    keys = [pool_key(c) for c in cfgs]
    assert len(set(fps)) == len(COMPUTE_DTYPES)
    assert len(set(keys)) == len(COMPUTE_DTYPES)


def test_bf16_islands_and_epilogue_cast_tier1():
    """Build-free tier-1 slice of the lane's numerics: the ops/nn fp32
    accumulation islands fire exactly on bf16 input (fp32 input lowers
    the pre-lane graph verbatim — no convert ops appear), and the
    feature epilogue always hands back float32. The full per-family
    error ladder — real extractor builds, measured drift vs the pinned
    bounds — lives in the slow lane below; tier-1's STRUCTURAL bf16
    gate is the lock census in test_programs (resnet, both lanes)."""
    import jax
    import jax.numpy as jnp

    from video_features_tpu.ops.nn import adaptive_avg_pool, softmax
    from video_features_tpu.ops.precision import features_to_f32

    x32 = np.linspace(-3, 3, 4 * 7 * 7 * 5,
                      dtype=np.float32).reshape(4, 7, 7, 5)
    xb = jnp.asarray(x32, jnp.bfloat16)
    # islands keep the lane's dtype on the outside...
    assert softmax(xb).dtype == jnp.bfloat16
    assert adaptive_avg_pool(xb).dtype == jnp.bfloat16
    # ...and compute fp32 inside: the bf16 result equals the fp32
    # computation rounded ONCE at the end (not bf16 all the way through)
    ref = jax.nn.softmax(jnp.asarray(np.asarray(xb, np.float32)), axis=-1)
    np.testing.assert_array_equal(
        np.asarray(softmax(xb), np.float32),
        np.asarray(ref.astype(jnp.bfloat16), np.float32))
    # fp32 path byte-clean: the island branch emits NOTHING for f32
    jx_f32 = jax.make_jaxpr(softmax)(x32)
    assert 'bf16' not in str(jx_f32)
    # the epilogue cast is a no-op (no convert) on the fp32 lane and a
    # single convert on the bf16 lane
    assert features_to_f32(jnp.asarray(x32)) .dtype == jnp.float32
    assert 'convert' not in str(jax.make_jaxpr(features_to_f32)(x32))
    assert features_to_f32(xb).dtype == jnp.float32


def test_int8_quant_dequant_numerics_tier1():
    """Build-free tier-1 slice of the int8 lane's numerics: the
    quantizer's per-channel scales, symmetric clip, zero-guard and the
    in-graph dequant roundtrip — plus the load-bearing structural
    identity (dequantize_tree on a PLAIN tree adds zero graph ops, which
    is what keeps the fp32 lane's StableHLO byte-identical with the call
    compiled into every accepting family's forward). The full
    per-family error ladder — real builds, measured drift vs the pinned
    bounds — lives in the slow lane below; tier-1's STRUCTURAL int8
    gate is the lock census in test_programs."""
    import jax

    from video_features_tpu.ops.quant import (
        QMAX, QuantizedTensor, dequantize_tree, quantize_array,
        quantize_flat, tree_is_quantized,
    )

    rng = np.random.RandomState(0)
    # per-channel: each output channel's amax maps exactly to +/-127
    w = (rng.randn(3, 3, 8, 16) * np.linspace(0.1, 4.0, 16)).astype(
        np.float32)
    qt = quantize_array(w)
    assert qt.q.dtype == np.int8 and qt.q.shape == w.shape
    assert qt.scale.dtype == np.float32
    assert qt.scale.shape == (1, 1, 1, 16)
    assert int(np.abs(qt.q).max()) == QMAX
    np.testing.assert_allclose(
        qt.scale.ravel(), np.abs(w).max(axis=(0, 1, 2)) / QMAX)
    # roundtrip error bounded by scale/2 per element (round-to-nearest)
    deq = np.asarray(qt.dequantize())
    assert np.abs(deq - w).max() <= float(qt.scale.max()) / 2 + 1e-7
    # axis-0 channel layout (CLIP's torch-layout in_proj_weight)
    qt0 = quantize_array(rng.randn(24, 8).astype(np.float32), axis=0)
    assert qt0.scale.shape == (24, 1)
    # all-zero channel: scale guards to 1.0, payload is zeros
    wz = np.zeros((4, 3), np.float32)
    wz[:, 0] = 5.0
    qz = quantize_array(wz)
    assert np.all(np.asarray(qz.scale).ravel()[1:] == 1.0)
    assert np.all(qz.q[:, 1:] == 0)
    assert np.isfinite(np.asarray(qz.dequantize())).all()
    # eligibility (the transplant re-layout rule): weights quantize,
    # biases/norm params stay fp32, embedding tables and the skip set
    # stay fp32, in_proj_weight rides the axis-0 path
    flat = {
        'conv1.weight': rng.randn(3, 3, 3, 8).astype(np.float32),
        'fc.weight': rng.randn(16, 10).astype(np.float32),
        'fc.bias': rng.randn(10).astype(np.float32),
        'bn.weight': rng.randn(8).astype(np.float32),
        'token_embedding.weight': rng.randn(50, 16).astype(np.float32),
        'attn.in_proj_weight': rng.randn(48, 16).astype(np.float32),
        'skipme.weight': rng.randn(4, 4).astype(np.float32),
    }
    q = quantize_flat(flat, skip={'skipme.weight'})
    assert isinstance(q['conv1.weight'], QuantizedTensor)
    assert isinstance(q['fc.weight'], QuantizedTensor)
    assert isinstance(q['attn.in_proj_weight'], QuantizedTensor)
    assert q['attn.in_proj_weight'].scale.shape == (48, 1)
    for kept in ('fc.bias', 'bn.weight', 'token_embedding.weight',
                 'skipme.weight'):
        assert q[kept].dtype == np.float32, kept
    # dequantize_tree: expands quantized leaves, identity on plain trees
    tree = {'a': {'w': quantize_array(w)}, 'b': flat['fc.bias']}
    assert tree_is_quantized(tree) and not tree_is_quantized(flat)
    out = dequantize_tree(tree)
    assert out['a']['w'].dtype == jax.numpy.float32
    assert out['b'] is tree['b']          # untouched leaf, same object
    # the structural-identity proof: on a plain tree the compiled
    # program contains NO convert/multiply from the dequant seam
    plain = {'w': flat['fc.weight'], 'b': flat['fc.bias']}

    def fwd(p, x):
        p = dequantize_tree(p)
        return x @ p['w'] + p['b']

    x = rng.randn(2, 16).astype(np.float32)
    jx = jax.make_jaxpr(fwd)(plain, x)
    assert 'convert' not in str(jx)
    # and on a quantized tree the SAME forward computes the dequantized
    # matmul
    qplain = {'w': quantize_array(flat['fc.weight']), 'b': plain['b']}
    np.testing.assert_allclose(
        np.asarray(jax.jit(fwd)(qplain, x)),
        x @ np.asarray(qplain['w'].dequantize()) + plain['b'], rtol=1e-5)


def test_int8_scale_table_roundtrip(tmp_path):
    """The checkpoint-adjacent calibration store: derived scales pin to
    <ckpt>.int8-scales.npz, load back bit-identical, and
    load_torch_checkpoint consumes a pinned table automatically on the
    int8 lane (same quantized bytes as the derived path — the table is
    the derived scales made explicit)."""
    from video_features_tpu.ops.quant import (
        derive_scales, load_scale_table, save_scale_table,
        scale_table_path,
    )
    from video_features_tpu.transplant.torch2jax import (
        load_torch_checkpoint, save_transplanted,
    )
    rng = np.random.RandomState(1)
    params = {'conv': {'weight': rng.randn(3, 3, 4, 8).astype(np.float32),
                       'bias': rng.randn(8).astype(np.float32)}}
    ckpt = str(tmp_path / 'model.npz')
    save_transplanted(params, ckpt)
    flat = {'conv.weight': params['conv']['weight'],
            'conv.bias': params['conv']['bias']}
    scales = derive_scales(flat)
    assert set(scales) == {'conv.weight'}
    table = scale_table_path(ckpt)
    assert table == f'{ckpt}.int8-scales.npz'
    save_scale_table(table, scales, meta={'measured_rel_l2': '1e-2'})
    loaded = load_scale_table(table)
    np.testing.assert_array_equal(loaded['conv.weight'],
                                  scales['conv.weight'])
    assert load_scale_table(str(tmp_path / 'absent.npz')) == {}
    # the int8 load path consumes the pinned table
    from video_features_tpu.ops.quant import QuantizedTensor
    loaded_params = load_torch_checkpoint(ckpt, dtype=np.int8)
    qt = loaded_params['conv']['weight']
    assert isinstance(qt, QuantizedTensor)
    np.testing.assert_array_equal(np.asarray(qt.scale).ravel(),
                                  scales['conv.weight'].ravel())
    assert loaded_params['conv']['bias'].dtype == np.float32


@pytest.mark.slow
@pytest.mark.parametrize('ft', sorted(_BF16_CASES))
def test_bf16_lane_parity_all_families(ft, tmp_path, _f32_reference):
    """The full bf16 lane gate, one family per case: real extractor
    builds (fp32 reference shared module-wide), identical inputs,
    measured rel-L2 under the pinned bound, all-bf16 params census,
    float32 feature outputs."""
    _assert_lane_contract(ft, 'bfloat16', str(tmp_path),
                          _f32_reference(ft))


@pytest.mark.slow
@pytest.mark.parametrize('ft', sorted(INT8_REL_L2_BOUNDS))
def test_int8_lane_parity_all_families(ft, tmp_path, _f32_reference):
    """The full int8 lane gate for every accepting family: real builds
    (fp32 reference shared with the bf16 ladder above), identical
    inputs, measured rel-L2 under the pinned INT8_REL_L2_BOUNDS entry,
    int8-majority params census, float32 feature outputs."""
    _assert_lane_contract(ft, 'int8', str(tmp_path), _f32_reference(ft))


def test_iter_early_pin_structurally_sound():
    """iter_early splits the GRU scan; on CPU (fp32 everywhere) the split
    must be bit-identical to the single scan, for any split point."""
    import jax

    from video_features_tpu.models import raft as raft_model
    from video_features_tpu.transplant.torch2jax import transplant

    params = transplant(raft_model.init_state_dict())
    rng = np.random.RandomState(0)
    f1 = (rng.rand(1, 64, 64, 3) * 255).astype(np.float32)
    f2 = (rng.rand(1, 64, 64, 3) * 255).astype(np.float32)
    with jax.default_matmul_precision('highest'):
        base = np.asarray(raft_model.forward(params, f1, f2, iters=6))
        for n in (0, 3, 6, 99):
            split = np.asarray(raft_model.forward(
                params, f1, f2, iters=6,
                pins=(('iter_early', f'default:{n}'),)))
            np.testing.assert_array_equal(split, base)
