"""ops/precision pins + the precision='mixed' extraction mode + the
compute_dtype=bfloat16 fast lane's pinned parity bounds (PARITY.md-style:
the bounds table lives in ops/precision.BF16_REL_L2_BOUNDS; this module
asserts the measured drift of every accepting family's REAL jitted step
stays under it — the cheapest family in tier-1, the full six-family
ladder in the slow lane)."""
import numpy as np
import pytest

from video_features_tpu.ops.precision import (
    BF16_REL_L2_BOUNDS, COMPUTE_DTYPES, ComputeDtypeError, MIXED_PINS,
    check_compute_dtype, normalize_pins, param_np_dtype, pin_scope,
    rel_l2,
)


def test_normalize_pins():
    assert normalize_pins(None) is None
    assert normalize_pins({'b': 'high', 'a': 'highest'}) == (
        ('a', 'highest'), ('b', 'high'))
    assert normalize_pins((('a', 'x'),)) == (('a', 'x'),)


def test_pin_scope_null_when_unpinned():
    from contextlib import nullcontext
    assert isinstance(pin_scope(None, 'corr'), nullcontext)
    assert isinstance(pin_scope((('iter', 'high'),), 'corr'), nullcontext)
    assert not isinstance(pin_scope((('iter', 'high'),), 'iter'),
                          nullcontext)
    # the tuned 'mixed' policy is ambient-only (no sub-graph survives
    # 1-pass bf16 — see ops/precision.py); pins stay empty
    assert MIXED_PINS == ()


def test_pin_scope_sets_matmul_precision():
    import jax

    from jax._src import config as jax_config
    with pin_scope((('corr', 'high'),), 'corr'):
        assert jax_config.default_matmul_precision.value == 'high'
    # sanity: jax accepts the context in a traced function
    @jax.jit
    def f(x):
        with pin_scope((('corr', 'highest'),), 'corr'):
            return x @ x
    np.testing.assert_allclose(np.asarray(f(np.eye(4, dtype=np.float32))),
                               np.eye(4))


def test_mixed_mode_extractor_runs_and_matches_on_cpu(tmp_path):
    """precision='mixed' compiles and runs; on CPU every precision executes
    fp32, so mixed must be bit-identical to highest — this checks the pin
    plumbing doesn't alter the graph structure. ONE i3d build serves
    both precisions (mixed's pins are empty, so the jitted step is the
    same callable — only the ambient matmul-precision context differs,
    which is exactly the knob under test); the second transplant the old
    two-build version paid bought nothing but tier-1 wall clock."""
    import jax

    from video_features_tpu.config import load_config
    from video_features_tpu.registry import create_extractor

    args = load_config('i3d', overrides={
        'video_paths': 'v.mp4', 'device': 'cpu',
        'precision': 'mixed', 'stack_size': 10, 'step_size': 10,
        'allow_random_weights': True,
        'output_path': str(tmp_path / 'o'),
        'tmp_path': str(tmp_path / 't'),
    })
    ex = create_extractor(args)
    assert ex.precision == 'mixed' and ex.precision_pins == ()

    stacks = np.random.RandomState(0).randint(
        0, 255, (1, 11, 64, 64, 3)).astype(np.float32)
    outs = {}
    scopes = {'mixed': ex.precision_scope(),
              'highest': jax.default_matmul_precision('highest')}
    for precision, scope in scopes.items():
        with scope:
            out = ex._step(ex.params, jax.device_put(stacks),
                           pads=(0, 0, 0, 0), streams=('rgb', 'flow'))
        outs[precision] = {k: np.asarray(v) for k, v in out.items()}
    for k in ('rgb', 'flow'):
        np.testing.assert_array_equal(outs['mixed'][k], outs['highest'][k])


# -- the bf16 fast lane (compute_dtype=bfloat16) ------------------------------
#
# One extractor per (family, lane) serves ALL of a family's assertions
# (parity, census, output dtype — the PR 11 reuse pattern: builds are
# the expensive part); the fp32 reference and the bf16 candidate see
# IDENTICAL uint8 inputs, so every diff is the lane's. The builds live
# in the SLOW lane (tier-1's 870 s budget has no room for six extractor
# pairs); tier-1 keeps the build-free numerics + identity gates below
# plus the lock-census gate in test_programs.

# family → (config overrides, input batch builder). Geometries are the
# smallest each family compiles quickly at on CPU; the bound is rel-L2,
# stable across geometry/weights (max-abs scales with feature magnitude).
_BF16_CASES = {
    'vggish': ({}, lambda: np.random.RandomState(0)
               .rand(4, 96, 64, 1).astype(np.float32)),
    'r21d': ({'stack_size': 10, 'step_size': 10},
             lambda: np.random.RandomState(0)
             .randint(0, 255, (1, 10, 64, 86, 3)).astype(np.uint8)),
    's3d': ({'stack_size': 16, 'step_size': 16},
            lambda: np.random.RandomState(0)
            .randint(0, 255, (1, 16, 64, 86, 3)).astype(np.uint8)),
    'resnet': ({'model_name': 'resnet18', 'batch_size': 2},
               lambda: np.random.RandomState(0)
               .randint(0, 255, (2, 224, 224, 3)).astype(np.uint8)),
    'clip': ({'model_name': 'ViT-B/32', 'batch_size': 2},
             lambda: np.random.RandomState(0)
             .randint(0, 255, (2, 224, 224, 3)).astype(np.uint8)),
    'timm': ({'model_name': 'vit_base_patch16_224', 'batch_size': 2,
              'pretrained': False},
             lambda: np.random.RandomState(0)
             .randint(0, 255, (2, 224, 224, 3)).astype(np.uint8)),
}


def _build_lane(ft, compute_dtype, tmp_root):
    from video_features_tpu.config import load_config
    from video_features_tpu.registry import create_extractor
    overrides = {
        'video_paths': 'v.mp4', 'device': 'cpu',
        'allow_random_weights': True, 'compute_dtype': compute_dtype,
        'output_path': f'{tmp_root}/out_{ft}_{compute_dtype}',
        'tmp_path': f'{tmp_root}/tmp_{ft}_{compute_dtype}',
    }
    overrides.update(_BF16_CASES[ft][0])
    return create_extractor(load_config(ft, overrides=overrides))


def _lane_outputs(ft, tmp_root):
    """(fp32 features, bf16-lane features, bf16 extractor) on identical
    inputs — the step functions the hot paths dispatch, not re-wraps."""
    import jax
    batch = _BF16_CASES[ft][1]()
    outs = {}
    ex_b = None
    for lane in ('float32', 'bfloat16'):
        ex = _build_lane(ft, lane, tmp_root)
        if lane == 'bfloat16':
            ex_b = ex
        x = batch
        if ft == 'vggish' and lane == 'bfloat16':
            x = x.astype(ex.param_dtype)       # the _run_batched edge cast
        if ft == 's3d':
            step, _, _ = ex._geometry_step(*batch.shape[2:4])
            out = step(ex.params, jax.device_put(x))
        else:
            out = ex._step(ex.params, jax.device_put(x))
        outs[lane] = np.asarray(out)
    return outs['float32'], outs['bfloat16'], ex_b


def _assert_lane_contract(ft, tmp_root):
    import jax
    ref, fast, ex_b = _lane_outputs(ft, tmp_root)
    # the lane actually computed differently...
    assert np.abs(ref - fast).max() > 0, f'{ft}: lanes identical?'
    # ...features still leave the device as float32 (on-disk contract)...
    assert fast.dtype == np.float32
    # ...within the family's pinned parity bound...
    err = rel_l2(ref, fast)
    assert err <= BF16_REL_L2_BOUNDS[ft], (
        f'{ft}: bf16 lane rel-L2 {err:.3e} over the pinned bound '
        f'{BF16_REL_L2_BOUNDS[ft]:.1e}')
    # ...and the cast reached EVERY param: bf16 in HBM, zero fp32
    # survivors (the PROGRAMS.lock census holds the same line)
    dtypes = {str(leaf.dtype)
              for leaf in jax.tree_util.tree_leaves(ex_b.params)
              if hasattr(leaf, 'dtype')}
    assert dtypes == {'bfloat16'}, (ft, dtypes)


def test_bf16_bounds_table_is_pinned():
    """PARITY.md-style pin: the bounds (and who accepts the lane) are an
    intentional, test-visible contract — moving one is a review event,
    not a drive-by edit."""
    from video_features_tpu.registry import BF16_FEATURES
    assert BF16_REL_L2_BOUNDS == {
        'r21d': 1.5e-2, 's3d': 2e-2, 'resnet': 2e-2,
        'clip': 3e-2, 'timm': 5e-2, 'vggish': 2.5e-2,
    }
    assert set(BF16_REL_L2_BOUNDS) == BF16_FEATURES
    assert COMPUTE_DTYPES == ('float32', 'bfloat16')


def test_bf16_refusal_is_structured_and_names_the_bound():
    for ft in ('i3d', 'raft'):
        with pytest.raises(ComputeDtypeError) as e:
            check_compute_dtype(ft, 'bfloat16')
        msg = str(e.value)
        assert ft in msg and '1e-3' in msg and 'precision=mixed' in msg
    with pytest.raises(ComputeDtypeError):
        check_compute_dtype('resnet', 'float16')    # unknown value
    assert check_compute_dtype('i3d', 'float32') == 'float32'
    assert check_compute_dtype('resnet', 'bfloat16') == 'bfloat16'


def test_param_np_dtype():
    import ml_dtypes
    assert param_np_dtype('float32') == np.dtype(np.float32)
    assert param_np_dtype('bfloat16') == np.dtype(ml_dtypes.bfloat16)


def test_compute_dtype_is_identity_on_both_axes():
    """The KNOB_CLASSIFICATION 'both' contract, pinned via the two REAL
    consumers: fp32 and bf16 runs of the same video must produce
    distinct cache fingerprints (never share a cache entry) and
    distinct serve pool keys (never share a warm program)."""
    from video_features_tpu.cache.key import config_fingerprint
    from video_features_tpu.config import KNOB_CLASSIFICATION, Config
    from video_features_tpu.serve.server import pool_key
    assert KNOB_CLASSIFICATION['compute_dtype'] == 'both'
    base = dict(feature_type='resnet', model_name='resnet18',
                batch_size=8, device='cpu', output_path='/o',
                tmp_path='/t')
    f32 = Config(base, compute_dtype='float32')
    bf16 = Config(base, compute_dtype='bfloat16')
    assert config_fingerprint(f32) != config_fingerprint(bf16)
    assert pool_key(f32) != pool_key(bf16)


def test_bf16_islands_and_epilogue_cast_tier1():
    """Build-free tier-1 slice of the lane's numerics: the ops/nn fp32
    accumulation islands fire exactly on bf16 input (fp32 input lowers
    the pre-lane graph verbatim — no convert ops appear), and the
    feature epilogue always hands back float32. The full per-family
    error ladder — real extractor builds, measured drift vs the pinned
    bounds — lives in the slow lane below; tier-1's STRUCTURAL bf16
    gate is the lock census in test_programs (resnet, both lanes)."""
    import jax
    import jax.numpy as jnp

    from video_features_tpu.ops.nn import adaptive_avg_pool, softmax
    from video_features_tpu.ops.precision import features_to_f32

    x32 = np.linspace(-3, 3, 4 * 7 * 7 * 5,
                      dtype=np.float32).reshape(4, 7, 7, 5)
    xb = jnp.asarray(x32, jnp.bfloat16)
    # islands keep the lane's dtype on the outside...
    assert softmax(xb).dtype == jnp.bfloat16
    assert adaptive_avg_pool(xb).dtype == jnp.bfloat16
    # ...and compute fp32 inside: the bf16 result equals the fp32
    # computation rounded ONCE at the end (not bf16 all the way through)
    ref = jax.nn.softmax(jnp.asarray(np.asarray(xb, np.float32)), axis=-1)
    np.testing.assert_array_equal(
        np.asarray(softmax(xb), np.float32),
        np.asarray(ref.astype(jnp.bfloat16), np.float32))
    # fp32 path byte-clean: the island branch emits NOTHING for f32
    jx_f32 = jax.make_jaxpr(softmax)(x32)
    assert 'bf16' not in str(jx_f32)
    # the epilogue cast is a no-op (no convert) on the fp32 lane and a
    # single convert on the bf16 lane
    assert features_to_f32(jnp.asarray(x32)) .dtype == jnp.float32
    assert 'convert' not in str(jax.make_jaxpr(features_to_f32)(x32))
    assert features_to_f32(xb).dtype == jnp.float32


@pytest.mark.slow
@pytest.mark.parametrize('ft', sorted(_BF16_CASES))
def test_bf16_lane_parity_all_families(ft, tmp_path):
    """The full lane gate, one family per case: real extractor builds on
    both lanes, identical inputs, measured rel-L2 under the pinned
    bound, all-bf16 params census, float32 feature outputs."""
    _assert_lane_contract(ft, str(tmp_path))


def test_iter_early_pin_structurally_sound():
    """iter_early splits the GRU scan; on CPU (fp32 everywhere) the split
    must be bit-identical to the single scan, for any split point."""
    import jax

    from video_features_tpu.models import raft as raft_model
    from video_features_tpu.transplant.torch2jax import transplant

    params = transplant(raft_model.init_state_dict())
    rng = np.random.RandomState(0)
    f1 = (rng.rand(1, 64, 64, 3) * 255).astype(np.float32)
    f2 = (rng.rand(1, 64, 64, 3) * 255).astype(np.float32)
    with jax.default_matmul_precision('highest'):
        base = np.asarray(raft_model.forward(params, f1, f2, iters=6))
        for n in (0, 3, 6, 99):
            split = np.asarray(raft_model.forward(
                params, f1, f2, iters=6,
                pins=(('iter_early', f'default:{n}'),)))
            np.testing.assert_array_equal(split, base)
