"""ops/precision pins + the precision='mixed' extraction mode."""
import numpy as np

from video_features_tpu.ops.precision import (
    MIXED_PINS, normalize_pins, pin_scope,
)


def test_normalize_pins():
    assert normalize_pins(None) is None
    assert normalize_pins({'b': 'high', 'a': 'highest'}) == (
        ('a', 'highest'), ('b', 'high'))
    assert normalize_pins((('a', 'x'),)) == (('a', 'x'),)


def test_pin_scope_null_when_unpinned():
    from contextlib import nullcontext
    assert isinstance(pin_scope(None, 'corr'), nullcontext)
    assert isinstance(pin_scope((('iter', 'high'),), 'corr'), nullcontext)
    assert not isinstance(pin_scope((('iter', 'high'),), 'iter'),
                          nullcontext)
    # the tuned 'mixed' policy is ambient-only (no sub-graph survives
    # 1-pass bf16 — see ops/precision.py); pins stay empty
    assert MIXED_PINS == ()


def test_pin_scope_sets_matmul_precision():
    import jax

    from jax._src import config as jax_config
    with pin_scope((('corr', 'high'),), 'corr'):
        assert jax_config.default_matmul_precision.value == 'high'
    # sanity: jax accepts the context in a traced function
    @jax.jit
    def f(x):
        with pin_scope((('corr', 'highest'),), 'corr'):
            return x @ x
    np.testing.assert_allclose(np.asarray(f(np.eye(4, dtype=np.float32))),
                               np.eye(4))


def test_mixed_mode_extractor_runs_and_matches_on_cpu(tmp_path):
    """precision='mixed' compiles and runs; on CPU every precision executes
    fp32, so mixed must be bit-identical to highest — this checks the pin
    plumbing doesn't alter the graph structure."""
    import jax

    from video_features_tpu.config import load_config
    from video_features_tpu.registry import create_extractor

    def build(precision):
        args = load_config('i3d', overrides={
            'video_paths': 'v.mp4', 'device': 'cpu',
            'precision': precision, 'stack_size': 10, 'step_size': 10,
            'allow_random_weights': True,
            'output_path': str(tmp_path / f'o{precision}'),
            'tmp_path': str(tmp_path / f't{precision}'),
        })
        return create_extractor(args)

    stacks = np.random.RandomState(0).randint(
        0, 255, (1, 11, 64, 64, 3)).astype(np.float32)
    outs = {}
    for precision in ('mixed', 'highest'):
        ex = build(precision)
        with ex.precision_scope():
            out = ex._step(ex.params, jax.device_put(stacks),
                           pads=(0, 0, 0, 0), streams=('rgb', 'flow'))
        outs[precision] = {k: np.asarray(v) for k, v in out.items()}
    for k in ('rgb', 'flow'):
        np.testing.assert_array_equal(outs['mixed'][k], outs['highest'][k])


def test_iter_early_pin_structurally_sound():
    """iter_early splits the GRU scan; on CPU (fp32 everywhere) the split
    must be bit-identical to the single scan, for any split point."""
    import jax

    from video_features_tpu.models import raft as raft_model
    from video_features_tpu.transplant.torch2jax import transplant

    params = transplant(raft_model.init_state_dict())
    rng = np.random.RandomState(0)
    f1 = (rng.rand(1, 64, 64, 3) * 255).astype(np.float32)
    f2 = (rng.rand(1, 64, 64, 3) * 255).astype(np.float32)
    with jax.default_matmul_precision('highest'):
        base = np.asarray(raft_model.forward(params, f1, f2, iters=6))
        for n in (0, 3, 6, 99):
            split = np.asarray(raft_model.forward(
                params, f1, f2, iters=6,
                pins=(('iter_early', f'default:{n}'),)))
            np.testing.assert_array_equal(split, base)
