"""vft-wire (video_features_tpu/analysis/wire.py): the wire-contract
checker itself.

Same two layers as the vft-lint suite (tests/test_analysis.py):

  * fixture packages with a MINIMAL wire surface, mutated per rule —
    the checker must catch each planted drift/desync (and stay quiet on
    the clean variant);
  * the live codebase: the extracted surface must match the shipped
    ``WIRE.lock.json`` exactly, and every cross-layer rule must be
    green — the same gate CI's ``wire-check`` job enforces.

Everything here is pure AST — no extractor builds, no jax, no sockets
(tier-1 wall-clock budget: the one subprocess test is the analyzer
itself, ~1 s). Runtime wire behavior lives in tests/test_serve.py and
tests/test_ingress.py.
"""
import json
import subprocess
import sys
import textwrap
import time
from pathlib import Path

from video_features_tpu.analysis.core import Package
from video_features_tpu.analysis.wire import (
    check_docs, check_error_echo, check_sync, default_lock_path,
    diff_lock, extract_surface, load_lock, lock_view, main, write_lock,
)

REPO_ROOT = Path(__file__).resolve().parent.parent
PKG_ROOT = REPO_ROOT / 'video_features_tpu'


# -- fixture wire package -----------------------------------------------------

_PROTOCOL = '''
    CMD_PING = 'ping'
    CMD_SUBMIT = 'submit'
    COMMANDS = (CMD_PING, CMD_SUBMIT)
    VERSION = '1.0'
    MAJOR = 1
    SUBMIT_FIELDS = ('cmd', 'v', 'feature_type', 'video_paths',
                     'timeout_s')
    PRIORITIES = ('interactive', 'batch')


    def check_version(msg):
        v = msg.get('v')
        if v is None:
            return None
        return error('unsupported version',
                     v=VERSION, request_id=msg.get('request_id'))


    def error(message, **extra):
        out = {'ok': False, 'error': message}
        out.update(extra)
        return out


    def ok(**fields):
        out = {'ok': True}
        out.update(fields)
        return out
'''

_SERVER = '''
    from fixwire.serve import protocol


    class ExtractionServer:
        def submit(self, feature_type, video_paths, timeout_s=None):
            if not video_paths:
                return protocol.error('queue_full', depth=1, capacity=1)
            return protocol.ok(request_id='r1')

        def status(self, request_id):
            req = self._requests.get(request_id)
            if req is None:
                return protocol.error('unknown request_id')
            return protocol.ok(**req.snapshot())

        def _dispatch(self, msg):
            cmd = msg.get('cmd')
            if cmd == protocol.CMD_PING:
                return protocol.ok(draining=False, v=protocol.VERSION)
            if cmd == protocol.CMD_SUBMIT:
                unknown = set(msg) - set(protocol.SUBMIT_FIELDS)
                if unknown:
                    return protocol.error('unknown submit fields')
                return self.submit(msg.get('feature_type'),
                                   msg.get('video_paths'),
                                   timeout_s=msg.get('timeout_s'))
            return protocol.error('unknown cmd')


    class Request:
        def snapshot(self):
            out = {'request_id': self.id, 'state': self.state()}
            if self.done_t is not None:
                out['latency_s'] = 1.0
            return out
'''

_CLIENT = '''
    from fixwire.serve import protocol


    class ServeClient:
        def _call(self, msg):
            msg.setdefault('v', protocol.VERSION)
            return msg

        def ping(self):
            return self._call({'cmd': protocol.CMD_PING})

        def submit(self, feature_type, video_paths, timeout_s=None):
            msg = {'cmd': protocol.CMD_SUBMIT,
                   'feature_type': feature_type,
                   'video_paths': list(video_paths)}
            if timeout_s is not None:
                msg['timeout_s'] = float(timeout_s)
            return self._call(msg)['request_id']
'''

_HTTP = '''
    OK = 200
    BAD_REQUEST = 400
    FORBIDDEN = 403
    NOT_FOUND = 404
    METHOD_NOT_ALLOWED = 405
    SERVICE_UNAVAILABLE = 503


    class HttpError(Exception):
        def __init__(self, status, code, message, **extra):
            super().__init__(message)
            self.status = status
'''

_GATEWAY = '''
    from fixwire.ingress.http import (
        BAD_REQUEST, FORBIDDEN, METHOD_NOT_ALLOWED, NOT_FOUND, OK,
        HttpError,
    )

    _EXTRACT_FIELDS = frozenset({'feature_type', 'video_paths',
                                 'timeout_s'})


    class IngressGateway:
        def __init__(self, server):
            reg = server.registry
            self._c = reg.counter('vft_ingress_requests_total', 'h',
                                  labels={'tenant': '', 'endpoint': '',
                                          'code': ''})
            self._g = reg.gauge('vft_ingress_open_connections', 'h')

        def _handle(self, req, resp, conn):
            if req.path == '/healthz':
                resp.send_json(OK, {'ok': True, 'draining': False})
                return
            tenant = self.auth.authenticate(req.headers)
            self._route(req, resp, conn, tenant)

        def _route(self, req, resp, conn, tenant):
            path, method = req.path, req.method
            if path == '/v1/extract' and method == 'POST':
                return self._handle_extract(req, resp, tenant)
            if path.startswith('/v1/requests/') and method == 'GET':
                return self._handle_status(req, resp, tenant)
            raise HttpError(NOT_FOUND if method in ('GET', 'POST')
                            else METHOD_NOT_ALLOWED,
                            'not_found', 'no route')

        def _handle_extract(self, req, resp, tenant):
            body = req.json_body(1)
            unknown = set(body) - _EXTRACT_FIELDS
            if unknown:
                raise HttpError(BAD_REQUEST, 'bad_request', 'unknown',
                                tenant=tenant.name)
            resp.send_json(OK, {'ok': True, 'request_id': 'r1',
                                'tenant': tenant.name})
            return OK, 'r1'

        def _handle_status(self, req, resp, tenant):
            rid = req.path[len('/v1/requests/'):]
            owner = self._owners.get(rid)
            if owner != tenant.name:
                raise HttpError(NOT_FOUND, 'not_found', 'unknown',
                                tenant=tenant.name, request_id=rid)
            st = {'state': 'done'}
            st['tenant'] = tenant.name
            resp.send_json(OK, {'ok': True, **st})
            return OK, rid
'''

_INGRESS_MD = '''
    # Ingress

    | Route | What |
    |---|---|
    | `GET /healthz` | liveness |
    | `POST /v1/extract` | submit |
    | `GET /v1/requests/<id>` | status |
'''

_SERVING_MD = '''
    # Serving

    | command | what |
    |---|---|
    | `submit` | submit |
    | `ping` | liveness |
'''

_FILES = {
    'serve/protocol.py': _PROTOCOL,
    'serve/server.py': _SERVER,
    'serve/client.py': _CLIENT,
    'ingress/http.py': _HTTP,
    'ingress/gateway.py': _GATEWAY,
}


def make_wire_pkg(tmp_path, mutate=None, name='fixwire', docs=True):
    # dedent FIRST: mutations operate on the final module text, so a
    # non-matching replacement fails loudly instead of silently
    files = {rel: textwrap.dedent(src) for rel, src in _FILES.items()}
    if mutate:
        mutate(files)
    root = tmp_path / name
    root.mkdir(exist_ok=True)
    (root / '__init__.py').write_text('')
    for rel, src in files.items():
        p = root / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(src)
        init = p.parent / '__init__.py'
        if not init.exists():
            init.write_text('')
    docs_dir = None
    if docs:
        docs_dir = tmp_path / 'docs'
        docs_dir.mkdir(exist_ok=True)
        (docs_dir / 'ingress.md').write_text(textwrap.dedent(_INGRESS_MD))
        (docs_dir / 'serving.md').write_text(textwrap.dedent(_SERVING_MD))
    return Package(root, name), docs_dir


def rules_of(findings):
    return {f.rule for f in findings}


def _sub(files, rel, old, new):
    assert old in files[rel], (rel, old)
    files[rel] = files[rel].replace(old, new)


# -- extraction ---------------------------------------------------------------

def test_extracts_the_fixture_surface(tmp_path):
    pkg, _ = make_wire_pkg(tmp_path)
    s = extract_surface(pkg)
    assert s['version'] == '1.0'
    assert set(s['commands']) == {'ping', 'submit'}
    sub = s['commands']['submit']
    # request fields come from msg.get + the SUBMIT_FIELDS reference
    assert sub['request_fields'] == ['feature_type', 'timeout_s', 'v',
                                     'video_paths']
    # response/error fields resolve through the self.submit hop
    assert sub['response_fields'] == ['ok', 'request_id']
    assert set(sub['error_fields']) >= {'ok', 'error', 'depth',
                                        'capacity'}
    assert sub['client_methods'] == ['submit']
    # **snapshot() spreads resolve against Request.snapshot statically
    ping = s['commands']['ping']
    assert ping['response_fields'] == ['draining', 'ok', 'v']
    assert set(s['routes']) == {'* /healthz', 'POST /v1/extract',
                                'GET /v1/requests/<id>'}
    ext = s['routes']['POST /v1/extract']
    assert ext['auth'] and not ext['tenant_scoped']
    assert ext['status'] == [200, 400]
    assert ext['errors'] == [[400, 'bad_request']]
    assert ext['request_fields'] == ['feature_type', 'timeout_s',
                                     'video_paths']
    st = s['routes']['GET /v1/requests/<id>']
    assert st['tenant_scoped'] and st['status'] == [200, 404]
    # **st spread resolves the assigned keys of the local dict
    assert 'tenant' in st['response_fields']
    hz = s['routes']['* /healthz']
    assert not hz['auth'] and hz['response_fields'] == ['draining', 'ok']
    # transport picks up the un-routed 404/405 fallback
    assert {404, 405} <= set(s['transport']['status'])
    assert s['metrics'] == {
        'vft_ingress_requests_total': ['code', 'endpoint', 'tenant'],
        'vft_ingress_open_connections': []}


def test_clean_fixture_has_no_rule_findings(tmp_path):
    pkg, docs = make_wire_pkg(tmp_path)
    s = extract_surface(pkg)
    assert check_sync(pkg, s) == []
    assert check_error_echo(pkg, s) == []
    assert check_docs(pkg, s, docs) == []


# -- wire-sync ----------------------------------------------------------------

def test_sync_flags_client_only_command(tmp_path):
    def mutate(files):
        files['serve/client.py'] += (
            "\n    def frob(self):\n"
            "        return self._call({'cmd': 'frobnicate'})\n")
    pkg, _ = make_wire_pkg(tmp_path, mutate)
    findings = check_sync(pkg, extract_surface(pkg))
    assert any(f.key == 'client-only:frobnicate' for f in findings)


def test_sync_flags_server_only_and_undeclared_command(tmp_path):
    def mutate(files):
        _sub(files, 'serve/server.py',
             "return protocol.error('unknown cmd')",
             "if cmd == 'reload':\n"
             "            return protocol.ok(reloaded=True)\n"
             "        return protocol.error('unknown cmd')")
    pkg, _ = make_wire_pkg(tmp_path, mutate)
    keys = {f.key for f in check_sync(pkg, extract_surface(pkg))}
    # handled but not declared in COMMANDS, and no client method
    assert {'undeclared:reload', 'server-only:reload'} <= keys


def test_sync_flags_declared_but_undispatched_command(tmp_path):
    def mutate(files):
        _sub(files, 'serve/protocol.py',
             "COMMANDS = (CMD_PING, CMD_SUBMIT)",
             "CMD_STATUS = 'status'\n"
             "COMMANDS = (CMD_PING, CMD_SUBMIT, CMD_STATUS)")
    pkg, _ = make_wire_pkg(tmp_path, mutate)
    keys = {f.key for f in check_sync(pkg, extract_surface(pkg))}
    assert 'undispatched:status' in keys


def test_sync_flags_client_field_the_server_rejects(tmp_path):
    def mutate(files):
        _sub(files, 'serve/client.py',
             "msg['timeout_s'] = float(timeout_s)",
             "msg['timeout_s'] = float(timeout_s)\n"
             "            msg['surprise'] = 1")
    pkg, _ = make_wire_pkg(tmp_path, mutate)
    keys = {f.key for f in check_sync(pkg, extract_surface(pkg))}
    assert 'submit-field:surprise' in keys


# -- error-echo ---------------------------------------------------------------

def test_error_echo_flags_check_version_without_request_id(tmp_path):
    def mutate(files):
        _sub(files, 'serve/protocol.py',
             ", request_id=msg.get('request_id')", "")
    pkg, _ = make_wire_pkg(tmp_path, mutate)
    findings = check_error_echo(pkg, extract_surface(pkg))
    assert [f.key for f in findings] == ['check_version:request_id']


def test_error_echo_flags_tenant_scoped_error_without_echo(tmp_path):
    def mutate(files):
        _sub(files, 'ingress/gateway.py',
             "tenant=tenant.name, request_id=rid)",
             "tenant=tenant.name)")
    pkg, _ = make_wire_pkg(tmp_path, mutate)
    findings = check_error_echo(pkg, extract_surface(pkg))
    assert any('request_id' in f.key for f in findings)


def test_error_echo_suppression_comment(tmp_path):
    def mutate(files):
        _sub(files, 'ingress/gateway.py',
             "raise HttpError(NOT_FOUND, 'not_found', 'unknown',\n"
             "                            tenant=tenant.name, "
             "request_id=rid)",
             "# vft-wire: ok=error-echo — fixture rationale\n"
             "            raise HttpError(NOT_FOUND, 'not_found', "
             "'unknown',\n                            "
             "tenant=tenant.name)")
    pkg, _ = make_wire_pkg(tmp_path, mutate)
    assert check_error_echo(pkg, extract_surface(pkg)) == []


# -- doc-sync -----------------------------------------------------------------

def test_doc_sync_flags_undocumented_route_and_command(tmp_path):
    pkg, docs = make_wire_pkg(tmp_path)
    (docs / 'ingress.md').write_text('# Ingress\n| `GET /healthz` |\n')
    (docs / 'serving.md').write_text('# Serving\n| `submit` |\n')
    keys = {f.key for f in check_docs(pkg, extract_surface(pkg), docs)}
    assert 'route:POST /v1/extract' in keys
    assert 'command:ping' in keys


def test_doc_sync_flags_stale_documented_route(tmp_path):
    pkg, docs = make_wire_pkg(tmp_path)
    text = (docs / 'ingress.md').read_text()
    (docs / 'ingress.md').write_text(
        text + '| `POST /v1/retired` | gone |\n')
    keys = {f.key for f in check_docs(pkg, extract_surface(pkg), docs)}
    assert keys == {'stale-route:/v1/retired'}


def test_doc_sync_skips_without_docs_dir(tmp_path):
    pkg, _ = make_wire_pkg(tmp_path, docs=False)
    assert check_docs(pkg, extract_surface(pkg), None) == []


# -- lock semantics -----------------------------------------------------------

def _pin(tmp_path, pkg):
    lock = tmp_path / 'WIRE.lock.json'
    write_lock(lock, lock_view(extract_surface(pkg)))
    return lock


def test_lock_roundtrip_is_clean(tmp_path):
    pkg, _ = make_wire_pkg(tmp_path)
    lock = _pin(tmp_path, pkg)
    assert diff_lock(extract_surface(pkg), load_lock(lock)) == []


def test_removed_command_demands_major_bump(tmp_path):
    pkg, _ = make_wire_pkg(tmp_path)
    lock = _pin(tmp_path, pkg)

    def mutate(files):
        _sub(files, 'serve/server.py',
             "if cmd == protocol.CMD_PING:\n"
             "            return protocol.ok(draining=False, "
             "v=protocol.VERSION)\n        ", "")
        _sub(files, 'serve/protocol.py',
             "COMMANDS = (CMD_PING, CMD_SUBMIT)",
             "COMMANDS = (CMD_SUBMIT,)")
        _sub(files, 'serve/client.py',
             "def ping(self):\n"
             "        return self._call({'cmd': protocol.CMD_PING})\n",
             "")
    pkg2, _ = make_wire_pkg(tmp_path, mutate, name='fixwire2')
    findings = diff_lock(extract_surface(pkg2), load_lock(lock))
    drops = [f for f in findings if f.key == 'command:-ping']
    assert len(drops) == 1
    assert 'MAJOR' in drops[0].message and '2.0' in drops[0].message


def test_removed_route_demands_major_bump(tmp_path):
    pkg, _ = make_wire_pkg(tmp_path)
    lock = _pin(tmp_path, pkg)

    def mutate(files):
        _sub(files, 'ingress/gateway.py',
             "        if path == '/v1/extract' and method == 'POST':\n"
             "            return self._handle_extract(req, resp, "
             "tenant)\n", "")
    pkg2, _ = make_wire_pkg(tmp_path, mutate, name='fixwire3')
    findings = diff_lock(extract_surface(pkg2), load_lock(lock))
    assert any(f.key == 'route:-POST /v1/extract'
               and 'MAJOR' in f.message for f in findings)


def test_added_field_demands_minor_bump_then_repin_clears(tmp_path):
    pkg, _ = make_wire_pkg(tmp_path)
    lock = _pin(tmp_path, pkg)

    def add_field(files):
        _sub(files, 'serve/server.py',
             "return protocol.ok(request_id='r1')",
             "return protocol.ok(request_id='r1', trace_id='t1')")

    def add_field_and_bump(files):
        add_field(files)
        _sub(files, 'serve/protocol.py',
             "VERSION = '1.0'", "VERSION = '1.1'")

    pkg2, _ = make_wire_pkg(tmp_path, add_field, name='fixwire4')
    findings = diff_lock(extract_surface(pkg2), load_lock(lock))
    adds = [f for f in findings if f.key.endswith('+trace_id')]
    assert adds and 'MINOR' in adds[0].message and '1.1' in adds[0].message
    # with the MINOR bump taken the advice flips to plain re-pin …
    pkg3, _ = make_wire_pkg(tmp_path, add_field_and_bump, name='fixwire5')
    findings = diff_lock(extract_surface(pkg3), load_lock(lock))
    adds = [f for f in findings if f.key.endswith('+trace_id')]
    assert adds and 'already taken' in adds[0].message
    # … and --write-lock settles it
    write_lock(lock, lock_view(extract_surface(pkg3)))
    assert diff_lock(extract_surface(pkg3), load_lock(lock)) == []


def test_version_drift_alone_is_reported(tmp_path):
    pkg, _ = make_wire_pkg(tmp_path)
    lock = _pin(tmp_path, pkg)

    def mutate(files):
        _sub(files, 'serve/protocol.py',
             "VERSION = '1.0'", "VERSION = '1.1'")
    pkg2, _ = make_wire_pkg(tmp_path, mutate, name='fixwire6')
    findings = diff_lock(extract_surface(pkg2), load_lock(lock))
    assert [f.key for f in findings] == ['version:1.0->1.1']


def test_scope_subset_write_merges_and_full_scope_prunes(tmp_path):
    pkg, _ = make_wire_pkg(tmp_path)
    lock = _pin(tmp_path, pkg)
    doc = load_lock(lock)
    # poison the routes section, then re-pin ONLY commands: routes must
    # survive untouched (subset merge), so the poison still diffs
    doc['routes']['POST /v1/retired'] = {'auth': True, 'status': [200]}
    lock.write_text(json.dumps(doc))
    surface = extract_surface(pkg)
    write_lock(lock, lock_view(surface), scopes=('commands',))
    kept = load_lock(lock)
    assert 'POST /v1/retired' in kept['routes']
    findings = diff_lock(surface, kept)
    assert [f.key for f in findings] == ['route:-POST /v1/retired']
    # the full-scope re-pin rebuilds the document and prunes the stale
    # route entry
    write_lock(lock, lock_view(surface))
    kept = load_lock(lock)
    assert 'POST /v1/retired' not in kept['routes']
    assert diff_lock(surface, kept) == []


# -- CLI contract -------------------------------------------------------------

def _cli(tmp_path, pkg_name, extra=()):
    return main(['--root', str(tmp_path / pkg_name),
                 '--package-name', pkg_name,
                 '--docs-dir', str(tmp_path / 'docs'),
                 '--lock', str(tmp_path / 'w.json'), *extra])


def test_cli_write_lock_then_clean_then_drift(tmp_path, capsys):
    make_wire_pkg(tmp_path)
    assert _cli(tmp_path, 'fixwire', ['--write-lock']) == 0
    assert _cli(tmp_path, 'fixwire') == 0
    # plant a removed route in place
    gw = tmp_path / 'fixwire' / 'ingress' / 'gateway.py'
    src = gw.read_text()
    cut = ("        if path == '/v1/extract' and method == 'POST':\n"
           "            return self._handle_extract(req, resp, tenant)\n")
    assert cut in src
    gw.write_text(src.replace(cut, ''))
    rc = _cli(tmp_path, 'fixwire')
    assert rc == 2
    out = capsys.readouterr().out
    assert 'POST /v1/extract' in out and 'MAJOR' in out


def test_cli_rejects_unknown_scope(tmp_path, capsys):
    make_wire_pkg(tmp_path)
    assert _cli(tmp_path, 'fixwire', ['--scope', 'nonsense']) == 1


# -- the live codebase --------------------------------------------------------

def test_live_tree_matches_shipped_lock():
    """The CI ``wire-check`` gate, pinned in tier-1: the extracted wire
    surface equals WIRE.lock.json and every sync/doc rule is green."""
    pkg = Package(PKG_ROOT, 'video_features_tpu')
    surface = extract_surface(pkg)
    findings = (check_sync(pkg, surface)
                + check_error_echo(pkg, surface)
                + check_docs(pkg, surface, REPO_ROOT / 'docs')
                + diff_lock(surface, load_lock(default_lock_path())))
    assert findings == [], '\n'.join(f.render() for f in findings)


def test_live_lock_covers_the_whole_surface():
    """Acceptance criteria: every loopback command and every ingress
    route is pinned — an empty section would make the drift rules
    vacuous without failing anything."""
    lock = load_lock(default_lock_path())
    from video_features_tpu.serve import protocol
    assert set(lock['commands']) == set(protocol.COMMANDS)
    assert protocol.VERSION == lock['version'] == '1.5'
    paths = {k.split(' ', 1)[1] for k in lock['routes']}
    assert {'/healthz', '/v1/extract', '/v1/requests/<id>',
            '/v1/requests/<id>/trace', '/v1/live/<id>', '/v1/metrics',
            '/metrics', '/v1/search'} == paths
    # the structural facts the fleet story depends on
    assert lock['routes']['GET /v1/requests/<id>/trace']['tenant_scoped']
    assert not lock['routes']['* /healthz']['auth']
    assert lock['metrics']['vft_ingress_shed_total'] == \
        ['class', 'reason', 'tenant']


def test_analyzer_subprocess_never_imports_jax_and_is_fast():
    """Acceptance criteria: the wire checker runs via the wrapper in
    well under the 30 s CI target and never imports jax (the wrapper
    exits 3 on a purity self-violation)."""
    t0 = time.monotonic()
    proc = subprocess.run(
        [sys.executable, str(REPO_ROOT / 'tools' / 'vft_wire.py')],
        capture_output=True, text=True, cwd=str(REPO_ROOT), timeout=60)
    wall = time.monotonic() - t0
    assert proc.returncode == 0, (proc.returncode, proc.stdout,
                                  proc.stderr)
    assert wall < 10, f'vft-wire took {wall:.1f}s (budget: 10s)'
