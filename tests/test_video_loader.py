"""VideoLoader: batch/overlap/timestamp semantics on the sample videos."""
import numpy as np
import pytest

from video_features_tpu.io.video import (
    VideoLoader, get_video_props, resample_frame_indices,
)


def test_props(sample_video):
    props = get_video_props(sample_video)
    assert props['fps'] > 0 and props['num_frames'] > 0
    assert props['height'] > 0 and props['width'] > 0


def test_native_fps_batches_and_timestamps(sample_video):
    loader = VideoLoader(sample_video, batch_size=32)
    total = 0
    first_times = None
    for batch, times, indices in loader:
        assert batch.dtype == np.uint8
        assert batch.shape[1:] == (loader.height, loader.width, 3)
        assert len(times) == len(indices) == batch.shape[0]
        if first_times is None:
            first_times = times
        # timestamp formula: idx / fps * 1000
        np.testing.assert_allclose(
            times, [i / loader.fps * 1000 for i in indices])
        total += batch.shape[0]
    assert total == len(loader)
    assert first_times[0] == 0.0


def test_overlap_caching(sample_video):
    loader = VideoLoader(sample_video, batch_size=8, overlap=1)
    prev_last = None
    for batch, times, indices in loader:
        if prev_last is not None:
            np.testing.assert_array_equal(batch[0], prev_last)
            assert indices[0] == prev_idx
        prev_last, prev_idx = batch[-1], indices[-1]
    # overlap=1 means each batch after the first contributes batch-1 new frames


def test_overlap_counts(sample_video):
    n = len(VideoLoader(sample_video, batch_size=8))
    loader = VideoLoader(sample_video, batch_size=8, overlap=1)
    seen = []
    for batch, times, indices in loader:
        seen.extend(indices if not seen else indices[1:])
    assert seen == list(range(n))


def test_fps_resampling_downsample(sample_video):
    props = get_video_props(sample_video)
    target = props['fps'] / 2
    loader = VideoLoader(sample_video, batch_size=16, fps=target, use_ffmpeg=False)
    assert loader.fps == target
    frames = sum(b.shape[0] for b, _, _ in loader)
    expected = props['num_frames'] / 2
    assert abs(frames - expected) <= 2
    assert frames == len(loader)


def test_total_mode(sample_video):
    loader = VideoLoader(sample_video, batch_size=16, total=20, use_ffmpeg=False)
    frames = sum(b.shape[0] for b, _, _ in loader)
    assert abs(frames - 20) <= 1


def test_resample_indices_identity():
    idx = resample_frame_indices(10, 25.0, 25.0)
    np.testing.assert_array_equal(idx, np.arange(10))


def test_resample_indices_upsample():
    idx = resample_frame_indices(10, 10.0, 20.0)
    assert len(idx) == 20
    assert idx[0] == 0 and idx[-1] == 9
    assert (np.diff(idx) >= 0).all()


def test_transform_applied(sample_video):
    loader = VideoLoader(sample_video, batch_size=4,
                         transform=lambda f: f.astype(np.float32) / 255.0)
    batch, _, _ = next(iter(loader))
    assert batch[0].dtype == np.float32
    assert batch[0].max() <= 1.0


def test_fps_and_total_mutually_exclusive(sample_video):
    with pytest.raises(ValueError):
        VideoLoader(sample_video, fps=10, total=10)


def test_transform_workers_preserve_order_and_values(short_video):
    """Threaded host transforms must equal the serial path exactly,
    including frame order and timestamps."""
    def tf(frame):
        return frame[:8, :8].astype(np.float32) / 255.0

    serial = VideoLoader(short_video, batch_size=7, transform=tf)
    threaded = VideoLoader(short_video, batch_size=7, transform=tf,
                           transform_workers=4)
    out_s = [(np.stack(b), t, i) for b, t, i in serial]
    out_t = [(np.stack(b), t, i) for b, t, i in threaded]
    assert len(out_s) == len(out_t) > 0
    for (bs, ts, idx_s), (bt, tt, idx_t) in zip(out_s, out_t):
        np.testing.assert_array_equal(bs, bt)
        assert ts == tt and idx_s == idx_t


def test_transform_worker_exception_propagates(short_video):
    def bad(frame):
        raise ValueError('boom')

    loader = VideoLoader(short_video, batch_size=4, transform=bad,
                         transform_workers=2)
    with pytest.raises(ValueError, match='boom'):
        next(iter(loader))


def test_missing_file_raises_filenotfound(tmp_path):
    with pytest.raises(FileNotFoundError, match='does not exist'):
        VideoLoader('/nonexistent/clip.mp4', batch_size=4)
    with pytest.raises(FileNotFoundError, match='does not exist'):
        VideoLoader(str(tmp_path), batch_size=4)  # a directory is not a video
