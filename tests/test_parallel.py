"""Parallel layer: mesh factoring, worklist sharding, sharded-step parity.

The conftest forces 8 virtual CPU devices, so these tests exercise real
(data, time) meshes and XLA's sharding propagation without TPU hardware —
the same path the driver's dryrun_multichip validates.
"""
import numpy as np
import pytest

import jax

from video_features_tpu.parallel import (
    factor_mesh_shape, make_mesh, shard_worklist, shuffled,
)

pytestmark = pytest.mark.slow  # parity/e2e/sharding: full lane only


def test_factor_mesh_shape():
    assert factor_mesh_shape(8) == (4, 2)
    assert factor_mesh_shape(1) == (1, 1)
    assert factor_mesh_shape(8, time_parallel=4) == (2, 4)
    with pytest.raises(ValueError):
        factor_mesh_shape(6, time_parallel=4)


def test_make_mesh_axes():
    mesh = make_mesh(n_devices=8)
    assert mesh.shape == {'data': 4, 'time': 2}
    mesh = make_mesh(n_devices=4, time_parallel=1)
    assert mesh.shape == {'data': 4, 'time': 1}


def test_shard_worklist_partitions_exactly():
    paths = [f'v{i}.mp4' for i in range(11)]
    shards = [shard_worklist(paths, shard_id=i, num_shards=3) for i in range(3)]
    # disjoint and complete
    merged = sorted(p for s in shards for p in s)
    assert merged == sorted(paths)
    assert all(len(s) in (3, 4) for s in shards)
    # deterministic
    assert shards[1] == shard_worklist(paths, shard_id=1, num_shards=3)


def test_shuffled_is_seeded_permutation():
    paths = [f'v{i}.mp4' for i in range(20)]
    a = shuffled(paths, seed=7)
    b = shuffled(paths, seed=7)
    assert a == b and sorted(a) == sorted(paths) and a != paths


def test_sharded_two_stream_step_matches_single_device():
    """The mesh-sharded fused step must be numerically identical to the
    unsharded one — sharding is a layout choice, not a numerics choice."""
    from functools import partial

    if not (hasattr(jax.lax, 'pvary') or hasattr(jax.lax, 'pcast')):
        # jax 0.4.x (pre-pvary): the (data>1, time>1) sharded program's
        # FLOW stream diverges materially from single-device (measured
        # max abs 5.49 on 0.4.37; data-only meshes stay within float32
        # noise) — the time-axis resharding semantics this graph was
        # validated against do not hold there. parallel/mesh.py warns at
        # mesh build; this parity pin applies on the targeted jax only.
        pytest.skip('(data, time) sharded two-stream numerics are not '
                    'valid on jax 0.4.x (no pvary/pcast)')

    from video_features_tpu.extract.i3d import fused_two_stream_step
    from video_features_tpu.models import i3d as i3d_model
    from video_features_tpu.models import raft as raft_model
    from video_features_tpu.parallel import (
        build_sharded_two_stream_step, put_batch, put_replicated,
    )
    from video_features_tpu.transplant.torch2jax import transplant

    params = {
        'rgb': transplant(i3d_model.init_state_dict(modality='rgb')),
        'flow': transplant(i3d_model.init_state_dict(modality='flow')),
        'raft': transplant(raft_model.init_state_dict()),
    }
    rng = np.random.RandomState(0)
    # B=4 over data=4; stack=16 pairs over time=2. 64px is the smallest
    # frame whose /8 feature grid survives RAFT's 4-level corr pyramid.
    stacks = rng.randint(0, 255, size=(4, 17, 64, 64, 3)).astype(np.float32)
    kwargs = dict(pads=(0, 0, 0, 0), streams=('rgb', 'flow'), crop_size=64)

    with jax.default_matmul_precision('highest'):
        ref = jax.jit(partial(fused_two_stream_step, **kwargs))(params, stacks)

        mesh = make_mesh(n_devices=8)
        step = build_sharded_two_stream_step(mesh)
        out = step(put_replicated(mesh, params), put_batch(mesh, stacks),
                   pads=(0, 0, 0, 0), crop_size=64)

    for key in ('rgb', 'flow'):
        np.testing.assert_allclose(np.asarray(out[key]), np.asarray(ref[key]),
                                   rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize('device_resize', [False, True],
                         ids=['host-resize', 'device-resize'])
def test_extractor_data_parallel_e2e(short_video, tmp_path, device_resize):
    """ExtractI3D(data_parallel=true) runs the mesh-sharded step from the
    normal extract() path and matches the single-device extractor — with
    the host PIL resize and (round 5) with the bit-exact in-graph resize,
    which is per-sample work that composes with the data sharding."""
    from video_features_tpu.config import load_config
    from video_features_tpu.registry import create_extractor

    common = {
        'video_paths': short_video, 'device': 'cpu',
        'streams': 'rgb',                       # rgb-only keeps CPU cost low
        'stack_size': 16, 'step_size': 16,
        'concat_rgb_flow': False, 'device_resize': device_resize,
        'output_path': str(tmp_path / 'out'), 'tmp_path': str(tmp_path / 'tmp'),
    }
    dp = create_extractor(load_config('i3d', overrides={
        **common, 'data_parallel': True, 'batch_size': 1}))
    assert dp.mesh.shape['data'] == 4
    assert dp.batch_size == 4        # global batch rounded up to the data axis

    single = create_extractor(load_config('i3d', overrides=common))

    feats_dp = dp.extract(short_video)
    feats_single = single.extract(short_video)
    assert feats_dp['rgb'].shape == feats_single['rgb'].shape
    np.testing.assert_allclose(feats_dp['rgb'], feats_single['rgb'],
                               atol=2e-5, rtol=1e-5)


def test_initialize_passthrough_and_already_init(monkeypatch):
    from video_features_tpu.parallel import distributed

    calls = []
    monkeypatch.setattr(jax.distributed, 'initialize',
                        lambda **kw: calls.append(kw))
    distributed.initialize('host:1234', 4, 2)
    assert calls == [{'coordinator_address': 'host:1234',
                      'num_processes': 4, 'process_id': 2}]

    def boom(**kw):
        raise RuntimeError('backend already initialized')
    monkeypatch.setattr(jax.distributed, 'initialize', boom)
    distributed.initialize()  # swallowed

    def other(**kw):
        raise RuntimeError('connection refused')
    monkeypatch.setattr(jax.distributed, 'initialize', other)
    with pytest.raises(RuntimeError, match='connection refused'):
        distributed.initialize()


def test_cli_multihost_shards_worklist(short_video, tmp_path, monkeypatch, capsys):
    """multihost=true initializes the runtime and takes this host's shard
    (process 0 of 1 == the full list) without shuffling."""
    from video_features_tpu import cli
    from video_features_tpu.parallel import distributed

    inited = []
    monkeypatch.setattr(distributed, 'initialize',
                        lambda *a, **k: inited.append(1))
    rc = cli.main([
        'feature_type=resnet', 'model_name=resnet18', 'device=cpu',
        'batch_size=16', f'video_paths={short_video}', 'multihost=true',
        'on_extraction=save_numpy',
        f'output_path={tmp_path / "out"}', f'tmp_path={tmp_path / "tmp"}',
    ])
    assert rc == 0
    assert inited == [1]
    stem = short_video.rsplit('/', 1)[-1].rsplit('.', 1)[0]
    assert (tmp_path / 'out' / 'resnet' / 'resnet18' / f'{stem}_resnet.npy').exists()


def test_framewise_data_parallel_matches_single_device(short_video, tmp_path):
    """ResNet with data_parallel=true: mesh-sharded batches == single-device."""
    from video_features_tpu.config import load_config
    from video_features_tpu.registry import create_extractor

    common = {
        'model_name': 'resnet18', 'device': 'cpu', 'batch_size': 16,
        'video_paths': short_video,
        'output_path': str(tmp_path / 'out'), 'tmp_path': str(tmp_path / 'tmp'),
    }
    dp = create_extractor(load_config('resnet', overrides={
        **common, 'data_parallel': True}))
    single = create_extractor(load_config('resnet', overrides=common))

    feats_dp = dp.extract(short_video)
    assert dp._mesh is not None and dp.batch_size % dp._mesh.shape['data'] == 0
    feats_single = single.extract(short_video)
    np.testing.assert_allclose(feats_dp['resnet'], feats_single['resnet'],
                               atol=2e-5, rtol=1e-5)
    np.testing.assert_array_equal(feats_dp['timestamps_ms'],
                                  feats_single['timestamps_ms'])


def test_r21d_data_parallel_matches_single_device(short_video, tmp_path):
    from video_features_tpu.config import load_config
    from video_features_tpu.registry import create_extractor

    common = {
        'video_paths': short_video, 'device': 'cpu',
        'output_path': str(tmp_path / 'out'), 'tmp_path': str(tmp_path / 'tmp'),
    }
    dp = create_extractor(load_config('r21d', overrides={
        **common, 'data_parallel': True}))
    single = create_extractor(load_config('r21d', overrides=common))

    feats_dp = dp.extract(short_video)
    assert dp._mesh is not None
    assert dp.stack_batch % dp._mesh.shape['data'] == 0
    feats_single = single.extract(short_video)
    np.testing.assert_allclose(feats_dp['r21d'], feats_single['r21d'],
                               atol=2e-5, rtol=1e-5)


def test_data_parallel_capability_set_is_valid():
    from video_features_tpu.registry import DATA_PARALLEL_FEATURES, EXTRACTORS
    # every claimed-capable type must exist; the set is intentionally a
    # literal so new extractors default to NOT claiming DP support
    assert DATA_PARALLEL_FEATURES <= frozenset(EXTRACTORS)


def test_raft_pair_sharding_matches_single_device():
    """RAFT pairs data-sharded over the mesh (halo paid host-side) at few
    iterations: over the full 20, random (non-contracting) weights amplify
    fp-reorder noise between shardings — same caveat as the pallas
    cross-path tests — so parity is checked where it is meaningful."""
    from video_features_tpu.models import raft as raft_model
    from video_features_tpu.parallel import put_batch, put_replicated
    from video_features_tpu.transplant.torch2jax import transplant

    params = transplant(raft_model.init_state_dict())
    rng = np.random.RandomState(3)
    frames = rng.randint(0, 255, (9, 64, 64, 3)).astype(np.float32)

    with jax.default_matmul_precision('highest'):
        ref = np.asarray(raft_model.forward(
            params, frames[:-1], frames[1:], iters=3))

        mesh = make_mesh(n_devices=8, time_parallel=1)
        sharded = jax.jit(
            lambda p, f1, f2: raft_model.forward(p, f1, f2, iters=3))
        out = np.asarray(sharded(put_replicated(mesh, params),
                                 put_batch(mesh, frames[:-1]),
                                 put_batch(mesh, frames[1:])))
    np.testing.assert_allclose(out, ref, atol=5e-4, rtol=1e-4)


def test_raft_data_parallel_e2e_smoke(short_video, tmp_path):
    """data_parallel=true through the full extractor path: mesh built,
    batch rounded, outputs finite and correctly shaped."""
    from video_features_tpu.config import load_config
    from video_features_tpu.registry import create_extractor

    dp = create_extractor(load_config('raft', overrides={
        'video_paths': short_video, 'device': 'cpu',
        'side_size': 64, 'extraction_total': 9, 'batch_size': 8,
        'data_parallel': True,
        'output_path': str(tmp_path / 'out'), 'tmp_path': str(tmp_path / 'tmp'),
    }))
    feats = dp.extract(short_video)
    assert dp._mesh is not None and dp.batch_size % dp._mesh.shape['data'] == 0
    assert feats['raft'].shape[1] == 2 and feats['raft'].shape[0] >= 8
    assert np.isfinite(feats['raft']).all()


def test_s3d_data_parallel_matches_single_device(short_video, tmp_path):
    from video_features_tpu.config import load_config
    from video_features_tpu.registry import create_extractor

    common = {
        'video_paths': short_video, 'device': 'cpu',
        'stack_size': 16, 'step_size': 16, 'extraction_fps': None,
        'output_path': str(tmp_path / 'out'), 'tmp_path': str(tmp_path / 'tmp'),
    }
    dp = create_extractor(load_config('s3d', overrides={
        **common, 'data_parallel': True}))
    single = create_extractor(load_config('s3d', overrides=common))

    feats_dp = dp.extract(short_video)
    assert dp._mesh is not None
    feats_single = single.extract(short_video)
    np.testing.assert_allclose(feats_dp['s3d'], feats_single['s3d'],
                               atol=2e-5, rtol=1e-5)


def test_vggish_data_parallel_matches_single_device(tmp_path):
    import wave

    from video_features_tpu.config import load_config
    from video_features_tpu.registry import create_extractor

    sr = 16000
    t = np.arange(int(sr * 3.5)) / sr
    samples = (np.sin(2 * np.pi * 330 * t) * 0.4 * 32767).astype('<i2')
    wav = str(tmp_path / 'tone.wav')
    with wave.open(wav, 'wb') as f:
        f.setnchannels(1)
        f.setsampwidth(2)
        f.setframerate(sr)
        f.writeframes(samples.tobytes())

    common = {
        'video_paths': wav, 'device': 'cpu',
        'output_path': str(tmp_path / 'out'), 'tmp_path': str(tmp_path / 'tmp'),
    }
    dp = create_extractor(load_config('vggish', overrides={
        **common, 'data_parallel': True, 'batch_size': 8}))
    single = create_extractor(load_config('vggish', overrides=common))

    feats_dp = dp.extract(wav)
    assert dp._mesh is not None and dp.example_batch % dp._mesh.shape['data'] == 0
    feats_single = single.extract(wav)
    np.testing.assert_allclose(feats_dp['vggish'], feats_single['vggish'],
                               atol=2e-5, rtol=1e-5)


def test_data_parallel_warn_path_for_future_unsupported(
        tmp_path, capsys, short_video, monkeypatch):
    """The warn-and-disable gate must keep working when an extractor
    without DP support is added (simulated by shrinking the registry set)."""
    from video_features_tpu import registry
    from video_features_tpu.config import load_config

    monkeypatch.setattr(registry, 'DATA_PARALLEL_FEATURES',
                        frozenset({'i3d'}))
    args = load_config('resnet', overrides={
        'model_name': 'resnet18', 'video_paths': short_video, 'device': 'cpu',
        'data_parallel': True,
        'output_path': str(tmp_path / 'out'), 'tmp_path': str(tmp_path / 'tmp'),
    })
    assert args['data_parallel'] is False
    assert 'not implemented for resnet' in capsys.readouterr().out


def test_raft_halo_shard_dp_matches_single_device():
    """The data-parallel halo layout (each device gets its k+1-frame run,
    boundary frames duplicated host-side) must reproduce the single-device
    forward_consecutive at few iterations (same fp-noise caveat as the
    pair-sharding test)."""
    from video_features_tpu.utils.device import shard_map
    from jax.sharding import PartitionSpec as P

    from video_features_tpu.models import raft as raft_model
    from video_features_tpu.parallel import put_batch, put_replicated
    from video_features_tpu.transplant.torch2jax import transplant

    params = transplant(raft_model.init_state_dict())
    rng = np.random.RandomState(4)
    n, k = 8, 2
    frames = rng.randint(0, 255, (n * k + 1, 64, 64, 3)).astype(np.float32)

    with jax.default_matmul_precision('highest'):
        ref = np.asarray(raft_model.forward_consecutive(
            params, frames, iters=3))

        mesh = make_mesh(n_devices=n, time_parallel=1)
        halo = np.stack([frames[d * k: d * k + k + 1] for d in range(n)])
        halo = halo.reshape(n * (k + 1), 64, 64, 3)
        step = jax.jit(shard_map(
            lambda p, f: raft_model.forward_consecutive(p, f, iters=3),
            mesh=mesh, in_specs=(P(), P('data')), out_specs=P('data')))
        out = np.asarray(step(put_replicated(mesh, params),
                              put_batch(mesh, halo)))
    assert out.shape == ref.shape == (n * k, 64, 64, 2)
    np.testing.assert_allclose(out, ref, atol=5e-4, rtol=1e-4)


def test_vit_sequence_parallel_matches_single_device():
    """Ring-attention sequence parallelism over the token axis: the
    production consumer path for very long token sequences. 197 ragged
    tokens pad to 200 over an 8-device time axis (masked keys rotate with
    their shards) and must match the unsharded forward."""
    from video_features_tpu.models import vit as vit_model
    from video_features_tpu.transplant.torch2jax import transplant

    params = transplant(vit_model.init_state_dict(arch='vit_tiny_patch16_224'))
    x = np.random.RandomState(0).rand(2, 224, 224, 3).astype(np.float32)
    mesh = make_mesh(time_parallel=8)
    assert mesh.shape['time'] == 8

    with jax.default_matmul_precision('highest'):
        ref = np.asarray(vit_model.forward(params, x,
                                           arch='vit_tiny_patch16_224'))
        got = np.asarray(jax.jit(
            lambda p, t: vit_model.forward_sequence_parallel(
                p, t, mesh, arch='vit_tiny_patch16_224'))(params, x))
    rel = np.linalg.norm(got - ref) / np.linalg.norm(ref)
    assert rel < 1e-5, f'rel L2 {rel}'


def test_timm_sequence_parallel_extractor_e2e(short_video, tmp_path):
    """sequence_parallel=true through the real extractor: tokens shard over
    all 8 virtual devices, features match the single-device extractor."""
    from video_features_tpu.config import load_config
    from video_features_tpu.registry import create_extractor

    common = {
        'video_paths': short_video, 'device': 'cpu', 'batch_size': 8,
        'model_name': 'vit_tiny_patch16_224', 'allow_random_weights': True,
        'extraction_fps': 2,
        'output_path': str(tmp_path / 'o'), 'tmp_path': str(tmp_path / 't'),
    }
    sp = create_extractor(load_config('timm', overrides={
        **common, 'sequence_parallel': True}))
    assert sp._mesh is not None and sp._mesh.shape['time'] == 8
    single = create_extractor(load_config('timm', overrides=common))

    feats_sp = sp.extract(short_video)
    feats_single = single.extract(short_video)
    np.testing.assert_allclose(feats_sp['timm'], feats_single['timm'],
                               atol=2e-5, rtol=1e-5)


def test_timm_sequence_parallel_rejects_conv_families(tmp_path):
    from video_features_tpu.config import load_config
    from video_features_tpu.registry import create_extractor

    args = load_config('timm', overrides={
        'video_paths': 'v.mp4', 'device': 'cpu',
        'model_name': 'resnet18', 'sequence_parallel': True,
        'allow_random_weights': True,
        'output_path': str(tmp_path / 'o'), 'tmp_path': str(tmp_path / 't'),
    })
    with pytest.raises(NotImplementedError, match='token axis'):
        create_extractor(args)
