"""Test env: force JAX onto CPU with 8 virtual devices so sharding tests run
without TPU hardware. Must run before jax is imported anywhere.

``VFT_TEST_PLATFORM=native`` leaves the process's real backend alone —
required for the ``tpu``-marked hardware lane (``VFT_TEST_PLATFORM=native
pytest -m tpu``), which would otherwise see the forced-CPU backend and
skip itself on every host."""
import os
import sys
from pathlib import Path

_PLAT = os.environ.get('VFT_TEST_PLATFORM', 'cpu')
if _PLAT not in ('cpu', 'native'):
    raise SystemExit(
        f'VFT_TEST_PLATFORM={_PLAT!r} is not recognized: use "cpu" (the '
        f'default hermetic 8-virtual-device environment) or "native" '
        f'(real hardware, for the `-m tpu` lane)')
_NATIVE = _PLAT == 'native'
if _NATIVE:
    print('conftest: VFT_TEST_PLATFORM=native — running on the REAL '
          'backend (no CPU pin, no 8-device virtual mesh); intended for '
          'the `-m tpu` hardware lane only', file=sys.stderr)
if not _NATIVE:
    os.environ['JAX_PLATFORMS'] = 'cpu'
    xla_flags = os.environ.get('XLA_FLAGS', '')
    if '--xla_force_host_platform_device_count' not in xla_flags:
        os.environ['XLA_FLAGS'] = (
            xla_flags + ' --xla_force_host_platform_device_count=8').strip()

# A site hook may have pre-imported jax with JAX_PLATFORMS pointed at a
# remote TPU backend; the env var above is then too late (the config read
# it at import). Force the runtime config before any backend initializes
# so tests never try to dial real hardware.
import jax  # noqa: E402

if not _NATIVE:
    jax.config.update('jax_platforms', 'cpu')

# Pretrained blobs are not bundled: the suite intentionally runs random
# weights (parity tests transplant seeded torch modules instead). The
# production path hard-errors without this escape — tests/test_weights.py
# unsets it to assert that.
os.environ.setdefault('VFT_ALLOW_RANDOM_WEIGHTS', '1')

REPO_ROOT = Path(__file__).parent.parent
REFERENCE_ROOT = Path('/root/reference')

if str(REPO_ROOT) not in sys.path:
    sys.path.insert(0, str(REPO_ROOT))

import pytest  # noqa: E402


def pytest_collection_modifyitems(config, items):
    """Scope native mode to the hardware lane: everything NOT tpu-marked
    assumes the hermetic 8-virtual-device CPU backend and would hard-fail
    (mesh size) or silently compile against real hardware."""
    if not _NATIVE:
        return
    skip = pytest.mark.skip(
        reason='VFT_TEST_PLATFORM=native runs only the `-m tpu` lane')
    for item in items:
        if 'tpu' not in item.keywords:
            item.add_marker(skip)


@pytest.fixture(scope='session')
def sample_video() -> str:
    """The reference repo's sample clip (read-only)."""
    path = REFERENCE_ROOT / 'sample' / 'v_GGSY1Qvo990.mp4'
    if not path.exists():
        pytest.skip('sample video unavailable')
    return str(path)


@pytest.fixture(scope='session')
def sample_video_2() -> str:
    path = REFERENCE_ROOT / 'sample' / 'v_ZNVhz7ctTq0.mp4'
    if not path.exists():
        pytest.skip('sample video unavailable')
    return str(path)


@pytest.fixture(scope='session')
def short_video(tmp_path_factory) -> str:
    """A ~48-frame clip cut from the sample video (keeps CPU E2E tests fast)."""
    import cv2

    src = REFERENCE_ROOT / 'sample' / 'v_ZNVhz7ctTq0.mp4'
    if not src.exists():
        pytest.skip('sample video unavailable')
    out = str(tmp_path_factory.mktemp('vids') / 'short_clip.mp4')
    cap = cv2.VideoCapture(str(src))
    fps = cap.get(cv2.CAP_PROP_FPS)
    w = int(cap.get(cv2.CAP_PROP_FRAME_WIDTH))
    h = int(cap.get(cv2.CAP_PROP_FRAME_HEIGHT))
    writer = cv2.VideoWriter(out, cv2.VideoWriter_fourcc(*'mp4v'), fps, (w, h))
    for _ in range(48):
        ok, frame = cap.read()
        if not ok:
            break
        writer.write(frame)
    writer.release()
    cap.release()
    return out


def _clip_from_sample(tmp_path_factory, n_frames: int, tag: str) -> str:
    """First ``n_frames`` of the reference sample, re-encoded via cv2."""
    import cv2

    src = REFERENCE_ROOT / 'sample' / 'v_ZNVhz7ctTq0.mp4'
    if not src.exists():
        pytest.skip('sample video unavailable')
    out = str(tmp_path_factory.mktemp(tag) / f'clip{n_frames}.mp4')
    cap = cv2.VideoCapture(str(src))
    fps = cap.get(cv2.CAP_PROP_FPS)
    w = int(cap.get(cv2.CAP_PROP_FRAME_WIDTH))
    h = int(cap.get(cv2.CAP_PROP_FRAME_HEIGHT))
    writer = cv2.VideoWriter(out, cv2.VideoWriter_fourcc(*'mp4v'), fps, (w, h))
    for _ in range(n_frames):
        ok, frame = cap.read()
        if not ok:
            break
        writer.write(frame)
    writer.release()
    cap.release()
    return out


@pytest.fixture(scope='session')
def video_33(tmp_path_factory) -> str:
    """A 33-frame clip: exactly two stack_size=16 windows (2·16+1 frames)
    for the end-to-end golden parity tests."""
    return _clip_from_sample(tmp_path_factory, 33, 'vids33')


@pytest.fixture(scope='session')
def video_65(tmp_path_factory) -> str:
    """A 65-frame clip: exactly one stack_size=64 window (64+1 frames) —
    upstream's documented default stack (reference docs/models/i3d.md:15-18),
    for the published-geometry golden."""
    return _clip_from_sample(tmp_path_factory, 65, 'vids65')


@pytest.fixture(scope='session')
def reference_repo() -> Path:
    """Path to the reference implementation, importable for parity tests only."""
    if not REFERENCE_ROOT.exists():
        pytest.skip('reference repo unavailable')
    if str(REFERENCE_ROOT) not in sys.path:
        sys.path.insert(0, str(REFERENCE_ROOT))
    return REFERENCE_ROOT
