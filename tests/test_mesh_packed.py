"""Mesh-sharded packed execution (``mesh_devices=``): the data-parallel
device loop must be externally invisible — byte-identical outputs across
the packed CLI, worklist, and serve warm paths at any device count —
while planning batches at capacity × ndev, masking uneven tails instead
of stalling them, and keeping the per-video fault contract (a poisoned
video fails alone; both shards' siblings survive).

Runs everywhere: tests/conftest.py forces
``--xla_force_host_platform_device_count=8`` virtual host CPU devices,
so the ndev=2 sharded path is exercised without hardware.
"""
import json
import os
from pathlib import Path

import numpy as np
import pytest

from video_features_tpu.config import load_config
from video_features_tpu.registry import create_extractor
from video_features_tpu.utils.output import make_path

from tools.make_sample_video import write_noise_clip as _write_clip  # noqa: E402


@pytest.fixture(scope='module')
def mesh_worklist(tmp_path_factory):
    """Mixed-length clips: 9+4+14 = 27 resnet frames, so capacity 4 over
    2 devices (global batch 8) runs 3 full batches plus an UNEVEN tail of
    3 — the second shard's tail slice is entirely padding."""
    d = tmp_path_factory.mktemp('meshvids')
    return [_write_clip(d / f'mv{i}.mp4', n, seed=i)
            for i, n in enumerate((9, 4, 14))]


def _resnet_args(paths, out, tmp, **kw):
    over = dict(video_paths=paths, device='cpu', model_name='resnet18',
                batch_size=4, allow_random_weights=True,
                on_extraction='save_numpy', output_path=str(out),
                tmp_path=str(tmp))
    over.update(kw)
    return load_config('resnet', overrides=over)


RESNET_KEYS = ('resnet', 'fps', 'timestamps_ms')


def _assert_outputs_identical(root_a, root_b, paths, keys=RESNET_KEYS):
    compared = 0
    for p in paths:
        for k in keys:
            a = Path(make_path(str(root_a), p, k, '.npy'))
            b = Path(make_path(str(root_b), p, k, '.npy'))
            assert a.read_bytes() == b.read_bytes(), (p, k)
            compared += 1
    assert compared == len(paths) * len(keys)


# -- mesh planning units (no extractor) --------------------------------------


def test_make_mesh_autodetect_spans_every_device():
    """``n_devices=0`` is the auto-detect spelling: the mesh spans every
    available device (8 forced host CPUs under the conftest flag)."""
    import jax

    from video_features_tpu.parallel.mesh import DATA_AXIS, make_mesh
    mesh = make_mesh(n_devices=0, time_parallel=1)
    assert mesh.shape[DATA_AXIS] == len(jax.devices())


def test_make_mesh_overask_raises_named_error():
    """Asking for more devices than exist must raise a ValueError naming
    both counts — not an XLA placement error downstream."""
    import jax

    from video_features_tpu.parallel.mesh import make_mesh
    have = len(jax.devices())
    with pytest.raises(ValueError, match=f'requested {have + 1}.*{have}'):
        make_mesh(n_devices=have + 1, time_parallel=1)


def test_batch_planning_errors_are_named():
    """capacity × ndev planning failures surface as clear ValueErrors at
    plan time, never as an XLA shape error mid-batch."""
    from video_features_tpu.parallel.mesh import (
        make_mesh, plan_device_batch, require_shardable,
    )
    mesh = make_mesh(n_devices=2, time_parallel=1)
    assert plan_device_batch(4, mesh) == 8
    with pytest.raises(ValueError, match='capacity'):
        plan_device_batch(0, mesh)
    assert require_shardable(8, mesh) == 4
    with pytest.raises(ValueError, match='cannot shard over 2'):
        require_shardable(7, mesh)


def test_configure_mesh_resolves_and_validates(mesh_worklist, tmp_path):
    """The config knob resolves at BUILD time: 0 auto-detects every local
    device, an over-ask raises with the host's device count named, a
    negative count is rejected by sanity_check, and data_parallel keeps
    ownership of the device set (mesh_devices degrades with a warning)."""
    import jax

    ndev = len(jax.devices())
    ex = create_extractor(_resnet_args(
        mesh_worklist, tmp_path / 'auto', tmp_path / 'ta',
        mesh_devices=0))
    assert ex.mesh_devices == ndev

    with pytest.raises(ValueError, match=f'mesh_devices={ndev + 3}'):
        create_extractor(_resnet_args(
            mesh_worklist, tmp_path / 'over', tmp_path / 'to',
            mesh_devices=ndev + 3))

    with pytest.raises(ValueError, match='mesh_devices'):
        _resnet_args(mesh_worklist, tmp_path / 'neg', tmp_path / 'tn',
                     mesh_devices=-1)

    with pytest.warns(UserWarning, match='data_parallel'):
        args = _resnet_args(mesh_worklist, tmp_path / 'dp',
                            tmp_path / 'tdp',
                            mesh_devices=2, data_parallel=True)
    assert args['mesh_devices'] == 1          # data_parallel wins


# -- packed worklist parity ---------------------------------------------------


def test_mesh_parity_packed_framewise(mesh_worklist, tmp_path):
    """resnet packed worklist: outputs at mesh_devices=2 (batches planned
    at 4 × 2 and sharded over the data axis) are byte-identical to the
    single-device loop, and the sharded run really built a 2-device
    mesh."""
    ex1 = create_extractor(_resnet_args(
        mesh_worklist, tmp_path / 'm1', tmp_path / 't1',
        pack_across_videos=True, mesh_devices=1))
    ex1.extract_packed(mesh_worklist)
    assert ex1._mesh is None                   # 1 ≡ today's loop

    ex2 = create_extractor(_resnet_args(
        mesh_worklist, tmp_path / 'm2', tmp_path / 't2',
        pack_across_videos=True, mesh_devices=2))
    ex2.extract_packed(mesh_worklist)
    assert ex2._packed_mesh_ndev == 2
    assert ex2._mesh is not None

    _assert_outputs_identical(ex1.output_path, ex2.output_path,
                              mesh_worklist)


def test_mesh_parity_packed_stacks(mesh_worklist, tmp_path):
    """r21d (stack family, mixed window counts): byte-identical at
    mesh_devices=1 vs 2."""
    def run(tag, ndev):
        args = load_config('r21d', overrides=dict(
            video_paths=mesh_worklist, device='cpu', stack_size=4,
            step_size=4, batch_size=2, allow_random_weights=True,
            on_extraction='save_numpy',
            output_path=str(tmp_path / tag / 'out'),
            tmp_path=str(tmp_path / tag / 'tmp'),
            pack_across_videos=True, mesh_devices=ndev))
        ex = create_extractor(args)
        ex.extract_packed(mesh_worklist)
        return ex

    ex1 = run('s1', 1)
    ex2 = run('s2', 2)
    assert ex2._packed_mesh_ndev == 2
    _assert_outputs_identical(ex1.output_path, ex2.output_path,
                              mesh_worklist, keys=('r21d',))


def test_cli_mesh_byte_identity_and_manifest(mesh_worklist, tmp_path,
                                             capsys):
    """The full CLI entry at mesh_devices=2 writes byte-identical
    features to mesh_devices=1, the run manifest records the mesh shape
    with per-device occupancy, and the model/d2h spans carry the mesh
    width + per-shard valid counts."""
    from video_features_tpu.cli import main as cli_main

    manifest = str(tmp_path / 'mesh_manifest.json')
    trace = str(tmp_path / 'mesh_trace.json')
    roots = {}
    for ndev in (1, 2):
        out = tmp_path / f'cli{ndev}'
        argv = [
            'feature_type=resnet', 'model_name=resnet18', 'device=cpu',
            'batch_size=4', 'allow_random_weights=true',
            'on_extraction=save_numpy', 'pack_across_videos=true',
            f'mesh_devices={ndev}',
            f'output_path={out}', f'tmp_path={tmp_path / "ctmp"}',
            'video_paths=[' + ','.join(str(p) for p in mesh_worklist) + ']',
        ]
        if ndev == 2:
            argv += [f'manifest_out={manifest}', f'trace_out={trace}']
        assert cli_main(argv) == 0
        roots[ndev] = os.path.join(str(out), 'resnet', 'resnet18')
    capsys.readouterr()
    _assert_outputs_identical(roots[1], roots[2], mesh_worklist)

    man = json.loads(Path(manifest).read_text())
    assert man['mesh']['mesh_devices'] == 2
    assert man['mesh']['shape']['data'] == 2
    assert man['mesh']['capacity_per_device'] == 4
    assert man['mesh']['global_batch'] == 8
    assert len(man['mesh']['devices']) == 2
    occ_dev = man['stages']['model'].get('occ_device') or {}
    assert set(occ_dev) == set(man['mesh']['devices'])
    for rec in occ_dev.values():
        assert 0.0 <= rec['occupancy'] <= 1.0

    events = json.loads(Path(trace).read_text())['traceEvents']
    mesh_spans = [e for e in events if e['ph'] == 'X'
                  and e['name'] in ('model', 'd2h')
                  and (e.get('args') or {}).get('mesh_devices')]
    assert mesh_spans, 'no mesh-annotated model/d2h spans in the trace'
    for e in mesh_spans:
        assert e['args']['mesh_devices'] == 2
        assert len(e['args']['shard_valid']) == 2


# -- fault isolation + uneven tails -------------------------------------------


def test_mesh_fault_isolation_poisoned_video(mesh_worklist, tmp_path):
    """A decode failure MID-video on the sharded loop: the poisoned video
    fails alone — every sibling (on both shards of its batches) saves
    byte-identically to a clean mesh run."""
    clean = create_extractor(_resnet_args(
        mesh_worklist, tmp_path / 'clean', tmp_path / 'tc',
        pack_across_videos=True, mesh_devices=2))
    clean.extract_packed(mesh_worklist)

    victim = mesh_worklist[1]
    ex = create_extractor(_resnet_args(
        mesh_worklist, tmp_path / 'hurt', tmp_path / 'th',
        pack_across_videos=True, mesh_devices=2))
    orig = ex.packed_windows

    def flaky(task):
        it = orig(task)
        if task.path == victim:
            yield next(it)                    # one window enters a batch
            raise RuntimeError('decoder died mid-video')
        yield from it

    ex.packed_windows = flaky
    ex.extract_packed(mesh_worklist)          # must not raise

    assert not Path(make_path(ex.output_path, victim, 'resnet',
                              '.npy')).exists()
    survivors = [p for p in mesh_worklist if p != victim]
    _assert_outputs_identical(clean.output_path, ex.output_path,
                              survivors)


def test_mesh_uneven_tail_masked_not_stalled(mesh_worklist, tmp_path):
    """27 windows through a global batch of 8 (4 × 2 devices): the final
    batch carries 3 valid rows — the first shard runs partially padded
    and the second ENTIRELY padded, masked at scatter-back. The per-device
    occupancy ledger must show exactly that split (raw valid counts sum
    to the corpus), every ratio staying ≤ 1."""
    ex = create_extractor(_resnet_args(
        mesh_worklist, tmp_path / 'tail', tmp_path / 'tt',
        pack_across_videos=True, mesh_devices=2, profile=True))
    report = {}
    real_reset = ex.tracer.reset
    ex.tracer.reset = lambda: report.update(ex.tracer.report()) \
        or real_reset()
    ex.extract_packed(mesh_worklist)
    ex.tracer.reset = real_reset

    model = report['model']
    assert model['count'] == 4                # 3 full + 1 tail (vs 7 at ndev=1)
    assert model['occ_valid'] == 27
    assert model['occ_capacity'] == 32        # 4 batches × global 8
    occ_dev = model['occ_device']
    assert len(occ_dev) == 2
    valids = sorted(d['occ_valid'] for d in occ_dev.values())
    assert valids == [12, 15]                 # tail: shard0=3, shard1=0
    assert all(d['occ_capacity'] == 16 for d in occ_dev.values())
    assert all(0.0 <= d['occupancy'] <= 1.0 for d in occ_dev.values())
    # every video still completed — the lone tail never stalled
    for p in mesh_worklist:
        assert Path(make_path(ex.output_path, p, 'resnet',
                              '.npy')).exists()


# -- merge_reports device dimension (regression) ------------------------------


def test_merge_reports_device_occupancy_not_double_counted():
    """Regression (the serve metrics bug this PR fixes): merging stage
    tables that carry per-device occupancy must keep the merged aggregate
    at the global-capacity accounting — folding the shard slices into the
    flat counts again would push occupancy past 100%. Device counts merge
    DEVICE-WISE instead."""
    from video_features_tpu.utils.tracing import Tracer, merge_reports

    t1, t2 = Tracer(), Tracer()
    for t, valid in ((t1, 8), (t2, 6)):
        t.add('model', 1.0)
        t.add_occupancy('model', valid, 8)           # aggregate, global cap
        t.add_occupancy('model', min(valid, 4), 4, device='d0')
        t.add_occupancy('model', max(valid - 4, 0), 4, device='d1')

    m = merge_reports([t1.report(), t2.report()])
    model = m['model']
    assert model['occ_valid'] == 14
    assert model['occ_capacity'] == 16
    assert model['occupancy'] == pytest.approx(14 / 16)
    assert model['occupancy'] <= 1.0          # the >100% regression guard
    dev = model['occ_device']
    assert dev['d0']['occ_valid'] == 8 and dev['d0']['occ_capacity'] == 8
    assert dev['d1']['occ_valid'] == 6 and dev['d1']['occ_capacity'] == 8
    assert dev['d0']['occupancy'] == pytest.approx(1.0)
    assert dev['d1']['occupancy'] == pytest.approx(6 / 8)


def test_round_report_rounds_nested_device_records():
    from video_features_tpu.utils.tracing import Tracer, round_report

    t = Tracer()
    t.add('model', 1.0 / 3.0)
    t.add_occupancy('model', 1, 3, device='d0')
    rec = round_report(t.report(), ndigits=3)['model']
    assert rec['occ_device']['d0']['occupancy'] == pytest.approx(0.333)


# -- staged H2D (satellite: overlap device_put with compute) ------------------


def test_transfer_batches_stages_ahead_with_staged_attr():
    """``transfer_batches`` at depth 2 (the default) issues the next
    batch's device_put while the current batch runs; the h2d span's
    ``staged`` attr records the mode so profiles distinguish staged from
    on-demand transfers. depth=1 keeps the old single-buffer overlap."""
    from video_features_tpu.extract.streaming import transfer_batches
    from video_features_tpu.obs.spans import SpanRecorder
    from video_features_tpu.utils.tracing import Tracer

    def run(depth):
        rec = SpanRecorder(capacity=64)
        tracer = Tracer(enabled=True, recorder=rec)
        items = [(np.full((2, 2), i, dtype=np.float32), i)
                 for i in range(3)]
        out = list(transfer_batches(iter(items), put=lambda b: b + 1,
                                    tracer=tracer, depth=depth))
        assert [m for _, _, m in out] == [0, 1, 2]
        assert all((d == np.full((2, 2), m + 1)).all()
                   for d, _, m in out)
        h2d = [e for e in rec.snapshot()
               if e['ph'] == 'X' and e['name'] == 'h2d']
        assert len(h2d) == 3
        return h2d

    assert all(e['args']['staged'] for e in run(2))
    assert not any(e['args']['staged'] for e in run(1))


# -- serve warm path ----------------------------------------------------------


def test_serve_mesh_parity_and_device_metrics(mesh_worklist, tmp_path):
    """A mesh-sharded server (mesh_devices=2 base override) answers warm
    requests byte-identically to the single-device server, the warm pool
    reports which chips each entry is resident on, and the Prometheus
    exposition grows device-labelled series (vft_device_resident_entries,
    vft_stage_occupancy{device=...})."""
    from video_features_tpu.serve.client import ServeClient
    from video_features_tpu.serve.server import ExtractionServer

    def base(ndev):
        return {
            'device': 'cpu', 'model_name': 'resnet18', 'batch_size': 4,
            'allow_random_weights': True, 'on_extraction': 'save_numpy',
            'tmp_path': str(tmp_path / f'stmp{ndev}'),
            'mesh_devices': ndev,
        }

    roots = {}
    for ndev in (1, 2):
        server = ExtractionServer(base_overrides=base(ndev),
                                  queue_depth=32, pool_size=2).start()
        try:
            client = ServeClient(port=server.port)
            # two passes: the second rides the WARM pool entry
            for tag in ('cold', 'warm'):
                out_root = str(tmp_path / f'serve{ndev}_{tag}')
                rid = client.submit('resnet', mesh_worklist,
                                    overrides={'output_path': out_root})
                st = client.wait(rid, timeout_s=300)
                assert st['state'] == 'done', st
            m = client.metrics()
            assert m['warm_pool']['hit_rate'] > 0       # warm pass hit
            placements = m['warm_pool']['placements']
            assert placements, 'no placement recorded for the warm entry'
            (chips,) = placements.values()
            assert len(chips) == ndev
            residents = m['warm_pool']['device_residents']
            assert sum(residents.values()) == ndev
            prom = client.metrics_prom()
            assert 'vft_device_resident_entries{device=' in prom
            if ndev == 2:
                assert 'vft_stage_occupancy{device=' in prom \
                    or 'device=' in prom.split('vft_stage_occupancy', 1)[-1]
        finally:
            server.drain(wait=True, grace_s=60)
        roots[ndev] = os.path.join(out_root, 'resnet', 'resnet18')
    _assert_outputs_identical(roots[1], roots[2], mesh_worklist)


def test_serve_pool_key_includes_mesh_devices():
    """mesh_devices changes the compiled program's sharding, so it must
    stay IN the serve pool key — a 1-chip and a 2-chip request never
    share a warm entry (unlike the cache fingerprint, which excludes
    it: outputs are byte-identical by contract). The auto-detect
    spelling resolves BEFORE keying: mesh_devices=0 and the equivalent
    explicit width share one entry instead of double-building the same
    sharded program."""
    import jax

    from video_features_tpu.cache.key import config_fingerprint
    from video_features_tpu.serve.server import (
        pool_key, resolve_mesh_devices,
    )

    base = {'feature_type': 'resnet', 'model_name': 'resnet18',
            'device': 'cpu', 'batch_size': 4}
    k1 = pool_key(dict(base, mesh_devices=1))
    k2 = pool_key(dict(base, mesh_devices=2))
    assert k1 != k2

    f1 = config_fingerprint(dict(base, mesh_devices=1))
    f2 = config_fingerprint(dict(base, mesh_devices=2))
    assert f1 == f2

    ndev = len(jax.devices())                 # conftest forces 8
    auto = pool_key(resolve_mesh_devices(dict(base, mesh_devices=0)))
    explicit = pool_key(dict(base, mesh_devices=ndev))
    assert auto == explicit


def test_place_on_moves_declared_device_buffers(mesh_worklist, tmp_path):
    """``place_on`` migrates every buffer a family declares in
    ``_device_buffer_attrs`` along with the params (vggish's PCA
    matrices) — a placed entry must never feed a jit call operands
    committed to two different chips."""
    import jax

    ex = create_extractor(_resnet_args(
        mesh_worklist, tmp_path / 'place', tmp_path / 'tp'))
    d0, d1 = jax.devices()[:2]
    ex._aux = jax.device_put(np.ones(4, np.float32), d0)
    ex._device_buffer_attrs = ('_aux', '_absent')   # absent: skipped
    ex.place_on([d1])
    assert ex._device is d1
    assert next(iter(ex._aux.devices())) is d1
    leaf = jax.tree_util.tree_leaves(ex.params)[0]
    assert next(iter(leaf.devices())) is d1
    # vggish declares its PCA matrices
    from video_features_tpu.extract.vggish import ExtractVGGish
    assert ExtractVGGish._device_buffer_attrs == ('_pca_eig', '_pca_means')


def test_put_input_names_unshardable_batches(mesh_worklist, tmp_path):
    """An indivisible global batch through a sharded ``put_input`` must
    raise the named require_shardable error, not an opaque XLA
    sharding/shape failure."""
    ex = create_extractor(_resnet_args(
        mesh_worklist, tmp_path / 'shard', tmp_path / 'tsh',
        pack_across_videos=True, mesh_devices=2))
    assert ex._ensure_packed_mesh() == 2
    ok = ex.put_input(np.zeros((8, 4, 4, 3), np.float32))
    assert ok.shape[0] == 8
    with pytest.raises(ValueError, match='cannot shard over 2'):
        ex.put_input(np.zeros((7, 4, 4, 3), np.float32))


def test_place_extractor_releases_chips_on_placement_failure():
    """A place_on failure after assign() counted the chips must give
    them back — a leaked count would skew every future least-loaded
    decision for the server's lifetime."""
    from video_features_tpu.serve.pool import DevicePlacer
    from video_features_tpu.serve.server import ExtractionServer

    server = ExtractionServer.__new__(ExtractionServer)   # no socket
    server._placer = DevicePlacer()

    class Boom:
        device = 'cpu'
        mesh_devices = 1

        def place_on(self, devices):
            raise RuntimeError('device_put OOM')

    assert server._place_extractor(Boom()) is None        # best-effort
    # nothing leaked — the count went back to 0 (zero persists so the
    # vft_device_resident_entries gauge can follow it down)
    assert set(server._placer.snapshot().values()) <= {0}


def test_device_placer_spreads_families_and_releases():
    """Least-loaded placement: two single-device entries land on
    DIFFERENT chips, a mesh entry takes N chips, release returns them,
    and ties break deterministically by device id."""
    import jax

    from video_features_tpu.serve.pool import DevicePlacer

    devices = jax.devices()
    assert len(devices) >= 4                  # conftest forces 8
    placer = DevicePlacer()
    a = placer.assign(devices, 1)
    b = placer.assign(devices, 1)
    assert a[0].id != b[0].id                 # different silicon
    mesh_entry = placer.assign(devices, 2)
    assert len(mesh_entry) == 2
    assert {d.id for d in mesh_entry}.isdisjoint({a[0].id, b[0].id})
    snap = placer.snapshot()
    assert sum(snap.values()) == 4
    placer.release(mesh_entry)
    placer.release(a)
    placer.release(b)
    # fully drained: every count back to 0, labels KEPT so the metrics
    # mirror can drive each chip's residency gauge back down
    drained = placer.snapshot()
    assert set(drained) == set(snap)
    assert set(drained.values()) == {0}
    # ask for more than exists: clamped, never raises (build-time
    # validation already rejected genuine over-asks)
    assert len(placer.assign(devices, len(devices) + 5)) == len(devices)


def test_device_placer_ranks_by_real_bytes():
    """Byte-aware placement (the bf16 fast lane's accounting): two
    half-size entries should stack on one chip before a second full-size
    copy does, the bytes gauges read REAL residency, and release nets
    the ledger back to zero."""
    import jax

    from video_features_tpu.serve.pool import DevicePlacer

    devices = jax.devices()[:2]
    placer = DevicePlacer()
    big = placer.assign(devices, 1, nbytes=1000)     # fp32-sized entry
    small1 = placer.assign(devices, 1, nbytes=500)   # bf16-sized
    small2 = placer.assign(devices, 1, nbytes=400)
    assert big[0].id != small1[0].id
    # 500 < 1000: the second small entry stacks on the small chip —
    # byte ranking, not entry-count ranking (which would tie 1 vs 1 and
    # fall back to device id, landing on the BIG chip)
    assert small2[0].id == small1[0].id
    by_bytes = placer.snapshot_bytes()
    assert by_bytes[f'd{big[0].id}'] == 1000
    assert by_bytes[f'd{small1[0].id}'] == 900
    # zero-byte callers (tests, unknown sizes) keep the historical
    # entry-count ordering as the secondary key
    placer.release(small2, nbytes=400)
    placer.release(small1, nbytes=500)
    placer.release(big, nbytes=1000)
    assert set(placer.snapshot_bytes().values()) == {0}
    assert set(placer.snapshot().values()) == {0}
