"""I3D net: numerical parity vs the reference torch net (TF-SAME padding)."""
import numpy as np
import pytest
import torch

from video_features_tpu.models import i3d as i3d_model
from video_features_tpu.transplant.torch2jax import transplant

pytestmark = pytest.mark.slow  # parity/e2e/sharding: full lane only



def _torch_i3d(reference_repo, modality):
    from models.i3d.i3d_src.i3d_net import I3D
    torch.manual_seed(0)
    model = I3D(num_classes=400, modality=modality)
    model.eval()
    return model


@pytest.mark.parametrize('modality,channels', [('rgb', 3), ('flow', 2)])
def test_parity_features(reference_repo, modality, channels):
    model = _torch_i3d(reference_repo, modality)
    params = transplant(model.state_dict())
    rng = np.random.RandomState(0)
    # T=16 (fork default stack), 224 spatial is required by the fixed (2,7,7) avg-pool head and
    # still exercises every asymmetric-padding branch (stride-2 convs/pools)
    x = (rng.rand(1, 16, 224, 224, channels).astype(np.float32) * 2) - 1

    with torch.no_grad():
        ref = model(torch.from_numpy(x).permute(0, 4, 1, 2, 3),
                    features=True).numpy()
    import jax
    with jax.default_matmul_precision('highest'):
        ours = np.asarray(i3d_model.forward(params, x, features=True))

    assert ours.shape == ref.shape == (1, 1024)
    l2 = np.linalg.norm(ours - ref) / max(np.linalg.norm(ref), 1e-12)
    assert l2 < 1e-3, f'relative L2 {l2}'
    np.testing.assert_allclose(ours, ref, atol=5e-4)


def test_parity_logits(reference_repo):
    model = _torch_i3d(reference_repo, 'rgb')
    params = transplant(model.state_dict())
    rng = np.random.RandomState(1)
    x = (rng.rand(1, 16, 224, 224, 3).astype(np.float32) * 2) - 1
    with torch.no_grad():
        ref_sm, ref_logits = model(torch.from_numpy(x).permute(0, 4, 1, 2, 3),
                                   features=False)
    import jax
    with jax.default_matmul_precision('highest'):
        sm, logits = i3d_model.forward(params, x, features=False)
    np.testing.assert_allclose(np.asarray(logits), ref_logits.numpy(), atol=5e-4)
    np.testing.assert_allclose(np.asarray(sm), ref_sm.numpy(), atol=1e-5)


def test_tf_same_pads_rule():
    # k=7 s=2 -> pad 5 -> (2,3); k=3 s=1 -> (1,1); k=2 s=2 -> (0,0)
    assert i3d_model.tf_same_pads((7, 7, 7), (2, 2, 2)) == [(2, 3)] * 3
    assert i3d_model.tf_same_pads((3, 3, 3), (1, 1, 1)) == [(1, 1)] * 3
    assert i3d_model.tf_same_pads((2, 2, 2), (2, 2, 2)) == [(0, 0)] * 3
    assert i3d_model.tf_same_pads((1, 3, 3), (1, 2, 2)) == [(0, 0), (0, 1), (0, 1)]
