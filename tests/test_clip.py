"""CLIP: numerical parity vs the reference torch model + tokenizer + E2E."""
import numpy as np
import pytest
import torch

from video_features_tpu.config import load_config
from video_features_tpu.models import clip as clip_model
from video_features_tpu.registry import create_extractor
from video_features_tpu.transplant.torch2jax import transplant

pytestmark = pytest.mark.slow  # parity/e2e/sharding: full lane only



def _load_reference_module(reference_repo, relpath, name):
    """Import a reference source file directly, bypassing package __init__s
    (models/clip/__init__.py pulls in omegaconf, absent here)."""
    import importlib.util
    spec = importlib.util.spec_from_file_location(name, reference_repo / relpath)
    module = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(module)
    return module


@pytest.fixture(scope='module')
def torch_clip(reference_repo):
    """A small ViT-B/32-shaped torch CLIP built from the reference's vendored
    model code (reference models/clip/clip_src/model.py:399-436) with a tiny
    text tower so CPU parity tests stay fast."""
    CLIP = _load_reference_module(
        reference_repo, 'models/clip/clip_src/model.py', 'ref_clip_model').CLIP
    torch.manual_seed(0)
    model = CLIP(embed_dim=512, image_resolution=224, vision_layers=12,
                 vision_width=768, vision_patch_size=32, context_length=77,
                 vocab_size=512, transformer_width=512, transformer_heads=8,
                 transformer_layers=2)
    model.eval()
    return model


def test_image_parity_vs_reference_torch(torch_clip):
    params = transplant(torch_clip.state_dict(),
                        no_transpose=set(clip_model.NO_TRANSPOSE))
    rng = np.random.RandomState(0)
    x = rng.rand(2, 224, 224, 3).astype(np.float32)

    with torch.no_grad():
        ref = torch_clip.encode_image(
            torch.from_numpy(x).permute(0, 3, 1, 2)).numpy()
    import jax
    with jax.default_matmul_precision('highest'):
        ours = np.asarray(clip_model.encode_image(params, x, 'ViT-B/32'))

    assert ours.shape == ref.shape == (2, 512)
    l2 = np.linalg.norm(ours - ref) / max(np.linalg.norm(ref), 1e-12)
    assert l2 < 1e-3, f'relative L2 {l2}'


def test_text_parity_vs_reference_torch(torch_clip):
    params = transplant(torch_clip.state_dict(),
                        no_transpose=set(clip_model.NO_TRANSPOSE))
    rng = np.random.RandomState(1)
    tokens = np.zeros((3, 77), np.int64)
    for i in range(3):
        n = rng.randint(3, 20)
        tokens[i, :n] = rng.randint(1, 500, size=n)
        tokens[i, n - 1] = 511  # highest id = argmax pooling token (EOT)

    with torch.no_grad():
        ref = torch_clip.encode_text(torch.from_numpy(tokens)).numpy()
    import jax
    with jax.default_matmul_precision('highest'):
        ours = np.asarray(clip_model.encode_text(params, tokens, 'ViT-B/32'))

    assert ours.shape == ref.shape == (3, 512)
    l2 = np.linalg.norm(ours - ref) / max(np.linalg.norm(ref), 1e-12)
    assert l2 < 1e-3, f'relative L2 {l2}'


def test_tokenizer_parity(reference_repo):
    """Our BPE must produce the same ids as the reference's vendored
    tokenizer for representative zero-shot prompts."""
    pytest.importorskip('regex')

    # The reference tokenizer imports ftfy (absent here); both tokenizers
    # then see identical un-fixed text, so parity still holds with a stub.
    import sys
    import types
    if 'ftfy' not in sys.modules:
        stub = types.ModuleType('ftfy')
        stub.fix_text = lambda s: s
        sys.modules['ftfy'] = stub

    RefTok = _load_reference_module(
        reference_repo, 'models/clip/clip_src/simple_tokenizer.py',
        'ref_clip_tokenizer').SimpleTokenizer

    from video_features_tpu.utils.clip_tokenizer import (
        SimpleTokenizer, find_bpe_vocab, tokenize,
    )
    if find_bpe_vocab() is None:
        pytest.skip('BPE vocab unavailable')

    ref = RefTok()
    ours = SimpleTokenizer()
    prompts = [
        'a photo of riding a bike',
        'Hello, World! 123',
        "it's the tokenizer's edge-cases: don't fail",
        'playing    ukulele',
    ]
    for p in prompts:
        assert ours.encode(p) == ref.encode(p), p

    mat = tokenize(prompts, tokenizer=ours)
    assert mat.shape == (4, 77)
    sot, eot = ours.encoder['<|startoftext|>'], ours.encoder['<|endoftext|>']
    assert (mat[:, 0] == sot).all()
    assert all(eot in row for row in mat)


def test_infer_model_name(torch_clip):
    assert clip_model.infer_model_name(torch_clip.state_dict()) == 'ViT-B/32'


def test_e2e_extraction(short_video, tmp_path):
    args = load_config('clip', overrides={
        'video_paths': short_video,
        'device': 'cpu',
        'batch_size': 16,
        'extraction_fps': None,
        'output_path': str(tmp_path / 'out'),
        'tmp_path': str(tmp_path / 'tmp'),
    })
    ex = create_extractor(args)
    out = ex.extract(short_video)
    assert out['clip'].shape == (48, 512)
    assert np.isfinite(out['clip']).all()
    assert out['timestamps_ms'].shape == (48,)


def test_rn50_image_parity_vs_reference_torch(reference_repo):
    """ModifiedResNet visual tower parity (reference model.py:94-241)."""
    CLIP = _load_reference_module(
        reference_repo, 'models/clip/clip_src/model.py', 'ref_clip_model').CLIP
    torch.manual_seed(1)
    model = CLIP(embed_dim=1024, image_resolution=224,
                 vision_layers=(3, 4, 6, 3), vision_width=64,
                 vision_patch_size=None, context_length=77, vocab_size=128,
                 transformer_width=512, transformer_heads=8,
                 transformer_layers=1)
    model.eval()

    params = transplant(model.state_dict(),
                        no_transpose=set(clip_model.NO_TRANSPOSE))
    rng = np.random.RandomState(0)
    x = rng.rand(2, 224, 224, 3).astype(np.float32)

    with torch.no_grad():
        ref = model.encode_image(
            torch.from_numpy(x).permute(0, 3, 1, 2)).numpy()
    import jax
    with jax.default_matmul_precision('highest'):
        ours = np.asarray(clip_model.encode_image(params, x, 'RN50'))

    assert ours.shape == ref.shape == (2, 1024)
    l2 = np.linalg.norm(ours - ref) / max(np.linalg.norm(ref), 1e-12)
    assert l2 < 1e-3, f'relative L2 {l2}'


def test_npz_checkpoint_with_custom_arch(tmp_path, short_video):
    """model_name=custom + a pre-transplanted .npz: arch inferred from the
    pytree, no torch needed at load time (docs/checkpoints.md contract)."""
    from video_features_tpu.config import load_config
    from video_features_tpu.registry import create_extractor
    from video_features_tpu.transplant.torch2jax import (
        save_transplanted, transplant,
    )

    params = transplant(clip_model.init_state_dict(model_name='ViT-B/32'),
                        no_transpose=set(clip_model.NO_TRANSPOSE),
                        dtype=np.float32)
    ckpt = str(tmp_path / 'clip.npz')
    save_transplanted(params, ckpt)

    args = load_config('clip', overrides={
        'model_name': 'custom', 'checkpoint_path': ckpt,
        'device': 'cpu', 'batch_size': 16, 'video_paths': short_video,
        'output_path': str(tmp_path / 'out'), 'tmp_path': str(tmp_path / 'tmp'),
    })
    ex = create_extractor(args)
    assert ex.arch == 'ViT-B/32'
    out = ex.extract(short_video)
    assert out['clip'].shape[1] == 512


@pytest.mark.parametrize('name', ['ViT-L/14', 'ViT-L/14@336px', 'RN50x64'])
def test_infer_model_name_large_variants(name):
    """Shape-only state_dicts for the large OpenAI checkpoints (reference
    clip_src/clip.py:33-41 _MODELS): the two ViT-L/14 variants differ only
    in input resolution, disambiguated by the positional-embedding grid."""
    cfg = clip_model.VISUAL_CFGS[name]
    sd = {}
    if cfg['kind'] == 'vit':
        grid = cfg['input_resolution'] // cfg['patch']
        sd['visual.proj'] = np.zeros((cfg['width'], cfg['embed_dim']))
        sd['visual.conv1.weight'] = np.zeros(
            (cfg['width'], 3, cfg['patch'], cfg['patch']))
        sd['visual.positional_embedding'] = np.zeros(
            (grid * grid + 1, cfg['width']))
        for i in range(cfg['layers']):
            sd[f'visual.transformer.resblocks.{i}.ln_1.weight'] = (
                np.zeros(cfg['width']))
    else:
        sd['visual.layer1.0.conv1.weight'] = np.zeros(
            (cfg['width'], 1, 1, 1))
        for li, nb in enumerate(cfg['layers'], start=1):
            for bi in range(nb):
                sd[f'visual.layer{li}.{bi}.bn1.weight'] = np.zeros(1)
    assert clip_model.infer_model_name(sd) == name


def test_infer_model_name_from_params_rn50(reference_repo):
    CLIP = _load_reference_module(
        reference_repo, 'models/clip/clip_src/model.py', 'ref_clip_model').CLIP
    torch.manual_seed(0)
    model = CLIP(embed_dim=1024, image_resolution=224,
                 vision_layers=(3, 4, 6, 3), vision_width=64,
                 vision_patch_size=None, context_length=77, vocab_size=128,
                 transformer_width=512, transformer_heads=8,
                 transformer_layers=1)
    params = transplant(model.state_dict(),
                        no_transpose=set(clip_model.NO_TRANSPOSE))
    assert clip_model.infer_model_name_from_params(params) == 'RN50'


@pytest.mark.slow
def test_zero_shot_e2e_golden(torch_clip, video_33, tmp_path):
    """Whole zero-shot pipeline golden: decode → visual tower → REAL-prompt
    tokenization → text tower → normalized cosine logits with learned
    temperature → per-frame softmax, ours vs the reference's own pieces
    (extract_clip.py:86-105 maybe_show_pred math on run_reference_clip
    features). Real 'a photo of X' prompts are tokenized with the real BPE,
    then mapped into the reduced test vocab IDENTICALLY on both sides (the
    argmax-pooled EOT stays the highest id, model.py:355-368 semantics)."""
    import jax
    import jax.numpy as jnp

    from tests.reference_pipeline import run_reference_clip
    from video_features_tpu.config import load_config
    from video_features_tpu.registry import create_extractor
    from video_features_tpu.utils.clip_tokenizer import tokenize

    prompts = [f'a photo of {c}' for c in
               ('archery', 'bowling', 'dancing', 'juggling balls',
                'playing guitar', 'surfing water')]
    tokens = np.asarray(tokenize(prompts))
    # reduced-vocab mapping: content ids into [1, 510), EOT (argmax pool
    # position = the sequence's max id) pinned to vocab-1, pads stay 0
    content = tokens > 0
    eot = tokens == tokens.max(axis=1, keepdims=True)
    mapped = np.where(content, tokens % 509 + 1, 0)
    mapped = np.where(eot, 511, mapped).astype(np.int64)

    # reference side: frame features + double-precision zero-shot math
    ref_vis = run_reference_clip(video_33, torch_clip)
    with torch.no_grad():
        ref_txt = torch_clip.encode_text(torch.from_numpy(mapped)).double()
        v = torch.from_numpy(ref_vis).double()
        v = v / v.norm(dim=1, keepdim=True)
        t = ref_txt / ref_txt.norm(dim=1, keepdim=True)
        ref_logits = (torch_clip.logit_scale.exp().double() * v @ t.T)
        ref_probs = ref_logits.softmax(dim=-1).numpy()

    # our side: the real extractor end-to-end + the extractor's zero-shot ops
    ckpt = tmp_path / 'clip_seeded.pt'
    torch.save(torch_clip.state_dict(), str(ckpt))
    args = load_config('clip', overrides={
        'video_paths': video_33, 'device': 'cpu', 'precision': 'highest',
        'decode_backend': 'cv2', 'batch_size': 16, 'model_name': 'custom',
        'checkpoint_path': str(ckpt),
        'output_path': str(tmp_path / 'out'), 'tmp_path': str(tmp_path / 't'),
    })
    ex = create_extractor(args)
    ours_vis = ex.extract(video_33)['clip']
    with jax.default_matmul_precision('highest'):
        ours_txt = np.asarray(clip_model.encode_text(
            transplant(torch_clip.state_dict(),
                       no_transpose=set(clip_model.NO_TRANSPOSE)),
            mapped, 'ViT-B/32'))
        logits = np.asarray(clip_model.zero_shot_logits(
            ex.params, jnp.asarray(ours_vis), jnp.asarray(ours_txt)))
    ours_probs = np.asarray(jax.nn.softmax(jnp.asarray(logits), axis=-1))

    assert ours_probs.shape == ref_probs.shape == (33, len(prompts))
    rel = np.linalg.norm(ours_probs - ref_probs) / np.linalg.norm(ref_probs)
    print(f'[golden e2e] clip zero-shot prob table rel L2: {rel}')
    assert rel < 1e-3, rel
