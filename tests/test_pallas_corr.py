"""Pallas correlation-lookup kernel vs the XLA gather path.

The kernel must reproduce the reference lookup semantics exactly
(reference models/raft/raft_src/corr.py:29-50 + utils/utils.py:58-72:
zeros padding, align_corners bilinear, dy-major window ordering), which the
XLA path in models/raft.py already verifies against torch. CPU runs use
interpret mode — the same kernel body the TPU compiles.
"""
import numpy as np
import pytest

jax = pytest.importorskip('jax')
import jax.numpy as jnp  # noqa: E402

from video_features_tpu.models import raft  # noqa: E402
from video_features_tpu.ops import pallas_corr  # noqa: E402

pytestmark = pytest.mark.slow  # parity/e2e/sharding: full lane only



def _random_pyramid(rng, n, h, w, levels=4):
    pyr = []
    for i in range(levels):
        hi, wi = max(h >> i, 1), max(w >> i, 1)
        pyr.append(jnp.asarray(rng.randn(n, hi, wi, 1).astype(np.float32)))
    return pyr


@pytest.mark.parametrize('h,w', [(8, 12), (13, 9)])
def test_lookup_matches_xla(h, w):
    rng = np.random.RandomState(0)
    b = 2
    n = b * h * w
    pyr = _random_pyramid(rng, n, h, w)
    # centroids spanning in-range, fractional, and far out-of-range coords
    coords = rng.uniform(-9, max(h, w) + 9, size=(b, h, w, 2))
    coords = jnp.asarray(coords.astype(np.float32))

    ref = raft.lookup_corr(pyr, coords)
    got = pallas_corr.lookup_corr(pallas_corr.prep_pyramid(pyr, 4), coords,
                                  interpret=True)
    assert got.shape == ref.shape
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_lookup_integer_coords_exact():
    """Integer coords hit map values exactly (weights 0, no blending)."""
    rng = np.random.RandomState(1)
    h = w = 8
    n = h * w
    pyr = _random_pyramid(rng, n, h, w, levels=1)
    yy, xx = np.meshgrid(np.arange(h), np.arange(w), indexing='ij')
    coords = jnp.asarray(
        np.stack([xx, yy], -1)[None].astype(np.float32))

    got = np.asarray(pallas_corr.lookup_corr(
        pallas_corr.prep_pyramid(pyr, 4), coords, interpret=True))
    corr = np.asarray(pyr[0])[..., 0]
    # window element (i=r, j=r) — zero offset — is flat index r·9 + r
    center = got[0].reshape(h, w, 81)[..., 4 * 9 + 4]
    want = corr[np.arange(n).reshape(h, w), yy, xx]
    np.testing.assert_allclose(center, want, rtol=1e-6, atol=1e-6)


def test_forward_with_all_lookup_impls(monkeypatch):
    """Full RAFT forward: gather oracle == dense == pallas end-to-end."""
    sd = raft.init_state_dict(seed=0)
    from video_features_tpu.transplant.torch2jax import transplant
    params = transplant(sd)
    rng = np.random.RandomState(2)
    # ≥64px so the coarsest of the 4 pyramid levels is still non-empty
    img1 = jnp.asarray(rng.randint(0, 255, (1, 64, 80, 3)).astype(np.float32))
    img2 = jnp.asarray(rng.randint(0, 255, (1, 64, 80, 3)).astype(np.float32))

    monkeypatch.delenv('VFT_RAFT_PALLAS', raising=False)
    monkeypatch.setenv('VFT_RAFT_LOOKUP', 'gather')
    ref = np.asarray(raft.forward(params, img1, img2, iters=3))
    for impl in ('dense', 'pallas'):
        monkeypatch.setenv('VFT_RAFT_LOOKUP', impl)
        got = np.asarray(raft.forward(params, img1, img2, iters=3))
        np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-4,
                                   err_msg=impl)


def test_lanes_lookup_matches_gather_oracle():
    """Lane-packed mask-reduce kernel (interpret mode): identical to the
    gather oracle, incl. zeros padding at out-of-map coords."""
    from video_features_tpu.ops import pallas_corr

    rng = np.random.RandomState(1)
    B, H8, W8, D = 4, 12, 9, 32
    f1 = jnp.asarray(rng.randn(B, H8, W8, D).astype(np.float32))
    f2 = jnp.asarray(rng.randn(B, H8, W8, D).astype(np.float32))
    py = raft.build_corr_pyramid(f1, f2)
    coords = jnp.asarray(
        (rng.rand(B, H8, W8, 2) * [W8 * 1.6, H8 * 1.6]
         - [W8 * 0.3, H8 * 0.3]).astype(np.float32))
    ref = np.asarray(raft.lookup_corr(py, coords))
    prepped = pallas_corr.prep_pyramid_lanes(py)
    got = np.asarray(pallas_corr.lookup_corr_lanes(prepped, coords,
                                                   interpret=True))
    np.testing.assert_allclose(got, ref, atol=1e-4)


def test_forward_with_lanes_lookup(monkeypatch):
    """Full RAFT forward with the lanes lookup == the gather oracle."""
    sd = raft.init_state_dict(seed=0)
    from video_features_tpu.transplant.torch2jax import transplant
    params = transplant(sd)
    rng = np.random.RandomState(2)
    img1 = jnp.asarray(rng.randint(0, 255, (1, 64, 80, 3)).astype(np.float32))
    img2 = jnp.asarray(rng.randint(0, 255, (1, 64, 80, 3)).astype(np.float32))

    monkeypatch.delenv('VFT_RAFT_PALLAS', raising=False)
    monkeypatch.setenv('VFT_RAFT_LOOKUP', 'gather')
    ref = np.asarray(raft.forward(params, img1, img2, iters=3))
    monkeypatch.setenv('VFT_RAFT_LOOKUP', 'lanes')
    got = np.asarray(raft.forward(params, img1, img2, iters=3))
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-4)


def test_auto_lookup_dispatch(monkeypatch):
    """Default dispatch: lanes on TPU within the VMEM budget, dense
    otherwise (non-TPU backends and oversized level-0 blocks)."""
    monkeypatch.delenv('VFT_RAFT_PALLAS', raising=False)
    monkeypatch.delenv('VFT_RAFT_LOOKUP', raising=False)
    assert raft._lookup_impl() == 'auto'

    assert raft._resolve_auto_lookup(28, 28, 'tpu') == 'lanes'   # fused i3d
    assert raft._resolve_auto_lookup(28, 28, 'cpu') == 'dense'   # off-TPU
    assert raft._resolve_auto_lookup(135, 240, 'tpu') == 'dense'  # 1080p L0
    monkeypatch.setenv('VFT_RAFT_LANES_VMEM_MB', '64')
    assert raft._resolve_auto_lookup(135, 240, 'tpu') == 'lanes'
    monkeypatch.delenv('VFT_RAFT_LANES_VMEM_MB')


def _load_validate_lanes():
    import importlib.util
    from pathlib import Path

    spec = importlib.util.spec_from_file_location(
        'validate_lanes',
        Path(__file__).resolve().parents[1] / 'tools' / 'validate_lanes.py')
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_lanes_full_depth_interpret():
    """The production lanes kernel at FULL 20-iteration depth (reduced
    geometry, interpret mode): a depth-dependent kernel regression —
    accumulated window drift steering later lookups off course — fails
    automation here, not a human remembering tools/validate_lanes.py."""
    vl = _load_validate_lanes()
    # smallest geometry whose 4-level pyramid keeps every level nonzero
    # (H/8 must be ≥ 8 so level 3 is ≥ 1 pixel)
    rels = vl.measure_drift(h=64, w=88, impls=('dense', 'lanes'),
                            iters=20, platform='cpu')
    assert rels['lanes'] < 1e-3, rels


@pytest.mark.tpu
def test_lanes_full_depth_tpu():
    """The same full-depth validation on real TPU hardware at CLI geometry
    (the compiled Mosaic kernel, not interpret mode):
    `VFT_TEST_PLATFORM=native pytest -m tpu`."""
    if jax.devices()[0].platform != 'tpu':
        pytest.skip('no TPU attached')
    vl = _load_validate_lanes()
    rels = vl.measure_drift(impls=('dense', 'lanes', 'gather'))
    assert rels['lanes'] < 1e-3, rels
    assert rels['gather'] < 1e-3, rels


def test_prep_fused_matches_two_step():
    """prep_pyramid_lanes_fused ≡ build_corr_pyramid → prep_pyramid_lanes
    at every level (the round-5 transpose-free prep — 106 → 75 ms on v5e
    at batch-16 CLI geometry). Tolerance is fp reassociation noise only:
    the einsum contracts in a different order."""
    from video_features_tpu.models.raft import build_corr_pyramid
    from video_features_tpu.ops.pallas_corr import (
        prep_pyramid_lanes, prep_pyramid_lanes_fused,
    )

    rng = np.random.RandomState(0)
    B, H, W, D = 3, 8, 11, 16     # odd W exercises the valid-pool crop
    f1 = jnp.asarray(0.1 * rng.randn(B, H, W, D).astype(np.float32))
    f2 = jnp.asarray(0.1 * rng.randn(B, H, W, D).astype(np.float32))
    two_step = prep_pyramid_lanes(build_corr_pyramid(f1, f2))
    fused = prep_pyramid_lanes_fused(f1, f2)
    assert len(two_step) == len(fused)
    for i, (a, b) in enumerate(zip(two_step, fused)):
        assert a.shape == b.shape, (i, a.shape, b.shape)
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=0, atol=1e-6, err_msg=f'level {i}')
