"""Decode farm (farm/): N decoder worker PROCESSES feeding the packed
scheduler over bounded shared-memory rings must be externally
indistinguishable from in-process decode — byte-identical outputs across
the CLI, packed, and serve paths at any worker count — while surviving
worker crashes with the per-video fault contract (one casualty, siblings
complete, the worker respawns).

The recipe classes used for fault injection / transport tests live at
module level: spawn'd workers unpickle them by reference, so they must
be importable (``tests.test_farm``) from the child process.
"""
import os
import signal
import time
from pathlib import Path

import numpy as np
import pytest

from video_features_tpu.config import load_config
from video_features_tpu.registry import create_extractor
from video_features_tpu.utils.output import make_path

from tools.make_sample_video import write_noise_clip as _write_clip  # noqa: E402


# -- shared-memory ring: pure units (no processes, no jax) -------------------


def _make_ring(capacity):
    from video_features_tpu.farm.ring import RingProducer
    buf = memoryview(bytearray(capacity))
    return RingProducer(buf, capacity), buf


def test_ring_roundtrip_with_wraps():
    """Windows written through the producer come back byte-exact through
    ``read_window`` across many arena wraps, including the skipped-tail
    case (a region never straddles the wrap)."""
    from video_features_tpu.farm.ring import read_window
    ring, buf = _make_ring(1 << 12)           # 4 KiB arena
    rng = np.random.RandomState(0)
    inflight = []                             # (offset, adv, expected)
    freed = []

    def wait_free():
        assert inflight, 'alloc blocked with nothing to free: deadlock'
        off, adv, expect = inflight.pop(0)
        got = read_window(buf, off, expect.shape, expect.dtype.str)
        np.testing.assert_array_equal(got, expect)
        ring.freed(adv)
        freed.append(adv)

    for i in range(64):
        # odd sizes force misaligned offsets and frequent wraps
        arr = rng.randint(0, 255, size=(rng.randint(200, 600),),
                          ).astype(np.uint8)
        region = ring.alloc(arr.nbytes, wait_free)
        assert region is not None
        off, adv = region
        assert adv >= arr.nbytes              # adv folds any skipped tail
        assert off + arr.nbytes <= ring.capacity   # contiguous region
        ring.write(off, arr)
        inflight.append((off, adv, arr))
    while inflight:
        wait_free()
    # both sides agree on total advance: frees reported verbatim
    assert ring.write_pos == ring.read_pos == sum(freed)


def test_ring_oversized_window_takes_queue_fallback():
    """A window over half the arena can never be satisfied by freeing
    (its wrap skip could exceed capacity) — alloc must return None (the
    worker then ships bytes through the message queue) instead of
    deadlocking in wait_free."""
    ring, _ = _make_ring(1 << 10)
    assert ring.alloc((1 << 9) + 1) is None
    # exactly half still fits
    assert ring.alloc(1 << 9) is not None


def test_ring_backpressure_blocks_until_freed():
    """When the arena is full the producer spins in ``wait_free`` — a
    slow consumer stalls decode instead of growing memory."""
    from video_features_tpu.farm.ring import RingFull
    ring, _ = _make_ring(1 << 10)
    a = ring.alloc(400)
    b = ring.alloc(400)
    assert a is not None and b is not None
    # no free callback → RingFull, proving alloc would have to wait
    with pytest.raises(RingFull):
        ring.alloc(400)
    calls = []

    def wait_free():
        ring.freed(a[1])                     # consumer frees the oldest
        calls.append(1)

    c = ring.alloc(400, wait_free)
    assert c is not None and calls           # it blocked, then proceeded


# -- picklable transport/fault recipes (unpickled inside spawn'd workers) ----


class SyntheticRecipe:
    """Deterministic windows derived from the path — no video decode, so
    transport tests isolate the SHM ring + queue machinery."""

    def __init__(self, n_windows=24, nbytes=300_000):
        self.n_windows = n_windows
        self.nbytes = nbytes

    def open(self, path):
        # crc32, not hash(): PYTHONHASHSEED differs across spawned
        # processes, and the parent recomputes these seeds to verify
        import zlib
        seed = zlib.crc32(os.path.basename(path).encode()) % (2 ** 31)

        def windows():
            for i in range(self.n_windows):
                rng = np.random.RandomState(seed + i)
                yield rng.randint(0, 255, size=(self.nbytes,)
                                  ).astype(np.uint8), i

        return {'seed': seed}, windows()


def expected_window(path, i, nbytes=300_000):
    import zlib
    seed = zlib.crc32(os.path.basename(path).encode()) % (2 ** 31)
    return np.random.RandomState(seed + i).randint(
        0, 255, size=(nbytes,)).astype(np.uint8)


class CrashRecipe(SyntheticRecipe):
    """SIGKILLs its own worker process mid-video for paths containing
    'CRASH' — the closest harness-reachable stand-in for a decoder
    segfault (no Python teardown, no 'err' message, just a dead pid)."""

    def open(self, path):
        info, windows = super().open(path)
        if 'CRASH' not in os.path.basename(path):
            return info, windows

        def crashing():
            it = iter(windows)
            yield next(it)                    # one window escapes first
            os.kill(os.getpid(), signal.SIGKILL)

        return info, crashing()


class CrashingRealRecipe(CrashRecipe):
    """Module-level (spawn unpickles recipes by reference): decode real
    clips via the extractor's own recipe, but SIGKILL the worker on the
    marked one (``CrashRecipe.open`` handles the marker)."""

    def __init__(self, inner):
        super().__init__(n_windows=4)
        self.inner = inner

    def open(self, path):
        if 'CRASH' in os.path.basename(path):
            return super().open(path)
        return self.inner.open(path)


def _tasks(paths):
    from video_features_tpu.parallel.packing import VideoTask
    return [VideoTask(str(p)) for p in paths]


def _drain_farm(farm, tasks):
    """Consume a farm stream to completion; returns {path: [windows]}."""
    from video_features_tpu.parallel.packing import FLUSH, NUDGE
    got = {str(t.path): [] for t in tasks}
    for item in farm.stream(iter(tasks), lambda t: True):
        if item is FLUSH or item is NUDGE:
            continue
        task, window, meta = item
        got[str(task.path)].append((meta, window))
    return got


# -- farm transport: integrity, backpressure, fallback (no jax) --------------


def test_farm_ships_windows_byte_exact_across_workers(tmp_path):
    """Every window of every video arrives exactly once, in order, with
    the exact bytes the worker produced — through rings small enough to
    wrap and backpressure many times per video."""
    from video_features_tpu.farm import DecodeFarm
    paths = [tmp_path / f'v{i}.bin' for i in range(4)]
    tasks = _tasks(paths)
    farm = DecodeFarm(SyntheticRecipe(), workers=2,
                      ring_bytes=1 << 20)     # ~3 windows per ring
    got = _drain_farm(farm, tasks)
    for t in tasks:
        assert not t.failed and t.exhausted
        assert t.emitted == 24
        wins = got[str(t.path)]
        assert [m for m, _ in wins] == list(range(24))   # in order
        for i, (_, w) in enumerate(wins):
            np.testing.assert_array_equal(w, expected_window(t.path, i))
    st = farm.stats()
    assert st['windows'] == 4 * 24
    assert st['queue_fallback'] == 0
    assert st['videos_failed'] == 0 and st['respawns'] == 0


def test_farm_slow_consumer_backpressures_not_balloons(tmp_path):
    """With a consumer slower than decode, producer-side ring occupancy
    is the only buffer: the run completes, every byte intact, and the
    reported in-flight ring bytes never exceed ring capacity."""
    from video_features_tpu.farm import DecodeFarm
    from video_features_tpu.parallel.packing import FLUSH, NUDGE
    paths = [tmp_path / 'slow0.bin', tmp_path / 'slow1.bin']
    tasks = _tasks(paths)
    ring_bytes = 1 << 20
    farm = DecodeFarm(SyntheticRecipe(n_windows=12), workers=2,
                      ring_bytes=ring_bytes)
    seen = 0
    for item in farm.stream(iter(tasks), lambda t: True):
        if item is FLUSH or item is NUDGE:
            continue
        task, window, meta = item
        np.testing.assert_array_equal(
            window, expected_window(task.path, meta))
        seen += 1
        for w in farm._workers:               # producer-reported usage
            assert w.ring_used <= ring_bytes
        time.sleep(0.02)                      # slower than decode
    assert seen == 2 * 12


def test_farm_oversized_windows_fall_back_to_queue(tmp_path):
    """Windows larger than half a ring take the message-queue fallback —
    slower, but never wrong and never deadlocked."""
    from video_features_tpu.farm import DecodeFarm
    paths = [tmp_path / 'big.bin']
    tasks = _tasks(paths)
    farm = DecodeFarm(SyntheticRecipe(n_windows=5, nbytes=400_000),
                      workers=1, ring_bytes=1 << 19)   # windows > ring/2
    got = _drain_farm(farm, tasks)
    wins = got[str(paths[0])]
    assert len(wins) == 5
    for i, (_, w) in enumerate(wins):
        np.testing.assert_array_equal(
            w, expected_window(paths[0], i, nbytes=400_000))
    assert farm.stats()['queue_fallback'] == 5


def test_farm_oversized_fallback_backpressures(tmp_path):
    """Queue-transport windows are credit-bounded (MAX_UNACKED_WINQ,
    acked by the consumer per consumed window): a slow consumer stalls
    decode instead of growing the parent's message queue without bound
    — the fallback path honors the same memory contract as the ring."""
    from video_features_tpu.farm import DecodeFarm
    from video_features_tpu.farm.worker import MAX_UNACKED_WINQ
    from video_features_tpu.parallel.packing import FLUSH, NUDGE
    paths = [tmp_path / 'big.bin']
    tasks = _tasks(paths)
    farm = DecodeFarm(SyntheticRecipe(n_windows=12, nbytes=400_000),
                      workers=1, ring_bytes=1 << 19)   # all > ring/2
    seen = 0
    for item in farm.stream(iter(tasks), lambda t: True):
        if item is FLUSH or item is NUDGE:
            continue
        task, window, meta = item
        np.testing.assert_array_equal(
            window, expected_window(task.path, meta, nbytes=400_000))
        seen += 1
        time.sleep(0.05)                      # much slower than decode
        for w in farm._workers:
            try:                              # queued = unacked ≤ cap
                backlog = w.out_q.qsize()
            except NotImplementedError:       # macOS qsize — skip bound
                backlog = 0
            # slack beyond the winq credit cap: the start/end markers
            # plus at most two tiny clock-calibration replies (startup
            # + the min-RTT refinement, which stops once tight) — all
            # O(bytes) control messages, not window payloads, so the
            # memory contract this test pins is untouched
            assert backlog <= MAX_UNACKED_WINQ + 3
    assert seen == 12
    assert farm.stats()['queue_fallback'] == 12


def test_farm_worker_crash_fails_one_video_and_respawns(tmp_path):
    """A worker SIGKILLed mid-video fails exactly that video; its
    queued siblings re-dispatch to the respawned worker and complete
    byte-exact; the farm records the respawn."""
    from video_features_tpu.farm import DecodeFarm
    paths = [tmp_path / 'a.bin', tmp_path / 'CRASH.bin',
             tmp_path / 'b.bin', tmp_path / 'c.bin', tmp_path / 'd.bin']
    tasks = _tasks(paths)
    farm = DecodeFarm(CrashRecipe(n_windows=8), workers=2,
                      ring_bytes=1 << 20)
    got = _drain_farm(farm, tasks)

    by_path = {str(t.path): t for t in tasks}
    victim = by_path[str(tmp_path / 'CRASH.bin')]
    assert victim.failed and victim.exhausted
    for t in tasks:
        if t is victim:
            continue
        assert not t.failed, t.path
        wins = got[str(t.path)]
        assert len(wins) == 8, t.path
        for i, (_, w) in enumerate(wins):
            np.testing.assert_array_equal(w, expected_window(t.path, i))
    st = farm.stats()
    assert st['respawns'] >= 1
    assert st['videos_failed'] == 1


def test_farm_worker_spans_land_under_worker_pid_calibrated(tmp_path):
    """vft-flight cross-process span round-trip: decode spans are
    MEASURED in the worker and shipped on the result channel; the
    parent records them under the worker's own pid with the
    clock-calibration offset applied, tagged with the task's trace
    context — so the merged timeline shows true in-worker decode time,
    not parent-side drain time."""
    from tools.trace_view import validate_events

    from video_features_tpu.farm import DecodeFarm
    from video_features_tpu.obs.context import mint
    from video_features_tpu.obs.spans import SpanRecorder
    from video_features_tpu.utils.tracing import Tracer

    paths = [tmp_path / 'sa.bin', tmp_path / 'sb.bin']
    tasks = _tasks(paths)
    ctx = mint()
    for t in tasks:
        t.trace = ctx.child()
    rec = SpanRecorder(capacity=4096)
    farm = DecodeFarm(SyntheticRecipe(n_windows=6), workers=2,
                      ring_bytes=1 << 20,
                      tracer=Tracer(enabled=True, recorder=rec))
    worker_pids = []
    got = {str(p): 0 for p in paths}
    from video_features_tpu.parallel.packing import FLUSH, NUDGE
    for item in farm.stream(iter(tasks), lambda t: True):
        if not worker_pids:
            worker_pids = [w.proc.pid for w in farm._workers
                           if w.proc is not None]
            # calibration sanity: perf_counter is process-shared on
            # Linux, so the midpoint offset must be tiny — a huge value
            # means the handshake mixed up its operands
            assert all(abs(w.clock_offset) < 60.0
                       for w in farm._workers)
        if item is FLUSH or item is NUDGE:
            continue
        got[str(item[0].path)] += 1
    events = rec.snapshot()
    assert validate_events(events) == []
    decode = [e for e in events
              if e['ph'] == 'X' and e['name'] == 'decode']
    # one in-worker span per shipped window, every one under a WORKER
    # pid (never the parent's), per-video ordering intact
    assert len(decode) == sum(got.values()) == 2 * 6
    assert all(e['pid'] in worker_pids for e in decode)
    assert all(e['pid'] != os.getpid() for e in decode)
    for p in paths:
        vid_spans = [e for e in decode
                     if e['args']['video'] == str(p)]
        assert len(vid_spans) == 6
        # calibrated offsets: in-worker spans sit on the parent
        # timeline (non-negative, ts-ordered per video)
        ts = [e['ts'] for e in vid_spans]
        assert ts == sorted(ts) and ts[0] >= 0
        # trace context crossed the process boundary
        assert all(e['args']['trace_id'] == ctx.trace_id
                   for e in vid_spans)
        assert all(e['args'].get('span_id') for e in vid_spans)
        assert all(e['tid'] == e['args']['worker'] for e in vid_spans)


def test_farm_clock_calibration_keeps_min_rtt_measurement():
    """The offset error is bounded by half the exchange's round trip,
    so only the tightest exchange ever seen may update the offset: the
    startup handshake (round trip spans process SPAWN — its midpoint
    would shift spans by ~spawn/2) only seeds it, and a tight in-decode
    re-sync replaces it; later coarse replies never regress it."""
    from video_features_tpu.farm import DecodeFarm
    from video_features_tpu.farm.farm import _Worker
    farm = DecodeFarm(SyntheticRecipe(), workers=1)   # never started
    w = _Worker(0, 0)
    t = time.perf_counter()
    # startup-grade exchange: 1s round trip (spawn) → coarse seed
    farm._handle(w, ('clock', 0, 0, t - 1.0, t - 0.2))
    assert w.clock_rtt >= 1.0
    assert abs(w.clock_offset + 0.3) < 0.05           # ≈ -(spawn)/2 bias
    # tight in-decode refinement: ~2ms round trip → replaces the seed
    t2 = time.perf_counter()
    farm._handle(w, ('clock', 0, 0, t2 - 0.002, t2 - 0.001))
    assert w.clock_rtt < 0.05
    tight = w.clock_offset
    assert abs(tight) < 0.05          # shared clock ⇒ true offset ≈ 0
    # a later COARSE reply must never regress the calibration
    t3 = time.perf_counter()
    farm._handle(w, ('clock', 0, 0, t3 - 2.0, t3 - 1.0))
    assert w.clock_offset == tight and w.clock_rtt < 0.05


def test_farm_pending_cb_mirrors_backlog_and_zeroes_on_shutdown(tmp_path):
    """The stall-watchdog feed: the farm mirrors each worker's
    assignment backlog through pending_cb, and shutdown zeroes the rows
    so a retired farm can never read as a stall."""
    from video_features_tpu.farm import DecodeFarm
    calls = []
    paths = [tmp_path / f'pb{i}.bin' for i in range(3)]
    tasks = _tasks(paths)
    farm = DecodeFarm(SyntheticRecipe(n_windows=6), workers=2,
                      ring_bytes=1 << 20,
                      pending_cb=lambda idx, n: calls.append((idx, n)))
    _drain_farm(farm, tasks)
    assert calls, 'pending_cb never fired'
    last = {}
    for idx, n in calls:
        last[idx] = n
    assert set(last) == {0, 1}
    assert all(n == 0 for n in last.values())   # zeroed at shutdown


def test_farm_sigkill_loses_at_most_inflight_spans(tmp_path):
    """A SIGKILLed worker loses at most its in-flight video's unsent
    spans: every window that reached the parent has its span, siblings
    keep a full per-window span ledger, and the victim's spans stop at
    what it shipped before dying."""
    from video_features_tpu.farm import DecodeFarm
    from video_features_tpu.obs.spans import SpanRecorder
    from video_features_tpu.utils.tracing import Tracer

    paths = [tmp_path / 'ka.bin', tmp_path / 'CRASH.bin',
             tmp_path / 'kb.bin']
    tasks = _tasks(paths)
    rec = SpanRecorder(capacity=4096)
    farm = DecodeFarm(CrashRecipe(n_windows=8), workers=2,
                      ring_bytes=1 << 20,
                      tracer=Tracer(enabled=True, recorder=rec))
    got = _drain_farm(farm, tasks)
    decode = [e for e in rec.snapshot()
              if e['ph'] == 'X' and e['name'] == 'decode']
    by_video = {}
    for e in decode:
        by_video.setdefault(e['args']['video'], []).append(e)
    for p in (paths[0], paths[2]):
        assert len(by_video[str(p)]) == 8 == len(got[str(p)])
    victim_spans = by_video.get(str(paths[1]), [])
    # exactly the windows that escaped before the SIGKILL (one), no
    # phantom spans for windows that never reached the parent
    assert len(victim_spans) == len(got[str(paths[1])]) <= 1


def test_farm_unparks_duplicate_while_stream_stays_open(tmp_path):
    """Serve regression: a duplicate parked behind a mid-decode twin
    must resolve as soon as the twin FINALIZES — not when the task
    stream ends, because a serve feed never ends until server drain. The
    drain loop's supervise tick owns the unpark."""
    import threading

    from video_features_tpu.farm import DecodeFarm
    from video_features_tpu.parallel.packing import FLUSH, NUDGE
    a, b = _tasks([tmp_path / 'dup_a.bin', tmp_path / 'dup_b.bin'])
    stop = threading.Event()
    feed_timed_out = []

    def feed():
        yield a
        yield b                               # same key, twin mid-decode
        # serve-style: the stream stays open until told otherwise,
        # punctuated by idle FLUSHes (packed_batches' lull behavior) —
        # the unpark must happen while the stream is still live
        deadline = time.monotonic() + 20
        while not stop.is_set():
            if time.monotonic() > deadline:
                feed_timed_out.append(True)
                return
            time.sleep(0.05)
            yield FLUSH

    def admit(t):
        # the cache seam: misses while the twin is mid-decode (so B gets
        # gated through to the dedupe park), hits once it published (so
        # B's re-gate is terminal without decoding)
        return t is a or not a.finalized

    farm = DecodeFarm(SyntheticRecipe(n_windows=12), workers=2,
                      ring_bytes=1 << 20,
                      cache_key_fn=lambda p: 'same-content')
    for item in farm.stream(feed(), admit):
        if item is not FLUSH and item is not NUDGE:
            task, window, meta = item
            np.testing.assert_array_equal(
                window, expected_window(task.path, meta))
        if a.exhausted and not a.finalized:
            a.finalized = True                # run_packed's finalize()
        if b.exhausted:
            stop.set()                        # only now may the feed end
    assert not feed_timed_out, \
        'duplicate stayed parked until the stream ended'
    assert a.emitted == 12 and not a.failed
    assert b.exhausted and not b.failed
    assert b.emitted == 0                     # never decoded
    assert farm.stats()['deduped'] == 1


# -- packed-path parity: byte-identical to decode_workers=1 ------------------


@pytest.fixture(scope='module')
def farm_worklist(tmp_path_factory):
    """Mixed-length clips: windows straddle batch boundaries and workers
    finish out of order, so interleaving is actually exercised."""
    d = tmp_path_factory.mktemp('farmvids')
    return [_write_clip(d / f'fv{i}.mp4', n, seed=i)
            for i, n in enumerate((11, 4, 16))]


def _resnet_args(paths, out, tmp, **kw):
    over = dict(video_paths=paths, device='cpu', model_name='resnet18',
                batch_size=4, allow_random_weights=True,
                on_extraction='save_numpy', output_path=str(out),
                tmp_path=str(tmp))
    over.update(kw)
    return load_config('resnet', overrides=over)


RESNET_KEYS = ('resnet', 'fps', 'timestamps_ms')


def _assert_outputs_identical(root_a, root_b, paths, keys=RESNET_KEYS):
    compared = 0
    for p in paths:
        for k in keys:
            a = Path(make_path(str(root_a), p, k, '.npy'))
            b = Path(make_path(str(root_b), p, k, '.npy'))
            assert a.read_bytes() == b.read_bytes(), (p, k)
            compared += 1
    assert compared == len(paths) * len(keys)


def test_packed_farm_byte_identity_framewise(farm_worklist, tmp_path):
    """resnet (FramewiseRecipe: per-frame edge-resize + crop in the
    worker) — packed outputs at decode_workers=2 are byte-identical to
    decode_workers=1, and the farm actually ran."""
    # ONE extractor, both decode paths via the run-level decode_workers
    # override with per-task out_roots (the serve warm-reuse pattern) —
    # halves this tier-1 test's transplant+compile cost
    from video_features_tpu.parallel.packing import VideoTask
    ex = create_extractor(_resnet_args(
        farm_worklist, tmp_path / 'w1', tmp_path / 't1',
        pack_across_videos=True, decode_workers=1))
    ex.extract_packed(farm_worklist)
    assert ex._farm is None                    # 1 ≡ in-process path

    farm_root = str(tmp_path / 'w2')
    ex.extract_packed([VideoTask(p, out_root=farm_root)
                       for p in farm_worklist], decode_workers=2)
    assert ex._farm is not None
    st = ex._farm.stats()
    assert st['videos_assigned'] == len(farm_worklist)
    assert st['windows'] > 0 and st['videos_failed'] == 0

    _assert_outputs_identical(ex.output_path, farm_root, farm_worklist)


def test_packed_farm_byte_identity_stacks(farm_worklist, tmp_path):
    """r21d (StackRecipe: raw-frame stack windows off the worker's
    decoder) — byte-identical at any worker count."""
    # ONE extractor, in-process then farm decode (run-level override +
    # per-task out_roots) — same parity contract, half the build cost
    from video_features_tpu.parallel.packing import VideoTask
    args = load_config('r21d', overrides=dict(
        video_paths=farm_worklist, device='cpu',
        model_name='r2plus1d_18_16_kinetics', stack_size=8,
        step_size=8, batch_size=2, allow_random_weights=True,
        on_extraction='save_numpy',
        output_path=str(tmp_path / 's1' / 'out'),
        tmp_path=str(tmp_path / 's1' / 'tmp'),
        pack_across_videos=True, decode_workers=1))
    ex = create_extractor(args)
    ex.extract_packed(farm_worklist)
    farm_root = str(tmp_path / 's2' / 'out')
    ex.extract_packed([VideoTask(p, out_root=farm_root)
                       for p in farm_worklist], decode_workers=2)
    _assert_outputs_identical(ex.output_path, farm_root,
                              farm_worklist, keys=('r21d',))


def test_packed_farm_crash_spares_siblings_end_to_end(farm_worklist,
                                                     tmp_path):
    """The whole stack under a worker kill: a crashing recipe injected
    into a real resnet packed run fails only the marked video — the
    siblings' saved features are byte-identical to a clean farm run."""
    # ONE extractor: clean farm pass, then the crash pass through the
    # same warm build (per-task out_roots keep the trees apart) — half
    # the transplant+compile cost, same end-to-end contract
    from video_features_tpu.parallel.packing import VideoTask
    ex = create_extractor(_resnet_args(
        farm_worklist, tmp_path / 'clean', tmp_path / 'tc',
        pack_across_videos=True, decode_workers=2))
    ex.extract_packed(farm_worklist)
    clean_root = str(ex.output_path)

    crash_clip = str(Path(farm_worklist[0]).parent / 'CRASH_e2e.mp4')
    if not os.path.exists(crash_clip):
        _write_clip(crash_clip, 8, seed=99)
    worklist = farm_worklist[:1] + [crash_clip] + farm_worklist[1:]

    hurt_root = str(tmp_path / 'hurt')
    real = ex.farm_recipe()
    ex.farm_recipe = lambda: CrashingRealRecipe(real)
    ex.extract_packed([VideoTask(str(p), out_root=hurt_root)
                       for p in worklist])

    assert ex._farm.stats()['respawns'] >= 1
    # the victim has no outputs; every sibling is byte-identical
    assert not Path(make_path(hurt_root, crash_clip, 'resnet',
                              '.npy')).exists()
    _assert_outputs_identical(clean_root, hurt_root, farm_worklist)


def test_packed_farm_cache_dedupe_decodes_shared_content_once(
        farm_worklist, tmp_path):
    """Two worklist entries with IDENTICAL content (different names):
    the farm consults the content-addressed cache key before assigning,
    parks the duplicate while its twin decodes, and serves it from the
    cache once the twin publishes — one decode, two complete outputs."""
    import shutil
    twin_dir = tmp_path / 'twins'
    twin_dir.mkdir()
    a = str(twin_dir / 'orig.mp4')
    b = str(twin_dir / 'copy.mp4')
    shutil.copyfile(farm_worklist[0], a)
    shutil.copyfile(farm_worklist[0], b)

    ex = create_extractor(_resnet_args(
        [a, b], tmp_path / 'dd', tmp_path / 'td',
        pack_across_videos=True, decode_workers=2,
        cache_enabled=True, cache_dir=str(tmp_path / 'cache')))
    ex.extract_packed([a, b])

    st = ex._farm.stats()
    assert st['videos_assigned'] == 1          # one decode for two tasks
    assert st['deduped'] == 1
    for p in (a, b):
        for k in RESNET_KEYS:
            assert Path(make_path(str(ex.output_path), p, k,
                                  '.npy')).exists(), (p, k)
    # the copy's features are byte-identical to the original's
    for k in RESNET_KEYS:
        fa = Path(make_path(str(ex.output_path), a, k, '.npy'))
        fb = Path(make_path(str(ex.output_path), b, k, '.npy'))
        assert fa.read_bytes() == fb.read_bytes(), k


def test_packed_farm_fallback_without_recipe(farm_worklist, tmp_path,
                                             capsys):
    """decode_workers>1 on an extractor that publishes no recipe must
    degrade to in-process decode with a structured warning — outputs
    complete, no farm."""
    ex = create_extractor(_resnet_args(
        farm_worklist, tmp_path / 'fb', tmp_path / 'tf',
        pack_across_videos=True, decode_workers=2))
    ex.farm_recipe = lambda: None
    ex.extract_packed(farm_worklist)
    assert ex._farm is None
    err = capsys.readouterr().err
    assert 'decode_workers=2' in err and 'in-process' in err
    for p in farm_worklist:
        assert Path(make_path(str(ex.output_path), p, 'resnet',
                              '.npy')).exists()


# -- CLI + serve paths -------------------------------------------------------


def test_cli_farm_byte_identity(farm_worklist, tmp_path, capsys):
    """The full CLI entry (cli.main) with pack_across_videos=true +
    decode_workers=2 writes byte-identical features to the
    decode_workers=1 run."""
    from video_features_tpu.cli import main as cli_main
    roots = {}
    for workers in (1, 2):
        out = tmp_path / f'cli{workers}'
        rc = cli_main([
            'feature_type=resnet', 'model_name=resnet18', 'device=cpu',
            'batch_size=4', 'allow_random_weights=true',
            'on_extraction=save_numpy', 'pack_across_videos=true',
            f'decode_workers={workers}',
            f'output_path={out}', f'tmp_path={tmp_path / "ctmp"}',
            # YAML flow-list syntax: a bare comma-joined string would
            # parse as ONE path
            'video_paths=[' + ','.join(str(p) for p in farm_worklist) + ']',
        ])
        assert rc == 0
        roots[workers] = os.path.join(str(out), 'resnet', 'resnet18')
    capsys.readouterr()
    _assert_outputs_identical(roots[1], roots[2], farm_worklist)


def test_serve_farm_parity_and_metrics(farm_worklist, tmp_path):
    """A farm-backed server (decode_workers=2 base override) answers a
    request byte-identically to the in-process server, and the metrics
    document's 'farm' section + vft_farm_* families report the workers
    that ran it."""
    from video_features_tpu.serve.client import ServeClient
    from video_features_tpu.serve.server import ExtractionServer

    def base(workers):
        return {
            'device': 'cpu', 'model_name': 'resnet18', 'batch_size': 4,
            'allow_random_weights': True, 'on_extraction': 'save_numpy',
            'tmp_path': str(tmp_path / f'stmp{workers}'),
            'decode_workers': workers,
        }

    roots = {}
    for workers in (1, 2):
        server = ExtractionServer(base_overrides=base(workers),
                                  queue_depth=32, pool_size=2).start()
        try:
            client = ServeClient(port=server.port)
            out_root = str(tmp_path / f'serve{workers}')
            rid = client.submit('resnet', farm_worklist,
                                overrides={'output_path': out_root})
            st = client.wait(rid, timeout_s=300)
            assert st['state'] == 'done', st
            m = client.metrics()
            assert 'farm' in m
            if workers > 1:
                assert m['farm']['decode_workers'] >= 2
                assert m['farm']['windows'] > 0
                prom = client.metrics_prom()
                assert 'vft_farm_windows' in prom
            else:
                assert m['farm']['windows'] == 0
        finally:
            server.drain(wait=True, grace_s=60)
        roots[workers] = os.path.join(out_root, 'resnet', 'resnet18')
    _assert_outputs_identical(roots[1], roots[2], farm_worklist)
