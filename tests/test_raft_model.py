"""RAFT: numerical parity vs the reference torch net (20-iteration GRU)."""
import numpy as np
import pytest
import torch

from video_features_tpu.models import raft as raft_model
from video_features_tpu.transplant.torch2jax import transplant

pytestmark = pytest.mark.slow  # parity/e2e/sharding: full lane only



@pytest.fixture(scope='module')
def torch_raft(reference_repo):
    from models.raft.raft_src.raft import RAFT
    torch.manual_seed(0)
    model = RAFT()
    model.eval()
    return model


def test_parity_flow(torch_raft):
    """Same random weights + input pair → same flow after 20 GRU iterations.

    The iterative structure gives numerical drift little room: agreement here
    means the encoders, corr pyramid, bilinear lookup, GRU, and convex
    upsampling all match (SURVEY.md §7 hard-part #1).
    """
    params = transplant(torch_raft.state_dict())
    rng = np.random.RandomState(0)
    # 128x128: smallest corr-pyramid level is 2x2 — the torch reference
    # divides by (H-1) when normalizing grid coords and NaNs on 1-pixel
    # levels, so anything smaller is outside its operating envelope
    f1 = rng.randint(0, 256, (1, 128, 128, 3)).astype(np.float32)
    f2 = np.clip(f1 + rng.randn(1, 128, 128, 3) * 8, 0, 255).astype(np.float32)

    with torch.no_grad():
        ref = torch_raft(
            torch.from_numpy(f1).permute(0, 3, 1, 2),
            torch.from_numpy(f2).permute(0, 3, 1, 2),
        ).permute(0, 2, 3, 1).numpy()

    import jax
    with jax.default_matmul_precision('highest'):
        ours = np.asarray(raft_model.forward(params, f1, f2))

    assert ours.shape == ref.shape == (1, 128, 128, 2)
    l2 = np.linalg.norm(ours - ref) / max(np.linalg.norm(ref), 1e-12)
    assert l2 < 1e-3, f'relative L2 {l2}'
    np.testing.assert_allclose(ours, ref, atol=2e-3)


def test_bilinear_sample_matches_grid_sample():
    rng = np.random.RandomState(0)
    img = rng.rand(2, 6, 7, 1).astype(np.float32)
    # include out-of-range coords to exercise zeros padding
    coords = (rng.rand(2, 11, 2).astype(np.float32) * 10) - 2

    ours = np.asarray(raft_model.bilinear_sample(img, coords))

    timg = torch.from_numpy(img).permute(0, 3, 1, 2)
    x = torch.from_numpy(coords[..., 0])
    y = torch.from_numpy(coords[..., 1])
    grid = torch.stack([2 * x / (7 - 1) - 1, 2 * y / (6 - 1) - 1], dim=-1)
    ref = torch.nn.functional.grid_sample(
        timg, grid.unsqueeze(2), align_corners=True).squeeze(-1).permute(0, 2, 1).numpy()
    np.testing.assert_allclose(ours, ref, atol=1e-6)


def test_pad_unpad_roundtrip():
    x = np.random.RandomState(0).rand(1, 61, 125, 3).astype(np.float32)
    padded, pads = raft_model.pad_to_multiple(x)
    assert padded.shape[1] % 8 == 0 and padded.shape[2] % 8 == 0
    back = np.asarray(raft_model.unpad(padded, pads))
    np.testing.assert_array_equal(back, x)


def test_coords_grid_xy_order():
    g = np.asarray(raft_model.coords_grid(1, 3, 4))
    assert g.shape == (1, 3, 4, 2)
    assert g[0, 2, 3, 0] == 3  # x = column
    assert g[0, 2, 3, 1] == 2  # y = row


def test_lookup_dense_matches_gather():
    """The MXU-friendly dense lookup must equal the gather oracle, including
    zeros-padding at out-of-map coords (reference corr.py:29-50 semantics)."""
    import jax
    import jax.numpy as jnp

    rng = np.random.RandomState(0)
    B, H8, W8, D = 6, 12, 9, 32
    f1 = jnp.asarray(rng.randn(B, H8, W8, D).astype(np.float32))
    f2 = jnp.asarray(rng.randn(B, H8, W8, D).astype(np.float32))
    py = raft_model.build_corr_pyramid(f1, f2)
    # coords spill past every edge to exercise the zero-weight region
    coords = jnp.asarray(
        (rng.rand(B, H8, W8, 2) * [W8 * 1.6, H8 * 1.6]
         - [W8 * 0.3, H8 * 0.3]).astype(np.float32))
    with jax.default_matmul_precision('highest'):
        a = np.asarray(raft_model.lookup_corr(py, coords))
        b = np.asarray(raft_model.lookup_corr_dense(py, coords))
    assert a.shape == b.shape
    np.testing.assert_allclose(a, b, atol=1e-5)


def test_forward_consecutive_matches_pairwise():
    """Frame-deduplicated encoding must equal the stacked-pair forward —
    same math, each interior frame's fnet encoding computed once."""
    import jax

    from video_features_tpu.transplant.torch2jax import transplant
    params = transplant(raft_model.init_state_dict(seed=0))
    rng = np.random.RandomState(3)
    frames = rng.randint(0, 255, (5, 48, 64, 3)).astype(np.float32)

    with jax.default_matmul_precision('highest'):
        ref = np.asarray(raft_model.forward(
            params, frames[:-1], frames[1:], iters=3))
        got = np.asarray(raft_model.forward_consecutive(
            params, frames, iters=3))
    assert got.shape == ref.shape == (4, 48, 64, 2)
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-4)


def test_forward_stack_pairs_matches_pairwise():
    """The fused-I3D stack form: (B, S+1) frames → (B, S) within-stack
    flows, equal to pairwise forward on each stack's consecutive pairs."""
    import jax

    from video_features_tpu.transplant.torch2jax import transplant
    params = transplant(raft_model.init_state_dict(seed=0))
    rng = np.random.RandomState(4)
    B, S = 2, 3
    stacks = rng.randint(0, 255, (B, S + 1, 48, 64, 3)).astype(np.float32)

    with jax.default_matmul_precision('highest'):
        f1 = stacks[:, :-1].reshape(B * S, 48, 64, 3)
        f2 = stacks[:, 1:].reshape(B * S, 48, 64, 3)
        ref = np.asarray(raft_model.forward(params, f1, f2, iters=3))
        got = np.asarray(raft_model.forward_stack_pairs(
            params, stacks, iters=3))
    assert got.shape == (B, S, 48, 64, 2)
    np.testing.assert_allclose(got.reshape(B * S, 48, 64, 2), ref,
                               rtol=1e-4, atol=1e-4)
