"""PyAV (torchvision.io) vs our decode — the r21d/s3d decode-backend row.

The reference decodes the r21d and s3d families through
``torchvision.io.read_video`` (PyAV) rather than cv2
(reference models/r21d/extract_r21d.py:72, models/s3d/extract_s3d.py:63),
while every golden in this repo re-composes the reference side over cv2
decode (torchvision is absent in the dev environment). PyAV-vs-cv2 frame
divergence is exactly the class of delta that measured 2.9e-3 on the
round-4 native-decode row — these tests quantify it for the two families
where the reference actually uses PyAV (VERDICT r4 task 7).

Runs where torchvision IS installed (the CI full lane installs it —
.github/workflows/ci.yml); self-skips elsewhere. The clip is the
reference sample when that checkout exists, else a locally-synthesized
H.264-free mp4 (cv2.VideoWriter) — so the tests RUN in CI rather than
silently skipping on the missing reference checkout. Both the
frame-level delta and the feature-level delta through the r21d step are
measured and printed, and asserted at documentation bands (frame deltas
are expected to be small-but-nonzero: PyAV's decode is spec-exact like
libavcodec's, so any difference is YUV→RGB conversion rounding, the same
mechanism as the native-decode row — see docs/design.md).
"""
from __future__ import annotations

import numpy as np
import pytest

torchvision = pytest.importorskip('torchvision')

pytestmark = pytest.mark.slow


@pytest.fixture(scope='module')
def decode_clip(tmp_path_factory):
    """The reference sample when present, else a synthetic mp4 — never a
    skip, so CI (no reference checkout) still exercises the comparison."""
    from tests.conftest import REFERENCE_ROOT

    sample = REFERENCE_ROOT / 'sample' / 'v_ZNVhz7ctTq0.mp4'
    if sample.exists():
        return str(sample)
    import cv2
    out = str(tmp_path_factory.mktemp('pyav') / 'clip.mp4')
    rng = np.random.RandomState(3)
    h, w = 240, 320
    wr = cv2.VideoWriter(out, cv2.VideoWriter_fourcc(*'mp4v'), 25, (w, h))
    base = rng.randint(0, 256, (h, w, 3), np.uint8)
    for t in range(40):
        wr.write(np.roll(base, 3 * t, axis=1))
    wr.release()
    return out


@pytest.fixture(scope='module')
def frame_pair(decode_clip):
    """(pyav_frames, our_frames) uint8 RGB for the same clip, equal-length
    prefix."""
    tv_frames, _, _ = torchvision.io.read_video(
        decode_clip, pts_unit='sec', output_format='THWC')
    tv_frames = tv_frames.numpy()

    from video_features_tpu.io.video import VideoLoader
    ours = [f for batch, _, _ in VideoLoader(decode_clip, batch_size=64)
            for f in batch]
    n = min(len(tv_frames), len(ours), 64)
    assert n >= 17, f'too few frames decoded: {n}'
    return tv_frames[:n], np.stack(ours[:n])


def test_pyav_frame_delta_quantified(frame_pair):
    """Frame-level PyAV-vs-ours delta: measured, printed, and bounded.

    Zero would mean torchvision's PyAV build converts YUV→RGB with the
    same integer tables cv2 does (both bundle FFmpeg); small-nonzero
    means conversion rounding exactly like the round-4 native-decode
    analysis predicts. Either way the number is on record, and a LARGE
    delta (mean > 2 levels / any pixel > 64) would indicate a real
    decode divergence worth a golden re-run with this backend."""
    tv, ours = frame_pair
    assert tv.shape == ours.shape
    d = np.abs(tv.astype(np.int16) - ours.astype(np.int16))
    stats = dict(mean=float(d.mean()), max=int(d.max()),
                 frac_nonzero=float((d > 0).mean()))
    print(f'[pyav] frame delta vs our decode: {stats}')
    assert stats['mean'] <= 2.0, stats
    assert stats['max'] <= 64, stats


def test_pyav_feature_delta_r21d(frame_pair):
    """Feature-level cost of the PyAV-vs-ours frame delta through the
    r21d production step (the family the reference feeds from PyAV):
    both frame sets run the IDENTICAL step + seeded weights, so the only
    difference is the decode. Held to the 1e-3 parity bar — if this
    fails, the decode-backend divergence is feature-relevant and the
    r21d/s3d goldens need a PyAV-side recomposition."""
    import jax

    from video_features_tpu.extract.r21d import ExtractR21D
    from video_features_tpu.models import r21d as r21d_model
    from video_features_tpu.transplant.torch2jax import transplant

    tv, ours = frame_pair
    stack = 16
    params = transplant(r21d_model.init_state_dict(arch='r2plus1d_18'))
    step = jax.jit(lambda p, x: ExtractR21D._forward_batch(
        p, x, arch='r2plus1d_18'))

    def feats(frames):
        batch = frames[:stack][None].astype(np.float32)
        return np.asarray(step(params, batch))

    fa, fb = feats(tv), feats(ours)
    rel = np.linalg.norm(fa - fb) / max(np.linalg.norm(fb), 1e-12)
    print(f'[pyav] r21d feature rel L2 (decode-backend delta): {rel:.3e}')
    assert rel < 1e-3, f'PyAV decode diverges at feature level: {rel}'
