"""Transplant layer: layout conversion, nesting, DP-prefix stripping."""
import numpy as np

from video_features_tpu.transplant.torch2jax import (
    convert_tensor, nest, strip_dataparallel, transplant,
)


def test_conv2d_layout():
    w = np.arange(2 * 3 * 5 * 7).reshape(2, 3, 5, 7).astype(np.float32)
    out = convert_tensor('conv.weight', w)
    assert out.shape == (5, 7, 3, 2)  # (O,I,kH,kW) -> (kH,kW,I,O)
    assert out[1, 2, 0, 1] == w[1, 0, 1, 2]


def test_conv3d_layout():
    w = np.zeros((4, 3, 1, 7, 7), np.float32)
    assert convert_tensor('stem.0.weight', w).shape == (1, 7, 7, 3, 4)


def test_linear_layout():
    w = np.arange(6).reshape(2, 3).astype(np.float32)
    out = convert_tensor('fc.weight', w)
    assert out.shape == (3, 2)
    np.testing.assert_array_equal(out, w.T)


def test_bias_untouched():
    b = np.arange(4).astype(np.float32)
    np.testing.assert_array_equal(convert_tensor('fc.bias', b), b)


def test_bn_vectors_untouched():
    v = np.ones(8, np.float32)
    np.testing.assert_array_equal(convert_tensor('bn.running_mean', v), v)
    # BN '.weight' is 1-D → not transposed
    np.testing.assert_array_equal(convert_tensor('bn.weight', v), v)


def test_strip_dataparallel_keeps_unprefixed():
    sd = {'module.a.weight': 1, 'b.bias': 2}
    out = strip_dataparallel(sd)
    assert out == {'a.weight': 1, 'b.bias': 2}


def test_nest():
    tree = nest({'a.b.c': 1, 'a.b.d': 2, 'e': 3})
    assert tree == {'a': {'b': {'c': 1, 'd': 2}}, 'e': 3}


def test_transplant_drops_num_batches_tracked():
    sd = {'bn.num_batches_tracked': np.int64(7), 'bn.weight': np.ones(2, np.float32)}
    tree = transplant(sd)
    assert 'num_batches_tracked' not in tree['bn']


def test_transplant_dtype_cast():
    sd = {'fc.weight': np.ones((2, 2), np.float16)}
    tree = transplant(sd, dtype=np.float32)
    assert tree['fc']['weight'].dtype == np.float32


def test_npz_roundtrip_and_torchfree_load(tmp_path):
    """save_transplanted → load via load_torch_checkpoint('.npz') preserves
    the exact pytree (torch-free deployment path)."""
    from video_features_tpu.models import r21d as r21d_model
    from video_features_tpu.transplant.torch2jax import (
        load_torch_checkpoint, save_transplanted, transplant,
    )

    params = transplant(r21d_model.init_state_dict(seed=3))
    path = str(tmp_path / 'ckpt.npz')
    save_transplanted(params, path)
    loaded = load_torch_checkpoint(path)

    def flatten(t, p=''):
        for k, v in t.items():
            if isinstance(v, dict):
                yield from flatten(v, f'{p}{k}.')
            else:
                yield f'{p}{k}', v

    a, b = dict(flatten(params)), dict(flatten(loaded))
    assert a.keys() == b.keys()
    for k in a:
        np.testing.assert_array_equal(a[k], b[k], err_msg=k)


def test_npz_end_to_end_in_extractor(tmp_path, short_video):
    """An extractor consumes a .npz checkpoint_path with no torch import."""
    from video_features_tpu.config import load_config
    from video_features_tpu.registry import create_extractor
    from video_features_tpu.models import resnet as resnet_model
    from video_features_tpu.transplant.torch2jax import (
        save_transplanted, transplant,
    )

    params = transplant(resnet_model.init_state_dict(arch='resnet18'))
    ckpt = str(tmp_path / 'resnet18.npz')
    save_transplanted(params, ckpt)

    args = load_config('resnet', overrides={
        'model_name': 'resnet18', 'device': 'cpu', 'batch_size': 16,
        'video_paths': short_video, 'checkpoint_path': ckpt,
        'output_path': str(tmp_path / 'out'), 'tmp_path': str(tmp_path / 'tmp'),
    })
    out = create_extractor(args).extract(short_video)
    assert out['resnet'].shape[1] == 512


def test_npz_load_applies_dtype_and_rejects_key():
    import pytest

    from video_features_tpu.transplant.torch2jax import (
        load_torch_checkpoint, save_transplanted,
    )
    import tempfile, os
    tree = {'a': {'w': np.ones((2, 2), np.float16)},
            'idx': np.arange(3, dtype=np.int64)}
    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, 'x.npz')
        save_transplanted(tree, path)
        out = load_torch_checkpoint(path)            # default dtype=float32
        assert out['a']['w'].dtype == np.float32     # fp16 upcast honored
        assert out['idx'].dtype == np.int64          # ints untouched
        with pytest.raises(ValueError, match='already transplanted'):
            load_torch_checkpoint(path, key='state_dict')
