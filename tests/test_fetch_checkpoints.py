"""tools/fetch_checkpoints.py: offline-verifiable provisioning paths.

Network downloads can't run in CI; the URL machinery is exercised through
``file://`` URLs and the bundled-blob path through a fake reference
checkout. The URL/hash table itself mirrors the reference sources
(clip_src/clip.py:32-43, extract_resnet.py:38-40, vggish_slim.py:119-131).
"""
import hashlib
import importlib.util
import sys
from pathlib import Path

import numpy as np
import pytest

_spec = importlib.util.spec_from_file_location(
    'fetch_checkpoints',
    Path(__file__).parent.parent / 'tools' / 'fetch_checkpoints.py')
fc = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(fc)


def test_expected_hash_conventions():
    # full sha256 (CLIP style)
    art = {'name': 'ViT-B-32.pt', 'sha256': 'ab' * 32}
    assert fc.expected_hash(art) == 'ab' * 32
    # torch-hub filename prefix (torchvision style)
    art = {'name': 'resnet50-0676ba61.pth', 'sha256': 'filename'}
    assert fc.expected_hash(art) == '0676ba61'


def test_registry_covers_every_family():
    from video_features_tpu.config import KNOWN_FEATURE_TYPES
    # timm weights come via the pip-timm bridge, not this tool
    assert set(fc.SOURCES) == set(KNOWN_FEATURE_TYPES) - {'timm'}


def test_file_url_download_and_verify(tmp_path):
    blob = tmp_path / 'mirror' / 'weights' / 'model-aaaa.pth'
    blob.parent.mkdir(parents=True)
    blob.write_bytes(b'weights-bytes')
    sha = hashlib.sha256(b'weights-bytes').hexdigest()
    art = {'kind': 'url', 'name': 'model-aaaa.pth',
           'url': 'https://example.com/weights/model-aaaa.pth',
           'sha256': sha}
    out = tmp_path / 'out'
    got = fc.fetch_artifact(art, out, url_base=f'file://{tmp_path}/mirror')
    assert got.read_bytes() == b'weights-bytes'
    # second call: checksum-verified skip (corrupt the mirror to prove it)
    blob.write_bytes(b'changed')
    assert fc.fetch_artifact(
        art, out, url_base=f'file://{tmp_path}/mirror') == got


def test_checksum_mismatch_raises_and_removes(tmp_path):
    blob = tmp_path / 'mirror' / 'w' / 'model-bbbb.pth'
    blob.parent.mkdir(parents=True)
    blob.write_bytes(b'tampered')
    art = {'kind': 'url', 'name': 'model-bbbb.pth',
           'url': 'https://example.com/w/model-bbbb.pth',
           'sha256': hashlib.sha256(b'original').hexdigest()}
    with pytest.raises(RuntimeError, match='sha256 mismatch'):
        fc.fetch_artifact(art, tmp_path / 'out',
                          url_base=f'file://{tmp_path}/mirror')
    assert not (tmp_path / 'out' / 'model-bbbb.pth').exists()


def test_bundled_copy_requires_checkout(tmp_path):
    art = fc.SOURCES['raft'][0]
    with pytest.raises(RuntimeError, match='from-checkout'):
        fc.fetch_artifact(art, tmp_path / 'out')


def test_bundled_copy_and_npz_conversion(tmp_path):
    torch = pytest.importorskip('torch')
    checkout = tmp_path / 'checkout'
    src = checkout / 'models/raft/checkpoints/raft-sintel.pth'
    src.parent.mkdir(parents=True)
    sd = {'module.fnet.conv1.weight': torch.zeros(4, 3, 3, 3),
          'module.fnet.conv1.bias': torch.arange(4.0)}
    torch.save(sd, src)

    art = fc.SOURCES['raft'][0]
    got = fc.fetch_artifact(art, tmp_path / 'out', checkout=checkout)
    npz = fc.convert_artifact(got, art['convert'])
    assert npz.suffix == '.npz'

    from video_features_tpu.transplant.torch2jax import load_torch_checkpoint
    params = load_torch_checkpoint(str(npz))  # torch-free load path
    # DataParallel prefix stripped + conv laid out channels-last
    assert params['fnet']['conv1']['weight'].shape == (3, 3, 3, 4)
    np.testing.assert_array_equal(params['fnet']['conv1']['bias'],
                                  np.arange(4.0, dtype=np.float32))


def test_main_rejects_unknown_family(tmp_path, monkeypatch):
    monkeypatch.setattr(sys, 'argv',
                        ['fetch_checkpoints.py', 'nope', '--out',
                         str(tmp_path)])
    with pytest.raises(SystemExit):
        fc.main()


def test_main_happy_path_offline(tmp_path, monkeypatch, capsys):
    """CLI end-to-end with the bundled-blob source + --no-convert (the
    offline provisioning path)."""
    torch = pytest.importorskip('torch')
    checkout = tmp_path / 'checkout'
    for rel in ['models/raft/checkpoints/raft-sintel.pth',
                'models/raft/checkpoints/raft-kitti.pth']:
        p = checkout / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        torch.save({'w': torch.zeros(2)}, p)
    monkeypatch.setattr(sys, 'argv', [
        'fetch_checkpoints.py', 'raft', '--out', str(tmp_path / 'out'),
        '--no-convert', '--from-checkout', str(checkout)])
    assert fc.main() == 0
    assert (tmp_path / 'out' / 'raft-sintel.pth').exists()
    assert (tmp_path / 'out' / 'raft-kitti.pth').exists()
    assert '2 artifacts ready' in capsys.readouterr().out
