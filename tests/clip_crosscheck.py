"""Shared CLIP ViT-B/32 full-geometry cross-check harness.

One implementation for both consumers — the pytest cross-check
(tests/test_hf_crosscheck.py) and the PARITY.md row generator
(tools/measure_parity.py:measure_hf_clip) — so the two can never drift
into validating different things.

transformers' default CLIPConfig IS OpenAI ViT-B/32 (vision width 768 /
12 layers / patch 32 / 224 px → 512-d; text width 512 / 12 layers /
8 heads / vocab 49408 / ctx 77; quick_gelu). eos_token_id is pinned to
the OpenAI EOT id (49407) so HF's eos-based pooling and our argmax
pooling provably select the same token.
"""
from __future__ import annotations

from typing import Dict

import numpy as np


def run_clip_vitb32_crosscheck() -> Dict[str, np.ndarray]:
    """Returns {ref,got} × {img,txt,logits}: transformers.CLIPModel vs our
    tower through the production converter (transplant/hf.py:
    clip_to_openai), identical inputs, float32/highest."""
    import jax
    import torch
    import transformers

    from video_features_tpu.models import clip as clip_model
    from video_features_tpu.transplant.hf import clip_to_openai
    from video_features_tpu.transplant.torch2jax import transplant

    hf_cfg = transformers.CLIPConfig()
    assert hf_cfg.vision_config.hidden_size == 768
    assert hf_cfg.vision_config.patch_size == 32
    assert hf_cfg.text_config.hidden_size == 512
    assert hf_cfg.projection_dim == 512
    hf_cfg.text_config.eos_token_id = 49407
    torch.manual_seed(0)
    hf = transformers.CLIPModel(hf_cfg).eval()

    params = transplant(clip_to_openai(hf.state_dict()),
                        no_transpose=set(clip_model.NO_TRANSPOSE),
                        dtype=np.float32)

    rng = np.random.RandomState(1)
    x = rng.rand(2, 224, 224, 3).astype(np.float32) * 2 - 1
    # tokens: ids < EOT, then EOT (=vocab max id), zero padding after —
    # argmax and ==eos pooling agree by construction
    tokens = np.zeros((2, 77), np.int64)
    tokens[0, :9] = list(rng.randint(1, 49406, size=8)) + [49407]
    tokens[1, :15] = list(rng.randint(1, 49406, size=14)) + [49407]

    with torch.no_grad():
        pixel = torch.from_numpy(x).permute(0, 3, 1, 2)
        ref_img = hf.get_image_features(pixel).numpy()
        ref_txt = hf.get_text_features(torch.from_numpy(tokens)).numpy()
        ref_logits = hf(input_ids=torch.from_numpy(tokens),
                        pixel_values=pixel).logits_per_image.numpy()
    with jax.default_matmul_precision('highest'):
        got_img = np.asarray(clip_model.encode_image(params, x, 'ViT-B/32'))
        got_txt = np.asarray(clip_model.encode_text(params, tokens,
                                                    'ViT-B/32'))
        got_logits = np.asarray(clip_model.zero_shot_logits(
            params, got_img, got_txt))

    return {'ref_img': ref_img, 'got_img': got_img,
            'ref_txt': ref_txt, 'got_txt': got_txt,
            'ref_logits': ref_logits, 'got_logits': got_logits}
