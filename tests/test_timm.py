"""timm family: ViT parity vs a torch mirror of timm's VisionTransformer
(qkv-fused pre-norm blocks, exact-erf GELU, eps=1e-6 LayerNorm, cls-token
pooling — the math behind reference models/timm/extract_timm.py's
`timm.create_model` + `reset_classifier(0)`), plus registry/E2E coverage."""
import numpy as np
import pytest
import torch
import torch.nn as nn
import torch.nn.functional as F

from video_features_tpu.config import load_config
from video_features_tpu.models import vit as vit_model
from video_features_tpu.registry import create_extractor
from video_features_tpu.transplant.torch2jax import transplant


class _Block(nn.Module):
    def __init__(self, width, heads):
        super().__init__()
        self.heads = heads
        self.norm1 = nn.LayerNorm(width, eps=1e-6)
        self.attn = nn.Module()
        self.attn.qkv = nn.Linear(width, 3 * width)
        self.attn.proj = nn.Linear(width, width)
        self.norm2 = nn.LayerNorm(width, eps=1e-6)
        self.mlp = nn.Module()
        self.mlp.fc1 = nn.Linear(width, 4 * width)
        self.mlp.fc2 = nn.Linear(4 * width, width)

    def forward(self, x):
        B, N, D = x.shape
        hd = D // self.heads
        h = self.norm1(x)
        qkv = self.attn.qkv(h).reshape(B, N, 3, self.heads, hd)
        q, k, v = qkv.permute(2, 0, 3, 1, 4).unbind(0)
        attn = (q @ k.transpose(-2, -1) * hd ** -0.5).softmax(dim=-1)
        h = (attn @ v).transpose(1, 2).reshape(B, N, D)
        x = x + self.attn.proj(h)
        h = self.norm2(x)
        return x + self.mlp.fc2(F.gelu(self.mlp.fc1(h)))


class _TorchViT(nn.Module):
    """State-dict-compatible mirror of timm VisionTransformer (features)."""

    def __init__(self, width, layers, heads, patch, img=224):
        super().__init__()
        self.cls_token = nn.Parameter(torch.randn(1, 1, width) * 0.02)
        self.pos_embed = nn.Parameter(
            torch.randn(1, 1 + (img // patch) ** 2, width) * 0.02)
        self.patch_embed = nn.Module()
        self.patch_embed.proj = nn.Conv2d(3, width, patch, patch)
        self.blocks = nn.ModuleList(_Block(width, heads) for _ in range(layers))
        self.norm = nn.LayerNorm(width, eps=1e-6)
        self.head = nn.Linear(width, 1000)

    def forward(self, x, features=True):
        B = x.shape[0]
        x = self.patch_embed.proj(x).flatten(2).transpose(1, 2)
        x = torch.cat([self.cls_token.expand(B, -1, -1), x], 1) + self.pos_embed
        for blk in self.blocks:
            x = blk(x)
        feats = self.norm(x)[:, 0]
        return feats if features else self.head(feats)


@pytest.mark.parametrize('arch', ['vit_tiny_patch16_224'])
def test_vit_parity_vs_torch_mirror(arch):
    cfg = vit_model.ARCHS[arch]
    torch.manual_seed(0)
    ref_model = _TorchViT(cfg['width'], cfg['layers'], cfg['heads'],
                          cfg['patch']).eval()
    params = transplant(ref_model.state_dict())

    rng = np.random.RandomState(0)
    x = rng.rand(2, 224, 224, 3).astype(np.float32)
    with torch.no_grad():
        ref = ref_model(torch.from_numpy(x).permute(0, 3, 1, 2)).numpy()
        ref_logits = ref_model(
            torch.from_numpy(x).permute(0, 3, 1, 2), features=False).numpy()

    import jax
    with jax.default_matmul_precision('highest'):
        ours = np.asarray(vit_model.forward(params, x, arch=arch))
        ours_logits = np.asarray(
            vit_model.forward(params, x, arch=arch, features=False))

    assert ours.shape == ref.shape == (2, cfg['width'])
    l2 = np.linalg.norm(ours - ref) / max(np.linalg.norm(ref), 1e-12)
    assert l2 < 1e-3, f'relative L2 {l2}'
    l2 = np.linalg.norm(ours_logits - ref_logits) / \
        max(np.linalg.norm(ref_logits), 1e-12)
    assert l2 < 1e-3, f'head relative L2 {l2}'


def test_state_dict_shapes_roundtrip():
    """init_state_dict must transplant into shapes forward() accepts."""
    sd = vit_model.init_state_dict(arch='vit_tiny_patch16_224')
    params = transplant(sd)
    assert params['patch_embed']['proj']['weight'].shape == (16, 16, 3, 192)
    assert params['blocks']['0']['attn']['qkv']['weight'].shape == (192, 576)
    x = np.zeros((1, 224, 224, 3), np.float32)
    out = np.asarray(vit_model.forward(params, x, 'vit_tiny_patch16_224'))
    assert out.shape == (1, 192)


def test_registry_resolution():
    from video_features_tpu.extract.timm import REGISTRY
    assert 'vit_base_patch16_224' in REGISTRY
    assert 'resnet50' in REGISTRY
    assert REGISTRY['resnet50']['family'] == 'resnet'


@pytest.mark.parametrize('model_name,family', [
    ('vit_tiny_patch16_224', 'vit'),
    ('hf_hub:timm/vit_tiny_patch16_224.augreg_in21k', 'vit'),
    ('resnet18', 'resnet'),
])
def test_e2e_extraction(short_video, tmp_path, model_name, family):
    args = load_config('timm', overrides={
        'model_name': model_name,
        'video_paths': short_video,
        'device': 'cpu',
        'batch_size': 16,
        'output_path': str(tmp_path / 'out'),
        'tmp_path': str(tmp_path / 'tmp'),
    })
    ex = create_extractor(args)
    assert ex.family == family
    out = ex.extract(short_video)
    T, D = out['timm'].shape
    assert T == 48 and D == ex.feat_dim
    assert np.isfinite(out['timm']).all()
    assert out['timestamps_ms'].shape == (T,)


def test_unknown_model_rejected(tmp_path):
    args = load_config('timm', overrides={
        'model_name': 'maxvit_tiny_tf_224',
        'video_paths': '/dev/null',
        'device': 'cpu',
        'output_path': str(tmp_path / 'out'),
        'tmp_path': str(tmp_path / 'tmp'),
    })
    with pytest.raises(NotImplementedError):
        create_extractor(args)


def test_pos_embed_interpolation_identity_and_resample():
    import jax.numpy as jnp

    pos = np.random.RandomState(0).randn(1, 1 + 14 * 14, 8).astype(np.float32)
    same = vit_model.interpolate_pos_embed(jnp.asarray(pos), (14, 14))
    np.testing.assert_array_equal(np.asarray(same), pos)
    up = np.asarray(vit_model.interpolate_pos_embed(jnp.asarray(pos), (20, 20)))
    assert up.shape == (1, 1 + 20 * 20, 8)
    # cls position untouched
    np.testing.assert_array_equal(up[:, 0], pos[:, 0])


def test_vit_high_res_forward_crosses_blockwise_threshold():
    """352px at patch16 → 485 tokens with an interpolated pos embed; with
    the threshold dropped the same input runs the blockwise (ragged) path
    and must match the dense result — the high-res production consumer of
    blockwise attention."""
    import video_features_tpu.models.vit as vit

    arch = 'vit_tiny_patch16_224'
    params = transplant(vit_model.init_state_dict(arch=arch))
    x = np.random.RandomState(0).rand(1, 352, 352, 3).astype(np.float32)

    dense = np.asarray(vit_model.forward(params, x, arch=arch))
    assert dense.shape == (1, 192)
    old = vit.BLOCKWISE_THRESHOLD
    try:
        vit.BLOCKWISE_THRESHOLD = 256  # force the long-token path
        block = np.asarray(vit_model.forward(params, x, arch=arch))
    finally:
        vit.BLOCKWISE_THRESHOLD = old
    np.testing.assert_allclose(block, dense, atol=2e-4)


def test_timm_image_size_must_divide_patch(tmp_path):
    args = load_config('timm', overrides={
        'video_paths': 'v.mp4', 'device': 'cpu',
        'model_name': 'vit_tiny_patch16_224', 'image_size': 350,
        'allow_random_weights': True,
        'output_path': str(tmp_path / 'o'), 'tmp_path': str(tmp_path / 't'),
    })
    with pytest.raises(ValueError, match='multiple of the patch'):
        create_extractor(args)


@pytest.mark.slow
def test_convnext_parity_vs_torch_mirror():
    """ConvNeXt numerics vs a state-dict-compatible timm mirror (depthwise
    7x7 → LN → MLP → layer scale; stem + downsample LayerNorm2d)."""
    import jax

    from tests.torch_mirrors import TorchConvNeXt
    from video_features_tpu.models import convnext as convnext_model

    torch.manual_seed(0)
    mirror = TorchConvNeXt('convnext_tiny').eval()
    params = transplant(mirror.state_dict())

    x = np.random.RandomState(1).rand(2, 96, 96, 3).astype(np.float32) * 2 - 1
    with torch.no_grad():
        xt = torch.from_numpy(x).permute(0, 3, 1, 2)
        ref = mirror(xt).numpy()
        ref_logits = mirror(xt, features=False).numpy()
    with jax.default_matmul_precision('highest'):
        got = np.asarray(convnext_model.forward(params, x,
                                                arch='convnext_tiny'))
        got_logits = np.asarray(convnext_model.forward(
            params, x, arch='convnext_tiny', features=False))

    for ours, theirs in ((got, ref), (got_logits, ref_logits)):
        rel = np.linalg.norm(ours - theirs) / np.linalg.norm(theirs)
        assert rel < 1e-3, f'rel L2 {rel}'


def test_registry_covers_deit_and_convnext(tmp_path):
    from video_features_tpu.extract.timm import REGISTRY
    assert REGISTRY['deit_base_patch16_224']['family'] == 'deit'
    assert REGISTRY['deit_base_patch16_224']['arch'] == 'vit_base_patch16_224'
    assert REGISTRY['convnext_tiny']['feat_dim'] == 768
    # deit data config: ImageNet stats (not vit's 0.5), crop_pct 0.9;
    # pretrained=False keeps the test hermetic when pip timm is installed
    args = load_config('timm', overrides={
        'video_paths': 'v.mp4', 'device': 'cpu', 'pretrained': False,
        'model_name': 'deit_tiny_patch16_224', 'allow_random_weights': True,
        'output_path': str(tmp_path / 'o'), 'tmp_path': str(tmp_path / 't'),
    })
    ex = create_extractor(args)
    assert ex.data_cfg['resize'] == 248
    assert abs(ex.data_cfg['mean'][0] - 0.485) < 1e-6


@pytest.mark.slow
def test_convnext_extractor_e2e(short_video, tmp_path):
    args = load_config('timm', overrides={
        'video_paths': short_video, 'device': 'cpu', 'batch_size': 16,
        'model_name': 'convnext_tiny', 'allow_random_weights': True,
        'extraction_fps': 2,
        'output_path': str(tmp_path / 'o'), 'tmp_path': str(tmp_path / 't'),
    })
    out = create_extractor(args).extract(short_video)
    assert out['timm'].shape[1] == 768
    assert out['timm'].shape[0] > 0
    assert np.isfinite(out['timm']).all()


@pytest.mark.slow
def test_pip_timm_bridge_end_to_end(short_video, tmp_path):
    """The reference's native path: any pip-timm model by hf-hub id
    (reference tests/timm/test_timm.py:24). Runs only where timm (and its
    pretrained weights) are available — exercised in the timm CI lane."""
    pytest.importorskip('timm')
    args = load_config('timm', overrides={
        'video_paths': short_video, 'device': 'cpu', 'batch_size': 16,
        'model_name': 'hf_hub:timm/vit_tiny_patch16_224.augreg_in21k',
        'extraction_fps': 1,
        'output_path': str(tmp_path / 'o'), 'tmp_path': str(tmp_path / 't'),
    })
    out = create_extractor(args).extract(short_video)
    assert out['timm'].shape[1] == 192
    assert np.isfinite(out['timm']).all()


def test_swin_parity_vs_torch_mirror():
    """Swin numerics vs the timm-0.9.12-layout mirror: windowed attention
    with relative position bias, SHIFTED windows with the -100 additive
    mask (blocks 1,3,...), stage-start PatchMerging, NHWC final norm+pool.
    192px input makes stage maps (48,24,12,6): stage-3 maps smaller than
    the window exercise the window-collapse rule, and stage-2 exercises
    the real shift mask."""
    import jax

    from tests.torch_mirrors import TorchSwin
    from video_features_tpu.models import swin as swin_model

    torch.manual_seed(0)
    mirror = TorchSwin('swin_tiny_patch4_window7_224', num_classes=5,
                       img_size=192).eval()
    params = transplant(mirror.state_dict())

    x = np.random.RandomState(1).rand(2, 192, 192, 3).astype(np.float32) * 2 - 1
    with torch.no_grad():
        xt = torch.from_numpy(x).permute(0, 3, 1, 2)
        ref_logits = mirror(xt).numpy()
        mirror.head.fc = torch.nn.Identity()
        ref = mirror(xt).numpy()
    with jax.default_matmul_precision('highest'):
        got = np.asarray(swin_model.forward(
            params, x, arch='swin_tiny_patch4_window7_224'))
        got_logits = np.asarray(swin_model.forward(
            params, x, arch='swin_tiny_patch4_window7_224', features=False))

    assert got.shape == ref.shape == (2, 768)
    for ours, theirs in ((got, ref), (got_logits, ref_logits)):
        rel = np.linalg.norm(ours - theirs) / np.linalg.norm(theirs)
        assert rel < 1e-3, f'rel L2 {rel}'


def test_swin_state_dict_keys_match_mirror():
    """init_state_dict emits exactly the timm persistent key set (the
    non-persistent index/mask buffers excluded) so real checkpoints load
    into the same tree."""
    from tests.torch_mirrors import TorchSwin
    from video_features_tpu.models import swin as swin_model

    ours = set(swin_model.init_state_dict('swin_small_patch4_window7_224'))
    theirs = set(TorchSwin('swin_small_patch4_window7_224').state_dict())
    theirs = {k for k in theirs if 'relative_position_index' not in k}
    assert ours == theirs


@pytest.mark.slow
def test_swin_extractor_e2e(short_video, tmp_path):
    args = load_config('timm', overrides={
        'video_paths': short_video, 'device': 'cpu', 'batch_size': 16,
        'model_name': 'swin_tiny_patch4_window7_224',
        'allow_random_weights': True, 'extraction_fps': 2,
        'output_path': str(tmp_path / 'o'), 'tmp_path': str(tmp_path / 't'),
    })
    ex = create_extractor(args)
    assert ex.data_cfg['resize'] == 248
    out = ex.extract(short_video)
    assert out['timm'].shape[1] == 768
    assert out['timm'].shape[0] > 0
    assert np.isfinite(out['timm']).all()


def test_efficientnet_parity_vs_torch_mirror():
    """EfficientNet numerics vs the timm-layout mirror: depthwise convs
    (feature_group_count), squeeze-excite gating, SiLU, inverted residuals,
    stage-0 depthwise-separable blocks."""
    import jax

    from tests.torch_mirrors import TorchEfficientNet
    from video_features_tpu.models import efficientnet as eff_model

    torch.manual_seed(0)
    mirror = TorchEfficientNet('efficientnet_b0', num_classes=5).eval()
    # randomize BN running stats so batch_norm parity is actually exercised
    from tests.torch_mirrors import randomize_bn_stats
    randomize_bn_stats(mirror)
    params = transplant(mirror.state_dict())

    x = np.random.RandomState(1).rand(2, 224, 224, 3).astype(np.float32) * 2 - 1
    with torch.no_grad():
        xt = torch.from_numpy(x).permute(0, 3, 1, 2)
        ref_logits = mirror(xt).numpy()
        mirror.classifier = torch.nn.Identity()
        ref = mirror(xt).numpy()
    with jax.default_matmul_precision('highest'):
        got = np.asarray(eff_model.forward(params, x,
                                           arch='efficientnet_b0'))
        got_logits = np.asarray(eff_model.forward(
            params, x, arch='efficientnet_b0', features=False))

    assert got.shape == ref.shape == (2, 1280)
    for ours, theirs in ((got, ref), (got_logits, ref_logits)):
        rel = np.linalg.norm(ours - theirs) / np.linalg.norm(theirs)
        assert rel < 1e-3, f'rel L2 {rel}'


def test_efficientnet_state_dict_keys_match_mirror():
    from tests.torch_mirrors import TorchEfficientNet
    from video_features_tpu.models import efficientnet as eff_model

    for arch in ('efficientnet_b0', 'efficientnet_b1'):
        ours = set(eff_model.init_state_dict(arch))
        theirs = {k for k in TorchEfficientNet(arch).state_dict()
                  if not k.endswith('num_batches_tracked')}
        assert ours == theirs, arch


@pytest.mark.slow
def test_efficientnet_extractor_e2e(short_video, tmp_path):
    args = load_config('timm', overrides={
        'video_paths': short_video, 'device': 'cpu', 'batch_size': 16,
        'model_name': 'efficientnet_b1',
        'allow_random_weights': True, 'extraction_fps': 2,
        'output_path': str(tmp_path / 'o'), 'tmp_path': str(tmp_path / 't'),
    })
    ex = create_extractor(args)
    assert ex.data_cfg['crop'] == 240            # b1's native resolution
    out = ex.extract(short_video)
    assert out['timm'].shape[1] == 1280
    assert out['timm'].shape[0] > 0
    assert np.isfinite(out['timm']).all()


class _TorchDeiTDistilled(_TorchViT):
    """timm VisionTransformerDistilled mirror: dist_token + head_dist,
    2-slot pos-embed prefix, inference = mean of the two tokens/heads."""

    def __init__(self, width, layers, heads, patch, img=224):
        super().__init__(width, layers, heads, patch, img)
        self.dist_token = nn.Parameter(torch.randn(1, 1, width) * 0.02)
        self.pos_embed = nn.Parameter(
            torch.randn(1, 2 + (img // patch) ** 2, width) * 0.02)
        self.head_dist = nn.Linear(width, 1000)

    def forward(self, x, features=True):
        B = x.shape[0]
        x = self.patch_embed.proj(x).flatten(2).transpose(1, 2)
        x = torch.cat([self.cls_token.expand(B, -1, -1),
                       self.dist_token.expand(B, -1, -1), x], 1)
        x = x + self.pos_embed
        for blk in self.blocks:
            x = blk(x)
        x = self.norm(x)
        if features:
            return (x[:, 0] + x[:, 1]) / 2
        return (self.head(x[:, 0]) + self.head_dist(x[:, 1])) / 2


def test_deit_distilled_parity_vs_torch_mirror():
    """Distilled DeiT: the dist_token rides the checkpoint — our forward
    dispatches on its presence (features = mean of cls/dist tokens,
    logits = mean of the two heads, timm deit.py semantics)."""
    import jax

    arch = 'vit_tiny_patch16_224'
    cfg = vit_model.ARCHS[arch]
    torch.manual_seed(0)
    ref_model = _TorchDeiTDistilled(cfg['width'], cfg['layers'],
                                    cfg['heads'], cfg['patch']).eval()
    params = transplant(ref_model.state_dict())

    rng = np.random.RandomState(0)
    x = rng.rand(2, 224, 224, 3).astype(np.float32)
    with torch.no_grad():
        xt = torch.from_numpy(x).permute(0, 3, 1, 2)
        ref = ref_model(xt).numpy()
        ref_logits = ref_model(xt, features=False).numpy()
    with jax.default_matmul_precision('highest'):
        ours = np.asarray(vit_model.forward(params, x, arch=arch))
        ours_logits = np.asarray(
            vit_model.forward(params, x, arch=arch, features=False))

    for a, b in ((ours, ref), (ours_logits, ref_logits)):
        rel = np.linalg.norm(a - b) / np.linalg.norm(b)
        assert rel < 1e-3, f'rel L2 {rel}'


def test_deit_distilled_registry_and_random_init(tmp_path):
    from video_features_tpu.extract.timm import REGISTRY
    spec = REGISTRY['deit_tiny_distilled_patch16_224']
    assert spec['family'] == 'deit' and spec['init'] == {'distilled': True}
    args = load_config('timm', overrides={
        'video_paths': 'v.mp4', 'device': 'cpu', 'pretrained': False,
        'model_name': 'deit_tiny_distilled_patch16_224',
        'allow_random_weights': True,
        'output_path': str(tmp_path / 'o'), 'tmp_path': str(tmp_path / 't'),
    })
    ex = create_extractor(args)
    assert 'dist_token' in ex.params          # distilled graph selected


@pytest.mark.slow
def test_swin_high_res_extractor(short_video, tmp_path):
    """image_size works for swin: windows/masks derive from the runtime
    feature size (stage maps 64->32->16->8 at 256px; stage 3 gets real
    8>7 windows + shift where 224px collapses it), no pos-embed resample
    needed (relative bias is window-local)."""
    args = load_config('timm', overrides={
        'video_paths': short_video, 'device': 'cpu', 'batch_size': 8,
        'model_name': 'swin_tiny_patch4_window7_224', 'image_size': 256,
        'allow_random_weights': True, 'extraction_fps': 1,
        'output_path': str(tmp_path / 'o'), 'tmp_path': str(tmp_path / 't'),
    })
    ex = create_extractor(args)
    assert ex.data_cfg['crop'] == 256
    out = ex.extract(short_video)
    assert out['timm'].shape[1] == 768
    assert np.isfinite(out['timm']).all()


@pytest.mark.parametrize('arch,width', [('regnety_008', 768),
                                        ('regnetx_008', 672)])
def test_regnet_parity_vs_torch_mirror(arch, width):
    """RegNet numerics vs the timm-layout mirror: per-stage grouped 3x3
    convs (group-width-tied feature_group_count), squeeze-excite sized
    from the block INPUT width (y variants; x variants dispatch SE off the
    checkpoint), no-act conv3 + post-sum ReLU, stride-2 projection
    downsample on every stage's first block."""
    import jax

    from tests.torch_mirrors import TorchRegNet, randomize_bn_stats
    from video_features_tpu.models import regnet as regnet_model

    torch.manual_seed(0)
    mirror = TorchRegNet(arch, num_classes=5).eval()
    randomize_bn_stats(mirror)
    params = transplant(mirror.state_dict())

    x = np.random.RandomState(1).rand(2, 224, 224, 3).astype(np.float32) * 2 - 1
    with torch.no_grad():
        xt = torch.from_numpy(x).permute(0, 3, 1, 2)
        ref_logits = mirror(xt).numpy()
        mirror.head.fc = torch.nn.Identity()
        ref = mirror(xt).numpy()
    with jax.default_matmul_precision('highest'):
        got = np.asarray(regnet_model.forward(params, x, arch=arch))
        got_logits = np.asarray(regnet_model.forward(
            params, x, arch=arch, features=False))

    assert got.shape == ref.shape == (2, width)
    for ours, theirs in ((got, ref), (got_logits, ref_logits)):
        rel = np.linalg.norm(ours - theirs) / np.linalg.norm(theirs)
        assert rel < 1e-3, f'rel L2 {rel}'


def test_regnet_state_dict_keys_match_mirror():
    from tests.torch_mirrors import TorchRegNet
    from video_features_tpu.models import regnet as regnet_model

    for arch in regnet_model.ARCHS:
        ours = set(regnet_model.init_state_dict(arch))
        theirs = {k for k in TorchRegNet(arch).state_dict()
                  if not k.endswith('num_batches_tracked')}
        assert ours == theirs, arch


@pytest.mark.slow
def test_regnet_extractor_e2e(short_video, tmp_path):
    args = load_config('timm', overrides={
        'video_paths': short_video, 'device': 'cpu', 'batch_size': 16,
        'model_name': 'regnety_004',
        'allow_random_weights': True, 'extraction_fps': 2,
        'output_path': str(tmp_path / 'o'), 'tmp_path': str(tmp_path / 't'),
    })
    ex = create_extractor(args)
    assert ex.data_cfg['interpolation'] == 'bicubic'
    out = ex.extract(short_video)
    assert out['timm'].shape[1] == 440
    assert out['timm'].shape[0] > 0
    assert np.isfinite(out['timm']).all()


@pytest.mark.parametrize('arch', ['mobilenetv3_large_100',
                                  'mobilenetv3_small_100'])
def test_mobilenetv3_parity_vs_torch_mirror(arch):
    """MobileNetV3 numerics vs the timm-layout mirror: per-block ReLU vs
    hard-swish switching, hard-sigmoid-gated SE on only some stages, the
    post-pool biased head conv, and (small_100) a stride-2 SE'd
    depthwise-separable stage 0."""
    import jax

    from tests.torch_mirrors import TorchMobileNetV3, randomize_bn_stats
    from video_features_tpu.models import mobilenetv3 as mnv3_model

    torch.manual_seed(0)
    mirror = TorchMobileNetV3(arch, num_classes=5).eval()
    randomize_bn_stats(mirror)
    params = transplant(mirror.state_dict())

    x = np.random.RandomState(1).rand(2, 224, 224, 3).astype(np.float32) * 2 - 1
    with torch.no_grad():
        xt = torch.from_numpy(x).permute(0, 3, 1, 2)
        ref_logits = mirror(xt).numpy()
        mirror.classifier = torch.nn.Identity()
        ref = mirror(xt).numpy()
    with jax.default_matmul_precision('highest'):
        got = np.asarray(mnv3_model.forward(params, x, arch=arch))
        got_logits = np.asarray(mnv3_model.forward(
            params, x, arch=arch, features=False))

    assert got.shape == ref.shape == (2, mnv3_model.feat_dim(arch))
    for ours, theirs in ((got, ref), (got_logits, ref_logits)):
        rel = np.linalg.norm(ours - theirs) / np.linalg.norm(theirs)
        assert rel < 1e-3, f'{arch}: rel L2 {rel}'


def test_mobilenetv3_state_dict_keys_match_mirror():
    from tests.torch_mirrors import TorchMobileNetV3
    from video_features_tpu.models import mobilenetv3 as mnv3_model

    for arch in mnv3_model.ARCHS:
        ours = set(mnv3_model.init_state_dict(arch))
        theirs = {k for k in TorchMobileNetV3(arch).state_dict()
                  if not k.endswith('num_batches_tracked')}
        assert ours == theirs, arch


@pytest.mark.slow
def test_mobilenetv3_extractor_e2e(short_video, tmp_path):
    args = load_config('timm', overrides={
        'video_paths': short_video, 'device': 'cpu', 'batch_size': 16,
        'model_name': 'mobilenetv3_large_100',
        'allow_random_weights': True, 'extraction_fps': 2,
        'output_path': str(tmp_path / 'o'), 'tmp_path': str(tmp_path / 't'),
    })
    ex = create_extractor(args)
    out = ex.extract(short_video)
    assert out['timm'].shape[1] == 1280
    assert out['timm'].shape[0] > 0
    assert np.isfinite(out['timm']).all()


def test_beit_parity_vs_torch_mirror():
    """BEiT numerics vs the timm-layout mirror: per-block relative
    position bias (732-row table + cls rows), q/v-only qkv biases, gamma
    layer scale, no absolute pos embed, fc_norm mean pooling."""
    import jax

    from tests.torch_mirrors import TorchBeit
    from video_features_tpu.models import beit as beit_model

    torch.manual_seed(0)
    mirror = TorchBeit('beit_base_patch16_224', num_classes=5).eval()
    params = transplant(mirror.state_dict())

    x = np.random.RandomState(1).rand(2, 224, 224, 3).astype(np.float32) * 2 - 1
    with torch.no_grad():
        xt = torch.from_numpy(x).permute(0, 3, 1, 2)
        ref_logits = mirror(xt).numpy()
        mirror.head = torch.nn.Identity()
        ref = mirror(xt).numpy()
    with jax.default_matmul_precision('highest'):
        got = np.asarray(beit_model.forward(
            params, x, arch='beit_base_patch16_224'))
        got_logits = np.asarray(beit_model.forward(
            params, x, arch='beit_base_patch16_224', features=False))

    assert got.shape == ref.shape == (2, 768)
    for ours, theirs in ((got, ref), (got_logits, ref_logits)):
        rel = np.linalg.norm(ours - theirs) / np.linalg.norm(theirs)
        assert rel < 1e-3, f'rel L2 {rel}'


def test_beit_state_dict_keys_match_mirror():
    from tests.torch_mirrors import TorchBeit
    from video_features_tpu.models import beit as beit_model

    for arch in beit_model.ARCHS:
        ours = set(beit_model.init_state_dict(arch))
        theirs = set(TorchBeit(arch).state_dict())
        assert ours == theirs, arch


def test_beit_rejects_image_size(tmp_path):
    args = load_config('timm', overrides={
        'video_paths': '/dev/null', 'device': 'cpu',
        'model_name': 'beit_base_patch16_224', 'image_size': 384,
        'allow_random_weights': True,
        'output_path': str(tmp_path / 'o'), 'tmp_path': str(tmp_path / 't'),
    })
    with pytest.raises(NotImplementedError, match='relative-position'):
        create_extractor(args)


@pytest.mark.slow
def test_beit_extractor_e2e(short_video, tmp_path):
    args = load_config('timm', overrides={
        'video_paths': short_video, 'device': 'cpu', 'batch_size': 8,
        'model_name': 'beit_base_patch16_224',
        'allow_random_weights': True, 'extraction_fps': 2,
        'output_path': str(tmp_path / 'o'), 'tmp_path': str(tmp_path / 't'),
    })
    ex = create_extractor(args)
    out = ex.extract(short_video)
    assert out['timm'].shape[1] == 768
    assert out['timm'].shape[0] > 0
    assert np.isfinite(out['timm']).all()


def test_mixer_parity_vs_torch_mirror():
    """MLP-Mixer numerics vs the timm-layout mirror: token-mixing MLP over
    the transposed patch axis (attention-free), channel MLP, mean-token
    pooling after the final norm."""
    import jax

    from tests.torch_mirrors import TorchMixer
    from video_features_tpu.models import mixer as mixer_model

    torch.manual_seed(0)
    mirror = TorchMixer('mixer_b16_224', num_classes=5).eval()
    params = transplant(mirror.state_dict())

    x = np.random.RandomState(1).rand(2, 224, 224, 3).astype(np.float32) * 2 - 1
    with torch.no_grad():
        xt = torch.from_numpy(x).permute(0, 3, 1, 2)
        ref_logits = mirror(xt).numpy()
        mirror.head = torch.nn.Identity()
        ref = mirror(xt).numpy()
    with jax.default_matmul_precision('highest'):
        got = np.asarray(mixer_model.forward(params, x, arch='mixer_b16_224'))
        got_logits = np.asarray(mixer_model.forward(
            params, x, arch='mixer_b16_224', features=False))

    assert got.shape == ref.shape == (2, 768)
    for ours, theirs in ((got, ref), (got_logits, ref_logits)):
        rel = np.linalg.norm(ours - theirs) / np.linalg.norm(theirs)
        assert rel < 1e-3, f'rel L2 {rel}'


def test_mixer_state_dict_keys_match_mirror():
    from tests.torch_mirrors import TorchMixer
    from video_features_tpu.models import mixer as mixer_model

    for arch in mixer_model.ARCHS:
        ours = set(mixer_model.init_state_dict(arch))
        theirs = set(TorchMixer(arch).state_dict())
        assert ours == theirs, arch


@pytest.mark.slow
def test_mixer_extractor_e2e(short_video, tmp_path):
    args = load_config('timm', overrides={
        'video_paths': short_video, 'device': 'cpu', 'batch_size': 8,
        'model_name': 'mixer_b16_224',
        'allow_random_weights': True, 'extraction_fps': 2,
        'output_path': str(tmp_path / 'o'), 'tmp_path': str(tmp_path / 't'),
    })
    ex = create_extractor(args)
    out = ex.extract(short_video)
    assert out['timm'].shape[1] == 768
    assert out['timm'].shape[0] > 0
    assert np.isfinite(out['timm']).all()
