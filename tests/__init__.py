"""Regular package marker.

Required: parity tests put /root/reference on sys.path, and the reference
repo's own ``tests`` directory is a regular package — without this
__init__.py ours would be a namespace portion, and regular packages beat
namespace portions regardless of sys.path order, silently shadowing
``tests.torch_mirrors`` / ``tests.reference_pipeline``.
"""
