"""I3D extractor: rgb-only E2E + the fused two-stream device step."""
from pathlib import Path

import numpy as np
import pytest

from video_features_tpu.config import load_config
from video_features_tpu.registry import create_extractor


@pytest.mark.slow
def test_e2e_rgb_only(short_video, tmp_path):
    args = load_config('i3d', overrides={
        'video_paths': short_video,
        'device': 'cpu',
        'streams': 'rgb',
        'stack_size': 16, 'step_size': 16,
        'on_extraction': 'save_numpy',
        'output_path': str(tmp_path / 'out'),
        'tmp_path': str(tmp_path / 'tmp'),
    })
    ex = create_extractor(args)
    feats = ex.extract(short_video)
    # 48 frames -> windows of 17: (48-17)//16+1 = 2 stacks
    assert feats['rgb'].shape == (2, 1024)
    assert np.isfinite(feats['rgb']).all()

    # single-stream extraction must not attempt the concat (fork bug fixed)
    ex._extract(short_video)
    from pathlib import Path
    stem = Path(short_video).stem
    assert (tmp_path / 'out' / 'i3d' / f'{stem}.npy').exists()


@pytest.mark.slow
def test_fused_two_stream_step():
    """The flagship fused graph: stacks → RAFT flow → both I3D towers."""
    args = load_config('i3d', overrides={
        'video_paths': ['/dev/null'], 'device': 'cpu',
        'stack_size': 10, 'step_size': 10,
    }, run_sanity_check=False)
    args['output_path'] = '/tmp/i3d_out'
    args['tmp_path'] = '/tmp/i3d_tmp'
    args['device'] = 'cpu'
    ex = create_extractor(args)

    rng = np.random.RandomState(0)
    stacks = rng.randint(0, 256, (1, 11, 224, 224, 3)).astype(np.float32)
    import jax
    with jax.default_matmul_precision('highest'):
        out = ex._step(ex.params, stacks, pads=(0, 0, 0, 0),
                       streams=('rgb', 'flow'))
    assert np.asarray(out['rgb']).shape == (1, 1024)
    assert np.asarray(out['flow']).shape == (1, 1024)
    assert np.isfinite(np.asarray(out['rgb'])).all()
    assert np.isfinite(np.asarray(out['flow'])).all()

    # concat contract: rgb||flow under 'rgb'
    merged = ex._maybe_concat_streams(
        {k: np.asarray(v) for k, v in out.items()})
    assert merged['rgb'].shape == (1, 2048)
    assert 'flow' not in merged


@pytest.mark.parametrize('stack,step,total', [
    (16, 16, 48),   # contiguous windows
    (16, 8, 50),    # overlapping windows
    (10, 24, 100),  # gaps between windows (step > stack+1)
    (16, 16, 10),   # too short: zero windows
])
def test_stream_windows_matches_form_slices(stack, step, total):
    """Streaming windower == form_slices over the fully-decoded video."""
    import numpy as np

    from video_features_tpu.extract.i3d import ExtractI3D
    from video_features_tpu.utils.slicing import form_slices
    from video_features_tpu.utils.tracing import NULL_TRACER

    ex = ExtractI3D.__new__(ExtractI3D)
    ex.stack_size, ex.step_size, ex.tracer = stack, step, NULL_TRACER

    frames = [np.full((2, 2, 3), i, np.float32) for i in range(total)]
    # decoder yields ragged batches to exercise buffer bookkeeping
    batches, i = [], 0
    for n in ([7, 13, 1, 64] * 10):
        if i >= total:
            break
        batches.append((frames[i:i + n], None, None))
        i += n

    got = list(ex._stream_windows(batches))
    want = [np.stack(frames[s:e])
            for s, e in form_slices(total, stack + 1, step)]
    assert len(got) == len(want)
    for g, w in zip(got, want):
        np.testing.assert_array_equal(g, w)


def test_show_pred_covers_both_streams(capsys, tmp_path):
    """Reference parity: the classifier head prints top-5 for EVERY stream
    (reference extract_i3d.py:212-216), flow included; headless flow viz
    preserves the cv2-window artifact as a PNG (base_flow_extractor.py:
    134-149)."""
    import jax
    jax.config.update('jax_platforms', 'cpu')
    import numpy as np

    from video_features_tpu.extract.i3d import ExtractI3D
    from video_features_tpu.models import i3d as i3d_model
    from video_features_tpu.models import raft as raft_model
    from video_features_tpu.transplant.torch2jax import transplant

    ex = ExtractI3D.__new__(ExtractI3D)
    ex.streams = ['rgb', 'flow']
    ex.output_path = str(tmp_path / 'out')
    ex._device = jax.devices('cpu')[0]
    ex.params = {
        'rgb': transplant(i3d_model.init_state_dict(modality='rgb')),
        'flow': transplant(i3d_model.init_state_dict(modality='flow')),
        'raft': transplant(raft_model.init_state_dict()),
    }
    stacks = np.random.RandomState(0).randint(
        0, 255, (1, 11, 64, 64, 3)).astype(np.float32)
    with jax.default_matmul_precision('highest'):
        ex.maybe_show_pred(stacks, (0, 0, 0, 0), stack_counter=0)
    out = capsys.readouterr().out
    assert 'At stack 0 (rgb stream)' in out
    assert 'At stack 0 (flow stream)' in out
    assert out.count('Logits') == 2
    pngs = list((tmp_path / 'out' / 'flow_debug').glob('*.png'))
    assert pngs, 'flow stream show_pred must write the rendered flow PNG'


@pytest.mark.slow
def test_e2e_two_stream_with_flow(short_video, tmp_path):
    """Full flagship path on a real clip: decode → windows → RAFT flow →
    both I3D towers → concat (T, 2048) under the 'rgb' key (fork naming)."""
    args = load_config('i3d', overrides={
        'video_paths': short_video,
        'device': 'cpu',
        'stack_size': 16, 'step_size': 16,   # 48-frame clip -> 2 windows
        'concat_rgb_flow': True,
        'on_extraction': 'save_numpy',
        'output_path': str(tmp_path / 'out'),
        'tmp_path': str(tmp_path / 'tmp'),
    })
    ex = create_extractor(args)
    ex._extract(short_video)

    stem = Path(short_video).stem
    saved = np.load(tmp_path / 'out' / 'i3d' / f'{stem}.npy')
    assert saved.shape == (2, 2048)          # rgb || flow concat
    assert np.isfinite(saved).all()
    # the two halves come from different towers: they must differ
    assert not np.allclose(saved[:, :1024], saved[:, 1024:])
