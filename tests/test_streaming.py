"""Unit tests for the shared streaming helpers: window batching semantics
(tail padding, valid counts, window indices), the producer-thread
transfer pipeline (ordering, keep_host, passthrough put), and the
deferred-D2H fetch window (overlap_fetch)."""
import numpy as np

from video_features_tpu.extract.streaming import (
    iter_batched_windows, overlap_fetch, stream_windows, transfer_batches,
)


def test_overlap_fetch_defers_by_depth_and_preserves_order():
    """At depth k the oldest dispatch is fetched only once k items are in
    flight; results come back in dispatch order with meta intact, and
    the tail drains at stream end. depth=1 is strictly alternating
    (synchronous)."""
    events = []

    def dispatched(n):
        for i in range(n):
            events.append(('dispatch', i))
            yield f'dev{i}', i * 10

    def fetch(dev):
        i = int(dev[3:])
        events.append(('fetch', i))
        return f'host{i}'

    out = list(overlap_fetch(dispatched(4), fetch, depth=2))
    assert out == [(f'host{i}', i * 10) for i in range(4)]
    # fetch(0) happens only after dispatch(1); fetch(3) after the stream
    assert events.index(('fetch', 0)) > events.index(('dispatch', 1))
    assert events[-1] == ('fetch', 3)

    events.clear()
    list(overlap_fetch(dispatched(3), fetch, depth=1))
    assert events == [('dispatch', 0), ('fetch', 0), ('dispatch', 1),
                      ('fetch', 1), ('dispatch', 2), ('fetch', 2)]


def test_overlap_fetch_records_d2h_stage():
    from video_features_tpu.utils.tracing import Tracer
    t = Tracer(enabled=True)
    out = list(overlap_fetch(((x,) for x in 'ab'), lambda x: x.upper(),
                             depth=3, tracer=t))
    assert out == [('A',), ('B',)]
    assert t.report()['d2h']['count'] == 2


def _windows(n, shape=(2, 3)):
    return [np.full(shape, i, np.float32) for i in range(n)]


def test_iter_batched_windows_exact_multiple():
    out = list(iter_batched_windows(iter(_windows(6)), batch=3))
    assert [(v, i) for _, v, i in out] == [(3, 0), (3, 3)]
    for stacks, _, start in out:
        assert stacks.shape == (3, 2, 3)
        np.testing.assert_array_equal(stacks[:, 0, 0],
                                      np.arange(start, start + 3))


def test_iter_batched_windows_tail_padding():
    out = list(iter_batched_windows(iter(_windows(5)), batch=3))
    assert [(v, i) for _, v, i in out] == [(3, 0), (2, 3)]
    tail = out[-1][0]
    # tail padded by repeating the last window; mask with [:valid]
    np.testing.assert_array_equal(tail[:, 0, 0], [3.0, 4.0, 4.0])


def test_iter_batched_windows_empty_and_single():
    assert list(iter_batched_windows(iter([]), batch=4)) == []
    out = list(iter_batched_windows(iter(_windows(1)), batch=4))
    assert len(out) == 1
    stacks, valid, idx = out[0]
    assert (valid, idx) == (1, 0)
    assert stacks.shape == (4, 2, 3)


def test_transfer_batches_order_and_meta():
    items = [(np.full((2,), i, np.float32), 10 * i, f'm{i}')
             for i in range(7)]
    seen_by_put = []

    def put(batch):
        seen_by_put.append(float(batch[0]))
        return batch + 1000.0  # stand-in for a device placement

    out = list(transfer_batches(iter(items), put))
    assert seen_by_put == [float(i) for i in range(7)]  # producer order
    for i, (dev, host, meta1, meta2) in enumerate(out):
        assert float(dev[0]) == 1000.0 + i
        assert host is None
        assert (meta1, meta2) == (10 * i, f'm{i}')


def test_transfer_batches_keep_host():
    items = [(np.full((2,), i, np.float32), i) for i in range(3)]
    out = list(transfer_batches(iter(items), put=lambda b: b * 0, keep_host=True))
    for i, (dev, host, meta) in enumerate(out):
        assert float(host[0]) == float(i)   # untouched host array
        assert float(dev[0]) == 0.0
        assert meta == i


def test_stream_windows_overlapping_steps():
    """step < win: overlapping windows, matching form_slices semantics."""
    frames = [np.full((1,), i, np.float32) for i in range(10)]
    batches = iter([(frames[:4], None, None), (frames[4:], None, None)])
    wins = list(stream_windows(batches, win=4, step=2))
    # starts at 0, 2, 4, 6; start 8 would need frame 11 -> dropped
    assert [int(w[0, 0]) for w in wins] == [0, 2, 4, 6]
    assert all(w.shape == (4, 1) for w in wins)
