"""vft-lint (video_features_tpu/analysis): the checker suite itself.

Two layers:

  * fixture packages with PLANTED violations, one per rule — the suite
    must catch each (and must NOT fire on the matching clean variant);
  * the live codebase: running every rule over the real package with the
    shipped (empty) baseline must be clean — this is the same gate CI's
    ``lint`` job enforces, pinned here so a tier-1 run catches a new
    violation even without the lint job.

The analyzer is pure-AST by contract: the subprocess test asserts the
CLI process never imports jax and finishes well inside the 10 s budget.
"""
import json
import subprocess
import sys
import textwrap
import time
from pathlib import Path

import pytest

from video_features_tpu.analysis import (
    Package, analyze, filter_suppressed, load_baseline, new_findings,
    run_checks, write_baseline,
)
from video_features_tpu.analysis.checks import (
    check_contract_keys, check_knob_classification,
    check_knob_registry_single_source, check_lock_order,
    check_recipe_picklable, check_spawn_purity, check_stage_vocabulary,
    check_stdout_purity, check_swallowed_exceptions,
    check_thread_discipline, check_wire_literal,
)

REPO_ROOT = Path(__file__).resolve().parent.parent
PKG_ROOT = REPO_ROOT / 'video_features_tpu'


def make_pkg(tmp_path, files, name='fixpkg', tests=None):
    root = tmp_path / name
    root.mkdir(exist_ok=True)
    (root / '__init__.py').write_text('')
    for rel, src in files.items():
        p = root / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
        init = p.parent / '__init__.py'
        if not init.exists():
            init.write_text('')
    tests_dir = None
    if tests:
        tests_dir = tmp_path / 'tests'
        tests_dir.mkdir(exist_ok=True)
        for fname, src in tests.items():
            (tests_dir / fname).write_text(textwrap.dedent(src))
    return Package(root, name, tests_dir=tests_dir)


def rules_of(findings):
    return {f.rule for f in findings}


# -- spawn-purity ------------------------------------------------------------

def test_spawn_purity_detects_planted_jax(tmp_path):
    pkg = make_pkg(tmp_path, {
        'farm/worker.py': '''
            def worker_main():
                from fixpkg.io.video import load
        ''',
        'io/video.py': '''
            import numpy as np
            import jax

            def load():
                return np.zeros(1)
        ''',
    })
    findings = check_spawn_purity(pkg)
    assert len(findings) == 1
    assert findings[0].file == 'io/video.py'
    assert 'jax' in findings[0].key


def test_spawn_purity_allows_gated_function_level_jax(tmp_path):
    pkg = make_pkg(tmp_path, {
        'farm/worker.py': '''
            from fixpkg.utils.tracing import trace
        ''',
        'utils/tracing.py': '''
            def trace():
                import jax      # gated: never runs in a worker
                return jax
        ''',
    })
    assert check_spawn_purity(pkg) == []


def test_spawn_purity_class_body_import_is_module_level(tmp_path):
    # class bodies execute at module import time: a jax import hidden in
    # one runs in every spawned worker and must be flagged
    pkg = make_pkg(tmp_path, {
        'ops/host_transforms.py': '''
            class Helper:
                import jax
        ''',
    })
    findings = check_spawn_purity(pkg)
    assert len(findings) == 1 and 'jax' in findings[0].key


def test_spawn_purity_resolves_relative_imports(tmp_path):
    # `from ..io import video` must expand the closure, not silently
    # shrink it (a dropped edge would blind the rule)
    pkg = make_pkg(tmp_path, {
        'farm/recipes.py': '''
            from ..io import video
        ''',
        'io/video.py': '''
            import jax
        ''',
    })
    findings = check_spawn_purity(pkg)
    assert len(findings) == 1
    assert findings[0].file == 'io/video.py'


def test_spawn_purity_relative_import_in_package_init(tmp_path):
    # `from . import transforms` in ops/__init__.py resolves against
    # ops ITSELF (a package), not its parent — getting this wrong drops
    # the edge and silently blinds the rule
    pkg = make_pkg(tmp_path, {
        'farm/worker.py': '''
            import fixpkg.ops
        ''',
        'ops/__init__.py': '''
            from . import transforms
        ''',
        'ops/transforms.py': '''
            import jax
        ''',
    })
    findings = check_spawn_purity(pkg)
    assert len(findings) == 1
    assert findings[0].file == 'ops/transforms.py'


def test_spawn_purity_deep_lazy_imports_do_not_expand_closure(tmp_path):
    # a non-root closure module lazily importing a jax-heavy module is
    # the package's gating idiom, not part of the spawn footprint
    pkg = make_pkg(tmp_path, {
        'farm/recipes.py': '''
            def open_video():
                from fixpkg.streaming import windows
        ''',
        'streaming.py': '''
            def other_path():
                from fixpkg.heavy import step
        ''',
        'heavy.py': '''
            import jax
        ''',
    })
    assert check_spawn_purity(pkg) == []


# -- recipe-picklable --------------------------------------------------------

def test_recipe_picklable_flags_lambda_in_init(tmp_path):
    pkg = make_pkg(tmp_path, {
        'farm/recipes.py': '''
            class StackRecipe:
                def __init__(self, size):
                    self.transform = lambda f: f[:size]
        ''',
    })
    findings = check_recipe_picklable(pkg)
    assert rules_of(findings) == {'recipe-picklable'}
    assert findings[0].key == 'init:StackRecipe'


def test_recipe_picklable_flags_lambda_at_call_site(tmp_path):
    pkg = make_pkg(tmp_path, {
        'farm/recipes.py': '''
            class StackRecipe:
                def __init__(self, transform):
                    self.transform = transform
        ''',
        'extract/i3d.py': '''
            from fixpkg.farm.recipes import StackRecipe

            def farm_recipe():
                return StackRecipe(transform=lambda f: f)
        ''',
    })
    findings = check_recipe_picklable(pkg)
    assert any(f.file == 'extract/i3d.py' for f in findings)


def test_recipe_picklable_allows_spec_fields_and_open_closures(tmp_path):
    # nested defs in open() run AFTER unpickling, worker-side — legal
    pkg = make_pkg(tmp_path, {
        'farm/recipes.py': '''
            class StackRecipe:
                def __init__(self, spec):
                    self.spec = tuple(spec)

                def open(self, path):
                    def windows():
                        yield path
                    return {}, windows()
        ''',
    })
    assert check_recipe_picklable(pkg) == []


# -- knob-classification -----------------------------------------------------

_CLEAN_CONFIG = '''
    KNOB_CLASSIFICATION = {
        'foo_knob': 'neither',
    }

    FOO_DEFAULTS = {'foo_knob': 1}

    def knob_exclude(axis):
        return frozenset()

    def sanity_check(args):
        if args.get('foo_knob') is not None:
            args['foo_knob'] = int(args['foo_knob'])
'''


def test_knob_classification_clean_fixture(tmp_path):
    pkg = make_pkg(tmp_path, {'config.py': _CLEAN_CONFIG})
    assert check_knob_classification(pkg) == []


def test_knob_classification_flags_unclassified_and_unvalidated(tmp_path):
    pkg = make_pkg(tmp_path, {'config.py': '''
        KNOB_CLASSIFICATION = {}

        FOO_DEFAULTS = {'foo_knob': 1}

        def sanity_check(args):
            pass
    '''})
    keys = {f.key for f in check_knob_classification(pkg)}
    assert keys == {'unclassified:foo_knob', 'unvalidated:foo_knob'}


def test_knob_classification_rejects_unknown_class_value(tmp_path):
    pkg = make_pkg(tmp_path, {'config.py': '''
        KNOB_CLASSIFICATION = {'foo_knob': 'sometimes'}

        def sanity_check(args):
            pass
    '''})
    assert any(f.key == 'class:foo_knob'
               for f in check_knob_classification(pkg))


def test_knob_registry_rejects_local_exclusion_list(tmp_path):
    pkg = make_pkg(tmp_path, {
        'config.py': _CLEAN_CONFIG,
        'cache/key.py': '''
            CONFIG_KEY_EXCLUDE = frozenset({'a', 'b', 'c'})
        ''',
        'serve/server.py': '''
            from fixpkg.config import knob_exclude

            _KEY_EXCLUDE = knob_exclude('pool_key')
        ''',
    })
    findings = check_knob_registry_single_source(pkg)
    assert {f.file for f in findings} == {'cache/key.py'}
    assert any(f.key == 'literal:CONFIG_KEY_EXCLUDE' for f in findings)
    assert any(f.key == 'registry:unused' for f in findings)


# -- swallowed-exception -----------------------------------------------------

def test_swallowed_exception_flags_silent_pass(tmp_path):
    pkg = make_pkg(tmp_path, {'a.py': '''
        def f():
            try:
                risky()
            except Exception:
                pass
    '''})
    assert rules_of(check_swallowed_exceptions(pkg)) \
        == {'swallowed-exception'}


@pytest.mark.parametrize('body', [
    'raise',
    'event(1, "boom", exc_info=True)',
    'log_extraction_error(p)',
    'warnings.warn("boom")',
])
def test_swallowed_exception_allows_reporting_bodies(tmp_path, body):
    pkg = make_pkg(tmp_path, {'a.py': f'''
        def f():
            try:
                risky()
            except Exception:
                {body}
    '''})
    assert check_swallowed_exceptions(pkg) == []


def test_swallowed_exception_one_hop_helper_indirection(tmp_path):
    # packing.py idiom: the handler delegates to doom_batch, which reports
    pkg = make_pkg(tmp_path, {'a.py': '''
        def doom(v):
            log_batch_error(v)

        def f():
            try:
                risky()
            except Exception:
                doom(1)
    '''})
    assert check_swallowed_exceptions(pkg) == []


def test_swallowed_exception_suppression_comment(tmp_path):
    pkg = make_pkg(tmp_path, {'a.py': '''
        def f():
            try:
                risky()
            except Exception:
                # vft-lint: ok=swallowed-exception — fixture teardown
                pass
    '''})
    findings = filter_suppressed(pkg, check_swallowed_exceptions(pkg))
    assert findings == []


def test_narrow_exceptions_are_fine(tmp_path):
    pkg = make_pkg(tmp_path, {'a.py': '''
        def f():
            try:
                risky()
            except (OSError, ValueError):
                pass
    '''})
    assert check_swallowed_exceptions(pkg) == []


# -- stdout-purity -----------------------------------------------------------

def test_stdout_purity_flags_bare_print(tmp_path):
    pkg = make_pkg(tmp_path, {'a.py': 'print("hello")\n'})
    assert rules_of(check_stdout_purity(pkg)) == {'stdout-purity'}


def test_stdout_purity_allows_explicit_stream_and_cli(tmp_path):
    pkg = make_pkg(tmp_path, {
        'a.py': 'import sys\nprint("x", file=sys.stderr)\n',
        'cli.py': 'print("usage: ...")\n',
    })
    assert check_stdout_purity(pkg) == []


def test_stdout_purity_whitelists_print_mode_branch_only(tmp_path):
    pkg = make_pkg(tmp_path, {'a.py': '''
        def act(self, key):
            if self.on_extraction == 'print':
                print(key)          # the feature stream itself: allowed
            else:
                print('saving')     # save mode: flagged
    '''})
    findings = check_stdout_purity(pkg)
    assert len(findings) == 1
    assert 'saving' in pkg.get('a.py').lines[findings[0].line - 1]


# -- contract-key-sync -------------------------------------------------------

def test_contract_keys_clean_and_both_drift_directions(tmp_path):
    metrics = '''
        def build_metrics():
            doc = {'uptime_s': 1}
            doc['queue'] = {}
            return doc
    '''
    pkg = make_pkg(tmp_path, {'serve/metrics.py': metrics}, tests={
        'test_obs.py': "METRICS_DOC_KEYS = {'uptime_s', 'queue'}\n"})
    assert check_contract_keys(pkg) == []

    pkg = make_pkg(tmp_path, {'serve/metrics.py': metrics}, tests={
        'test_obs.py': "METRICS_DOC_KEYS = {'uptime_s', 'stale_key'}\n"})
    keys = {f.key for f in check_contract_keys(pkg)}
    assert keys == {'serve metrics document:unpinned:queue',
                    'serve metrics document:stale:stale_key'}


def test_contract_keys_skip_without_tests_dir(tmp_path):
    pkg = make_pkg(tmp_path, {'serve/metrics.py': 'def build_metrics():\n'
                                                  '    return {}\n'})
    assert check_contract_keys(pkg) == []


# -- stage-vocabulary --------------------------------------------------------

def test_stage_vocabulary_flags_unknown_stage_literal(tmp_path):
    pkg = make_pkg(tmp_path, {
        'utils/tracing.py': "STAGES = ('decode', 'model')\n",
        'extract/x.py': '''
            def f(tracer):
                with tracer.stage('warp_drive'):
                    pass
                with tracer.stage('model'):
                    pass
        ''',
    })
    findings = check_stage_vocabulary(pkg)
    assert [f.key for f in findings] == ['stage:warp_drive']


def test_stage_vocabulary_contract_drift(tmp_path):
    pkg = make_pkg(tmp_path, {
        'utils/tracing.py': "STAGES = ('decode', 'model')\n",
    }, tests={'test_obs.py': "CANONICAL_STAGES = {'decode'}\n"})
    assert any(f.key == 'stages:contract'
               for f in check_stage_vocabulary(pkg))


# -- thread-discipline -------------------------------------------------------

def test_thread_discipline_requires_locked_by(tmp_path):
    pkg = make_pkg(tmp_path, {'serve/state.py': '''
        import threading

        _PENDING = {}
        _PENDING_LOCK = threading.Lock()
    '''})
    findings = check_thread_discipline(pkg)
    assert [f.key for f in findings] == ['unlocked:_PENDING']


def test_thread_discipline_accepts_declared_lock_or_immutable(tmp_path):
    pkg = make_pkg(tmp_path, {'serve/state.py': '''
        import threading

        _LOCKED_BY = {'_PENDING': '_PENDING_LOCK', '_NAMES': 'immutable'}
        _PENDING = {}
        _PENDING_LOCK = threading.Lock()
        _NAMES = {1: 'a'}
    '''})
    assert check_thread_discipline(pkg) == []


def test_thread_discipline_rejects_missing_lock_name(tmp_path):
    pkg = make_pkg(tmp_path, {'farm/state.py': '''
        _LOCKED_BY = {'_PENDING': '_NO_SUCH_LOCK'}
        _PENDING = {}
    '''})
    assert [f.key for f in check_thread_discipline(pkg)] \
        == ['missing-lock:_PENDING']


def test_thread_discipline_scope_is_concurrent_dirs_only(tmp_path):
    pkg = make_pkg(tmp_path, {'utils/memo.py': '_MEMO = {}\n'})
    assert check_thread_discipline(pkg) == []


# -- lock-order --------------------------------------------------------------

def test_lock_order_flags_blocking_call_under_lock(tmp_path):
    pkg = make_pkg(tmp_path, {'farm/hub.py': '''
        import threading
        _LOCK = threading.Lock()

        def drain(q):
            with _LOCK:
                return q.get()
    '''})
    findings = check_lock_order(pkg)
    assert [f.key for f in findings] == ['blocking:drain.get']
    assert '_LOCK' in findings[0].message


def test_lock_order_allows_timeout_and_unlocked_blocking(tmp_path):
    pkg = make_pkg(tmp_path, {'serve/hub.py': '''
        import threading
        _LOCK = threading.Lock()

        def ok(q, t, conn, d):
            q.get()                   # not under a lock
            with _LOCK:
                q.get(timeout=1.0)    # bounded
                t.join(2.0)           # positional deadline
                d.get('key')          # dict.get, not Queue.get
            conn.recv()
    '''})
    assert check_lock_order(pkg) == []


def test_lock_order_nested_def_resets_held_set(tmp_path):
    # a function DEFINED under the lock runs later, not under it
    pkg = make_pkg(tmp_path, {'ingress/hub.py': '''
        import threading
        _LOCK = threading.Lock()

        def make(q):
            with _LOCK:
                def later():
                    return q.get()
                return later
    '''})
    assert check_lock_order(pkg) == []


def test_lock_order_instance_lock_counts(tmp_path):
    pkg = make_pkg(tmp_path, {'serve/pool.py': '''
        class Pool:
            def drain(self, q):
                with self._lock:
                    return q.recv()
    '''})
    assert [f.key for f in check_lock_order(pkg)] \
        == ['blocking:Pool.drain.recv']


def test_lock_order_detects_acquisition_cycle(tmp_path):
    pkg = make_pkg(tmp_path, {'farm/ab.py': '''
        import threading
        _A = threading.Lock()
        _B_LOCK = threading.Lock()
        _LOCKED_BY = {'_S': '_A', '_T': '_B_LOCK'}
        _S = {}
        _T = {}

        def fwd():
            with _A:
                with _B_LOCK:
                    pass

        def rev():
            with _B_LOCK:
                with _A:
                    pass
    '''})
    findings = check_lock_order(pkg)
    assert len(findings) == 1
    assert findings[0].key.startswith('cycle:')
    assert '_A' in findings[0].message and '_B_LOCK' in findings[0].message


def test_lock_order_nesting_without_cycle_is_clean(tmp_path):
    pkg = make_pkg(tmp_path, {'farm/ab.py': '''
        import threading
        _A = threading.Lock()
        _B_LOCK = threading.Lock()

        def fwd():
            with _A:
                with _B_LOCK:
                    pass
    '''})
    assert check_lock_order(pkg) == []


def test_lock_order_name_match_is_token_anchored(tmp_path):
    # 'block'/'clock'/'_nonblocking_guard' context managers are not
    # locks; '_lock'/'build_lock'/'_LIVE_LOCK' are
    pkg = make_pkg(tmp_path, {'serve/hub.py': '''
        def f(self, q, clock, block):
            with clock:
                q.get()
            with block:
                q.get()
            with self._nonblocking_guard:
                q.get()
    '''})
    assert check_lock_order(pkg) == []
    pkg2 = make_pkg(tmp_path, {'serve/hub2.py': '''
        def f(self, q, build_lock):
            with build_lock:
                q.get()
    '''}, name='fixpkg2')
    assert [f.key for f in check_lock_order(pkg2)] == ['blocking:f.get']


def test_lock_order_suppression_comment(tmp_path):
    pkg = make_pkg(tmp_path, {'farm/hub.py': '''
        import threading
        _LOCK = threading.Lock()

        def drain(q):
            with _LOCK:
                # vft-lint: ok=lock-order — the only producer holds no
                # locks; bounded by the producer's own deadline
                return q.get()
    '''})
    assert filter_suppressed(pkg, check_lock_order(pkg)) == []


# -- wire-literal ------------------------------------------------------------

_WIRE_HTTP = '''
    OK = 200
    NOT_FOUND = 404
'''
_WIRE_PROTOCOL = '''
    CMD_PING = 'ping'
    COMMANDS = (CMD_PING,)
'''


def test_wire_literal_flags_inline_status_int(tmp_path):
    pkg = make_pkg(tmp_path, {
        'ingress/http.py': _WIRE_HTTP,
        'ingress/gateway.py': '''
            from fixpkg.ingress.http import HttpError, NOT_FOUND

            def route(resp):
                resp.send_json(200, {'ok': True})
                raise HttpError(NOT_FOUND, 'not_found', 'x')
        ''',
    })
    findings = check_wire_literal(pkg)
    assert [f.key for f in findings] == ['status:200']


def test_wire_literal_flags_inline_command_string(tmp_path):
    pkg = make_pkg(tmp_path, {
        'serve/protocol.py': _WIRE_PROTOCOL,
        'serve/server.py': '''
            def dispatch(msg):
                cmd = msg.get('cmd')
                if cmd == 'ping':
                    return {'ok': True}
        ''',
        'serve/client.py': '''
            def ping(self):
                return self._call({'cmd': 'ping'})
        ''',
    })
    keys = {f.key for f in check_wire_literal(pkg)}
    assert keys == {'cmd:ping'}
    assert {f.file for f in check_wire_literal(pkg)} \
        == {'serve/server.py', 'serve/client.py'}


def test_wire_literal_clean_when_constants_are_used(tmp_path):
    pkg = make_pkg(tmp_path, {
        'ingress/http.py': _WIRE_HTTP,
        'serve/protocol.py': _WIRE_PROTOCOL,
        'ingress/gateway.py': '''
            from fixpkg.ingress.http import OK

            def route(resp):
                resp.send_json(OK, {'ok': True})
        ''',
        'serve/server.py': '''
            from fixpkg.serve import protocol

            def dispatch(msg):
                if msg.get('cmd') == protocol.CMD_PING:
                    return {'ok': True}
        ''',
    })
    assert check_wire_literal(pkg) == []


def test_wire_literal_defining_modules_are_exempt(tmp_path):
    # http.py spells its own reason table with ints; protocol.py IS the
    # command vocabulary — neither is a violation
    pkg = make_pkg(tmp_path, {
        'ingress/http.py': '''
            OK = 200
            NOT_FOUND = 404

            class HttpError(Exception):
                pass

            def reject(resp):
                resp.send_json(503, {'ok': False})
        ''',
        'serve/protocol.py': _WIRE_PROTOCOL,
    })
    assert check_wire_literal(pkg) == []


# -- baseline ----------------------------------------------------------------

def test_baseline_identity_survives_line_drift(tmp_path):
    src = 'def f():\n    try:\n        g()\n    except Exception:\n' \
          '        pass\n'
    pkg = make_pkg(tmp_path, {'a.py': src}, name='drift1')
    findings = check_swallowed_exceptions(pkg)
    baseline_path = tmp_path / 'baseline.json'
    write_baseline(baseline_path, findings)

    shifted = '# pushed\n# down\n# by\n# comments\n' + src
    pkg2 = make_pkg(tmp_path, {'a.py': shifted}, name='drift2')
    fresh = new_findings(check_swallowed_exceptions(pkg2),
                         load_baseline(baseline_path))
    assert fresh == []


def test_missing_baseline_is_empty(tmp_path):
    assert load_baseline(tmp_path / 'nope.json') == set()


def test_ordinals_assigned_after_suppression_filtering(tmp_path):
    # a suppressed sibling must not consume an ordinal: removing it
    # later must not rename (and resurface) a baselined neighbor
    src = '''
        def f():
            # vft-lint: ok=stdout-purity — fixture
            print("suppressed")
            print("live one")
            print("live two")
    '''
    pkg = make_pkg(tmp_path, {'a.py': src})
    keys = [f.key for f in analyze(pkg)]
    assert keys == ['print:f', 'print:f#2']

    without_suppressed = make_pkg(
        tmp_path, {'a.py': src.replace('print("suppressed")', 'pass')},
        name='fix2')
    assert [f.key for f in analyze(without_suppressed)] == keys


# -- CLI contract ------------------------------------------------------------

def _run_cli(args):
    from video_features_tpu.analysis.__main__ import main
    return main(args)


def test_cli_exit_0_on_clean_fixture(tmp_path, capsys):
    make_pkg(tmp_path, {'a.py': 'x = 1\n'})
    assert _run_cli(['--root', str(tmp_path / 'fixpkg'),
                     '--package-name', 'fixpkg',
                     '--baseline', str(tmp_path / 'b.json')]) == 0


def test_cli_exit_2_on_planted_violation(tmp_path, capsys):
    make_pkg(tmp_path, {'a.py': 'print("boom")\n'})
    rc = _run_cli(['--root', str(tmp_path / 'fixpkg'),
                   '--package-name', 'fixpkg',
                   '--baseline', str(tmp_path / 'b.json')])
    assert rc == 2
    out = capsys.readouterr().out
    assert 'stdout-purity' in out and 'a.py:1' in out


def test_cli_write_baseline_then_clean(tmp_path, capsys):
    make_pkg(tmp_path, {'a.py': 'print("boom")\n'})
    base = ['--root', str(tmp_path / 'fixpkg'), '--package-name', 'fixpkg',
            '--baseline', str(tmp_path / 'b.json')]
    assert _run_cli(base + ['--write-baseline']) == 0
    doc = json.loads((tmp_path / 'b.json').read_text())
    assert doc and doc[0]['rule'] == 'stdout-purity'
    assert _run_cli(base + ['--fail-on-new']) == 0


# -- the live codebase -------------------------------------------------------

def test_live_tree_is_clean_against_shipped_baseline():
    """The same gate CI's ``lint`` job enforces: every rule over the
    real package, minus inline suppressions, minus the (empty) shipped
    baseline, must report nothing."""
    pkg = Package(PKG_ROOT, 'video_features_tpu',
                  tests_dir=REPO_ROOT / 'tests')
    fresh = new_findings(analyze(pkg), load_baseline(
        REPO_ROOT / 'tools' / 'vft_lint_baseline.json'))
    assert fresh == [], '\n'.join(f.render() for f in fresh)


def test_analyzer_entry_chain_is_jax_free():
    """The import chain `python -m video_features_tpu.analysis`
    traverses (package __init__ -> config/registry) must never gain a
    module-level jax import — this static check is what keeps the CLI's
    exit-3 guard meaningful even on hosts where jax is preloaded."""
    from video_features_tpu.analysis.checks import closure_forbidden_imports
    pkg = Package(PKG_ROOT, 'video_features_tpu')
    assert closure_forbidden_imports(
        pkg, ('__init__.py',), 'analyzer-purity', 'analyzer entry') == []


def test_live_spawn_closure_covers_the_farm_surface():
    """The worker/recipe closure must actually include the modules the
    farm contract names (a rename that silently empties the closure
    would turn rule spawn-purity into a no-op)."""
    from video_features_tpu.analysis.checks import SPAWN_ROOTS
    from video_features_tpu.analysis.imports import spawn_closure
    pkg = Package(PKG_ROOT, 'video_features_tpu')
    closure = spawn_closure(pkg, SPAWN_ROOTS)
    assert {'farm/worker.py', 'farm/recipes.py', 'ops/host_transforms.py',
            'farm/ring.py', 'io/video.py',
            'extract/streaming.py'} <= set(closure)


def test_analyzer_subprocess_never_imports_jax_and_is_fast():
    """Acceptance criteria: the analyzer process never imports jax and
    the whole run fits comfortably in CI's <10 s budget."""
    t0 = time.monotonic()
    proc = subprocess.run(
        [sys.executable, str(REPO_ROOT / 'tools' / 'vft_lint.py')],
        capture_output=True, text=True, cwd=str(REPO_ROOT), timeout=60)
    wall = time.monotonic() - t0
    # exit 3 is the analyzer's own "I imported jax" self-violation code
    assert proc.returncode == 0, (proc.returncode, proc.stdout, proc.stderr)
    assert wall < 10, f'vft-lint took {wall:.1f}s (budget: 10s)'


def test_knob_registry_is_behavior_preserving():
    """The derived exclusion sets must match the PRE-refactor
    hand-maintained lists exactly (fingerprint/pool-key parity tests
    depend on membership; this pins the full sets — new knobs extend it
    intentionally, here: the vft-flight telemetry knobs, 'neither' like
    the trace knobs they sit beside, and the vft-aot store knobs,
    'pool_only' like the cache_* knobs they mirror (loaded executables
    are byte-identical to compiled ones, so the fingerprint excludes
    them; a worker consults the store it was built with, so the pool
    key keeps them; and the fused 'features' routing key, 'neither' —
    split_fused_overrides drops it before any per-family config exists,
    and a stray copy fragmenting the fused key space against sequential
    runs would break the keys-identical contract, tests/test_fused.py;
    and the vft-index knobs, 'neither' like the cache knobs the index
    derives from — the index stores nothing the cache does not, so its
    presence can never change what bytes a run produces or which warm
    entry serves it; and the vft-scope SLO knobs, 'neither' — burn-rate
    evaluation only reads metrics the serving path already records)."""
    from video_features_tpu.config import knob_exclude
    assert knob_exclude('fingerprint') == {
        'video_paths', 'file_with_video_paths', 'output_path', 'tmp_path',
        'keep_tmp_files', 'device', 'device_ids', 'data_parallel',
        'multihost', 'coordinator_address', 'num_processes', 'process_id',
        'pack_across_videos', 'pack_decode_ahead', 'decode_workers',
        'mesh_devices', 'decode_farm_ring_mb', 'inflight',
        'compilation_cache_dir', 'profile', 'profile_dir', 'show_pred',
        'trace_out', 'trace_capacity', 'manifest_out',
        'postmortem_dir', 'postmortem_max_bytes', 'watchdog_stall_s',
        'slo_latency_p99_s', 'slo_availability',
        'cache_enabled', 'cache_dir', 'cache_max_bytes', 'cache_l2_dir',
        'aot_enabled', 'aot_dir', 'aot_max_bytes', 'aot_l2_dir',
        'index_enabled', 'index_dir', 'index_shard_rows',
        'index_poll_s', 'index_query_block', 'index_k_max',
        'allow_random_weights', 'timeout_s', 'config', 'features'}
    assert knob_exclude('pool_key') == {
        'video_paths', 'file_with_video_paths', 'output_path', 'profile',
        'profile_dir', 'timeout_s', 'trace_out', 'trace_capacity',
        'manifest_out', 'inflight', 'decode_workers',
        'decode_farm_ring_mb',
        'postmortem_dir', 'postmortem_max_bytes', 'watchdog_stall_s',
        'slo_latency_p99_s', 'slo_availability',
        'index_enabled', 'index_dir', 'index_shard_rows',
        'index_poll_s', 'index_query_block', 'index_k_max',
        'features'}


def test_deleting_a_knob_from_the_registry_breaks_both_consumers():
    """Acceptance criterion: the registry is the single source of truth
    — removing a knob's classification changes BOTH the cache
    fingerprint and the serve pool key."""
    from unittest import mock

    from video_features_tpu import config as config_mod
    from video_features_tpu.cache.key import config_fingerprint
    from video_features_tpu.serve.server import pool_key

    args = {'feature_type': 'resnet', 'batch_size': 4, 'inflight': 2}
    fp_before = config_fingerprint(args)
    pk_before = pool_key(args)

    pruned = {k: v for k, v in config_mod.KNOB_CLASSIFICATION.items()
              if k != 'inflight'}
    with mock.patch.dict(config_mod.KNOB_CLASSIFICATION, pruned,
                         clear=True):
        # consumers bound their frozensets at import time — re-derive the
        # way they do, and verify the derivation now disagrees
        assert 'inflight' not in config_mod.knob_exclude('fingerprint')
        assert 'inflight' not in config_mod.knob_exclude('pool_key')
        with mock.patch('video_features_tpu.cache.key.CONFIG_KEY_EXCLUDE',
                        config_mod.knob_exclude('fingerprint')), \
                mock.patch('video_features_tpu.serve.server._KEY_EXCLUDE',
                           config_mod.knob_exclude('pool_key')):
            assert config_fingerprint(args) != fp_before
            assert pool_key(args) != pk_before
