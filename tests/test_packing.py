"""Packed corpus mode (pack_across_videos): the batch-major outer loop
must be externally indistinguishable from the per-video loop — identical
output files, identical resume/skip behavior, per-video fault isolation —
while filling device batches across video boundaries (parallel/packing.py).

All fixtures are synthesized with cv2 so the suite runs without the
reference sample corpus.
"""
import os
import time
from pathlib import Path

import numpy as np
import pytest

from video_features_tpu.config import load_config
from video_features_tpu.registry import create_extractor
from video_features_tpu.utils.output import make_path


from tools.make_sample_video import write_noise_clip as _write_clip  # noqa: E402


@pytest.fixture(scope='module')
def mixed_worklist(tmp_path_factory):
    """Three clips of DIFFERENT lengths: none fills a whole device batch
    alone, so packing across boundaries is actually exercised."""
    d = tmp_path_factory.mktemp('packvids')
    return [_write_clip(d / f'vid{i}.mp4', n, seed=i)
            for i, n in enumerate((9, 4, 14))]


def _resnet_args(paths, out, tmp, **kw):
    over = dict(video_paths=paths, device='cpu', model_name='resnet18',
                batch_size=4, allow_random_weights=True,
                on_extraction='save_numpy', output_path=str(out),
                tmp_path=str(tmp))
    over.update(kw)
    return load_config('resnet', overrides=over)


RESNET_KEYS = ('resnet', 'fps', 'timestamps_ms')


def _load_outputs(out_path, paths, keys=RESNET_KEYS):
    return {(p, k): np.load(make_path(str(out_path), p, k, '.npy'))
            for p in paths for k in keys}


def test_packed_matches_per_video_framewise(mixed_worklist, tmp_path):
    """Packed outputs are element-identical to the per-video path on a
    mixed-length worklist: same filenames, same arrays — the batches
    differ (packed slots carry other videos' frames where the per-video
    loop carried padding), but per-sample results must not."""
    ex_pv = create_extractor(_resnet_args(
        mixed_worklist, tmp_path / 'pv', tmp_path / 'tmp1'))
    for p in mixed_worklist:
        ex_pv._extract(p)
    ex_pk = create_extractor(_resnet_args(
        mixed_worklist, tmp_path / 'pk', tmp_path / 'tmp2'))
    ex_pk.extract_packed(mixed_worklist)

    a = _load_outputs(ex_pv.output_path, mixed_worklist)
    b = _load_outputs(ex_pk.output_path, mixed_worklist)
    assert set(Path(f).name for f in os.listdir(ex_pv.output_path)) == \
        set(Path(f).name for f in os.listdir(ex_pk.output_path))
    for key in a:
        assert a[key].shape == b[key].shape, key
        np.testing.assert_array_equal(a[key], b[key], err_msg=str(key))


def test_packed_matches_per_video_i3d_stacks(tmp_path, tmp_path_factory):
    """The stack family: i3d rgb stream over windows that straddle the
    batch across videos (stack 10, batch 2 → 2+1 windows from 2 clips)."""
    d = tmp_path_factory.mktemp('i3dvids')
    paths = [_write_clip(d / 'a.mp4', 25, seed=7),
             _write_clip(d / 'b.mp4', 12, seed=8)]

    # ONE extractor runs both loops (per-task out_roots keep the output
    # trees apart) — the i3d transplant+compile dominates this test's
    # cost and the parity contract is about the LOOPS, not the build
    from video_features_tpu.parallel.packing import VideoTask
    ex = create_extractor(load_config('i3d', overrides=dict(
        video_paths=paths, device='cpu', streams='rgb',
        stack_size=10, step_size=10, batch_size=2,
        concat_rgb_flow=False, allow_random_weights=True,
        on_extraction='save_numpy', output_path=str(tmp_path / 'pv'),
        tmp_path=str(tmp_path / 'tmp1'))))
    for p in paths:
        ex._extract(p)
    pk_root = str(tmp_path / 'pk')
    ex.extract_packed([VideoTask(p, out_root=pk_root) for p in paths])

    for p, n_windows in zip(paths, (2, 1)):
        a = np.load(make_path(ex.output_path, p, 'rgb', '.npy'))
        b = np.load(make_path(pk_root, p, 'rgb', '.npy'))
        assert a.shape == b.shape == (n_windows, 1024)
        np.testing.assert_array_equal(a, b, err_msg=p)


def test_packed_fault_isolation_bad_file(mixed_worklist, tmp_path):
    """A video that fails to open mid-worklist must not poison the batches
    it would have shared: the good videos' outputs are still written and
    still identical to a clean run's."""
    clean = create_extractor(_resnet_args(
        mixed_worklist, tmp_path / 'clean', tmp_path / 'tmpc'))
    clean.extract_packed(mixed_worklist)

    bad = str(tmp_path / 'gone.mp4')          # never created
    worklist = mixed_worklist[:1] + [bad] + mixed_worklist[1:]
    ex = create_extractor(_resnet_args(
        mixed_worklist, tmp_path / 'faulty', tmp_path / 'tmpf'))
    ex.extract_packed(worklist)               # must not raise

    for p in mixed_worklist:
        for k in RESNET_KEYS:
            got = np.load(make_path(ex.output_path, p, k, '.npy'))
            ref = np.load(make_path(clean.output_path, p, k, '.npy'))
            np.testing.assert_array_equal(got, ref)
    assert not Path(make_path(ex.output_path, bad, 'resnet',
                              '.npy')).exists()


def test_packed_fault_isolation_mid_stream(mixed_worklist, tmp_path):
    """A decode failure MID-video (after windows already entered shared
    batches): the failing video saves nothing, its batch-mates save
    everything, bit-identical to a clean run."""
    clean = create_extractor(_resnet_args(
        mixed_worklist, tmp_path / 'clean2', tmp_path / 'tmpc2'))
    clean.extract_packed(mixed_worklist)

    victim = mixed_worklist[1]
    ex = create_extractor(_resnet_args(
        mixed_worklist, tmp_path / 'mid', tmp_path / 'tmpm'))
    orig = ex.packed_windows

    def flaky(task):
        it = orig(task)
        if task.path == victim:
            yield next(it)                    # one frame reaches the pool
            raise RuntimeError('decoder died mid-video')
        yield from it

    ex.packed_windows = flaky
    ex.extract_packed(mixed_worklist)         # must not raise

    assert not Path(make_path(ex.output_path, victim, 'resnet',
                              '.npy')).exists()
    for p in mixed_worklist:
        if p == victim:
            continue
        for k in RESNET_KEYS:
            got = np.load(make_path(ex.output_path, p, k, '.npy'))
            ref = np.load(make_path(clean.output_path, p, k, '.npy'))
            np.testing.assert_array_equal(got, ref)


def _r21d_args(paths, out, tmp, **kw):
    over = dict(video_paths=paths, device='cpu', stack_size=4, step_size=4,
                batch_size=2, allow_random_weights=True,
                on_extraction='save_numpy', output_path=str(out),
                tmp_path=str(tmp))
    over.update(kw)
    return load_config('r21d', overrides=over)


@pytest.fixture(scope='module')
def mixed_geometry_worklist(tmp_path_factory):
    """Three clips where the MIDDLE one has a different resolution: its
    windows pool separately (stack families ship decode-geometry windows)
    and only flush at the final drain."""
    d = tmp_path_factory.mktemp('geomvids')
    return [_write_clip(d / 'a.mp4', 9, w=64, h=48, seed=1),
            _write_clip(d / 'odd.mp4', 5, w=80, h=64, seed=2),
            _write_clip(d / 'c.mp4', 9, w=64, h=48, seed=3)]


def test_packed_mixed_geometry_parity_and_no_head_blocking(
        mixed_geometry_worklist, tmp_path):
    """A mixed-resolution corpus packs per geometry and still matches the
    per-video path; and a video whose pool can't fill (the lone odd clip)
    must NOT hold up the flush of completed videos behind it — its own
    output simply lands at the final drain."""
    paths = mixed_geometry_worklist
    ex_pv = create_extractor(_r21d_args(paths, tmp_path / 'pv',
                                        tmp_path / 'tmp1'))
    for p in paths:
        ex_pv._extract(p)
    ex_pk = create_extractor(_r21d_args(paths, tmp_path / 'pk',
                                        tmp_path / 'tmp2'))
    save_order = []
    orig_save = ex_pk.action_on_extraction

    def recording_save(feats_dict, video_path):
        save_order.append(Path(video_path).stem)
        return orig_save(feats_dict, video_path)

    ex_pk.action_on_extraction = recording_save
    ex_pk.extract_packed(paths)

    for p, n_windows in zip(paths, (2, 1, 2)):
        a = np.load(make_path(ex_pv.output_path, p, 'r21d', '.npy'))
        b = np.load(make_path(ex_pk.output_path, p, 'r21d', '.npy'))
        assert a.shape == b.shape == (n_windows, 512)
        np.testing.assert_array_equal(a, b, err_msg=p)
    # 'c' completes while 'odd' is still pooled — it must flush before
    # 'odd', not behind it (head-of-line regression guard)
    assert save_order.index('c') < save_order.index('odd')


def test_packed_device_step_fault_isolation(mixed_geometry_worklist,
                                            tmp_path):
    """A device-step failure (e.g. a geometry that won't compile) fails
    exactly the videos in that batch and the worklist continues — same
    blast radius as the per-video loop."""
    paths = mixed_geometry_worklist
    ex = create_extractor(_r21d_args(paths, tmp_path / 'stepf',
                                     tmp_path / 'tmpsf'))
    orig_step = ex.packed_step

    def bad_step(stacks):
        if stacks.shape[2] == 64:     # the odd 80x64 clip's geometry
            raise RuntimeError('no executable for this geometry')
        return orig_step(stacks)

    ex.packed_step = bad_step
    ex.extract_packed(paths)          # must not raise

    victim = paths[1]
    assert not Path(make_path(ex.output_path, victim, 'r21d',
                              '.npy')).exists()
    for p, n_windows in zip(paths, (2, 1, 2)):
        if p == victim:
            continue
        feats = np.load(make_path(ex.output_path, p, 'r21d', '.npy'))
        assert feats.shape == (n_windows, 512)


def test_packed_resume_contract(mixed_worklist, tmp_path, capsys):
    """is_already_exist semantics survive the inversion: a second packed
    run skips every video without rewriting anything, and after deleting
    one video's outputs (interrupted-run shape) only that video is
    re-extracted."""
    ex = create_extractor(_resnet_args(
        mixed_worklist, tmp_path / 'res', tmp_path / 'tmpr'))
    ex.extract_packed(mixed_worklist)
    files = sorted(Path(ex.output_path).glob('*.npy'))
    assert len(files) == len(mixed_worklist) * len(RESNET_KEYS)
    mtimes = {f: f.stat().st_mtime_ns for f in files}

    capsys.readouterr()
    ex.extract_packed(mixed_worklist)         # resume: everything skips
    out = capsys.readouterr().out
    assert out.count('already exist') == len(mixed_worklist)
    assert {f: f.stat().st_mtime_ns for f in files} == mtimes

    # resume-after-interrupt: one video's outputs lost mid-corpus
    victim = mixed_worklist[1]
    removed = [f for f in files
               if f.name.startswith(Path(victim).stem + '_')]
    assert removed
    for f in removed:
        f.unlink()
    time.sleep(0.01)                          # mtime resolution guard
    ex.extract_packed(mixed_worklist)
    for f in files:
        if f in removed:
            assert f.exists()                 # re-extracted
        else:
            assert f.stat().st_mtime_ns == mtimes[f], f  # untouched


def test_packed_batch_occupancy_reported(mixed_worklist, tmp_path, capsys):
    """The packed run reports batch occupancy: 9+4+14=27 frames in batches
    of 4 → 7 batches, 27/28 slots real (the per-video loop would run 9
    batches at 27/36). The occ% and ramp columns land in the summary."""
    ex = create_extractor(_resnet_args(
        mixed_worklist, tmp_path / 'occ', tmp_path / 'tmpo', profile=True))
    real_summary = {}
    real_reset = ex.tracer.reset
    ex.tracer.reset = lambda: real_summary.update(ex.tracer.report()) \
        or real_reset()
    ex.extract_packed(mixed_worklist)
    ex.tracer.reset = real_reset
    captured = capsys.readouterr()
    # the stage table is a diagnostic and prints to STDERR — stdout
    # belongs to the feature stream (vft-lint: stdout-purity)
    err = captured.err
    assert 'occ%' in err and 'ramp' in err
    assert 'packed worklist' in err
    assert 'occ%' not in captured.out

    model = real_summary['model']
    assert model['count'] == 7                # vs 9 in the per-video loop
    assert model['occupancy'] == pytest.approx(27 / 28)
    assert model['occupancy'] > 27 / 36       # strictly beats per-video
    assert 'ramp' in model                    # first-call wall measured


def test_packed_zero_window_video(tmp_path, tmp_path_factory):
    """A clip shorter than one stack window still produces its (empty)
    output files, exactly like the per-video path — resume depends on it."""
    d = tmp_path_factory.mktemp('tiny')
    paths = [_write_clip(d / 'long.mp4', 25, seed=3),
             _write_clip(d / 'short.mp4', 5, seed=4)]
    ex = create_extractor(load_config('i3d', overrides=dict(
        video_paths=paths, device='cpu', streams='rgb',
        stack_size=10, step_size=10, batch_size=2,
        concat_rgb_flow=False, allow_random_weights=True,
        on_extraction='save_numpy', output_path=str(tmp_path / 'zout'),
        tmp_path=str(tmp_path / 'ztmp'))))
    ex.extract_packed(paths)
    long_feats = np.load(make_path(ex.output_path, paths[0], 'rgb', '.npy'))
    short_feats = np.load(make_path(ex.output_path, paths[1], 'rgb', '.npy'))
    assert long_feats.shape == (2, 1024)
    assert short_feats.shape == (0, 1024)


# -- async device loop (inflight > 1): parity + deferred fault isolation ----

def _output_bytes(out_path):
    return {f.name: f.read_bytes()
            for f in sorted(Path(out_path).rglob('*.npy'))}


def test_async_parity_resnet_and_r21d(mixed_worklist,
                                      mixed_geometry_worklist, tmp_path):
    """The deferred-D2H loop must be externally invisible: packed outputs
    at inflight=2 (and deeper) are BYTE-identical to the synchronous
    inflight=1 loop — framewise (resnet) and stack (r21d, mixed
    geometry) families."""
    # ONE extractor per family, driven at both depths via the run-level
    # inflight override with per-task output roots — the serve warm-pool
    # reuse pattern, and it halves the transplant+compile cost of this
    # tier-1 test without weakening the byte-parity contract
    from video_features_tpu.parallel.packing import VideoTask
    ex = create_extractor(_resnet_args(
        mixed_worklist, tmp_path / 's1', tmp_path / 'ts1', inflight=1))
    ex.extract_packed(mixed_worklist)
    deep_root = str(tmp_path / 's2' / 'resnet' / 'resnet18')
    ex.extract_packed([VideoTask(p, out_root=deep_root)
                       for p in mixed_worklist], inflight=3)
    a, b = _output_bytes(ex.output_path), _output_bytes(deep_root)
    assert a and a == b

    paths = mixed_geometry_worklist
    ex = create_extractor(_r21d_args(paths, tmp_path / 'r1',
                                     tmp_path / 'tr1', inflight=1))
    ex.extract_packed(paths)
    deep_root = str(tmp_path / 'r2' / 'r21d')
    ex.extract_packed([VideoTask(p, out_root=deep_root)
                       for p in paths], inflight=2)
    a, b = _output_bytes(ex.output_path), _output_bytes(deep_root)
    assert a and a == b


def test_async_parity_i3d_and_s3d(tmp_path, tmp_path_factory):
    """The stack families with geometry-cached executables (i3d rgb,
    s3d): async packed outputs byte-identical to the synchronous loop."""
    d = tmp_path_factory.mktemp('asyncvids')
    paths = [_write_clip(d / 'a.mp4', 25, seed=21),
             _write_clip(d / 'b.mp4', 18, seed=22)]

    from video_features_tpu.parallel.packing import VideoTask

    def run_both(feature_type, **kw):
        # ONE extractor per family (the transplant+compile dominates
        # this test's cost), run synchronous then async with per-task
        # output roots — the serve warm-pool reuse pattern
        over = dict(video_paths=paths, device='cpu',
                    allow_random_weights=True, on_extraction='save_numpy',
                    output_path=str(tmp_path / f'{feature_type}_1'),
                    tmp_path=str(tmp_path / f'tmp_{feature_type}'),
                    inflight=1)
        over.update(kw)
        ex = create_extractor(load_config(feature_type, overrides=over))
        ex.extract_packed(paths)
        deep_root = str(tmp_path / f'{feature_type}_2')
        ex.extract_packed([VideoTask(p, out_root=deep_root)
                           for p in paths], inflight=2)
        return _output_bytes(ex.output_path), _output_bytes(deep_root)

    a, b = run_both('i3d', streams='rgb', stack_size=10, step_size=10,
                    batch_size=2, concat_rgb_flow=False)
    assert a and a == b
    a, b = run_both('s3d', stack_size=16, step_size=16, batch_size=2)
    assert a and a == b


def test_async_fault_isolation_at_sync_point(mixed_geometry_worklist,
                                             tmp_path):
    """An execution fault that only surfaces at the DEFERRED sync point
    (fetch_outputs — where async backends raise) must doom exactly the
    videos of the batch that produced it; batch-mates and neighbors
    still save, identical to a clean run."""
    paths = mixed_geometry_worklist
    clean = create_extractor(_r21d_args(paths, tmp_path / 'clean',
                                        tmp_path / 'tmpc', inflight=2))
    clean.extract_packed(paths)

    ex = create_extractor(_r21d_args(paths, tmp_path / 'sync',
                                     tmp_path / 'tmps', inflight=2))
    orig_step, orig_fetch = ex.packed_step, ex.fetch_outputs
    # strong references + identity checks (never id(): a freed array's
    # address can be recycled by a later innocent batch)
    poisoned = []

    def marking_step(stacks):
        out = orig_step(stacks)
        if stacks.shape[2] == 64:         # the odd 80x64 clip's geometry
            poisoned.append(out[ex.feature_type])
        return out

    def bad_fetch(out):
        if any(out[ex.feature_type] is p for p in poisoned):
            raise RuntimeError('async execution fault surfaced at D2H')
        return orig_fetch(out)

    ex.packed_step, ex.fetch_outputs = marking_step, bad_fetch
    ex.extract_packed(paths)              # must not raise
    assert poisoned                       # the bad batch really dispatched

    victim = paths[1]
    assert not Path(make_path(ex.output_path, victim, 'r21d',
                              '.npy')).exists()
    for p in paths:
        if p == victim:
            continue
        got = np.load(make_path(ex.output_path, p, 'r21d', '.npy'))
        ref = np.load(make_path(clean.output_path, p, 'r21d', '.npy'))
        np.testing.assert_array_equal(got, ref, err_msg=p)


def test_async_stage_split_model_plus_d2h(mixed_worklist, tmp_path):
    """The stage table shows model (dispatch) and d2h (deferred
    readback) as distinct stages with one record each per batch, and
    both carry the batch-occupancy accounting."""
    ex = create_extractor(_resnet_args(
        mixed_worklist, tmp_path / 'st', tmp_path / 'tmpst',
        profile=True, inflight=2))
    rep = {}
    real_reset = ex.tracer.reset
    ex.tracer.reset = lambda: rep.update(ex.tracer.report()) or real_reset()
    ex.extract_packed(mixed_worklist)
    ex.tracer.reset = real_reset
    assert rep['model']['count'] == rep['d2h']['count'] == 7
    assert rep['model']['occupancy'] == pytest.approx(27 / 28)
    assert rep['d2h']['occupancy'] == pytest.approx(27 / 28)


def test_sanity_check_gates_packing(tmp_path):
    """pack_across_videos degrades (with a warning) for families without
    packed support and for the per-video show_pred debug surface."""
    clip = _write_clip(tmp_path / 'c.mp4', 4)
    args = load_config('vggish', overrides=dict(
        video_paths=clip, device='cpu', pack_across_videos=True,
        output_path=str(tmp_path / 'o'), tmp_path=str(tmp_path / 't')))
    assert args['pack_across_videos'] is False
    args = load_config('resnet', overrides=dict(
        video_paths=clip, device='cpu', model_name='resnet18',
        pack_across_videos=True, show_pred=True,
        output_path=str(tmp_path / 'o2'), tmp_path=str(tmp_path / 't2')))
    assert args['pack_across_videos'] is False


def test_inflight_knob_default_and_validation(tmp_path):
    """The async-depth knob is injected into every merged config
    (default 2) and sanity_check rejects non-positive depths."""
    clip = _write_clip(tmp_path / 'k.mp4', 4)
    common = dict(video_paths=clip, device='cpu', model_name='resnet18',
                  output_path=str(tmp_path / 'o'),
                  tmp_path=str(tmp_path / 't'))
    args = load_config('resnet', overrides=dict(common))
    assert args['inflight'] == 2
    args = load_config('resnet', overrides=dict(common, inflight='1'))
    assert args['inflight'] == 1              # coerced to int
    with pytest.raises(ValueError):
        load_config('resnet', overrides=dict(common, inflight=0))


def test_cli_routes_packed(tmp_path, tmp_path_factory, capsys):
    """End to end through the CLI: pack_across_videos=true drives the
    packed scheduler and writes the standard outputs."""
    from video_features_tpu.cli import main

    d = tmp_path_factory.mktemp('clivids')
    paths = [str(_write_clip(d / f'v{i}.mp4', n, seed=i))
             for i, n in enumerate((6, 9))]
    out = tmp_path / 'cliout'
    rc = main([
        'feature_type=resnet', 'model_name=resnet18', 'device=cpu',
        f'video_paths=[{",".join(paths)}]', 'pack_across_videos=true',
        'batch_size=4', 'allow_random_weights=true',
        'on_extraction=save_numpy', f'output_path={out}',
        f'tmp_path={tmp_path / "clitmp"}'])
    assert rc == 0
    assert 'Packing device batches across 2 videos' in capsys.readouterr().out
    for p in paths:
        # sanity_check appends <feature_type>/<model_name> to output_path
        feats = np.load(make_path(str(out / 'resnet' / 'resnet18'), p,
                                  'resnet', '.npy'))
        assert feats.shape[1] == 512
