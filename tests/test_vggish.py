"""VGGish: DSP parity vs reference mel_features, net parity vs torch, E2E."""
import wave

import numpy as np
import pytest
import torch

from video_features_tpu.config import load_config
from video_features_tpu.models import vggish as vggish_model
from video_features_tpu.ops import audio as audio_ops
from video_features_tpu.registry import create_extractor
from video_features_tpu.transplant.torch2jax import transplant


def test_log_mel_parity_vs_reference(reference_repo):
    """Our host DSP must match the reference's numpy chain bit-for-bit
    (same float64 ops: framing, periodic Hann, rFFT, HTK mel, log)."""
    from models.vggish.vggish_src import mel_features as ref

    rng = np.random.RandomState(0)
    data = rng.randn(16000 * 3).astype(np.float64) * 0.1

    ours = audio_ops.log_mel_spectrogram(data, 16000)
    theirs = ref.log_mel_spectrogram(
        data, audio_sample_rate=16000, log_offset=0.01,
        window_length_secs=0.025, hop_length_secs=0.010,
        num_mel_bins=64, lower_edge_hertz=125.0, upper_edge_hertz=7500.0)

    assert ours.shape == theirs.shape
    np.testing.assert_allclose(ours, theirs, rtol=1e-12, atol=1e-12)


def test_examples_framing():
    """3.5 s of 16 kHz audio → 3 whole 0.96 s examples, tail dropped
    (reference vggish_input.py:62-67 floor semantics)."""
    data = np.zeros(int(16000 * 3.5))
    ex = audio_ops.waveform_to_examples(data, 16000)
    assert ex.shape == (3, 96, 64)
    assert ex.dtype == np.float32


@pytest.mark.slow
def test_net_parity_vs_torch():
    """Same weights, same input → same embeddings as a torch net with the
    reference's architecture (vggish_slim.py:15-37,100-111), including the
    channels-last flatten before the FC stack."""
    torch.manual_seed(0)
    layers, in_ch = [], 1
    for v in [64, 'M', 128, 'M', 256, 256, 'M', 512, 512, 'M']:
        if v == 'M':
            layers.append(torch.nn.MaxPool2d(2, 2))
        else:
            layers.append(torch.nn.Conv2d(in_ch, v, 3, padding=1))
            layers.append(torch.nn.ReLU())
            in_ch = v
    net = torch.nn.Sequential()  # container for state_dict naming
    features = torch.nn.Sequential(*layers)
    embeddings = torch.nn.Sequential(
        torch.nn.Linear(512 * 4 * 6, 4096), torch.nn.ReLU(),
        torch.nn.Linear(4096, 4096), torch.nn.ReLU(),
        torch.nn.Linear(4096, 128), torch.nn.ReLU())
    net.add_module('features', features)
    net.add_module('embeddings', embeddings)
    net.eval()

    rng = np.random.RandomState(0)
    x = rng.randn(2, 96, 64, 1).astype(np.float32)
    with torch.no_grad():
        h = features(torch.from_numpy(x).permute(0, 3, 1, 2))
        h = h.transpose(1, 3).transpose(1, 2).contiguous()  # NCHW → NHWC
        ref = embeddings(h.view(h.size(0), -1)).numpy()

    params = transplant(net.state_dict())
    import jax
    with jax.default_matmul_precision('highest'):
        ours = np.asarray(vggish_model.forward(params, x))

    assert ours.shape == ref.shape == (2, 128)
    np.testing.assert_allclose(ours, ref, atol=1e-4)


def test_postprocess_quantization():
    rng = np.random.RandomState(0)
    emb = rng.randn(5, 128).astype(np.float32)
    eig = rng.randn(128, 128).astype(np.float32) * 0.1
    means = rng.randn(128).astype(np.float32)
    out = np.asarray(vggish_model.postprocess(eig, means, emb))
    assert out.shape == (5, 128)
    assert out.min() >= 0 and out.max() <= 255
    assert np.all(out == np.round(out))


@pytest.fixture()
def sine_wav(tmp_path):
    """2.5 s 440 Hz mono PCM16 wav → expect 2 examples."""
    sr = 16000
    t = np.arange(int(sr * 2.5)) / sr
    samples = (np.sin(2 * np.pi * 440 * t) * 0.5 * 32767).astype('<i2')
    path = str(tmp_path / 'tone.wav')
    with wave.open(path, 'wb') as f:
        f.setnchannels(1)
        f.setsampwidth(2)
        f.setframerate(sr)
        f.writeframes(samples.tobytes())
    return path


def test_e2e_wav_extraction(sine_wav, tmp_path):
    args = load_config('vggish', overrides={
        'video_paths': sine_wav,
        'device': 'cpu',
        'output_path': str(tmp_path / 'out'),
        'tmp_path': str(tmp_path / 'tmp'),
    })
    ex = create_extractor(args)
    out = ex.extract(sine_wav)
    assert out['vggish'].shape == (2, 128)
    assert np.isfinite(out['vggish']).all()


def test_read_wav_roundtrip(sine_wav):
    from video_features_tpu.io.audio import read_wav
    data, sr = read_wav(sine_wav)
    assert sr == 16000
    assert data.ndim == 1 and len(data) == 40000
    assert abs(data).max() <= 0.5 + 1e-3


def test_postprocess_parity_vs_reference_torch(reference_repo):
    """Our jax postprocess == the reference torch Postprocessor with the
    real AudioSet PCA params (reference vggish_slim.py:63-94)."""
    import sys
    import types

    import torch

    # vggish_slim transitively imports resampy/soundfile (audio resampling
    # deps not present here); stub them — Postprocessor touches neither
    for name in ('resampy', 'soundfile'):
        sys.modules.setdefault(name, types.ModuleType(name))
    from models.vggish.vggish_src.vggish_slim import Postprocessor

    npz = reference_repo / 'models/vggish/checkpoints/vggish_pca_params.npz'
    pca = np.load(npz)
    eig = pca['pca_eigen_vectors'].astype(np.float32)
    means = pca['pca_means'].astype(np.float32)

    rng = np.random.RandomState(7)
    emb = (rng.randn(6, 128) * 3).astype(np.float32)

    pp = Postprocessor()
    pp.pca_eigen_vectors.data = torch.from_numpy(eig)
    pp.pca_means.data = torch.from_numpy(means.reshape(-1, 1))
    with torch.no_grad():
        ref = pp.postprocess(torch.from_numpy(emb)).numpy()

    ours = np.asarray(vggish_model.postprocess(eig, means.reshape(-1), emb))
    # quantization boundaries: values within half a level can legitimately
    # round apart across float orders-of-operation; require <=1 level on
    # <1% of entries and exact match elsewhere
    diff = np.abs(ours - ref)
    assert diff.max() <= 1
    assert (diff > 0).mean() < 0.01


def test_e2e_post_process_extraction(sine_wav, tmp_path, reference_repo):
    npz = reference_repo / 'models/vggish/checkpoints/vggish_pca_params.npz'
    args = load_config('vggish', overrides={
        'video_paths': sine_wav,
        'device': 'cpu',
        'output_path': str(tmp_path / 'out'),
        'tmp_path': str(tmp_path / 'tmp'),
        'post_process': True,
        'pca_params_path': str(npz),
    })
    ex = create_extractor(args)
    out = ex.extract(sine_wav)
    feats = out['vggish']
    assert feats.shape == (2, 128)
    assert feats.dtype == np.uint8


def test_post_process_requires_pca_path(sine_wav, tmp_path):
    args = load_config('vggish', overrides={
        'video_paths': sine_wav,
        'device': 'cpu',
        'output_path': str(tmp_path / 'out'),
        'tmp_path': str(tmp_path / 'tmp'),
        'post_process': True,
    })
    with pytest.raises(ValueError, match='pca_params_path'):
        create_extractor(args)
