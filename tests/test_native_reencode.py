"""Native in-process CFR re-encode (the ffmpeg `fps=` stage without the
binary): determinism, fps-filter semantics, loader wiring, and the
measured index-resample divergence.

The reference retimes by shelling out to
``ffmpeg -filter:v fps=fps=N`` and decoding the re-encoded file
(reference utils/io.py:14-36,78-89). This host has no ffmpeg binary, so
``native/vfdecode.cc:vf_reencode_fps`` implements that stage in-process
(libavformat/libavcodec + libx264 at the CLI defaults). The
vs-real-ffmpeg equivalence test runs wherever a binary exists (CI).
"""
from __future__ import annotations

import numpy as np
import pytest

from video_features_tpu.io import native
from video_features_tpu.io.video import (
    VideoLoader, get_video_props, which_ffmpeg,
)

pytestmark = pytest.mark.skipif(not native.available(),
                                reason='native library unavailable')

SRC_FPS = 20
N_FRAMES = 50


@pytest.fixture(scope='module')
def graded_video(tmp_path_factory) -> str:
    """Solid-gray frames whose level encodes the frame index (level =
    10 + 5·i): lossy encoders preserve solid frames to ≪1 level, so the
    decoded mean recovers which SOURCE frame each output slot shows."""
    import cv2

    out = str(tmp_path_factory.mktemp('reenc') / 'graded.mp4')
    w = cv2.VideoWriter(out, cv2.VideoWriter_fourcc(*'mp4v'), SRC_FPS,
                        (128, 96))
    for i in range(N_FRAMES):
        w.write(np.full((96, 128, 3), 10 + 5 * i, np.uint8))
    w.release()
    return out


def _decoded_levels(path: str) -> np.ndarray:
    import cv2

    cap = cv2.VideoCapture(path)
    means = []
    while True:
        ok, frame = cap.read()
        if not ok:
            break
        means.append(frame.astype(np.float64).mean())
    cap.release()
    return np.asarray(means)


def _recover_schedule(out_path: str, src_path: str) -> np.ndarray:
    """Map each output frame to the SOURCE frame it shows, by nearest
    decoded mean level (calibrated on the source's own decoded levels —
    codecs shift solid-gray means by a constant, so absolute level
    arithmetic would be off by a frame)."""
    src_levels = _decoded_levels(src_path)
    out_levels = _decoded_levels(out_path)
    return np.asarray([int(np.argmin(np.abs(src_levels - v)))
                       for v in out_levels])


def _fps_filter_model(n_src: int, src_fps: float, target: float) -> list:
    """The fps filter's zero-order hold on a CFR source: output slot k
    shows the last source frame whose near-rounded rescaled pts ≤ k;
    total slots = the stream end time rescaled (eof_action=round)."""
    def near(x):  # av_rescale NEAR_INF: halves away from zero
        return int(np.floor(x + 0.5))

    pts_out = [near(i * target / src_fps) for i in range(n_src)]
    end = near(n_src * target / src_fps)
    out = []
    for k in range(min(pts_out), end):
        shown = max(i for i in range(n_src) if pts_out[i] <= k)
        out.append(shown)
    return out


@pytest.mark.parametrize('target', [8.0, 40.0])
def test_fps_filter_semantics(graded_video, tmp_path, target):
    """Down- and up-sampling both reproduce the fps-filter's
    duplicate/drop schedule (recovered per-slot source indices match the
    model exactly)."""
    got = native.reencode_fps_native(graded_video, str(tmp_path), target)
    recovered = _recover_schedule(got, graded_video)
    expect = _fps_filter_model(N_FRAMES, SRC_FPS, target)
    assert len(recovered) == len(expect), (len(recovered), len(expect))
    assert recovered.tolist() == expect
    props = get_video_props(got)
    assert abs(props['fps'] - target) < 1e-6


def test_reencode_deterministic(graded_video, tmp_path):
    """Two independent re-encodes produce byte-identical files (x264 is
    deterministic for a fixed build/settings/thread count)."""
    a = native.reencode_fps_native(graded_video, str(tmp_path / 'a'), 8.0)
    b = native.reencode_fps_native(graded_video, str(tmp_path / 'b'), 8.0)
    with open(a, 'rb') as fa, open(b, 'rb') as fb:
        assert fa.read() == fb.read()


def test_loader_uses_native_reencode(graded_video, tmp_path):
    """With no ffmpeg binary, VideoLoader's fps path routes through the
    native re-encoder (a real tmp re-encode, not the index fallback) and
    reports the re-encoded stream's properties."""
    loader = VideoLoader(graded_video, batch_size=8, fps=8.0,
                         tmp_path=str(tmp_path))
    if which_ffmpeg():
        pytest.skip('binary present: loader prefers the CLI path')
    assert loader._tmp_file is not None, 'index fallback was used'
    assert loader._index_map is None
    assert abs(loader.fps - 8.0) < 1e-6
    frames = sum(b.shape[0] for b, _, _ in loader)
    assert frames == loader.num_frames == 20   # round(2.5 s · 8)


def test_total_mode_uses_native_reencode(graded_video, tmp_path):
    """`extraction_total=N` resolves to an fps and rides the same
    re-encode backend: ~N frames come back through a real tmp re-encode
    (the pre-existing total-mode test pins only the index fallback)."""
    if which_ffmpeg():
        pytest.skip('binary present: loader prefers the CLI path')
    loader = VideoLoader(graded_video, batch_size=16, total=20,
                         tmp_path=str(tmp_path))
    assert loader._tmp_file is not None and loader._index_map is None
    frames = sum(b.shape[0] for b, _, _ in loader)
    assert abs(frames - 20) <= 1


def test_index_resample_divergence_measured(graded_video, tmp_path):
    """The documented divergence of the pure index-resample fallback vs
    the re-encode path (VERDICT r3 #6): on a CFR source the FRAME
    SCHEDULES land within one source frame of each other at every output
    slot (the two roundings differ at slot boundaries), plus the
    re-encode's lossy-pixel delta. Measured here at the schedule level;
    the pixel-level term is bounded by test_fps_filter_semantics'
    exact recovery (≪1 gray level on solid frames)."""
    from video_features_tpu.io.video import resample_frame_indices

    target = 8.0
    got = native.reencode_fps_native(graded_video, str(tmp_path), target)
    reenc_schedule = _recover_schedule(got, graded_video)
    index_schedule = resample_frame_indices(N_FRAMES, SRC_FPS, target)
    n = min(len(reenc_schedule), len(index_schedule))
    assert abs(len(reenc_schedule) - len(index_schedule)) <= 1
    diff = np.abs(reenc_schedule[:n] - index_schedule[:n])
    frac_differing = float((diff > 0).mean())
    print(f'[retiming] schedules differ at {frac_differing:.0%} of slots, '
          f'max |Δsource-frame| = {diff.max()}')
    assert diff.max() <= 1, 'schedules should disagree by ≤1 source frame'


def test_real_sample_noninteger_fps(sample_video, tmp_path):
    """The reference sample decodes at a NON-integer rate (~19.6 fps from
    VFR-ish timestamps): re-encoding it to CFR 25 must produce a fully
    decodable stream whose frame count matches round(duration·25) within
    a frame — the tail/rounding arithmetic on real-world pts."""
    got = native.reencode_fps_native(sample_video, str(tmp_path), 25.0)
    props = get_video_props(got)
    assert abs(props['fps'] - 25.0) < 1e-6
    n = len(_decoded_levels(got))
    # the encoder is byte-deterministic and the sample fixed, so the
    # count is exact: the sample's real pts span ~18.05 s → 451 slots
    # (cv2's metadata-derived 355/19.62·25 ≈ 452.3 is off by ~1 — VFR-ish
    # container metadata); an off-by-one tail regression fails this hard
    assert n == 451, n


@pytest.mark.skipif(which_ffmpeg() == '', reason='needs the ffmpeg binary')
def test_matches_ffmpeg_cli(graded_video, tmp_path):
    """Where a real ffmpeg exists (CI), the native re-encode matches the
    CLI's output at the decoded-frame level: identical frame count and
    per-frame mean abs pixel delta < 2 levels (same filter schedule, same
    x264 defaults; bitstreams may differ in container metadata)."""
    import subprocess

    from video_features_tpu.io.video import reencode_video_with_diff_fps

    cli = reencode_video_with_diff_fps(graded_video,
                                      str(tmp_path / 'cli'), 8.0)
    ours = native.reencode_fps_native(graded_video,
                                      str(tmp_path / 'native'), 8.0)
    import cv2

    def frames(path):
        cap = cv2.VideoCapture(path)
        out = []
        while True:
            ok, f = cap.read()
            if not ok:
                break
            out.append(f.astype(np.int16))
        cap.release()
        return out

    fa, fb = frames(cli), frames(ours)
    assert len(fa) == len(fb), (len(fa), len(fb))
    deltas = [np.abs(a - b).mean() for a, b in zip(fa, fb)]
    assert max(deltas) < 2.0, f'max per-frame mean delta: {max(deltas)}'
    del subprocess