"""The fleet tier (fleet/): multi-host front door + shared caches.

Three layers, cheapest first:

  * pure units — the consistent-hash ring's determinism/minimal-movement
    contract, structured error-code classification (the wire-1.4
    failover driver), fleet config splitting, and both shared tiers
    (feature cache L1+L2, AOT artifact store) over tmp dirs, no jax;
  * fake-backend router tests — tiny in-process threads speaking the
    loopback JSON-lines protocol with canned responses pin failover,
    proactive unhealthy-marking, drain-aware membership, and the
    mid-stream-kill semantics without ever building a model;
  * ONE real two-backend integration — two ExtractionServers sharing an
    L2 feature cache + artifact tier behind a router: the acceptance
    scenario (extract on the ring owner, kill it, the survivor serves
    the same video byte-identically from the shared cache without
    decoding, having cold-booted on a peer-compiled executable with
    ``builds_compiled == 0``).
"""
import os
import socket
import threading
import time
from pathlib import Path

import pytest

from video_features_tpu.fleet.ring import HashRing
from video_features_tpu.serve import protocol

from tools.make_sample_video import write_noise_clip as _write_clip  # noqa: E402


# -- hash ring ---------------------------------------------------------------


def test_ring_determinism_and_failover_order():
    hosts = ['h0:1', 'h1:1', 'h2:1', 'h3:1']
    r1, r2 = HashRing(hosts), HashRing(list(reversed(hosts)))
    # duplicate entries collapse; host ORDER never affects placement
    assert HashRing(hosts + hosts).hosts == hosts
    keys = [f'video{i}' for i in range(500)]
    assert [r1.host_for(k) for k in keys] == [r2.host_for(k) for k in keys]
    for k in keys[:50]:
        order = r1.hosts_for(k)
        assert order[0] == r1.host_for(k)
        assert sorted(order) == sorted(hosts)      # every host, once


def test_ring_rebalance_moves_only_the_removed_hosts_keys():
    """The property the fleet's cache warmth rides on: dropping one of
    N hosts reassigns EXACTLY the keys it owned (~1/N of the space) —
    every other key keeps its backend, its L1 entries, and its warm
    pool."""
    hosts = [f'10.0.0.{i}:9300' for i in range(4)]
    ring = HashRing(hosts)
    keys = [f'sha256:{i:06d}' for i in range(4000)]
    before = {k: ring.host_for(k) for k in keys}
    victim = hosts[1]
    after = ring.without(victim)
    moved = [k for k in keys if before[k] != after.host_for(k)]
    owned = [k for k in keys if before[k] == victim]
    assert set(moved) == set(owned)
    # ~1/N with virtual-node variance: a generous band still catches a
    # broken ring (all keys moving, or none)
    assert 0.10 < len(moved) / len(keys) < 0.45
    # the eligibility FILTER (what the router actually uses mid-flight)
    # agrees with a rebuilt ring: same owners, no rebuild needed
    eligible = set(hosts) - {victim}
    for k in keys[:300]:
        assert ring.hosts_for(k, eligible=eligible)[0] == after.host_for(k)


# -- structured error codes (wire 1.4) ---------------------------------------


def test_error_code_classification_drives_retry():
    """Failover keys on ``ServeError.code``, never on message text: the
    retryable set is exactly {shed, connect_refused, deadline}, and the
    compat subclasses still satisfy the OS-exception types pre-1.4
    callers caught."""
    from video_features_tpu.serve.client import (
        ServeConnectError, ServeDeadlineError, ServeError,
    )
    for code in (protocol.ERR_SHED, protocol.ERR_CONNECT_REFUSED,
                 protocol.ERR_DEADLINE):
        assert ServeError('anything at all', code=code).retryable
    for code in (protocol.ERR_INVALID, protocol.ERR_UNSUPPORTED,
                 protocol.ERR_NOT_FOUND, protocol.ERR_INTERNAL, None):
        assert not ServeError('queue full', code=code).retryable
    assert isinstance(ServeConnectError('x'), ConnectionRefusedError)
    assert ServeConnectError('x').code == protocol.ERR_CONNECT_REFUSED
    assert isinstance(ServeDeadlineError('x'), TimeoutError)
    assert ServeDeadlineError('x').code == protocol.ERR_DEADLINE
    e = ServeError('shed', code=protocol.ERR_SHED,
                   extra={'queue_depth': 64})
    assert e.extra['queue_depth'] == 64


def test_split_fleet_config_validates():
    from video_features_tpu.config import parse_dotlist, split_fleet_config
    fleet, extra = split_fleet_config(parse_dotlist(
        ['fleet_hosts=[127.0.0.1:9301,127.0.0.1:9302]', 'fleet_port=0',
         'feature_type=resnet']))
    assert fleet['fleet_hosts'] == ['127.0.0.1:9301', '127.0.0.1:9302']
    assert fleet['fleet_port'] == 0 and fleet['fleet_max_attempts'] == 3
    assert dict(extra) == {'feature_type': 'resnet'}   # refused by main
    with pytest.raises(ValueError, match='Unknown fleet option'):
        split_fleet_config({'fleet_hots': '127.0.0.1:1'})
    with pytest.raises(ValueError, match='fleet_auth_file'):
        split_fleet_config({'fleet_hosts': ['127.0.0.1:1'],
                            'fleet_http_port': 8080})
    with pytest.raises(ValueError, match='fleet_probe_interval_s'):
        split_fleet_config({'fleet_hosts': ['127.0.0.1:1'],
                            'fleet_probe_interval_s': 0})


def test_l2_knobs_require_their_subsystems():
    from video_features_tpu.config import sanity_check
    base = {'feature_type': 'resnet', 'device': 'cpu',
            'on_extraction': 'save_numpy', 'output_path': '/tmp/o',
            'tmp_path': '/tmp/t'}
    with pytest.raises(ValueError, match='cache_l2_dir requires'):
        sanity_check(dict(base, cache_l2_dir='/tmp/l2'))
    with pytest.raises(ValueError, match='aot_l2_dir requires'):
        sanity_check(dict(base, aot_l2_dir='/tmp/l2'))


# -- shared feature-cache tier -----------------------------------------------


def _seed_entry(cache, tmp_path, key, payload: bytes):
    src = tmp_path / f'{key}.npy'
    src.write_bytes(payload)
    cache.put(key, {'resnet': (str(src), '.npy')}, meta={'n': 1})


def test_tiered_cache_peer_hit_promotes_and_publishes(tmp_path):
    """The two-host story in one process: host A's put lands in the
    shared L2; host B (empty L1, same L2) serves it byte-identically
    and promotes it into its own L1 so the NEXT hit is local."""
    from video_features_tpu.cache.store import FeatureCache
    from video_features_tpu.fleet.tier import TieredFeatureCache
    l2 = str(tmp_path / 'shared')
    a = TieredFeatureCache(str(tmp_path / 'a'), l2)
    b = TieredFeatureCache(str(tmp_path / 'b'), l2)
    payload = os.urandom(512)
    _seed_entry(a, tmp_path, 'k1', payload)
    assert a.stats()['l2_publishes'] == 1
    assert b.contains('k1')                     # union view: via L2

    out = tmp_path / 'out_b'
    assert b.fetch_to('k1', str(out), '/videos/clip.mp4')
    served = out / 'clip_resnet.npy'
    assert served.read_bytes() == payload       # byte-identical via L2
    st = b.stats()
    assert st['peer_hits'] == 1 and st['hits'] == 0
    # promoted: B's own L1 now holds the entry — the next fetch never
    # touches the L2
    assert FeatureCache.contains(b, 'k1')
    assert b.fetch_to('k1', str(tmp_path / 'out_b2'), '/videos/clip.mp4')
    assert b.stats()['peer_hits'] == 1 and b.stats()['hits'] >= 1


def test_tiered_cache_corrupt_l2_entry_is_a_miss(tmp_path):
    """Same integrity contract at both levels: a truncated shared entry
    is evicted, reads as a miss, and is never served."""
    from video_features_tpu.fleet.tier import TieredFeatureCache
    l2_dir = str(tmp_path / 'shared')
    a = TieredFeatureCache(str(tmp_path / 'a'), l2_dir)
    _seed_entry(a, tmp_path, 'k1', os.urandom(256))
    # truncate the SHARED copy only
    edir = Path(a.l2._entry_dir('k1'))
    victim = next(p for p in edir.iterdir() if p.suffix == '.npy')
    victim.write_bytes(b'torn')
    b = TieredFeatureCache(str(tmp_path / 'b'), l2_dir)
    assert not b.fetch_to('k1', str(tmp_path / 'o'), '/v/clip.mp4')
    assert b.stats()['peer_hits'] == 0
    assert b.stats()['l2']['corrupt_evicted'] == 1


def test_tiered_cache_get_pair_is_process_global(tmp_path):
    from video_features_tpu.fleet.tier import TieredFeatureCache
    p1 = TieredFeatureCache.get_pair(tmp_path / 'l1', tmp_path / 'l2')
    p2 = TieredFeatureCache.get_pair(tmp_path / 'l1', tmp_path / 'l2')
    assert p1 is p2
    assert TieredFeatureCache.get_pair(tmp_path / 'x', tmp_path / 'l2') \
        is not p1


# -- shared AOT artifact tier ------------------------------------------------


def test_tiered_exec_store_publish_pull_and_corrupt_purge(tmp_path):
    from video_features_tpu.fleet.artifacts import TieredExecStore
    shared = str(tmp_path / 'artifacts')
    a = TieredExecStore(str(tmp_path / 'aot_a'), shared)
    payload = os.urandom(1024)
    meta = {'program_sha': 'sha256:p1', 'lane': 'mesh1'}
    a.put('digest1', payload, meta)              # publish-on-compile
    assert a.stats()['published'] == 1

    b = TieredExecStore(str(tmp_path / 'aot_b'), shared)
    assert b.contains('digest1')                 # union view
    assert b.metas_for('sha256:p1')              # fleet-wide, not empty L1
    assert b.fetch('digest1') == payload         # pull-on-miss
    st = b.stats()
    assert st['pulled'] == 1
    # re-published locally: the next fetch is an L1 hit (no pull bump)
    assert b.fetch('digest1') == payload
    assert b.stats()['pulled'] == 1

    # a corrupt payload purges BOTH tiers — the shared copy must not
    # re-poison the next cold host
    b.evict_corrupt('digest1')
    assert not b.l2.contains('digest1')
    c = TieredExecStore(str(tmp_path / 'aot_c'), shared)
    assert c.fetch('digest1') is None            # structural miss now


# -- fake-backend router tests ----------------------------------------------


class _FakeBackend:
    """A thread speaking just enough of the loopback protocol: canned
    per-command responses, a call log, and a kill switch."""

    def __init__(self, respond):
        self.respond = respond
        self.calls = []
        self.sock = socket.socket()
        self.sock.bind(('127.0.0.1', 0))
        self.sock.listen(16)
        self.port = self.sock.getsockname()[1]
        self.addr = f'127.0.0.1:{self.port}'
        threading.Thread(target=self._loop, daemon=True).start()

    def _loop(self):
        while True:
            try:
                conn, _ = self.sock.accept()
            except OSError:
                return
            threading.Thread(target=self._serve, args=(conn,),
                             daemon=True).start()

    def _serve(self, conn):
        try:
            with conn:
                rfile, wfile = conn.makefile('rb'), conn.makefile('wb')
                for line in rfile:
                    msg = protocol.decode(line)
                    self.calls.append(msg['cmd'])
                    wfile.write(protocol.encode(self.respond(msg)))
                    wfile.flush()
        except (OSError, ValueError):
            pass

    def kill(self):
        # shutdown BEFORE close: a bare close leaves the listener
        # half-alive in the kernel while the accept thread is blocked
        # on it, and exactly one more connection would sneak through
        try:
            self.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        self.sock.close()


def _healthy(msg, **submit_fields):
    if msg['cmd'] == protocol.CMD_PING:
        return protocol.ok(draining=False, v=protocol.VERSION)
    if msg['cmd'] == protocol.CMD_SUBMIT:
        return protocol.ok(request_id='r1', **submit_fields)
    if msg['cmd'] == protocol.CMD_STATUS:
        return protocol.ok(request_id=msg.get('request_id'), state='done')
    if msg['cmd'] == protocol.CMD_METRICS:
        return protocol.ok(metrics={'queue': {'depth': 2},
                                    'cache': {'hit_rate': 0.25},
                                    'warm_pool': {'builds_compiled': 1,
                                                  'builds_loaded': 0}})
    return protocol.error('unknown', code=protocol.ERR_INVALID)


def _router(hosts, **kw):
    from video_features_tpu.fleet.router import FleetRouter
    opts = dict(port=0, probe_interval_s=30.0, backoff_base_s=0.005,
                connect_timeout_s=0.5)
    opts.update(kw)
    return FleetRouter(hosts, **opts).start()


def test_router_sheds_failover_and_code_propagation():
    """One shedding backend + one healthy one: retryable codes walk the
    ring (counted), non-retryable codes propagate verbatim, and the
    router's own rejections are structured."""
    ok = _FakeBackend(_healthy)
    shed = _FakeBackend(lambda m: _healthy(m) if m['cmd'] != 'submit'
                        else protocol.error('queue full (64/64)',
                                            code=protocol.ERR_SHED))
    router = _router([shed.addr, ok.addr])
    try:
        from video_features_tpu.serve.client import ServeClient
        client = ServeClient(router.port)
        assert client.ping()
        for i in range(8):
            resp = client._call({'cmd': 'submit',
                                 'video_paths': [f'/v/{i}.mp4']})
            assert resp['ok'] and resp['backend'] == ok.addr
        fleet = client.metrics()['fleet']
        assert fleet['routed'][ok.addr] == 8
        assert fleet['routed'][shed.addr] == 0
        # some keys hash to the shedding backend first → failovers
        assert fleet['failovers'] > 0
        # status routes by the remembered request_id → backend binding
        assert client.status('r1')['state'] == 'done'
        from video_features_tpu.serve.client import ServeError
        with pytest.raises(ServeError) as ei:
            client.status('never')
        assert ei.value.code == protocol.ERR_NOT_FOUND
    finally:
        router.stop()
        ok.kill()
        shed.kill()


def test_router_invalid_request_never_retries():
    """A request the whole fleet would reject identically must fail
    ONCE — retrying an `invalid` N times would triple every bad
    request's latency and lie about the failure."""
    calls = []

    def invalid(msg):
        if msg['cmd'] == protocol.CMD_PING:
            return protocol.ok(draining=False)
        calls.append(msg['cmd'])
        return protocol.error('unknown feature_type zzz',
                              code=protocol.ERR_INVALID)
    b1, b2 = _FakeBackend(invalid), _FakeBackend(invalid)
    router = _router([b1.addr, b2.addr])
    try:
        from video_features_tpu.serve.client import ServeClient, ServeError
        with pytest.raises(ServeError) as ei:
            ServeClient(router.port).submit('zzz', ['/v/a.mp4'])
        assert ei.value.code == protocol.ERR_INVALID
        assert len(calls) == 1                   # no second backend tried
    finally:
        router.stop()
        b1.kill()
        b2.kill()


def test_router_kill_midstream_survivor_takes_over():
    """The acceptance semantics: killing a backend fails only what was
    in flight on it; the very next submit routes to the survivor
    (proactive unhealthy-marking on connect_refused, no probe wait),
    and the probe keeps it out of the eligible set."""
    b1, b2 = _FakeBackend(_healthy), _FakeBackend(_healthy)
    router = _router([b1.addr, b2.addr], max_attempts=2)
    try:
        from video_features_tpu.serve.client import ServeClient
        client = ServeClient(router.port)
        assert sorted(router.eligible()) == sorted([b1.addr, b2.addr])
        b1.kill()
        # every submit still lands (failover covers b1's keys)
        for i in range(8):
            resp = client._call({'cmd': 'submit',
                                 'video_paths': [f'/v/{i}.mp4']})
            assert resp['ok'] and resp['backend'] == b2.addr, resp
        assert router.eligible() == [b2.addr]    # marked without a probe
        table = router.probe()
        assert not table[b1.addr]['healthy']
        assert table[b2.addr]['healthy']
        # with BOTH dead the router sheds with a structured code
        b2.kill()
        router.probe()
        from video_features_tpu.serve.client import ServeError
        with pytest.raises(ServeError) as ei:
            client._call({'cmd': 'submit', 'video_paths': ['/v/z.mp4']})
        assert ei.value.code == protocol.ERR_SHED
        assert ei.value.retryable                # a later fleet may recover
    finally:
        router.stop()


def test_router_drain_aware_membership():
    """A DRAINING backend is alive (its ping answers) but leaves the
    eligible set — new work must not land on a host that is shutting
    down; it comes back when the drain flag clears."""
    state = {'draining': False}

    def drainable(msg):
        if msg['cmd'] == protocol.CMD_PING:
            return protocol.ok(draining=state['draining'])
        return _healthy(msg)
    d = _FakeBackend(drainable)
    ok = _FakeBackend(_healthy)
    router = _router([d.addr, ok.addr])
    try:
        assert sorted(router.eligible()) == sorted([d.addr, ok.addr])
        state['draining'] = True
        router.probe()
        assert router.eligible() == [ok.addr]
        from video_features_tpu.serve.client import ServeClient
        for i in range(4):
            resp = ServeClient(router.port)._call(
                {'cmd': 'submit', 'video_paths': [f'/v/{i}.mp4']})
            assert resp['ok'] and resp['backend'] == ok.addr
        state['draining'] = False                # drain cancelled
        router.probe()
        assert sorted(router.eligible()) == sorted([d.addr, ok.addr])
    finally:
        router.stop()
        d.kill()
        ok.kill()


def test_router_failover_yields_one_merged_trace():
    """Acceptance pin (vft-scope): a submit that fails over mid-walk
    yields ONE trace — the router's route/failover spans plus spans
    from BOTH attempted backends, merged ts-sorted under a single
    trace_id, every event stamped with its contributing host."""
    import re

    def traced(tag, captured, shed_submit=False):
        def respond(msg):
            if msg['cmd'] == protocol.CMD_PING:
                return protocol.ok(draining=False)
            if msg['cmd'] == protocol.CMD_SUBMIT:
                captured[tag] = msg.get('traceparent')
                if shed_submit:
                    return protocol.error('queue full (64/64)',
                                          code=protocol.ERR_SHED)
                return protocol.ok(request_id='r-trace')
            if msg['cmd'] == protocol.CMD_TRACE:
                tid = captured[tag].split('-')[1]
                return protocol.ok(
                    request_id=msg.get('request_id'), trace_id=tid,
                    state='done',
                    events=[{'name': f'{tag}_admission', 'ph': 'X',
                             'ts': 10.0 if shed_submit else 20.0,
                             'dur': 5.0, 'pid': 1, 'tid': 1,
                             'args': {'trace_id': tid}}])
            return protocol.error('unknown', code=protocol.ERR_INVALID)
        return respond

    captured = {}
    shed = _FakeBackend(traced('shed', captured, shed_submit=True))
    ok = _FakeBackend(traced('ok', captured))
    router = _router([shed.addr, ok.addr])
    try:
        from video_features_tpu.fleet.router import FleetRouter
        from video_features_tpu.serve.client import ServeClient
        # pick a key the SHEDDING backend owns, so the ring walk
        # attempts it first and fails over to the healthy one
        path = next(
            p for p in (f'/v/trace{i}.mp4' for i in range(200))
            if router.ring.host_for(FleetRouter.route_key(
                {'video_paths': [p]})) == shed.addr)
        client = ServeClient(router.port)
        resp = client._call({'cmd': 'submit', 'video_paths': [path]})
        assert resp['ok'] and resp['backend'] == ok.addr
        rid = resp['request_id']

        # the router minted ONE W3C traceparent and forwarded it to
        # BOTH attempted backends — same trace_id on each wire
        w3c = re.compile(r'^00-[0-9a-f]{32}-[0-9a-f]{16}-[0-9a-f]{2}$')
        assert w3c.match(captured['shed']), captured
        assert w3c.match(captured['ok']), captured
        tid = captured['ok'].split('-')[1]
        assert captured['shed'].split('-')[1] == tid

        trace = client.trace(rid)
        assert trace['trace_id'] == tid
        assert sorted(trace['hosts']) == sorted(
            ['router', shed.addr, ok.addr])
        spans = [e for e in trace['events'] if e.get('ph') != 'M']
        by_host = {}
        for e in spans:
            by_host.setdefault(e['args']['host'], []).append(e['name'])
        assert 'shed_admission' in by_host[shed.addr]
        assert 'ok_admission' in by_host[ok.addr]
        assert 'failover' in by_host['router']
        assert 'route' in by_host['router']
        assert by_host['router'].count('backend_call') == 2
        # merged presentation order: ts-sorted across all hosts
        ts = [e['ts'] for e in spans]
        assert ts == sorted(ts)
        assert client.metrics()['fleet']['failovers'] >= 1
    finally:
        router.stop()
        shed.kill()
        ok.kill()


def test_router_metrics_prom_aggregates_host_labeled_families():
    """The router's exposition is the FLEET's: every backend's families
    relabeled ``host=``, family headers emitted once, plus the router's
    own ``vft_fleet_*`` and ``vft_slo_*`` series."""
    def with_prom(msg):
        if msg['cmd'] == protocol.CMD_METRICS_PROM:
            return protocol.ok(text='# HELP vft_up liveness\n'
                                    '# TYPE vft_up gauge\n'
                                    'vft_up 1\n')
        return _healthy(msg)
    b1, b2 = _FakeBackend(with_prom), _FakeBackend(with_prom)
    router = _router([b1.addr, b2.addr])
    try:
        from video_features_tpu.serve.client import ServeClient
        client = ServeClient(router.port)
        resp = client._call({'cmd': 'submit', 'video_paths': ['/v/a.mp4']})
        assert resp['ok']
        text = client.metrics_prom()
        for addr in (b1.addr, b2.addr):
            assert f'vft_up{{host="{addr}"}} 1' in text, text
            assert f'vft_fleet_backend_up{{host="{addr}"}} 1' in text
            assert f'vft_fleet_probe_age_seconds{{host="{addr}"}}' in text
        # one merged family header despite two contributing hosts
        assert text.count('# TYPE vft_up gauge') == 1
        assert 'vft_fleet_routed_total{host=' in text
        assert 'vft_fleet_requests_total{outcome="completed"} 1' in text
        assert 'vft_slo_latency_burn_rate{window="5m"}' in text
        assert 'vft_slo_availability_burn_rate{window="1h"}' in text
        # a dead backend contributes nothing but stays visible as down
        b2.kill()
        router.probe()
        text = router.metrics_prom()
        assert f'vft_up{{host="{b2.addr}"}}' not in text
        assert f'vft_fleet_backend_up{{host="{b2.addr}"}} 0' in text
    finally:
        router.stop()
        b1.kill()
        b2.kill()


# -- real two-backend integration (the acceptance scenario) ------------------


@pytest.fixture(scope='module')
def fleet_clip(tmp_path_factory):
    d = tmp_path_factory.mktemp('fleetvids')
    return str(_write_clip(d / 'fv0.mp4', 6, seed=7))


def _fleet_overrides(tmp_path, host_tag, shared):
    return {
        'device': 'cpu', 'model_name': 'resnet18', 'batch_size': 4,
        'allow_random_weights': True, 'on_extraction': 'save_numpy',
        'tmp_path': str(tmp_path / f'{host_tag}_tmp'),
        'cache_enabled': True,
        'cache_dir': str(tmp_path / f'{host_tag}_cache'),
        'cache_l2_dir': str(shared / 'features'),
        'aot_enabled': True,
        'aot_dir': str(tmp_path / f'{host_tag}_aot'),
        'aot_l2_dir': str(shared / 'artifacts'),
    }


def test_fleet_two_backends_cache_parity_and_cold_boot(
        fleet_clip, tmp_path):
    """Two real serve daemons sharing an L2 feature cache + artifact
    tier behind a router:

    1. the ring owner extracts the clip (compiles, publishes features
       to the L2 and executables to the artifact tier);
    2. the OTHER backend pre-warms compile-free off the peer's
       executables (``builds_compiled == 0``, ``builds_loaded >= 1``);
    3. the owner dies; the router routes the same video to the
       survivor, which serves it byte-identically from the shared
       cache WITHOUT decoding (admission-time 'cached' status — no
       extraction task, hence no decode, ever enqueued).
    """
    from video_features_tpu.fleet.router import FleetRouter
    from video_features_tpu.serve.client import ServeClient
    from video_features_tpu.serve.server import ExtractionServer
    from video_features_tpu.utils.output import make_path

    shared = tmp_path / 'shared'
    servers = {}
    for tag in ('a', 'b'):
        servers[tag] = ExtractionServer(
            base_overrides=_fleet_overrides(tmp_path, tag, shared),
            queue_depth=16, pool_size=2).start()
    addr = {tag: f'127.0.0.1:{s.port}' for tag, s in servers.items()}
    router = FleetRouter(list(addr.values()), port=0,
                         probe_interval_s=30.0).start()
    try:
        client = ServeClient(router.port)
        owner_addr = router.ring.host_for(
            FleetRouter.route_key({'video_paths': [fleet_clip]}))
        owner = next(t for t in servers if addr[t] == owner_addr)
        other = 'b' if owner == 'a' else 'a'

        # 1: extract on the ring owner, through the router
        out1 = str(tmp_path / 'out1')
        rid = client.submit('resnet', [fleet_clip],
                            overrides={'output_path': out1})
        st = client.wait(rid, timeout_s=300)
        assert st['state'] == 'done' and st['videos'][fleet_clip] == 'saved'
        assert client.metrics()['fleet']['routed'][owner_addr] == 1

        # 2: cold boot on the survivor: its empty L1 pulls the peer's
        # executables from the shared artifact tier — zero compiles
        report = servers[other].prewarm(['resnet'])
        assert report['errors'] == []
        m_other = servers[other].metrics()['warm_pool']
        assert m_other['builds_compiled'] == 0, m_other
        assert m_other['builds_loaded'] >= 1, m_other

        # 3: the owner dies mid-fleet; the survivor serves the same
        # video from the shared cache, byte-identically, no decode
        servers[owner].drain(wait=True, grace_s=60)
        router.probe()
        assert router.eligible() == [addr[other]]
        out2 = str(tmp_path / 'out2')
        rid2 = client.submit('resnet', [fleet_clip],
                             overrides={'output_path': out2})
        st2 = client.wait(rid2, timeout_s=120)
        assert st2['state'] == 'done'
        assert st2['videos'][fleet_clip] == 'cached'    # admission hit
        for key in ('resnet', 'fps', 'timestamps_ms'):
            p1 = Path(make_path(os.path.join(out1, 'resnet', 'resnet18'),
                                fleet_clip, key, '.npy'))
            p2 = Path(make_path(os.path.join(out2, 'resnet', 'resnet18'),
                                fleet_clip, key, '.npy'))
            assert p1.read_bytes() == p2.read_bytes(), key
        m = servers[other].metrics()
        assert m['warm_pool']['builds_compiled'] == 0   # still never compiled
        assert m['requests']['cached_videos'] >= 1
        # the serve-side tier saw the peer hit (L2 → L1 promotion)
        assert m['cache']['peer_hits'] >= 1, m['cache']
    finally:
        router.stop()
        for s in servers.values():
            try:
                s.drain(wait=True, grace_s=30)
            except Exception:
                pass
