"""Missing-checkpoint hard error + allow_random_weights escape.

The reference always runs real weights (extract_i3d.py:180-183,
extract_resnet.py:38-40); our equivalent guarantee is that a run without a
configured checkpoint fails loudly, naming the config key, unless random
weights are explicitly allowed (extract/weights.py).
"""
import pytest

from video_features_tpu.config import load_config
from video_features_tpu.extract.weights import ENV_FLAG, MissingCheckpointError
from video_features_tpu.registry import create_extractor


def _resnet_args(tmp_path, **over):
    return load_config('resnet', overrides={
        'video_paths': 'v.mp4', 'output_path': str(tmp_path / 'o'),
        'tmp_path': str(tmp_path / 't'), 'device': 'cpu',
        'model_name': 'resnet18', **over})


def test_missing_checkpoint_is_hard_error(tmp_path, monkeypatch):
    monkeypatch.delenv(ENV_FLAG, raising=False)
    with pytest.raises(MissingCheckpointError) as exc:
        create_extractor(_resnet_args(tmp_path))
    assert 'checkpoint_path' in str(exc.value)
    assert 'fetch_checkpoints' in str(exc.value)


def test_i3d_error_names_stream_specific_key(tmp_path, monkeypatch):
    monkeypatch.delenv(ENV_FLAG, raising=False)
    args = load_config('i3d', overrides={
        'video_paths': 'v.mp4', 'output_path': str(tmp_path / 'o'),
        'tmp_path': str(tmp_path / 't'), 'device': 'cpu', 'streams': 'rgb'})
    with pytest.raises(MissingCheckpointError, match='i3d_rgb_checkpoint_path'):
        create_extractor(args)


def test_allow_random_weights_config_flag(tmp_path, monkeypatch, capsys):
    monkeypatch.delenv(ENV_FLAG, raising=False)
    ex = create_extractor(_resnet_args(tmp_path, allow_random_weights=True))
    assert ex is not None
    assert 'RANDOM weights' in capsys.readouterr().err  # stderr: stdout is machine-read


def test_env_escape_hatch(tmp_path, monkeypatch):
    monkeypatch.setenv(ENV_FLAG, '1')
    assert create_extractor(_resnet_args(tmp_path)) is not None
