"""RAFT extractor: E2E flow extraction with pair batching + flow_viz."""
from pathlib import Path

import numpy as np
import pytest

from video_features_tpu.config import load_config
from video_features_tpu.io.video import get_video_props
from video_features_tpu.registry import create_extractor
from video_features_tpu.utils.flow_viz import flow_to_image, make_colorwheel


@pytest.mark.slow
def test_e2e_flow(short_video, tmp_path):
    args = load_config('raft', overrides={
        'video_paths': short_video,
        'device': 'cpu',
        'batch_size': 16,
        'side_size': 128,        # small frames keep CPU runtime sane
        'show_pred': True,       # headless flow viz writes PNG artifacts
        'output_path': str(tmp_path / 'out'),
        'tmp_path': str(tmp_path / 'tmp'),
    })
    ex = create_extractor(args)
    feats = ex.extract(short_video)

    # headless show_pred preserves the reference's cv2-window debug
    # capability (base_flow_extractor.py:134-149) as on-disk PNGs
    pngs = list((Path(args.output_path) / 'flow_debug').glob('*.png'))
    assert pngs, 'show_pred=true must write rendered flow PNGs'

    n = get_video_props(short_video)['num_frames']
    flow = feats['raft']
    # reference contract: (T-1, 2, H, W) channels-first on disk
    assert flow.shape[0] == n - 1
    assert flow.shape[1] == 2
    # side_size=128 on a 320x240 video -> 128 is the smaller (height) edge
    assert min(flow.shape[2], flow.shape[3]) == 128
    assert np.isfinite(flow).all()
    # timestamps cover every decoded frame (one more than flows)
    assert len(feats['timestamps_ms']) == n
    assert feats['fps'] > 0


def test_colorwheel():
    wheel = make_colorwheel()
    assert wheel.shape == (55, 3)
    assert wheel.max() == 255 and wheel.min() == 0


def test_flow_to_image():
    rng = np.random.RandomState(0)
    flow = rng.randn(16, 24, 2).astype(np.float32) * 3
    img = flow_to_image(flow)
    assert img.shape == (16, 24, 3)
    assert img.dtype == np.uint8
    # zero flow maps to (near-)white center of the wheel
    white = flow_to_image(np.zeros((4, 4, 2), np.float32))
    assert (white > 250).all()


@pytest.mark.slow
def test_raft_iters_knob(short_video, tmp_path):
    """raft_iters controls refinement depth for the raft family (upstream
    RAFT's own iters parameter, raft_src/raft.py:118): fewer iterations
    produce a valid flow field and a different (less-refined) result."""
    def run(iters):
        args = load_config('raft', overrides={
            'video_paths': short_video, 'device': 'cpu', 'batch_size': 4,
            'extraction_total': 5, 'side_size': 128,
            'raft_iters': iters, 'allow_random_weights': True,
            'output_path': str(tmp_path / f'o{iters}'),
            'tmp_path': str(tmp_path / f't{iters}'),
        })
        return create_extractor(args).extract(short_video)['raft']

    few, full = run(2), run(20)
    assert few.shape == full.shape
    assert np.isfinite(few).all() and np.isfinite(full).all()
    assert not np.allclose(few, full)      # depth changes the refinement


@pytest.mark.slow
def test_bucket_multiple_shares_executables(short_video, tmp_path):
    """bucket_multiple=64 rounds the replicate-pad to coarse buckets so
    near-alike resolutions share ONE compiled step (shapes are static
    per jit — without bucketing every distinct source geometry is a
    fresh multi-minute compile). Checks (a) two different side_size
    geometries land in one executable, (b) outputs keep their exact
    source geometries, and (c) the measured flow delta vs the
    reference-exact /8 pad (the cost of the wider visible pad) is on
    record."""
    def run(side, bucket, tag):
        args = load_config('raft', overrides={
            'video_paths': short_video, 'device': 'cpu', 'batch_size': 4,
            'extraction_total': 5, 'side_size': side,
            'raft_iters': 2, 'allow_random_weights': True,
            'bucket_multiple': bucket,
            'output_path': str(tmp_path / f'o{tag}'),
            'tmp_path': str(tmp_path / f't{tag}'),
        })
        ex = create_extractor(args)
        return ex, ex.extract(short_video)['raft']

    # short_video is 320x240: side 96 -> 96x128 frames, side 90 -> 90x120;
    # both round up to 128x128 at bucket 64 (one executable), while at
    # the reference /8 pad they are distinct padded shapes (96x128 is
    # already /8; 90x120 pads to 96x120)
    ex96, flow96 = run(96, 64, 'b96')
    assert ex96._step._cache_size() == 1
    _, flow90 = run(90, 64, 'b90')
    # same underlying jit cache only if it's the same Extractor instance;
    # instead assert via a single instance processing both geometries
    args = load_config('raft', overrides={
        'video_paths': short_video, 'device': 'cpu', 'batch_size': 4,
        'extraction_total': 5, 'raft_iters': 2,
        'allow_random_weights': True, 'bucket_multiple': 64,
        'output_path': str(tmp_path / 'oshared'),
        'tmp_path': str(tmp_path / 'tshared'),
    })
    ex = create_extractor(args)
    for side in (96, 90):
        ex.side_size = side
        ex.extract(short_video)
    assert ex._step._cache_size() == 1, (
        'bucketed geometries must share one compiled executable')

    # geometry contract: outputs keep exact source dims
    assert flow96.shape[2:] == (96, 128)
    assert flow90.shape[2:] == (90, 120)

    # numeric cost vs the reference-exact /8 pad, on record
    _, flow96_ref = run(96, 8, 'ref96')
    assert flow96.shape == flow96_ref.shape
    rel = (np.linalg.norm(flow96 - flow96_ref)
           / max(np.linalg.norm(flow96_ref), 1e-12))
    print(f'[bucket] flow rel L2 bucket64 vs /8 pad: {rel:.3e}')
    assert np.isfinite(rel)
