"""RAFT extractor: E2E flow extraction with pair batching + flow_viz."""
from pathlib import Path

import numpy as np
import pytest

from video_features_tpu.config import load_config
from video_features_tpu.io.video import get_video_props
from video_features_tpu.registry import create_extractor
from video_features_tpu.utils.flow_viz import flow_to_image, make_colorwheel


@pytest.mark.slow
def test_e2e_flow(short_video, tmp_path):
    args = load_config('raft', overrides={
        'video_paths': short_video,
        'device': 'cpu',
        'batch_size': 16,
        'side_size': 128,        # small frames keep CPU runtime sane
        'show_pred': True,       # headless flow viz writes PNG artifacts
        'output_path': str(tmp_path / 'out'),
        'tmp_path': str(tmp_path / 'tmp'),
    })
    ex = create_extractor(args)
    feats = ex.extract(short_video)

    # headless show_pred preserves the reference's cv2-window debug
    # capability (base_flow_extractor.py:134-149) as on-disk PNGs
    pngs = list((Path(args.output_path) / 'flow_debug').glob('*.png'))
    assert pngs, 'show_pred=true must write rendered flow PNGs'

    n = get_video_props(short_video)['num_frames']
    flow = feats['raft']
    # reference contract: (T-1, 2, H, W) channels-first on disk
    assert flow.shape[0] == n - 1
    assert flow.shape[1] == 2
    # side_size=128 on a 320x240 video -> 128 is the smaller (height) edge
    assert min(flow.shape[2], flow.shape[3]) == 128
    assert np.isfinite(flow).all()
    # timestamps cover every decoded frame (one more than flows)
    assert len(feats['timestamps_ms']) == n
    assert feats['fps'] > 0


def test_colorwheel():
    wheel = make_colorwheel()
    assert wheel.shape == (55, 3)
    assert wheel.max() == 255 and wheel.min() == 0


def test_flow_to_image():
    rng = np.random.RandomState(0)
    flow = rng.randn(16, 24, 2).astype(np.float32) * 3
    img = flow_to_image(flow)
    assert img.shape == (16, 24, 3)
    assert img.dtype == np.uint8
    # zero flow maps to (near-)white center of the wheel
    white = flow_to_image(np.zeros((4, 4, 2), np.float32))
    assert (white > 250).all()


@pytest.mark.slow
def test_raft_iters_knob(short_video, tmp_path):
    """raft_iters controls refinement depth for the raft family (upstream
    RAFT's own iters parameter, raft_src/raft.py:118): fewer iterations
    produce a valid flow field and a different (less-refined) result."""
    def run(iters):
        args = load_config('raft', overrides={
            'video_paths': short_video, 'device': 'cpu', 'batch_size': 4,
            'extraction_total': 5, 'side_size': 128,
            'raft_iters': iters, 'allow_random_weights': True,
            'output_path': str(tmp_path / f'o{iters}'),
            'tmp_path': str(tmp_path / f't{iters}'),
        })
        return create_extractor(args).extract(short_video)['raft']

    few, full = run(2), run(20)
    assert few.shape == full.shape
    assert np.isfinite(few).all() and np.isfinite(full).all()
    assert not np.allclose(few, full)      # depth changes the refinement
