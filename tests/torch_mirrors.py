"""State-dict-compatible torch mirrors of torchvision ResNet / VideoResNet.

torchvision is not installed in this environment, but the reference's r21d
and resnet extractors are thin wrappers over torchvision nets
(reference models/r21d/extract_r21d.py:109-118,
models/resnet/extract_resnet.py:38-40). These mirrors reproduce the exact
module tree — same state_dict keys, same math — so parity tests can
transplant a seeded torch net into our JAX models and compare numerics,
and real torchvision checkpoints load into them unchanged.
"""
from __future__ import annotations

import torch
import torch.nn as nn
import torch.nn.functional as F

from video_features_tpu.models.r21d import midplanes

# ---------------------------------------------------------------- resnet --


class _TVBasicBlock(nn.Module):
    expansion = 1

    def __init__(self, in_p, planes, stride=1, downsample=None):
        super().__init__()
        self.conv1 = nn.Conv2d(in_p, planes, 3, stride, 1, bias=False)
        self.bn1 = nn.BatchNorm2d(planes)
        self.conv2 = nn.Conv2d(planes, planes, 3, 1, 1, bias=False)
        self.bn2 = nn.BatchNorm2d(planes)
        self.downsample = downsample

    def forward(self, x):
        identity = x if self.downsample is None else self.downsample(x)
        out = F.relu(self.bn1(self.conv1(x)))
        out = self.bn2(self.conv2(out))
        return F.relu(out + identity)


class _TVBottleneck(nn.Module):
    expansion = 4

    def __init__(self, in_p, planes, stride=1, downsample=None,
                 groups=1, base_width=64):
        super().__init__()
        # torchvision Bottleneck: conv1/conv2 at width = planes *
        # base_width/64 * groups (ResNeXt groups, wide-ResNet base_width)
        width = int(planes * base_width / 64) * groups
        self.conv1 = nn.Conv2d(in_p, width, 1, bias=False)
        self.bn1 = nn.BatchNorm2d(width)
        # stride on the 3x3 = torchvision's ResNet V1.5 convention
        self.conv2 = nn.Conv2d(width, width, 3, stride, 1, groups=groups,
                               bias=False)
        self.bn2 = nn.BatchNorm2d(width)
        self.conv3 = nn.Conv2d(width, planes * 4, 1, bias=False)
        self.bn3 = nn.BatchNorm2d(planes * 4)
        self.downsample = downsample

    def forward(self, x):
        identity = x if self.downsample is None else self.downsample(x)
        out = F.relu(self.bn1(self.conv1(x)))
        out = F.relu(self.bn2(self.conv2(out)))
        out = self.bn3(self.conv3(out))
        return F.relu(out + identity)


class TorchResNet(nn.Module):
    """torchvision.models.resnet* mirror (IMAGENET1K layout)."""

    CFGS = {
        'resnet18': (_TVBasicBlock, [2, 2, 2, 2], {}),
        'resnet34': (_TVBasicBlock, [3, 4, 6, 3], {}),
        'resnet50': (_TVBottleneck, [3, 4, 6, 3], {}),
        'resnet101': (_TVBottleneck, [3, 4, 23, 3], {}),
        'resnet152': (_TVBottleneck, [3, 8, 36, 3], {}),
        'resnext50_32x4d': (_TVBottleneck, [3, 4, 6, 3],
                            dict(groups=32, base_width=4)),
        'resnext101_32x8d': (_TVBottleneck, [3, 4, 23, 3],
                             dict(groups=32, base_width=8)),
        'resnext101_64x4d': (_TVBottleneck, [3, 4, 23, 3],
                             dict(groups=64, base_width=4)),
        'wide_resnet50_2': (_TVBottleneck, [3, 4, 6, 3],
                            dict(base_width=128)),
        'wide_resnet101_2': (_TVBottleneck, [3, 4, 23, 3],
                             dict(base_width=128)),
    }

    def __init__(self, arch='resnet50', num_classes=1000):
        super().__init__()
        block, layers, bkw = self.CFGS[arch]
        self.conv1 = nn.Conv2d(3, 64, 7, 2, 3, bias=False)
        self.bn1 = nn.BatchNorm2d(64)
        self.maxpool = nn.MaxPool2d(3, 2, 1)
        in_p = 64
        for li, (nb, planes) in enumerate(zip(layers, [64, 128, 256, 512]), 1):
            blocks = []
            for bi in range(nb):
                stride = 2 if (li > 1 and bi == 0) else 1
                down = None
                if stride != 1 or in_p != planes * block.expansion:
                    down = nn.Sequential(
                        nn.Conv2d(in_p, planes * block.expansion, 1, stride,
                                  bias=False),
                        nn.BatchNorm2d(planes * block.expansion))
                blocks.append(block(in_p, planes, stride, down, **bkw))
                in_p = planes * block.expansion
            setattr(self, f'layer{li}', nn.Sequential(*blocks))
        self.fc = nn.Linear(in_p, num_classes)

    def forward(self, x, features=True):
        x = self.maxpool(F.relu(self.bn1(self.conv1(x))))
        for li in range(1, 5):
            x = getattr(self, f'layer{li}')(x)
        x = x.mean(dim=(2, 3))
        return x if features else self.fc(x)


# ------------------------------------------------------------------ r21d --


class _Conv2Plus1D(nn.Sequential):
    """torchvision Conv2Plus1D: spatial conv → BN → ReLU → temporal conv."""

    def __init__(self, in_p, out_p, mid, stride=1):
        super().__init__(
            nn.Conv3d(in_p, mid, (1, 3, 3), (1, stride, stride), (0, 1, 1),
                      bias=False),
            nn.BatchNorm3d(mid),
            nn.ReLU(inplace=True),
            nn.Conv3d(mid, out_p, (3, 1, 1), (stride, 1, 1), (1, 0, 0),
                      bias=False))


class _VRBasicBlock(nn.Module):
    def __init__(self, in_p, planes, stride=1, downsample=None):
        super().__init__()
        self.conv1 = nn.Sequential(
            _Conv2Plus1D(in_p, planes, midplanes(in_p, planes), stride),
            nn.BatchNorm3d(planes), nn.ReLU(inplace=True))
        self.conv2 = nn.Sequential(
            _Conv2Plus1D(planes, planes, midplanes(planes, planes)),
            nn.BatchNorm3d(planes))
        self.downsample = downsample

    def forward(self, x):
        identity = x if self.downsample is None else self.downsample(x)
        return F.relu(self.conv2(self.conv1(x)) + identity)


class TorchVideoResNet(nn.Module):
    """torchvision.models.video.r2plus1d_18/34 mirror (R2Plus1dStem)."""

    CFGS = {'r2plus1d_18': [2, 2, 2, 2], 'r2plus1d_34': [3, 4, 6, 3]}

    def __init__(self, arch='r2plus1d_18', num_classes=400):
        super().__init__()
        layers = self.CFGS[arch]
        self.stem = nn.Sequential(
            nn.Conv3d(3, 45, (1, 7, 7), (1, 2, 2), (0, 3, 3), bias=False),
            nn.BatchNorm3d(45), nn.ReLU(inplace=True),
            nn.Conv3d(45, 64, (3, 1, 1), 1, (1, 0, 0), bias=False),
            nn.BatchNorm3d(64), nn.ReLU(inplace=True))
        in_p = 64
        for li, (nb, planes) in enumerate(zip(layers, [64, 128, 256, 512]), 1):
            blocks = []
            for bi in range(nb):
                stride = 2 if (li > 1 and bi == 0) else 1
                down = None
                if stride != 1 or in_p != planes:
                    down = nn.Sequential(
                        nn.Conv3d(in_p, planes, 1, (stride, stride, stride),
                                  bias=False),
                        nn.BatchNorm3d(planes))
                blocks.append(_VRBasicBlock(in_p, planes, stride, down))
                in_p = planes
            setattr(self, f'layer{li}', nn.Sequential(*blocks))
        self.fc = nn.Linear(512, num_classes)

    def forward(self, x, features=True):
        x = self.stem(x)
        for li in range(1, 5):
            x = getattr(self, f'layer{li}')(x)
        x = x.mean(dim=(2, 3, 4))
        return x if features else self.fc(x)


def randomize_bn_stats(model: nn.Module, seed: int = 0) -> None:
    """Give every BN layer non-trivial running stats (fresh modules carry
    mean=0/var=1, which would hide transplant bugs in those tensors)."""
    gen = torch.Generator().manual_seed(seed)
    for m in model.modules():
        if isinstance(m, (nn.BatchNorm2d, nn.BatchNorm3d)):
            m.running_mean = torch.randn(
                m.num_features, generator=gen) * 0.1
            m.running_var = torch.rand(m.num_features, generator=gen) + 0.5


# -------------------------------------------------------------- convnext --


class _LayerNorm2d(nn.LayerNorm):
    """timm LayerNorm2d: LN over C of an NCHW tensor."""

    def forward(self, x):
        x = x.permute(0, 2, 3, 1)
        x = super().forward(x)
        return x.permute(0, 3, 1, 2)


class _CNBlock(nn.Module):
    def __init__(self, dim):
        super().__init__()
        self.conv_dw = nn.Conv2d(dim, dim, 7, padding=3, groups=dim)
        self.norm = nn.LayerNorm(dim, eps=1e-6)
        self.mlp = nn.Module()
        self.mlp.fc1 = nn.Linear(dim, 4 * dim)
        self.mlp.fc2 = nn.Linear(4 * dim, dim)
        self.gamma = nn.Parameter(torch.full((dim,), 1e-6))

    def forward(self, x):
        h = self.conv_dw(x).permute(0, 2, 3, 1)
        h = self.mlp.fc2(F.gelu(self.mlp.fc1(self.norm(h))))
        return x + (self.gamma * h).permute(0, 3, 1, 2)


class _CNStage(nn.Module):
    def __init__(self, in_dim, dim, depth, downsample):
        super().__init__()
        if downsample:
            self.downsample = nn.Sequential(
                _LayerNorm2d(in_dim, eps=1e-6),
                nn.Conv2d(in_dim, dim, 2, 2))
        self.blocks = nn.Sequential(*[_CNBlock(dim) for _ in range(depth)])

    def forward(self, x):
        if hasattr(self, 'downsample'):
            x = self.downsample(x)
        return self.blocks(x)


class TorchConvNeXt(nn.Module):
    """timm `ConvNeXt` mirror (stem/stages/head state_dict layout)."""

    CFGS = {
        'convnext_tiny': ((3, 3, 9, 3), (96, 192, 384, 768)),
        'convnext_small': ((3, 3, 27, 3), (96, 192, 384, 768)),
        'convnext_base': ((3, 3, 27, 3), (128, 256, 512, 1024)),
        'convnext_large': ((3, 3, 27, 3), (192, 384, 768, 1536)),
    }

    def __init__(self, arch='convnext_tiny', num_classes=1000):
        super().__init__()
        depths, dims = self.CFGS[arch]
        self.stem = nn.Sequential(nn.Conv2d(3, dims[0], 4, 4),
                                  _LayerNorm2d(dims[0], eps=1e-6))
        self.stages = nn.Sequential(*[
            _CNStage(dims[max(s - 1, 0)], dims[s], depths[s], s > 0)
            for s in range(4)])
        self.head = nn.Module()
        self.head.norm = nn.LayerNorm(dims[-1], eps=1e-6)
        self.head.fc = nn.Linear(dims[-1], num_classes)

    def forward(self, x, features=True):
        x = self.stages(self.stem(x)).mean(dim=(2, 3))
        x = self.head.norm(x)
        return x if features else self.head.fc(x)


# ---------------------------------------------------------------- vggish --


class TorchVGGish(nn.Module):
    """The reference VGG audio net (vggish_slim.py:15-37,100-111): conv
    feature stack + channels-last flatten + 3-layer FC embeddings. Same
    state_dict keys as the harritaylor/torchvggish checkpoint the reference
    downloads, so real weights load unchanged.
    """

    def __init__(self):
        super().__init__()
        layers, in_ch = [], 1
        for v in [64, 'M', 128, 'M', 256, 256, 'M', 512, 512, 'M']:
            if v == 'M':
                layers.append(nn.MaxPool2d(2, 2))
            else:
                layers.append(nn.Conv2d(in_ch, v, 3, padding=1))
                layers.append(nn.ReLU())
                in_ch = v
        self.features = nn.Sequential(*layers)
        self.embeddings = nn.Sequential(
            nn.Linear(512 * 4 * 6, 4096), nn.ReLU(),
            nn.Linear(4096, 4096), nn.ReLU(),
            nn.Linear(4096, 128), nn.ReLU())

    def forward(self, x):
        # (B, 1, 96, 64) NCHW → NHWC flatten before the FCs (the
        # tensorflow-era layout quirk the reference preserves)
        h = self.features(x)
        h = h.transpose(1, 3).transpose(1, 2).contiguous()
        return self.embeddings(h.view(h.size(0), -1))


# ------------------------------------------------------------------ swin --


def _swin_rel_index(wh, ww):
    import numpy as np
    coords = np.stack(np.meshgrid(np.arange(wh), np.arange(ww),
                                  indexing='ij'))
    flat = coords.reshape(2, -1)
    rel = flat[:, :, None] - flat[:, None, :]
    rel = rel.transpose(1, 2, 0).copy()
    rel[:, :, 0] += wh - 1
    rel[:, :, 1] += ww - 1
    rel[:, :, 0] *= 2 * ww - 1
    return torch.from_numpy(rel.sum(-1)).long()


class _SwinWindowAttention(nn.Module):
    def __init__(self, dim, num_heads, window):
        super().__init__()
        self.num_heads = num_heads
        self.window = window
        self.relative_position_bias_table = nn.Parameter(
            torch.zeros((2 * window - 1) ** 2, num_heads))
        self.register_buffer('relative_position_index',
                             _swin_rel_index(window, window),
                             persistent=False)
        self.qkv = nn.Linear(dim, dim * 3)
        self.proj = nn.Linear(dim, dim)

    def forward(self, x, mask=None):
        Bn, N, C = x.shape
        hd = C // self.num_heads
        qkv = self.qkv(x).reshape(Bn, N, 3, self.num_heads, hd)
        q, k, v = qkv.permute(2, 0, 3, 1, 4).unbind(0)      # (Bn, H, N, hd)
        attn = (q * hd ** -0.5) @ k.transpose(-2, -1)
        bias = self.relative_position_bias_table[
            self.relative_position_index.view(-1)].view(N, N, -1)
        attn = attn + bias.permute(2, 0, 1)
        if mask is not None:
            nw = mask.shape[0]
            attn = attn.view(Bn // nw, nw, self.num_heads, N, N)
            attn = attn + mask[None, :, None]
            attn = attn.view(Bn, self.num_heads, N, N)
        attn = attn.softmax(dim=-1)
        x = (attn @ v).transpose(1, 2).reshape(Bn, N, C)
        return self.proj(x)


class _SwinBlock(nn.Module):
    def __init__(self, dim, num_heads, feat, window, shift):
        super().__init__()
        self.feat = feat
        self.window = tuple(f if f <= window else window for f in feat)
        self.shift = tuple(0 if f <= w else (window // 2 if shift else 0)
                           for f, w in zip(feat, self.window))
        self.norm1 = nn.LayerNorm(dim)
        self.attn = _SwinWindowAttention(dim, num_heads, self.window[0])
        self.norm2 = nn.LayerNorm(dim)
        self.mlp = nn.Module()
        self.mlp.fc1 = nn.Linear(dim, 4 * dim)
        self.mlp.fc2 = nn.Linear(4 * dim, dim)
        if any(self.shift):
            wh, ww = self.window
            sh, sw = self.shift
            hp = -(-feat[0] // wh) * wh
            wp = -(-feat[1] // ww) * ww
            img = torch.zeros(hp, wp)
            cnt = 0
            for hs in (slice(0, -wh), slice(-wh, -sh if sh else None),
                       slice(-sh, None) if sh else slice(0, 0)):
                for ws_ in (slice(0, -ww), slice(-ww, -sw if sw else None),
                            slice(-sw, None) if sw else slice(0, 0)):
                    img[hs, ws_] = cnt
                    cnt += 1
            win = (img.view(hp // wh, wh, wp // ww, ww)
                   .permute(0, 2, 1, 3).reshape(-1, wh * ww))
            diff = win[:, None, :] - win[:, :, None]
            mask = torch.where(diff != 0, torch.tensor(-100.0),
                               torch.tensor(0.0))
            self.register_buffer('attn_mask', mask, persistent=False)
        else:
            self.attn_mask = None

    def _attn_part(self, x):
        B, H, W, C = x.shape
        wh, ww = self.window
        sh, sw = self.shift
        if sh or sw:
            x = torch.roll(x, shifts=(-sh, -sw), dims=(1, 2))
        pad_h = (wh - H % wh) % wh
        pad_w = (ww - W % ww) % ww
        x = F.pad(x, (0, 0, 0, pad_w, 0, pad_h))
        Hp, Wp = H + pad_h, W + pad_w
        wins = (x.view(B, Hp // wh, wh, Wp // ww, ww, C)
                .permute(0, 1, 3, 2, 4, 5).reshape(-1, wh * ww, C))
        wins = self.attn(wins, self.attn_mask)
        x = (wins.view(B, Hp // wh, Wp // ww, wh, ww, C)
             .permute(0, 1, 3, 2, 4, 5).reshape(B, Hp, Wp, C))
        x = x[:, :H, :W]
        if sh or sw:
            x = torch.roll(x, shifts=(sh, sw), dims=(1, 2))
        return x

    def forward(self, x):
        x = x + self._attn_part(self.norm1(x))
        h = self.mlp.fc2(F.gelu(self.mlp.fc1(self.norm2(x))))
        return x + h


class _SwinPatchMerging(nn.Module):
    def __init__(self, in_dim, out_dim):
        super().__init__()
        self.norm = nn.LayerNorm(4 * in_dim)
        self.reduction = nn.Linear(4 * in_dim, out_dim, bias=False)

    def forward(self, x):
        B, H, W, C = x.shape
        x = F.pad(x, (0, 0, 0, W % 2, 0, H % 2))
        _, H, W, _ = x.shape
        x = (x.reshape(B, H // 2, 2, W // 2, 2, C)
             .permute(0, 1, 3, 4, 2, 5).flatten(3))
        return self.reduction(self.norm(x))


class TorchSwin(nn.Module):
    """timm 0.9.12 SwinTransformer mirror (same module tree / state_dict
    keys: stage-START PatchMerging, NHWC blocks, non-persistent
    index/mask buffers, `head.fc`). Reference consumes it through pip-timm
    (models/timm/extract_timm.py:48, conda_env.yml timm==0.9.12)."""

    CFGS = {
        'swin_tiny_patch4_window7_224': (96, (2, 2, 6, 2), (3, 6, 12, 24)),
        'swin_small_patch4_window7_224': (96, (2, 2, 18, 2), (3, 6, 12, 24)),
        'swin_base_patch4_window7_224': (128, (2, 2, 18, 2), (4, 8, 16, 32)),
    }

    def __init__(self, arch='swin_tiny_patch4_window7_224', num_classes=0,
                 img_size=224, patch=4, window=7):
        super().__init__()
        C0, depths, heads = self.CFGS[arch]
        self.patch = patch
        self.patch_embed = nn.Module()
        self.patch_embed.proj = nn.Conv2d(3, C0, patch, patch)
        self.patch_embed.norm = nn.LayerNorm(C0)
        feat = img_size // patch
        self.layers = nn.ModuleList()
        for i, depth in enumerate(depths):
            dim = C0 * 2 ** i
            if i > 0:
                feat //= 2
            stage = nn.Module()
            stage.downsample = (_SwinPatchMerging(dim // 2, dim) if i > 0
                                else nn.Identity())
            stage.blocks = nn.ModuleList([
                _SwinBlock(dim, heads[i], (feat, feat), window,
                           shift=bool(j % 2))
                for j in range(depth)])
            self.layers.append(stage)
        self.norm = nn.LayerNorm(C0 * 8)
        self.head = nn.Module()
        self.head.fc = (nn.Linear(C0 * 8, num_classes) if num_classes
                        else nn.Identity())

    def forward(self, x):
        x = self.patch_embed.proj(x)                        # (B, C, H, W)
        x = x.permute(0, 2, 3, 1)                           # NHWC
        x = self.patch_embed.norm(x)
        for stage in self.layers:
            x = stage.downsample(x)
            for blk in stage.blocks:
                x = blk(x)
        x = self.norm(x)
        x = x.mean(dim=(1, 2))
        return self.head.fc(x)


# ---------------------------------------------------------- efficientnet --


class _EffSqueezeExcite(nn.Module):
    def __init__(self, chs, rd):
        super().__init__()
        self.conv_reduce = nn.Conv2d(chs, rd, 1)
        self.conv_expand = nn.Conv2d(rd, chs, 1)

    def forward(self, x):
        s = x.mean((2, 3), keepdim=True)
        s = self.conv_expand(F.silu(self.conv_reduce(s)))
        return x * torch.sigmoid(s)


class _EffDsBlock(nn.Module):
    def __init__(self, in_chs, out_chs, kernel, stride, rd):
        super().__init__()
        self.conv_dw = nn.Conv2d(in_chs, in_chs, kernel, stride,
                                 kernel // 2, groups=in_chs, bias=False)
        self.bn1 = nn.BatchNorm2d(in_chs)
        self.se = _EffSqueezeExcite(in_chs, rd)
        self.conv_pw = nn.Conv2d(in_chs, out_chs, 1, bias=False)
        self.bn2 = nn.BatchNorm2d(out_chs)
        self.has_skip = stride == 1 and in_chs == out_chs

    def forward(self, x):
        h = F.silu(self.bn1(self.conv_dw(x)))
        h = self.se(h)
        h = self.bn2(self.conv_pw(h))
        return x + h if self.has_skip else h


class _EffIrBlock(nn.Module):
    def __init__(self, in_chs, out_chs, kernel, stride, expand, rd):
        super().__init__()
        mid = in_chs * expand
        self.conv_pw = nn.Conv2d(in_chs, mid, 1, bias=False)
        self.bn1 = nn.BatchNorm2d(mid)
        self.conv_dw = nn.Conv2d(mid, mid, kernel, stride, kernel // 2,
                                 groups=mid, bias=False)
        self.bn2 = nn.BatchNorm2d(mid)
        self.se = _EffSqueezeExcite(mid, rd)
        self.conv_pwl = nn.Conv2d(mid, out_chs, 1, bias=False)
        self.bn3 = nn.BatchNorm2d(out_chs)
        self.has_skip = stride == 1 and in_chs == out_chs

    def forward(self, x):
        h = F.silu(self.bn1(self.conv_pw(x)))
        h = F.silu(self.bn2(self.conv_dw(h)))
        h = self.se(h)
        h = self.bn3(self.conv_pwl(h))
        return x + h if self.has_skip else h


class TorchEfficientNet(nn.Module):
    """timm 0.9.12 EfficientNet mirror (native efficientnet_b* tree:
    conv_stem/bn1, blocks.S.B.*, conv_head/bn2, classifier; symmetric
    k//2 padding — the tf_ ports' asymmetric SAME padding is out of
    scope). Reference consumes it through pip-timm
    (models/timm/extract_timm.py:48)."""

    # (kernel, stride, expand, out_channels, repeats) per stage — the
    # LITERAL timm 0.9.12 geometries, deliberately NOT derived from the
    # module under test so a wrong channel/repeat rule there fails the
    # parity/key tests instead of propagating into the mirror
    STAGES = {
        'efficientnet_b0': [(3, 1, 1, 16, 1), (3, 2, 6, 24, 2),
                            (5, 2, 6, 40, 2), (3, 2, 6, 80, 3),
                            (5, 1, 6, 112, 3), (5, 2, 6, 192, 4),
                            (3, 1, 6, 320, 1)],
        'efficientnet_b1': [(3, 1, 1, 16, 2), (3, 2, 6, 24, 3),
                            (5, 2, 6, 40, 3), (3, 2, 6, 80, 4),
                            (5, 1, 6, 112, 4), (5, 2, 6, 192, 5),
                            (3, 1, 6, 320, 2)],
    }

    def __init__(self, arch='efficientnet_b0', num_classes=0):
        super().__init__()
        stem, head = 32, 1280
        self.conv_stem = nn.Conv2d(3, stem, 3, 2, 1, bias=False)
        self.bn1 = nn.BatchNorm2d(stem)
        self.blocks = nn.ModuleList()
        cin = stem
        for si, (k, s, e, c, r) in enumerate(self.STAGES[arch]):
            stage = nn.ModuleList()
            for bi in range(r):
                block_in = cin if bi == 0 else c
                stride = s if bi == 0 else 1
                rd = max(1, block_in // 4)       # se_ratio 0.25 of block in
                if si == 0:
                    stage.append(_EffDsBlock(block_in, c, k, stride, rd))
                else:
                    stage.append(_EffIrBlock(block_in, c, k, stride, e, rd))
            self.blocks.append(stage)
            cin = c
        self.conv_head = nn.Conv2d(cin, head, 1, bias=False)
        self.bn2 = nn.BatchNorm2d(head)
        self.classifier = (nn.Linear(head, num_classes) if num_classes
                           else nn.Identity())

    def forward(self, x):
        x = F.silu(self.bn1(self.conv_stem(x)))
        for stage in self.blocks:
            for blk in stage:
                x = blk(x)
        x = F.silu(self.bn2(self.conv_head(x)))
        return self.classifier(x.mean((2, 3)))


# ---------------------------------------------------------------- regnet --


class _RegConvNormAct(nn.Module):
    """timm ConvNormAct: conv (no bias) → bn [→ relu]."""

    def __init__(self, i, o, k, stride=1, padding=0, groups=1, act=True):
        super().__init__()
        self.conv = nn.Conv2d(i, o, k, stride, padding, groups=groups,
                              bias=False)
        self.bn = nn.BatchNorm2d(o)
        self._act = act

    def forward(self, x):
        x = self.bn(self.conv(x))
        return F.relu(x) if self._act else x


class _RegSE(nn.Module):
    """timm SEModule: mean → fc1 (1×1 conv) → relu → fc2 → sigmoid gate."""

    def __init__(self, chs, rd):
        super().__init__()
        self.fc1 = nn.Conv2d(chs, rd, 1)
        self.fc2 = nn.Conv2d(rd, chs, 1)

    def forward(self, x):
        s = x.mean((2, 3), keepdim=True)
        s = self.fc2(F.relu(self.fc1(s)))
        return x * torch.sigmoid(s)


class _RegBottleneck(nn.Module):
    """timm regnet Bottleneck (bottle_ratio 1.0): conv1 1×1 → conv2
    grouped 3×3 → se (reduce width from the block INPUT channels) →
    conv3 1×1 no-act, + shortcut, ReLU after the sum."""

    def __init__(self, cin, w, stride, group_w, se=True):
        super().__init__()
        self.conv1 = _RegConvNormAct(cin, w, 1)
        self.conv2 = _RegConvNormAct(w, w, 3, stride, 1,
                                     groups=w // group_w)
        if se:   # RegNetY; the x variants carry no SE
            self.se = _RegSE(w, max(1, int(round(cin * 0.25))))
        self.conv3 = _RegConvNormAct(w, w, 1, act=False)
        self.downsample = (_RegConvNormAct(cin, w, 1, stride, act=False)
                           if stride != 1 or cin != w else None)

    def forward(self, x):
        sc = x if self.downsample is None else self.downsample(x)
        h = self.conv2(self.conv1(x))
        if hasattr(self, 'se'):
            h = self.se(h)
        h = self.conv3(h)
        return F.relu(h + sc)


class _RegStage(nn.Module):
    def __init__(self, cin, w, depth, group_w, se=True):
        super().__init__()
        for bi in range(1, depth + 1):
            self.add_module(f'b{bi}', _RegBottleneck(
                cin if bi == 1 else w, w, 2 if bi == 1 else 1, group_w,
                se=se))

    def forward(self, x):
        for blk in self.children():
            x = blk(x)
        return x


class _RegHead(nn.Module):
    def __init__(self, cin, num_classes):
        super().__init__()
        self.fc = nn.Linear(cin, num_classes) if num_classes else nn.Identity()

    def forward(self, x):
        return self.fc(x)


class TorchRegNet(nn.Module):
    """timm 0.9.12 RegNetY mirror (stem.{conv,bn}, s1..s4.b1..bN with
    ConvNormAct/SEModule children, head.fc). Reference consumes it through
    pip-timm (models/timm/extract_timm.py:48)."""

    # (depths, widths, group_width) — the LITERAL published RegNetY stage
    # tables, deliberately NOT derived from the module under test
    CFGS = {
        'regnety_004': ([1, 3, 6, 6], [48, 104, 208, 440], 8),
        'regnety_008': ([1, 3, 8, 2], [64, 128, 320, 768], 16),
        'regnety_016': ([2, 6, 17, 2], [48, 120, 336, 888], 24),
        'regnety_032': ([2, 5, 13, 1], [72, 216, 576, 1512], 24),
        'regnetx_008': ([1, 3, 7, 5], [64, 128, 288, 672], 16),
        'regnetx_016': ([2, 4, 10, 2], [72, 168, 408, 912], 24),
        'regnetx_032': ([2, 6, 15, 2], [96, 192, 432, 1008], 48),
    }

    def __init__(self, arch='regnety_008', num_classes=0):
        super().__init__()
        depths, widths, group_w = self.CFGS[arch]
        self.stem = _RegConvNormAct(3, 32, 3, 2, 1)
        cin = 32
        se = arch.startswith('regnety')
        for si, (d, w) in enumerate(zip(depths, widths), start=1):
            self.add_module(f's{si}', _RegStage(cin, w, d, group_w, se=se))
            cin = w
        self.head = _RegHead(cin, num_classes)

    def forward(self, x):
        x = self.stem(x)
        for si in range(1, 5):
            x = getattr(self, f's{si}')(x)
        return self.head(x.mean((2, 3)))


# ----------------------------------------------------------- mobilenetv3 --


class _MnvSE(nn.Module):
    """timm mobilenetv3 SqueezeExcite: ReLU inside, HARD-sigmoid gate."""

    def __init__(self, chs, rd):
        super().__init__()
        self.conv_reduce = nn.Conv2d(chs, rd, 1)
        self.conv_expand = nn.Conv2d(rd, chs, 1)

    def forward(self, x):
        s = x.mean((2, 3), keepdim=True)
        s = self.conv_expand(F.relu(self.conv_reduce(s)))
        return x * F.hardsigmoid(s)


class _MnvBlock(nn.Module):
    """One timm mobilenetv3 block: 'ds' / 'ir' / 'cn' with per-block
    activation (relu / hard-swish) and optional SE."""

    def __init__(self, cin, row):
        super().__init__()
        self.kind, k, self.stride, mid, out, act, se = row
        self.cin, self.out = cin, out
        self.act = F.relu if act == 're' else F.hardswish
        if self.kind == 'cn':
            self.conv = nn.Conv2d(cin, out, k, 1, 0, bias=False)
            self.bn1 = nn.BatchNorm2d(out)
            return
        if self.kind == 'ds':
            self.conv_dw = nn.Conv2d(cin, cin, k, self.stride, k // 2,
                                     groups=cin, bias=False)
            self.bn1 = nn.BatchNorm2d(cin)
            if se:
                self.se = _MnvSE(cin, se)
            self.conv_pw = nn.Conv2d(cin, out, 1, bias=False)
            self.bn2 = nn.BatchNorm2d(out)
            return
        self.conv_pw = nn.Conv2d(cin, mid, 1, bias=False)
        self.bn1 = nn.BatchNorm2d(mid)
        self.conv_dw = nn.Conv2d(mid, mid, k, self.stride, k // 2,
                                 groups=mid, bias=False)
        self.bn2 = nn.BatchNorm2d(mid)
        if se:
            self.se = _MnvSE(mid, se)
        self.conv_pwl = nn.Conv2d(mid, out, 1, bias=False)
        self.bn3 = nn.BatchNorm2d(out)

    def forward(self, x):
        if self.kind == 'cn':
            return self.act(self.bn1(self.conv(x)))
        if self.kind == 'ds':
            h = self.act(self.bn1(self.conv_dw(x)))
            if hasattr(self, 'se'):
                h = self.se(h)
            h = self.bn2(self.conv_pw(h))
        else:
            h = self.act(self.bn1(self.conv_pw(x)))
            h = self.act(self.bn2(self.conv_dw(h)))
            if hasattr(self, 'se'):
                h = self.se(h)
            h = self.bn3(self.conv_pwl(h))
        return x + h if self.stride == 1 and self.cin == self.out else h


class TorchMobileNetV3(nn.Module):
    """timm 0.9.12 MobileNetV3 mirror (conv_stem/bn1, blocks.S.B with
    efficientnet-style keys, post-pool conv_head WITH bias + hard-swish,
    classifier). Reference consumes it through pip-timm
    (models/timm/extract_timm.py:48)."""

    # (kind, kernel, stride, mid, out, act, se) — the LITERAL MobileNetV3
    # paper tables as timm builds them, deliberately NOT derived from the
    # module under test
    CFGS = {
        'mobilenetv3_large_100': (16, 1280, [
            [('ds', 3, 1, 16, 16, 're', 0)],
            [('ir', 3, 2, 64, 24, 're', 0), ('ir', 3, 1, 72, 24, 're', 0)],
            [('ir', 5, 2, 72, 40, 're', 24), ('ir', 5, 1, 120, 40, 're', 32),
             ('ir', 5, 1, 120, 40, 're', 32)],
            [('ir', 3, 2, 240, 80, 'hs', 0), ('ir', 3, 1, 200, 80, 'hs', 0),
             ('ir', 3, 1, 184, 80, 'hs', 0), ('ir', 3, 1, 184, 80, 'hs', 0)],
            [('ir', 3, 1, 480, 112, 'hs', 120),
             ('ir', 3, 1, 672, 112, 'hs', 168)],
            [('ir', 5, 2, 672, 160, 'hs', 168),
             ('ir', 5, 1, 960, 160, 'hs', 240),
             ('ir', 5, 1, 960, 160, 'hs', 240)],
            [('cn', 1, 1, 0, 960, 'hs', 0)],
        ]),
        'mobilenetv3_small_100': (16, 1024, [
            [('ds', 3, 2, 16, 16, 're', 8)],
            [('ir', 3, 2, 72, 24, 're', 0), ('ir', 3, 1, 88, 24, 're', 0)],
            [('ir', 5, 2, 96, 40, 'hs', 24), ('ir', 5, 1, 240, 40, 'hs', 64),
             ('ir', 5, 1, 240, 40, 'hs', 64)],
            [('ir', 5, 1, 120, 48, 'hs', 32), ('ir', 5, 1, 144, 48, 'hs', 40)],
            [('ir', 5, 2, 288, 96, 'hs', 72), ('ir', 5, 1, 576, 96, 'hs', 144),
             ('ir', 5, 1, 576, 96, 'hs', 144)],
            [('cn', 1, 1, 0, 576, 'hs', 0)],
        ]),
    }

    def __init__(self, arch='mobilenetv3_large_100', num_classes=0):
        super().__init__()
        stem, head, stages = self.CFGS[arch]
        self.conv_stem = nn.Conv2d(3, stem, 3, 2, 1, bias=False)
        self.bn1 = nn.BatchNorm2d(stem)
        self.blocks = nn.ModuleList()
        cin = stem
        for stage in stages:
            blocks = nn.ModuleList()
            for row in stage:
                blocks.append(_MnvBlock(cin, row))
                cin = row[4]
            self.blocks.append(blocks)
        self.conv_head = nn.Conv2d(cin, head, 1, bias=True)
        self.classifier = (nn.Linear(head, num_classes) if num_classes
                           else nn.Identity())

    def forward(self, x):
        x = F.hardswish(self.bn1(self.conv_stem(x)))
        for stage in self.blocks:
            for blk in stage:
                x = blk(x)
        x = x.mean((2, 3), keepdim=True)
        x = F.hardswish(self.conv_head(x))
        return self.classifier(x.flatten(1))


# ------------------------------------------------------------------ beit --


def _beit_rel_pos_index(wh, ww):
    coords = torch.stack(torch.meshgrid(
        torch.arange(wh), torch.arange(ww), indexing='ij'))
    flat = coords.flatten(1)
    rel = (flat[:, :, None] - flat[:, None, :]).permute(1, 2, 0).contiguous()
    rel[:, :, 0] += wh - 1
    rel[:, :, 1] += ww - 1
    rel[:, :, 0] *= 2 * ww - 1
    nrd = (2 * wh - 1) * (2 * ww - 1) + 3
    n = wh * ww
    index = torch.zeros((n + 1, n + 1), dtype=torch.long)
    index[1:, 1:] = rel.sum(-1)
    index[0, 0:] = nrd - 3
    index[0:, 0] = nrd - 2
    index[0, 0] = nrd - 1
    return index, nrd


class _BeitAttention(nn.Module):
    """timm beit Attention: packed qkv weight, q/v-only biases, per-block
    relative position bias table over a (N+1)² index."""

    def __init__(self, dim, heads, window):
        super().__init__()
        self.heads = heads
        self.qkv = nn.Linear(dim, dim * 3, bias=False)
        # random (not timm's zeros) so bias packing / table lookup bugs
        # are visible to every consumer of this mirror
        self.q_bias = nn.Parameter(torch.randn(dim) * 0.02)
        self.v_bias = nn.Parameter(torch.randn(dim) * 0.02)
        index, nrd = _beit_rel_pos_index(*window)
        self.relative_position_bias_table = nn.Parameter(
            torch.randn(nrd, heads) * 0.05)
        self.register_buffer('relative_position_index', index)
        self.proj = nn.Linear(dim, dim)

    def forward(self, x):
        B, N, D = x.shape
        hd = D // self.heads
        qkv_bias = torch.cat(
            [self.q_bias, torch.zeros_like(self.q_bias), self.v_bias])
        qkv = F.linear(x, self.qkv.weight, qkv_bias)
        qkv = qkv.reshape(B, N, 3, self.heads, hd).permute(2, 0, 3, 1, 4)
        q, k, v = qkv.unbind(0)                       # (B, H, N, hd)
        attn = (q * hd ** -0.5) @ k.transpose(-2, -1)
        bias = self.relative_position_bias_table[
            self.relative_position_index.view(-1)].view(N, N, -1)
        attn = attn + bias.permute(2, 0, 1).unsqueeze(0)
        attn = attn.softmax(dim=-1)
        out = (attn @ v).transpose(1, 2).reshape(B, N, D)
        return self.proj(out)


class _BeitBlock(nn.Module):
    def __init__(self, dim, heads, window):
        super().__init__()
        self.norm1 = nn.LayerNorm(dim, eps=1e-6)
        self.attn = _BeitAttention(dim, heads, window)
        self.gamma_1 = nn.Parameter(torch.ones(dim) * 0.1)
        self.norm2 = nn.LayerNorm(dim, eps=1e-6)
        self.mlp = nn.Sequential()
        self.mlp.fc1 = nn.Linear(dim, dim * 4)
        self.mlp.fc2 = nn.Linear(dim * 4, dim)
        self.gamma_2 = nn.Parameter(torch.ones(dim) * 0.1)

    def forward(self, x):
        x = x + self.gamma_1 * self.attn(self.norm1(x))
        h = self.mlp.fc2(F.gelu(self.mlp.fc1(self.norm2(x))))
        return x + self.gamma_2 * h


class _BeitPatchEmbed(nn.Module):
    def __init__(self, dim, patch):
        super().__init__()
        self.proj = nn.Conv2d(3, dim, patch, patch)

    def forward(self, x):
        return self.proj(x).flatten(2).transpose(1, 2)


class TorchBeit(nn.Module):
    """timm 0.9.12 Beit mirror: no absolute pos embed, per-block relative
    position bias, q/v-only qkv biases, gamma layer scale, mean-pooled
    patch tokens through fc_norm. Reference consumes it through pip-timm
    (models/timm/extract_timm.py:48)."""

    # (width, layers, heads, patch) — LITERAL beit geometries, deliberately
    # NOT derived from the module under test
    CFGS = {
        'beit_base_patch16_224': (768, 12, 12, 16),
        'beit_large_patch16_224': (1024, 24, 16, 16),
    }

    def __init__(self, arch='beit_base_patch16_224', num_classes=0,
                 img_size=224):
        super().__init__()
        width, layers, heads, patch = self.CFGS[arch]
        side = img_size // patch
        self.patch_embed = _BeitPatchEmbed(width, patch)
        self.cls_token = nn.Parameter(torch.randn(1, 1, width) * 0.02)
        self.blocks = nn.ModuleList(
            [_BeitBlock(width, heads, (side, side)) for _ in range(layers)])
        self.fc_norm = nn.LayerNorm(width, eps=1e-6)
        self.head = (nn.Linear(width, num_classes) if num_classes
                     else nn.Identity())

    def forward(self, x):
        x = self.patch_embed(x)
        cls = self.cls_token.expand(x.shape[0], -1, -1)
        x = torch.cat([cls, x], dim=1)
        for blk in self.blocks:
            x = blk(x)
        return self.head(self.fc_norm(x[:, 1:].mean(dim=1)))


# ----------------------------------------------------------------- mixer --


class _MixerMlp(nn.Module):
    def __init__(self, i, o):
        super().__init__()
        self.fc1 = nn.Linear(i, o)
        self.fc2 = nn.Linear(o, i)

    def forward(self, x):
        return self.fc2(F.gelu(self.fc1(x)))


class _MixerBlock(nn.Module):
    def __init__(self, dim, tokens, tok_dim, ch_dim):
        super().__init__()
        self.norm1 = nn.LayerNorm(dim, eps=1e-6)
        self.mlp_tokens = _MixerMlp(tokens, tok_dim)
        self.norm2 = nn.LayerNorm(dim, eps=1e-6)
        self.mlp_channels = _MixerMlp(dim, ch_dim)

    def forward(self, x):
        x = x + self.mlp_tokens(self.norm1(x).transpose(1, 2)).transpose(1, 2)
        return x + self.mlp_channels(self.norm2(x))


class _MixerStem(nn.Module):
    def __init__(self, dim, patch):
        super().__init__()
        self.proj = nn.Conv2d(3, dim, patch, patch)

    def forward(self, x):
        return self.proj(x).flatten(2).transpose(1, 2)


class TorchMixer(nn.Module):
    """timm 0.9.12 MlpMixer mirror (stem.proj, blocks.N.{norm1,mlp_tokens,
    norm2,mlp_channels}, norm; mean-token pooling). Reference consumes it
    through pip-timm (models/timm/extract_timm.py:48)."""

    # (width, layers, patch) — LITERAL mixer geometries, deliberately NOT
    # derived from the module under test; token MLP = width/2, channel
    # MLP = width*4 (timm MlpMixer mlp_ratio=(0.5, 4.0))
    CFGS = {
        'mixer_b16_224': (768, 12, 16),
        'mixer_l16_224': (1024, 24, 16),
    }

    def __init__(self, arch='mixer_b16_224', num_classes=0, img_size=224):
        super().__init__()
        width, layers, patch = self.CFGS[arch]
        tokens = (img_size // patch) ** 2
        self.stem = _MixerStem(width, patch)
        self.blocks = nn.ModuleList(
            [_MixerBlock(width, tokens, width // 2, width * 4)
             for _ in range(layers)])
        self.norm = nn.LayerNorm(width, eps=1e-6)
        self.head = (nn.Linear(width, num_classes) if num_classes
                     else nn.Identity())

    def forward(self, x):
        x = self.stem(x)
        for blk in self.blocks:
            x = blk(x)
        return self.head(self.norm(x).mean(dim=1))
