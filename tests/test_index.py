"""The sharded feature index (index/): ingest coherence + exact search.

The contracts under test:

  * **store** — shards bound at ``shard_rows``, vectors L2-normalized,
    tombstones never served, compaction rewrites shards + manifest
    atomically, replay survives torn tails, the ingest cursor persists;
  * **ingest** — ``fold_manifest`` folds each cache put exactly once
    (cursor + key-dedupe), resets idempotently when the cache manifest
    compacts under it, and skips non-framewise entries;
  * **coherence** — an evicted cache object is NEVER a search hit
    (the ``on_evict`` seam), and ``tools/index_gc.py`` repairs
    evictions nobody heard about with the 0/1/2 exit-code contract;
  * **locks** — the wire surface (v1.3: ``search``/``index_status``,
    ``POST /v1/search``) and the ``index`` program family (mesh widths
    1 and 2) are pinned.

Budget discipline (tier-1): store/fold units are numpy-only; search
tests share one tiny jit geometry; the served two-boot AOT e2e is
``slow`` (the index-smoke CI job and the full lane run it).
"""
import json
import os
import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

from video_features_tpu.cache.store import FeatureCache
from video_features_tpu.index.service import (
    CURSOR_SOURCE, fold_manifest, fold_put,
)
from video_features_tpu.index.shards import IndexStore

REPO_ROOT = Path(__file__).resolve().parents[1]

DIM = 8


def _store(tmp_path, name='idx', shard_rows=4):
    # direct ctor, not .get(): each test wants its own on-disk view
    return IndexStore(str(tmp_path / name), shard_rows=shard_rows)


def _rows(n, dim=DIM, seed=0):
    rng = np.random.default_rng(seed)
    return rng.standard_normal((n, dim)).astype(np.float32)


def _metas(n, key, t0=0):
    return [{'video': f'{key}.mp4', 'video_sha256': f'sha-{key}',
             't_ms': t0 + 1000 * i, 'key': key} for i in range(n)]


def _put_framewise(cache, tmp_path, key, n=3, seed=0, family='resnet'):
    """Publish one synthetic framewise entry the ingest path can fold."""
    src = tmp_path / f'src_{key}'
    src.mkdir(exist_ok=True)
    feat, ts = src / 'feat.npy', src / 'ts.npy'
    np.save(feat, _rows(n, seed=seed))
    np.save(ts, np.arange(n, dtype=np.int64) * 1000)
    cache.put(key, {family: (str(feat), '.npy'),
                    'timestamps_ms': (str(ts), '.npy')},
              meta={'video': f'{key}.mp4', 'feature_type': family,
                    'video_sha256': f'sha-{key}'})


# -- store: shards, tombstones, compaction, replay ---------------------------


def test_add_rows_bounds_shards_and_normalizes(tmp_path):
    store = _store(tmp_path, shard_rows=4)
    assert store.add_rows('resnet', _rows(10), _metas(10, 'k1')) == 10
    gkey = store.group_for('resnet')
    views = store.shard_views(gkey)
    # 10 rows at shard_rows=4 -> shards of 4, 4, 2 — bounded, unpadded
    assert [v[0].shape[0] for v in views] == [4, 4, 2]
    for arr, mask, metas in views:
        # every stored row is unit-norm: scores ARE cosine similarities
        np.testing.assert_allclose(np.linalg.norm(arr, axis=1), 1.0,
                                   atol=1e-5)
        assert mask.all() and all(m is not None for m in metas)
    st = store.stats()
    assert st['rows_live'] == 10 and st['rows_dead'] == 0
    # shard files are on disk, each within the row bound
    files = sorted((tmp_path / 'idx' / 'shards').rglob('shard_*.npy'))
    assert len(files) == 3


def test_drop_key_tombstones_then_compact_rewrites(tmp_path):
    store = _store(tmp_path, shard_rows=4)
    store.add_rows('resnet', _rows(6, seed=1), _metas(6, 'k1'))
    store.add_rows('resnet', _rows(5, seed=2), _metas(5, 'k2'))
    assert store.drop_key('k1') == 6
    assert store.drop_key('k1') == 0          # idempotent
    st = store.stats()
    assert st['rows_live'] == 5 and st['rows_dead'] == 6
    # dead rows are masked out of every served view
    for _, mask, metas in store.shard_views(store.group_for('resnet')):
        assert all((m is not None) == bool(b)
                   for m, b in zip(metas, mask))
    rep = store.compact()
    assert rep['rows_dropped'] == 6
    st = store.stats()
    assert st['rows_live'] == 5 and st['rows_dead'] == 0
    # a cold replay of the compacted dir agrees (and holds only k2)
    reloaded = IndexStore(store.index_dir, shard_rows=4)
    assert reloaded.stats()['rows_live'] == 5
    assert reloaded.keys() == ['k2']


def test_manifest_replay_skips_torn_and_foreign_lines(tmp_path):
    store = _store(tmp_path, shard_rows=4)
    store.add_rows('resnet', _rows(4, seed=3), _metas(4, 'k1'))
    store.set_cursor(CURSOR_SOURCE, 123)
    with open(store.manifest_path, 'ab') as f:
        f.write(b'{"op": "nonsense"}\n')
        f.write(b'{"op": "add", "family": "re')   # torn tail, no newline
    reloaded = IndexStore(store.index_dir, shard_rows=4)
    assert reloaded.stats()['rows_live'] == 4
    # the persisted ingest cursor replayed too
    assert reloaded.cursor(CURSOR_SOURCE) == 123


# -- ingest: fold_manifest cursor + dedupe + reset ---------------------------


def test_fold_manifest_folds_once_and_resumes_by_cursor(tmp_path):
    cache = FeatureCache(str(tmp_path / 'cache'))
    store = _store(tmp_path, shard_rows=4)
    _put_framewise(cache, tmp_path, 'k1', n=3, seed=4)
    _put_framewise(cache, tmp_path, 'k2', n=2, seed=5)
    rep = fold_manifest(store, cache)
    assert rep['rows_added'] == 5 and rep['bytes_folded'] > 0
    # second pass: cursor is past everything — nothing re-folds
    rep2 = fold_manifest(store, cache)
    assert rep2['rows_added'] == 0 and rep2['bytes_folded'] == 0
    # new put folds incrementally from the cursor
    _put_framewise(cache, tmp_path, 'k3', n=1, seed=6)
    assert fold_manifest(store, cache)['rows_added'] == 1
    assert sorted(store.keys()) == ['k1', 'k2', 'k3']


def test_fold_manifest_reset_on_shrink_is_idempotent(tmp_path):
    """A cache-manifest compaction shrinks the file under the ingest
    cursor; the fold replays from zero and key-dedupe makes the replay
    add nothing twice."""
    cache = FeatureCache(str(tmp_path / 'cache'))
    store = _store(tmp_path, shard_rows=4)
    for i in range(3):
        _put_framewise(cache, tmp_path, f'k{i}', n=2, seed=10 + i)
    assert fold_manifest(store, cache)['rows_added'] == 6
    # compact the cache manifest (offline gc's rewrite): file shrinks
    cache.gc(compact=True)
    assert os.path.getsize(cache.manifest_path) \
        < store.cursor(CURSOR_SOURCE)
    rep = fold_manifest(store, cache)
    assert rep['rows_added'] == 0           # replayed, deduped
    assert store.stats()['rows_live'] == 6


def test_fold_manifest_waits_out_torn_tail(tmp_path):
    cache = FeatureCache(str(tmp_path / 'cache'))
    store = _store(tmp_path, shard_rows=4)
    _put_framewise(cache, tmp_path, 'k1', n=2, seed=20)
    whole = open(cache.manifest_path, 'rb').read()
    # a writer mid-append: the last line has no newline yet
    torn_extra = b'{"op": "put", "key": "k2", "fi'
    with open(cache.manifest_path, 'ab') as f:
        f.write(torn_extra)
    rep = fold_manifest(store, cache)
    assert rep['rows_added'] == 2
    # the cursor stopped at the last COMPLETE line, not EOF
    assert store.cursor(CURSOR_SOURCE) == len(whole)


def test_fold_put_skips_non_framewise_entries(tmp_path):
    cache = FeatureCache(str(tmp_path / 'cache'))
    store = _store(tmp_path, shard_rows=4)
    src = tmp_path / 'solo.npy'
    np.save(src, _rows(2, seed=30))
    # no timestamps object, no feature_type meta: skipped, not an error
    cache.put('packed1', {'flow': (str(src), '.npy')})
    rep = fold_manifest(store, cache)
    assert rep == {'rows_added': 0, 'rows_dropped': 0,
                   'objects_skipped': 1,
                   'bytes_folded': rep['bytes_folded']}
    assert store.stats()['rows_live'] == 0
    # dedupe seam: a key already indexed folds to (0, 0)
    store.add_rows('resnet', _rows(1, seed=31), _metas(1, 'packed1'))
    assert fold_put(store, cache, 'packed1', {}) == (0, 0)


# -- coherence: eviction is never a search hit -------------------------------


def test_evicted_object_never_a_search_hit(tmp_path):
    """The regression the on_evict seam exists for: index a cache
    object, LRU-evict it, and its rows must be gone BEFORE the next
    query — tombstoned via the live hook, and the del-record replay
    keeps an offline rebuild coherent too."""
    from video_features_tpu.index.search import QueryEngine
    cache = FeatureCache(str(tmp_path / 'cache'), max_bytes=400)
    store = _store(tmp_path, shard_rows=4)
    # the serve-side subscription, minus the server
    cache.on_evict.append(lambda key, corrupt: store.drop_key(key))
    _put_framewise(cache, tmp_path, 'kold', n=2, seed=40)
    fold_manifest(store, cache)
    engine = QueryEngine(store, aot_store=None, query_block=2, k_max=4)
    probe = store.shard_views(store.group_for('resnet'))[0][0][0]
    hits, _ = engine.search('resnet', probe, k=4)
    assert hits[0][0]['key'] == 'kold'
    assert hits[0][0]['score'] == pytest.approx(1.0, abs=1e-5)
    # publish enough new bytes to LRU-evict kold inline
    for i in range(4):
        _put_framewise(cache, tmp_path, f'knew{i}', n=2, seed=41 + i)
    assert not cache.contains('kold')
    hits, _ = engine.search('resnet', probe, k=4)
    assert all(h['key'] != 'kold' for h in hits[0]), hits[0]
    # an offline rebuild from the same cache manifest agrees: the del
    # records fold as tombstones
    rebuilt = _store(tmp_path, name='rebuilt', shard_rows=4)
    fold_manifest(rebuilt, cache)
    assert 'kold' not in rebuilt.keys()


def test_orphan_sweep_drops_unbacked_rows(tmp_path):
    store = _store(tmp_path, shard_rows=4)
    store.add_rows('resnet', _rows(3, seed=50), _metas(3, 'gone'))
    store.add_rows('resnet', _rows(2, seed=51), _metas(2, 'kept'))
    dropped = store.orphan_sweep(lambda key: key == 'kept')
    assert dropped == 3
    assert store.keys() == ['kept']
    # a probing failure keeps the row (safe side; next sweep retries)
    assert store.orphan_sweep(
        lambda key: (_ for _ in ()).throw(OSError('nope'))) == 0
    assert store.keys() == ['kept']


def test_index_gc_exit_codes(tmp_path):
    """The 0/1/2 contract shared with cache_gc/aot_gc: 2 usage, 1
    orphans found (and dropped), 0 clean."""
    env = dict(os.environ, JAX_PLATFORMS='cpu')
    tool = str(REPO_ROOT / 'tools' / 'index_gc.py')

    def run(*args):
        return subprocess.run([sys.executable, tool, *args],
                              capture_output=True, text=True, env=env,
                              cwd=str(REPO_ROOT), timeout=120)
    # 2: not a directory
    assert run('--cache-dir', str(tmp_path / 'nope')).returncode == 2
    # seed an index whose rows point at keys an EMPTY cache denies
    cache_dir = tmp_path / 'cache'
    cache_dir.mkdir()
    store = IndexStore(str(cache_dir / 'index'), shard_rows=4)
    store.add_rows('resnet', _rows(5, seed=60), _metas(5, 'orphaned'))
    # 1: the orphan sweep found (and dropped) rows
    proc = run('--cache-dir', str(cache_dir), '--orphan-sweep')
    assert proc.returncode == 1, proc.stderr
    rep = json.loads(proc.stdout)
    assert rep['orphans_dropped'] == 5 and rep['rows_live'] == 0
    assert rep['compact']['rows_dropped'] == 5
    # 0: clean on the second pass
    proc = run('--cache-dir', str(cache_dir), '--orphan-sweep')
    assert proc.returncode == 0, proc.stderr
    assert json.loads(proc.stdout)['orphans_dropped'] == 0


# -- offline CLI --------------------------------------------------------------


def test_index_cli_ingest_query_status(tmp_path, capsys):
    """One in-process CLI pass composing ingest → query → status: the
    offline surface answers the same exact top-k as the served one,
    reporting on stdout as ONE machine-parseable JSON line."""
    from video_features_tpu.index.cli import index_main
    cache = FeatureCache(str(tmp_path / 'cache'))
    _put_framewise(cache, tmp_path, 'kcli', n=3, seed=70)
    q = tmp_path / 'q.npy'
    np.save(q, np.load(tmp_path / 'src_kcli' / 'feat.npy')[1])
    rc = index_main(['--cache-dir', str(tmp_path / 'cache'),
                     '--shard-rows', '4', '--ingest',
                     '--query', str(q), '--k', '2', '--status'])
    assert rc == 0
    out = capsys.readouterr().out.strip().splitlines()
    assert len(out) == 1                     # exactly one report line
    rep = json.loads(out[0])
    assert rep['ingest']['rows_added'] == 3
    assert rep['index']['rows_live'] == 3
    # family auto-picked (the index holds exactly one); the query row
    # retrieves itself at rank 1 with cosine 1.0
    top = rep['query']['hits'][0]
    assert rep['query']['family'] == 'resnet'
    assert top['key'] == 'kcli' and top['t_ms'] == 1000
    assert top['score'] == pytest.approx(1.0, abs=1e-5)
    # a failed query reports ok=false and exits 1
    rc = index_main(['--cache-dir', str(tmp_path / 'cache'),
                     '--query', str(q), '--family', 'nosuch'])
    assert rc == 1
    rep = json.loads(capsys.readouterr().out)
    assert rep['ok'] is False and 'nosuch' in rep['error']


# -- locks: the pinned index surface -----------------------------------------


def test_locks_pin_the_index_surface():
    """Re-pin coverage: the wire lock carries the v1.5 additive surface
    and the programs lock pins the ``index`` pseudo-family at BOTH mesh
    widths with the canonical geometry (the deep drift/rule gates live
    in test_wire.py / test_programs.py — this names the index rows)."""
    from video_features_tpu.index.search import (
        INDEX_DIM, INDEX_K, INDEX_QUERIES, INDEX_ROWS,
    )
    wire = json.loads((REPO_ROOT / 'WIRE.lock.json').read_text())
    assert wire['version'] == '1.5'
    assert 'search' in wire['commands'] and 'index_status' in wire['commands']
    assert 'POST /v1/search' in wire['routes']
    assert wire['routes']['POST /v1/search']['auth']

    lock = json.loads((REPO_ROOT / 'PROGRAMS.lock.json').read_text())
    idx = lock['families']['index']
    assert set(idx) == {'mesh1', 'mesh2'}
    for mesh in ('mesh1', 'mesh2'):
        topk = idx[mesh]['programs']['topk']
        assert topk['batch']['shape'] == [INDEX_ROWS, INDEX_DIM]
        assert [o['shape'] for o in topk['out']] == \
            [[INDEX_QUERIES, INDEX_K]] * 2
        assert [o['dtype'] for o in topk['out']] == ['float32', 'int32']


# -- slow lane: the served index end-to-end (+ zero cold start) ---------------


@pytest.mark.slow
def test_serve_search_e2e_two_boots_compile_free(tmp_path):
    """Acceptance run: fused extract publishes both families, ingest
    reaches lag 0, query-by-video answers the source video's own
    windows as top hits — byte-identical across two boots against one
    executable store, with the SECOND boot's index program loaded, not
    compiled (``serve_prewarm: [index]`` + ``aot_enabled``)."""
    import time

    from tools.make_sample_video import write_noise_clip
    from video_features_tpu.serve.client import ServeClient
    from video_features_tpu.serve.server import ExtractionServer
    clip = str(write_noise_clip(tmp_path / 'e2e.mp4', 9, seed=0))
    base = {
        'device': 'cpu', 'model_name': 'resnet18', 'batch_size': 4,
        'allow_random_weights': True, 'on_extraction': 'save_numpy',
        'tmp_path': str(tmp_path / 'tmp'),
        'output_path': str(tmp_path / 'out'),
        'cache_enabled': True, 'cache_dir': str(tmp_path / 'cache'),
        'index_enabled': True,
        'aot_enabled': True, 'aot_dir': str(tmp_path / 'aot'),
    }

    def boot_and_search(submit_first):
        srv = ExtractionServer(base_overrides=dict(base), queue_depth=16,
                               pool_size=2).start()
        try:
            rep = srv.prewarm(['index'])
            assert not rep['errors'], rep
            client = ServeClient(port=srv.port)
            if submit_first:
                rid = client.submit(None, [clip],
                                    features=['resnet', 'clip'],
                                    overrides={'clip.model_name':
                                               'ViT-B/32'})
                assert client.wait(rid, timeout_s=600)['state'] == 'done'
            deadline = time.monotonic() + 120
            while time.monotonic() < deadline:
                idx = client.index_status()
                if idx['rows_live'] > 0 and idx['ingest_lag_bytes'] == 0 \
                        and set(idx['families']) == {'resnet', 'clip'}:
                    break
                time.sleep(0.2)
            else:
                raise AssertionError(f'ingest never converged: {idx}')
            res = client.search(video_path=clip,
                                features=['resnet', 'clip'], k=3)
            return res, idx
        finally:
            srv.drain(wait=True, grace_s=120)
    first, idx1 = boot_and_search(submit_first=True)
    second, idx2 = boot_and_search(submit_first=False)
    for res, idx in ((first, idx1), (second, idx2)):
        assert res['ok'] and not res.get('errors')
        for fam in ('resnet', 'clip'):
            top = res['results'][fam][0]
            assert top['video_sha256'] == res['video_sha256'], fam
            assert top['score'] > 0.999, (fam, top)
    # byte-identical answers across boots (drop the timing field)
    def canon(res):
        res = dict(res)
        res.pop('wall_s', None)        # timing, and the per-boot request
        res.pop('request_id', None)    # counter; hits are pure identity
        return json.dumps(res, sort_keys=True)
    assert canon(first) == canon(second)
    # zero cold start for the query program: boot 1 compiled + published
    # it, boot 2 loaded it
    assert idx1['programs_compiled'] >= 1
    assert idx2['programs_loaded'] >= 1 and idx2['programs_compiled'] == 0
