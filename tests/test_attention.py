"""dense / blockwise / ring attention equivalence.

Ring attention runs on the 8-virtual-device CPU mesh from conftest — the
same shard_map program a TPU slice would compile, with ppermute collectives
over the time axis.
"""
import numpy as np
import pytest

jax = pytest.importorskip('jax')
import jax.numpy as jnp  # noqa: E402

from video_features_tpu.ops.attention import (  # noqa: E402
    blockwise_attention, dense_attention,
)
from video_features_tpu.parallel.mesh import make_mesh  # noqa: E402
from video_features_tpu.parallel.ring import (  # noqa: E402
    sequence_sharded_attention, sequence_sharding,
)


def _qkv(rng, b=2, s=64, h=4, d=16):
    def t():
        return jnp.asarray(rng.randn(b, s, h, d).astype(np.float32))
    return t(), t(), t()


def test_blockwise_matches_dense():
    rng = np.random.RandomState(0)
    q, k, v = _qkv(rng)
    ref = dense_attention(q, k, v)
    for block in (8, 16, 64):
        got = blockwise_attention(q, k, v, block_size=block)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)


def test_blockwise_large_scale_stability():
    """Large score magnitudes: online softmax must not overflow."""
    rng = np.random.RandomState(1)
    q, k, v = _qkv(rng, s=32)
    q = q * 40.0  # scores ~ O(1000) pre-softmax
    ref = dense_attention(q, k, v)
    got = blockwise_attention(q, k, v, block_size=8)
    assert np.isfinite(np.asarray(got)).all()
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize('time_parallel', [2, 4, 8])
def test_ring_matches_dense(time_parallel):
    if len(jax.devices()) < time_parallel:
        pytest.skip('needs virtual device mesh')
    mesh = make_mesh(time_parallel, time_parallel=time_parallel)
    rng = np.random.RandomState(2)
    q, k, v = _qkv(rng, s=8 * time_parallel)
    ref = dense_attention(q, k, v)

    sharding = sequence_sharding(mesh)
    qs, ks, vs = (jax.device_put(t, sharding) for t in (q, k, v))
    got = sequence_sharded_attention(mesh, qs, ks, vs)
    assert got.sharding.is_equivalent_to(sharding, got.ndim)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_ring_custom_scale():
    mesh = make_mesh(2, time_parallel=2)
    rng = np.random.RandomState(3)
    q, k, v = _qkv(rng, s=16)
    ref = dense_attention(q, k, v, scale=0.5)
    got = sequence_sharded_attention(mesh, q, k, v, scale=0.5)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_blockwise_ragged_matches_dense():
    """ViT token counts (grid²+1) are never block-aligned; the pad+mask
    path must agree with dense attention."""
    import numpy as np

    from video_features_tpu.ops.attention import (
        blockwise_attention, dense_attention,
    )
    rng = np.random.RandomState(0)
    q, k, v = (rng.randn(2, 197, 3, 16).astype(np.float32) for _ in range(3))
    ref = np.asarray(dense_attention(q, k, v))
    got = np.asarray(blockwise_attention(q, k, v, block_size=64))
    np.testing.assert_allclose(got, ref, atol=2e-5)
